"""The wideband portrait fit: (phi, DM, GM, tau, alpha) in the Fourier
domain with per-channel amplitudes profiled out analytically.

This single module replaces the reference's entire hand-written
autodiff graph (pptoaslib.py:195-773: phase/scattering derivative
chains, 5x5 block Hessians, Woodbury covariance) and its scipy
trust-ncg driver (pptoaslib.py:974-1144), and the legacy 2-parameter
fit (pplib.py:2185-2287).  One pure objective + a jittable
Levenberg-damped Newton loop (`lax.while_loop`), batched with `vmap`
over (archive, subint) and shardable with `pjit` over a device mesh.

Objective (Pennucci+ 2014 eq. 10-11, re-derived):

    t_n(theta)  = phi + (Dconst DM / P)(nu_n^-2 - nu_fit^-2)
                      + (Dconst^2 GM / P)(nu_n^-4 - nu_fit^-4)
    B_nk        = scattering_FT(tau (nu_n/nu_fit)^alpha)_k * IR_nk
    C_n         = Re sum_k d_nk conj(m_nk B_nk) e^{2 pi i k t_n} w_nk
    S_n         = sum_k |m_nk B_nk|^2 w_nk
    chi2'       = - sum_n C_n^2 / S_n          (a_n = C_n/S_n profiled)
    chi2        = sum_nk |d_nk|^2 w_nk + chi2'

with w_nk = harmonic weights (DC zeroed per F0_fact) * channel mask /
sigma_F,n^2.

Execution strategy (TPU):

- Everything is precomputed into X = d conj(m) w (complex) and
  M2 = |m|^2 w (real); each optimizer step streams X once from HBM.
- When no scattering parameter is active (the dominant (phi, DM[, GM])
  TOA workload), the objective value, gradient, and exact Hessian are
  produced in ONE fused pass via the harmonic moments
  Z_j,n = sum_k (2 pi k)^j X_nk e^{2 pi i k t_n}, j = 0..2:
      C = Re Z0,  dC/dt = -Im Z1,  d2C/dt2 = -Re Z2,
  and t_n is linear in (phi, DM, GM), so the 5x5 Hessian follows by
  chain rule with no extra array traffic.  This is strictly cheaper
  than both the reference's scipy loop and naive autodiff (which
  re-reads the arrays ~10x per step).
- When tau/alpha/instrumental-response are active, the same Newton
  loop runs on jax.grad/jax.hessian of the full objective.

Zero-covariance reference frequencies are computed exactly from the
covariance matrix in the infinite-frequency parameterization (a 2x2
linear solve), replacing the reference's per-flag-combination
closed-form polynomial-root branches (pptoaslib.py:776-950).
"""

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import config
from ..config import Dconst, F0_fact
from ..ops.noise import fourier_noise
from ..ops.phasor import cexp
from ..ops.scattering import scattering_portrait_FT
from ..ops.fourier import irfft_c, rfft_c


def _tiny(dtype):
    return jnp.finfo(dtype).tiny


class FitFlags(NamedTuple):
    """Which of (phi, DM, GM, tau, alpha) are free.  Static per jit."""

    phi: bool = True
    DM: bool = True
    GM: bool = False
    tau: bool = False
    alpha: bool = False

    def as_array(self, dtype=jnp.float64):
        return jnp.array([float(f) for f in self], dtype=dtype)


class FitResult(NamedTuple):
    """Per-fit outputs (all jnp arrays; batched fits stack them).

    Field meanings match the reference's result DataBunch
    (pptoaslib.py:1134-1143).  tau is in rotations (multiply by P for
    seconds); phi is referenced to nu_DM.
    """

    phi: jnp.ndarray
    phi_err: jnp.ndarray
    DM: jnp.ndarray
    DM_err: jnp.ndarray
    GM: jnp.ndarray
    GM_err: jnp.ndarray
    tau: jnp.ndarray
    tau_err: jnp.ndarray
    alpha: jnp.ndarray
    alpha_err: jnp.ndarray
    nu_DM: jnp.ndarray
    nu_GM: jnp.ndarray
    nu_tau: jnp.ndarray
    scales: jnp.ndarray
    scale_errs: jnp.ndarray
    channel_snrs: jnp.ndarray
    snr: jnp.ndarray
    covariance: jnp.ndarray
    chi2: jnp.ndarray
    dof: jnp.ndarray
    nfeval: jnp.ndarray
    return_code: jnp.ndarray

    @property
    def red_chi2(self):
        return self.chi2 / self.dof


def _tau_of(theta, log10_tau):
    return 10.0 ** theta[3] if log10_tau else theta[3]


def _t_coeffs(freqs, P, nu_fit):
    """t_n = phi + cvec_n * DM + gvec_n * GM."""
    cvec = (Dconst / P) * (freqs**-2.0 - nu_fit**-2.0)
    gvec = (Dconst**2.0 / P) * (freqs**-4.0 - nu_fit**-4.0)
    return cvec, gvec


def _scatter_B(theta, freqs, nu_fit, nharm, ir_FT, log10_tau):
    """Per-channel scattering+instrumental kernel B (complex)."""
    tau = _tau_of(theta, log10_tau)
    taus = tau * (freqs / nu_fit) ** theta[4]
    B = scattering_portrait_FT(taus, nharm)
    if ir_FT is not None:
        B = B * ir_FT
    return B


def chi2_prime(theta, dFT, mFT, w, freqs, P, nu_fit, ir_FT=None, log10_tau=False):
    """The profiled-amplitude objective chi2' (see module docstring).

    Reference API entry (kept for tests/oracles); the optimized path
    inside the fit uses the precomputed X/M2 forms below.
    """
    X = dFT * jnp.conj(mFT) * w
    M2 = (mFT.real**2 + mFT.imag**2) * w
    C, S = _CS_general(theta, X, M2, freqs, P, nu_fit, ir_FT, log10_tau)
    good = S > 0.0
    S_safe = jnp.where(good, S, 1.0)
    return -jnp.sum(jnp.where(good, C**2.0 / S_safe, 0.0))


def _CS_general(theta, X, M2, freqs, P, nu_fit, ir_FT, log10_tau):
    """C_n, S_n with scattering/instrumental response active."""
    nharm = X.shape[-1]
    k = jnp.arange(nharm, dtype=M2.dtype)
    B = _scatter_B(theta, freqs, nu_fit, nharm, ir_FT, log10_tau)
    cvec, gvec = _t_coeffs(freqs, P, nu_fit)
    t_n = theta[0] + cvec * theta[1] + gvec * theta[2]
    ph = cexp(2.0 * jnp.pi * t_n[:, None] * k)
    C = jnp.sum((X * jnp.conj(B) * ph).real, axis=-1)
    S = jnp.sum(M2 * (B.real**2 + B.imag**2), axis=-1)
    return C, S


def _chi2_prime_X(theta, X, M2, freqs, P, nu_fit, ir_FT, log10_tau):
    C, S = _CS_general(theta, X, M2, freqs, P, nu_fit, ir_FT, log10_tau)
    good = S > 0.0
    S_safe = jnp.where(good, S, 1.0)
    return -jnp.sum(jnp.where(good, C**2.0 / S_safe, 0.0))


def use_bf16_cross_spectrum():
    """Whether the fast fit stores its precomputed cross-spectrum in
    bfloat16 (config.cross_spectrum_dtype) — the single parse point for
    the knob, shared by the batch and sharded entry paths."""
    return str(getattr(config, "cross_spectrum_dtype", None)) == "bfloat16"


def use_fit_fused(setting=None):
    """Whether the fast lanes' prepare stage should run the fused
    hand-blocked DFT -> cross-spectrum program (ops/fused.py):
    config.fit_fused (True/False force; 'auto' = TPU backends, where
    the HBM round-trips between the unfused stages are the measured
    mfu ceiling — BENCH_r04/r05).  Strict like the other tri-states.
    Only takes effect when the harmonic window is active (the batch
    wrappers normalize the dead fused+unwindowed combination onto the
    unfused program so it never compiles twice); callers that don't
    thread it explicitly (the sharded path) resolve config at trace
    time with the usual already-traced caveat."""
    if setting is None:
        setting = getattr(config, "fit_fused", "auto")
    from ..tune.capability import resolve_auto

    return resolve_auto("fit_fused", setting)


def resolve_fit_fused(nharm_eff):
    """The batch wrappers' single resolution point for the fused-lane
    program-cache token: False when the fused lane is off or dead (no
    harmonic window — it must not key a second bit-identical program),
    else a token naming the implementation the prepare stage should
    take, so flipping config.fit_pallas or config.fused_block
    mid-process retraces instead of silently reusing the other arm:

      True          hand-blocked scan, default block
      'pallas'      Pallas kernel, default block
      'fused:<b>'   scan, config.fused_block = b
      'pallas:<b>'  Pallas kernel, config.fused_block = b

    Every token is truthy, so existing `if fit_fused` gates behave
    unchanged; _parse_fit_fused recovers (pallas, block) at the
    fused_cross_spectrum call site."""
    if not (use_fit_fused() and nharm_eff is not None):
        return False
    from ..ops.fused import use_fit_pallas

    pallas = use_fit_pallas()
    blk = getattr(config, "fused_block", None)
    if blk is None:
        return "pallas" if pallas else True
    return f"{'pallas' if pallas else 'fused'}:{int(blk)}"


def _parse_fit_fused(fit_fused):
    """Token -> (pallas, block) for the fused_cross_spectrum call (see
    resolve_fit_fused).  Plain True (legacy callers) means the scan at
    the default block."""
    if isinstance(fit_fused, str):
        mode, _, blk = fit_fused.partition(":")
        return mode == "pallas", (int(blk) if blk else None)
    return False, None


def use_scatter_compensated():
    """Whether scattering fits run the Dot2-compensated reductions
    (config.scatter_compensated) — the single parse point, shared by
    the batch, sharded, and streaming entry paths."""
    return bool(getattr(config, "scatter_compensated", False))


def model_harmonic_window(model, nbin, tail=None, floor_sigma=None):
    """Static harmonic count K for the fast fit's band-limited lane,
    derived from a HOST model portrait (numpy (nchan, nbin) or
    (nb, nchan, nbin)): the smallest K such that every channel keeps
    all but `tail` (config.harmonic_window_tail) of its spectral power
    below K, plus one 128-harmonic guard block, rounded up to a
    multiple of 128 (MXU/VPU tile width).  Returns None when no
    truncation is worthwhile (K would reach the full spectrum) — e.g.
    noise-dominated or unresolved templates.

    Every fit statistic is model-weighted (X = d conj(m) w, S ~ |m|^2
    w), so harmonics with ~zero model power contribute ~zero to the
    fit; chi2/Sd are NOT truncated (time-domain Parseval term in
    prepare_portrait_fit_real).  The reference evaluates all harmonics
    unconditionally (pptoaslib.py:564-614); on TPU the window cuts the
    two dominant fit costs (MXU DFT, VPU moment trig) by ~the same
    factor.

    DATA-BUILT templates (ppspline/ppgauss output from real archives)
    carry a white noise floor far above `tail` — measured ~1e-6..1e-4
    of total power for unsmoothed spline models — which would keep the
    absolute criterion at full spectrum and silently forfeit the whole
    win on the workload the framework targets.  Harmonics at the
    template's own noise floor carry no matched-filter information
    (their model "power" is noise, contributing variance but no
    signal), so the criterion is noise-floor-aware: per channel the
    white floor mu is estimated from the top-quarter spectral plateau
    (robust median / ln 2 for exponentially-distributed chi^2_2
    power), the expected pure-noise tail mu*(nharm-k) is subtracted
    from the reverse-cumulative power, and a harmonic only counts as
    needed when the excess clears BOTH the relative-tail criterion and
    a `floor_sigma`*sqrt(nharm-k)*mu fluctuation budget (the tail sum
    of m exponentials has std sqrt(m)*mu; 20 sigma keeps the
    false-trigger probability negligible across ~1e5 channels).  A
    clean template has mu ~ 0 and reduces exactly to the absolute
    criterion; an apparent "floor" holding >10% of total power is
    treated as signal (no subtraction), which keeps pure-noise
    templates — and pathological flat-spectrum templates — at full
    spectrum."""
    import numpy as _np

    if tail is None:
        tail = float(getattr(config, "harmonic_window_tail", 1e-12))
    if floor_sigma is None:
        floor_sigma = getattr(config, "harmonic_window_floor_sigma", 20.0)
    floor_sigma = 0.0 if floor_sigma is None else float(floor_sigma)
    nharm = nbin // 2 + 1
    # chunk over channels: a batched 3-D model at campaign shapes is
    # gigabytes, and the derivation only needs a per-channel max — the
    # spectrum is computed in f32 (numpy rfft of f32 -> complex64, half
    # the memory) with the tail accumulation in f64 per chunk
    m = _np.asarray(model).reshape(-1, nbin)
    if m.dtype not in (_np.float32, _np.float64):
        m = m.astype(_np.float32)
    # number of tail harmonics at-or-above each k (DC never counts)
    ntail = _np.maximum(nharm - _np.arange(nharm), 0).astype(_np.float64)
    ntail[0] = nharm - 1.0
    K = 0
    any_good = False
    for lo in range(0, m.shape[0], 256):
        spec = _np.abs(_np.fft.rfft(m[lo:lo + 256], axis=-1)) ** 2.0
        spec = spec.astype(_np.float64)
        # DC-free power: the fit zeroes harmonic 0 (F0_fact = 0,
        # reference pplib.py:82), so a template's baseline offset must
        # not inflate the denominator — a large (n*mu)^2 there would
        # loosen the tail criterion and truncate real AC support
        spec[:, 0] = 0.0
        tot = spec.sum(axis=-1)
        good = tot > 0.0
        if not _np.any(good):
            continue
        any_good = True
        spec = spec[good]
        tot = tot[good]
        if floor_sigma > 0.0 and nharm >= 64:
            q = nharm // 4
            mu = _np.median(spec[:, -q:], axis=-1) / _np.log(2.0)
            # a white floor is FLAT: the top eighth and the eighth
            # below it agree to fluctuation level (median of ~nharm/8
            # exponentials is stable to ~1/sqrt(m)).  A clean template
            # whose genuine spectrum is still decaying through the top
            # quarter (sharp/narrow profiles at high nbin) fails this
            # 2x-each-way flatness test and gets NO subtraction — the
            # absolute criterion must stay exact for clean templates
            q8 = nharm // 8
            med_hi = _np.median(spec[:, -q8:], axis=-1)
            med_lo = _np.median(spec[:, -2 * q8:-q8], axis=-1)
            flat = (med_lo <= 2.0 * med_hi) & (med_hi <= 2.0 * med_lo)
            # an apparent floor holding >10% of the power is signal
            # (or the template is pure noise): don't subtract it
            mu = _np.where(flat & (mu * (nharm - 1) <= 0.1 * tot),
                           mu, 0.0)
        else:
            mu = _np.zeros(spec.shape[0])
        # per-channel tail power above each k (rev_cum[k] is the power
        # at harmonics >= k), minus the expected pure-noise tail
        rev_cum = spec[:, ::-1].cumsum(axis=-1)[:, ::-1]
        excess = rev_cum - mu[:, None] * ntail
        budget = floor_sigma * _np.sqrt(ntail) * mu[:, None]
        tot_sig = _np.maximum(tot - mu * (nharm - 1), tot * 1e-30)
        needed = (excess > tail * tot_sig[:, None]) & (excess > budget)
        # K covers the LAST needed harmonic (the floor-subtracted mask
        # need not be monotone in k, so a True count would undercount)
        any_needed = needed.any(axis=-1)
        if any_needed.any():
            last = nharm - 1 - needed[:, ::-1].argmax(axis=-1)
            K = max(K, int((last[any_needed] + 1).max()))
    if not any_good:
        return None
    K = (K + 128 + 127) // 128 * 128  # +1 guard block, tile-rounded
    if K >= nharm:
        return None
    return K


def resolve_harmonic_window(harmonic_window, models, nbin):
    """The fast batch entry points' shared parse of the harmonic-window
    knob: explicit int wins (tile-rounded); None -> config
    (fit_harmonic_window); True or 'auto' derives from the model ONLY
    when it is host-resident (numpy) — deriving from a device array
    would cost a silent device->host pull mid-pipeline.  Unknown
    strings raise (strict like use_matmul_dft: a typo must not silently
    mean full-spectrum, and True must not mean K=128)."""
    import numpy as _np

    if harmonic_window is None:
        harmonic_window = getattr(config, "fit_harmonic_window", None)
    if harmonic_window is None or harmonic_window is False:
        return None
    if harmonic_window is True or harmonic_window == "auto":
        if isinstance(models, _np.ndarray):
            return model_harmonic_window(models, nbin)
        return None
    if isinstance(harmonic_window, str):
        raise ValueError(
            f"fit_harmonic_window must be 'auto', True/False/None, or "
            f"a positive int; got {harmonic_window!r}")
    K = int(harmonic_window)
    if K <= 0:
        raise ValueError(
            f"fit_harmonic_window must be positive (got {K}); use "
            f"None or False to disable the window")
    K = (K + 127) // 128 * 128
    return K if K < nbin // 2 + 1 else None


# Calibrated channel-S/N envelope of the bf16 cross-spectrum default:
# the |dphi| gate and the error-calibration tests hold at bench noise
# (channel S/N ~ 1.4e3); above ~2x that the ~4e-3 per-term bf16
# quantization can rival the noise floor (benchmarks/BENCHMARKS.md).
BF16_CALIBRATED_CHANNEL_SNR = 3.0e3
_bf16_snr_warned = [False]


def warn_bf16_high_snr(max_channel_snr, quiet=False):
    """One-line, once-per-process warning when the bf16 cross-spectrum
    storage default is active and a fit's channel S/N exceeds the
    regime the calibration tests cover — the knob's failure mode is
    documented (GUIDE.md), but users who never read it deserve a
    runtime signal.  Returns True when the warning fired."""
    import math

    if (_bf16_snr_warned[0] or not use_bf16_cross_spectrum()
            or not math.isfinite(max_channel_snr)
            or max_channel_snr <= BF16_CALIBRATED_CHANNEL_SNR):
        return False
    if quiet:
        # a quiet caller must not consume the single warning: a later
        # non-quiet run on the same hot data still deserves it
        return True
    _bf16_snr_warned[0] = True
    print(f"Warning: channel S/N {max_channel_snr:.0f} exceeds the "
          f"bf16 cross-spectrum calibrated regime "
          f"(~{BF16_CALIBRATED_CHANNEL_SNR:.0f}); consider "
          "config.cross_spectrum_dtype = None for this data")
    return True


def effective_x_bf16(compensated, x_bf16=None):
    """The bf16 cross-spectrum storage flag *actually in effect* for a
    scattering program: compensated mode forces f32 X, so the bf16 knob
    is dead under it.  Every lane that folds the knob into a jit cache
    key (fast batch, streaming bucket programs) must key on THIS value,
    or flipping the knob under compensated mode recompiles a
    bit-identical program."""
    if x_bf16 is None:
        x_bf16 = use_bf16_cross_spectrum()
    return bool(x_bf16) and not bool(compensated)


def split_ir_host(ir_FT, dt):
    """Split a HOST complex instrumental-response FT into two real
    device arrays.  Complex buffers cannot cross some tunneled-runtime
    transports at all, so the response always ships as (ir_r, ir_i)
    and is reassembled (or consumed split) device-side.  None -> (None,
    None)."""
    if ir_FT is None:
        return None, None
    import numpy as _np

    ir_h = _np.asarray(ir_FT)
    return jnp.asarray(ir_h.real, dt), jnp.asarray(ir_h.imag, dt)


def _moments_xla(t_n, X):
    """Harmonic moments (C, C1, C2) of complex X under rotation t_n —
    the XLA reference path (one read of X, three fused reductions)."""
    nharm = X.shape[-1]
    dt = t_n.dtype
    k2pi = 2.0 * jnp.pi * jnp.arange(nharm, dtype=dt)
    W = X * cexp(t_n[:, None] * k2pi)
    return (
        jnp.sum(W, axis=-1).real,
        -jnp.sum(W * k2pi, axis=-1).imag,
        -jnp.sum(W * k2pi**2, axis=-1).real,
    )


def _moments_real_xla(t_n, Xr, Xi):
    """Same moments from split real/imag parts, with no complex types
    anywhere (the real core's XLA fallback)."""
    nharm = Xr.shape[-1]
    dt = t_n.dtype
    k2pi = 2.0 * jnp.pi * jnp.arange(nharm, dtype=dt)
    ang = t_n[:, None] * k2pi
    c = jnp.cos(ang)
    s = jnp.sin(ang)
    wr = Xr * c - Xi * s
    wi = Xr * s + Xi * c
    return (
        jnp.sum(wr, axis=-1),
        -jnp.sum(wi * k2pi, axis=-1),
        -jnp.sum(wr * k2pi**2, axis=-1),
    )


def _cgh_tail(C, C1, C2, S0inv, cvec, gvec, dt):
    """(f, grad5, hess5) from the harmonic moments."""
    f = -jnp.sum(C**2.0 * S0inv)
    base1 = 2.0 * C * C1 * S0inv  # dchi2'/dt_n
    base2 = 2.0 * (C1**2.0 + C * C2) * S0inv
    ones = jnp.ones_like(cvec)
    J = jnp.stack([ones, cvec, gvec])  # (3, nchan): dt_n/d(phi,DM,GM)
    g3 = -(J @ base1)
    H3 = -(J * base2) @ J.T
    g5 = jnp.zeros(5, dt).at[:3].set(g3)
    H5 = jnp.zeros((5, 5), dt).at[:3, :3].set(H3)
    return f, g5, H5


def _cgh_fast(theta, X, S0inv, cvec, gvec):
    """(f, grad5, hess5) of chi2' in ONE pass over X — the fused
    analytic fast path for fits with no active scattering parameters.

    S0inv: precomputed 1/S_n (0 for masked channels); cvec/gvec: the
    linear coefficients of t_n in (DM, GM).
    """
    dt = S0inv.dtype
    t_n = theta[0] + cvec * theta[1] + gvec * theta[2]
    C, C1, C2 = _moments_xla(t_n, X)
    return _cgh_tail(C, C1, C2, S0inv, cvec, gvec, dt)


def _two_sum(ah, al, bh, bl):
    """Double-float (hi, lo) addition (Knuth TwoSum on the hi words,
    lows accumulated) — vectorized, no data-dependent control flow."""
    s = ah + bh
    bb = s - ah
    err = (ah - (s - bb)) + (bh - bb)
    return s, al + bl + err


def _pair_sum_df64(x, lo=None):
    """Sum the last axis exactly-to-working-precision via a pairwise
    double-float reduction tree: every level combines adjacent pairs
    with TwoSum, carrying the rounding residue in a lo word.  log2(n)
    passes over a halving array (total traffic ~2x a plain sum), fully
    vectorized — unlike Kahan/Neumaier loops, nothing is sequential.

    The result hi+lo is the correctly-rounded-to-~2eps sum of the f32
    inputs; combined with FMA product-error capture at the call sites
    this is the Ogita-Rump-Oishi Dot2 structure, giving as-if-2x-
    precision reductions on hardware with no f64 (TPU)."""
    hi = x
    lo = jnp.zeros_like(x) if lo is None else lo
    # combine contiguous HALVES at each level (same reduction tree as
    # adjacent pairs — TwoSum is exact for any operands — but the
    # slices stay contiguous along the lane dimension, which measures
    # ~10x faster than stride-2 gathers on TPU)
    while hi.shape[-1] > 1:
        n = hi.shape[-1]
        if n % 2:
            hi = jnp.pad(hi, [(0, 0)] * (hi.ndim - 1) + [(0, 1)])
            lo = jnp.pad(lo, [(0, 0)] * (lo.ndim - 1) + [(0, 1)])
            n += 1
        half = n // 2
        hi, lo = _two_sum(hi[..., :half], lo[..., :half],
                          hi[..., half:], lo[..., half:])
    return hi[..., 0] + lo[..., 0]


def _dot2(a, b):
    """sum_k a_k b_k with the product rounding errors captured by an
    exact two-product (Dekker/Veltkamp split — no FMA primitive exists
    in jax) and the summation done df64-pairwise (Dot2): error ~eps
    instead of ~n*eps — the compensated path for the scattering
    moments.  (A blocked f32-within-chunks variant measured the SAME
    throughput and tau floor on TPU — the cost is the elementwise
    two-product work, not the tree — so the simpler exact tree stays.)
    """
    p, e = _two_product(a, b)
    return _pair_sum_df64(p, e)


def _two_product(a, b):
    """Exact product splitting: returns (p, e) with p = fl(a*b) and
    p + e == a*b exactly (Dekker's TwoProduct via the Veltkamp split;
    the split constant is 2^ceil(prec/2)+1 per dtype).  Elementwise and
    branch-free, so it vectorizes like a plain multiply."""
    dt = jnp.result_type(a, b)
    split = {jnp.dtype(jnp.float32): 4097.0,        # 2^12 + 1
             jnp.dtype(jnp.float64): 134217729.0}    # 2^27 + 1
    c = split.get(jnp.dtype(dt), 4097.0)
    p = a * b
    ac = a * c
    ah = ac - (ac - a)
    al = a - ah
    bc = b * c
    bh = bc - (bc - b)
    bl = b - bh
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def _cgh_scatter(theta, Xr, Xi, M2, freqs, nu_fit, cvec, gvec,
                 log10_tau, compensated=False):
    """(f, grad5, hess5, (C, S)) of chi2' with the scattering kernel
    active, in ONE fused pass over the cross-spectrum — the analytic
    replacement for value_and_grad + jax.hessian re-evaluation (which
    re-read X ~10x per Newton step).  Complex-free: the cross-spectrum
    arrives as split (Xr, Xi) real parts, so the whole scattering fit
    compiles into one program on TPU runtimes that cannot lower complex
    FFTs (same design as _moments_real_xla for the no-scatter lane).

    Chain structure (reference pptoaslib.py:231-561, re-derived):
      t_n   = phi + c_n DM + g_n GM            (phasor path)
      tau_n = T(theta3) (nu_n/nu_fit)^alpha    (kernel path)
      B_k   = 1/(1 + 2 pi i tau_n k),  dB/dtau = -2 pi i k B^2
              (equivalently the reference's B(B-1)/tau,
               pptoaslib.py:344-356),  d2B/dtau2 = -8 pi^2 k^2 B^3
      C_n   = sum_k Re[X conj(B) e^{2 pi i t k}],  S_n = sum_k M2 |B|^2
      chi2' = -sum_n C_n^2 / S_n

    Nine k-reductions per channel feed exact 5x5 curvature; X/M2 must
    already include any instrumental response (X' = X conj(ir),
    M2' = M2 |ir|^2 — the response factors out of every derivative).
    (C, S) ride along as Newton-state aux so finalization needs no
    extra pass.

    compensated=True runs every k-reduction through the Dot2 scheme
    (_dot2: FMA product-residue capture + df64 pairwise summation),
    cutting the f32 accumulation error from ~n*eps to ~sqrt(n)*eps of
    the per-term flops — the option that lets the TPU-shaped f32 path
    resolve the chi^2 valley to the sigma_tau-limited regime instead
    of the 0.1-1% f32 floor (VERDICT round 2, weak #3).
    """
    dt = M2.dtype
    nharm = Xr.shape[-1]
    k = jnp.arange(nharm, dtype=dt)
    twopi = 2.0 * jnp.pi

    # kernel path
    r = (freqs / nu_fit).astype(dt)
    logr = jnp.log(r)
    if log10_tau:
        T = 10.0 ** theta[3]
        tau_n = T * r ** theta[4]
        ln10 = jnp.log(10.0).astype(dt)
        s1 = ln10 * tau_n
        s11 = ln10 ** 2.0 * tau_n
        s12 = ln10 * tau_n * logr
    else:
        T = theta[3]
        ra = r ** theta[4]
        tau_n = T * ra
        s1 = ra
        s11 = jnp.zeros_like(ra)
        s12 = ra * logr
    s2 = tau_n * logr
    s22 = tau_n * logr ** 2.0

    # phasor path
    t_n = theta[0] + cvec * theta[1] + gvec * theta[2]

    beta = twopi * tau_n  # (nchan,)
    bk = beta[:, None] * k  # (nchan, nharm)
    q = 1.0 / (1.0 + bk * bk)  # |B|^2
    # conj(B) = (1 + i bk) q
    cBr = q
    cBi = bk * q
    ang = twopi * t_n[:, None] * k
    c = jnp.cos(ang)
    s = jnp.sin(ang)
    er = Xr * c - Xi * s  # Re[X e]
    ei = Xr * s + Xi * c  # Im[X e]
    # U = X conj(B) e
    Ur = er * cBr - ei * cBi
    Ui = er * cBi + ei * cBr
    # U conj(B)
    UBr = Ur * cBr - Ui * cBi
    UBi = Ur * cBi + Ui * cBr
    # U conj(B)^2 (real part only needed)
    UB2r = UBr * cBr - UBi * cBi

    k2 = k * k
    if compensated:
        def red1(x):
            return _pair_sum_df64(x)

        def red(a, b):
            return _dot2(a, b)
    else:
        def red1(x):
            return jnp.sum(x, axis=-1)

        def red(a, b):
            return jnp.sum(a * b, axis=-1)
    C = red1(Ur)
    C_t = -twopi * red(k, Ui)
    C_tt = -(twopi ** 2.0) * red(k2, Ur)
    C_tau = -twopi * red(k, UBi)
    C_taut = -(twopi ** 2.0) * red(k2, UBr)
    C_tautau = -2.0 * twopi ** 2.0 * red(k2, UB2r)

    M2q = M2 * q
    S = red1(M2q)
    Sk2q2 = red(M2q * q, k2)
    Sk4q3 = red(M2q, (q * k2) ** 2.0)
    S_tau = -2.0 * twopi ** 2.0 * tau_n * Sk2q2
    S_tautau = (-2.0 * twopi ** 2.0 * Sk2q2
                + 8.0 * twopi ** 4.0 * tau_n ** 2.0 * Sk4q3)

    # chain to (phi, DM, GM, theta3, alpha)
    ones = jnp.ones_like(cvec)
    zeros = jnp.zeros_like(cvec)
    Jt = jnp.stack([ones, cvec, gvec, zeros, zeros])   # (5, nchan)
    Jtau = jnp.stack([zeros, zeros, zeros, s1, s2])    # (5, nchan)
    Cp = Jt * C_t + Jtau * C_tau                       # (5, nchan)
    Sp = Jtau * S_tau

    good = S > 0.0
    Sinv = jnp.where(good, 1.0 / jnp.where(good, S, 1.0), 0.0)
    CS = C * Sinv
    f = -jnp.sum(C * CS)

    g = -2.0 * (Cp @ CS) + (Sp @ CS ** 2.0)

    # Hessian: per-channel scalar weights contracted with the Jacobian
    # outer products (einsum keeps it one (5,5,nchan)-free assembly)
    w_tt = -2.0 * (C * C_tt) * Sinv
    w_taut = -2.0 * (C * C_taut) * Sinv
    w_tautau = -2.0 * (C * C_tautau) * Sinv
    H = (
        jnp.einsum("n,in,jn->ij", w_tt, Jt, Jt)
        + jnp.einsum("n,in,jn->ij", w_taut, Jt, Jtau)
        + jnp.einsum("n,in,jn->ij", w_taut, Jtau, Jt)
        + jnp.einsum("n,in,jn->ij", w_tautau, Jtau, Jtau)
    )
    # -2 C_p C_q / S
    H = H - 2.0 * jnp.einsum("in,n,jn->ij", Cp, Sinv, Cp)
    # + 2 C (C_p S_q + C_q S_p) / S^2
    CpSq = jnp.einsum("in,n,jn->ij", Cp, 2.0 * CS * Sinv, Sp)
    H = H + CpSq + CpSq.T
    # + C^2 S_pq / S^2 - 2 C^2 S_p S_q / S^3
    w_sp = CS ** 2.0
    H = H + jnp.einsum("n,in,jn->ij", w_sp * S_tautau, Jtau, Jtau)
    H = H - 2.0 * jnp.einsum("in,n,jn->ij", Sp, w_sp * Sinv, Sp)
    # second-derivative terms of the tau(theta3, alpha) chain itself:
    # dC/dtau_n * d2tau_n/(dp dq) and dS/dtau_n * d2tau_n/(dp dq)
    chain_C = -2.0 * CS * C_tau + w_sp * S_tau
    h33 = jnp.sum(chain_C * s11)
    h34 = jnp.sum(chain_C * s12)
    h44 = jnp.sum(chain_C * s22)
    H = H.at[3, 3].add(h33).at[3, 4].add(h34).at[4, 3].add(h34) \
         .at[4, 4].add(h44)
    return f, g, H, (C, S)


# Initial Levenberg damping for SCATTERING fits.  The generic 1e-3
# perturbs the well-seeded Newton trajectory enough that a tail of
# batch elements needs ~23 trips (the vmapped while_loop pays for the
# MAX, not the median); 1e-5 measured on TPU at bench config 3:
# nfev max 23 -> 16, every element rc=0, +37% throughput, tau accuracy
# unchanged.  Poor seeds stay safe: rejections still grow lam 8x/trip.
_SCATTER_LAM0 = 1e-5

# Per-iteration step bound for (phi, DM, GM, theta3, alpha) — see the
# trust-bound comment in _newton_loop's body.
_STEP_CAP = (float("inf"), float("inf"), float("inf"), 1.0, 2.0)


def _scatter_ftol(dt, compensated=False):
    """Convergence threshold for SCATTERING fits.  The generic
    50*eps(|f|+1) is loose enough that an f32 tau fit stops a
    deterministic ~0.3% short of the true minimum (measured round 3:
    bias -3.2e-3 at ftol=3e-6, -1.1e-4 at 1e-8, floor -6e-5 at 1e-10) —
    far above extreme-S/N sigma_tau.  f32 scattering fits therefore run
    to 1e-9 by default (round 6: was 1e-8 — the tau-matched CCF seed
    lands the loop so close that the old threshold could stop a trip
    early and leave the plain-lane high-S/N tau bias at ~2.5e-4; one
    decade buys ~1 extra trip from a 3-trip fit and holds the floor
    near -1.5e-4), and 1e-10 when the compensated Dot2 reductions are
    on (their purpose is precisely this regime; the remaining floor is
    elementwise product/trig rounding, which no summation scheme can
    remove).  f64 keeps 50*eps."""
    if jnp.dtype(dt) == jnp.float32:
        return 1e-10 if compensated else 1e-9
    return 50.0 * float(jnp.finfo(dt).eps)


# Compensated polish budget: the plain loop lands within ~1e-4 of the
# true minimum (its f32 convergence floor), from where the Dot2
# objective needs 1-3 accepted steps to reach the 1e-10 ftol — plus the
# bootstrap trip.  6 bounds the worst case; convergence exits earlier.
_POLISH_MAX_ITER = 6


def _hybrid_scatter_loop(cgh_plain, cgh_comp, theta0, flags_arr,
                         max_iter, ftol_comp, dt, lam0=_SCATTER_LAM0,
                         bounds=None):
    """Two-stage scattering Newton: plain f32 accumulation to its own
    convergence floor, then a short compensated (Dot2) polish from the
    converged point.  The first ~14 trips of a compensated fit never
    needed compensated arithmetic — only the endgame near the f32 noise
    floor does — so paying the ~2x Dot2 reduction traffic on 2-3 polish
    evals instead of every eval recovers most of the plain lane's
    throughput at the compensated mode's tau floor (VERDICT r3 #3).

    The polish restarts from a bootstrap trip (f=+inf): plain and
    compensated objectives differ by more than ftol*|f| near the floor,
    so f values cannot be carried across evaluator schedules (same
    reasoning as the in-loop bootstrap, _newton_loop docstring).
    nfev/it report the sum over both stages — a compensated fit can
    therefore report up to max_iter + _POLISH_MAX_ITER + 2 evals (the
    polish budget plus the two bootstrap trips), beyond the caller's
    max_iter.

    Return code: the polish's, except that exhausting the short polish
    budget (code 3) falls back to the plain stage's code when that
    stage terminated normally — a plain-converged fit polished to the
    cap is refined, not failed, and must not be demoted below what the
    plain lane would have reported."""
    s1 = _newton_loop(cgh_plain, theta0, flags_arr, max_iter,
                      _scatter_ftol(dt, False), lam0=lam0, bounds=bounds)
    s2 = _newton_loop(cgh_comp, s1.theta, flags_arr, _POLISH_MAX_ITER,
                      ftol_comp, lam0=lam0, bounds=bounds)
    code = jnp.where(jnp.logical_and(s2.code == 3, s1.code != 3),
                     s1.code, s2.code)
    return s2._replace(nfev=s1.nfev + s2.nfev, it=s1.it + s2.it,
                       code=code)


def _initial_phase_guess(X, cvec, DM0, oversamp=2):
    """Dense-CCF phase guess of the frequency-summed, DM0-derotated
    data against the frequency-summed model (the reference's
    rotate+fit_phase_shift seeding, pptoas.py:458-513, in one shot)."""
    nharm = X.shape[-1]
    nbin = 2 * (nharm - 1)
    dt = cvec.dtype
    k = jnp.arange(nharm, dtype=dt)
    ph = cexp(2.0 * jnp.pi * (cvec * DM0)[:, None] * k)
    x = jnp.sum(X * ph, axis=0)
    nlag = nbin * oversamp
    ccf = irfft_c(x, n=nlag)
    j0 = jnp.argmax(ccf)
    phi0 = j0.astype(dt) / nlag
    return jnp.mod(phi0 + 0.5, 1.0) - 0.5


class _NewtonState(NamedTuple):
    theta: jnp.ndarray
    f: jnp.ndarray
    g: jnp.ndarray
    H: jnp.ndarray
    aux: object
    lam: jnp.ndarray
    it: jnp.ndarray
    nfev: jnp.ndarray
    rej: jnp.ndarray
    code: jnp.ndarray
    done: jnp.ndarray


def _with_no_aux(cgh):
    """Adapt a (f, g, H) evaluator to the (f, g, H, aux) contract."""

    def wrapped(theta):
        f, g, H = cgh(theta)
        return f, g, H, ()

    return wrapped


def _newton_loop(cgh, theta0, flags_arr, max_iter, ftol, lam0=1.0e-3,
                 stall_max=4, bounds=None):
    """Levenberg-damped Newton minimization given a fused
    (f, grad, hess, aux) evaluator — exactly one cgh() call per
    iteration.  aux is any pytree computed alongside (e.g. the
    per-channel moments C); the state always carries the aux that
    matches state.theta, so callers can package results without an
    extra objective evaluation after the loop.

    Damping uses H + lam*diag(|H|) (scale-invariant, LM-style), so no
    per-parameter preconditioning is needed despite phi/DM/GM living on
    wildly different scales.  Convergence when the predicted quadratic
    improvement 0.5 g^T diag(H)^-1 g falls below ftol * (|f| + 1)
    (dtype-aware default).  A run of stall_max consecutive *flat*
    rejections — f_new within machine noise of f, i.e. no improving
    step exists and lam growth has shrunk the damped step to nothing —
    also terminates: that is the floating-point optimum, and without
    this exit a handful of such elements pins a whole vmapped batch at
    max_iter (measured 26 vs 2 median evals at bench shapes).  Genuine
    overshoots (f_new clearly above f, normal during early lam
    adaptation from a distant seed) reset the flat counter and never
    trigger the exit.  Return codes follow the reference's small
    vocabulary (config.RCSTRINGS): 0 converged, 2 step-size underflow
    (tolerated as success, like the reference's {1,2,4};
    pptoaslib.py:1068), 3 max-iterations.

    bounds: optional (5, 2) [lo, hi] box (+-inf = open), the
    user-facing analogue of the reference's TNC bounds
    (pptoaslib.py:1039-1060): steps are PROJECTED onto the box
    (clipped damped Newton — TNC's active-set behavior for a box), an
    infeasible seed is projected in, and the exit code follows TNC's
    vocabulary in bounds mode: a converged fit with an ACTIVE bound on
    a fitted parameter reports 0 (LOCALMINIMUM: |projected g| ~= 0 —
    the constrained-optimum stop), interior convergence reports 1
    (CONVERGED); stall/max-iteration codes are unchanged.  With
    bounds=None the vocabulary is exactly the historical one (0
    converged, 2 stall, 3 max-iter).

    The initial objective is evaluated INSIDE the loop (a bootstrap
    trip with a zero step from f=+inf, g=0, H=I), never before it.
    XLA fuses an outside-the-loop cgh instance into the surrounding
    program with a different reduction schedule than the loop body's
    instance, and on TPU the two disagree by O(sqrt(N) eps |f|) —
    larger than the whole first-step improvement of a near-perfectly
    seeded element, which then gets every step spuriously rejected
    (measured: 20/640 bench elements pinned at max_iter).  Keeping all
    f comparisons between identically-scheduled evaluations costs one
    loop trip and removes the failure mode.
    """
    nfix = 1.0 - flags_arr
    dt = theta0.dtype
    if bounds is not None:
        blo = jnp.asarray(bounds, dt)[..., 0]
        bhi = jnp.asarray(bounds, dt)[..., 1]
        # project an infeasible seed into the box (TNC does the same) —
        # FITTED parameters only: a fixed parameter's held value is
        # part of the model, and clipping it would silently corrupt
        # the fit (reference TNC only bounds fitted parameters)
        theta0 = jnp.where(flags_arr > 0.0,
                           jnp.clip(theta0, blo, bhi), theta0)

    def mask_gH(g, H):
        g = g * flags_arr
        H = H * jnp.outer(flags_arr, flags_arr) + jnp.diag(nfix)
        return g, H

    def project_active(theta, g, H):
        """Active-set projection at the box: a parameter pinned at a
        bound with the gradient pushing OUTWARD is treated like a
        fixed parameter (g zeroed, identity Hessian row/col), so the
        convergence measure becomes the PROJECTED gradient — without
        this, a bound-limited fit never satisfies the interior
        criterion and burns max_iter re-clipping the same step.  A
        bound-touching parameter whose gradient points inward stays
        free (it can leave the bound)."""
        if bounds is None:
            return g, H
        out = ((jnp.isfinite(blo) & (theta <= blo) & (g > 0.0))
               | (jnp.isfinite(bhi) & (theta >= bhi) & (g < 0.0)))
        free = 1.0 - out.astype(dt)
        return g * free, H * jnp.outer(free, free) + jnp.diag(
            1.0 - free)

    def cond(s):
        # max_iter + 1: the bootstrap trip is not a Newton iteration
        return jnp.logical_and(s.it < max_iter + 1, jnp.logical_not(s.done))

    def _pred(g, H):
        """Predicted quadratic improvement of a diagonal-Newton step —
        the convergence measure (scale-invariant in f)."""
        dH = jnp.abs(jnp.diag(H))
        dH = jnp.maximum(dH, 1e-12 * jnp.max(dH))
        return 0.5 * jnp.sum(g**2.0 / jnp.maximum(dH, _tiny(dt))), dH

    def body(s):
        g, H = mask_gH(s.g, s.H)
        g, H = project_active(s.theta, g, H)
        pred_cur, dH = _pred(g, H)
        # converged at the incumbent point (handles warm starts at the
        # optimum, where no strictly-improving step exists); the
        # isfinite guard keeps the bootstrap trip (f = +inf) alive
        conv_now = jnp.logical_and(
            pred_cur < ftol * (jnp.abs(s.f) + 1.0), jnp.isfinite(s.f))
        A = H + s.lam * jnp.diag(dH)
        step = -jnp.linalg.solve(A, g)
        # per-step trust bound on the scattering-kernel parameters:
        # along the soft tau-alpha valley a near-singular H makes the
        # Newton step arbitrarily large, and at extreme tau the
        # objective has a spurious descent path (every channel
        # collapses onto its lowest surviving harmonic, where C^2/S
        # stays finite as B -> 0).  One decade of log10-tau (or one
        # rotation) and 2 units of alpha per ITERATION is generous for
        # any legitimate trajectory while making the pathological
        # region unreachable within max_iter from any sane seed.
        # phi/DM/GM enter the phasor linearly and need no cap.
        cap = jnp.asarray(_STEP_CAP, dt)
        step = jnp.clip(step, -cap, cap)
        theta_new = s.theta + step * flags_arr
        if bounds is not None:
            theta_new = jnp.where(flags_arr > 0.0,
                                  jnp.clip(theta_new, blo, bhi),
                                  theta_new)
        f_new, g_new, H_new, aux_new = cgh(theta_new)
        accept_f = jnp.logical_and(f_new < s.f, jnp.logical_not(conv_now))
        gm, Hm = mask_gH(g_new, H_new)
        gm, _ = project_active(theta_new, gm, Hm)
        pred_new, _ = _pred(gm, H)
        # f-flat step: f_new within machine noise of f — near the
        # optimum true improvements sink below the f-evaluation noise
        # (~sqrt(N) eps |f|), so f comparisons go blind there
        f_flat = f_new <= s.f + 64.0 * jnp.finfo(dt).eps * (
            jnp.abs(s.f) + 1.0)
        # gradient-guided acceptance through the flat zone: the analytic
        # gradient keeps resolving descent long after f differences
        # drown (measured: cuts the extreme-S/N f32 tau floor ~5x).
        # A DECISIVE decrease (4x in the predicted improvement) is
        # required — accepting any fluctuation would random-walk along
        # soft Hessian directions (the tau-alpha degeneracy) where
        # near-singular H makes steps large at noise-level gradients;
        # the 4x floor makes the accepted sequence strictly contracting
        # in pred, so it must terminate at the conv threshold.  Guarded
        # by isfinite so the bootstrap trip can't take it.
        accept_g = jnp.logical_and(
            jnp.logical_and(f_flat, jnp.logical_not(accept_f)),
            jnp.logical_and(
                pred_new < 0.25 * pred_cur,
                jnp.logical_and(jnp.isfinite(s.f),
                                jnp.logical_not(conv_now))))
        accept = jnp.logical_or(accept_f, accept_g)
        # the isfinite guard keeps the bootstrap trip (whose pred_new is
        # judged against the placeholder identity Hessian, not real
        # curvature) from ever declaring step-convergence at the seed
        done_conv = jnp.logical_or(
            conv_now,
            jnp.logical_and(
                jnp.logical_and(accept, jnp.isfinite(s.f)),
                pred_new < ftol * (jnp.abs(f_new) + 1.0)),
        )
        flat = jnp.logical_and(jnp.logical_not(accept), f_flat)
        rej_new = jnp.where(flat, s.rej + 1, 0)
        done_stall = jnp.logical_and(rej_new >= stall_max,
                                     jnp.logical_not(done_conv))
        done = jnp.logical_or(done_conv, done_stall)
        code = jnp.where(done_conv, 0, jnp.where(done_stall, 2, s.code))
        return _NewtonState(
            theta=jnp.where(accept, theta_new, s.theta),
            f=jnp.where(accept, f_new, s.f),
            g=jnp.where(accept, g_new, s.g),
            H=jnp.where(accept, H_new, s.H),
            aux=jax.tree_util.tree_map(
                lambda a, b: jnp.where(accept, a, b), aux_new, s.aux),
            # flat (gradient-guided) accepts keep lam: decaying it there
            # would let later steps grow unboundedly along soft
            # directions where f can no longer arbitrate
            lam=jnp.where(
                accept_f, s.lam * 0.33,
                jnp.where(accept_g, s.lam, s.lam * 8.0),
            ).clip(1e-14, 1e14),
            it=s.it + 1,
            nfev=s.nfev + 1,
            rej=rej_new,
            code=code,
            done=done,
        )

    # bootstrap state: f=+inf, g=0, H=I => the first trip proposes a
    # zero step, evaluates cgh(theta0) in-loop, and always accepts it;
    # aux shapes come from eval_shape (nothing executes here)
    aux_shape = jax.eval_shape(cgh, theta0)[3]
    aux0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, a.dtype), aux_shape)
    s0 = _NewtonState(
        theta=theta0,
        f=jnp.asarray(jnp.inf, dt),
        g=jnp.zeros(5, dt),
        H=jnp.eye(5, dtype=dt),
        aux=aux0,
        # the bootstrap acceptance multiplies by 0.33; pre-divide so the
        # first Newton trip sees exactly lam0
        lam=jnp.asarray(lam0 / 0.33, dt),
        it=jnp.asarray(0, jnp.int32),
        nfev=jnp.asarray(0, jnp.int32),
        rej=jnp.asarray(0, jnp.int32),
        code=jnp.asarray(3, jnp.int32),
        done=jnp.asarray(False),
    )
    s = jax.lax.while_loop(cond, body, s0)
    if bounds is not None:
        # TNC-vocabulary exit codes in bounds mode: the projection
        # lands a bound-limited parameter EXACTLY on the clip value,
        # so activity is an equality test, masked to fitted params
        # with a finite bound on the touched side
        at_b = jnp.any(
            (flags_arr > 0.0)
            & ((jnp.isfinite(blo) & (s.theta <= blo))
               | (jnp.isfinite(bhi) & (s.theta >= bhi))))
        s = s._replace(code=jnp.where(
            s.code == 0, jnp.where(at_b, 0, 1), s.code))
    # if no trip ever accepted (objective NaN on every evaluation, e.g.
    # corrupted input data), the state still holds the bootstrap
    # placeholders (H=I, aux=0).  Poison them so _finalize_fit reports
    # NaN/inf errors and scales — matching the pre-bootstrap behavior
    # the degenerate-fit guards downstream rely on — instead of
    # plausible finite values.
    bad = jnp.logical_not(jnp.isfinite(s.f))
    nan = jnp.asarray(jnp.nan, dt)
    return s._replace(
        H=jnp.where(bad, nan, s.H),
        aux=jax.tree_util.tree_map(
            lambda a: jnp.where(bad, jnp.asarray(jnp.nan, a.dtype), a),
            s.aux),
    )


@partial(
    jax.jit,
    static_argnames=("fit_flags", "log10_tau", "max_iter", "use_ir",
                     "use_scatter", "auto_seed", "compensated"),
)
def _fit_portrait_core(
    dFT,
    mFT,
    w,
    freqs,
    P,
    nu_fit,
    nu_out,
    theta0,
    ir_FT=None,
    fit_flags=FitFlags(),
    log10_tau=False,
    max_iter=40,
    ftol=None,
    use_ir=False,
    use_scatter=False,
    auto_seed=True,
    compensated=False,
    bounds=None,
):
    dt = w.dtype
    flags_arr = FitFlags(*fit_flags).as_array(dt)
    ir = ir_FT if use_ir else None
    # log10_tau implies tau = 10^theta3 > 0 always, so the no-scatter
    # fast path would be inconsistent with the final scales/chi2
    scatter = (use_scatter or use_ir or fit_flags[3] or fit_flags[4]
               or log10_tau)
    if ftol is None:
        ftol = (_scatter_ftol(dt, compensated) if scatter
                else 50.0 * float(jnp.finfo(dt).eps))

    # --- precompute: everything the optimizer reads per step ----------
    X = dFT * jnp.conj(mFT) * w  # (nchan, nharm) complex
    cvec, gvec = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(dt)
    gvec = gvec.astype(dt)

    if scatter:
        M2 = (mFT.real**2 + mFT.imag**2) * w
        # the instrumental response factors out of every tau/phase
        # derivative, so fold it into the spectra once (X' = X conj(ir),
        # M2' = M2 |ir|^2) and run the pure-scattering chain
        if ir is not None:
            Xs = X * jnp.conj(ir)
            M2s_ = M2 * (ir.real**2.0 + ir.imag**2.0)
        else:
            Xs, M2s_ = X, M2

        def mk_cgh(comp):
            def cgh(theta):
                f, g, H, _aux = _cgh_scatter(
                    theta, Xs.real, Xs.imag, M2s_, freqs, nu_fit,
                    cvec, gvec, log10_tau, comp)
                return f, g, H
            return cgh

        cgh = mk_cgh(False)

    else:
        S0 = jnp.sum((mFT.real**2 + mFT.imag**2) * w, axis=-1)
        good0 = S0 > 0.0
        S0inv = jnp.where(good0, 1.0 / jnp.where(good0, S0, 1.0), 0.0)

        def cgh(theta):
            return _cgh_fast(theta, X, S0inv, cvec, gvec)

    # seed phi by dense CCF at the DM guess (unless the caller supplied
    # an explicit phase seed or phi is fixed)
    if auto_seed and fit_flags[0]:
        phi0 = _initial_phase_guess(X, cvec, theta0[1])
        theta0 = jnp.where(jnp.arange(5) == 0, phi0, theta0).astype(dt)
    else:
        theta0 = theta0.astype(dt)

    if scatter and compensated:
        s = _hybrid_scatter_loop(
            _with_no_aux(cgh), _with_no_aux(mk_cgh(True)),
            theta0, flags_arr, max_iter, ftol, dt, bounds=bounds)
    else:
        s = _newton_loop(_with_no_aux(cgh), theta0, flags_arr, max_iter,
                         ftol, lam0=_SCATTER_LAM0 if scatter else 1.0e-3,
                         bounds=bounds)
    theta = s.theta

    H = s.H
    M2s = (mFT.real**2 + mFT.imag**2) * w
    C, S = _CS_general(theta, X, M2s, freqs, P, nu_fit, ir, log10_tau)
    Sd = jnp.sum((dFT.real**2 + dFT.imag**2) * w)
    return _finalize_fit(
        theta, s, H, C, S, Sd, dFT.shape[-1], flags_arr, fit_flags,
        P, nu_fit, nu_out, log10_tau, dt)


def _finalize_fit(theta, s, H, C, S, Sd, nharm, flags_arr, fit_flags,
                  P, nu_fit, nu_out, log10_tau, dt):
    """Covariance, zero-covariance frequencies, re-referencing, scales,
    S/N, and chi2 packaging shared by the complex and real fit cores."""
    # --- covariance: chi2 ~ chi2_min + 0.5 d^T H d  =>  cov = 2 H^-1 on
    # the fitted subset (reference "inverted half-Hessian",
    # pplib.py:2266-2273 / pptoaslib.py:674-678)
    Hm = H * jnp.outer(flags_arr, flags_arr) + jnp.diag(1.0 - flags_arr)
    cov = 2.0 * jnp.linalg.inv(Hm) * jnp.outer(flags_arr, flags_arr)

    # --- zero-covariance reference frequencies (exact, via the
    # infinite-frequency parameterization; replaces pptoaslib.py:776-950)
    cD_fit = (Dconst / P) * nu_fit**-2.0
    cG_fit = (Dconst**2.0 / P) * nu_fit**-4.0
    J = jnp.eye(5, dtype=dt).at[0, 1].set(-cD_fit).at[0, 2].set(-cG_fit)
    covI = J @ cov @ J.T  # covariance of (phi_inf, DM, GM, taup, alpha)

    vD, vG, vDG = covI[1, 1], covI[2, 2], covI[1, 2]
    cpD, cpG = covI[0, 1], covI[0, 2]
    if fit_flags[1] and fit_flags[2]:
        det = vD * vG - vDG**2.0
        det_safe = jnp.where(jnp.abs(det) > _tiny(dt), det, 1.0)
        cD0 = (-cpD * vG + cpG * vDG) / det_safe
        cG0 = (-cpG * vD + cpD * vDG) / det_safe
    else:
        cD0 = -cpD / jnp.maximum(vD, _tiny(dt))
        cG0 = -cpG / jnp.maximum(vG, _tiny(dt))
    nu_zero_DM = jnp.where(
        cD0 > 0.0, (Dconst / (P * jnp.where(cD0 > 0, cD0, 1.0))) ** 0.5, nu_fit
    )
    nu_zero_GM = jnp.where(
        cG0 > 0.0, (Dconst**2.0 / (P * jnp.where(cG0 > 0, cG0, 1.0))) ** 0.25, nu_fit
    )
    if not fit_flags[1]:
        nu_zero_DM = nu_fit
    if not fit_flags[2]:
        nu_zero_GM = nu_fit

    # tau/alpha zero-covariance frequency: Cov(log tau_ref, alpha) = 0
    vA = covI[4, 4]
    cTA = covI[3, 4]
    tau_fit = _tau_of(theta, log10_tau)
    if log10_tau:
        dlog = -cTA / jnp.maximum(vA, _tiny(dt))
    else:
        dlog = -cTA / jnp.maximum(tau_fit * vA * jnp.log(10.0), _tiny(dt))
    dlog = jnp.where(jnp.logical_and(fit_flags[3], fit_flags[4]), dlog, 0.0)
    dlog = jnp.clip(dlog, -1.0, 1.0)  # keep within a decade of nu_fit
    nu_zero_tau = nu_fit * 10.0**dlog

    # --- re-reference outputs.  nu_out <= 0 means "use the
    # zero-covariance frequencies" (reference default behavior).
    nu_DM_out = jnp.where(nu_out > 0.0, nu_out, nu_zero_DM)
    nu_GM_out = jnp.where(nu_out > 0.0, nu_out, nu_zero_GM)
    nu_tau_out = jnp.where(nu_out > 0.0, nu_out, nu_zero_tau)

    cD_out = (Dconst / P) * nu_DM_out**-2.0
    cG_out = (Dconst**2.0 / P) * nu_GM_out**-4.0
    phi_inf = theta[0] - cD_fit * theta[1] - cG_fit * theta[2]
    phi_out = phi_inf + cD_out * theta[1] + cG_out * theta[2]
    phi_out = jnp.mod(phi_out + 0.5, 1.0) - 0.5
    u = jnp.array([1.0, cD_out, cG_out, 0.0, 0.0], dt)
    phi_var = u @ covI @ u
    # degenerate fits (e.g. all channels masked) produce a singular
    # Hessian -> NaN variance; report inf so downstream filters work
    phi_var = jnp.where(jnp.isfinite(phi_var), phi_var, jnp.inf)
    # fixed-phi fits report zero error
    phi_err = jnp.where(fit_flags[0], jnp.sqrt(jnp.maximum(phi_var, 0.0)), 0.0)

    r_tau = (nu_tau_out / nu_fit) ** theta[4]
    tau_out = tau_fit * r_tau
    if log10_tau:
        ut = jnp.array([0.0, 0.0, 0.0, 1.0, jnp.log10(nu_tau_out / nu_fit)], dt)
        taup_var = ut @ covI @ ut
        tau_err = jnp.sqrt(jnp.maximum(taup_var, 0.0)) * tau_out * jnp.log(10.0)
    else:
        ut = jnp.array(
            [0.0, 0.0, 0.0, r_tau, tau_out * jnp.log(nu_tau_out / nu_fit)], dt
        )
        tau_err = jnp.sqrt(jnp.maximum(ut @ covI @ ut, 0.0))

    DM_err = jnp.sqrt(jnp.maximum(cov[1, 1], 0.0))
    GM_err = jnp.sqrt(jnp.maximum(cov[2, 2], 0.0))
    alpha_err = jnp.sqrt(jnp.maximum(cov[4, 4], 0.0))

    # --- scales / SNRs / chi2
    S_safe = jnp.maximum(S, _tiny(dt))
    scales = C / S_safe
    scale_errs = S_safe**-0.5
    mask = (S > 0.0).astype(dt)
    channel_snrs = C / jnp.sqrt(S_safe) * mask
    snr = jnp.sqrt(jnp.maximum(jnp.sum(channel_snrs**2.0), 0.0))
    chi2 = Sd + s.f
    nbin = 2 * (nharm - 1)
    nfit = jnp.sum(flags_arr)
    dof = jnp.sum(mask) * (nbin - 1.0) - nfit - jnp.sum(mask)

    return FitResult(
        phi=phi_out,
        phi_err=phi_err,
        DM=theta[1],
        DM_err=DM_err,
        GM=theta[2],
        GM_err=GM_err,
        tau=tau_out,
        tau_err=tau_err,
        alpha=theta[4],
        alpha_err=alpha_err,
        nu_DM=nu_DM_out,
        nu_GM=nu_GM_out,
        nu_tau=nu_tau_out,
        scales=scales,
        scale_errs=scale_errs,
        channel_snrs=channel_snrs,
        snr=snr,
        covariance=cov,
        chi2=chi2,
        dof=dof,
        nfeval=s.nfev,
        return_code=s.code,
    )


def _initial_phase_guess_real(Xr, Xi, cvec, DM0, oversamp=2,
                              derotate=True, nbin=None):
    """_initial_phase_guess on split real/imag parts (complex-free):
    derotate by DM0, sum channels, dense CCF via the matmul inverse
    DFT, argmax.

    derotate=False (static) skips the per-channel trig entirely — valid
    when the caller knows DM0 == 0, where the phasor is identity.  At
    production shapes the derotation pass costs as much as a Newton
    moment pass, so the zero-DM-guess case (every cold-start batch fit)
    is worth the static branch.

    nbin: the true profile length — must be passed when Xr/Xi are
    band-limited (harmonic window) so the CCF lag grid keeps its full
    resolution."""
    from ..ops.fourier import irfft_mm

    nharm = Xr.shape[-1]
    if nbin is None:
        nbin = 2 * (nharm - 1)
    dt = cvec.dtype
    if derotate:
        k = jnp.arange(nharm, dtype=dt)
        ang = 2.0 * jnp.pi * (cvec * DM0)[:, None] * k
        c = jnp.cos(ang)
        s = jnp.sin(ang)
        xr = jnp.sum(Xr * c - Xi * s, axis=0)
        xi = jnp.sum(Xr * s + Xi * c, axis=0)
    else:
        xr = jnp.sum(Xr, axis=0)
        xi = jnp.sum(Xi, axis=0)
    nlag = nbin * oversamp
    ccf = irfft_mm(xr, xi, n=nlag)
    j0 = jnp.argmax(ccf)
    phi0 = j0.astype(dt) / nlag
    return jnp.mod(phi0 + 0.5, 1.0) - 0.5


def _parseval_Sd(port, w_full):
    """Weighted one-sided data power over ALL harmonics, computed from
    the TIME domain — the full-spectrum Sd that chi2 needs when the
    spectra themselves are band-limited (harmonic window).  Exact
    Parseval forms (DC handled per F0_fact):
      even n: sum_{k=1}^{n/2}   |X_k|^2 = (n sum x^2 - X_0^2
                                           + X_{n/2}^2)/2
      odd n (no Nyquist bin):   (n sum x^2 - X_0^2)/2
    w_full: the untruncated make_weights array — per-channel constant
    for k >= 1 (column 1), F0_fact-scaled at k = 0.

    The DC-free power uses the algebraically identical mean-removed
    form n*sum((x - mean)^2) rather than n*sum(x^2) - X_0^2: for data
    riding a baseline offset mu >> sigma the subtraction cancels
    catastrophically in f32 (measured 3x-wrong power at mu/sigma =
    5000), while the mean-removed form matches f64 to ~7 digits at the
    same cost."""
    dt = w_full.dtype
    nbin = port.shape[-1]
    x0 = jnp.sum(port, axis=-1)
    mu = x0 / nbin
    pwr = nbin * jnp.sum((port - mu[..., None]) ** 2, axis=-1)
    if nbin % 2 == 0:
        sgn = jnp.asarray((-1.0) ** jnp.arange(nbin), dt)
        xn = jnp.sum(port * sgn, axis=-1)
        pwr = pwr + xn**2
    Sd = jnp.sum(w_full[..., 1] * (0.5 * pwr))
    if float(F0_fact) != 0.0:
        Sd = Sd + jnp.sum(w_full[..., 0] * x0**2)
    return Sd


def prepare_portrait_fit_real(port, model, w, freqs, P, nu_fit, theta0,
                              seed_phi=True, seed_derotate=True,
                              x_dtype=None, nharm_eff=None,
                              dft_fold=None, fit_fused=None):
    """Everything before the Newton loop, in pure real arithmetic:
    matmul DFTs (ops/fourier.py — XLA's TPU FFT is ~2000x slower at
    these shapes), weighted cross-spectrum as a real pair, model/data
    powers, and the CCF phase seed.

    nharm_eff (static): band-limit the whole fit to the model's
    harmonic window (model_harmonic_window) — the DFTs emit only the
    first nharm_eff harmonics and Sd (the data power that chi2 needs
    over the FULL spectrum) switches to an exact time-domain Parseval
    form, so chi2/dof match the untruncated fit to rounding.

    Being complex-free end to end keeps the whole fit compilable on
    TPU runtimes whose transports and FFT lowerings cannot handle
    complex types at all (ops/fourier.py).
    Returns (Xr, Xi, S0, Sd, theta0_seeded).

    dft_fold: the fold-symmetry DFT knob, resolved by the BATCH
    wrappers and carried in their program-cache keys (None = read
    config at trace time, with the usual already-traced caveat).
    fit_fused: route the DFT -> cross-spectrum stage through the
    hand-blocked fused program (ops/fused.py; windowed lanes only —
    the full-spectrum Sd already comes from the time-domain Parseval
    form there, which is what keeps fused byte-identical to unfused).
    Resolved by the batch wrappers like dft_fold.
    """
    from ..ops.fourier import rfft_mm

    dt = w.dtype
    if fit_fused is None:
        fit_fused = resolve_fit_fused(nharm_eff)
    cvec, _ = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(dt)
    if fit_fused and nharm_eff is not None:
        from ..ops.fused import fused_cross_spectrum

        pallas, blk = _parse_fit_fused(fit_fused)
        w_full = w
        Xr, Xi, S0 = fused_cross_spectrum(
            port, model, w[..., :nharm_eff], nharm_eff, fold=dft_fold,
            block=blk, pallas=pallas)
        Sd = _parseval_Sd(port, w_full)
    else:
        dr, di = rfft_mm(port, nharm=nharm_eff, fold=dft_fold)
        mr, mi = rfft_mm(model, nharm=nharm_eff, fold=dft_fold)
        if nharm_eff is not None:
            w_full, w = w, w[..., :nharm_eff]
        # X = dFT * conj(mFT) * w, split into parts
        Xr = (dr * mr + di * mi) * w
        Xi = (di * mr - dr * mi) * w
        S0 = jnp.sum((mr**2 + mi**2) * w, axis=-1)
        if nharm_eff is None:
            Sd = jnp.sum((dr**2 + di**2) * w)
        else:
            Sd = _parseval_Sd(port, w_full)
    if seed_phi:
        phi0 = _initial_phase_guess_real(Xr, Xi, cvec, theta0[1],
                                         derotate=seed_derotate,
                                         nbin=port.shape[-1])
        theta0 = jnp.where(jnp.arange(5) == 0, phi0, theta0).astype(dt)
    else:
        theta0 = theta0.astype(dt)
    # optional narrow storage for the Newton loop's per-pass reads
    # (config.cross_spectrum_dtype); the seed above always reads the
    # full-precision values
    xdt = x_dtype or dt
    return Xr.astype(xdt), Xi.astype(xdt), S0, Sd, theta0


@partial(
    jax.jit,
    static_argnames=("fit_flags", "max_iter", "nharm_total"),
)
def _fit_portrait_core_real(
    Xr,
    Xi,
    S0,
    Sd,
    freqs,
    P,
    nu_fit,
    nu_out,
    theta0,
    fit_flags=FitFlags(),
    max_iter=40,
    ftol=None,
    nharm_total=None,
    bounds=None,
):
    """Stage 2 of the split fit: the (phi, DM, GM) Newton loop and
    result packaging in pure real arithmetic.

    Only valid for fits with no active scattering parameters (the
    _cgh_fast regime).  The harmonic moments run through the fused XLA
    reductions (_moments_real_xla) — results match _fit_portrait_core
    to round-off.  (A hand-written Pallas moment kernel existed through
    round 4 and was deleted: measured per-pass on v5e at 640x512x2048,
    XLA 10.9/9.9 ms f32/bf16 vs Pallas 31.3/21.5 direct and 24.2/14.6
    with a factorized phasor — benchmarks/BENCHMARKS.md round 4.)

    nharm_total: the FULL spectrum's harmonic count when Xr/Xi are
    band-limited (model_harmonic_window) — dof counts every data
    harmonic, not just the windowed ones.
    """
    assert not (fit_flags[3] or fit_flags[4]), (
        "real core handles the no-scattering path only")
    dt = S0.dtype
    nharm = nharm_total if nharm_total is not None else Xr.shape[-1]
    flags_arr = FitFlags(*fit_flags).as_array(dt)
    if ftol is None:
        ftol = 50.0 * float(jnp.finfo(dt).eps)
    good0 = S0 > 0.0
    S0inv = jnp.where(good0, 1.0 / jnp.where(good0, S0, 1.0), 0.0)
    cvec, gvec = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(dt)
    gvec = gvec.astype(dt)

    def cgh(theta):
        t_n = theta[0] + cvec * theta[1] + gvec * theta[2]
        C, C1, C2 = _moments_real_xla(t_n, Xr, Xi)
        f, g, H = _cgh_tail(C, C1, C2, S0inv, cvec, gvec, dt)
        return f, g, H, C

    s = _newton_loop(cgh, theta0.astype(dt), flags_arr, max_iter, ftol,
                     bounds=bounds)

    # the loop state carries the Hessian and moment vector C matching
    # s.theta, so no extra moment pass is needed at the solution
    return _finalize_fit(
        s.theta, s, s.H, s.aux, S0, Sd, nharm, flags_arr, fit_flags,
        P, nu_fit, nu_out, False, dt)


@partial(
    jax.jit,
    static_argnames=("fit_flags", "log10_tau", "max_iter", "compensated",
                     "nharm_total"),
)
def _fit_portrait_core_real_scatter(
    Xr,
    Xi,
    M2w,
    Sd,
    freqs,
    P,
    nu_fit,
    nu_out,
    theta0,
    fit_flags=FitFlags(),
    log10_tau=False,
    max_iter=40,
    ftol=None,
    compensated=False,
    nharm_total=None,
    bounds=None,
):
    """Stage 2 of the split SCATTERING fit: the (phi, DM, GM, tau,
    alpha) Newton loop on the fused analytic _cgh_scatter evaluator and
    result packaging, all in real arithmetic — the complex-free twin of
    _fit_portrait_core's scattering branch, so tau fits share the
    matmul-DFT fast lane (one program, no complex types; VERDICT round
    2 item 7).

    Xr/Xi: the weighted cross-spectrum split into parts (instrumental
    response already folded in); M2w: the weighted model power spectrum
    |m|^2 w (|ir|^2 folded in).  The (C, S) pair rides the Newton state
    as aux, so no extra pass over the spectra is needed at the
    solution.

    nharm_total: the full spectrum's harmonic count when the spectra
    are band-limited (model_harmonic_window; the scattering kernel
    only multiplies the template spectrum — it never widens it — so
    the unscattered template's window stays valid for every tau).
    """
    dt = M2w.dtype
    nharm = nharm_total if nharm_total is not None else Xr.shape[-1]
    flags_arr = FitFlags(*fit_flags).as_array(dt)
    if ftol is None:
        ftol = _scatter_ftol(dt, compensated)
    cvec, gvec = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(dt)
    gvec = gvec.astype(dt)

    def mk_cgh(comp):
        def cgh(theta):
            return _cgh_scatter(theta, Xr, Xi, M2w, freqs, nu_fit,
                                cvec, gvec, log10_tau, comp)
        return cgh

    if compensated:
        s = _hybrid_scatter_loop(mk_cgh(False), mk_cgh(True),
                                 theta0.astype(dt), flags_arr,
                                 max_iter, ftol, dt, bounds=bounds)
    else:
        s = _newton_loop(mk_cgh(False), theta0.astype(dt), flags_arr,
                         max_iter, ftol, lam0=_SCATTER_LAM0,
                         bounds=bounds)
    C, S = s.aux
    return _finalize_fit(
        s.theta, s, s.H, C, S, Sd, nharm, flags_arr, fit_flags,
        P, nu_fit, nu_out, log10_tau, dt)


def _initial_phase_guess_scatter(Xr, Xi, cvec, DM0, tau_n, nbin,
                                 derotate=True, oversamp=2):
    """The CCF phase seed MATCHED to the scattering kernel: the
    channel-summed CCF of X' = X conj(B(tau_seed)) is exactly
    sum_n C_n(phi) on the lag grid — argmax of the fit's own objective
    at the seeded tau — whereas CCF-ing the raw X against the
    unscattered template peaks early by O(tau) (the scattering tail
    drags the correlation peak), which used to cost the vmapped Newton
    loop several extra trips at heavy scattering (the whole batch pays
    for its worst element).  tau_n: per-channel seed timescale in
    rotations (0 reduces exactly to the unmatched seed: B = 1).
    Rational in 2 pi tau k — no extra trig."""
    nharm = Xr.shape[-1]
    dt = cvec.dtype
    k = jnp.arange(nharm, dtype=dt)
    bk = (2.0 * jnp.pi * tau_n)[:, None] * k
    q = 1.0 / (1.0 + bk * bk)
    cBi = bk * q
    Yr = Xr * q - Xi * cBi
    Yi = Xr * cBi + Xi * q
    return _initial_phase_guess_real(Yr, Yi, cvec, DM0,
                                     derotate=derotate, nbin=nbin,
                                     oversamp=oversamp)


def prepare_scatter_fit_real(port, model, noise_stds, chan_mask, freqs,
                             P, nu_fit, theta0, ir_r=None, ir_i=None, *,
                             fit_flags, log10_tau=False,
                             compensated=False, x_bf16=None,
                             nharm_eff=None, seed_derotate=True,
                             dft_fold=None, fit_fused=None):
    """Everything before the scattering Newton loop, in pure real
    arithmetic: weights, matmul DFTs (band-limited when nharm_eff is
    set), cross-spectrum/model-power assembly with the instrumental
    response folded in, full-spectrum Sd, and the tau-matched CCF phase
    seed — the scattering twin of prepare_portrait_fit_real, split out
    so the stage-attribution profiler (benchmarks/attrib.py) can time
    prefixes of the real program.  Returns (Xr, Xi, M2w, Sd, theta0).

    seed_derotate=False (static) skips the seed's DM-derotation trig
    pass — valid when the caller knows every DM guess is zero (the
    batch wrappers check the concrete theta0 on host)."""
    if x_bf16 is None:
        x_bf16 = use_bf16_cross_spectrum()
    from ..ops.fourier import _gated_precision, rfft_mm

    # clamp dft_precision 'default' up to 'high' like the complex
    # interface (rfft_c): the bench-validated single-pass-bf16 setting
    # would floor tau accuracy at ~2.5e-4, defeating the tightened
    # scatter ftol; the DFT is a once-per-fit cost, not per-Newton-step.
    # config.dft_fold (off by default) may halve the contraction length
    # here — the tau gates re-validate it wherever it is enabled.
    prec = _gated_precision(None)
    nbin = port.shape[-1]
    dt = port.dtype
    w = make_weights(noise_stds, nbin, chan_mask, dtype=dt)
    if fit_fused is None:
        fit_fused = resolve_fit_fused(nharm_eff)
    if fit_fused and nharm_eff is not None:
        # fused DFT -> cross-spectrum (ops/fused.py; scan or Pallas
        # kernel per the fit_fused token); windowed lanes only — Sd is
        # the exact time-domain Parseval form either way, so
        # fused-vs-unfused stays byte-identical
        from ..ops.fused import fused_cross_spectrum

        pallas, blk = _parse_fit_fused(fit_fused)
        w_full = w
        Xr, Xi, M2w = fused_cross_spectrum(
            port, model.astype(dt), w[..., :nharm_eff], nharm_eff,
            precision=prec, fold=dft_fold, want_m2=True,
            block=blk, pallas=pallas)
        Sd = _parseval_Sd(port, w_full)
    else:
        dr, di = rfft_mm(port, precision=prec, nharm=nharm_eff,
                         fold=dft_fold)
        mr, mi = rfft_mm(model.astype(dt), precision=prec,
                         nharm=nharm_eff, fold=dft_fold)
        if nharm_eff is not None:
            w_full, w = w, w[..., :nharm_eff]
        Xr = (dr * mr + di * mi) * w
        Xi = (di * mr - dr * mi) * w
        M2w = (mr**2 + mi**2) * w
        if nharm_eff is None:
            Sd = jnp.sum((dr**2 + di**2) * w)
        else:
            Sd = _parseval_Sd(port, w_full)
    if ir_r is not None:
        # X' = X conj(ir) with X = Xr + i Xi, ir = ir_r + i ir_i
        Xr, Xi = Xr * ir_r + Xi * ir_i, Xi * ir_r - Xr * ir_i
        M2w = M2w * (ir_r**2 + ir_i**2)
    cvec, _ = _t_coeffs(freqs, P, nu_fit)
    if fit_flags[0]:
        # per-channel seed timescale from the theta0 (tau, alpha)
        # columns — the same kernel the first Newton eval will see
        tau0 = 10.0 ** theta0[3] if log10_tau else theta0[3]
        tau_n = tau0 * (freqs.astype(dt) / nu_fit) ** theta0[4]
        phi0 = _initial_phase_guess_scatter(
            Xr, Xi, cvec.astype(dt), theta0[1], tau_n, nbin,
            derotate=seed_derotate)
        theta0 = jnp.where(jnp.arange(5) == 0, phi0, theta0).astype(dt)
    else:
        theta0 = theta0.astype(dt)
    # compensated mode exists to push the accumulation error below the
    # f32 noise floor — bf16 X storage would reintroduce ~4e-3 per-term
    # quantization that dominates what Dot2 removes, so force full-
    # precision X whenever the compensated reductions are on
    xdt = (dt if compensated
           else jnp.bfloat16 if (x_bf16 and dt == jnp.float32) else dt)
    return Xr.astype(xdt), Xi.astype(xdt), M2w, Sd, theta0


def fast_scatter_fit_one(port, model, noise_stds, chan_mask, freqs, P,
                         nu_fit, nu_out, theta0, ir_r=None, ir_i=None,
                         bounds=None, *, fit_flags, log10_tau, max_iter,
                         compensated=False, x_bf16=None, nharm_eff=None,
                         seed_derotate=True, dft_fold=None,
                         fit_fused=None):
    """One complex-free SCATTERING fit: weights, matmul DFTs + the
    tau-matched CCF seed (prepare_scatter_fit_real), the real
    _cgh_scatter Newton loop — the per-element body for scattering
    batches on TPU runtimes (vmapped by _fast_batch_fn, sharded by
    parallel.fit_portrait_sharded_fast).

    ir_r/ir_i: optional instrumental-response FT split into real parts
    (complex buffers cannot cross some tunneled-runtime transports, so
    the response ships as two real arrays and is folded into the
    spectra here: X' = X conj(ir), M2' = M2 |ir|^2); when nharm_eff is
    set they must already be sliced to the window.  The tau/alpha
    seeds arrive via theta0 (cols 3, 4), exactly like the complex
    engine.

    nharm_eff (static): the UNSCATTERED template's harmonic window —
    valid for every tau, because the scattering kernel and the
    response only multiply the template spectrum, never widen it."""
    nbin = port.shape[-1]
    Xr, Xi, M2w, Sd, theta0 = prepare_scatter_fit_real(
        port, model, noise_stds, chan_mask, freqs, P, nu_fit, theta0,
        ir_r, ir_i, fit_flags=fit_flags, log10_tau=log10_tau,
        compensated=compensated, x_bf16=x_bf16, nharm_eff=nharm_eff,
        seed_derotate=seed_derotate, dft_fold=dft_fold,
        fit_fused=fit_fused)
    return _fit_portrait_core_real_scatter.__wrapped__(
        Xr, Xi, M2w, Sd, freqs, P, nu_fit,
        nu_out, theta0, fit_flags=fit_flags, log10_tau=log10_tau,
        max_iter=max_iter, compensated=compensated,
        nharm_total=nbin // 2 + 1 if nharm_eff is not None else None,
        bounds=bounds)


def fit_portrait_batch_fast(
    ports,
    models,
    noise_stds,
    freqs,
    P,
    nu_fit,
    nu_out=None,
    theta0=None,
    fit_flags=FitFlags(),
    chan_masks=None,
    max_iter=40,
    log10_tau=False,
    ir_FT=None,
    use_scatter=None,
    compensated=None,
    harmonic_window=None,
    bounds=None,
):
    """Batched fit through the split real-arithmetic path: matmul DFTs,
    CCF seed, and a complex-free Newton loop in one program — the TPU
    throughput path (bench.py) for BOTH regimes:

    - no scattering: the 3-moment fused pass, exactly as before;
    - scattering active (tau/alpha fitted, log10_tau, or a fixed
      nonzero tau seed): the real _cgh_scatter lane (fast_scatter_fit
      _one) — same matmul-DFT front end, the fused analytic 9-reduction
      Newton loop, no complex types anywhere.  ir_FT (host complex
      (nchan, nharm)) is split into real parts before dispatch.
      compensated: None -> config.scatter_compensated (Dot2 reductions
      for f64-quality tau resolution on f32 hardware; hybrid — plain
      loop to convergence, short compensated polish — so nfeval may
      exceed max_iter by the polish budget).

    models may be (nb, nchan, nbin) or a shared (nchan, nbin) template
    (vmapped with in_axes=None — no batch materialization).
    harmonic_window: None -> config.fit_harmonic_window; int = explicit
    harmonic count; band-limits the fit to the model's spectral support
    (model_harmonic_window — chi2/dof stay full-spectrum).  'auto'
    derives from the model only when `models` is a host numpy array.
    bounds: optional (5, 2) [lo, hi] box shared across the batch, or
    (nb, 5, 2) per-element — the reference's TNC `bounds`
    (pptoaslib.py:1039-1060); see _newton_loop for the projection and
    return-code semantics.
    """
    if use_scatter is None:
        use_scatter = derive_use_scatter(fit_flags, log10_tau, theta0) \
            or ir_FT is not None
    if not use_scatter and ir_FT is not None:
        raise ValueError(
            "fit_portrait_batch_fast: an instrumental response needs "
            "the scatter-shaped engine; do not pass use_scatter=False "
            "with ir_FT")
    if use_scatter:
        return _fit_batch_fast_scatter(
            ports, models, noise_stds, freqs, P, nu_fit, nu_out=nu_out,
            theta0=theta0, fit_flags=fit_flags, chan_masks=chan_masks,
            max_iter=max_iter, log10_tau=log10_tau, ir_FT=ir_FT,
            compensated=compensated, harmonic_window=harmonic_window,
            bounds=bounds)
    reject_fixed_tau_seed(theta0, "fit_portrait_batch_fast")
    ports = jnp.asarray(ports)
    nb = ports.shape[0]
    dt = ports.dtype
    nharm_eff = resolve_harmonic_window(harmonic_window, models,
                                        ports.shape[-1])
    models = jnp.asarray(models)
    m_ax = 0 if models.ndim == 3 else None  # 2-D = shared template
    freqs = jnp.asarray(freqs, dt)
    f_ax = 0 if freqs.ndim == 2 else None
    P = jnp.asarray(P, dt)
    p_ax = 0 if P.ndim == 1 else None
    nu_fit = jnp.asarray(nu_fit, dt)
    nf_ax = 0 if nu_fit.ndim == 1 else None
    if theta0 is None:
        theta0 = jnp.zeros((nb, 5), dt)
        seed_derotate = False
    else:
        theta0 = jnp.asarray(theta0)
        if isinstance(theta0, jax.core.Tracer):
            # traced caller: can't inspect values without forcing a
            # sync/abstract-value error; keep the derotation pass
            seed_derotate = True
        else:
            # host-side check on the concrete seed: an all-zero DM
            # guess makes the seed's derotation phasor the identity,
            # and skipping it saves a pass over the cross-spectrum
            import numpy as _np

            seed_derotate = bool(
                _np.any(_np.asarray(theta0[..., 1]) != 0.0))
    nu_out_val = jnp.full((nb,), -1.0 if nu_out is None else nu_out, dt)
    if chan_masks is None:
        chan_masks = jnp.ones(ports.shape[:2], dt)

    from ..ops.fourier import use_dft_fold

    x_bf16 = use_bf16_cross_spectrum()
    bounds, b_ax = _resolve_bounds_axis(bounds, dt)
    # dead-knob normalization: fused is a no-op without the harmonic
    # window, so it must not key a second bit-identical program
    fit_fused = resolve_fit_fused(nharm_eff)
    fit = _fast_batch_fn(
        FitFlags(*[bool(f) for f in fit_flags]), int(max_iter),
        m_ax, f_ax, p_ax, nf_ax, seed_derotate, x_bf16,
        nharm_eff, b_ax, use_dft_fold(), fit_fused)
    args = (ports, models, jnp.asarray(noise_stds), chan_masks,
            freqs, P, nu_fit, nu_out_val, theta0)
    if b_ax != "off":
        args = args + (bounds,)
    return fit(*args)


def fast_fit_one(port, model, noise_stds, chan_mask, freqs, P, nu_fit,
                 nu_out, theta0, bounds=None, *, fit_flags, max_iter,
                 seed_derotate=True, x_bf16=None, nharm_eff=None,
                 dft_fold=None, fit_fused=None):
    """One complex-free fast fit: weights, matmul DFTs + CCF seed, real
    Newton core — the per-element body shared by the vmapped batch
    (_fast_batch_fn) and the sharded scale-out path
    (parallel.fit_portrait_sharded_fast).

    x_bf16 None resolves config.cross_spectrum_dtype at trace time (so
    the knob also reaches callers that don't thread it explicitly, like
    the sharded path — with the usual caveat that an already-traced
    program won't see later config changes).

    nharm_eff (static): the model's harmonic window
    (model_harmonic_window) — band-limits the DFTs and moment passes;
    chi2/dof stay full-spectrum (Parseval Sd, nharm_total)."""
    if x_bf16 is None:
        x_bf16 = use_bf16_cross_spectrum()
    nbin = port.shape[-1]
    w = make_weights(noise_stds, nbin, chan_mask, dtype=port.dtype)
    # f64 runs (CPU parity/oracle paths) never narrow — bf16 storage is
    # an f32-throughput optimization
    x_dtype = (jnp.bfloat16
               if (x_bf16 and port.dtype == jnp.float32)
               else None)
    Xr, Xi, S0, Sd, th0 = prepare_portrait_fit_real(
        port, model.astype(port.dtype), w, freqs, P, nu_fit, theta0,
        seed_phi=bool(fit_flags[0]), seed_derotate=seed_derotate,
        x_dtype=x_dtype, nharm_eff=nharm_eff, dft_fold=dft_fold,
        fit_fused=fit_fused)
    return _fit_portrait_core_real.__wrapped__(
        Xr, Xi, S0, Sd, freqs, P, nu_fit, nu_out, th0,
        fit_flags=fit_flags, max_iter=max_iter,
        nharm_total=nbin // 2 + 1 if nharm_eff is not None else None,
        bounds=bounds)


def use_fast_fit_default():
    """Whether no-scattering pipeline fits should take the complex-free
    f32 fast path: config.use_fast_fit ('auto' = TPU backends, where
    complex FFTs are unsupported or unusably slow)."""
    setting = getattr(config, "use_fast_fit", "auto")
    if setting is False:
        return False
    if setting is True:
        return True
    from ..tune.capability import resolve_auto

    # historically NON-strict: any non-True/False value means 'auto'
    return resolve_auto("fast_fit", "auto")


def reject_fixed_tau_seed(theta0, caller):
    """The real core has no scattering kernel, so a fixed nonzero tau
    seed (which fit_portrait_batch would apply via derive_use_scatter)
    must be refused, not silently dropped."""
    if theta0 is not None and bool(jnp.any(jnp.asarray(theta0)[..., 3]
                                           != 0.0)):
        raise ValueError(
            f"{caller}: fixed nonzero tau in theta0 requires the "
            "scattering kernel; use the complex engine instead")


@lru_cache(maxsize=None)
def _fast_batch_fn(fit_flags, max_iter, m_ax, f_ax, p_ax, nf_ax,
                   seed_derotate=True, x_bf16=False, nharm_eff=None,
                   b_ax="off", dft_fold=None, fit_fused=None):
    """Cached jitted end-to-end fast fit — a fresh jit per call would
    recompile every invocation.  One program: matmul DFTs, real
    cross-spectrum, CCF seed, Newton loop, finalize — no complex types
    anywhere.  dft_fold and fit_fused ride the cache key (resolved by
    callers via use_dft_fold / use_fit_fused, the latter normalized
    onto False when no harmonic window is active) so flipping either
    knob mid-process retraces instead of silently reusing the other
    arm's program."""
    one = partial(fast_fit_one, fit_flags=fit_flags, max_iter=max_iter,
                  seed_derotate=seed_derotate,
                  x_bf16=x_bf16, nharm_eff=nharm_eff,
                  dft_fold=dft_fold, fit_fused=fit_fused)
    # "off" (a string, NOT False) marks no-bounds: False == 0 in
    # Python, so a boolean sentinel would collide with per-element
    # bounds (b_ax=0) in the lru_cache key and return the wrong
    # cached program
    axes = (0, m_ax, 0, 0, f_ax, p_ax, nf_ax, 0, 0)
    if b_ax != "off":
        axes = axes + (b_ax,)
    return jax.jit(jax.vmap(one, in_axes=axes))


def prepare_portrait_fit_real_packed(raw, scl, offs, model, w, freqs, P,
                                     nu_fit, theta0, *, raw_code, nbin,
                                     seed_phi=True, seed_derotate=True,
                                     x_dtype=None, nharm_eff=None,
                                     dft_fold=None, fused_block=None):
    """prepare_portrait_fit_real for a sub-byte PACKED raw payload: the
    decode chain (bit-plane unpack, affine decode, min-window baseline)
    and the windowed DFT -> cross-spectrum run inside ONE Pallas
    channel-tile kernel (ops/fused.fused_decode_cross_spectrum_pallas),
    so the decoded f64 portrait is never materialized in HBM between
    the decode stage and the fit.

    raw: (nchan, bpc) uint8 per-channel packed bytes (the stream front
    guarantees nbin*nbit % 8 == 0 before routing here).  w: the FULL
    make_weights array — the kernel gets the harmonic window slice,
    and the full-spectrum Sd is assembled from the kernel's exact
    per-channel time-domain Parseval rows with _parseval_Sd's outer
    ops, so every output is bitwise identical to the decode-then-
    prepare program (the .tim byte gate vs the decoded oracle).
    Windowed lanes only: nharm_eff must be set."""
    from ..ops.fused import fused_decode_cross_spectrum_pallas

    dt = w.dtype
    cvec, _ = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(dt)
    Xr, Xi, S0, pwr, x0 = fused_decode_cross_spectrum_pallas(
        raw, scl, offs, model, w[..., :nharm_eff], nharm_eff,
        code=raw_code, nbin=nbin, fold=dft_fold, block=fused_block)
    # _parseval_Sd's outer reductions on the kernel's per-channel rows
    Sd = jnp.sum(w[..., 1] * (0.5 * pwr))
    if float(F0_fact) != 0.0:
        Sd = Sd + jnp.sum(w[..., 0] * x0**2)
    if seed_phi:
        phi0 = _initial_phase_guess_real(Xr, Xi, cvec, theta0[1],
                                         derotate=seed_derotate,
                                         nbin=nbin)
        theta0 = jnp.where(jnp.arange(5) == 0, phi0, theta0).astype(dt)
    else:
        theta0 = theta0.astype(dt)
    xdt = x_dtype or dt
    return Xr.astype(xdt), Xi.astype(xdt), S0, Sd, theta0


def fast_fit_one_packed(raw, scl, offs, model, noise_stds, chan_mask,
                        freqs, P, nu_fit, nu_out, theta0, *, raw_code,
                        nbin, fit_flags, max_iter, seed_derotate=True,
                        x_bf16=None, nharm_eff=None, dft_fold=None,
                        fused_block=None):
    """fast_fit_one for a sub-byte packed raw payload: decode+DFT in
    one Pallas kernel (prepare_portrait_fit_real_packed), then the same
    real Newton core — the per-element body of the raw streaming lane's
    decode-fused program (pipeline/stream._raw_fit_fn)."""
    if x_bf16 is None:
        x_bf16 = use_bf16_cross_spectrum()
    dt = noise_stds.dtype
    w = make_weights(noise_stds, nbin, chan_mask, dtype=dt)
    x_dtype = (jnp.bfloat16
               if (x_bf16 and dt == jnp.float32)
               else None)
    Xr, Xi, S0, Sd, th0 = prepare_portrait_fit_real_packed(
        raw, scl, offs, model.astype(dt), w, freqs, P, nu_fit, theta0,
        raw_code=raw_code, nbin=nbin, seed_phi=bool(fit_flags[0]),
        seed_derotate=seed_derotate, x_dtype=x_dtype,
        nharm_eff=nharm_eff, dft_fold=dft_fold,
        fused_block=fused_block)
    return _fit_portrait_core_real.__wrapped__(
        Xr, Xi, S0, Sd, freqs, P, nu_fit, nu_out, th0,
        fit_flags=fit_flags, max_iter=max_iter,
        nharm_total=nbin // 2 + 1, bounds=None)


@lru_cache(maxsize=None)
def _fast_batch_packed_fn(fit_flags, max_iter, raw_code, nbin,
                          seed_derotate=True, x_bf16=False,
                          nharm_eff=None, dft_fold=None,
                          fused_block=None):
    """Cached jitted batch wrapper for the decode-fused raw fit
    (fast_fit_one_packed): model and freqs shared across the batch
    (the raw bucket program's layout), everything else per-subint.
    raw_code/nbin/fused_block ride the cache key like the other
    resolved statics."""
    one = partial(fast_fit_one_packed, fit_flags=fit_flags,
                  max_iter=max_iter, raw_code=raw_code, nbin=nbin,
                  seed_derotate=seed_derotate, x_bf16=x_bf16,
                  nharm_eff=nharm_eff, dft_fold=dft_fold,
                  fused_block=fused_block)
    # (raw, scl, offs, model, noise, cmask, freqs, P, nu_fit, nu_out,
    #  theta0)
    axes = (0, 0, 0, None, 0, 0, None, 0, 0, 0, 0)
    return jax.jit(jax.vmap(one, in_axes=axes))


def _fit_batch_fast_scatter(ports, models, noise_stds, freqs, P, nu_fit,
                            nu_out=None, theta0=None,
                            fit_flags=FitFlags(), chan_masks=None,
                            max_iter=40, log10_tau=False, ir_FT=None,
                            compensated=None, harmonic_window=None,
                            bounds=None):
    """Batch wrapper for the complex-free scattering lane (see
    fit_portrait_batch_fast, which routes here)."""
    ports = jnp.asarray(ports)
    nb = ports.shape[0]
    dt = ports.dtype
    nharm_eff = resolve_harmonic_window(harmonic_window, models,
                                        ports.shape[-1])
    models = jnp.asarray(models)
    m_ax = 0 if models.ndim == 3 else None
    freqs = jnp.asarray(freqs, dt)
    f_ax = 0 if freqs.ndim == 2 else None
    P = jnp.asarray(P, dt)
    p_ax = 0 if P.ndim == 1 else None
    nu_fit = jnp.asarray(nu_fit, dt)
    nf_ax = 0 if nu_fit.ndim == 1 else None
    if theta0 is None:
        theta0 = jnp.zeros((nb, 5), dt)
        seed_derotate = False
    elif isinstance(theta0, jax.core.Tracer):
        # traced caller: can't inspect values; keep the derotation pass
        seed_derotate = True
    else:
        # host-side check on the concrete seed (same rule as the
        # no-scatter wrapper): an all-zero DM guess makes the seed's
        # derotation phasor the identity, and skipping it saves a
        # trig pass over the cross-spectrum
        import numpy as _np

        seed_derotate = bool(
            _np.any(_np.asarray(theta0)[..., 1] != 0.0))
    nu_out_arr = jnp.broadcast_to(
        jnp.asarray(-1.0 if nu_out is None else nu_out, dt), (nb,))
    if chan_masks is None:
        chan_masks = jnp.ones(ports.shape[:2], dt)
    if compensated is None:
        compensated = use_scatter_compensated()
    use_ir = ir_FT is not None
    if ir_FT is not None and nharm_eff is not None:
        import numpy as _np

        ir_FT = _np.asarray(ir_FT)[..., :nharm_eff]
    ir_r, ir_i = split_ir_host(ir_FT, dt)
    bounds, b_ax = _resolve_bounds_axis(bounds, dt)
    from ..ops.fourier import use_dft_fold

    fit = _fast_scatter_batch_fn(
        FitFlags(*[bool(f) for f in fit_flags]), bool(log10_tau),
        int(max_iter), bool(compensated),
        effective_x_bf16(compensated),
        m_ax, f_ax, p_ax, nf_ax, use_ir, nharm_eff, b_ax,
        seed_derotate, use_dft_fold(), resolve_fit_fused(nharm_eff))
    args = (ports, models, jnp.asarray(noise_stds),
            jnp.asarray(chan_masks, dt), freqs, P, nu_fit,
            nu_out_arr, jnp.asarray(theta0), ir_r, ir_i)
    if b_ax != "off":
        args = args + (bounds,)
    return fit(*args)


@lru_cache(maxsize=None)
def _fast_scatter_batch_fn(fit_flags, log10_tau, max_iter, compensated,
                           x_bf16, m_ax, f_ax, p_ax, nf_ax, use_ir,
                           nharm_eff=None, b_ax="off",
                           seed_derotate=True, dft_fold=None,
                           fit_fused=None):
    """Cached jitted end-to-end complex-free scattering batch fit.
    dft_fold and fit_fused ride the cache key like
    seed_derotate/x_bf16 (see _fast_batch_fn)."""
    one = partial(fast_scatter_fit_one, fit_flags=fit_flags,
                  log10_tau=log10_tau, max_iter=max_iter,
                  compensated=compensated, x_bf16=x_bf16,
                  nharm_eff=nharm_eff, seed_derotate=seed_derotate,
                  dft_fold=dft_fold, fit_fused=fit_fused)
    ir_ax = None  # shared response across the batch
    axes = (0, m_ax, 0, 0, f_ax, p_ax, nf_ax, 0, 0, ir_ax, ir_ax)
    if b_ax != "off":
        axes = axes + (b_ax,)
    return jax.jit(jax.vmap(one, in_axes=axes))


def _resolve_bounds_axis(bounds, dt=None):
    """Shared batch-wrapper parse of the bounds argument: returns
    (bounds_array_or_None, b_ax) where b_ax is the vmap axis — "off"
    (a string, NOT False: False == 0 would collide with per-element
    axis 0 in the lru_cache keys), None for a shared (5, 2) box, or 0
    for per-element (nb, 5, 2)."""
    if bounds is None:
        return None, "off"
    bounds = jnp.asarray(bounds) if dt is None \
        else jnp.asarray(bounds, dt)
    if bounds.shape[-2:] != (5, 2) or bounds.ndim not in (2, 3):
        raise ValueError(
            f"bounds must be (5, 2) or (nb, 5, 2); got {bounds.shape}")
    return bounds, (0 if bounds.ndim == 3 else None)


def derive_use_scatter(fit_flags, log10_tau, theta0):
    """True when the scattering kernel must be active: tau/alpha fitted,
    log10 parameterization (tau = 10^theta3 > 0 always), or a fixed
    nonzero tau seeded in theta0."""
    import numpy as np

    if bool(fit_flags[3]) or bool(fit_flags[4]) or log10_tau:
        return True
    if theta0 is not None:
        return bool(np.any(np.asarray(theta0)[..., 3] != 0.0))
    return False


def make_weights(noise_stds, nbin, chan_mask=None, dtype=None):
    """w_nk = chan_mask_n / sigma_F,n^2, DC harmonic scaled by F0_fact.

    noise_stds are *time-domain* per-channel stds; the sqrt(nbin/2)
    Fourier scaling (reference pplib.py:2160-2162) is applied here.
    """
    noise_stds = jnp.asarray(noise_stds)
    dtype = dtype or noise_stds.dtype
    nharm = nbin // 2 + 1
    errs_F = fourier_noise(noise_stds, nbin).astype(dtype)
    good = errs_F > 0.0
    inv = jnp.where(good, 1.0 / jnp.where(good, errs_F, 1.0) ** 2.0, 0.0)
    w = jnp.broadcast_to(inv[..., None], inv.shape + (nharm,))
    w = w * jnp.where(jnp.arange(nharm) == 0, F0_fact, 1.0).astype(dtype)
    if chan_mask is not None:
        w = w * jnp.asarray(chan_mask, dtype)[..., None]
    return w


def _canonical_real_dtype(x):
    """f64 -> f32 on TPU backends (c128 spectra do not compile there);
    unchanged elsewhere — including under a host_compute() context on a
    TPU session (jax.default_device pinned to a CPU device), where the
    ops execute on host and c128 is fine: callers like align's batched
    phase-guess rely on keeping f64 there."""
    from ..tune.capability import resolve_auto

    if x.dtype != jnp.float64 or not resolve_auto("device_f32", "auto"):
        return x
    dd = getattr(jax.config, "jax_default_device", None)
    if dd is not None and getattr(dd, "platform", None) == "cpu":
        return x
    return x.astype(jnp.float32)


def estimate_tau(port, model, noise_stds, chan_mask=None):
    """Seed-quality broadband scattering-timescale estimate [rotations]
    by matching the weighted cross-spectrum amplitude ratio against the
    scattering kernel's Lorentzian-amplitude shape.

    A one-sided-exponential scattering kernel multiplies the data's
    harmonic content by |B(k)| = (1 + (2 pi k tau)^2)^-1/2, which the
    channel-summed ratio q(k) = sum_n w|d conj(m)| / sum_n w|m|^2
    traces.  Phase shifts and per-channel amplitudes cancel in |X|, so
    no alignment is needed first.  Unscattered data fits best at the
    grid's bottom edge and returns the neutral half-bin seed.

    The fit is a profiled-amplitude least-squares match of q(k) against
    |B(k; tau)| on a fixed log grid of tau values (64 points spanning
    sub-bin to half a turn), after subtracting the analytic Rice floor
    of |X| under pure noise (E|X|_noise = sqrt(pi/2) sum_n sqrt(w)|m|)
    in quadrature — without that subtraction the high-k noise shelf
    biases large-tau estimates low.

    This replaces a user-supplied scat_guess, not the fit: the estimate
    is biased by model mismatch and residual noise rectification at the
    ~tens of percent level, which the Newton loop then removes in a few
    steps instead of the ~28 it needs from the neutral seed.  The
    reference has no analogue (its pipeline requires --scat_guess or
    starts neutral, pptoas.py:1497).
    """
    from ..ops.fourier import rfft_mm

    port = jnp.asarray(port)
    nbin = port.shape[-1]
    nharm = nbin // 2 + 1
    dt = port.dtype
    w = make_weights(noise_stds, nbin, chan_mask, dtype=dt)
    dr, di = rfft_mm(port)
    mr, mi = rfft_mm(jnp.asarray(model).astype(dt))
    mabs = jnp.sqrt(mr**2.0 + mi**2.0)
    Xa = jnp.sqrt((dr * mr + di * mi) ** 2.0 + (di * mr - dr * mi) ** 2.0)
    num = jnp.sum(w * Xa, axis=0)
    den = jnp.sum(w * mabs**2.0, axis=0)
    den_safe = jnp.maximum(den, _tiny(dt))
    q = num / den_safe
    # Rice floor of |X| under pure noise, subtracted in quadrature
    floor = jnp.sqrt(jnp.pi / 2.0) * jnp.sum(jnp.sqrt(w) * mabs,
                                             axis=0) / den_safe
    q_sig = jnp.sqrt(jnp.maximum(q**2.0 - floor**2.0, 0.0))
    # profiled-amplitude LS over a fixed log-tau grid, weighted by model
    # power (den); harmonic 0 is F0_fact-zeroed via w already
    k = jnp.arange(nharm, dtype=dt)
    taus = jnp.logspace(jnp.log10(0.25 / nbin), jnp.log10(0.5), 64,
                        dtype=dt)
    b = (1.0 + (2.0 * jnp.pi * taus[:, None] * k) ** 2.0) ** -0.5
    u = den
    A = jnp.sum(u * q_sig * b, axis=1) / jnp.maximum(
        jnp.sum(u * b**2.0, axis=1), _tiny(dt))
    sse = jnp.sum(u * (q_sig - A[:, None] * b) ** 2.0, axis=1)
    i0 = jnp.argmin(sse)
    # sub-grid refinement: parabolic interpolation of sse through the
    # argmin and its neighbors, in grid-index (= log-tau) units.  The
    # 64-point log grid spaces tau by ~13% — a pure-grid seed hands the
    # Newton loop up to half a grid step of error it must burn trips
    # removing; the parabola cuts that to ~1-2% for free.  Edge bins
    # and degenerate curvature keep the grid value.
    im = jnp.clip(i0 - 1, 0, sse.shape[0] - 1)
    ip = jnp.clip(i0 + 1, 0, sse.shape[0] - 1)
    f0, fm, fp = sse[i0], sse[im], sse[ip]
    denom = fm - 2.0 * f0 + fp
    interior = jnp.logical_and(i0 > 0, i0 < sse.shape[0] - 1)
    ok = jnp.logical_and(interior, denom > 0.0)
    delta = jnp.where(ok, 0.5 * (fm - fp)
                      / jnp.where(ok, denom, 1.0), 0.0)
    delta = jnp.clip(delta, -0.5, 0.5)
    dlog = (jnp.log10(taus[-1]) - jnp.log10(taus[0])) / (
        sse.shape[0] - 1.0)
    tau = 10.0 ** (jnp.log10(taus[i0]) + delta * dlog)
    neutral = 0.5 / nbin
    # an unscattered portrait fits best at the grid's bottom edge; the
    # neutral seed is the right answer there
    return jnp.maximum(tau, neutral)


def estimate_tau_batch(ports, models, noise_stds, chan_masks=None):
    """vmapped estimate_tau over a leading batch dim; models may be
    (nchan, nbin) shared or (nb, nchan, nbin)."""
    ports = jnp.asarray(ports)
    models = jnp.asarray(models)
    m_ax = 0 if models.ndim == 3 else None
    if chan_masks is None:
        return jax.vmap(
            lambda p, m, n: estimate_tau(p, m, n), in_axes=(0, m_ax, 0)
        )(ports, models, jnp.asarray(noise_stds))
    return jax.vmap(estimate_tau, in_axes=(0, m_ax, 0, 0))(
        ports, models, jnp.asarray(noise_stds), jnp.asarray(chan_masks))


def fit_portrait(
    port,
    model,
    noise_stds,
    freqs,
    P,
    nu_fit=None,
    nu_out=None,
    phi0=None,
    DM0=0.0,
    GM0=0.0,
    tau0=0.0,
    alpha0=None,
    fit_flags=FitFlags(),
    chan_mask=None,
    ir_FT=None,
    log10_tau=False,
    max_iter=40,
    dtype=None,
    bounds=None,
):
    """Fit (phi, DM[, GM, tau, alpha]) of a (nchan, nbin) data portrait
    against a model portrait.  Host-friendly wrapper around the jitted
    core; see fit_portrait_batch for the vmapped version.

    nu_fit: scalar reference frequency used during the fit (default:
    guess_fit_freq of the channel S/N weights); nu_out: output
    reference (None -> the exact zero-covariance frequencies);
    phi0: explicit phase seed at nu_fit (None -> dense-CCF auto-seed).
    Returns a FitResult (tau in rotations).
    """
    from ..config import scattering_alpha
    from ..ops.phasor import guess_fit_freq

    port = _canonical_real_dtype(jnp.asarray(port))
    model = jnp.asarray(model)
    freqs = jnp.asarray(freqs)
    nbin = port.shape[-1]
    dtype = dtype or port.dtype
    w = make_weights(noise_stds, nbin, chan_mask, dtype=dtype)
    dFT = rfft_c(port.astype(dtype))
    mFT = rfft_c(model.astype(dtype))
    if nu_fit is None:
        nu_fit = guess_fit_freq(freqs)
    if alpha0 is None:
        alpha0 = scattering_alpha
    taup0 = jnp.log10(jnp.maximum(tau0, 1e-30)) if log10_tau else tau0
    theta0 = jnp.array(
        [0.0 if phi0 is None else phi0, DM0, GM0, taup0, alpha0], w.dtype
    )
    nu_out_val = jnp.asarray(-1.0 if nu_out is None else nu_out, w.dtype)
    use_scatter = bool(fit_flags[3]) or bool(fit_flags[4]) or float(tau0) != 0.0
    return _fit_portrait_core(
        dFT,
        mFT,
        w,
        freqs.astype(w.dtype),
        jnp.asarray(P, w.dtype),
        jnp.asarray(nu_fit, w.dtype),
        nu_out_val,
        theta0,
        ir_FT=ir_FT,
        fit_flags=FitFlags(*[bool(f) for f in fit_flags]),
        log10_tau=log10_tau,
        max_iter=max_iter,
        use_ir=ir_FT is not None,
        use_scatter=use_scatter,
        auto_seed=phi0 is None,
        compensated=use_scatter_compensated(),
        bounds=None if bounds is None else jnp.asarray(bounds, w.dtype),
    )


def fit_portrait_batch(
    ports,
    models,
    noise_stds,
    freqs,
    P,
    nu_fit,
    nu_out=None,
    theta0=None,
    fit_flags=FitFlags(),
    chan_masks=None,
    log10_tau=False,
    max_iter=40,
    use_scatter=None,
    ir_FT=None,
    compensated=None,
    bounds=None,
):
    """vmapped portrait fit over a leading batch dimension.

    ports/models: (nb, nchan, nbin); noise_stds/chan_masks: (nb, nchan);
    freqs: (nchan,) shared or (nb, nchan); P, nu_fit: scalar or (nb,).
    use_scatter: None -> derived from fit_flags/log10_tau/theta0 (a
    fixed nonzero tau in theta0 must still be applied to the model).
    ir_FT: optional (nchan, nharm) instrumental-response FT shared by
    the whole batch (ops.instrumental_response_port_FT; reference
    convolves the model per subint at pptoas.py:428-434).
    compensated: None -> config.scatter_compensated (Dot2 reductions
    for f64-quality tau resolution on f32 hardware; same knob as
    fit_portrait_batch_fast).  In compensated mode nfeval may exceed
    max_iter by the short polish budget (_hybrid_scatter_loop).

    f64 inputs are canonicalized to f32 on TPU backends: the complex
    engine follows the input dtype, and c128 spectra do not compile on
    any TPU runtime.  Every pipeline call site inherits this guard.

    The whole preamble (weights, DFTs, casts) compiles into ONE program
    with the vmapped core: eager per-op dispatch costs ~25 ms per op on
    tunneled runtimes, which at ~25 wrapper ops used to dwarf the fit
    itself.
    """
    ports = _canonical_real_dtype(jnp.asarray(ports))
    nb = ports.shape[0]
    if use_scatter is None:
        use_scatter = derive_use_scatter(fit_flags, log10_tau, theta0)
    models = jnp.asarray(models)
    m_ax = 0 if models.ndim == 3 else None
    freqs = jnp.asarray(freqs)
    f_ax = 0 if freqs.ndim == 2 else None
    P = jnp.asarray(P)
    p_ax = 0 if P.ndim == 1 else None
    nu_fit = jnp.asarray(nu_fit)
    nf_ax = 0 if nu_fit.ndim == 1 else None
    if theta0 is None:
        theta0 = jnp.zeros((nb, 5), ports.dtype)
    nu_out_val = -1.0 if nu_out is None else nu_out
    use_ir = ir_FT is not None
    if compensated is None:
        compensated = use_scatter_compensated()
    bounds, b_ax = _resolve_bounds_axis(bounds)
    fn = _complex_batch_fn(
        FitFlags(*[bool(f) for f in fit_flags]), bool(log10_tau),
        int(max_iter), bool(use_scatter), use_ir, m_ax, f_ax, p_ax,
        nf_ax, bool(compensated), b_ax)
    ir_arg = ir_FT if use_ir else None
    nu_out_arr = jnp.broadcast_to(
        jnp.asarray(nu_out_val, ports.dtype), (nb,))
    return fn(ports, models, jnp.asarray(noise_stds),
              None if chan_masks is None else jnp.asarray(chan_masks),
              freqs, P, nu_fit, nu_out_arr, jnp.asarray(theta0), ir_arg,
              *(() if b_ax == "off" else (bounds,)))


@lru_cache(maxsize=None)
def _complex_batch_fn(fit_flags, log10_tau, max_iter, use_scatter,
                      use_ir, m_ax, f_ax, p_ax, nf_ax,
                      compensated=False, b_ax="off"):
    """Cached single-program complex-engine batch fit: weights + DFTs +
    vmapped _fit_portrait_core compiled together."""

    def run(ports, models, noise_stds, chan_masks, freqs, P, nu_fit,
            nu_out_arr, theta0, ir_FT, bounds=None):
        nbin = ports.shape[-1]
        dt = ports.dtype
        w = make_weights(noise_stds, nbin, chan_masks, dtype=dt)
        dFT = rfft_c(ports)
        mFT = rfft_c(models.astype(dt))
        axes = (0, m_ax, 0, f_ax, p_ax, nf_ax, 0, 0, None)

        def core_one(dFT1, mFT1, w1, fr, P1, nf1, no1, th1, ir1,
                     bnd=None):
            return _fit_portrait_core(
                dFT1, mFT1, w1, fr, P1, nf1, no1, th1, ir1,
                fit_flags=fit_flags, log10_tau=log10_tau,
                max_iter=max_iter, use_ir=use_ir,
                use_scatter=use_scatter, compensated=compensated,
                bounds=bnd)

        if b_ax != "off":
            axes = axes + (b_ax,)
        core = jax.vmap(core_one, in_axes=axes)
        ir_arg = ir_FT.astype(jnp.complex64 if dt == jnp.float32
                              else jnp.complex128) if use_ir else None
        args = (dFT, mFT, w,
                jnp.asarray(freqs, dt), jnp.asarray(P, dt),
                jnp.asarray(nu_fit, dt), nu_out_arr.astype(dt),
                theta0.astype(dt), ir_arg)
        if b_ax != "off":
            args = args + (jnp.asarray(bounds, dt),)
        return core(*args)

    return jax.jit(run, static_argnames=())
