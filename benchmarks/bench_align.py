"""BASELINE.md config 4 (single-chip form): one ppalign-style iteration
over 256 epochs at 512 chan x 2048 bin — batched (phi, DM) fits of every
epoch against the current template, then a weighted rotate-and-stack.

This is the in-memory math of pipeline/align.align_archives's inner
loop (the file-level driver adds PSRFITS IO around exactly this); the
multi-chip form shards the epoch axis (parallel/batch.py).

Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main_cli():
    """--cli: the file-level align_archives path (PSRFITS IO + batched
    phase-guess + harmonic-domain accumulate; round 5 batched its two
    per-subint host loops — A/B numbers in BENCHMARKS.md).  Host-bound
    either way; archives cached like bench_campaign."""
    import jax

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu.pipeline import align_archives
    from pulseportraiture_tpu.synth import default_test_model, \
        make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    NARCH = int(os.environ.get("PPT_NARCH", 4))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 64))
    NBIN = int(os.environ.get("PPT_NBIN", 512))
    NITER = int(os.environ.get("PPT_NITER", 2))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_ALIGN_CACHE", "/tmp/ppt_align_cli")
    root = os.path.join(cache, f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}")
    os.makedirs(root, exist_ok=True)
    model = default_test_model(1500.0)
    files = []
    for i in range(NARCH):
        p = os.path.join(root, f"ep{i}.fits")
        if not os.path.exists(p):
            make_fake_pulsar(model, PAR, outfile=p, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=600.0, tsub=60.0, phase=0.03 * i,
                             dDM=1e-4 * i, start_MJD=MJD(55100 + i, 0.2),
                             noise_stds=0.06, dedispersed=False,
                             quiet=True, rng=i)
        files.append(p)
    out = os.path.join(root, "out.fits")
    times = []
    for _ in range(3):  # first rep pays compile; report min (warm)
        t0 = time.perf_counter()
        align_archives(files, files[0], niter=NITER, quiet=True,
                       outfile=out)
        times.append(time.perf_counter() - t0)
    print(json.dumps({
        "metric": f"align_archives CLI path (IO + {NITER} iterations), "
                  f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}",
        "value": round(NARCH * NSUB * NITER / min(times), 2),
        "unit": "subint-iterations/sec",
        "warm_s": round(min(times), 2),
        "cold_s": round(times[0], 2),
        "device": str(jax.devices()[0]),
    }))


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.ops.rotation import rotate_portrait

    NE = int(os.environ.get("PPT_NE", 256))
    NCHAN = int(os.environ.get("PPT_NCHAN", 512))
    NBIN = int(os.environ.get("PPT_NBIN", 2048))
    DT = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    model, freqs = bench_model(NCHAN, NBIN)

    @jax.jit
    def synth(key):
        k1, k2 = jax.random.split(key)
        scales = 0.5 + jax.random.uniform(k1, (NE, 1, 1), DT)
        return model[None] * scales + 0.05 * jax.random.normal(
            k2, (NE, NCHAN, NBIN), DT)

    ports = synth(jax.random.PRNGKey(0))
    noise = jnp.full((NE, NCHAN), 0.05, DT)

    @jax.jit
    def stack(ports, phis, DMs, scales, noise_stds):
        rot = jax.vmap(
            lambda p, ph, dm: rotate_portrait(p, -ph, -dm, freqs, P, NU_FIT)
        )(ports, phis, DMs)
        wts = scales / noise_stds**2.0  # reference ppalign.py:236-242
        num = jnp.einsum("enb,en->nb", rot, wts)
        return num / jnp.maximum(jnp.sum(wts, 0), 1e-30)[:, None]

    # the production align_archives derives the harmonic window from
    # its host template each iteration (noisy averages resolve to full
    # spectrum); mirror that here from the one-time host pull
    import numpy as np

    from pulseportraiture_tpu.fit.portrait import resolve_harmonic_window

    hwin = resolve_harmonic_window(None, np.asarray(model), NBIN)

    def iteration():
        r = fit_portrait_batch_fast(
            ports, model, noise, freqs, P, NU_FIT, max_iter=25,
            harmonic_window=hwin if hwin is not None else False)
        return stack(ports, r.phi, r.DM, r.scales, noise)

    slope, single = devtime(iteration, lambda t: t)
    print(json.dumps({
        "metric": "align iteration (fit+stack), 256 epochs x 512ch x 2048bin",
        "value": round(NE / slope, 2),
        "unit": "epochs/sec",
        "iteration_latency_ms": round(single * 1e3, 1),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main_cli() if "--cli" in sys.argv else main()
