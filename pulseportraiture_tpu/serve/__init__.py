"""Continuous-batching TOA service (ISSUE 8; ROADMAP item 2).

One warm stream executor per host, fed by a shape-bucketed admission
queue: concurrent clients submit archives, compatible subints coalesce
into shared fused dispatches across requests (a bucket launches when
full or past ``config.serve_max_wait_ms``), and completed TOAs
demultiplex back to per-request ``.tim`` results byte-identical to the
one-shot drivers.  See serve/server.py for the architecture and
docs/GUIDE.md "Serving TOAs" for usage; the CLI is ``ppserve``.

Cross-host scale-out (ISSUE 10): ``transport.py`` wraps the client
surface in a length-prefixed JSON protocol (``ppserve --listen`` /
``SocketTransport``; ``InProcTransport`` for tests and emulated
fleets), and ``router.ToaRouter`` + the ``pproute`` CLI shard a
campaign's requests across N such hosts — least-loaded placement,
sticky per-template affinity, backpressure retries — with the demux
still byte-identical to one-shot no matter which host served; see
docs/GUIDE.md "Routing a campaign across hosts".

Elastic fleet (ISSUE 13): ``fleet.py`` gives the router dynamic
membership with a per-host health state machine (JOINING -> HEALTHY
-> SUSPECT -> DEAD -> REJOINED off bounded probes), ``codec.py``
factors the result wire codec into the no-shared-fs ``.tim`` demux
and the durable-``.tim`` failover primitives, and the router layers
exactly-once mid-fit failover, hedged requests, routed quality
refits, and per-tenant QoS lanes (``queue.AdmissionQueue``) on top;
see docs/GUIDE.md "Operating an elastic fleet".

Content-addressed result cache (ISSUE 17): ``cache.py`` keys
completed ``.tim`` payloads by SHA-256 over (archive bytes, template
bytes, frozen fit options) in a bounded on-disk LRU — a hit is
byte-identical to a fresh fit by construction (the codec's byte-exact
serialization) and O(1).  The router checks it before placement (a
hit never touches a host), the server checks at submit and populates
on completion, and per-tenant accounting sees hits without billing
them as fits.  Off by default: ``config.result_cache='auto'`` engages
only when ``config.cache_dir`` is set; see docs/GUIDE.md "The result
cache".
"""

from .cache import (ResultCache, content_key,  # noqa: F401
                    resolve_result_cache)
from .client import ToaClient  # noqa: F401
from .codec import (copy_tim_atomic, decode_result,  # noqa: F401
                    encode_result, read_tim_result, tim_complete,
                    write_tim_result)
from .fleet import (DEAD, HEALTHY, JOINING, REJOINED,  # noqa: F401
                    SUSPECT, Fleet, FleetFileWatcher, FleetMember)
from .queue import AdmissionQueue, ServeRejected, ServeRequest  # noqa: F401
from .router import RouteHandle, ToaRouter  # noqa: F401
from .server import ToaServer  # noqa: F401
from .transport import (InProcTransport, RemoteRequestError,  # noqa: F401
                        SocketTransport, TransportError,
                        TransportServer)
