"""Frequency-evolving Gaussian-component portrait models.

A model is ngauss Gaussian components whose (loc, wid, amp) each evolve
with frequency by either a power law or a linear law, selected by a
three-digit code string (one digit per parameter; '0' = power law,
'1' = linear), plus a DC offset and a scattering (tau, alpha) pair —
the .gmodel format's semantics (reference pplib.py:886-963, 1032-1084;
grammar documented in the reference's examples/example.gmodel).

The portrait generator is fully vectorized over (nchan, ngauss) and
jittable; parameters live in a flat pytree so the LM template fitter
(fit/lm.py) can differentiate through generation.
"""

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from ..ops.gaussian import gaussian_profile_FT
from ..ops.scattering import scattering_portrait_FT, scattering_times


def power_law_evolution(value, mod_index, freqs, nu_ref):
    """param(nu) = value * (nu/nu_ref)**mod_index
    (reference pplib.py:1032-1047)."""
    return value * (freqs / nu_ref) ** mod_index


def linear_evolution(value, slope, freqs, nu_ref):
    """param(nu) = value + slope * (nu - nu_ref)
    (reference pplib.py:1050-1065)."""
    return value + slope * (freqs - nu_ref)


_EVOLUTION = {"0": power_law_evolution, "1": linear_evolution}


def power_law_evolution_grads(value, mod_index, freqs, nu_ref):
    """(dp/dvalue, dp/dmod) of power_law_evolution:
    p = v (nu/nu_ref)^m => (r^m, v r^m ln r)."""
    r = freqs / nu_ref
    rm = r ** mod_index
    return rm, value * rm * jnp.log(r)


def linear_evolution_grads(value, mod_index, freqs, nu_ref):
    """(dp/dvalue, dp/dmod) of linear_evolution: (1, nu - nu_ref)."""
    one = jnp.ones(jnp.broadcast_shapes(jnp.shape(value),
                                        jnp.shape(mod_index),
                                        jnp.shape(freqs)),
                   jnp.result_type(value, freqs))
    return one, jnp.broadcast_to(freqs - nu_ref, one.shape)


_EVOLUTION_GRADS = {"0": power_law_evolution_grads,
                    "1": linear_evolution_grads}


def evolve_parameter(value, mod, freqs, nu_ref, code_digit="0"):
    """Dispatch on the .gmodel CODE digit (reference pplib.py:1068-1084)."""
    return _EVOLUTION[code_digit](value, mod, freqs, nu_ref)


@dataclass
class GaussianModel:
    """A .gmodel in memory.

    locs/wids/amps and their evolution moduli are (ngauss,) arrays at
    the reference frequency nu_ref [MHz]; tau is the scattering
    timescale in *seconds* at nu_ref (the on-disk unit); fit flags are
    kept for the template fitter and round-tripping.
    """

    name: str
    code: str
    nu_ref: float
    dc: float
    tau: float
    alpha: float
    locs: np.ndarray
    wids: np.ndarray
    amps: np.ndarray
    mlocs: np.ndarray
    mwids: np.ndarray
    mamps: np.ndarray
    fit_flags: dict = field(default_factory=dict)

    @property
    def ngauss(self):
        return len(np.atleast_1d(self.locs))

    def params_pytree(self):
        return {
            "dc": jnp.asarray(self.dc),
            "tau": jnp.asarray(self.tau),
            "alpha": jnp.asarray(self.alpha),
            "locs": jnp.asarray(self.locs),
            "wids": jnp.asarray(self.wids),
            "amps": jnp.asarray(self.amps),
            "mlocs": jnp.asarray(self.mlocs),
            "mwids": jnp.asarray(self.mwids),
            "mamps": jnp.asarray(self.mamps),
        }


def evolved_components(params, freqs, nu_ref, code="000"):
    """(locs, wids, amps) each (nchan, ngauss) at the given freqs."""
    ev_loc = _EVOLUTION[code[0]]
    ev_wid = _EVOLUTION[code[1]]
    ev_amp = _EVOLUTION[code[2]]
    f = freqs[:, None]
    locs = ev_loc(params["locs"][None, :], params["mlocs"][None, :], f, nu_ref)
    wids = ev_wid(params["wids"][None, :], params["mwids"][None, :], f, nu_ref)
    amps = ev_amp(params["amps"][None, :], params["mamps"][None, :], f, nu_ref)
    return locs, wids, amps


def gaussian_components_FT(params, freqs, nu_ref, nharm, code="000"):
    """rFFT (nchan, nharm) of DC + the sum of evolved Gaussian
    components — the shared spectral-model core used by both the
    pytree generator below and the flat-layout template fitter
    (fit/gauss.py)."""
    locs, wids, amps = evolved_components(params, freqs, nu_ref, code)
    nbin = 2 * (nharm - 1)
    # sum over components of analytic Gaussian FTs: (nchan, ngauss, nharm)
    gFT = gaussian_profile_FT(nharm, locs[..., None], wids[..., None], amps[..., None])
    pFT = jnp.sum(gFT, axis=1)
    return pFT.at[..., 0].add(params["dc"] * nbin)


def gaussian_components_FT_jac(params, freqs, nu_ref, nharm, code="000"):
    """Closed-form derivatives of gaussian_components_FT (ISSUE 14):
    returns (pFT, derivs) where pFT is the forward (nchan, nharm)
    model rFFT and derivs maps each flat-parameter family —
    'dc' (nchan, nharm), and 'locs'/'mlocs'/'wids'/'mwids'/'amps'/
    'mamps' each (nchan, ngauss, nharm) — to d pFT / d(that scalar of
    component g).  Evolution chain rules ride the per-family
    (dp/dvalue, dp/dmod) pairs (_EVOLUTION_GRADS); the Gaussian-kernel
    block comes from ops.gaussian.gaussian_profile_FT_jac (the
    sigma-multiplied NaN-free form, safe for frozen zero-amplitude
    pads)."""
    from ..ops.gaussian import gaussian_profile_FT_jac

    locs, wids, amps = evolved_components(params, freqs, nu_ref, code)
    f = freqs[:, None]
    vgrad_loc = _EVOLUTION_GRADS[code[0]](
        params["locs"][None, :], params["mlocs"][None, :], f, nu_ref)
    vgrad_wid = _EVOLUTION_GRADS[code[1]](
        params["wids"][None, :], params["mwids"][None, :], f, nu_ref)
    vgrad_amp = _EVOLUTION_GRADS[code[2]](
        params["amps"][None, :], params["mamps"][None, :], f, nu_ref)
    nbin = 2 * (nharm - 1)
    G, dloc, dwid, damp = gaussian_profile_FT_jac(
        nharm, locs[..., None], wids[..., None], amps[..., None])
    pFT = jnp.sum(G, axis=1).at[..., 0].add(params["dc"] * nbin)
    dc_col = jnp.zeros_like(pFT).at[..., 0].set(
        jnp.asarray(nbin, pFT.real.dtype))
    derivs = {
        "dc": dc_col,
        "locs": dloc * vgrad_loc[0][..., None],
        "mlocs": dloc * vgrad_loc[1][..., None],
        "wids": dwid * vgrad_wid[0][..., None],
        "mwids": dwid * vgrad_wid[1][..., None],
        "amps": damp * vgrad_amp[0][..., None],
        "mamps": damp * vgrad_amp[1][..., None],
    }
    return pFT, derivs


def apply_scattering_FT(pFT, tau_rot, alpha, freqs, nu_ref):
    """Multiply a model rFFT by the per-channel scattering kernel with
    tau given in rotations at nu_ref."""
    taus = scattering_times(tau_rot, alpha, freqs, nu_ref)
    return pFT * scattering_portrait_FT(taus, pFT.shape[-1])


def gen_gaussian_portrait_FT(
    params, freqs, nu_ref, nharm, P=None, code="000", scattered=True
):
    """rFFT (nchan, nharm) of the model portrait: DC + sum of evolved
    Gaussian FTs, optionally times the per-channel scattering kernel.

    tau in ``params`` is in seconds (gmodel convention) and needs P to
    convert to rotations; tau=0 or scattered=False skips scattering.
    """
    pFT = gaussian_components_FT(params, freqs, nu_ref, nharm, code)
    if scattered and P is not None:
        pFT = apply_scattering_FT(pFT, params["tau"] / P, params["alpha"],
                                  freqs, nu_ref)
    return pFT


def gen_gaussian_portrait(
    params, freqs, nu_ref, nbin, P=None, code="000", scattered=True
):
    """Model portrait (nchan, nbin) in the phase domain.

    Parity: reference pplib.py:886-963 (whose JOIN rotation step lives
    in the pipeline layer here, not in model generation).
    """
    nharm = nbin // 2 + 1
    pFT = gen_gaussian_portrait_FT(params, freqs, nu_ref, nharm, P, code, scattered)
    return jnp.fft.irfft(pFT, n=nbin, axis=-1)


def gen_gaussian_profile(params, nbin, nu_ref=None, code="000", P=None, scattered=True):
    """Single-frequency profile (at nu_ref): DC + components + scattering.

    Parity: reference pplib.py:859-883.
    """
    freqs = jnp.asarray([1.0 if nu_ref is None else nu_ref])
    prof = gen_gaussian_portrait(
        params, freqs, 1.0 if nu_ref is None else nu_ref, nbin, P, code, scattered
    )
    return prof[0]


def model_from_params(model: GaussianModel, freqs, nbin, P=None, scattered=True):
    """Convenience: portrait from a GaussianModel dataclass."""
    return gen_gaussian_portrait(
        model.params_pytree(),
        jnp.asarray(freqs),
        model.nu_ref,
        nbin,
        P=P,
        code=model.code,
        scattered=scattered,
    )
