"""Campaign-scale streaming benchmark (BASELINE.md config 5 shape):
NARCH archives x NSUB subints of NCHAN x NBIN through
stream_wideband_TOAs, end-to-end (PSRFITS IO -> raw h2d -> on-device
decode/stats/fit -> .tim assembly) — an A/B over the transfer
pipeline (ISSUE 6): depth 1 (copy serialized against fit-enqueue, the
pre-pipeline behavior) vs depth N (double-buffered h2d, default 2 or
PPT_PIPELINE_DEPTH), asserting byte-identical .tim output across arms.

ISSUE 15 adds the bytes-on-the-wire ladder: a SUB-BYTE arm (a 2-bit
NBIT corpus of the same synthetic data, streamed packed-raw vs its
decoded-f64 fallback via the PPT_RAW_SUBBYTE escape hatch — byte
accounting per arm, digit gate on the .tim, and the >= 8x
byte-reduction acceptance gate enforced IN-BENCH every run) and a
COMPRESSION arm (a coarsely-quantized byte corpus streamed with
config.transport_compress off / on / auto — 'on' must shrink shipped
bytes at identical .tim; 'auto' must never engage when the cost model
predicts a loss, which on a bare-CPU link is always).  Under
PPT_TUNNEL_EMU, where bytes are proportional to wall, the sub-byte
arm's throughput gain tracks its byte reduction — that is the
production claim; bare-CPU runs report the byte ratios with an honest
~1x wall.

When PPT_TELEMETRY is set, each arm writes its own trace
(<path>.d<depth>) and the emitted h2d_start/h2d_done events are
schema-validated; the JSON line then carries the pptrace-computed link
stall fraction per arm — the copy-stage drift guard CI runs at tiny
shapes (tests/test_bench_smoke.py).

A bare CPU host has no link to hide (device_put is a memcpy), so the
depth A/B measures ~1.0x there.  PPT_TUNNEL_EMU="<mbps>[:<dispatch_ms>]"
emulates the tunneled-runtime transport this pipeline exists for —
device_put throttled to <mbps> MB/s and each fused dispatch made
SYNCHRONOUS with a <dispatch_ms> round-trip floor (default 100, the
measured tunnel floor; same discipline as bench_stream's virtual
devices: a CPU-measurable model of the runtime property under study).
Under emulation depth 1 serializes copy-then-fit per device while
depth 2 overlaps them, which is exactly the production claim.

The synthetic dataset is generated once into a cache directory (env
PPT_CAMPAIGN_CACHE, default /tmp/ppt_campaign) and reused across runs —
generation is host-bound and would otherwise dominate.

Knobs via env: PPT_NARCH (default 200), PPT_NSUB (64), PPT_NCHAN (256),
PPT_NBIN (1024), PPT_PIPELINE_DEPTH (deep arm, default 2),
PPT_TUNNEL_EMU (off by default).  Prints ONE JSON line like bench.py.
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    import jax

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 200))
    NSUB = int(os.environ.get("PPT_NSUB", 64))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    DEEP = max(2, int(config.stream_pipeline_depth))
    TUNNEL = os.environ.get("PPT_TUNNEL_EMU", "")
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    t_gen = time.perf_counter()
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)
    t_gen = time.perf_counter() - t_gen

    # ---- optional tunneled-transport emulation ----------------------
    from pulseportraiture_tpu.pipeline import stream as S
    unpatch = []
    if TUNNEL:
        parts = TUNNEL.split(":")
        mbps = float(parts[0])
        disp_ms = float(parts[1]) if len(parts) > 1 else 100.0
        real_put = jax.device_put

        def throttled_put(x, device=None, **kw):
            out = real_put(x, device, **kw)
            time.sleep(getattr(x, "nbytes", 0) / (mbps * 1e6))
            return out

        real_fit_fn = S._raw_fit_fn

        def sync_fit_fn(*a, **kw):
            fn = real_fit_fn(*a, **kw)

            def run(*args):
                out = jax.block_until_ready(fn(*args))
                time.sleep(disp_ms / 1e3)  # tunnel round-trip floor
                return out

            return run

        jax.device_put = throttled_put
        S._raw_fit_fn = sync_fit_fn
        unpatch = [(jax, "device_put", real_put),
                   (S, "_raw_fit_fn", real_fit_fn)]

    # warm (compile) on one archive, then measure each pipeline arm
    # over the full campaign; the tunnel-emu patches MUST come off even
    # if an arm fails (test_bench_smoke runs main() in-process — a
    # leaked throttled device_put would slow every later test)
    arms = {}
    tims = {}
    try:
        stream_wideband_TOAs(files[:1], mpath, nsub_batch=64, quiet=True)
        for depth in (1, DEEP):
            tim = os.path.join(root, f"bench.d{depth}.tim")
            trace = f"{trace_base}.d{depth}" if trace_base else None
            t0 = time.perf_counter()
            res = stream_wideband_TOAs(files, mpath, nsub_batch=64,
                                       quiet=True, pipeline_depth=depth,
                                       tim_out=tim, telemetry=trace)
            wall = time.perf_counter() - t0
            arm = {
                "toas_per_sec": round(len(res.TOA_list) / wall, 2),
                "wall_s": round(wall, 2),
                "h2d_bytes": int(res.h2d_bytes),
                "h2d_s": round(float(res.h2d_duration), 3),
                "blocked_on_device_fraction": round(
                    float(res.fit_duration) / wall, 3),
            }
            if trace:
                # schema-validate the emitted trace (h2d events
                # included) and pull the pptrace link numbers —
                # event-shape drift in the copy stage fails RIGHT HERE
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["n_h2d"] == res.nfit, (
                    f"depth {depth}: {summary['n_h2d']} h2d_done events "
                    f"for {res.nfit} dispatches")
                assert summary["h2d_bytes"] == res.h2d_bytes
                arm["link_stall_frac"] = (
                    round(summary["h2d_stall_frac"], 3)
                    if summary["h2d_stall_frac"] is not None else None)
            arms[depth] = arm
            tims[depth] = open(tim).read()
            ntoa = len(res.TOA_list)
            nfit = int(res.nfit)
    finally:
        for obj, name, val in unpatch:
            setattr(obj, name, val)

    assert tims[1] == tims[DEEP], (
        "pipeline depth changed .tim content — the transfer pipeline "
        "must only reorder WHEN bytes move")

    # ---- ISSUE 15 arm 1: sub-byte (2-bit) corpus, packed-raw vs the
    # decoded-f64 fallback — byte accounting + digit gate + the >= 8x
    # acceptance gate, all enforced here at every shape
    sub_root = os.path.join(root, "nbit2")
    os.makedirs(sub_root, exist_ok=True)
    sub_files = []
    for i in range(NARCH):
        path = os.path.join(sub_root, f"s{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=600.0, phase=0.01 * (i % 50),
                             dDM=1e-4 * (i % 40), noise_stds=0.05,
                             quiet=True, rng=i, nbit=2)
        sub_files.append(path)
    unpatch2 = []
    if TUNNEL:
        jax.device_put = throttled_put
        S._raw_fit_fn = sync_fit_fn
        unpatch2 = unpatch
    try:
        t0 = time.perf_counter()
        tim_p = os.path.join(sub_root, "packed.tim")
        res_p = stream_wideband_TOAs(sub_files, mpath, nsub_batch=64,
                                     quiet=True, tim_out=tim_p)
        wall_p = time.perf_counter() - t0
        config.raw_subbyte = False
        t0 = time.perf_counter()
        tim_f = os.path.join(sub_root, "fallback.tim")
        res_f = stream_wideband_TOAs(sub_files, mpath, nsub_batch=64,
                                     quiet=True, tim_out=tim_f)
        wall_f = time.perf_counter() - t0
        config.raw_subbyte = True
    finally:
        config.raw_subbyte = True
        for obj, name, val in unpatch2:
            setattr(obj, name, val)
    subbyte_ratio = res_f.h2d_bytes / max(res_p.h2d_bytes, 1)
    assert open(tim_p).read() == open(tim_f).read(), (
        "sub-byte packed lane drifted from the decoded-f64 oracle")
    assert subbyte_ratio >= 8.0, (
        f"2-bit corpus shipped only {subbyte_ratio:.2f}x fewer bytes "
        "than the decoded fallback (acceptance gate: >= 8x)")
    subbyte = {
        "packed_bytes": int(res_p.h2d_bytes),
        "fallback_bytes": int(res_f.h2d_bytes),
        "byte_ratio": round(subbyte_ratio, 2),
        "packed_toas_per_sec": round(len(res_p.TOA_list) / wall_p, 2),
        "fallback_toas_per_sec": round(len(res_f.TOA_list) / wall_f,
                                       2),
        "speedup": round(wall_f / max(wall_p, 1e-9), 3),
        "tim_identical": True,
    }

    # ---- ISSUE 15 arm 2: transport compression on a coarsely-
    # quantized byte corpus — off / on / auto ladder with the digit
    # gate and the never-engages-at-a-loss gate enforced here
    cmp_root = os.path.join(root, "q4")
    os.makedirs(cmp_root, exist_ok=True)
    cmp_files = []
    for i in range(NARCH):
        path = os.path.join(cmp_root, f"q{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=600.0, phase=0.01 * (i % 50),
                             dDM=1e-4 * (i % 40), noise_stds=0.05,
                             quiet=True, rng=i, nbit=8, levels=4)
        cmp_files.append(path)
    unpatch3 = []
    if TUNNEL:
        jax.device_put = throttled_put
        S._raw_fit_fn = sync_fit_fn
        unpatch3 = unpatch
    comp = {}
    comp_tims = {}
    try:
        for mode in (False, True, "auto"):
            config.transport_compress = mode
            tim = os.path.join(cmp_root, f"c_{mode}.tim")
            t0 = time.perf_counter()
            r = stream_wideband_TOAs(cmp_files, mpath, nsub_batch=64,
                                     quiet=True, tim_out=tim)
            wall = time.perf_counter() - t0
            comp[str(mode)] = {
                "h2d_bytes": int(r.h2d_bytes),
                "h2d_bytes_logical": int(r.h2d_bytes_logical),
                "codec_s": round(float(r.codec_duration), 3),
                "toas_per_sec": round(len(r.TOA_list) / wall, 2),
            }
            comp_tims[str(mode)] = open(tim).read()
    finally:
        config.transport_compress = False
        for obj, name, val in unpatch3:
            setattr(obj, name, val)
    assert comp_tims["False"] == comp_tims["True"] \
        == comp_tims["auto"], (
        "transport compression changed .tim content — the codec must "
        "be lossless before any arithmetic the fit sees")
    assert comp["True"]["h2d_bytes"] < comp["False"]["h2d_bytes"], (
        "transport_compress=on did not shrink shipped bytes on the "
        "4-level corpus")
    if not TUNNEL:
        # bare CPU: the cost model must never engage (memcpy-class
        # link -> predicted loss) — the acceptance gate
        assert comp["auto"]["h2d_bytes"] \
            == comp["auto"]["h2d_bytes_logical"], (
            "transport_compress=auto engaged on a bare-CPU link "
            "(cost model predicted a loss)")
    compression = {
        **{k: v for k, v in comp.items()},
        "compress_ratio_on": round(
            comp["True"]["h2d_bytes_logical"]
            / max(comp["True"]["h2d_bytes"], 1), 2),
        "auto_engaged": comp["auto"]["h2d_bytes"]
        != comp["auto"]["h2d_bytes_logical"],
        "tim_identical": True,
    }

    print(json.dumps({
        "metric": f"streamed campaign TOAs incl. PSRFITS IO, {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin, "
                  f"transfer pipeline depth {DEEP} (vs 1)",
        "value": arms[DEEP]["toas_per_sec"],
        "unit": "TOAs/sec",
        "gen_s": round(t_gen, 2),
        "toas": ntoa,
        "dispatches": nfit,
        "pipeline": {str(d): arms[d] for d in arms},
        "pipeline_speedup": round(
            arms[DEEP]["toas_per_sec"]
            / max(arms[1]["toas_per_sec"], 1e-9), 3),
        "tim_identical": True,
        "subbyte": subbyte,
        "compression": compression,
        "tunnel_emu": TUNNEL or None,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
