"""Template factory: fleet-scale Gaussian/spline model building
(ISSUE 9 tentpole, ROADMAP item 3).

`build_templates` applies the R7/R11 playbook to the one remaining
host-bound production stage: instead of ppgauss-style one-pulsar-at-a-
time model building (dozens-to-hundreds of serial LM dispatches per
PTA), it fits MANY pulsars' profile and portrait stages per dispatch
through the batched LM engine (fit/lm.levenberg_marquardt_batched):

- **Profile stage**: every job's breadth-first `ngauss in 1..max_ngauss`
  trial problems (matching-pursuit seeds, fit/gauss.profile_trial_seeds)
  are fused across the whole fleet, bucketed by (nbin, power-of-two
  ngauss class), and fit in one dispatch per bucket; the best reduced
  chi2 per pulsar is selected on host with the serial acceptance rule.
- **Portrait stage**: each ppgauss iteration's evolving-Gaussian
  portrait fits are bucketed by power-of-two (nchan, nbin, ngauss)
  shape classes — channels padded with +inf errors (exactly-zero
  residual rows), components padded frozen at zero amplitude, batch
  rows padded to the next power of two with fully-frozen duplicates —
  while each pulsar's rotate/convergence bookkeeping (the fused-Newton
  (phi, DM) check and the data rotation between iterations) stays on
  host between batched iterations, exactly as in
  GaussPortrait.make_gaussian_model.
- **Spline jobs** ride the same batched profile lane: the S/N-weighted
  mean profile is Gaussian-smoothed by the fleet's shared profile
  dispatch and injected into make_spline_model(smooth_mean_prof=...);
  eigenprofile smoothing stays wavelet-based on host (eigenvectors have
  negative lobes the sign-constrained Gaussian basis cannot represent).

Routing: config.gauss_device tri-state ('auto' = TPU; PPT_GAUSS_DEVICE;
per-call gauss_device=).  The host-serial lane runs the SAME padded
problems one at a time through the single-problem engine and is the
digit-exactness oracle (bench_gauss gates .gmodel identity <= 1e-10).
Telemetry: `template_fit` per bucket dispatch, `template_job` per
pulsar, `factory_end`; `tools/pptrace.py` aggregates them into the
"template factory" section.

JOIN (metafile) jobs are refused loudly — multi-receiver fits keep the
single-pulsar driver, whose join parameters ride the LM problem vector.
"""

import os
import time

import numpy as np

from ..config import default_model_code, scattering_alpha
from ..fit.gauss import (fit_gaussian_portraits_batched,
                         fit_gaussian_profiles_batched,
                         pad_portrait_params, pad_profile_params,
                         portrait_vary, profile_trial_seeds,
                         profile_vary, select_best_trial,
                         use_gauss_device)
from ..fit.lm import _pow2ceil, use_lm_jacobian
from ..io.gmodel import write_gmodel
from ..io.psrfits import noise_std_ps
from ..telemetry import log, resolve_tracer
from ..utils.bunch import DataBunch
from ..utils.device import on_host
from .toas import _is_metafile

__all__ = ["build_templates", "TemplateJob", "gauss_smooth_mean"]


def gauss_smooth_mean(dp, max_ngauss=8, wid0=0.02, rchi2_tol=0.1,
                      gauss_device=None, max_iter=100):
    """Gaussian-smooth a portrait's S/N-weighted mean profile through
    the template LM lane (batched or host-serial per ``gauss_device``):
    breadth-first trials, host selection, analytic regeneration.
    Returns the smoothed mean profile (nbin,) — feed it to
    ``make_spline_model(smooth_mean_prof=...)``.  This is the
    single-pulsar form of what build_templates' spline jobs get from
    the fleet's shared profile buckets (``ppspline --gauss-device``
    routes here)."""
    from ..fit.gauss import fit_profile_trials, gen_gaussian_profile_flat
    from .spline import snr_weighted_mean

    profile = np.asarray(snr_weighted_mean(dp), float)
    noise = float(noise_std_ps(profile))
    sel = fit_profile_trials(profile, max_ngauss, noise, wid0=wid0,
                             rchi2_tol=rchi2_tol, max_iter=max_iter,
                             serial=not use_gauss_device(gauss_device))
    if sel is None:
        raise ValueError(
            "gauss_smooth_mean: every trial fit failed (non-finite "
            "chi2) — check the profile and noise level")
    return np.asarray(gen_gaussian_profile_flat(sel.params,
                                                len(profile)))


class TemplateJob:
    """One pulsar's template-building state inside the fleet driver:
    the loaded portrait object (all host bookkeeping — reference-
    profile selection, convergence checks, rotations — runs on it, the
    same methods the single-pulsar driver uses) plus the per-iteration
    fit state the bucketed dispatches read and write."""

    def __init__(self, datafile, kind, dp, outfile):
        self.datafile = datafile
        self.kind = kind
        self.dp = dp
        self.outfile = outfile
        # profile stage
        self.seeds = None
        self.trial_idx = []      # (bucket_key, row) per trial
        self.ngauss = None
        self.profile_red_chi2 = None
        # portrait stage (gauss jobs)
        self.x0 = None           # current flat portrait params
        self.alpha = None        # current scattering index
        self.flags = None
        self.niter = 0
        self.itern = 0
        self.converged = False
        self.model = None

    @property
    def n_ok(self):
        return len(self.dp.ok_ichans)


def _resolved_jac_mode():
    """The Jacobian source the factory's dispatches actually use:
    every gauss residual ships its analytic companion, so 'auto'
    resolves to 'analytic' and only an explicit 'ad' keeps autodiff —
    carried on every template_fit event so a trace names its lane."""
    return "ad" if use_lm_jacobian() == "ad" else "analytic"


def _profile_bucket_key(nbin, ngauss):
    return (int(nbin), _pow2ceil(ngauss))


def _portrait_bucket_key(nbin, nchan, ngauss, model_code):
    return (int(nbin), _pow2ceil(nchan), _pow2ceil(ngauss), model_code)


def _pad_rows(arrays, vary, B_pad):
    """Pad a bucket's stacked problem arrays to B_pad rows by
    duplicating row 0 with vary all-False: a fully-frozen problem
    converges on its first iteration and cannot perturb real rows
    (vmap keeps problems independent); its results are discarded."""
    B = len(vary)
    if B == B_pad:
        return arrays, vary
    pad = B_pad - B
    arrays = [np.concatenate([a, np.repeat(a[:1], pad, axis=0)])
              for a in arrays]
    vary = np.concatenate([vary, np.zeros((pad,) + vary.shape[1:],
                                          bool)])
    return arrays, vary


def _dispatch_profiles(bucket_key, rows, batched, max_iter, tracer):
    """Fit one profile bucket: rows = list of (job, trial_g, x0, vary,
    profile, noise).  Returns per-row LMResult fields (numpy), real
    rows only."""
    nbin, gclass = bucket_key
    B = len(rows)
    B_pad = _pow2ceil(B) if batched else B
    data = np.stack([r[4] for r in rows])
    errs = np.asarray([r[5] for r in rows], float)
    x0s = np.stack([r[2] for r in rows])
    vary = np.stack([r[3] for r in rows])
    (data, errs, x0s), vary = _pad_rows([data, errs, x0s], vary, B_pad)
    t0 = time.perf_counter()
    res = fit_gaussian_profiles_batched(data, x0s, errs, vary,
                                        max_iter=max_iter,
                                        serial=not batched)
    out = {f: np.asarray(getattr(res, f))[:B]
           for f in ("x", "x_err", "chi2", "dof", "nfev", "success",
                     "stalled")}
    wall = time.perf_counter() - t0
    if tracer.enabled:
        tracer.emit("template_fit", stage="profile",
                    bucket=f"prof:{nbin}b:{gclass}g", rows=B,
                    pad=B_pad - B, nfev_max=int(out["nfev"].max()),
                    wall_s=round(wall, 6), batched=bool(batched),
                    jac=_resolved_jac_mode())
    return out, wall


def _dispatch_portraits(bucket_key, rows, batched, max_iter, tracer):
    """Fit one portrait bucket: rows = list of (job, x0_full, vary,
    data_pad, errs_pad, freqs_pad, nu_ref, P, nchan_valid)."""
    nbin, cclass, gclass, model_code = bucket_key
    B = len(rows)
    B_pad = _pow2ceil(B) if batched else B
    data = np.stack([r[3] for r in rows])
    errs = np.stack([r[4] for r in rows])
    freqs = np.stack([r[5] for r in rows])
    nu_refs = np.asarray([r[6] for r in rows], float)
    Ps = np.asarray([r[7] for r in rows], float)
    ncv = np.asarray([r[8] for r in rows], int)
    x0s = np.stack([r[1] for r in rows])
    vary = np.stack([r[2] for r in rows])
    (data, errs, freqs, nu_refs, Ps, ncv, x0s), vary = _pad_rows(
        [data, errs, freqs, nu_refs, Ps, ncv, x0s], vary, B_pad)
    t0 = time.perf_counter()
    res = fit_gaussian_portraits_batched(
        data, x0s, errs, vary, freqs, nu_refs, Ps,
        model_code=model_code, nchan_valid=ncv, max_iter=max_iter,
        serial=not batched)
    out = {f: np.asarray(getattr(res, f))[:B]
           for f in ("x", "x_err", "chi2", "dof", "nfev", "success",
                     "stalled")}
    wall = time.perf_counter() - t0
    if tracer.enabled:
        tracer.emit("template_fit", stage="portrait",
                    bucket=f"port:{cclass}c:{nbin}b:{gclass}g", rows=B,
                    pad=B_pad - B, nfev_max=int(out["nfev"].max()),
                    wall_s=round(wall, 6), batched=bool(batched),
                    jac=_resolved_jac_mode())
    return out, wall


@on_host
def build_templates(datafiles, kind="gauss", outdir=None, outfiles=None,
                    max_ngauss=8, wid0=0.02, rchi2_tol=0.1, tau=0.0,
                    fixloc=False, fixwid=False, fixamp=False,
                    fixscat=True, fixalpha=True,
                    scattering_index=scattering_alpha,
                    model_code=default_model_code, niter=0,
                    fiducial_gaussian=False, normalize=None,
                    gauss_device=None, max_iter=200,
                    profile_max_iter=100, write=True,
                    spline_kwargs=None, telemetry=None, quiet=True):
    """Build one template per archive for a whole fleet, batching the
    LM fits across pulsars (module docstring has the architecture).

    datafiles: archive paths (or preloaded DataPortrait-like objects
    paired as (object, name) tuples — the bench uses this to exclude
    IO from the A/B).  kind: 'gauss' | 'spline', or a per-file
    sequence.  outfiles: explicit output paths (else outdir/<base> or
    <datafile> + '.gmodel'/'.spl').  gauss_device: per-call lane
    override (None -> config.gauss_device).  Remaining options follow
    make_gaussian_model / make_spline_model.

    Returns a list of DataBunch(datafile, kind, model, outfile, ngauss,
    converged, iters, red_chi2) in input order.
    """
    if not datafiles:
        raise ValueError("build_templates: no datafiles given")
    max_ngauss = int(max_ngauss)
    if max_ngauss < 1:
        raise ValueError(
            f"build_templates needs max_ngauss >= 1 (got {max_ngauss})")
    batched = use_gauss_device(gauss_device)
    kinds = ([kind] * len(datafiles) if isinstance(kind, str)
             else list(kind))
    if len(kinds) != len(datafiles):
        raise ValueError("kind must be a string or one entry per "
                         "datafile")
    for k in kinds:
        if k not in ("gauss", "spline"):
            raise ValueError(f"unknown template kind {k!r} "
                             "('gauss' or 'spline')")
    tracer, own_tracer = resolve_tracer(telemetry, run="build_templates")
    t_run = time.perf_counter()
    n_dispatch = 0
    try:
        # ---- load the fleet (host IO) --------------------------------
        from .gauss import (GaussPortrait, portrait_fit_flags,
                            profile_to_portrait_params)
        from .spline import SplinePortrait, snr_weighted_mean

        jobs = []
        for i, df in enumerate(datafiles):
            if isinstance(df, tuple):
                dp, name = df
            else:
                if _is_metafile(df):
                    raise ValueError(
                        f"build_templates: {df!r} is a metafile — JOIN "
                        "(multi-receiver) fits keep the single-pulsar "
                        "ppgauss driver, whose join parameters ride "
                        "the LM problem vector")
                cls = GaussPortrait if kinds[i] == "gauss" \
                    else SplinePortrait
                dp, name = cls(df, quiet=True), str(df)
            if outfiles is not None:
                out = outfiles[i]
            else:
                ext = ".gmodel" if kinds[i] == "gauss" else ".spl"
                out = (os.path.join(outdir, os.path.basename(name) + ext)
                       if outdir else name + ext)
            if normalize:
                dp.normalize_portrait(normalize)
            jobs.append(TemplateJob(name, kinds[i], dp, out))
        if outdir:
            os.makedirs(outdir, exist_ok=True)

        # ---- profile stage: fleet x trials, one dispatch per bucket --
        prof_buckets = {}
        for job in jobs:
            dp = job.dp
            if job.kind == "gauss":
                profile, nu_ref = dp.select_ref_profile()
                dp.nu_ref = nu_ref
            else:
                profile = snr_weighted_mean(dp)
            profile = np.asarray(profile, float)
            noise = float(noise_std_ps(profile))
            job.seeds = profile_trial_seeds(profile, max_ngauss,
                                            wid0=wid0, tau=tau,
                                            noise=noise)
            for g, seed in enumerate(job.seeds, start=1):
                key = _profile_bucket_key(len(profile), g)
                padded, _ = pad_profile_params(seed, key[1])
                vary = profile_vary(g, key[1],
                                    fit_scattering=not fixscat)
                rows = prof_buckets.setdefault(key, [])
                job.trial_idx.append((key, len(rows)))
                rows.append((job, g, padded, vary, profile, noise))
        prof_results = {}
        for key in sorted(prof_buckets):
            out, _ = _dispatch_profiles(key, prof_buckets[key], batched,
                                        profile_max_iter, tracer)
            prof_results[key] = out
            n_dispatch += 1

        # ---- per-job trial selection (host, serial-loop semantics) ---
        for job in jobs:
            reds, xs, xerrs, succ, stall = [], [], [], [], []
            for (key, row), g in zip(job.trial_idx,
                                     range(1, max_ngauss + 1)):
                r = prof_results[key]
                reds.append(float(r["chi2"][row])
                            / max(float(r["dof"][row]), 1.0))
                nsel = 2 + 3 * g
                xs.append(r["x"][row][:nsel])
                xerrs.append(r["x_err"][row][:nsel])
                succ.append(bool(r["success"][row]))
                stall.append(bool(r["stalled"][row]))
            ibest = select_best_trial(reds, rchi2_tol=rchi2_tol,
                                      success=succ, stalled=stall)
            if ibest is None:
                raise ValueError(
                    f"build_templates: every profile trial of "
                    f"{job.datafile!r} failed (non-finite chi2 for all "
                    f"ngauss in 1..{max_ngauss})")
            job.ngauss = ibest + 1
            job.profile_red_chi2 = reds[ibest]
            job.dp.init_params = np.asarray(xs[ibest])
            job.dp.init_param_errs = np.asarray(xerrs[ibest])
            job.dp.ngauss = job.ngauss
            log(f"{job.datafile}: {job.ngauss} components, profile red "
                f"chi2 {reds[ibest]:.2f}", quiet=quiet, tracer=tracer)

        # ---- spline jobs: host spline build on the Gauss-smoothed mean
        from ..fit.gauss import gen_gaussian_profile_flat

        for job in jobs:
            if job.kind != "spline":
                continue
            smooth_mean = np.asarray(gen_gaussian_profile_flat(
                job.dp.init_params, job.dp.nbin))
            job.model = job.dp.make_spline_model(
                smooth=True, smooth_mean_prof=smooth_mean,
                model_name=None, quiet=True,
                **(spline_kwargs or {}))
            job.converged = True
            job.itern = 1
            if write:
                job.dp.write_model(job.outfile, quiet=True)
            if tracer.enabled:
                tracer.emit("template_job", datafile=job.datafile,
                            kind="spline", ngauss=int(job.ngauss),
                            converged=True, iters=1)

        # ---- portrait stage: iterate bucketed fleet fits -------------
        import jax.numpy as jnp

        from ..ops.phasor import guess_fit_freq

        gauss_jobs = [j for j in jobs if j.kind == "gauss"]
        for job in gauss_jobs:
            dp = job.dp
            job.x0 = profile_to_portrait_params(dp.init_params)
            job.alpha = float(scattering_index)
            job.flags = portrait_fit_flags(
                job.ngauss, fixloc=fixloc, fixwid=fixwid,
                fixamp=fixamp, fixscat=fixscat,
                fiducial_gaussian=fiducial_gaussian)
            dp._flags_cache = job.flags
            dp.model_name = job.outfile
            dp.model_code = model_code
            dp.nu_fit = float(guess_fit_freq(
                jnp.asarray(dp.freqsxs[0]), jnp.asarray(dp.SNRsxs[0])))
            job.niter = int(niter)
        active = list(gauss_jobs)
        while active:
            buckets = {}
            for job in active:
                dp = job.dp
                key = _portrait_bucket_key(dp.nbin, job.n_ok,
                                           job.ngauss, model_code)
                nbin, cclass, gclass = key[0], key[1], key[2]
                okc = dp.ok_ichans
                data = np.zeros((cclass, nbin))
                data[:job.n_ok] = dp.port[okc]
                errs_full = np.where(
                    dp.noise_stds > 0, dp.noise_stds,
                    np.median(dp.noise_stds[okc]))
                errs = np.full(cclass, np.inf)
                errs[:job.n_ok] = errs_full[okc]
                freqs = np.full(cclass, dp.freqsxs[0][-1])
                freqs[:job.n_ok] = dp.freqsxs[0]
                xp, _ = pad_portrait_params(job.x0, gclass)
                x0_full = np.concatenate([xp, [job.alpha]])
                vary = portrait_vary(job.flags, gclass,
                                     fit_scattering_index=not fixalpha)
                buckets.setdefault(key, []).append(
                    (job, x0_full, vary, data, errs, freqs, dp.nu_ref,
                     float(dp.Ps[0]), job.n_ok))
            for key in sorted(buckets):
                rows = buckets[key]
                out, _ = _dispatch_portraits(key, rows, batched,
                                             max_iter, tracer)
                n_dispatch += 1
                for b, row in enumerate(rows):
                    job = row[0]
                    dp = job.dp
                    nmain = 2 + 6 * job.ngauss
                    dp.fitted_params = out["x"][b][:nmain].copy()
                    dp.fit_errs = out["x_err"][b][:nmain].copy()
                    job.alpha = float(out["x"][b][-1])
                    dp.portrait_red_chi2 = (
                        float(out["chi2"][b])
                        / max(float(out["dof"][b]), 1.0))
                    job.x0 = dp.fitted_params
            still = []
            for job in active:
                dp = job.dp
                dp._rebuild_model(model_code, job.alpha,
                                  float(dp.Ps[0]))
                converged = dp.check_convergence(efac=1.0, quiet=True)
                job.itern += 1
                if converged or job.itern > job.niter:
                    job.converged = bool(converged)
                else:
                    dp.rotate_stuff(phase=dp.phi, DM=dp.DM,
                                    nu_ref=dp.nu_fit)
                    still.append(job)
            active = still

        # ---- finalize gauss jobs -------------------------------------
        for job in gauss_jobs:
            dp = job.dp
            dp.scattering_index = job.alpha
            job.model = dp._to_gmodel(job.outfile, model_code,
                                      job.alpha, int(not fixalpha),
                                      job.flags, float(dp.Ps[0]))
            dp.gaussian_model = job.model
            if write:
                write_gmodel(job.model, job.outfile, quiet=True)
            if tracer.enabled:
                tracer.emit("template_job", datafile=job.datafile,
                            kind="gauss", ngauss=int(job.ngauss),
                            converged=bool(job.converged),
                            iters=int(job.itern))
            log(f"{job.datafile}: portrait red chi2 "
                f"{dp.portrait_red_chi2:.2f} after {job.itern} "
                f"iteration(s)"
                + ("" if job.converged else " (not converged)"),
                quiet=quiet, tracer=tracer)

        wall = time.perf_counter() - t_run
        if tracer.enabled:
            tracer.emit("factory_end", n_jobs=len(jobs),
                        n_dispatches=n_dispatch, wall_s=round(wall, 6))
        results = [DataBunch(
            datafile=j.datafile, kind=j.kind, model=j.model,
            outfile=(j.outfile if write else None), ngauss=j.ngauss,
            converged=j.converged, iters=j.itern,
            red_chi2=(getattr(j.dp, "portrait_red_chi2", None)
                      if j.kind == "gauss" else j.profile_red_chi2))
            for j in jobs]
        return results
    finally:
        if own_tracer:
            tracer.close()
