"""ctypes loader for the native SUBINT decode kernels (native/).

Builds ``libppt_native.so`` lazily with g++ the first time it is
needed; every entry point degrades gracefully to the pure-numpy path
in ``psrfits.read_archive`` when no compiler or binary is available,
so the package stays importable on any host.  pybind11 is not part of
this image, hence plain ctypes over an ``extern "C"`` surface.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "ppt_native.cpp")
_SO = os.path.join(_NATIVE_DIR, "libppt_native.so")

# DATA-column sample types, matching the enum in ppt_native.cpp
CODE_I16BE, CODE_U8, CODE_F32BE, CODE_I8 = 0, 1, 2, 3
_TFORM_CODE = {"I": CODE_I16BE, "B": CODE_U8, "E": CODE_F32BE}

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-fopenmp",
        "-o", _SO, _SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True, cwd=_NATIVE_DIR)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.SubprocessError):
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f8p = ctypes.POINTER(ctypes.c_double)
        lib.ppt_decode_fused.restype = ctypes.c_int
        lib.ppt_decode_fused.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, f8p, f8p,
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
        ]
        lib.ppt_gather_f.restype = ctypes.c_int
        lib.ppt_gather_f.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, f8p,
        ]
        _lib = lib
        return _lib


def available():
    return _load() is not None


def decode_fused(raw, nrows, row_stride, col_off, tform_code, npol, nchan,
                 nbin, scl=None, offs=None, dtype=np.float64):
    """Decode the DATA column from raw bintable bytes and apply
    DAT_SCL/DAT_OFFS in one fused, threaded pass.

    raw: bytes/buffer of the table payload; scl/offs: (nrows, npol*nchan)
    float64 or None.  Returns (nrows, npol, nchan, nbin) in ``dtype``
    (float32 or float64).  Raises ValueError for unsupported sample
    types; returns None if the native library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    if tform_code not in _TFORM_CODE:
        raise ValueError(f"unsupported DATA TFORM code {tform_code!r}")
    dtype = np.dtype(dtype)
    if dtype not in (np.dtype(np.float32), np.dtype(np.float64)):
        raise ValueError("dtype must be float32 or float64")
    ngrp = npol * nchan
    rawbuf = np.frombuffer(raw, np.uint8)
    out = np.empty((nrows, npol, nchan, nbin), dtype)

    def f8ptr(a):
        if a is None:
            return None
        a = np.ascontiguousarray(a, np.float64)
        if a.size != nrows * ngrp:
            raise ValueError(
                f"scale/offset size {a.size} != nrows*npol*nchan "
                f"{nrows * ngrp}")
        return a, a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))

    s = f8ptr(scl)
    o = f8ptr(offs)
    rc = lib.ppt_decode_fused(
        rawbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        nrows, row_stride, col_off, ngrp, nbin,
        s[1] if s else None, o[1] if o else None,
        _TFORM_CODE[tform_code],
        1 if dtype == np.dtype(np.float64) else 0,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        raise RuntimeError(f"ppt_decode_fused failed with code {rc}")
    return out
