from .mesh import make_mesh, batch_sharding, replicated
from .batch import (align_accumulate_archive, align_accumulator_init,
                    align_finalize, align_iteration_sharded,
                    fit_portrait_sharded, fit_portrait_sharded_fast,
                    shard_batch, use_align_device)
from .multihost import (global_mesh, init_multihost, process_allgather,
                        process_count, process_index, shard_files)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated",
    "align_accumulate_archive",
    "align_accumulator_init",
    "align_finalize",
    "use_align_device",
    "align_iteration_sharded",
    "fit_portrait_sharded",
    "fit_portrait_sharded_fast",
    "shard_batch",
    "init_multihost",
    "process_count",
    "process_index",
    "shard_files",
    "global_mesh",
    "process_allgather",
]
