from .fake import default_test_model, fake_portrait, fake_observation

__all__ = ["default_test_model", "fake_portrait", "fake_observation"]
