"""ppgauss — fit an evolving Gaussian-component model.

Flag parity: reference ppgauss.py:666-812.
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppgauss", description=__doc__.splitlines()[0])
    p.add_argument("-d", "--datafile", default=None,
                   help="PSRFITS archive to fit.")
    p.add_argument("-M", "--metafile", default=None,
                   help="Metafile of archives (JOIN fit across receivers).")
    p.add_argument("-I", "--improve", dest="modelfile", default=None,
                   help="Start from an existing .gmodel and improve it.")
    p.add_argument("-o", "--outfile", default=None,
                   help="Output model file name.")
    p.add_argument("-e", "--errfile", default=None,
                   help="Output parameter-error file name.")
    p.add_argument("-j", "--joinfile", default=None,
                   help="Joinfile with previously fitted JOIN parameters.")
    p.add_argument("-m", "--model_name", default=None)
    p.add_argument("--nu_ref", type=float, default=None,
                   help="Reference frequency [MHz] of the model.")
    p.add_argument("--bw", dest="bw_ref", type=float, default=None,
                   help="Bandwidth [MHz] of the reference profile slice.")
    p.add_argument("--tau", type=float, default=0.0,
                   help="Scattering timescale [bin].")
    p.add_argument("--fitloc", dest="fixloc", action="store_false",
                   default=True, help="Let component positions evolve.")
    p.add_argument("--fixwid", action="store_true", default=False,
                   help="Do not let widths evolve.")
    p.add_argument("--fixamp", action="store_true", default=False,
                   help="Do not let amplitudes evolve.")
    p.add_argument("--fitscat", dest="fixscat", action="store_false",
                   default=True, help="Fit a scattering timescale.")
    p.add_argument("--fitalpha", dest="fixalpha", action="store_false",
                   default=True, help="Fit the scattering index.")
    p.add_argument("--mcode", dest="model_code", default="000",
                   help="Three-digit evolution-function code.")
    p.add_argument("--niter", type=int, default=0,
                   help="Number of iterations after the initial fit.")
    p.add_argument("--fgauss", action="store_true", default=False,
                   help="Fix the first component as fiducial.")
    p.add_argument("--autogauss", dest="auto_gauss", type=float,
                   default=0.0,
                   help="Initial single-Gaussian width guess [rot] for a "
                        "non-interactive fit.")
    p.add_argument("--norm", dest="normalize", default=None,
                   choices=(None, "mean", "max", "prof", "rms", "abs"))
    p.add_argument("--figure", default=False,
                   help="Save a residual plot to this file name.")
    p.add_argument("--batch", action="store_true", default=False,
                   help="Fleet mode: treat -M as one archive per line, "
                        "one template PER ARCHIVE, fits batched across "
                        "the fleet (pipeline/factory.build_templates; "
                        "this is not the JOIN metafile mode).")
    p.add_argument("--max-ngauss", dest="max_ngauss", type=int,
                   default=8,
                   help="Trial component counts 1..N for the "
                        "breadth-first auto profile fit.")
    p.add_argument("--gauss-device", default=None,
                   help="LM lane: 'off' (host-serial oracle), 'auto' "
                        "(batched on TPU), 'on' (force batched) "
                        "[default: config.gauss_device].")
    p.add_argument("--lm-jacobian", dest="lm_jacobian", default=None,
                   help="LM Jacobian source: 'auto' (analytic when the "
                        "model provides one), 'analytic' (require it), "
                        "'ad' (force jax.jacfwd — the digit oracle) "
                        "[default: config.lm_jacobian].")
    p.add_argument("--verbose", dest="quiet", action="store_false",
                   default=True)
    return p


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if not args.datafile and not args.metafile:
        parser.error("need -d datafile or -M metafile")
    from .ppfactory import apply_lm_jacobian, parse_gauss_device

    gauss_device = None
    if args.gauss_device is not None:
        gauss_device = parse_gauss_device(args.gauss_device)
    apply_lm_jacobian(args.lm_jacobian)
    if args.max_ngauss < 1:
        raise SystemExit(f"--max-ngauss must be >= 1, got "
                         f"{args.max_ngauss}")
    if args.batch:
        if not args.metafile:
            raise SystemExit("--batch requires -M metafile (one "
                             "archive per line)")
        # options the fleet factory does not take must fail LOUDLY,
        # not be silently dropped (each model is named per archive;
        # JOIN/improve/reference-slice modes keep the single driver)
        for flag, val in (("-I/--improve", args.modelfile),
                          ("-o/--outfile", args.outfile),
                          ("-e/--errfile", args.errfile),
                          ("-j/--joinfile", args.joinfile),
                          ("-m/--model_name", args.model_name),
                          ("--nu_ref", args.nu_ref),
                          ("--bw", args.bw_ref),
                          ("--figure", args.figure or None)):
            if val is not None:
                raise SystemExit(
                    f"{flag} is not supported with --batch (models "
                    "are named per archive; use ppfactory -O for an "
                    "output directory, or the single-archive driver)")
        from ..pipeline.factory import build_templates
        from ..pipeline.toas import _read_metafile

        files = _read_metafile(args.metafile)
        build_templates(
            files, kind="gauss", max_ngauss=args.max_ngauss,
            wid0=args.auto_gauss or 0.02,
            tau=args.tau, fixloc=args.fixloc, fixwid=args.fixwid,
            fixamp=args.fixamp, fixscat=args.fixscat,
            fixalpha=args.fixalpha, model_code=args.model_code,
            niter=args.niter, fiducial_gaussian=args.fgauss,
            normalize=args.normalize, gauss_device=gauss_device,
            quiet=args.quiet)
        return 0
    from ..pipeline.gauss import GaussPortrait

    dp = GaussPortrait(args.metafile or args.datafile,
                       joinfile=args.joinfile, quiet=args.quiet)
    if args.normalize:
        dp.normalize_portrait(args.normalize)
    datafile = args.metafile or args.datafile
    outfile = args.outfile or (datafile + ".gmodel")
    dp.make_gaussian_model(
        modelfile=args.modelfile, ref_prof=(args.nu_ref, args.bw_ref),
        tau=args.tau, fixloc=args.fixloc, fixwid=args.fixwid,
        fixamp=args.fixamp, fixscat=args.fixscat, fixalpha=args.fixalpha,
        model_code=args.model_code, niter=args.niter,
        fiducial_gaussian=args.fgauss, auto_gauss=args.auto_gauss,
        writemodel=True, outfile=outfile, writeerrfile=bool(args.errfile),
        errfile=args.errfile, model_name=args.model_name,
        residplot=args.figure or None, gauss_device=gauss_device,
        max_ngauss=args.max_ngauss, quiet=args.quiet)
    return 0


if __name__ == "__main__":
    sys.exit(main())
