"""Host-side matplotlib visualization (SURVEY §2.2 'Visualization')."""

from .plots import (  # noqa: F401
    plot_flux_profile,
    set_colormap,
    show_eigenprofiles,
    show_portrait,
    show_profiles,
    show_residual_plot,
    show_spline_curve_projections,
    show_stacked_profiles,
)
