"""Noise / S-N estimation and analytic amplitude scales.

Parity targets: reference pplib.py:2290-2424 (get_noise dispatch,
get_noise_PS, get_SNR, get_scales).
"""

import jax.numpy as jnp
from jax import lax

from .fourier import rfft_c

_TOPBIT = 0x80000000
_FULL32 = 0xFFFFFFFF


def _order_u32(x):
    """Order-preserving map f32 -> uint32 (the radix-sort float trick):
    negative floats complement, positives set the top bit — total order
    as unsigned ints matches the float order (+-0 collide, harmless:
    the values are equal)."""
    u = lax.bitcast_convert_type(x, jnp.uint32)
    top = jnp.uint32(_TOPBIT)
    return jnp.where(u & top != 0, ~u, u | top)


def _unorder_u32(m):
    top = jnp.uint32(_TOPBIT)
    bits = jnp.where(m & top != 0, m ^ top, ~m)
    return lax.bitcast_convert_type(bits, jnp.float32)


def exact_median_lastaxis(x):
    """Median over the last axis, EXACTLY equal to jnp.median (same
    order statistics, same (lo+hi)/2 mean) but sort-free: a 32-trip
    bitwise binary search on the order-preserving uint32 image of the
    data, each trip one vectorized compare+count pass.

    XLA lowers jnp.median through a general comparator sort that is
    catastrophically slow on both CPU (measured 3.24 s for 16k profiles
    x 1024 bins — single-handedly ~80% of the streaming raw bucket's
    device time) and TPU (sorts don't vectorize on the VPU); the
    counting search is ~34 elementwise passes and measures 4.9x faster
    on CPU at that shape, bit-identical output.  f32 only (the raw
    campaign lane's dtype); other dtypes fall back to jnp.median.
    Assumes finite inputs (like every consumer on the streaming path).
    """
    if x.dtype != jnp.float32:
        return jnp.median(x, axis=-1)
    n = x.shape[-1]
    m = _order_u32(x)
    k_lo = (n - 1) // 2  # 0-indexed lower-middle order statistic

    def kth(k):
        """Smallest value v with count(<= v) >= k+1, by bisection on
        the uint32 key space."""
        lo = jnp.zeros(x.shape[:-1], jnp.uint32)
        hi = jnp.full(x.shape[:-1], _FULL32, jnp.uint32)

        def body(i, st):
            lo, hi = st
            mid = lo + ((hi - lo) >> 1)
            cnt = jnp.sum(m <= mid[..., None], axis=-1)
            go_hi = cnt <= k
            return (jnp.where(go_hi, mid + 1, lo),
                    jnp.where(go_hi, hi, mid))

        lo, hi = lax.fori_loop(0, 32, body, (lo, hi))
        return lo

    v1 = kth(k_lo)
    if n % 2 == 1:
        return _unorder_u32(v1)
    # upper middle: v1 again when its duplicates reach past k_lo+1,
    # else the smallest element strictly above it (two passes, no
    # second search)
    cnt1 = jnp.sum(m <= v1[..., None], axis=-1)
    above = jnp.where(m > v1[..., None], m, jnp.uint32(_FULL32))
    v2 = jnp.where(cnt1 >= k_lo + 2, v1, jnp.min(above, axis=-1))
    return (_unorder_u32(v1) + _unorder_u32(v2)) / 2


def get_noise_PS(data, frac=0.25):
    """Off-pulse noise std per profile from the power spectrum: the
    mean power in the top ``frac`` of rFFT frequencies, converted to a
    time-domain standard deviation.

    For white noise of std sigma, E|X_k|^2 = nbin * sigma^2, so
    sigma_hat = sqrt(mean_power / nbin).  Works on any (..., nbin)
    array, returning (...).  Parity: reference pplib.py:2312-2338.
    """
    data = jnp.asarray(data)
    nbin = data.shape[-1]
    X = rfft_c(data)
    nharm = X.shape[-1]
    kc = int((1.0 - frac) * nharm)
    power = jnp.abs(X[..., kc:]) ** 2.0
    return jnp.sqrt(jnp.mean(power, axis=-1) / nbin)


def min_window_baseline(profiles, frac=0.15):
    """Mean of the quietest circular duty-cycle window of each (...,
    nbin) profile — the device mirror of the PSRCHIVE-style 'minimum
    window' estimator in io/psrfits.py:baseline_window_stats, used by
    the streaming driver's on-device prepare stage so raw archive bytes
    never need a host decode pass.

    Same algorithm: cumulative sums -> all nbin circular window means
    -> the minimum one.  Accumulates in the input dtype: f64 on the
    CPU-parity path, f32 on TPU (relative window-mean error ~nbin*eps
    ~ 6e-5 of the data scale — far below any noise floor).

    On TPU the cumsum is a matmul against a device-built triangular
    mask: XLA lowers jnp.cumsum to a scan that costs ~5 s at campaign
    shapes, while the MXU does the O(nbin^2) triangular product in
    ~1 ms."""
    from ..tune.capability import resolve_auto

    p = jnp.asarray(profiles)
    nbin = p.shape[-1]
    w = max(1, int(round(frac * nbin)))
    if resolve_auto("noise_matmul_cumsum", "auto"):
        iota = jnp.arange(nbin)
        tri = (iota[:, None] <= iota[None, :]).astype(p.dtype)
        cs = jnp.matmul(p, tri, precision="highest")
    else:
        cs = jnp.cumsum(p, axis=-1)
    total = cs[..., -1:]
    first = cs[..., w - 1:w]
    direct = cs[..., w:] - cs[..., :nbin - w]
    wrapped = total - cs[..., nbin - w:nbin - 1] + cs[..., :w - 1]
    means = jnp.concatenate([first, direct, wrapped], axis=-1) / w
    return jnp.min(means, axis=-1).astype(p.dtype)


def get_noise(data, method="PS", **kwargs):
    """Dispatch noise estimator: 'PS' (power-spectrum tail, jax, hot
    path) or 'fit' (noise-floor-cutoff fit, host-side numpy, offline).
    Parity: reference pplib.py:2290-2309.
    """
    if method == "PS":
        return get_noise_PS(data, **kwargs)
    if method == "fit":
        from .filters import get_noise_fit

        import numpy as np

        data = np.asarray(data)
        # match get_noise_PS's batching: 2-D input -> per-channel noise
        kwargs.setdefault("chans", data.ndim >= 2)
        return get_noise_fit(data, **kwargs)
    raise ValueError(f"unknown noise method {method!r}")


def fourier_noise(noise_std, nbin):
    """Std of the real/imag parts of unnormalized rFFT coefficients of
    white noise with time-domain std ``noise_std``:
    sigma_F = noise_std * sqrt(nbin / 2).

    Parity: reference pplib.py:2160-2162 — this scaling must match the
    fit engines exactly for chi^2 to be calibrated.
    """
    return noise_std * jnp.sqrt(nbin / 2.0)


def channel_SNRs_FT(dFT, mFT, errs_F, harm_weights=None):
    """Matched-filter S/N of each channel of a data portrait against a
    (already aligned) model portrait, in the Fourier domain.

    snr_n = a_n * sqrt(S_n) with S_n = sum_k |m_nk|^2/sig_n^2 and
    a_n = C_n/S_n (see get_scales).  Parity: reference
    pptoaslib.py:1127-1131.
    """
    if harm_weights is None:
        harm_weights = jnp.ones(dFT.shape[-1], dtype=errs_F.dtype)
    w = harm_weights / errs_F[..., None] ** 2.0
    S = jnp.sum(jnp.abs(mFT) ** 2.0 * w, axis=-1)
    C = jnp.sum((dFT * jnp.conj(mFT)).real * w, axis=-1)
    S = jnp.maximum(S, jnp.finfo(S.dtype).tiny)
    return C / jnp.sqrt(S)


def get_SNR(profile, noise_std=None, fudge=3.25):
    """Equivalent-width S/N of a profile (reporting/weighting only; not
    on the fit path).

    weq = sum(p) / max(p); SNR = sum(p) / (noise * sqrt(weq)) / fudge,
    with the reference's empirical fudge factor (pplib.py:2376-2395).
    """
    profile = jnp.asarray(profile)
    # exact_median_lastaxis == jnp.median bit-for-bit; it exists because
    # this median sat on the streaming raw bucket's critical path as the
    # single most expensive stage (the XLA sort), per the stage
    # attribution in benchmarks/attrib.py
    p = profile - exact_median_lastaxis(profile)[..., None]
    if noise_std is None:
        noise_std = get_noise_PS(profile)
    peak = jnp.max(jnp.abs(p), axis=-1)
    peak = jnp.maximum(peak, jnp.finfo(p.dtype).tiny)
    weq = jnp.abs(jnp.sum(p, axis=-1)) / peak
    weq = jnp.maximum(weq, 1.0)
    return jnp.abs(jnp.sum(p, axis=-1)) / (noise_std * jnp.sqrt(weq)) / fudge


def get_scales(dFT, mFT, errs_F, harm_weights=None):
    """Analytic maximum-likelihood per-channel amplitudes
    a_n = C_n / S_n (eq. 11 of Pennucci+ 2014).

    dFT, mFT: (..., nchan, nharm) rFFTs of aligned data and model;
    errs_F: (..., nchan) Fourier-domain noise.  Parity: reference
    pplib.py:2398-2424 and pptoaslib.py:953-971.
    """
    if harm_weights is None:
        harm_weights = jnp.ones(dFT.shape[-1], dtype=errs_F.dtype)
    w = harm_weights / errs_F[..., None] ** 2.0
    S = jnp.sum(jnp.abs(mFT) ** 2.0 * w, axis=-1)
    C = jnp.sum((dFT * jnp.conj(mFT)).real * w, axis=-1)
    return C / jnp.maximum(S, jnp.finfo(S.dtype).tiny)
