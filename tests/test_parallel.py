"""Sharded execution: results on a multi-device mesh must match the
single-device batch fit exactly (it is the same program, partitioned)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import fit_portrait_batch
from pulseportraiture_tpu.ops import guess_fit_freq
from pulseportraiture_tpu.parallel import fit_portrait_sharded, make_mesh
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NCHAN, NBIN, NB = 32, 512, 8
FREQS = jnp.asarray(np.linspace(1300.0, 1899.0, NCHAN))


@pytest.fixture(scope="module")
def batch():
    model = default_test_model(1500.0)
    keys = jax.random.split(jax.random.PRNGKey(0), NB)
    ds = [
        fake_portrait(k, model, FREQS, NBIN, P, phi=0.005 * i, DM=0.0004 * i,
                      noise_std=0.05)
        for i, k in enumerate(keys)
    ]
    return (
        jnp.stack([d.port for d in ds]),
        jnp.stack([d.model_port for d in ds]),
        jnp.stack([d.noise_stds for d in ds]),
    )


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def _check(res_sharded, res_ref):
    np.testing.assert_allclose(
        np.asarray(res_sharded.phi), np.asarray(res_ref.phi), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(res_sharded.DM), np.asarray(res_ref.DM), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(res_sharded.snr), np.asarray(res_ref.snr), rtol=1e-9
    )


def test_data_parallel_matches_batch(batch):
    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    mesh = make_mesh(n_data=8, n_chan=1)
    res = fit_portrait_sharded(mesh, ports, models, stds, FREQS, P, nu_fit)
    _check(res, ref)


def test_data_x_chan_mesh_matches_batch(batch):
    """2-D mesh: batch over 'data', channels over 'chan' (psum path)."""
    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    mesh = make_mesh(n_data=4, n_chan=2)
    res = fit_portrait_sharded(
        mesh, ports, models, stds, FREQS, P, nu_fit, shard_channels=True
    )
    _check(res, ref)


@pytest.mark.slow  # ~11 s two-mesh sharded parity (tier-1 budget,
# r19): test_sharded_fast_scatter_matches_batch keeps the sharded
# fast lane's parity gate in tier-1
def test_sharded_fast_matches_batch(batch):
    """The complex-free sharded core (the real-TPU-pod path) matches
    the batch reference on both mesh shapes, incl. a shared template."""
    from pulseportraiture_tpu.parallel import fit_portrait_sharded_fast

    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    res = fit_portrait_sharded_fast(
        make_mesh(n_data=8, n_chan=1), ports, models, stds, FREQS, P,
        nu_fit)
    _check(res, ref)
    res2 = fit_portrait_sharded_fast(
        make_mesh(n_data=4, n_chan=2), ports, models, stds, FREQS, P,
        nu_fit, shard_channels=True)
    _check(res2, ref)
    # shared 2-D template path (fake_portrait's model_port is the same
    # clean template for every element, so ref is the right oracle)
    res3 = fit_portrait_sharded_fast(
        make_mesh(n_data=8, n_chan=1), ports, models[0], stds, FREQS, P,
        nu_fit)
    _check(res3, ref)
    # a fixed nonzero tau seed now routes to the sharded complex-free
    # scattering lane (round 3) instead of raising
    seeded = jnp.zeros((NB, 5)).at[:, 3].set(1e-4)
    r4 = fit_portrait_sharded_fast(
        make_mesh(n_data=8, n_chan=1), ports, models, stds, FREQS, P,
        nu_fit, theta0=seeded)
    assert np.all(np.isfinite(np.asarray(r4.phi)))


class TestMultihost:
    """Multi-host helpers on the single-process path (true multi-host
    needs real hosts; the campaign sharding logic and global-mesh
    construction are what can and must be exercised here)."""

    def test_init_is_noop_without_config(self, monkeypatch):
        import jax

        from pulseportraiture_tpu import parallel

        # isolate from the CI host: SLURM/OMPI/TPU env families would
        # make bare initialize() auto-detect a cluster and block
        def no_cluster():
            raise ValueError("coordinator_address should be defined.")

        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: no_cluster())
        assert parallel.init_multihost() is False

    def test_init_raises_on_detected_cluster_failure(self, monkeypatch):
        import jax

        import pytest

        from pulseportraiture_tpu import parallel
        from pulseportraiture_tpu.parallel import multihost

        def broken(*a, **k):
            raise RuntimeError("coordinator unreachable: host0:1234")

        # a cluster IS detected but its bootstrap fails: must surface
        monkeypatch.setattr(multihost, "_cluster_env_detected",
                            lambda: True)
        monkeypatch.setattr(jax.distributed, "initialize", broken)
        with pytest.raises(RuntimeError, match="unreachable"):
            parallel.init_multihost()

    def test_init_fallback_when_detection_unavailable(self, monkeypatch):
        """Private-API drift (detection returns None): the no-cluster
        ValueError fallback still returns False; anything else raises."""
        import jax

        import pytest

        from pulseportraiture_tpu import parallel
        from pulseportraiture_tpu.parallel import multihost

        monkeypatch.setattr(multihost, "_cluster_env_detected",
                            lambda: None)
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda *a, **k: (_ for _ in ()).throw(
                ValueError("coordinator_address should be defined.")))
        assert parallel.init_multihost() is False
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda *a, **k: (_ for _ in ()).throw(
                ValueError("some genuinely different failure")))
        with pytest.raises(ValueError, match="different"):
            parallel.init_multihost()

    def test_shard_files_round_robin(self):
        from pulseportraiture_tpu import parallel

        files = [f"a{i}.fits" for i in range(10)]
        parts = [parallel.shard_files(files, index=i, count=3)
                 for i in range(3)]
        # disjoint, complete, round-robin balanced
        assert sorted(sum(parts, [])) == sorted(files)
        assert [len(p) for p in parts] == [4, 3, 3]
        assert parts[0] == ["a0.fits", "a3.fits", "a6.fits", "a9.fits"]
        # defaults: single process owns everything
        assert parallel.shard_files(files) == files
        assert parallel.process_count() == 1
        assert parallel.process_index() == 0

    def test_global_mesh_and_allgather(self):
        from pulseportraiture_tpu import parallel

        mesh = parallel.global_mesh(n_chan=2)
        assert mesh.axis_names == ("data", "chan")
        assert mesh.devices.shape == (4, 2)  # 8 virtual devices
        g = parallel.process_allgather(np.arange(3.0))
        assert len(g) == 1 and g[0].shape == (3,)

    def test_sharded_campaign_partition_runs(self, tmp_path):
        """Each 'host' slice of a campaign streams independently and
        the concatenated results equal the single-process run."""
        from pulseportraiture_tpu import parallel
        from pulseportraiture_tpu.io import write_gmodel
        from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
        from pulseportraiture_tpu.synth import (default_test_model,
                                                make_fake_pulsar)
        from pulseportraiture_tpu.utils.mjd import MJD

        model = default_test_model(1500.0)
        gmodel = str(tmp_path / "m.gmodel")
        write_gmodel(model, gmodel, quiet=True)
        files = []
        for i in range(4):
            p = str(tmp_path / f"c{i}.fits")
            make_fake_pulsar(model, {"PSR": "F", "P0": 0.003, "DM": 10.0,
                                     "PEPOCH": 55000.0},
                             outfile=p, nsub=2, nchan=16, nbin=128,
                             dDM=1e-4 * i, start_MJD=MJD(55100 + i, 0.1),
                             noise_stds=0.05, dedispersed=False,
                             quiet=True, rng=i)
            files.append(p)
        whole = stream_wideband_TOAs(files, gmodel, nsub_batch=4,
                                     quiet=True)
        parts = []
        for i in range(2):
            mine = parallel.shard_files(files, index=i, count=2)
            parts.append(stream_wideband_TOAs(mine, gmodel, nsub_batch=4,
                                              quiet=True))
        got = {(t.archive, t.flags["subint"]): t.MJD
               for r in parts for t in r.TOA_list}
        want = {(t.archive, t.flags["subint"]): t.MJD
                for t in whole.TOA_list}
        assert got.keys() == want.keys()
        for k in want:
            assert abs((got[k] - want[k]) * 86400.0) < 1e-12

    def test_jax_no_cluster_error_contract(self):
        """Pins the jax no-cluster error message that init_multihost's
        single-process fallback matches on — a jax rewording must fail
        HERE, not silently crash laptops in production.  Runs in a
        fresh subprocess: in-suite the backend is already initialized
        and jax raises a different (RuntimeError) guard first."""
        import os
        import subprocess
        import sys

        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("SLURM", "OMPI_", "TPU_",
                                    "JAX_COORD", "CLOUD_TPU"))}
        env["JAX_PLATFORMS"] = "cpu"
        code = (
            "import jax\n"
            "try:\n"
            "    jax.distributed.initialize()\n"
            "except ValueError as e:\n"
            "    assert 'coordinator_address' in str(e), str(e)\n"
            "    print('CONTRACT-OK')\n"
            "else:\n"
            "    print('CLUSTER-DETECTED')\n"
        )
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=120)
        assert ("CONTRACT-OK" in out.stdout
                or "CLUSTER-DETECTED" in out.stdout), (out.stdout,
                                                       out.stderr)


def test_sharded_align_iteration(batch):
    """One sharded ppalign iteration: the fused fit + rotate + psum
    template update recovers the clean template from phase/DM-scattered
    subints, on both mesh shapes."""
    from pulseportraiture_tpu.parallel import align_iteration_sharded

    ports, models, stds = batch
    clean = np.asarray(models[0])
    masks = jnp.ones((NB, NCHAN))
    for mesh, shard_ch in ((make_mesh(n_data=8, n_chan=1), False),
                           (make_mesh(n_data=4, n_chan=2), True)):
        new_t, res = align_iteration_sharded(
            mesh, ports, models[0], stds, masks, FREQS, P,
            shard_channels=shard_ch)
        new_t = np.asarray(new_t)
        assert new_t.shape == (NCHAN, NBIN)
        assert np.all(np.isfinite(new_t))
        # each subint was injected with a different (phi, DM); after
        # back-rotation by the fits the stack must align with the clean
        # template to ~noise/sqrt(NB) while the UNALIGNED mean does not
        scale = np.abs(clean).max()
        err_aligned = np.abs(new_t - clean).max() / scale
        err_unaligned = np.abs(
            np.asarray(ports.mean(axis=0)) - clean).max() / scale
        assert err_aligned < 0.05, err_aligned
        assert err_unaligned > 5 * err_aligned
        assert np.asarray(res.phi).shape == (NB,)


def test_sharded_fast_scatter_matches_batch(key=None):
    """Scattering fits through the sharded complex-free lane match the
    complex engine on the 4x2 mesh (psum over 'chan' + the _cgh_scatter
    Newton loop in one sharded program)."""
    import jax

    from pulseportraiture_tpu.parallel import fit_portrait_sharded_fast
    from pulseportraiture_tpu.fit import FitFlags
    from pulseportraiture_tpu.synth import default_test_model, fake_portrait

    model = default_test_model(1500.0)
    nb = 4
    keys = jax.random.split(jax.random.PRNGKey(3), nb)
    ds = [fake_portrait(k, model, FREQS, NBIN, P, phi=0.01 * i,
                        DM=2e-4 * i, tau=1.5e-4, alpha=-4.0,
                        noise_std=0.02)
          for i, k in enumerate(keys)]
    ports = jnp.stack([d.port for d in ds])
    models = jnp.stack([d.model_port for d in ds])
    stds = jnp.stack([d.noise_stds for d in ds])
    th0 = np.zeros((nb, 5))
    th0[:, 3] = np.log10(0.5 / NBIN)
    th0[:, 4] = -4.0
    flags = FitFlags(True, True, False, True, False)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, 1500.0,
                             fit_flags=flags, theta0=jnp.asarray(th0),
                             log10_tau=True, max_iter=60)
    res = fit_portrait_sharded_fast(
        make_mesh(n_data=4, n_chan=2), ports, models, stds, FREQS, P,
        1500.0, fit_flags=flags, theta0=jnp.asarray(th0),
        log10_tau=True, max_iter=60, shard_channels=True)
    np.testing.assert_allclose(np.asarray(res.phi), np.asarray(ref.phi),
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(res.tau), np.asarray(ref.tau),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(res.tau_err),
                               np.asarray(ref.tau_err), rtol=1e-4)


def test_cluster_env_private_api_is_inspectable():
    """Canary for the private jax._src.clusters registry that
    _cluster_env_detected leans on (pinned against jax 0.9.x): its
    silent None fallback is sound, but an upgrade that moves the API
    must fail HERE visibly, not degrade cluster detection quietly."""
    from pulseportraiture_tpu.parallel import multihost

    assert multihost._cluster_env_detected() in (True, False)
