"""Fleet-batched wideband GLS timing: every pulsar's solution from a
handful of padded device dispatches (ISSUE 11 tentpole, layer 3).

The timing stage was the last per-pulsar-serial production stage in
the system: a PTA campaign ends with N_psr independent linear solves,
each milliseconds of math behind a full dispatch + transfer floor.
This module applies the R12 batched-LM playbook to timing:

* the LINEARIZATION stays on host (timing/gls.build_gls_system —
  exact rational spin-phase reduction per pulsar; f64 host work that
  no accelerator improves at these sizes);
* the SOLVES are bucketed by power-of-two (rows, params) class,
  zero-padded (extra rows and columns are exactly inert: zero rows
  add nothing to the normal equations, zero columns ride the
  pseudoinverse's null space out with zero value and zero error),
  the batch axis padded to its own power of two with all-zero
  systems, and each bucket solved in ONE jitted device dispatch;
* the device program mirrors timing/gls.gls_solve_np op-for-op
  (column-normalized normal equations through a pseudoinverse), so
  batched-vs-serial stays digit-comparable: the serial lane runs the
  SAME padded program one pulsar at a time (batched=False — the A/B
  arm benchmarks/bench_gls.py measures), and the host lane
  (device=False) is the NumPy oracle.

Telemetry: one ``timing_fit`` event per solve dispatch and a
``fleet_end`` rollup ride whatever tracer the caller threads through
(stream_ipta_campaign passes its campaign tracer, so archives → TOAs
→ timing solutions land in ONE trace; tools/pptrace.py renders the
"timing" section from exactly these events).
"""

import functools
import time

import numpy as np

from ..telemetry import log, resolve_tracer
from ..utils.bunch import DataBunch
from .gls import build_gls_system, finalize_gls, gls_solve_np
from .tim import TimTOA, read_tim

__all__ = ["TimingJob", "fleet_gls_fit", "toas_from_measurements",
           "resolve_gls_device"]


def _pow2(n):
    """Smallest power of two >= n (>= 1)."""
    p = 1
    while p < n:
        p <<= 1
    return p


def resolve_gls_device(device=None):
    """Tri-state resolution of the fleet solve lane, mirroring the
    align_device/gauss_device convention: None follows
    config.gls_device; 'auto' = device on TPU backends (where the
    per-pulsar dispatch floor dominates a millisecond solve);
    True/False force.  Loud on anything else."""
    from .. import config

    if device is None:
        device = getattr(config, "gls_device", "auto")
    from ..tune.capability import resolve_auto

    return resolve_auto("gls_device", device)


class TimingJob:
    """One pulsar's timing problem: TOAs + parfile (+ per-pulsar fit
    overrides forwarded to build_gls_system, e.g. fit_f1=True for the
    one pulsar with a measurable spindown)."""

    def __init__(self, pulsar, toas, par, **fit_kwargs):
        self.pulsar = str(pulsar)
        if isinstance(toas, str):
            toas = read_tim(toas)
        self.toas = list(toas)
        if isinstance(par, str):
            from ..io.psrfits import parse_parfile

            par = parse_parfile(par)
        self.par = par
        self.fit_kwargs = dict(fit_kwargs)


def toas_from_measurements(toa_list):
    """Adapt pipeline TOA objects (io/tim.TOA, as collected by
    GetTOAs / the streaming drivers) to the TimTOA records the timing
    fit consumes — the in-memory equivalent of writing and re-reading
    a .tim file, minus the formatting round-trip."""
    out = []
    for t in toa_list:
        out.append(TimTOA(
            archive=str(t.archive), frequency=float(t.frequency),
            mjd_int=int(t.MJD.day), mjd_frac=float(t.MJD.frac),
            error_us=float(t.TOA_error), site=str(t.telescope_code),
            dm=None if t.DM is None else float(t.DM),
            dm_err=None if t.DM_error is None else float(t.DM_error),
            flags=dict(t.flags)))
    return out


@functools.lru_cache(maxsize=None)
def _solve_program(nbatch, nrow, nparam):
    """Compiled batched GLS solve for one (B, m, p) bucket class.

    The math is gls_solve_np verbatim, vmapped by shape: column
    normalization, normal equations, batched pseudoinverse, whitened
    post-fit residuals.  f64 throughout — timing precision is the
    point, and the batch sizes are tiny by accelerator standards.
    Cached per shape class (pow2 bucketing keeps the class count
    logarithmic in fleet diversity)."""
    import jax
    import jax.numpy as jnp

    def solve(A, r):
        col = jnp.sqrt(jnp.sum(A * A, axis=-2))
        col = jnp.where(col > 0, col, 1.0)
        An = A / col[..., None, :]
        G = jnp.swapaxes(An, -1, -2) @ An
        N = jnp.linalg.pinv(G)
        Atr = jnp.einsum("...ji,...j->...i", An, r)
        xn = jnp.einsum("...ij,...j->...i", N, Atr)
        x = xn / col
        perr = jnp.sqrt(jnp.maximum(
            jnp.diagonal(N, axis1=-2, axis2=-1), 0.0)) / col
        post = r - jnp.einsum("...ij,...j->...i", An, xn)
        chi2 = jnp.sum(post * post, axis=-1)
        return x, perr, post, chi2

    return jax.jit(solve)


def _solve_bucket(systems, nrow, nparam, batched, tracer, key):
    """Solve a list of (index, system) pairs in one padded dispatch
    (batched=True) or one B=1 dispatch per system (the serial A/B
    arm).  Returns {index: (x, perr, post, chi2)}."""
    out = {}
    groups = [systems] if batched else [[s] for s in systems]
    for group in groups:
        B = _pow2(len(group)) if batched else 1
        A = np.zeros((B, nrow, nparam))
        r = np.zeros((B, nrow))
        for b, (_, s) in enumerate(group):
            m, p = s.A.shape
            A[b, :m, :p] = s.A
            r[b, :m] = s.r
        t0 = time.perf_counter()
        fn = _solve_program(B, nrow, nparam)
        x, perr, post, chi2 = (np.asarray(v) for v in fn(A, r))
        wall = time.perf_counter() - t0
        if tracer.enabled:
            tracer.emit("timing_fit", bucket=key, rows=len(group),
                        pad=B - len(group), wall_s=round(wall, 6),
                        batched=bool(batched))
        for b, (idx, s) in enumerate(group):
            m, p = s.A.shape
            out[idx] = (x[b, :p], perr[b, :p], post[b, :m],
                        float(chi2[b]))
    return out


def fleet_gls_fit(jobs, fit_f0=True, fit_f1=False, fit_binary=True,
                  epoch_gap_days=0.5, allow_wraps=False, device=None,
                  batched=True, telemetry=None, quiet=True):
    """Wideband GLS timing solutions for a whole pulsar fleet.

    jobs: sequence of TimingJob (or (pulsar, toas, par) tuples; toas
    may be a .tim path, par a parfile path).  Fit options are
    campaign-wide defaults; per-job fit_kwargs override.

    device: None follows config.gls_device ('auto' = TPU); False =
    host-NumPy per-pulsar solves (the oracle lane); True = bucketed
    device dispatches.  batched: True packs each power-of-two
    (rows, params) bucket into one dispatch; False runs the SAME
    padded program per pulsar — the serial arm of bench_gls.py's A/B
    (only meaningful with the device lane).

    telemetry: tracer or path (resolve_tracer semantics); emits one
    ``timing_fit`` per solve dispatch and a ``fleet_end`` rollup.

    Returns DataBunch(pulsars, results={pulsar: WidebandGLSResult},
    n_dispatches, wall_s, device, batched).  A pulsar whose parfile or
    TOAs are invalid raises the underlying loud error naming it —
    a fleet with a broken member should fail visibly, not drop it.
    """
    jobs = [j if isinstance(j, TimingJob) else TimingJob(*j)
            for j in jobs]
    names = [j.pulsar for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate pulsar names in jobs: {names}")
    use_device = resolve_gls_device(device)
    tracer, own_tracer = resolve_tracer(telemetry, run="fleet_gls_fit")
    t0 = time.time()
    try:
        systems = []
        for j in jobs:
            kw = dict(fit_f0=fit_f0, fit_f1=fit_f1,
                      fit_binary=fit_binary,
                      epoch_gap_days=epoch_gap_days,
                      allow_wraps=allow_wraps)
            kw.update(j.fit_kwargs)
            try:
                systems.append(build_gls_system(j.toas, j.par, **kw))
            except Exception as e:
                raise type(e)(f"fleet_gls_fit: pulsar {j.pulsar!r}: "
                              f"{e}") from e

        solved = {}
        n_dispatches = 0
        if not use_device:
            for i, s in enumerate(systems):
                t1 = time.perf_counter()
                x, perr, _, post, chi2 = gls_solve_np(s.A, s.r)
                solved[i] = (x, perr, post, chi2)
                if tracer.enabled:
                    m, p = s.A.shape
                    tracer.emit(
                        "timing_fit", bucket=f"host:{m}x{p}", rows=1,
                        pad=0,
                        wall_s=round(time.perf_counter() - t1, 6),
                        batched=False)
                n_dispatches += 1
        else:
            buckets = {}
            for i, s in enumerate(systems):
                m, p = s.A.shape
                buckets.setdefault((_pow2(m), _pow2(p)),
                                   []).append((i, s))
            for (mm, pp), group in sorted(buckets.items()):
                key = f"{mm}x{pp}"
                solved.update(_solve_bucket(group, mm, pp, batched,
                                            tracer, key))
                n_dispatches += 1 if batched else len(group)

        results = {}
        for i, (j, s) in enumerate(zip(jobs, systems)):
            x, perr, post, chi2 = solved[i]
            results[j.pulsar] = finalize_gls(s, x, perr, post, chi2)
        wall = time.time() - t0
        tracer.emit("fleet_end", n_pulsars=len(jobs),
                    n_dispatches=n_dispatches, wall_s=round(wall, 6))
        log(f"fleet GLS: {len(jobs)} pulsar(s) solved in "
            f"{n_dispatches} dispatch(es) "
            f"({'device' if use_device else 'host'}"
            f"{', batched' if use_device and batched else ''}) in "
            f"{wall:.3f} s", quiet=quiet, tracer=tracer)
    finally:
        if own_tracer:
            tracer.close()
    return DataBunch(pulsars=names, results=results,
                     n_dispatches=n_dispatches, wall_s=wall,
                     device=use_device, batched=bool(batched))
