"""PCA + B-spline template building (ppspline equivalent).

Parity target: reference ppspline.DataPortrait.make_spline_model
(ppspline.py:39-217): S/N-weighted mean profile, weighted PCA,
significant-eigenvector selection with auto-tuned wavelet smoothing,
parametric B-spline fit of the projected shape curve vs frequency,
model regeneration, and pickle/npz persistence.

The PCA/eigh, wavelet grid-search, and model evaluation run as batched
JAX ops (models/spline.py, models/wavelet.py); only scipy's splprep
stays on host (offline model building, SURVEY §7.2 step 6).
"""

import numpy as np

from ..utils.device import on_host
from ..io.splmodel import SplineModel, write_spline_model
from ..models.spline import (
    fit_spline_curve,
    find_significant_eigvec,
    gen_spline_portrait,
    pca,
    reconstruct_portrait,
)
from ..models.wavelet import smart_smooth
from .portrait import DataPortrait as _BasePortrait


def snr_weighted_mean(dp):
    """The S/N-weighted mean profile of a portrait — the quantity
    make_spline_model averages and the template factory's spline lane
    Gaussian-smooths.  ONE definition: if the weighting ever changes,
    the injected smooth_mean_prof must keep smoothing the same profile
    make_spline_model subtracts."""
    SNRsx = np.asarray(dp.SNRsxs[0], float)
    w = SNRsx / SNRsx.sum()
    # the trailing normalization is ~1.0 by construction; kept so this
    # helper is bit-identical to the historical inline computation
    return (dp.portx * w[:, None]).sum(axis=0) / w.sum()


class SplinePortrait(_BasePortrait):
    """DataPortrait specialized with make_spline_model / write_model
    (the reference shadows the base class name; here the subclass is
    distinct, with `DataPortrait` kept as an alias in ppspline-style
    scripts via pipeline.spline.DataPortrait)."""

    @on_host
    def make_spline_model(self, max_ncomp=10, smooth=True,
                          snr_cutoff=150.0, rchi2_tol=0.1, k=3, sfac=1.0,
                          max_nbreak=None, model_name=None,
                          smooth_mean_prof=None, quiet=False,
                          **kwargs):
        """Build the PCA+spline model; same options/semantics as the
        reference (ppspline.py:39-217).

        smooth_mean_prof: an externally smoothed mean profile (same
        nbin) used INSTEAD of the wavelet smart_smooth of the mean when
        smooth=True — the template factory (pipeline/factory.py)
        injects the fleet's batched Gaussian-fit of the S/N-weighted
        mean here, so spline jobs ride the shared batched LM lane.
        Eigenprofile smoothing is unaffected (eigenvectors have
        negative lobes the sign-constrained Gaussian basis cannot
        represent)."""
        port = self.portx
        SNRsx = np.asarray(self.SNRsxs[0], float)
        noise_x = np.asarray(self.noise_stdsxs[0], float)
        pca_weights = SNRsx / SNRsx.sum()
        mean_prof = snr_weighted_mean(self)
        freqs = np.asarray(self.freqsxs[0], float)
        nbin = port.shape[1]
        if nbin % 2 != 0:
            if not quiet:
                print(f"nbin = {nbin} is odd; cannot wavelet_smooth.")
            smooth = False

        eigval, eigvec = pca(port, mean_prof, pca_weights)
        eigval = np.asarray(eigval)
        eigvec = np.asarray(eigvec)
        return_max = 10 if max_ncomp is None else min(max_ncomp, 10)
        ieig, smooth_eigvec = find_significant_eigvec(
            eigvec, check_max=10, return_max=return_max,
            snr_cutoff=snr_cutoff, return_smooth=True,
            rchi2_tol=rchi2_tol, **kwargs)
        if not smooth:
            smooth_eigvec = eigvec.copy()
        ncomp = len(ieig)
        if smooth:
            if smooth_mean_prof is not None:
                smooth_mean_prof = np.asarray(smooth_mean_prof, float)
                if smooth_mean_prof.shape != mean_prof.shape:
                    raise ValueError(
                        f"smooth_mean_prof shape "
                        f"{smooth_mean_prof.shape} != mean profile "
                        f"shape {mean_prof.shape}")
            else:
                smooth_mean_prof = np.asarray(smart_smooth(
                    mean_prof, rchi2_tol=rchi2_tol))
            if not smooth_mean_prof.any():
                # smart_smooth zeroes a profile when no (nlevel, fact)
                # passes the red-chi2 gate — right for noise
                # eigenvectors, wrong for the mean profile; keep the
                # raw mean instead of a zero model
                smooth_mean_prof = mean_prof
            self.smooth_mean_prof = smooth_mean_prof
            self.smooth_eigvec = smooth_eigvec
        used_mean = smooth_mean_prof if smooth else mean_prof
        used_eigvec = smooth_eigvec[:, ieig] if ncomp else \
            np.zeros((nbin, 0))

        if ncomp == 0:
            proj_port = port[:, :0]
            tck = (np.array([freqs.min(), freqs.max()]),
                   np.zeros((0, 2)), 1)
            modelx = np.tile(used_mean, (len(freqs), 1))
            model = np.tile(used_mean, (len(self.freqs[0]), 1))
            reconst_port = modelx
        else:
            delta_port = port - mean_prof
            proj_port = delta_port @ used_eigvec
            reconst_port = np.asarray(reconstruct_portrait(
                port, mean_prof, used_eigvec))
            tck = fit_spline_curve(proj_port, freqs, flux_errs=noise_x,
                                   snrs=SNRsx, sfac=sfac,
                                   max_nbreak=max_nbreak, k=k)
            modelx = np.asarray(gen_spline_portrait(
                used_mean, freqs, used_eigvec, tck))
            model = np.asarray(gen_spline_portrait(
                used_mean, self.freqs[0], used_eigvec, tck))

        self.ieig = ieig
        self.ncomp = ncomp
        self.eigvec = eigvec
        self.eigval = eigval
        self.mean_prof = mean_prof
        self.proj_port = proj_port
        self.reconst_port = reconst_port
        self.tck = tck
        self.model_name = model_name or (str(self.datafile) + ".spl")
        self.model = model
        self.modelx = modelx
        self.spline_model = SplineModel(
            modelname=self.model_name, source=self.source,
            datafile=str(self.datafile), mean_prof=used_mean,
            eigvec=used_eigvec, tck=tck)
        if not quiet:
            nbreak = len(np.unique(np.asarray(tck[0])))
            print(f"B-spline interpolation model {self.model_name} uses "
                  f"{ncomp} basis profile components and {nbreak} "
                  f"breakpoints (degree k={tck[2]}).")
        return self.spline_model

    def write_model(self, outfile=None, quiet=False):
        """Persist the spline model (.spl pickle or .npz; reference
        ppspline.py:219-244)."""
        if not hasattr(self, "spline_model"):
            raise RuntimeError("call make_spline_model first")
        outfile = outfile or self.model_name
        write_spline_model(self.spline_model, outfile, quiet=quiet)
        return outfile

    # plotting wrappers (ppspline.py:246-288)
    def show_eigenprofiles(self, **kwargs):
        from ..viz.plots import show_eigenprofiles

        ncomp = getattr(self, "ncomp", 0)
        show_eigenprofiles(
            self.eigvec[:, self.ieig] if ncomp else np.zeros((self.nbin, 0)),
            smooth_eigvec=(self.smooth_eigvec[:, self.ieig]
                           if hasattr(self, "smooth_eigvec") and ncomp
                           else None),
            mean_prof=self.mean_prof,
            smooth_mean_prof=getattr(self, "smooth_mean_prof", None),
            **kwargs)

    def show_spline_curve_projections(self, **kwargs):
        from ..viz.plots import show_spline_curve_projections

        snrs = np.asarray(self.SNRsxs[0], float)
        kwargs.setdefault("weights", snrs / snrs.sum())
        show_spline_curve_projections(self.proj_port, self.freqsxs[0],
                                      tck=self.tck, **kwargs)


# reference ppspline scripts use the name DataPortrait
DataPortrait = SplinePortrait
