"""Cross-host campaign router (ISSUE 10): the ToaRouter must demux
per-request .tim output byte-identical to the single-host one-shot
driver over BOTH transports (in-process and socket), shard requests
across hosts with load-aware placement, keep same-template traffic
sticky under light load, retry retryable backpressure with the capped
budget, and record the route ledger pptrace's router section reads."""

import io
import json
import os
import threading

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.serve import (InProcTransport,
                                        RemoteRequestError, ServeRejected,
                                        SocketTransport, ToaRouter,
                                        ToaServer, TransportError,
                                        TransportServer)
from pulseportraiture_tpu.serve.transport import (decode_result,
                                                  encode_result)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    """A mixed-shape multi-pulsar-ish corpus: 4 archives, two of them
    at a different channel count so the fleet serves >1 bucket
    shape."""
    root = tmp_path_factory.mktemp("router")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(4):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2,
                         nchan=16 if i < 2 else 12, nbin=128,
                         nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55100 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=200 + i)
        files.append(path)
    return files, gmodel


def _oneshot_tims(files, gmodel, outdir, slices):
    refs = []
    for i, sl in enumerate(slices):
        tim = os.path.join(str(outdir), f"ref{i}.tim")
        stream_wideband_TOAs(sl, gmodel, nsub_batch=8,
                             tim_out=tim, quiet=True)
        refs.append(open(tim, "rb").read())
    return refs


def test_router_two_hosts_tim_identical_and_balanced(campaign,
                                                     tmp_path):
    """The acceptance core, in-process transport: two warm hosts, two
    mixed-shape requests; the router spreads them (affinity yields to
    balance), and each demuxed .tim is byte-identical to the one-shot
    driver regardless of which host served it."""
    files, gmodel = campaign
    slices = [files[:2], files[2:]]
    refs = _oneshot_tims(files, gmodel, tmp_path, slices)

    trace = str(tmp_path / "route.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="hostA"),
                            InProcTransport(h1, label="hostB")],
                           telemetry=trace)
        tims = [str(tmp_path / f"routed{i}.tim") for i in range(2)]
        handles = [router.submit(sl, gmodel, tim_out=tims[i],
                                 name=f"R{i}")
                   for i, sl in enumerate(slices)]
        results = [h.result(300) for h in handles]
        stats = router.stats()
        router.close()

    for i, ref in enumerate(refs):
        assert open(tims[i], "rb").read() == ref, f"request {i}"
    assert all(len(r.TOA_list) == 4 for r in results)
    # both hosts took exactly one request (equal template: affinity
    # must yield — placing the second request on the first host would
    # leave it strictly more loaded than the idle one)
    assert sorted(st["n_requests"] for st in stats.values()) == [1, 1]
    assert all(st["outstanding"] == 0 for st in stats.values())

    manifest, events = telemetry.validate_trace(trace)
    subs = [e for e in events if e["type"] == "route_submit"]
    assert {e["host"] for e in subs} == {"hostA", "hostB"}
    done = [e for e in events if e["type"] == "route_done"]
    assert len(done) == 2 and all(e["error"] is None for e in done)
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_route_submit"] == 2
    assert summary["n_route_retry"] == 0
    assert summary["n_route_done"] == 2
    assert summary["router_imbalance"] == pytest.approx(1.0)
    assert sum(summary["router_host_counts"].values()) == 4


def test_router_socket_transport_end_to_end(campaign, tmp_path):
    """The same demux gate over the REAL wire: a ppserve-style
    listener on an ephemeral port, a SocketTransport in the fleet;
    .tim bytes and the decoded result survive the protocol, and a
    request-level failure arrives as RemoteRequestError naming the
    original exception type."""
    files, gmodel = campaign
    refs = _oneshot_tims(files, gmodel, tmp_path, [files[:2]])
    one = stream_wideband_TOAs(files[:2], gmodel, nsub_batch=8,
                               quiet=True)

    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as srv:
        with TransportServer(srv, port=0) as listener:
            transport = SocketTransport(f"127.0.0.1:{listener.port}")
            router = ToaRouter([transport])
            tim = str(tmp_path / "sock.tim")
            res = router.get_TOAs(files[:2], gmodel, timeout=300,
                                  tim_out=tim, name="S")
            assert open(tim, "rb").read() == refs[0]
            assert len(res.TOA_list) == len(one.TOA_list) == 4
            assert res.order == one.order
            assert np.allclose(res.DeltaDM_means, one.DeltaDM_means)
            for ta, tb in zip(one.TOA_list, res.TOA_list):
                assert (ta.MJD.day, ta.MJD.frac) == \
                    (tb.MJD.day, tb.MJD.frac)
                assert ta.flags == tb.flags
            # stat crosses the wire (the router's placement signal)
            st = transport.stat()
            assert st["pending_archives"] == 0 and st["n_live"] == 0
            # a bad option set fails ITS request with the original
            # exception type named, and the host keeps serving
            with pytest.raises(RemoteRequestError,
                               match="no_such_option") as ei:
                router.get_TOAs(files[:1], gmodel, timeout=300,
                                name="bad", no_such_option=True)
            assert ei.value.etype == "TypeError"
            again = router.get_TOAs(files[:1], gmodel, timeout=300,
                                    name="again")
            assert len(again.TOA_list) == 2
            # collect-once handle hygiene: collected requests were
            # EVICTED server-side (a long-lived fleet connection must
            # stay O(outstanding)), so drain sees nothing pending...
            assert transport.drain(30) == 0
            # ...while an uncollected submit is what drain waits on
            h = transport.submit([files[3]], gmodel, name="drainme")
            assert transport.drain(60) == 1
            assert len(transport.result(h, 60).TOA_list) == 2
            with pytest.raises(RemoteRequestError, match="unknown"):
                transport.result(h, 1)  # evicted after collection
            router.close()
    # a dead endpoint is a TransportError (the router's
    # host-unreachable signal), not a hang
    with pytest.raises(TransportError, match="cannot reach"):
        SocketTransport(f"127.0.0.1:{listener.port}")


def test_socket_protocol_violations_reply_loudly(campaign):
    """Unknown ops and unknown handles get error replies (connection
    survives); a garbage host spec is a loud ValueError."""
    files, gmodel = campaign
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as srv:
        with TransportServer(srv, port=0) as listener:
            t = SocketTransport(f"127.0.0.1:{listener.port}")
            reply = t._call({"op": "frobnicate"})
            assert not reply["ok"] and "unknown op" in reply["error"]
            with pytest.raises(RemoteRequestError, match="unknown"):
                t.result(999, timeout=1)
            assert t.stat()["queue_len"] == 0  # still alive
            t.close()
    for bad in ("nohost", "h:port", "h:99999", ":123"):
        with pytest.raises(ValueError):
            config.parse_hostport(bad)
    with pytest.raises(ValueError, match="no host endpoints"):
        ToaRouter([])


class _FlakyTransport:
    """Injected backpressure: rejects the first ``n_reject`` submits
    with ServeRejected(retryable=True), then delegates."""

    def __init__(self, inner, n_reject):
        self.inner = inner
        self.label = inner.label
        self.n_reject = n_reject

    def submit(self, *a, **kw):
        if self.n_reject > 0:
            self.n_reject -= 1
            raise ServeRejected("admission queue full (injected)",
                                retryable=True)
        return self.inner.submit(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_router_backpressure_retry_and_exhaustion(campaign, tmp_path):
    """Injected ServeRejected backpressure: the router retries on the
    next-least-loaded host, backs off between full fleet passes, and
    the demuxed .tim is STILL byte-identical; an all-rejecting fleet
    exhausts retry_max and raises the last rejection; a terminal
    rejection raises immediately without consuming the fleet."""
    files, gmodel = campaign
    refs = _oneshot_tims(files, gmodel, tmp_path, [files[:2]])
    trace = str(tmp_path / "retry.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        # BOTH hosts reject once: pass 1 fails entirely, the router
        # backs off, pass 2 places on the least-loaded host
        router = ToaRouter(
            [_FlakyTransport(InProcTransport(h0, label="f0"), 1),
             _FlakyTransport(InProcTransport(h1, label="f1"), 1)],
            retry_max=8, telemetry=trace)
        tim = str(tmp_path / "retried.tim")
        res = router.submit(files[:2], gmodel, tim_out=tim,
                            name="RT").result(300)
        assert len(res.TOA_list) == 4
        assert open(tim, "rb").read() == refs[0]
        router.close()
    _, events = telemetry.validate_trace(trace)
    retries = [e for e in events if e["type"] == "route_retry"]
    assert len(retries) == 2
    assert all(e["backoff_s"] > 0 for e in retries)
    subs = [e for e in events if e["type"] == "route_submit"]
    assert len(subs) == 1 and subs[0]["attempt"] == 3
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_route_retry"] == 2

    # exhaustion: every host always sheds -> the LAST rejection
    # surfaces after exactly retry_max placement attempts
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0:
        router = ToaRouter(
            [_FlakyTransport(InProcTransport(h0, label="x0"), 99)],
            retry_max=3)
        with pytest.raises(ServeRejected, match="injected"):
            router.submit(files[:1], gmodel, name="never")
        router.close()

        # terminal (retryable=False) rejection: raised immediately
        router = ToaRouter([InProcTransport(h0, label="t0")])
        h0.queue.max_pending = 1
        with pytest.raises(ServeRejected, match="split it"):
            router.submit(files[:2], gmodel, name="huge")
        h0.queue.max_pending = 64
        router.close()


def test_router_affinity_sticks_under_light_traffic(campaign,
                                                    tmp_path):
    """Light traffic (results collected between submits, loads drain
    to zero): same-template requests stay on ONE host, so their
    subints keep coalescing into that host's shared buckets instead
    of fragmenting across the fleet."""
    files, gmodel = campaign
    trace = str(tmp_path / "affinity.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=20, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=20, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="a0"),
                            InProcTransport(h1, label="a1")],
                           telemetry=trace)
        for i in range(3):
            router.get_TOAs(files[i:i + 1], gmodel, timeout=300,
                            name=f"L{i}")
        stats = router.stats()
        router.close()
    placed = [lbl for lbl, st in stats.items() if st["n_requests"]]
    assert placed == ["a0"], stats  # all three stuck to one host
    _, events = telemetry.validate_trace(trace)
    subs = [e for e in events if e["type"] == "route_submit"]
    assert [e["affinity"] for e in subs] == [False, True, True]


def test_router_ipta_campaign_thin_client(campaign, tmp_path):
    """stream_ipta_campaign(router=) shards per-pulsar requests over
    the fleet and reproduces the executor-per-pulsar path's .tim
    files and DeltaDM summaries; server=/router= exclusivity, resume,
    and executor-kwarg refusals are loud."""
    from pulseportraiture_tpu.pipeline import stream_ipta_campaign

    files, gmodel = campaign
    jobs = [("PSRA", files[:2], gmodel), ("PSRB", files[2:], gmodel)]
    out1, out2 = tmp_path / "solo", tmp_path / "routed"
    r1 = stream_ipta_campaign(jobs, outdir=str(out1), nsub_batch=8,
                              quiet=True)
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="i0"),
                            InProcTransport(h1, label="i1")])
        r2 = stream_ipta_campaign(jobs, outdir=str(out2), nsub_batch=8,
                                  quiet=True, router=router)
        with pytest.raises(ValueError, match="not both"):
            stream_ipta_campaign(jobs, outdir=str(out2), quiet=True,
                                 server=h0, router=router)
        with pytest.raises(ValueError, match="resume"):
            stream_ipta_campaign(jobs, outdir=str(out2), resume=True,
                                 quiet=True, router=router)
        with pytest.raises(ValueError, match="max_inflight"):
            stream_ipta_campaign(jobs, outdir=str(out2), quiet=True,
                                 router=router, max_inflight=8)
        stats = router.stats()
        router.close()
    # the two pulsars really sharded across the fleet
    assert sorted(st["n_requests"] for st in stats.values()) == [1, 1]
    for psr in ("PSRA", "PSRB"):
        assert ((out1 / f"{psr}.tim").read_bytes()
                == (out2 / f"{psr}.tim").read_bytes())
        m1, e1 = r1.DeltaDM_summary[psr]
        m2, e2 = r2.DeltaDM_summary[psr]
        assert np.array_equal(m1, m2) and np.array_equal(e1, e2)
    assert len(r1.TOA_list) == len(r2.TOA_list) == 8


def test_transport_codec_roundtrip():
    """The result codec preserves everything .tim formatting and the
    campaign rollups read: MJD (int, f64) exactness, inf frequency,
    the int/float/str flag trichotomy, numpy scalars narrowed."""
    from pulseportraiture_tpu.io.tim import TOA, toa_string
    from pulseportraiture_tpu.utils.bunch import DataBunch

    t = TOA("ep0.fits", np.inf, MJD(55432, 0.9876543210987654), 1.25,
            "GBT", "1", DM=3.139, DM_error=0.0123,
            flags={"subint": np.int64(3), "snr": np.float32(12.5),
                   "be": "GUPPI", "chi2": 1.0625})
    res = DataBunch(TOA_list=[t], order=["ep0.fits"], DM0s=[3.139],
                    DeltaDM_means=[1e-4], DeltaDM_errs=[2e-5],
                    tim_out=None, n_skipped=0)
    wire = json.dumps(encode_result(res))
    back = decode_result(json.loads(wire))
    t2 = back.TOA_list[0]
    assert toa_string(t2) == toa_string(t)
    assert (t2.MJD.day, t2.MJD.frac) == (t.MJD.day, t.MJD.frac)
    assert t2.frequency == np.inf
    assert isinstance(t2.flags["subint"], int)
    assert isinstance(t2.flags["snr"], float)
    assert t2.flags["be"] == "GUPPI"
    assert back.DeltaDM_means == [1e-4] and back.DM0s == [3.139]
    assert back.order == ["ep0.fits"] and back.n_skipped == 0


def test_router_env_hooks(monkeypatch):
    """PPT_ROUTER_HOSTS / PPT_ROUTER_RETRY_MAX / PPT_SERVE_LISTEN:
    registered in KNOWN_PPT_ENV, strict parses, loud errors."""
    old = (config.router_hosts, config.router_retry_max,
           config.serve_listen)
    try:
        for name in ("PPT_ROUTER_HOSTS", "PPT_ROUTER_RETRY_MAX",
                     "PPT_SERVE_LISTEN"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_ROUTER_HOSTS",
                           "nodeA:9090, nodeB:9091")
        monkeypatch.setenv("PPT_ROUTER_RETRY_MAX", "7")
        monkeypatch.setenv("PPT_SERVE_LISTEN", "0.0.0.0:9090")
        changed = config.env_overrides()
        for key in ("router_hosts", "router_retry_max",
                    "serve_listen"):
            assert key in changed
        assert config.router_hosts == ("nodeA:9090", "nodeB:9091")
        assert config.router_retry_max == 7
        assert config.serve_listen == "0.0.0.0:9090"
        monkeypatch.setenv("PPT_ROUTER_HOSTS", "off")
        monkeypatch.setenv("PPT_SERVE_LISTEN", "off")
        config.env_overrides()
        assert config.router_hosts == ()
        assert config.serve_listen is None
        monkeypatch.setenv("PPT_ROUTER_HOSTS", "nodeA")  # no port
        with pytest.raises(ValueError, match="PPT_ROUTER_HOSTS"):
            config.env_overrides()
        monkeypatch.setenv("PPT_ROUTER_HOSTS", "a:1,a:1")
        with pytest.raises(ValueError, match="duplicate"):
            config.env_overrides()
        monkeypatch.setenv("PPT_ROUTER_HOSTS", "a:1")
        monkeypatch.setenv("PPT_ROUTER_RETRY_MAX", "0")
        with pytest.raises(ValueError, match="PPT_ROUTER_RETRY_MAX"):
            config.env_overrides()
        monkeypatch.setenv("PPT_ROUTER_RETRY_MAX", "2")
        monkeypatch.setenv("PPT_SERVE_LISTEN", "nowhere")
        with pytest.raises(ValueError, match="PPT_SERVE_LISTEN"):
            config.env_overrides()
    finally:
        (config.router_hosts, config.router_retry_max,
         config.serve_listen) = old


def test_router_concurrent_clients(campaign, tmp_path):
    """Thread-safety: concurrent client threads submit through ONE
    router; every request resolves, loads return to zero, nothing is
    lost or double-collected."""
    files, gmodel = campaign
    results = {}
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="c0"),
                            InProcTransport(h1, label="c1")])

        def go(tag, fs):
            results[tag] = router.get_TOAs(fs, gmodel, timeout=300,
                                           name=tag)

        threads = [threading.Thread(target=go, args=(f"T{i}", [f]))
                   for i, f in enumerate(files)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = router.stats()
        router.close()
    assert len(results) == 4
    assert sum(len(r.TOA_list) for r in results.values()) == 8
    assert sum(st["n_requests"] for st in stats.values()) == 4
    assert all(st["outstanding"] == 0 for st in stats.values())


class _StatStubTransport:
    """A host that exists only as its stat() report: fixed pending
    load + measured TOAs/s + capability record — the unit surface for
    the backend-aware cost model (ISSUE 19) without paying real
    fits."""

    def __init__(self, label, pending, toas_per_s):
        self.label = label
        self.pending = pending
        self.toas_per_s = toas_per_s

    def stat(self):
        return {"pending_archives": self.pending, "queue_len": 0,
                "n_live": 0, "toas_per_s": self.toas_per_s,
                "capability": {"platform": "cpu",
                               "fingerprint": "stub:cpu:jax-0"}}

    def close(self):
        pass


def test_router_cost_model_heterogeneous_placement():
    """Backend-aware placement (ISSUE 19): equal archive loads on a
    fast (10 TOAs/s) and a slow (2 TOAs/s) host must rank the fast
    host first under the cost model (cost = load / relative speed),
    degrade to EXACT least-loaded order with cost_model=False or when
    nothing is measured, and surface each host's measured rate in
    stats()."""
    slow = _StatStubTransport("slow", pending=4, toas_per_s=2.0)
    fast = _StatStubTransport("fast", pending=4, toas_per_s=10.0)
    router = ToaRouter([slow, fast])  # slow listed first (index 0)
    try:
        ranked, _ = router._rank("m.gmodel", 1)
        assert [m.label for m in ranked] == ["fast", "slow"]
        loads = router.fleet.probe_all()
        costs, speeds = router._costs(loads)
        by_label = {m.label: costs[m] for m in costs}
        # slow runs at 2/10 relative speed -> 5x the cost per archive
        assert by_label["slow"] == pytest.approx(5 * by_label["fast"])
        st = router.stats()
        assert st["fast"]["toas_per_s"] == 10.0
        assert st["slow"]["toas_per_s"] == 2.0
    finally:
        router.close()

    # cost model OFF: raw least-loaded, ties broken by index
    router = ToaRouter([slow, fast], cost_model=False)
    try:
        ranked, _ = router._rank("m.gmodel", 1)
        assert [m.label for m in ranked] == ["slow", "fast"]
        costs, speeds = router._costs(router.fleet.probe_all())
        assert all(s == 1.0 for s in speeds.values())
        assert {c for c in costs.values()} == {4}
    finally:
        router.close()

    # unmeasured fleet (cold hosts / pre-cost-model peers): the cost
    # model degrades to exact least-loaded — speeds all 1.0
    cold_a = _StatStubTransport("a", pending=2, toas_per_s=None)
    cold_b = _StatStubTransport("b", pending=1, toas_per_s=None)
    router = ToaRouter([cold_a, cold_b])
    try:
        ranked, _ = router._rank("m.gmodel", 1)
        assert [m.label for m in ranked] == ["b", "a"]
        costs, speeds = router._costs(router.fleet.probe_all())
        assert all(s == 1.0 for s in speeds.values())
    finally:
        router.close()
