"""pproute — shard a campaign's TOA requests across a fleet of warm
``ppserve --listen`` hosts (ISSUE 10).

Reads the SAME JSONL request file as ``ppserve -r`` (one JSON object
per line: name, datafiles, modelfile, options), but instead of serving
locally it routes every request through a
:class:`~..serve.router.ToaRouter` over ``--hosts`` (or
PPT_ROUTER_HOSTS): least-pending-archives placement with sticky
per-template affinity, retryable-backpressure retries with capped
exponential backoff, and per-request ``.tim`` files written by
whichever host served the request — byte-identical to the single-host
one-shot driver.

Fleet assumptions: archive paths are visible on every host, and each
endpoint is a running ``ppserve --listen``.  With the default
shared-filesystem lane ``--outdir`` must be host-visible too (the
serving host writes each ``.tim``); with ``--no-shared-fs`` the full
TOA payload returns over the wire and THIS process writes the
``.tim`` (byte-identical, serve/codec.py).

Elastic-fleet controls (ISSUE 13): ``--fleet-file`` watches a
host-list file for joins/leaves, ``--probe-ms`` bounds liveness
probes, ``--hedge-ms`` enables tail-latency request hedging,
``--quality-refit`` routes one zap-and-refit of gate-tripping
archives to the least-loaded HEALTHY host, and a request line may
carry ``"tenant"`` for the per-host QoS lanes.  ``--telemetry``
records the route/fleet ledger; read it with ``tools/pptrace.py
report`` (the "router" and "fleet" sections: per-host shares, health
timeline, failover/hedge counts, per-tenant latency split).
"""

import argparse
import json
import os
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="pproute", description=__doc__.splitlines()[0])
    p.add_argument("-r", "--requests", metavar="requests.jsonl",
                   required=True,
                   help="JSONL request file (ppserve's format: name, "
                        "datafiles, modelfile, options per line).")
    p.add_argument("-H", "--hosts", metavar="host:port[,host:port...]",
                   default=None,
                   help="Fleet endpoints, each a running 'ppserve "
                        "--listen'. [default: config.router_hosts / "
                        "PPT_ROUTER_HOSTS]")
    p.add_argument("--fleet-file", dest="fleet_file", metavar="FILE",
                   default=None,
                   help="WATCHED membership file (one host:port per "
                        "line, # comments): the router joins/leaves "
                        "hosts to match whenever the file changes — "
                        "edit it to grow or shrink the fleet mid-run. "
                        "Mutually exclusive with --hosts. Also via "
                        "PPT_ROUTER_FLEET_FILE. [default: "
                        "config.router_fleet_file]")
    p.add_argument("--probe-ms", dest="probe_ms", type=float,
                   default=None, metavar="MS",
                   help="Deadline on per-host stat liveness probes; a "
                        "probe past it feeds the host's SUSPECT "
                        "transition and placement uses the cached "
                        "load. [default: config.router_probe_ms / "
                        "PPT_ROUTER_PROBE_MS]")
    p.add_argument("--hedge-ms", dest="hedge_ms", type=float,
                   default=None, metavar="MS",
                   help="Hedged requests: a request unresolved after "
                        "this long launches one duplicate on the "
                        "least-loaded other host; first completion "
                        "wins. [default: config.router_hedge_ms / "
                        "PPT_ROUTER_HEDGE_MS — off]")
    p.add_argument("--no-shared-fs", dest="no_shared_fs",
                   action="store_true", default=False,
                   help="Codec lane: hosts return the full TOA "
                        "payload over the wire and THIS process "
                        "writes each request's .tim (byte-identical "
                        "to the shared-fs lane) — for fleets without "
                        "a shared filesystem. [default: hosts write]")
    p.add_argument("--quality-refit", dest="quality_refit",
                   action="store_true", default=False,
                   help="Routed quality loop: a collected request "
                        "whose TOAs trip config.quality_max_gof gets "
                        "ONE zap-and-refit placed on the current "
                        "least-loaded HEALTHY host (enable here OR "
                        "server-side PPT_QUALITY_REFIT, not both). "
                        "[default: off]")
    p.add_argument("-O", "--outdir", metavar="DIR", default=".",
                   help="Directory for per-request <name>.tim outputs "
                        "(must be visible to every host). "
                        "[default: .]")
    p.add_argument("--retry-max", dest="retry_max", type=int,
                   default=None, metavar="N",
                   help="Total placement attempts per request before "
                        "the last retryable rejection is raised. "
                        "[default: config.router_retry_max / "
                        "PPT_ROUTER_RETRY_MAX]")
    p.add_argument("--timeout", type=float, default=None, metavar="S",
                   help="Per-request result timeout in seconds. "
                        "[default: none]")
    p.add_argument("--transport-compress", dest="transport_compress",
                   default=None, metavar="off|auto|on",
                   help="zlib-compress large socket frames to the "
                        "fleet (the no-shared-fs result payloads are "
                        "the big ones): 'off', 'auto' (size/saving "
                        "rule), 'on'.  Peers decode transparently; "
                        "payload content is byte-identical.  Also via "
                        "PPT_TRANSPORT_COMPRESS / "
                        "config.transport_compress. [default: off]")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Write the routing trace (route_submit/"
                        "route_retry/route_done) here; analyze with "
                        "tools/pptrace.py. Also via PPT_TELEMETRY. "
                        "[default: off]")
    p.add_argument("--monitor", dest="monitor", type=int,
                   default=None, metavar="PORT",
                   help="Expose the router's live fleet-wide "
                        "'metrics' op on 127.0.0.1:PORT while the "
                        "batch routes (port 0 = ephemeral, printed): "
                        "point 'ppmon 127.0.0.1:PORT' at it for the "
                        "live dashboard. [default: off]")
    from .ppserve import (add_cache_flags, add_obs_flags,
                          add_tune_flags)

    add_cache_flags(p)
    add_tune_flags(p)
    add_obs_flags(p)
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.retry_max is not None and args.retry_max < 1:
        raise SystemExit("--retry-max: must be >= 1, got "
                         f"{args.retry_max}")
    if args.probe_ms is not None and not args.probe_ms > 0:
        raise SystemExit("--probe-ms: must be > 0, got "
                         f"{args.probe_ms}")
    if args.hedge_ms is not None and args.hedge_ms < 0:
        raise SystemExit("--hedge-ms: must be >= 0, got "
                         f"{args.hedge_ms}")
    from .. import config

    if args.transport_compress is not None:
        table = {"off": False, "auto": "auto", "on": True}
        v = str(args.transport_compress).lower()
        if v not in table:
            raise SystemExit("pproute: --transport-compress expected "
                             "one of off/auto/on, got "
                             f"{args.transport_compress!r}")
        config.transport_compress = table[v]
    from .ppserve import (apply_cache_flags, apply_obs_flags,
                          apply_tune_flags)

    apply_cache_flags(args, "pproute")
    apply_tune_flags(args, "pproute")
    apply_obs_flags(args, "pproute")
    if args.monitor is not None and not 0 <= args.monitor <= 65535:
        raise SystemExit(f"--monitor: port out of range, got "
                         f"{args.monitor}")
    if args.hosts is not None and args.fleet_file is not None:
        raise SystemExit("pproute: --hosts and --fleet-file are "
                         "mutually exclusive (static list vs watched "
                         "membership)")
    fleet_file = args.fleet_file
    if fleet_file is None and args.hosts is None:
        fleet_file = config.router_fleet_file
    hosts = args.hosts
    if hosts is not None:
        hosts = [h.strip() for h in str(hosts).split(",") if h.strip()]
    elif fleet_file is None:
        hosts = list(config.router_hosts)
    else:
        hosts = []
        if not os.path.exists(fleet_file):
            raise SystemExit(
                f"pproute: --fleet-file not found: {fleet_file}")
    if not hosts and not fleet_file:
        raise SystemExit("pproute: no fleet endpoints — pass --hosts "
                         "host:port[,host:port...], --fleet-file, or "
                         "set PPT_ROUTER_HOSTS")
    for h in hosts:
        try:
            config.parse_hostport(h)
        except ValueError as e:
            raise SystemExit(f"pproute: --hosts: {e}")

    from .ppserve import parse_requests

    reqs = parse_requests(args.requests)
    # tim paths cross the wire and are resolved by the SERVING host —
    # the shared-filesystem assumption only holds for absolute paths
    # (a relative outdir would land in the remote ppserve's cwd)
    args.outdir = os.path.abspath(args.outdir)
    os.makedirs(args.outdir, exist_ok=True)

    from ..serve import ToaRouter, TransportError

    try:
        router = ToaRouter(hosts, retry_max=args.retry_max,
                           telemetry=args.telemetry, quiet=args.quiet,
                           probe_ms=args.probe_ms,
                           hedge_ms=args.hedge_ms,
                           write_tim=("router" if args.no_shared_fs
                                      else "host"),
                           quality_refit=args.quality_refit,
                           fleet_file=fleet_file)
    except TransportError as e:
        raise SystemExit(f"pproute: {e}")
    monitor = None
    if args.monitor is not None:
        # the TransportServer speaks the same framed ops over the
        # router as over a ToaServer — 'metrics' returns the
        # fleet-wide aggregation, which is exactly what ppmon polls
        from ..serve import TransportServer

        monitor = TransportServer(router, host="127.0.0.1",
                                  port=args.monitor,
                                  quiet=args.quiet).start()
        print(f"pproute: monitor endpoint on {monitor.label} "
              "(poll with ppmon)", flush=True)
    failures = 0
    t0 = time.time()
    with router:
        handles = []
        for rec in reqs:
            tim = os.path.join(args.outdir, f"{rec['name']}.tim")
            try:
                handles.append(router.submit(
                    rec["datafiles"], rec["modelfile"], tim_out=tim,
                    name=rec["name"], tenant=rec.get("tenant"),
                    **rec["options"]))
            except Exception as e:
                # a saturated/terminal fleet fails THIS request (the
                # documented rc=1 path), not the whole batch — the
                # already-placed requests must still be collected
                handles.append(None)
                failures += 1
                print(f"pproute: request {rec['name']!r} FAILED to "
                      f"place: {e}", file=sys.stderr)
        for rec, h in zip(reqs, handles):
            if h is None:
                continue
            try:
                res = h.result(args.timeout)
            except Exception as e:
                failures += 1
                print(f"pproute: request {rec['name']!r} FAILED on "
                      f"{h.host.label}: {e}", file=sys.stderr)
                continue
            if not args.quiet:
                print(f"pproute: {rec['name']}: "
                      f"{len(res.TOA_list)} TOAs from "
                      f"{len(res.order)} archive(s) on "
                      f"{h.host.label} -> {res.tim_out}")
        placed = router.stats()
    if monitor is not None:
        monitor.close()
    if not args.quiet:
        share = ", ".join(f"{lbl}: {st['n_archives']} archive(s)/"
                          f"{st['n_requests']} request(s)"
                          for lbl, st in placed.items())
        print(f"pproute: {len(reqs) - failures}/{len(reqs)} requests "
              f"across {len(hosts)} host(s) in {time.time() - t0:.2f} "
              f"s [{share}]")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
