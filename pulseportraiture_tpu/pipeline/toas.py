"""Wideband (and narrowband) TOA measurement pipeline.

Parity target: the reference's GetTOAs (pptoas.py:87-1476) — same
options, per-archive result attributes, TOA flags, and DeltaDM
statistics — re-architected TPU-first:

- the reference loops subints sequentially, re-parsing the model file
  and calling scipy trust-ncg per subint (pptoas.py:384-513); here ALL
  ok subints of an archive are stacked into one (nsub, nchan, nbin)
  batch and fitted by a single vmapped fused-Newton call
  (fit.portrait.fit_portrait_batch), with zero-weight channels masked,
  not compressed, so shapes stay static for XLA;
- subints with too few channels for the requested parameter set are
  fitted in separate, smaller flag groups (phase-only for 1 channel,
  no-GM for 2), mirroring the reference's degenerate-geometry
  fallbacks (pptoas.py:519-527);
- templates are built once per unique frequency layout and cached
  (pipeline/models.TemplateModel), fixing the reference's known
  per-subint regeneration inefficiency.
"""

import time

import jax.numpy as jnp
import numpy as np

from ..config import Dconst, scattering_alpha
from ..fit.portrait import (FitFlags, fit_portrait_batch,
                            fit_portrait_batch_fast, use_fast_fit_default)
from ..io.psrfits import load_data
from ..io.tim import TOA
from ..ops.scattering import scattering_portrait_FT, scattering_times
from ..telemetry import finite, log, resolve_tracer
from ..utils.device import on_host
from .models import TemplateModel

MAX_NFILE = 999  # parity: cfitsio open-file guard (pptoas.py:28-33)


def weighted_mean(values, errs):
    """Error-weighted mean and its uncertainty."""
    values = np.asarray(values, float)
    errs = np.asarray(errs, float)
    good = errs > 0
    w = np.where(good, 1.0 / np.where(good, errs, 1.0) ** 2, 0.0)
    wsum = w.sum()
    if wsum == 0.0:
        return float(np.mean(values)), np.inf
    mean = float((values * w).sum() / wsum)
    return mean, float(wsum ** -0.5)


def scat_time_flags(tau_rot, tau_err_rot, seconds_per_rot, log10_tau):
    """TOA flag dict for a fitted scattering timescale.

    tau_rot/tau_err_rot: FitResult.tau/tau_err (linear rotations always,
    whatever the fit's internal parameterization); seconds_per_rot:
    P or P/doppler_factor.  scat_time is microseconds."""
    flags = {"scat_time": tau_rot * seconds_per_rot * 1e6}
    if log10_tau:
        safe = max(tau_rot, 1e-300)
        flags["log10_scat_time"] = np.log10(safe * seconds_per_rot)
        flags["log10_scat_time_err"] = tau_err_rot / (safe * np.log(10.0))
    else:
        flags["scat_time_err"] = tau_err_rot * seconds_per_rot * 1e6
    return flags


def _validate_scat_guess(scat_guess, fit_scat):
    """Normalize/validate the scat_guess argument: a (tau_s, nu, alpha)
    triple, the literal 'auto', or None.  Anything else raises instead
    of being silently ignored."""
    if isinstance(scat_guess, str):
        s = scat_guess.strip().lower()
        if s != "auto":
            raise ValueError(
                f"scat_guess string must be 'auto', got {scat_guess!r}")
        if not fit_scat:
            raise ValueError("scat_guess='auto' requires fit_scat=True")
        return "auto"
    if scat_guess is not None and len(tuple(scat_guess)) != 3:
        raise ValueError(
            "scat_guess must be (tau_s, nu_MHz, alpha), 'auto', or None")
    return scat_guess


def scat_seed_tau0(scat_guess, fit_scat, nok, nbin, P_mean, nu_fit_arr,
                   default_alpha, ports=None, modelx=None, noise=None,
                   masks=None):
    """(tau0 array [rot], alpha0) seeding shared by GetTOAs and the
    streaming driver.  scat_guess: (tau_s, nu, alpha) triple, "auto"
    (data-driven estimate — requires ports/modelx/noise), or None
    (neutral half-bin when fit_scat, zeros otherwise)."""
    alpha0 = default_alpha
    if scat_guess is not None and not isinstance(scat_guess, str):
        t_s, nu_s, a_s = scat_guess
        tau0 = (t_s / P_mean) * (np.asarray(nu_fit_arr) / nu_s) ** a_s
        alpha0 = a_s
    elif fit_scat and scat_guess == "auto":
        from ..fit.portrait import estimate_tau_batch

        tau0 = np.asarray(estimate_tau_batch(
            jnp.asarray(ports, jnp.float32),
            jnp.asarray(modelx, jnp.float32),
            jnp.asarray(noise, jnp.float32),
            None if masks is None else jnp.asarray(masks, jnp.float32)))
    elif fit_scat:
        tau0 = np.full(nok, 0.5 / nbin)  # half a bin: neutral seed
    else:
        tau0 = np.zeros(nok)
    return tau0, alpha0


def effective_fit_flags(nchx_i, base):
    """Degenerate-geometry flag demotion (reference pptoas.py:519-527),
    the SINGLE source for both GetTOAs' flag groups and the streaming
    driver's bucket keys: one usable channel -> phase-only; two
    channels with GM requested -> drop GM."""
    if nchx_i <= 1:
        return (True, False, False, False, False)
    if nchx_i == 2 and base[2]:
        return (True, base[1], False, base[3], base[4])
    return base


def doppler_corrected_DM_GM(DM, GM, df, fit_DM, fit_GM, bary):
    """(DM, GM) with the PSRCHIVE barycentric convention applied:
    DM *= df, GM *= df^3 under bary for FITTED parameters (reference
    pptoas.py:583-591; the Pennucci+2014 paper printed it reversed).
    Shared by GetTOAs and the streaming assembly."""
    if bary:
        if fit_DM:
            DM = DM * df
        if fit_GM:
            GM = GM * df ** 3
    return DM, GM


def scattering_toa_flags(tau, tau_err, nu_tau, alpha, alpha_err, P, df,
                         log10_tau, alpha_fitted, nu_ref_tau=None):
    """The scat_* TOA flag set for one fitted subint (scat_time [us],
    optional log10 form, Doppler-corrected reference frequency, index
    and its error when fitted) — the single assembly for GetTOAs and
    the streaming driver.  nu_ref_tau re-references tau first (the CLI
    -nu_tau behavior); pass None when the caller already re-referenced.
    """
    if nu_ref_tau is not None:
        tau, tau_err = reref_tau(tau, tau_err, nu_tau, nu_ref_tau, alpha)
        nu_tau = float(nu_ref_tau)
    flags = scat_time_flags(tau, tau_err, P / df, log10_tau)
    flags["scat_ref_freq"] = nu_tau * df
    flags["scat_ind"] = alpha
    if alpha_fitted:
        flags["scat_ind_err"] = alpha_err
    return flags


def reref_tau(tau, tau_err, nu_from, nu_to, alpha):
    """Re-reference a scattering timescale (and its error) between
    frequencies via its own power law (reference pptoaslib.py:1107-1113
    semantics: tau' = tau (nu'/nu)^alpha, error scaled by the same
    factor; the alpha-covariance cross term is neglected, as in the
    reference's output path)."""
    r = (np.asarray(nu_to, float) / np.asarray(nu_from, float)) \
        ** np.asarray(alpha, float)
    return tau * r, tau_err * np.abs(r)


DEFAULT_IR_DICT = {"DM-smear": False, "wids": [], "irf_types": []}


def build_instrumental_response_FT(ird, freqs0, nbin, DM_guess, P_mean,
                                   bw=0.0):
    """(nchan, nharm) instrumental-response FT for one archive layout,
    or None when the config requests nothing — the construction shared
    by GetTOAs and the streaming driver (reference pptoas.py:428-434).

    ird: {"DM-smear": bool, "wids": [...], "irf_types": [...]} (missing
    keys default off/empty); raises ValueError on unpaired wids/kinds."""
    ird = {**DEFAULT_IR_DICT, **(ird or {})}
    if len(ird["wids"]) != len(ird["irf_types"]):
        raise ValueError(
            "instrumental_response_dict: wids and irf_types must pair "
            f"up (got {len(ird['wids'])} widths, "
            f"{len(ird['irf_types'])} kinds)")
    if not (ird["wids"] or ird["DM-smear"]):
        return None
    from ..ops.gaussian import instrumental_response_port_FT

    freqs0 = np.asarray(freqs0, float)
    nchan = len(freqs0)
    chan_bw = float(np.abs(np.median(np.diff(freqs0)))) if nchan > 1 \
        else float(bw) / max(nchan, 1)
    return instrumental_response_port_FT(
        nbin // 2 + 1, jnp.asarray(freqs0),
        widths=tuple(ird["wids"]), kinds=tuple(ird["irf_types"]),
        DM_smear=DM_guess if ird["DM-smear"] else None,
        chan_bw=chan_bw, P=P_mean)


def snr_weighted_nu_fit(snrs_chan, freqs0):
    """Per-subint fit reference frequency: the S/N * nu^-2-weighted
    center-of-mass frequency (reference guess_fit_freq,
    pplib.py:2715-2729), with a mean-frequency fallback for empty
    subints.  snrs_chan: (nsub, nchan) masked channel S/Ns."""
    w = np.maximum(snrs_chan, 0.0) * freqs0 ** -2.0
    denom = (w * freqs0 ** -2.0).sum(axis=1)
    denom = np.where(denom > 0, denom, 1.0)
    nu_fit = np.sqrt(w.sum(axis=1) / denom)
    return np.where(np.isfinite(nu_fit) & (nu_fit > 0), nu_fit,
                    freqs0.mean())


def load_for_toas(f, tscrunch=False, quiet=True, dtype=None):
    """The load_data configuration every TOA driver uses: dispersed
    data (dedisperse later via the fit), pscrunched, no flux profile,
    archive object dropped.  dtype None = float64; the streaming
    campaign driver passes float32 on fast-fit backends."""
    import numpy as _np

    return load_data(f, dedisperse=False, dededisperse=True,
                     tscrunch=tscrunch, pscrunch=True, flux_prof=False,
                     refresh_arch=False, return_arch=False, quiet=quiet,
                     dtype=_np.float64 if dtype is None else dtype)


def delta_dm_stats(dDMs, dDM_errs):
    """Per-archive offset-DM mean and inflated error (reference
    pptoas.py:713-729): inverse-variance weights when every error is
    positive, uniform otherwise; variance inflated by the weighted
    scatter when more than one subint."""
    dDMs = np.asarray(dDMs, float)
    errs = np.asarray(dDM_errs, float)
    n = len(dDMs)
    if n == 0:
        return np.nan, np.nan
    if np.all(errs > 0):
        w = errs ** -2.0
    else:
        w = np.ones(n)
    mean = float(np.average(dDMs, weights=w))
    var = 1.0 / w.sum()
    if n > 1:
        var *= float(((dDMs - mean) ** 2 * w).sum() / (n - 1))
    return mean, float(np.sqrt(var))


def _iter_archives(datafiles, loader, prefetch):
    """Yield (datafile, DataBunch-or-Exception).  With prefetch, worker
    threads load archives ahead of the consumer — IO/compute overlap
    for long archive lists (the reference loads and fits strictly
    sequentially, pptoas.py:258).  prefetch: False/0 disables, True
    uses the default depth (4), an int sets the window depth (number of
    archives decoded ahead)."""
    if not prefetch or len(datafiles) <= 1:
        for f in datafiles:
            try:
                yield f, loader(f)
            except Exception as e:
                yield f, e
        return
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    depth = 4 if prefetch is True else max(1, int(prefetch))

    def safe(f):
        try:
            return loader(f)
        except Exception as e:
            return e

    with ThreadPoolExecutor(max_workers=min(depth, 4)) as ex:
        futs = deque()
        it = iter(datafiles)
        for f in datafiles[:depth]:
            next(it)
            futs.append((f, ex.submit(safe, f)))
        while futs:
            f, fut = futs.popleft()
            nxt = next(it, None)
            if nxt is not None:
                futs.append((nxt, ex.submit(safe, nxt)))
            yield f, fut.result()


def _read_metafile(path):
    with open(path) as f:
        return [line.strip() for line in f
                if line.strip() and not line.strip().startswith("#")]


def _is_metafile(path):
    """A metafile is a short ASCII list of existing file paths."""
    try:
        with open(path, "rb") as f:
            head = f.read(256)
        head.decode("ascii")
    except (UnicodeDecodeError, OSError):
        return False
    if head.startswith(b"SIMPLE"):
        return False
    import os

    names = _read_metafile(path)
    return bool(names) and all(os.path.exists(n) for n in names[:3])


class GetTOAs:
    """Measure wideband TOAs + DMs (+ GM, tau, alpha) from archives.

    Usage parity with the reference (pptoas.py:87-159): construct with
    datafiles (single archive, metafile, or list) and a modelfile
    (.gmodel / spline / PSRFITS template), call get_TOAs(), read the
    per-archive parallel result lists, pass TOA_list to io.tim.
    """

    def __init__(self, datafiles, modelfile, quiet=False):
        from ..utils.device import enable_compile_cache

        # persistent compilation cache (config.compile_cache_dir /
        # PPT_COMPILE_CACHE / pptoas --compile-cache): a no-op when
        # unset — the per-shape jit cold start is paid here exactly
        # like in the streaming drivers, so library users of this
        # lane get the cache without their own wiring
        enable_compile_cache()
        if isinstance(datafiles, str):
            if _is_metafile(datafiles):
                self.datafiles = _read_metafile(datafiles)
            else:
                self.datafiles = [datafiles]
        else:
            self.datafiles = list(datafiles)
        if len(self.datafiles) > MAX_NFILE:
            raise ValueError(
                f"> {MAX_NFILE} archives in one run; split the metafile")
        self.modelfile = str(modelfile)
        self.model = TemplateModel(modelfile, quiet=quiet)
        # mutable instrumental-response config (parity:
        # pptoas.py:156-158): set "DM-smear" True and/or append
        # (width [rot], kind) pairs to wids/irf_types before get_TOAs
        self.instrumental_response_dict = {
            "DM-smear": False, "wids": [], "irf_types": []}
        self.obs = []
        self.doppler_fs = []
        self.nu0s = []
        self.nu_fits = []
        self.nu_refs = []
        self.ok_isubs = []
        self.epochs = []
        self.MJDs = []
        self.Ps = []
        self.phis = []
        self.phi_errs = []
        self.TOAs = []
        self.TOA_errs = []
        self.DM0s = []
        self.DMs = []
        self.DM_errs = []
        self.DeltaDM_means = []
        self.DeltaDM_errs = []
        self.GMs = []
        self.GM_errs = []
        self.taus = []
        self.tau_errs = []
        self.alphas = []
        self.alpha_errs = []
        self.scales = []
        self.scale_errs = []
        self.snrs = []
        self.channel_snrs = []
        self.profile_fluxes = []
        self.profile_flux_errs = []
        self.fluxes = []
        self.flux_errs = []
        self.flux_freqs = []
        self.covariances = []
        self.red_chi2s = []
        self.channel_red_chi2s = []
        self.nfevals = []
        self.rcs = []
        self.fit_durations = []
        self.order = []
        self.TOA_list = []
        self.quiet = quiet

    # ------------------------------------------------------------------
    def get_TOAs(self, datafile=None, tscrunch=False, nu_refs=None,
                 DM0=None, bary=True, fit_DM=True, fit_GM=False,
                 fit_scat=False, log10_tau=True, scat_guess=None,
                 fix_alpha=False, print_phase=False, print_flux=False,
                 print_parangle=False, addtnl_toa_flags={},
                 nu_fits=None, max_iter=40, prefetch=False, quiet=None,
                 bounds=None, quality_flags=False, telemetry=None):
        """Measure wideband TOAs (reference pptoas.py:161-792; same
        options minus the scipy `method` knob, which has no analogue
        in the fused-Newton engine).  prefetch=True overlaps
        the next archive's load with the current archive's fits.
        scat_guess: (tau_s, nu_MHz, alpha) like the reference, or
        "auto" to estimate tau per subint from the data
        (fit.portrait.estimate_tau — no reference analogue).
        bounds: optional (5, 2) [lo, hi] box on (phi, DM, GM,
        tau-or-log10tau, alpha) — the reference's TNC `bounds`
        (pptoaslib.py:1039-1060): parameters are clipped to the box and
        a fit converging ON a bound reports return code 0
        (LOCALMINIMUM, |projected g| ~= 0); use None entries as +-inf
        via np.inf.

        quality_flags=True adds per-TOA -nfev and -chi2 fit
        diagnostics to the TOA flags from the already-computed result
        arrays (-snr and -gof are always emitted); off by default so
        .tim output stays byte-identical.  telemetry: a trace path or
        telemetry.Tracer — per-archive load/fit events and per-TOA
        quality records (nfeval, chi2/dof, snr) append to the JSONL
        trace (None follows config.telemetry_path; default off)."""
        if quiet is None:
            quiet = self.quiet
        if bounds is not None:
            bounds = np.asarray(bounds, float)
            if bounds.shape != (5, 2):
                raise ValueError(
                    f"bounds must be (5, 2) [lo, hi] rows for (phi, DM,"
                    f" GM, tau, alpha); got shape {bounds.shape}")
            if np.any(np.isnan(bounds)):
                # NaN would sail through the ordering check (nan > hi
                # is False) and silently poison every fit via the
                # seed projection's clip
                raise ValueError("bounds: NaN entries (use +-np.inf "
                                 "for open bounds)")
            if np.any(bounds[:, 0] > bounds[:, 1]):
                raise ValueError("bounds: a lower bound exceeds its "
                                 "upper bound")
        scat_guess = _validate_scat_guess(scat_guess, fit_scat)
        if not fit_scat:
            log10_tau = False
        self.fit_flags = [1, int(fit_DM), int(fit_GM), int(fit_scat),
                          int(fit_scat and not fix_alpha)]
        self.log10_tau = log10_tau
        self.tscrunch = tscrunch
        self.bary = bary
        self.DM0 = DM0
        datafiles = self.datafiles if datafile is None else [datafile]
        nu_ref_DM = nu_refs[0] if nu_refs is not None else None
        nu_ref_tau = nu_refs[1] if nu_refs is not None else None

        load_times = {}
        tracer, own_tracer = resolve_tracer(telemetry,
                                            run="GetTOAs.get_TOAs")
        ntoa_before = len(self.TOA_list)
        narch_before = len(self.order)
        nfit_calls = 0  # batched fit invocations (one per flag group
        # per archive) — run_end.nfit matches the stream drivers'
        # fused-dispatch semantics, not the archive count

        def _loader(f):
            t0 = time.time()
            try:
                return load_for_toas(f, tscrunch=tscrunch, quiet=quiet)
            finally:
                load_times[f] = time.time() - t0

        try:
            for datafile, d in _iter_archives(datafiles, _loader, prefetch):
                t_start = time.time()
                if isinstance(d, Exception):
                    # skip-and-continue (pptoas.py:261-304)
                    tracer.emit("archive_skip", datafile=datafile,
                                reason=str(d))
                    log(f"Skipping {datafile}: {d}", level="warn")
                    continue
                if d.nsub == 0 or len(d.ok_isubs) == 0:
                    tracer.emit("archive_skip", datafile=datafile,
                                reason="no subints to fit")
                    log(f"No subints to fit in {datafile}; skipping.",
                        level="warn")
                    continue
                if tracer.enabled:
                    tracer.emit("archive_load", datafile=datafile,
                                load_s=round(load_times.get(datafile, 0.0),
                                             6),
                                n_ok=len(d.ok_isubs))
                nsub, nchan, nbin = d.nsub, d.nchan, d.nbin
                ok = np.asarray(d.ok_isubs, int)
                nok = len(ok)
                P_mean = float(np.mean(d.Ps[ok]))
                freqs0 = np.asarray(d.freqs[0], float)
                DM_stored = float(d.DM)
                DM0_arch = DM_stored if DM0 is None else float(DM0)
                DM_guess = DM_stored if DM_stored != 0.0 else DM0_arch

                # template (cached per unique frequency layout)
                try:
                    modelx = self.model.portrait(freqs0, nbin, P=P_mean)
                except ValueError as e:
                    tracer.emit("archive_skip", datafile=datafile,
                                reason=str(e))
                    log(f"Skipping {datafile}: {e}", level="warn")
                    continue

                ports = np.asarray(d.subints[ok, 0], float)
                masks = np.asarray(d.weights[ok] > 0.0, float)
                noise = np.asarray(d.noise_stds[ok, 0], float)
                snrs_chan = np.asarray(d.SNRs[ok, 0], float) * masks

                # per-subint fit reference frequency (pplib.py:2715-2729)
                if nu_fits is not None:
                    nu_fit_arr = np.full(nok, float(nu_fits[0]))
                else:
                    nu_fit_arr = snr_weighted_nu_fit(snrs_chan, freqs0)

                # initial tau guess [rot at nu_fit]; "auto" = data-driven
                # broadband estimate per subint (|X| is phase-invariant, so
                # no alignment needed first) — cuts the scattering fit's
                # Newton evals severalfold vs the neutral seed
                tau0, alpha0 = scat_seed_tau0(
                    scat_guess, fit_scat, nok, nbin, P_mean, nu_fit_arr,
                    self.model.gauss.alpha if self.model.is_gaussian
                    else scattering_alpha,
                    ports=ports, modelx=modelx, noise=noise, masks=masks)

                theta0 = np.zeros((nok, 5))
                theta0[:, 1] = DM_guess
                theta0[:, 3] = (np.log10(np.maximum(tau0, 1e-12))
                                if log10_tau else tau0)
                theta0[:, 4] = alpha0

                # group subints by effective fit flags (degenerate-geometry
                # fallbacks, pptoas.py:519-527)
                nchx = masks.sum(axis=1).astype(int)
                base = (True, bool(fit_DM), bool(fit_GM), bool(fit_scat),
                        bool(fit_scat and not fix_alpha))
                groups = {}
                for i in range(nok):
                    groups.setdefault(
                        effective_fit_flags(nchx[i], base), []).append(i)

                # instrumental-response FT for this archive's layout
                # (pptoas.py:428-434): product of configured achromatic
                # kernels and, optionally, per-channel DM-smearing sincs
                ir_FT = build_instrumental_response_FT(
                    self.instrumental_response_dict, freqs0, nbin,
                    DM_guess, P_mean, bw=d.bw)

                fit_duration = 0.0
                res_arrays = {k: np.full(nok, np.nan) for k in
                              ("phi", "phi_err", "DM", "DM_err", "GM", "GM_err",
                               "tau", "tau_err", "alpha", "alpha_err", "nu_DM",
                               "nu_GM", "nu_tau", "snr", "chi2", "dof")}
                res_arrays["nfeval"] = np.zeros(nok, int)
                res_arrays["rc"] = np.full(nok, -2, int)
                scales_arr = np.zeros((nok, nchan))
                scale_errs_arr = np.zeros((nok, nchan))
                channel_snrs_arr = np.zeros((nok, nchan))
                covs = np.zeros((nok, 5, 5))

                for flags, idx in groups.items():
                    idx = np.asarray(idx, int)
                    nfit_calls += 1
                    tfit = time.time()
                    # no-scattering fits route through the complex-free f32
                    # fast path on TPU backends, where complex FFTs are
                    # unsupported/unusably slow (config.use_fast_fit)
                    use_fast = (not flags[3] and not flags[4]
                                and ir_FT is None
                                # a fixed nonzero tau seed (scat_guess, or a
                                # scattering run's degenerate subint group)
                                # still needs the scattering kernel
                                and not np.any(theta0[idx][:, 3] != 0.0)
                                and use_fast_fit_default())
                    if use_fast:
                        r = fit_portrait_batch_fast(
                            jnp.asarray(ports[idx], jnp.float32),
                            # host numpy template: lets the harmonic-window
                            # 'auto' derivation see the model's spectrum
                            # (fit.portrait.resolve_harmonic_window)
                            np.asarray(modelx, np.float32),
                            jnp.asarray(noise[idx], jnp.float32),
                            jnp.asarray(freqs0, jnp.float32),
                            jnp.asarray(d.Ps[ok][idx], jnp.float32),
                            jnp.asarray(nu_fit_arr[idx], jnp.float32),
                            nu_out=nu_ref_DM,
                            theta0=jnp.asarray(theta0[idx], jnp.float32),
                            fit_flags=FitFlags(*flags),
                            chan_masks=jnp.asarray(masks[idx], jnp.float32),
                            max_iter=max_iter,
                            bounds=bounds,
                        )
                    else:
                        # fit_portrait_batch canonicalizes f64 -> f32 on TPU
                        # backends itself (c128 spectra do not compile there)
                        r = fit_portrait_batch(
                            jnp.asarray(ports[idx]),
                            jnp.asarray(np.broadcast_to(modelx,
                                                        ports[idx].shape)),
                            jnp.asarray(noise[idx]),
                            jnp.asarray(freqs0),
                            jnp.asarray(d.Ps[ok][idx]),
                            jnp.asarray(nu_fit_arr[idx]),
                            nu_out=nu_ref_DM,
                            theta0=jnp.asarray(theta0[idx]),
                            fit_flags=FitFlags(*flags),
                            chan_masks=jnp.asarray(masks[idx]),
                            # unconditional: a degenerate (phase-only) group
                            # in a log10 scattering run still carries its
                            # fixed tau seed in log10 space, and the engine
                            # must decode it that way (log10_tau is already
                            # False whenever fit_scat is off)
                            log10_tau=log10_tau,
                            max_iter=max_iter,
                            ir_FT=ir_FT,
                            bounds=bounds,
                        )
                    r = {k: np.asarray(v) for k, v in r._asdict().items()}
                    fit_duration += time.time() - tfit
                    for k_res, k_arr in (
                            ("phi", "phi"), ("phi_err", "phi_err"),
                            ("DM", "DM"), ("DM_err", "DM_err"),
                            ("GM", "GM"), ("GM_err", "GM_err"),
                            ("tau", "tau"), ("tau_err", "tau_err"),
                            ("alpha", "alpha"), ("alpha_err", "alpha_err"),
                            ("nu_DM", "nu_DM"), ("nu_GM", "nu_GM"),
                            ("nu_tau", "nu_tau"), ("snr", "snr"),
                            ("chi2", "chi2"), ("dof", "dof")):
                        res_arrays[k_arr][idx] = r[k_res]
                    res_arrays["nfeval"][idx] = r["nfeval"]
                    res_arrays["rc"][idx] = r["return_code"]
                    scales_arr[idx] = r["scales"] * masks[idx]
                    scale_errs_arr[idx] = r["scale_errs"] * masks[idx]
                    channel_snrs_arr[idx] = r["channel_snrs"] * masks[idx]
                    covs[idx] = r["covariance"]

                if tracer.enabled:
                    tracer.emit("archive_fit", datafile=datafile,
                                n_ok=nok, fit_s=round(fit_duration, 6))
                    dofs = np.maximum(res_arrays["dof"], 1.0)
                    with np.errstate(invalid="ignore"):
                        # finite() maps NaN/Inf from degenerate fits to
                        # JSON null (bare NaN tokens break strict readers)
                        tracer.emit(
                            "quality", datafile=datafile,
                            snr=[finite(v, 3) for v in res_arrays["snr"]],
                            gof=[finite(float(c) / float(s), 4) for c, s in
                                 zip(res_arrays["chi2"], dofs)],
                            nfev=[int(v) for v in res_arrays["nfeval"]])

                # guard rail for the bf16 cross-spectrum default: warn
                # (once per process) when this archive's channel S/N
                # leaves the calibrated regime
                from ..fit.portrait import warn_bf16_high_snr
                with np.errstate(invalid="ignore"):
                    warn_bf16_high_snr(float(np.nanmax(
                        channel_snrs_arr, initial=0.0)), quiet=quiet)

                # user-requested tau output reference (reference -nu_tau;
                # None keeps each fit's zero-covariance frequency)
                if fit_scat and nu_ref_tau is not None:
                    tau_r, tau_err_r = reref_tau(
                        res_arrays["tau"], res_arrays["tau_err"],
                        res_arrays["nu_tau"], nu_ref_tau, res_arrays["alpha"])
                    res_arrays["tau"], res_arrays["tau_err"] = tau_r, tau_err_r
                    res_arrays["nu_tau"] = np.full(nok, float(nu_ref_tau))

                # ---- per-subint host post-processing --------------------------
                phis = np.full(nsub, np.nan)
                phi_errs = np.full(nsub, np.nan)
                TOAs_arr = [None] * nsub
                TOA_errs = np.full(nsub, np.nan)
                DMs = np.full(nsub, np.nan)
                DM_errs = np.full(nsub, np.nan)
                GMs = np.full(nsub, np.nan)
                GM_errs = np.full(nsub, np.nan)
                taus = np.full(nsub, np.nan)
                tau_errs = np.full(nsub, np.nan)
                alphas = np.full(nsub, np.nan)
                alpha_errs = np.full(nsub, np.nan)
                snrs_sub = np.full(nsub, np.nan)
                red_chi2s = np.full(nsub, np.nan)
                nfevals = np.zeros(nsub, int)
                rcs = np.full(nsub, -2, int)
                nu_refs_sub = np.full((nsub, 3), np.nan)
                scales_full = np.zeros((nsub, nchan))
                scale_errs_full = np.zeros((nsub, nchan))
                channel_snrs_full = np.zeros((nsub, nchan))
                covariances = np.zeros((nsub, 5, 5))
                profile_fluxes = np.zeros((nsub, nchan))
                profile_flux_errs = np.zeros((nsub, nchan))
                fluxes = np.full(nsub, np.nan)
                flux_errs = np.full(nsub, np.nan)
                flux_freqs = np.full(nsub, np.nan)
                MJDs = np.full(nsub, np.nan)

                for j, isub in enumerate(ok):
                    phi = float(res_arrays["phi"][j])
                    P = float(d.Ps[isub])
                    epoch = d.epochs[isub]
                    toa_mjd = epoch.add_seconds(phi * P + d.backend_delay)
                    df = float(d.doppler_factors[isub]) if bary else 1.0
                    DM_j, GM_j = doppler_corrected_DM_GM(
                        float(res_arrays["DM"][j]), float(res_arrays["GM"][j]),
                        df, self.fit_flags[1], self.fit_flags[2], bary)

                    phis[isub] = phi
                    phi_errs[isub] = res_arrays["phi_err"][j]
                    TOAs_arr[isub] = toa_mjd
                    TOA_errs[isub] = res_arrays["phi_err"][j] * P * 1e6
                    DMs[isub] = DM_j
                    DM_errs[isub] = res_arrays["DM_err"][j]
                    GMs[isub] = GM_j
                    GM_errs[isub] = res_arrays["GM_err"][j]
                    taus[isub] = res_arrays["tau"][j]
                    tau_errs[isub] = res_arrays["tau_err"][j]
                    alphas[isub] = res_arrays["alpha"][j]
                    alpha_errs[isub] = res_arrays["alpha_err"][j]
                    snrs_sub[isub] = res_arrays["snr"][j]
                    dof = max(float(res_arrays["dof"][j]), 1.0)
                    red_chi2s[isub] = res_arrays["chi2"][j] / dof
                    nfevals[isub] = res_arrays["nfeval"][j]
                    rcs[isub] = res_arrays["rc"][j]
                    nu_refs_sub[isub] = (res_arrays["nu_DM"][j],
                                         res_arrays["nu_GM"][j],
                                         res_arrays["nu_tau"][j])
                    scales_full[isub] = scales_arr[j]
                    scale_errs_full[isub] = scale_errs_arr[j]
                    channel_snrs_full[isub] = channel_snrs_arr[j]
                    covariances[isub] = covs[j]
                    MJDs[isub] = toa_mjd.to_float()

                    # flux estimate (pptoas.py:595-624).  The reference
                    # rebuilds the scattered model here, but the one-sided
                    # exponential kernel has unit DC gain (B_0 = 1), so the
                    # model CHANNEL MEANS — the only model quantity flux
                    # uses — are unchanged by any fitted tau; the rebuild
                    # was pure waste (one FFT round-trip per subint).
                    if print_flux:
                        okc = np.asarray(d.ok_ichans[isub], int)
                        means = modelx.mean(axis=1)
                        profile_fluxes[isub, okc] = means[okc] * \
                            scales_full[isub, okc]
                        profile_flux_errs[isub, okc] = np.abs(means[okc]) * \
                            scale_errs_full[isub, okc]
                        fl, fl_err = weighted_mean(profile_fluxes[isub, okc],
                                                   profile_flux_errs[isub, okc])
                        ffreq, _ = weighted_mean(freqs0[okc],
                                                 profile_flux_errs[isub, okc])
                        fluxes[isub], flux_errs[isub] = fl, fl_err
                        flux_freqs[isub] = ffreq

                    # ---- TOA flags (pptoas.py:653-707) -----------------------
                    okc = np.asarray(d.ok_ichans[isub], int)
                    freqsx = freqs0[okc]
                    toa_flags = {}
                    DM_out, DM_err_out = DM_j, float(DM_errs[isub])
                    if not self.fit_flags[1]:
                        DM_out = DM_err_out = None
                    if self.fit_flags[2]:
                        toa_flags["gm"] = GM_j
                        toa_flags["gm_err"] = float(GM_errs[isub])
                    if self.fit_flags[3]:
                        # nu_ref_tau=None: the array-level reref above
                        # already applied any user-requested reference
                        toa_flags.update(scattering_toa_flags(
                            float(res_arrays["tau"][j]),
                            float(res_arrays["tau_err"][j]),
                            float(res_arrays["nu_tau"][j]),
                            float(res_arrays["alpha"][j]),
                            float(res_arrays["alpha_err"][j]), P, df,
                            log10_tau, bool(self.fit_flags[4])))
                    toa_flags["be"] = d.backend
                    toa_flags["fe"] = d.frontend
                    toa_flags["f"] = f"{d.frontend}_{d.backend}"
                    toa_flags["nbin"] = int(nbin)
                    toa_flags["nch"] = int(nchan)
                    toa_flags["nchx"] = int(len(freqsx))
                    toa_flags["bw"] = float(freqsx.max() - freqsx.min()) \
                        if len(freqsx) else 0.0
                    toa_flags["chbw"] = abs(float(d.bw)) / nchan
                    toa_flags["subint"] = int(isub)
                    toa_flags["tobs"] = float(d.subtimes[isub])
                    toa_flags["fratio"] = float(freqsx.max() / freqsx.min()) \
                        if len(freqsx) else 1.0
                    toa_flags["tmplt"] = self.modelfile
                    toa_flags["snr"] = float(res_arrays["snr"][j])
                    if nu_ref_DM is None and self.fit_flags[1]:
                        toa_flags["phi_DM_cov"] = float(covs[j][0, 1])
                    toa_flags["gof"] = float(red_chi2s[isub])
                    if quality_flags:
                        # per-TOA fit diagnostics from res_arrays (-snr
                        # and -gof are always present above); OFF by
                        # default so golden .tim files stay byte-identical
                        toa_flags["nfev"] = int(res_arrays["nfeval"][j])
                        toa_flags["chi2"] = float(res_arrays["chi2"][j])
                    if print_phase:
                        toa_flags["phs"] = phi
                        toa_flags["phs_err"] = float(phi_errs[isub])
                    if print_flux:
                        toa_flags["flux"] = float(fluxes[isub])
                        toa_flags["flux_err"] = float(flux_errs[isub])
                        toa_flags["flux_ref_freq"] = float(flux_freqs[isub])
                    if print_parangle:
                        toa_flags["par_angle"] = \
                            float(d.parallactic_angles[isub])
                    toa_flags.update(addtnl_toa_flags)
                    self.TOA_list.append(TOA(
                        datafile, float(res_arrays["nu_DM"][j]), toa_mjd,
                        float(TOA_errs[isub]), d.telescope, d.telescope_code,
                        DM_out, DM_err_out, toa_flags))

                # ---- per-archive DeltaDM statistics (pptoas.py:713-729) ------
                DeltaDM_mean, DeltaDM_err = delta_dm_stats(
                    DMs[ok] - DM0_arch, DM_errs[ok])
                self.order.append(datafile)
                self.obs.append(d.telescope_code)
                self.doppler_fs.append(np.asarray(d.doppler_factors))
                self.nu0s.append(d.nu0)
                self.nu_fits.append(nu_fit_arr)
                self.nu_refs.append(nu_refs_sub)
                self.ok_isubs.append(ok)
                self.epochs.append(d.epochs)
                self.MJDs.append(MJDs)
                self.Ps.append(np.asarray(d.Ps))
                self.phis.append(phis)
                self.phi_errs.append(phi_errs)
                self.TOAs.append(TOAs_arr)
                self.TOA_errs.append(TOA_errs)
                self.DM0s.append(DM0_arch)
                self.DMs.append(DMs)
                self.DM_errs.append(DM_errs)
                self.DeltaDM_means.append(DeltaDM_mean)
                self.DeltaDM_errs.append(DeltaDM_err)
                self.GMs.append(GMs)
                self.GM_errs.append(GM_errs)
                self.taus.append(taus)
                self.tau_errs.append(tau_errs)
                self.alphas.append(alphas)
                self.alpha_errs.append(alpha_errs)
                self.scales.append(scales_full)
                self.scale_errs.append(scale_errs_full)
                self.snrs.append(snrs_sub)
                self.channel_snrs.append(channel_snrs_full)
                self.profile_fluxes.append(profile_fluxes)
                self.profile_flux_errs.append(profile_flux_errs)
                self.fluxes.append(fluxes)
                self.flux_errs.append(flux_errs)
                self.flux_freqs.append(flux_freqs)
                self.covariances.append(covariances)
                self.red_chi2s.append(red_chi2s)
                self.nfevals.append(nfevals)
                self.rcs.append(rcs)
                self.fit_durations.append(fit_duration)
                if not quiet:
                    # the load happened inside the archive iterator (maybe
                    # on the prefetch thread) — count it back into 'total'
                    tot = (time.time() - t_start
                           + load_times.get(datafile, 0.0))
                    med = np.nanmedian(phi_errs[ok]) * np.mean(d.Ps[ok]) * 1e6
                    log("--------------------------\n"
                        f"{datafile}\n"
                        f"~{fit_duration / max(nok, 1):.4f} sec/TOA (fit), "
                        f"{tot:.2f} sec total\n"
                        f"Med. TOA error is {med:.3f} us", quiet=quiet)

            if tracer.enabled:
                done = self.fit_durations[narch_before:]
                tracer.emit("run_end", driver="GetTOAs.get_TOAs",
                            n_toas=len(self.TOA_list) - ntoa_before,
                            n_archives=len(self.order) - narch_before,
                            nfit=nfit_calls, fit_s=round(sum(done), 6))
        finally:
            # an exception mid-campaign (or Ctrl-C) must
            # still leave a closed, counter-bearing trace —
            # same stance as the stream/ipta drivers
            if own_tracer:
                tracer.close()

    # ------------------------------------------------------------------
    def get_narrowband_TOAs(self, datafile=None, tscrunch=False,
                            fit_scat=False, log10_tau=True,
                            scat_guess=None, print_phase=False,
                            addtnl_toa_flags={}, max_iter=40,
                            quiet=None):
        """Per-channel 1-D FFTFIT TOAs (reference pptoas.py:794-1189),
        batched: every (subint, channel) profile of an archive is fitted
        in one vmapped phase-shift call.

        fit_scat=True fits a per-channel scattering timescale alongside
        the phase by running the 5-parameter engine on single-channel
        portraits with flags (phi, tau) — the capability the reference
        stubbed out ('NOT YET IMPLEMENTED', pptoas.py:1046-1049).
        scat_guess: optional (tau [s], freq [MHz], alpha) seed or
        "auto", as in get_TOAs.  The linear parameterization
        (log10_tau=False) only converges from a realistic seed, so it
        requires scat_guess."""
        from ..fit.phase_shift import fit_phase_shift_batch

        scat_guess = _validate_scat_guess(scat_guess, fit_scat)

        if quiet is None:
            quiet = self.quiet
        if fit_scat and not log10_tau and scat_guess is None:
            raise ValueError(
                "get_narrowband_TOAs: log10_tau=False needs scat_guess "
                "(the linear parameterization cannot converge from the "
                "neutral half-bin seed)")
        datafiles = self.datafiles if datafile is None else [datafile]
        for datafile in datafiles:
            try:
                d = load_data(datafile, dedisperse=False, dededisperse=True,
                              tscrunch=tscrunch, pscrunch=True, quiet=quiet)
            except Exception as e:
                log(f"Skipping {datafile}: {e}", level="warn")
                continue
            ok = np.asarray(d.ok_isubs, int)
            if len(ok) == 0:
                continue
            nchan, nbin = d.nchan, d.nbin
            freqs0 = np.asarray(d.freqs[0], float)
            P_mean = float(np.mean(d.Ps[ok]))
            modelx = self.model.portrait(freqs0, nbin, P=P_mean)
            ports = jnp.asarray(d.subints[ok, 0])  # (nok, nchan, nbin)
            noise = jnp.asarray(d.noise_stds[ok, 0])
            nok = len(ok)
            taus = tau_errs = None
            if fit_scat:
                # (nok*nchan) single-channel portraits through the
                # 5-param engine with flags (phi, tau); phase seeded by
                # the CCF, tau by half a bin
                flat_ports = ports.reshape(nok * nchan, 1, nbin)
                flat_models = jnp.broadcast_to(
                    jnp.asarray(modelx), ports.shape
                ).reshape(nok * nchan, 1, nbin)
                flat_noise = noise.reshape(nok * nchan, 1)
                flat_freqs = jnp.broadcast_to(
                    jnp.asarray(freqs0), (nok, nchan)
                ).reshape(nok * nchan, 1)
                flat_P = jnp.repeat(jnp.asarray(d.Ps[ok]), nchan)
                masks = jnp.asarray(
                    (d.weights[ok] > 0.0).reshape(nok * nchan, 1), float)
                th0 = np.zeros((nok * nchan, 5))
                if scat_guess == "auto":
                    # broadband estimate per subint, scaled to each
                    # channel with the default scattering index
                    from ..fit.portrait import estimate_tau_batch

                    tau_sub = np.asarray(estimate_tau_batch(
                        jnp.asarray(ports, jnp.float32),
                        jnp.asarray(modelx, jnp.float32),
                        jnp.asarray(noise, jnp.float32)))
                    nu_mid = float(np.mean(freqs0))
                    tau_seed = (tau_sub[:, None] * (freqs0[None, :] / nu_mid)
                                ** scattering_alpha).reshape(nok * nchan)
                elif scat_guess is not None:
                    t_s, nu_s, a_s = scat_guess
                    tau_seed = ((t_s / P_mean)
                                * (np.asarray(flat_freqs[:, 0]) / nu_s)
                                ** a_s)
                else:
                    tau_seed = np.full(nok * nchan, 0.5 / nbin)
                th0[:, 3] = (np.log10(np.maximum(tau_seed, 1e-12))
                             if log10_tau else tau_seed)
                r = fit_portrait_batch(
                    flat_ports, flat_models, flat_noise, flat_freqs,
                    flat_P, flat_freqs[:, 0],
                    fit_flags=FitFlags(True, False, False, True, False),
                    theta0=jnp.asarray(th0), chan_masks=masks,
                    log10_tau=log10_tau, max_iter=max_iter)
                phase = np.asarray(r.phi).reshape(nok, nchan)
                phase_err = np.asarray(r.phi_err).reshape(nok, nchan)
                snr = np.asarray(r.snr).reshape(nok, nchan)
                dof = np.maximum(np.asarray(r.dof), 1.0)
                red_chi2 = (np.asarray(r.chi2) / dof).reshape(nok, nchan)
                taus = np.asarray(r.tau).reshape(nok, nchan)
                tau_errs = np.asarray(r.tau_err).reshape(nok, nchan)
            else:
                models = jnp.broadcast_to(jnp.asarray(modelx), ports.shape)
                r = fit_phase_shift_batch(ports, models, noise)
                phase = np.asarray(r.phase)
                phase_err = np.asarray(r.phase_err)
                snr = np.asarray(r.snr)
                red_chi2 = np.asarray(r.red_chi2)
            self.order.append(datafile)
            self.ok_isubs.append(ok)
            for j, isub in enumerate(ok):
                P = float(d.Ps[isub])
                okc = np.asarray(d.ok_ichans[isub], int)
                for ichan in okc:
                    toa_mjd = d.epochs[isub].add_seconds(
                        float(phase[j, ichan]) * P + d.backend_delay)
                    toa_flags = {
                        "be": d.backend, "fe": d.frontend,
                        "f": f"{d.frontend}_{d.backend}",
                        "nbin": int(nbin), "subint": int(isub),
                        "chan": int(ichan),
                        "tobs": float(d.subtimes[isub]),
                        "tmplt": self.modelfile,
                        "snr": float(snr[j, ichan]),
                        "gof": float(red_chi2[j, ichan]),
                    }
                    if fit_scat:
                        toa_flags.update(scat_time_flags(
                            float(taus[j, ichan]),
                            float(tau_errs[j, ichan]), P, log10_tau))
                        # each channel's tau is referenced to its own
                        # frequency
                        toa_flags["scat_ref_freq"] = float(freqs0[ichan])
                    if print_phase:
                        toa_flags["phs"] = float(phase[j, ichan])
                        toa_flags["phs_err"] = float(phase_err[j, ichan])
                    toa_flags.update(addtnl_toa_flags)
                    self.TOA_list.append(TOA(
                        datafile, float(freqs0[ichan]), toa_mjd,
                        float(phase_err[j, ichan]) * P * 1e6,
                        d.telescope, d.telescope_code, None, None,
                        toa_flags))

    # ------------------------------------------------------------------
    def get_crosscheck_TOAs(self, datafile=None, tscrunch=False,
                            DM0=None, oversamp=16, addtnl_toa_flags={},
                            append_to_list=False, quiet=None):
        """Independent-algorithm TOA cross-check (the role of the
        reference's get_psrchive_TOAs, pptoas.py:1191-1264, which
        delegated to PSRCHIVE's ArrivalTime/'pat'; with the PSRCHIVE
        dependency dropped, this provides the second opinion).

        Pure-NumPy f64 time-domain estimator sharing no code with the
        harmonic-domain Newton engine: channels are derotated by the
        header DM, frequency-scrunched with 1/sigma^2 weights, and the
        phase shift found by argmax of the oversampled circular
        cross-correlation with the scrunched template, refined by
        parabolic interpolation; errors from the FFTFIT curvature
        formula.  Returns the list of TOA objects; append_to_list=True
        additionally appends them to TOA_list (off by default so a
        cross-check never contaminates a .tim written from a prior
        get_TOAs run)."""
        if quiet is None:
            quiet = self.quiet
        datafiles = self.datafiles if datafile is None else [datafile]
        out = []
        for datafile in datafiles:
            try:
                d = load_data(datafile, dedisperse=False,
                              dededisperse=True, tscrunch=tscrunch,
                              pscrunch=True, quiet=quiet)
            except Exception as e:
                log(f"Skipping {datafile}: {e}", level="warn")
                continue
            ok = np.asarray(d.ok_isubs, int)
            if len(ok) == 0:
                continue
            nchan, nbin = d.nchan, d.nbin
            nharm = nbin // 2 + 1
            freqs0 = np.asarray(d.freqs[0], float)
            P_mean = float(np.mean(d.Ps[ok]))
            modelx = np.asarray(
                self.model.portrait(freqs0, nbin, P=P_mean), float)
            DM_guess = float(d.DM) if d.DM else (DM0 or 0.0)
            k = np.arange(nharm)
            nlag = nbin * oversamp
            Mf_chan = np.fft.rfft(modelx, axis=-1)  # constant per archive
            for isub in ok:
                P = float(d.Ps[isub])
                okc = np.asarray(d.ok_ichans[isub], int)
                if len(okc) == 0:
                    continue
                port = np.asarray(d.subints[isub, 0], float)
                sig = np.asarray(d.noise_stds[isub, 0], float)
                wch = np.zeros(nchan)
                wch[okc] = np.where(sig[okc] > 0, sig[okc] ** -2.0, 0.0)
                # derotate the DATA by the header DM so its channels add
                # coherently; the template's channels are already
                # aligned (no dispersion), so they sum as-is
                delays = (Dconst * DM_guess / P) * (
                    freqs0 ** -2.0 - float(d.nu0) ** -2.0)
                ph = np.exp(2.0j * np.pi * np.outer(delays, k))
                Df = (np.fft.rfft(port, axis=-1) * ph * wch[:, None]).sum(0)
                Mf = (Mf_chan * wch[:, None]).sum(0)
                # oversampled circular CCF + parabolic refinement
                cc = np.fft.irfft(Df * np.conj(Mf), n=nlag)
                j0 = int(np.argmax(cc))
                ym, y0, yp = cc[(j0 - 1) % nlag], cc[j0], cc[(j0 + 1) % nlag]
                denom = ym - 2.0 * y0 + yp
                frac = 0.5 * (ym - yp) / denom if denom != 0.0 else 0.0
                phi = (j0 + frac) / nlag
                phi = (phi + 0.5) % 1.0 - 0.5
                # FFTFIT curvature error: the scrunched profile's noise
                # (E|rfft_k|^2 = nbin sigma^2 for white noise; same
                # convention as ops/noise.get_noise_PS)
                prof = np.fft.irfft(Df / max(wch.sum(), 1e-300), n=nbin)
                spec = np.abs(np.fft.rfft(prof)) ** 2
                noise = np.sqrt(np.mean(spec[-len(spec) // 4:]) / nbin)
                sigF = noise * np.sqrt(nbin / 2.0) * max(wch.sum(), 1e-300)
                e = np.exp(2.0j * np.pi * k * phi)
                p = (np.abs(Mf) ** 2).sum() / sigF ** 2
                c = np.real(Df * np.conj(Mf) * e).sum() / sigF ** 2
                c2 = np.real(Df * np.conj(Mf) * e
                             * (2.0 * np.pi * k) ** 2).sum() / sigF ** 2
                scale = max(c, 0.0) / p
                phi_err = (abs(scale * c2)) ** -0.5 \
                    if scale > 0 and c2 != 0 else 1.0 / nbin
                toa_mjd = d.epochs[isub].add_seconds(
                    phi * P + d.backend_delay)
                toa_flags = {
                    "be": d.backend, "fe": d.frontend,
                    "f": f"{d.frontend}_{d.backend}",
                    "nbin": int(nbin), "subint": int(isub),
                    "tobs": float(d.subtimes[isub]),
                    "tmplt": self.modelfile, "alg": "ccf-parabolic",
                }
                toa_flags.update(addtnl_toa_flags)
                toa = TOA(datafile, float(d.nu0), toa_mjd,
                          phi_err * P * 1e6, d.telescope,
                          d.telescope_code, None, None, toa_flags)
                out.append(toa)
                if append_to_list:
                    self.TOA_list.append(toa)
        return out

    def get_psrchive_TOAs(self, datafile=None, tscrunch=False,
                          algorithm="PGS", addtnl_toa_flags={},
                          quiet=None, **kwargs):
        """Compatibility shim for the reference's PSRCHIVE-delegating
        cross-check (pptoas.py:1191-1264).  PSRCHIVE is not a
        dependency here; the internal time-domain CCF estimator
        (get_crosscheck_TOAs) provides the independent second opinion.
        `algorithm` and any extra pat-oriented kwargs are accepted for
        signature compatibility and ignored (the shift algorithm is
        always 'ccf-parabolic', recorded in each TOA's -alg flag)."""
        if (algorithm != "PGS" or kwargs) and not (quiet or self.quiet):
            ignored = ([f"algorithm={algorithm!r}"] if algorithm != "PGS"
                       else []) + [f"{k}=..." for k in kwargs]
            log("get_psrchive_TOAs: ignoring PSRCHIVE-specific "
                f"option(s) {', '.join(ignored)}")
        return self.get_crosscheck_TOAs(
            datafile=datafile, tscrunch=tscrunch,
            addtnl_toa_flags=addtnl_toa_flags, quiet=quiet)

    # ------------------------------------------------------------------
    @on_host
    def _fitted_model(self, iarch, isub, d, modelx, freqs0):
        """The template rotated onto the (dispersed) data at the
        fitted (phi, DM), including any fitted scattering — the
        reconstruction used by show_fit and channel zapping
        (reference show_fit, pptoas.py:1375-1476)."""
        from ..ops.rotation import rotate_portrait

        nbin = modelx.shape[-1]
        # self.taus stores FitResult.tau: linear rotations always
        tau_r = float(self.taus[iarch][isub])
        port_model = modelx
        if np.isfinite(tau_r) and tau_r > 0.0:
            tt = np.asarray(scattering_times(
                tau_r, float(self.alphas[iarch][isub]), freqs0,
                float(self.nu_refs[iarch][isub][2])))
            B = np.asarray(scattering_portrait_FT(jnp.asarray(tt),
                                                  nbin // 2 + 1))
            port_model = np.fft.irfft(B * np.fft.rfft(modelx, axis=-1),
                                      n=nbin, axis=-1)
        phi = float(self.phis[iarch][isub])
        DM = float(self.DMs[iarch][isub])
        df = float(self.doppler_fs[iarch][isub]) if self.bary else 1.0
        return np.asarray(rotate_portrait(
            jnp.asarray(port_model), -phi, -DM / df,
            float(self.Ps[iarch][isub]), jnp.asarray(freqs0),
            float(self.nu_refs[iarch][isub][0])))

    def show_subint(self, datafile=None, isub=0, show=True,
                    savefig=False):
        """Display one subintegration portrait (reference
        pptoas.py:1345-1373)."""
        from ..viz.plots import show_portrait

        datafile = datafile or self.order[0]
        d = load_data(datafile, dedisperse=False, dededisperse=True,
                      tscrunch=self.tscrunch, pscrunch=True, quiet=True)
        return show_portrait(
            np.asarray(d.subints[isub, 0]) *
            (np.asarray(d.weights[isub]) > 0)[:, None],
            d.phases, d.freqs[isub],
            title=f"{datafile} subint {isub}", show=show,
            savefig=savefig or None)

    def show_fit(self, datafile=None, isub=0, show=True, savefig=False):
        """Data / fitted-model / residual triptych for one subint
        (reference pptoas.py:1375-1476)."""
        from ..viz.plots import show_residual_plot

        datafile = datafile or self.order[0]
        iarch = self.order.index(datafile)
        d = load_data(datafile, dedisperse=False, dededisperse=True,
                      tscrunch=self.tscrunch, pscrunch=True, quiet=True)
        freqs0 = np.asarray(d.freqs[0], float)
        modelx = self.model.portrait(freqs0, d.nbin,
                                     P=float(np.mean(d.Ps)))
        aligned = self._fitted_model(iarch, isub, d, modelx, freqs0)
        scaled = self.scales[iarch][isub][:, None] * aligned
        return show_residual_plot(
            np.asarray(d.subints[isub, 0]), scaled, d.phases, freqs0,
            noise_stds=np.asarray(d.noise_stds[isub, 0]),
            weights=np.asarray(d.weights[isub]),
            titles=(f"{datafile} subint {isub}",
                    str(self.modelfile), "Residuals"),
            show=show, savefig=savefig or None)

    # ------------------------------------------------------------------
    @on_host
    def get_channels_to_zap(self, SNR_threshold=8.0, rchi2_threshold=1.3,
                            iterate=True, show=False, device=None,
                            telemetry=None):
        """Flag channels with bad per-channel reduced chi2 or low S/N
        (reference pptoas.py:1266-1343).  Requires get_TOAs() results;
        fills self.zap_channels as [archive][subint] index lists.

        The iteration core lives in ``quality/postfit.py``: the host
        NumPy oracle or — ``device`` tri-state, following
        config.zap_device / PPT_ZAP_DEVICE like the median algorithm —
        one batched device pass per archive over the (nsub, nchan)
        quality arrays.  The two lanes are bit-identical (the cut's
        only statistics are an exact masked median, a multiply, and
        comparisons).  telemetry: optional tracer/path; emits one
        ``zap_propose`` per archive."""
        from ..pipeline.zap import resolve_zap_device
        from ..quality.postfit import postfit_cut_device, postfit_cut_np
        from ..telemetry import resolve_tracer

        use_device = resolve_zap_device(device)
        tracer, own_tracer = resolve_tracer(telemetry,
                                            run="get_channels_to_zap")
        self.zap_channels = []
        try:
            for iarch, datafile in enumerate(self.order):
                d = load_data(datafile, dedisperse=False,
                              dededisperse=True, tscrunch=self.tscrunch,
                              pscrunch=True, quiet=True)
                nbin = d.nbin
                freqs0 = np.asarray(d.freqs[0], float)
                P_mean = float(np.mean(d.Ps))
                modelx = self.model.portrait(freqs0, nbin, P=P_mean)
                ok = np.asarray(self.ok_isubs[iarch], int)
                nok, nchan = len(ok), d.nchan
                chan_rchi2 = np.zeros((nok, nchan))
                chan_snr = np.zeros((nok, nchan))
                snr_tot = np.full(nok, np.nan)
                okc_mask = np.zeros((nok, nchan), bool)
                t0 = time.perf_counter()
                for j, isub in enumerate(ok):
                    okc = np.asarray(d.ok_ichans[isub], int)
                    if not len(okc):
                        continue
                    okc_mask[j, okc] = True
                    port = np.asarray(d.subints[isub, 0])
                    # rotate the model onto the (dispersed) data at
                    # the fitted (phi, DM) and scale per channel
                    from ..ops.rotation import rotate_portrait

                    phi = self.phis[iarch][isub]
                    DM = self.DMs[iarch][isub]
                    df = self.doppler_fs[iarch][isub] if self.bary \
                        else 1.0
                    aligned = np.asarray(rotate_portrait(
                        jnp.asarray(modelx), -phi, -DM / df,
                        float(d.Ps[isub]), jnp.asarray(freqs0),
                        float(self.nu_refs[iarch][isub][0])))
                    scales = self.scales[iarch][isub]
                    resid = port - scales[:, None] * aligned
                    noise = np.asarray(d.noise_stds[isub, 0])
                    noise = np.where(noise > 0, noise, 1.0)
                    chan_rchi2[j] = (resid ** 2).sum(axis=1) / \
                        noise ** 2 / max(nbin - 1, 1)
                    chan_snr[j] = self.channel_snrs[iarch][isub]
                    snr_tot[j] = self.snrs[iarch][isub]
                cut_fn = postfit_cut_device if use_device \
                    else postfit_cut_np
                bad = cut_fn(chan_rchi2, chan_snr, snr_tot, okc_mask,
                             SNR_threshold=SNR_threshold,
                             rchi2_threshold=rchi2_threshold,
                             iterate=iterate) if nok else \
                    np.zeros((0, nchan), bool)
                arch_zaps = [[] for _ in range(d.nsub)]
                for j, isub in enumerate(ok):
                    arch_zaps[isub] = sorted(
                        int(c) for c in np.flatnonzero(bad[j]))
                if tracer.enabled:
                    tracer.emit(
                        "zap_propose", datafile=datafile,
                        n_channels=int(bad.sum()), n_iter=0,
                        device=bool(use_device),
                        wall_s=round(time.perf_counter() - t0, 6))
                self.zap_channels.append(arch_zaps)
        finally:
            if own_tracer:
                tracer.close()
        return self.zap_channels

    # ------------------------------------------------------------------
    def apply_one_DM(self):
        """Replace each TOA's DM with the per-archive DM0 + weighted
        mean DeltaDM, inflating errors (the CLI --one_DM behavior,
        pptoas.py:1661-1673)."""
        for iarch, datafile in enumerate(self.order):
            one_DM = self.DM0s[iarch] + self.DeltaDM_means[iarch]
            for toa in self.TOA_list:
                if toa.archive == datafile and toa.DM is not None:
                    toa.DM = one_DM
                    toa.DM_error = self.DeltaDM_errs[iarch]
                    toa.flags["one_DM"] = "True"
