"""Gaussian template fitting: profile and evolving-portrait fits.

TPU-native replacement for the reference's lmfit-based template
builders (fit_gaussian_profile pplib.py:1922-2002,
fit_gaussian_portrait pplib.py:2005-2133), driven by the JAX
Levenberg-Marquardt engine in fit/lm.py.  Model generation is the
analytic-FT Gaussian portrait from models/gaussian.py, so the Jacobian
comes from autodiff through the FFT instead of finite differences.

Flat parameter layouts mirror the reference exactly (so .gmodel round-
tripping and ppgauss-style iteration carry over):

profile:  [dc, tau_bins, (loc, wid, amp) * ngauss]
portrait: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp) * ngauss]
          (+ per-join (phase, DM) pairs, + scattering index, handled as
          separate arguments like the reference's lmfit Parameters)
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Dconst, wid_max
from ..ops.gaussian import gaussian_profile_FT, gaussian_profile_FT_jac
from ..ops.phasor import cexp
from ..ops.scattering import (scattering_portrait_FT,
                              scattering_portrait_FT_dtau,
                              scattering_profile_FT,
                              scattering_profile_FT_dtau)
from ..utils.bunch import DataBunch
from .lm import (COMPACT_EVERY_CONFIG, levenberg_marquardt,
                 levenberg_marquardt_batched, resolve_compact_every)

__all__ = ["fit_gaussian_profile", "fit_gaussian_portrait",
           "gen_gaussian_profile_flat", "gen_gaussian_portrait_flat",
           "use_gauss_device", "profile_trial_seeds", "select_best_trial",
           "fit_profile_trials",
           "pad_profile_params", "profile_bounds", "profile_vary",
           "fit_gaussian_profiles_batched", "pad_portrait_params",
           "portrait_bounds", "portrait_vary",
           "fit_gaussian_portraits_batched"]


def use_gauss_device(setting=None):
    """Whether template building should run its Gaussian LM fits
    through the BATCHED engine (fit/lm.levenberg_marquardt_batched):
    config.gauss_device (True/False force; 'auto' = TPU backends, where
    serial per-problem dispatches idle the chip).  Read per call so
    in-process A/B flips take effect.  setting: an explicit per-call
    override (build_templates' gauss_device= argument / the CLIs'
    --gauss-device); None -> config."""
    if setting is None:
        from .. import config

        setting = getattr(config, "gauss_device", "auto")
    from ..tune.capability import resolve_auto

    # strict like config's other tri-state knobs — a typo must not
    # silently mean 'auto'; resolve_auto enforces it
    return resolve_auto("gauss_device", setting)


def _profile_FT_flat(theta, nbin):
    """rFFT of DC + ngauss Gaussians + scattering, theta as in the
    profile layout (tau in bins)."""
    nharm = nbin // 2 + 1
    dc, tau = theta[0], theta[1]
    locs, wids, amps = theta[2::3], theta[3::3], theta[4::3]
    gFT = gaussian_profile_FT(nharm, locs[:, None], wids[:, None],
                              amps[:, None])
    pFT = jnp.sum(gFT, axis=0)
    pFT = pFT.at[0].add(dc * nbin)
    return pFT * scattering_profile_FT(tau / nbin, nharm)


def gen_gaussian_profile_flat(theta, nbin):
    """Phase-domain profile from the flat layout (reference
    gen_gaussian_profile, pplib.py:859-883; tau in bins)."""
    return jnp.fft.irfft(_profile_FT_flat(jnp.asarray(theta, float), nbin),
                         n=nbin)


def _profile_resid(theta, data, errs):
    nbin = data.shape[-1]
    return (data - jnp.fft.irfft(_profile_FT_flat(theta, nbin), n=nbin)) / errs


def _profile_FT_flat_jac(theta, nbin):
    """Closed-form d(_profile_FT_flat)/dtheta -> (nparam, nharm)
    complex (ISSUE 14).  Component blocks come from
    ops.gaussian.gaussian_profile_FT_jac, the scattering chain from
    ops.scattering.scattering_profile_FT_dtau (tau is in BINS in this
    layout, hence the /nbin)."""
    nharm = nbin // 2 + 1
    dc, tau = theta[0], theta[1]
    locs, wids, amps = theta[2::3], theta[3::3], theta[4::3]
    G, dloc, dwid, damp = gaussian_profile_FT_jac(
        nharm, locs[:, None], wids[:, None], amps[:, None])
    A = jnp.sum(G, axis=0).at[0].add(dc * nbin)
    B = scattering_profile_FT(tau / nbin, nharm)
    dB_dbins = scattering_profile_FT_dtau(tau / nbin, nharm) / nbin
    n = theta.shape[0]
    out = jnp.zeros((n, nharm), B.dtype)
    out = out.at[0].set(jnp.zeros(nharm, B.dtype).at[0].set(
        nbin * B[0]))                       # B(0) = 1 exactly
    out = out.at[1].set(A * dB_dbins)
    out = out.at[2::3].set(dloc * B[None, :])
    out = out.at[3::3].set(dwid * B[None, :])
    out = out.at[4::3].set(damp * B[None, :])
    return out


def _profile_resid_jac(theta, data, errs):
    """Analytic residual-Jacobian companion of _profile_resid:
    (nres, nparam) in external space.  The irfft is linear, so each
    column is -irfft(dpFT_j)/errs — one batched inverse DFT instead of
    nparam forward-mode passes re-tracing the model."""
    nbin = data.shape[-1]
    dmodel = jnp.fft.irfft(_profile_FT_flat_jac(theta, nbin), n=nbin,
                           axis=-1)         # (nparam, nbin)
    return -(dmodel / errs[None, :]).T


def fit_gaussian_profile(data, init_params, errs, fit_flags=None,
                         fit_scattering=False, quiet=True):
    """Fit DC + ngauss Gaussians (+ scattering tau) to a profile.

    init_params: [dc, tau_bins, (loc, wid, amp)*ngauss].  Bounds follow
    the reference: tau >= 0, 0 <= wid <= wid_max, amp >= 0
    (pplib.py:1954-1974).  fit_flags covers the NON-scattering params
    (dc + 3*ngauss entries) as in the reference signature; scattering
    is toggled by fit_scattering.  Returns DataBunch(fitted_params,
    fit_errs, residuals, chi2, dof, red_chi2).
    """
    data = jnp.asarray(data, float)
    errs_arr = jnp.broadcast_to(jnp.asarray(errs, float), data.shape)
    x0 = np.asarray(init_params, float)
    n = len(x0)
    ngauss = (n - 2) // 3
    vary = np.ones(n, bool)
    if fit_flags is not None:
        ff = [bool(f) for f in fit_flags]
        vary[0] = ff[0]
        vary[2:] = ff[1:]
    vary[1] = bool(fit_scattering)
    nbin = data.shape[-1]
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    # wids: reference uses min=0 (pplib.py:1969), but an exactly-zero
    # width is a stationary trap (all derivatives vanish, the component
    # can never regrow).  A half-bin floor is below anything resolvable
    # and keeps the optimizer out of the trap.
    lower[3::3] = 0.5 / nbin
    upper[3::3] = wid_max
    lower[4::3] = 0.0  # amps
    res = levenberg_marquardt(_profile_resid, x0, aux=(data, errs_arr),
                              lower=lower, upper=upper, vary=vary,
                              jacobian=_profile_resid_jac)
    residuals = np.asarray(_profile_resid(res.x, data, errs_arr)) * \
        np.asarray(errs_arr)
    dof = int(res.dof)
    out = DataBunch(
        fitted_params=np.asarray(res.x),
        fit_errs=np.asarray(res.x_err),
        residuals=residuals,
        chi2=float(res.chi2),
        dof=dof,
        red_chi2=float(res.chi2) / max(dof, 1),
    )
    if not quiet:
        print(f"Gaussians: {ngauss}  DoF: {dof}  "
              f"reduced chi-sq: {out.red_chi2:.2f}")
    return out


# --------------------------------------------------------------------------
# Portrait fit
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("code", "nbin", "njoin"))
def _portrait_FT_flat(theta, join_theta, alpha_s, freqs, nu_ref, P,
                      join_mask, code="000", nbin=None, njoin=0):
    """(nchan, nharm) model rFFT from the flat portrait layout.

    theta: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp)*ngauss];
    join_theta: (njoin, 2) of (phase, DM) applied to channels selected
    by join_mask (njoin, nchan); alpha_s: scattering index.
    """
    from ..models.gaussian import apply_scattering_FT, gaussian_components_FT

    nharm = nbin // 2 + 1
    params = {
        "dc": theta[0],
        "locs": theta[2::6], "mlocs": theta[3::6],
        "wids": theta[4::6], "mwids": theta[5::6],
        "amps": theta[6::6], "mamps": theta[7::6],
    }
    pFT = gaussian_components_FT(params, freqs, nu_ref, nharm, code)
    # tau in this layout is in bins (the fitter's unit): /nbin -> rotations
    pFT = apply_scattering_FT(pFT, theta[1] / nbin, alpha_s, freqs, nu_ref)
    if njoin:
        k = jnp.arange(nharm, dtype=freqs.dtype)
        for ij in range(njoin):
            phi, DM = join_theta[ij, 0], join_theta[ij, 1]
            delays = phi + (Dconst * DM / P) * (freqs**-2.0 - nu_ref**-2.0)
            rot = jnp.conj(cexp(2.0 * jnp.pi * delays[:, None] * k))
            pFT = jnp.where(join_mask[ij][:, None], pFT * rot, pFT)
    return pFT


def gen_gaussian_portrait_flat(theta, freqs, nu_ref, nbin, alpha_s,
                               code="000", join_theta=None, join_mask=None,
                               P=None):
    """Phase-domain portrait from the flat layout (reference
    gen_gaussian_portrait, pplib.py:886-963, incl. JOIN rotations)."""
    theta = jnp.asarray(theta, float)
    freqs = jnp.asarray(freqs, float)
    njoin = 0 if join_theta is None else int(np.shape(join_theta)[0])
    if join_theta is None:
        join_theta = jnp.zeros((0, 2))
        join_mask = jnp.zeros((0, len(freqs)), bool)
    pFT = _portrait_FT_flat(theta, jnp.asarray(join_theta),
                            jnp.asarray(alpha_s, float), freqs,
                            jnp.asarray(nu_ref, float),
                            jnp.asarray(1.0 if P is None else P, float),
                            jnp.asarray(join_mask), code=code, nbin=nbin,
                            njoin=njoin)
    return jnp.fft.irfft(pFT, n=nbin, axis=-1)


def _make_portrait_resid(code, nbin, njoin, nmain):
    """Residual over the concatenated [theta, join.flat, alpha_s]."""

    def resid(x, data, errs, freqs, nu_ref, P, join_mask):
        theta = x[:nmain]
        join_theta = x[nmain:nmain + 2 * njoin].reshape(njoin, 2)
        alpha_s = x[-1]
        pFT = _portrait_FT_flat(theta, join_theta, alpha_s, freqs, nu_ref,
                                P, join_mask, code=code, nbin=nbin,
                                njoin=njoin)
        model = jnp.fft.irfft(pFT, n=nbin, axis=-1)
        return ((data - model) / errs[:, None]).ravel()

    return resid


def _make_portrait_resid_jac(code, nbin, njoin, nmain):
    """Analytic residual-Jacobian companion of _make_portrait_resid
    over the same concatenated [theta, join.flat, alpha_s] vector
    (ISSUE 14): component blocks from
    models.gaussian.gaussian_components_FT_jac, the per-channel
    scattering chain tau_n = (tau_bins/nbin) (nu/nu_ref)^alpha through
    ops.scattering.scattering_portrait_FT_dtau, and JOIN rotations
    handled exactly — every base column is rotated on the masked
    channels (the rotation multiplies the whole spectrum) and the
    (phase, DM) columns fall out of the final rotated model itself
    (d rot/dphi = -2 pi i k rot, linear in the delay)."""
    from ..models.gaussian import gaussian_components_FT_jac

    def resid_jac(x, data, errs, freqs, nu_ref, P, join_mask):
        nharm = nbin // 2 + 1
        theta = x[:nmain]
        join_theta = x[nmain:nmain + 2 * njoin].reshape(njoin, 2)
        alpha_s = x[-1]
        params = {
            "dc": theta[0],
            "locs": theta[2::6], "mlocs": theta[3::6],
            "wids": theta[4::6], "mwids": theta[5::6],
            "amps": theta[6::6], "mamps": theta[7::6],
        }
        pFT_u, d = gaussian_components_FT_jac(params, freqs, nu_ref,
                                              nharm, code)
        r = freqs / nu_ref
        ra = r ** alpha_s
        taus = (theta[1] / nbin) * ra
        B = scattering_portrait_FT(taus, nharm)
        dB = scattering_portrait_FT_dtau(taus, nharm)
        # (ngauss, 6, nchan, nharm) -> (6*ngauss, nchan, nharm) in the
        # flat layout's per-component (loc, mloc, wid, mwid, amp, mamp)
        # interleave
        comp = jnp.stack([d["locs"], d["mlocs"], d["wids"], d["mwids"],
                          d["amps"], d["mamps"]], axis=2)
        ngauss = comp.shape[1]
        comp = comp.transpose(1, 2, 0, 3).reshape(
            6 * ngauss, comp.shape[0], nharm)
        # base columns in [theta..., alpha] order — alpha rides at the
        # end so one masked-rotate pass covers every pre-join column
        base = jnp.concatenate([
            (d["dc"] * B)[None],
            (pFT_u * dB * (ra / nbin)[:, None])[None],
            comp * B[None],
            (pFT_u * dB * (taus * jnp.log(r))[:, None])[None],
        ], axis=0)                          # (nmain + 1, nchan, nharm)
        full = pFT_u * B
        k = jnp.arange(nharm, dtype=freqs.dtype)
        for ij in range(njoin):
            phi, DM = join_theta[ij, 0], join_theta[ij, 1]
            delays = phi + (Dconst * DM / P) * (freqs**-2.0
                                                - nu_ref**-2.0)
            rot = jnp.conj(cexp(2.0 * jnp.pi * delays[:, None] * k))
            base = jnp.where(join_mask[ij][None, :, None],
                             base * rot[None], base)
            full = jnp.where(join_mask[ij][:, None], full * rot, full)
        mk = jax.lax.complex(jnp.zeros_like(k), -2.0 * jnp.pi * k)
        jcols = []
        for ij in range(njoin):
            dphi = jnp.where(join_mask[ij][:, None], full * mk, 0.0)
            ddm = dphi * ((Dconst / P) * (freqs**-2.0
                                          - nu_ref**-2.0))[:, None]
            jcols += [dphi[None], ddm[None]]
        dpFT = jnp.concatenate([base[:nmain]] + jcols + [base[nmain:]],
                               axis=0)
        dmodel = jnp.fft.irfft(dpFT, n=nbin, axis=-1)
        nx = dmodel.shape[0]
        return -(dmodel / errs[None, :, None]).reshape(nx, -1).T

    return resid_jac


_PORTRAIT_RESID_CACHE = {}
_PORTRAIT_JAC_CACHE = {}


def _portrait_fns(code, nbin, njoin, nmain):
    """(resid, resid_jac) for a portrait layout, cached so the SAME
    function objects key every jit/vmap cache (fit/lm's batched-core
    caches key on function identity)."""
    key = (code, nbin, njoin, nmain)
    if key not in _PORTRAIT_RESID_CACHE:
        _PORTRAIT_RESID_CACHE[key] = _make_portrait_resid(
            code, nbin, njoin, nmain)
    if key not in _PORTRAIT_JAC_CACHE:
        _PORTRAIT_JAC_CACHE[key] = _make_portrait_resid_jac(
            code, nbin, njoin, nmain)
    return _PORTRAIT_RESID_CACHE[key], _PORTRAIT_JAC_CACHE[key]


def fit_gaussian_portrait(data, init_params, scattering_index, errs,
                          fit_flags, fit_scattering_index, freqs, nu_ref,
                          model_code="000", join_params=None, P=None,
                          quiet=True):
    """Fit evolving Gaussian components to an (nchan, nbin) portrait.

    init_params: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp)*g];
    fit_flags: same length; join_params = (join_ichans, values, flags)
    with values/flags = [phase1, DM1, phase2, DM2, ...] as in the
    reference (pplib.py:2073-2092).  Bounds: tau >= 0,
    0 <= wid <= wid_max, amp >= 0.  Returns DataBunch(fitted_params,
    fit_errs, scattering_index, scattering_index_err, join_fit, chi2,
    dof, red_chi2, residuals).
    """
    data = jnp.asarray(data, float)
    nchan, nbin = data.shape
    errs = jnp.broadcast_to(jnp.asarray(errs, float), (nchan,))
    freqs = jnp.asarray(freqs, float)
    x0_main = np.asarray(init_params, float)
    nmain = len(x0_main)
    vary_main = np.asarray(fit_flags, bool)

    if join_params:
        join_ichans, join_vals, join_flags = join_params
        njoin = len(join_ichans)
        join_mask = np.zeros((njoin, nchan), bool)
        for ij, ichans in enumerate(join_ichans):
            join_mask[ij, np.asarray(ichans)] = True
        x0_join = np.asarray(join_vals, float)
        vary_join = np.asarray(join_flags, bool)
    else:
        njoin = 0
        join_mask = np.zeros((0, nchan), bool)
        x0_join = np.zeros(0)
        vary_join = np.zeros(0, bool)

    x0 = np.concatenate([x0_main, x0_join, [float(scattering_index)]])
    vary = np.concatenate([vary_main, vary_join, [bool(fit_scattering_index)]])
    n = len(x0)
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    lower[4:nmain:6] = 0.5 / nbin  # wids: half-bin floor (see profile fit)
    upper[4:nmain:6] = wid_max
    lower[6:nmain:6] = 0.0       # amps

    resid, resid_jac = _portrait_fns(model_code, nbin, njoin, nmain)

    aux = (data, errs, freqs, jnp.asarray(nu_ref, float),
           jnp.asarray(1.0 if P is None else P, float),
           jnp.asarray(join_mask))
    res = levenberg_marquardt(resid, x0, aux=aux, lower=lower, upper=upper,
                              vary=vary, max_iter=200,
                              jacobian=resid_jac)
    x = np.asarray(res.x)
    x_err = np.asarray(res.x_err)
    residuals = np.asarray(resid(res.x, *aux)).reshape(nchan, nbin) * \
        np.asarray(errs)[:, None]
    dof = int(res.dof)
    out = DataBunch(
        fitted_params=x[:nmain],
        fit_errs=x_err[:nmain],
        join_fit=x[nmain:nmain + 2 * njoin],
        join_fit_errs=x_err[nmain:nmain + 2 * njoin],
        scattering_index=float(x[-1]),
        scattering_index_err=float(x_err[-1]),
        residuals=residuals,
        chi2=float(res.chi2),
        dof=dof,
        red_chi2=float(res.chi2) / max(dof, 1),
        nfev=int(res.nfev),
    )
    if not quiet:
        print(f"Gaussian portrait fit: ngauss={(nmain - 2) // 6} "
              f"DoF={dof} reduced chi-sq: {out.red_chi2:.2f}")
    return out


def _serial_lm(resid_fn, aux_of, x0s, lower, upper, varys, max_iter,
               nres_valid=None, jacobian=None):
    """The host-serial oracle lane shared by both batched front-ends:
    the SAME padded problems through the single-problem engine one at a
    time, results stacked into an LMResult with a leading B axis (host
    numpy).  The Jacobian source follows config.lm_jacobian exactly
    like the batched lane, so serial-vs-batched A/Bs compare engines,
    not derivative sources."""
    from .lm import LMResult

    outs = [levenberg_marquardt(
        resid_fn, x0s[b], aux=aux_of(b), lower=lower, upper=upper,
        vary=varys[b], max_iter=max_iter,
        nres_valid=(None if nres_valid is None else int(nres_valid[b])),
        jacobian=jacobian)
        for b in range(len(x0s))]
    return LMResult(*[np.stack([np.asarray(getattr(o, f))
                                for o in outs])
                      for f in LMResult._fields])


# --------------------------------------------------------------------------
# Breadth-first trial seeding + batched fleet dispatch (ISSUE 9)
#
# The template factory (pipeline/factory.py) and the breadth-first
# auto_fit_profile fit MANY flat-layout problems per LM dispatch.  The
# helpers here build the trial problems (matching-pursuit seeds, padded
# parameter layouts, shared bounds/vary masks) and run them either
# batched (one vmapped dispatch — the device lane) or serially through
# the single-problem engine on the SAME padded problems (the host
# oracle), so the two lanes are digit peers by construction.
# --------------------------------------------------------------------------


def profile_trial_seeds(profile, max_ngauss, wid0=0.02, tau=0.0,
                        noise=None):
    """Matching-pursuit seeds for the breadth-first multi-component
    auto fit: greedily place a component of width wid0 at the running
    residual peak and subtract its ANALYTIC profile (no intermediate
    fits — that serialization is exactly what breadth-first removes).
    Returns [trial_1, ..., trial_max_ngauss] where trial_g is the flat
    profile layout [0, tau, (loc, wid, amp) * g] (numpy, host math)."""
    profile = np.asarray(profile, float)
    nbin = len(profile)
    if noise is None:
        noise = float(profile.std())
    grid = np.arange(nbin) / nbin
    resid = profile.copy()
    comps = []
    seeds = []
    for _ in range(int(max_ngauss)):
        ipeak = int(np.argmax(resid))
        loc = (ipeak + 0.5) / nbin
        amp = max(float(resid[ipeak]), float(noise))
        comps.append((loc, wid0, amp))
        d = np.mod(grid - loc + 0.5, 1.0) - 0.5
        resid = resid - amp * np.exp(-4.0 * np.log(2.0)
                                     * (d / wid0) ** 2.0)
        seeds.append(np.concatenate([[0.0, tau],
                                     np.ravel(comps)]))
    return seeds


def select_best_trial(red_chi2s, rchi2_tol=0.1, success=None,
                      stalled=None):
    """Host-side selection over ascending-ngauss trial results,
    mirroring the serial add-refit loop's acceptance rule: a trial must
    improve the best reduced chi2 to be kept; scanning stops early once
    within rchi2_tol of 1 (good enough) or when adding a component
    stopped helping.  Returns the selected index, or None when every
    trial failed (non-finite chi2).

    Lane reproducibility: a CONVERGED trial's chi2 is digit-stable
    (~1e-15) between the batched and serial engines, so converged
    trials use the reference 1% improvement margin.  A trial that
    burned max_iter — or stopped on the STALL exit — sits in a flat,
    ill-conditioned valley whose stop point (and hence chi2, at up to
    the ~1% scale) is NOT digit-reproducible across program variants;
    such trials still compete (a well-fitting unconverged trial must
    beat a converged underfit — high-S/N blended profiles routinely
    cap out while fitting well), but must improve by >5%, so a
    lane-dependent chi2 wobble cannot flip the selected component
    count.  ``success``/``stalled``: per-trial flags from the engine
    (None = treat every trial as converged, the reference rule)."""
    reds = np.asarray(red_chi2s, float)
    n = len(reds)
    conv = np.ones(n, bool)
    if success is not None:
        conv &= np.asarray(success, bool)
    if stalled is not None:
        conv &= ~np.asarray(stalled, bool)
    best = None
    for i, red in enumerate(reds):
        if not np.isfinite(red):
            continue
        margin = 0.99 if conv[i] else 0.95
        if best is None or red < reds[best] * margin:
            best = i
            if red < 1.0 + rchi2_tol:
                break
        else:  # adding components stopped helping
            break
    return best


def fit_profile_trials(profile, max_ngauss, noise, wid0=0.02, tau=0.0,
                       fit_scattering=False, rchi2_tol=0.1,
                       max_iter=100, serial=True):
    """The breadth-first trial pipeline shared by
    GaussPortrait.auto_fit_profile and the factory's gauss_smooth_mean:
    matching-pursuit seeds for every ngauss in 1..max_ngauss, padded to
    a common max_ngauss width, fit in ONE dispatch (serial=False) or
    through the single-problem oracle loop (serial=True), selected on
    host.  Returns DataBunch(index, ngauss, params, param_errs,
    red_chi2s) with params/param_errs trimmed to the selected
    component count, or None when every trial failed (non-finite chi2).
    (The fleet driver keeps its own bucketed version of this flow — it
    fuses trials ACROSS pulsars; the math is this, per bucket.)"""
    profile = np.asarray(profile, float)
    max_ngauss = int(max_ngauss)
    if max_ngauss < 1:
        raise ValueError(
            f"fit_profile_trials needs max_ngauss >= 1 (got "
            f"{max_ngauss}): no trial component counts to fit")
    seeds = profile_trial_seeds(profile, max_ngauss, wid0=wid0,
                                tau=tau, noise=noise)
    x0s, varys = [], []
    for s in seeds:
        padded, g = pad_profile_params(s, max_ngauss)
        x0s.append(padded)
        varys.append(profile_vary(g, max_ngauss,
                                  fit_scattering=fit_scattering))
    res = fit_gaussian_profiles_batched(
        np.broadcast_to(profile, (max_ngauss, len(profile))),
        np.stack(x0s), np.full(max_ngauss, float(noise)),
        np.stack(varys), max_iter=max_iter, serial=serial)
    red = np.asarray(res.chi2, float) / np.maximum(
        np.asarray(res.dof, float), 1.0)
    ibest = select_best_trial(red, rchi2_tol=rchi2_tol,
                              success=np.asarray(res.success),
                              stalled=np.asarray(res.stalled))
    if ibest is None:
        return None
    nsel = 2 + 3 * (ibest + 1)
    return DataBunch(
        index=ibest, ngauss=ibest + 1,
        params=np.asarray(res.x)[ibest][:nsel].copy(),
        param_errs=np.asarray(res.x_err)[ibest][:nsel].copy(),
        red_chi2s=red)


def pad_profile_params(params, ngauss_pad):
    """Pad a flat profile layout [dc, tau, (loc, wid, amp)*g] to
    ngauss_pad components.  Pad components get amp=0 (contributes
    EXACTLY nothing to the model — gaussian_profile_FT scales by amp)
    and are frozen by profile_vary, so the padded fit is digit-
    identical to the unpadded one.  Returns (padded_params, ngauss)."""
    params = np.asarray(params, float)
    ngauss = (len(params) - 2) // 3
    if ngauss > ngauss_pad:
        raise ValueError(f"cannot pad {ngauss} components into "
                         f"{ngauss_pad}")
    out = np.zeros(2 + 3 * ngauss_pad)
    out[:len(params)] = params
    for ig in range(ngauss, ngauss_pad):
        out[2 + 3 * ig: 5 + 3 * ig] = [0.5, 0.02, 0.0]
    return out, ngauss


def profile_bounds(ngauss_pad, nbin):
    """(lower, upper) for the padded profile layout — the same bounds
    fit_gaussian_profile applies (tau >= 0, half-bin <= wid <= wid_max,
    amp >= 0)."""
    n = 2 + 3 * ngauss_pad
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    lower[3::3] = 0.5 / nbin
    upper[3::3] = wid_max
    lower[4::3] = 0.0
    return lower, upper


def profile_vary(ngauss, ngauss_pad, fit_flags=None,
                 fit_scattering=False):
    """vary mask for a padded profile problem: pad components frozen;
    fit_flags covers the non-scattering params of the REAL components
    (dc + 3*ngauss, the fit_gaussian_profile convention)."""
    n = 2 + 3 * ngauss_pad
    vary = np.zeros(n, bool)
    vary[0] = True
    vary[1] = bool(fit_scattering)
    vary[2:2 + 3 * ngauss] = True
    if fit_flags is not None:
        ff = [bool(f) for f in fit_flags]
        vary[0] = ff[0]
        vary[2:2 + 3 * ngauss] = ff[1:1 + 3 * ngauss]
    return vary


def fit_gaussian_profiles_batched(data, x0s, errs, varys, nbin=None,
                                  max_iter=100, serial=False,
                                  compact_every=COMPACT_EVERY_CONFIG):
    """Fit B padded profile problems.  data (B, nbin); x0s (B, n) padded
    flat layouts; errs (B,) or (B, nbin); varys (B, n).

    serial=False: ONE batched LM dispatch (the device lane), chunked
    with straggler compaction every ``compact_every`` iterations (an
    underfit trial burning max_iter must not cost a full-width
    lock-step loop; trajectories are identical either way).
    serial=True: the same problems through the single-problem engine
    one at a time (the host oracle — digit peer of the batched lane).
    Returns an LMResult with leading B axis (host numpy in serial
    mode)."""
    data = np.asarray(data, float)
    B, nbin_d = data.shape
    nbin = nbin_d if nbin is None else nbin
    x0s = np.asarray(x0s, float)
    ngauss_pad = (x0s.shape[1] - 2) // 3
    lower, upper = profile_bounds(ngauss_pad, nbin)
    errs = np.asarray(errs, float)
    if errs.ndim == 1:
        errs = np.broadcast_to(errs[:, None], data.shape)
    if serial:
        return _serial_lm(_profile_resid,
                          lambda b: (jnp.asarray(data[b]),
                                     jnp.asarray(errs[b])),
                          x0s, lower, upper, varys, max_iter,
                          jacobian=_profile_resid_jac)
    return levenberg_marquardt_batched(
        _profile_resid, x0s, aux=(data, errs), lower=lower, upper=upper,
        vary=np.asarray(varys), max_iter=max_iter,
        jacobian=_profile_resid_jac,
        # min_rows=1: template stragglers (underfit trials) routinely
        # run alone for many chunks, and the narrow-width run programs
        # compile once per process — measured a net win over the
        # engine's recompile-bounding default of 4 (BENCHMARKS r12)
        compact_every=resolve_compact_every(compact_every),
        compact_min_rows=1)


def pad_portrait_params(params, ngauss_pad):
    """Pad a flat portrait layout [dc, tau, (loc, mloc, wid, mwid, amp,
    mamp)*g] to ngauss_pad frozen zero-amplitude components.  Returns
    (padded_params, ngauss)."""
    params = np.asarray(params, float)
    ngauss = (len(params) - 2) // 6
    if ngauss > ngauss_pad:
        raise ValueError(f"cannot pad {ngauss} components into "
                         f"{ngauss_pad}")
    out = np.zeros(2 + 6 * ngauss_pad)
    out[:len(params)] = params
    for ig in range(ngauss, ngauss_pad):
        out[2 + 6 * ig: 8 + 6 * ig] = [0.5, 0.0, 0.02, 0.0, 0.0, 0.0]
    return out, ngauss


def portrait_bounds(ngauss_pad, nbin):
    """(lower, upper) over the concatenated [theta, alpha_s] vector of
    a padded joinless portrait problem (the fit_gaussian_portrait
    bounds; alpha free)."""
    nmain = 2 + 6 * ngauss_pad
    n = nmain + 1
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    lower[4:nmain:6] = 0.5 / nbin
    upper[4:nmain:6] = wid_max
    lower[6:nmain:6] = 0.0
    return lower, upper


def portrait_vary(fit_flags, ngauss_pad, fit_scattering_index=False):
    """vary mask over [theta_padded, alpha_s]: the portrait-layout
    fit_flags (2 + 6*ngauss entries) for the real components, pad
    components frozen."""
    fit_flags = np.asarray(fit_flags, bool)
    nmain = 2 + 6 * ngauss_pad
    vary = np.zeros(nmain + 1, bool)
    vary[:len(fit_flags)] = fit_flags
    vary[-1] = bool(fit_scattering_index)
    return vary


def fit_gaussian_portraits_batched(data, x0s, errs, varys, freqs,
                                   nu_refs, Ps, model_code="000",
                                   nchan_valid=None, max_iter=200,
                                   serial=False,
                                   compact_every=COMPACT_EVERY_CONFIG):
    """Fit B padded joinless portrait problems (the template factory's
    bucket dispatch).

    data (B, nchan, nbin): portraits with pad channels zero; errs
    (B, nchan) with pad channels +inf (an infinite error makes the
    padded residual row and its Jacobian EXACTLY zero, IEEE finite/inf);
    x0s (B, nmain+1) concatenated [theta_padded, alpha_s]; varys
    (B, nmain+1); freqs (B, nchan) with pad channels edge-replicated;
    nchan_valid (B,) true channel counts (restores dof under padding).
    serial=True runs the same problems through the single-problem
    engine (the host oracle)."""
    data = np.asarray(data, float)
    B, nchan, nbin = data.shape
    x0s = np.asarray(x0s, float)
    nmain = x0s.shape[1] - 1
    ngauss_pad = (nmain - 2) // 6
    lower, upper = portrait_bounds(ngauss_pad, nbin)
    errs = np.asarray(errs, float)
    freqs = np.asarray(freqs, float)
    nu_refs = np.broadcast_to(np.asarray(nu_refs, float), (B,))
    Ps = np.broadcast_to(np.asarray(Ps, float), (B,))
    if nchan_valid is None:
        nres_valid = None
    else:
        nres_valid = np.asarray(nchan_valid, int) * nbin
    resid, resid_jac = _portrait_fns(model_code, nbin, 0, nmain)
    join_mask = np.zeros((B, 0, nchan), bool)
    if serial:
        return _serial_lm(resid,
                          lambda b: (jnp.asarray(data[b]),
                                     jnp.asarray(errs[b]),
                                     jnp.asarray(freqs[b]),
                                     jnp.asarray(nu_refs[b]),
                                     jnp.asarray(Ps[b]),
                                     jnp.asarray(join_mask[b])),
                          x0s, lower, upper, varys, max_iter,
                          nres_valid=nres_valid, jacobian=resid_jac)
    return levenberg_marquardt_batched(
        resid, x0s, aux=(data, errs, freqs, nu_refs, Ps, join_mask),
        lower=lower, upper=upper, vary=np.asarray(varys),
        max_iter=max_iter, nres_valid=nres_valid, jacobian=resid_jac,
        # min_rows=1: see fit_gaussian_profiles_batched
        compact_every=resolve_compact_every(compact_every),
        compact_min_rows=1)
