"""Template factory (ISSUE 9): fleet-batched model building vs the
host-serial oracle and the single-pulsar driver, telemetry events, env
hooks, the spline mean-profile hook, and degenerate-input handling —
all at tiny shapes (tier-1 runs near its cap)."""

import os

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io.gmodel import model_to_flat, read_gmodel
from pulseportraiture_tpu.pipeline import build_templates
from pulseportraiture_tpu.pipeline.gauss import GaussPortrait
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

NCHAN, NBIN = 8, 64
MAX_NG = 2
NITER = 1


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    root = tmp_path_factory.mktemp("factory")
    files = []
    for i in range(2):
        p = str(root / f"psr{i}.fits")
        make_fake_pulsar(default_test_model(1500.0),
                         {"PSR": f"FAKE{i}", "P0": 0.003 + 0.001 * i,
                          "DM": 20.0 + i, "PEPOCH": 56000.0},
                         outfile=p, nsub=2, nchan=NCHAN, nbin=NBIN,
                         nu0=1500.0, bw=600.0, tsub=60.0,
                         start_MJD=MJD(55100 + i, 0.3),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=30 + i)
        files.append(p)
    return root, files


@pytest.fixture(scope="module")
def built(fleet):
    """One serial + one batched factory run shared by the assertions
    below (compiles dominate the cost at these shapes)."""
    root, files = fleet
    out_s, out_b = str(root / "serial"), str(root / "batched")
    trace = str(root / "trace.jsonl")
    res_s = build_templates(files, outdir=out_s, max_ngauss=MAX_NG,
                            niter=NITER, gauss_device=False, quiet=True)
    res_b = build_templates(files, outdir=out_b, max_ngauss=MAX_NG,
                            niter=NITER, gauss_device=True, quiet=True,
                            telemetry=trace)
    return root, files, out_s, out_b, trace, res_s, res_b


def _params(path):
    m = read_gmodel(path, quiet=True)
    return model_to_flat(m)[0], float(m.alpha)


class TestFactoryGauss:
    def test_batched_matches_serial_oracle(self, built):
        """The acceptance gate: batched-lane .gmodel digit-identical
        (<= 1e-10) to the host-serial oracle on every pulsar."""
        root, files, out_s, out_b, _, res_s, res_b = built
        for f, rs, rb in zip(files, res_s, res_b):
            base = os.path.basename(f)
            ps, al_s = _params(os.path.join(out_s, base + ".gmodel"))
            pb, al_b = _params(os.path.join(out_b, base + ".gmodel"))
            assert np.max(np.abs(ps - pb)) <= 1e-10
            assert abs(al_s - al_b) <= 1e-10
            assert rs.ngauss == rb.ngauss
            assert rs.iters == rb.iters

    def test_matches_single_pulsar_driver(self, built):
        """The factory's serial lane reproduces the single-pulsar
        make_gaussian_model pipeline (same breadth-first profile fit,
        same iteration/rotation bookkeeping; padding is the only
        difference and contributes exactly zero)."""
        root, files, out_s, _, _, res_s, _ = built
        f = files[0]
        dp = GaussPortrait(f, quiet=True)
        single_out = str(root / "single.gmodel")
        dp.make_gaussian_model(niter=NITER, writemodel=True,
                               outfile=single_out, quiet=True)
        # the single driver's auto_fit_profile defaults max_ngauss=8;
        # rebuild with the factory's trial budget for a like-for-like
        dp2 = GaussPortrait(f, quiet=True)
        dp2.auto_fit_profile(max_ngauss=MAX_NG, quiet=True)
        dp2.make_gaussian_model(niter=NITER, writemodel=True,
                                outfile=single_out, quiet=True)
        ps, al_s = _params(single_out)
        pf, al_f = _params(os.path.join(
            out_s, os.path.basename(f) + ".gmodel"))
        assert np.max(np.abs(ps - pf)) <= 1e-8
        assert abs(al_s - al_f) <= 1e-8

    def test_telemetry_events_and_report(self, built):
        root, files, _, _, trace, _, res_b = built
        manifest, events = telemetry.validate_trace(trace)
        assert manifest["config"]["gauss_device"] is not None
        etypes = [e["type"] for e in events]
        assert "template_fit" in etypes
        assert "factory_end" in etypes
        tfit = [e for e in events if e["type"] == "template_fit"]
        stages = {e["stage"] for e in tfit}
        assert stages == {"profile", "portrait"}
        for e in tfit:
            assert e["rows"] >= 1 and e["pad"] >= 0
            assert e["wall_s"] >= 0 and e["nfev_max"] >= 1
            assert e["batched"] is True
            # ISSUE 14: every dispatch names its Jacobian source
            assert e["jac"] == "analytic"
        assert manifest["config"]["lm_jacobian"] == "auto"
        assert "fit_fused" in manifest["config"]
        jobs = [e for e in events if e["type"] == "template_job"]
        assert len(jobs) == len(files)
        import io

        buf = io.StringIO()
        summary = telemetry.report(trace, file=buf)
        assert summary["n_template_fit"] == len(tfit)
        assert summary["n_template_jobs"] == len(files)
        assert summary["template_pad_frac"] is not None
        assert summary["template_wall_s"] > 0
        assert "template factory" in buf.getvalue()

    def test_refuses_metafile_and_bad_inputs(self, fleet, tmp_path):
        root, files = fleet
        meta = tmp_path / "meta.txt"
        meta.write_text("\n".join(files) + "\n")
        with pytest.raises(ValueError, match="metafile"):
            build_templates([str(meta)], quiet=True)
        with pytest.raises(ValueError, match="no datafiles"):
            build_templates([], quiet=True)
        with pytest.raises(ValueError, match="max_ngauss"):
            build_templates(files, max_ngauss=0, quiet=True)
        with pytest.raises(ValueError, match="kind"):
            build_templates(files, kind="wavelet", quiet=True)
        with pytest.raises(ValueError, match="one entry per"):
            build_templates(files, kind=["gauss"], quiet=True)


class TestFactorySpline:
    @pytest.mark.slow
    def test_spline_jobs_ride_the_batched_profile_lane(self, fleet):
        """kind='spline': the S/N-weighted mean profile is smoothed by
        the fleet's batched Gaussian fit and injected through
        make_spline_model(smooth_mean_prof=...)."""
        root, files = fleet
        out = str(root / "spl")
        res = build_templates([files[0]], kind="spline", outdir=out,
                              max_ngauss=MAX_NG, gauss_device=True,
                              quiet=True,
                              spline_kwargs={"snr_cutoff": 50.0})
        assert len(res) == 1
        assert res[0].kind == "spline"
        assert os.path.exists(res[0].outfile)
        from pulseportraiture_tpu.io.splmodel import read_spline_model

        m = read_spline_model(res[0].outfile, quiet=True)
        assert m.mean_prof.shape == (NBIN,)

    def test_smooth_mean_prof_hook(self, fleet, rng):
        """make_spline_model uses an injected smoothed mean verbatim
        and validates its shape."""
        root, files = fleet
        from pulseportraiture_tpu.pipeline.spline import SplinePortrait

        dp = SplinePortrait(files[0], quiet=True)
        injected = np.linspace(0.0, 1.0, NBIN)
        dp.make_spline_model(smooth=True, smooth_mean_prof=injected,
                             snr_cutoff=50.0, quiet=True)
        assert np.array_equal(dp.smooth_mean_prof, injected)
        dp2 = SplinePortrait(files[0], quiet=True)
        with pytest.raises(ValueError, match="smooth_mean_prof"):
            dp2.make_spline_model(smooth=True, quiet=True,
                                  smooth_mean_prof=np.zeros(NBIN + 2))


class TestDegenerateInputs:
    def test_auto_fit_profile_max_ngauss_validation(self, fleet):
        """The ISSUE 9 satellite: max_ngauss < 1 raises a loud
        ValueError naming the argument instead of dying with TypeError
        at best[1]."""
        root, files = fleet
        dp = GaussPortrait(files[0], quiet=True)
        with pytest.raises(ValueError, match="max_ngauss"):
            dp.auto_fit_profile(max_ngauss=0)
        with pytest.raises(ValueError, match="max_ngauss"):
            dp.auto_fit_profile(max_ngauss=-3)


class TestEnvHooks:
    def test_ppt_gauss_device_env(self, monkeypatch):
        saved = config.gauss_device
        try:
            for val, want in (("off", False), ("auto", "auto"),
                              ("on", True)):
                monkeypatch.setenv("PPT_GAUSS_DEVICE", val)
                assert "gauss_device" in config.env_overrides()
                assert config.gauss_device == want
            monkeypatch.setenv("PPT_GAUSS_DEVICE", "sometimes")
            with pytest.raises(ValueError, match="PPT_GAUSS_DEVICE"):
                config.env_overrides()
        finally:
            config.gauss_device = saved

    def test_new_knobs_registered(self):
        for name in ("PPT_GAUSS_DEVICE", "PPT_GAUSS_CACHE",
                     "PPT_NGAUSS"):
            assert name in config.KNOWN_PPT_ENV

    def test_ppt_lm_jacobian_env(self, monkeypatch):
        saved = config.lm_jacobian
        try:
            for val in ("auto", "analytic", "ad"):
                monkeypatch.setenv("PPT_LM_JACOBIAN", val)
                assert "lm_jacobian" in config.env_overrides()
                assert config.lm_jacobian == val
            monkeypatch.setenv("PPT_LM_JACOBIAN", "symbolic")
            with pytest.raises(ValueError, match="PPT_LM_JACOBIAN"):
                config.env_overrides()
        finally:
            config.lm_jacobian = saved

    def test_ppt_fit_fused_env(self, monkeypatch):
        saved = config.fit_fused
        try:
            for val, want in (("off", False), ("auto", "auto"),
                              ("on", True)):
                monkeypatch.setenv("PPT_FIT_FUSED", val)
                assert "fit_fused" in config.env_overrides()
                assert config.fit_fused == want
            monkeypatch.setenv("PPT_FIT_FUSED", "sometimes")
            with pytest.raises(ValueError, match="PPT_FIT_FUSED"):
                config.env_overrides()
        finally:
            config.fit_fused = saved

    def test_issue14_knobs_registered(self):
        for name in ("PPT_LM_JACOBIAN", "PPT_FIT_FUSED", "PPT_RETUNE"):
            assert name in config.KNOWN_PPT_ENV
        for key in ("lm_jacobian", "fit_fused"):
            assert key in telemetry.CONFIG_SNAPSHOT_KEYS


class TestAnalyticVsAdFactory:
    def test_zero_gmodel_selection_flips(self, fleet):
        """ISSUE 14 acceptance: the whole factory under the autodiff
        oracle vs the analytic Jacobian — ZERO component-count
        selection flips on the fleet, converged parameters far below
        the selection margins (the trajectory-level drift is ~ulp of
        J amplified by the iteration count, not the 1e-10 Jacobian
        gate — that one lives in test_lm_batched)."""
        root, files = fleet
        saved = config.lm_jacobian
        try:
            config.lm_jacobian = "ad"
            res_ad = build_templates(files, outdir=str(root / "j_ad"),
                                     max_ngauss=MAX_NG, niter=NITER,
                                     gauss_device=True, quiet=True)
            config.lm_jacobian = "analytic"
            res_an = build_templates(files, outdir=str(root / "j_an"),
                                     max_ngauss=MAX_NG, niter=NITER,
                                     gauss_device=True, quiet=True)
        finally:
            config.lm_jacobian = saved
        for ra, rb in zip(res_ad, res_an):
            assert ra.ngauss == rb.ngauss  # zero selection flips
            pa = model_to_flat(ra.model)[0]
            pb = model_to_flat(rb.model)[0]
            assert len(pa) == len(pb)
            assert np.max(np.abs(pa - pb)) < 1e-6
            assert abs(ra.model.alpha - rb.model.alpha) < 1e-6
