"""Zero-covariance reference frequencies for every fit-flag combination
of the reference's case table (pptoaslib.py:776-950).

Two independent validations, both f64 on CPU:

1. Property check (all combos, including the reference's polynomial-
   root cases): rebuild the parameter covariance from an AUTODIFF
   Hessian of the plain objective at the fitted point — fully
   independent of the engine's fused analytic Hessian and of
   _finalize_fit — transform to the infinite-frequency
   parameterization, and assert that the REPORTED nu_DM/nu_GM/nu_tau
   actually zero the corresponding covariances.  This is the defining
   property the closed forms encode.

2. Closed-form comparison (the weighted-mean cases {phi,DM}, {phi,GM},
   {tau,alpha}): the reference's analytic forms — a per-channel-
   Hessian-weighted mean frequency — evaluated from autodiff
   per-channel Hessians, compared to the engine's output at rtol 1e-6.

Documented divergence: for {phi,DM,GM} (and +tau) the reference
constrains nu_DM == nu_GM and zeroes ONLY Cov(phi, DM) via a
polynomial root (option 0; pptoaslib.py:822-935).  This engine instead
solves the exact 2x2 system for separate nu_DM, nu_GM zeroing BOTH
Cov(phi, DM) and Cov(phi, GM) — a strictly stronger decorrelation,
verified here by the property check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.config import Dconst
from pulseportraiture_tpu.fit import FitFlags, fit_portrait
from pulseportraiture_tpu.fit.portrait import _chi2_prime_X
from pulseportraiture_tpu.ops.noise import fourier_noise
from pulseportraiture_tpu.config import F0_fact
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NU_FIT = 1500.0
TAU_IN = 8e-3  # rotations at nu_ref
ALPHA_IN = -4.0


@pytest.fixture(scope="module")
def data():
    model = default_test_model(1500.0)
    freqs = jnp.asarray(np.linspace(1200.0, 1800.0, 48))
    d = fake_portrait(jax.random.PRNGKey(5), model, freqs, 512, P,
                      phi=0.0213, DM=0.004, GM=0.0, tau=TAU_IN,
                      alpha=ALPHA_IN, nu_ref=NU_FIT, noise_std=0.01,
                      dtype=jnp.float64)
    return d


def _theta_hat(r, log10_tau):
    """Reconstruct the internal fit-frame theta from a FitResult."""
    cD = Dconst / P
    cG = Dconst ** 2.0 / P
    r_tau = (float(r.nu_tau) / NU_FIT) ** float(r.alpha)
    tau_fit = float(r.tau) / r_tau
    th3 = np.log10(max(tau_fit, 1e-300)) if log10_tau else tau_fit
    phi_fit = (float(r.phi)
               + (cD * NU_FIT ** -2.0 - cD * float(r.nu_DM) ** -2.0)
               * float(r.DM)
               + (cG * NU_FIT ** -4.0 - cG * float(r.nu_GM) ** -4.0)
               * float(r.GM))
    phi_fit = (phi_fit + 0.5) % 1.0 - 0.5
    return jnp.asarray([phi_fit, float(r.DM), float(r.GM), th3,
                        float(r.alpha)])


def _spectra(d):
    port = jnp.asarray(d.port, jnp.float64)
    model = jnp.asarray(d.model_port, jnp.float64)
    noise = jnp.asarray(d.noise_stds, jnp.float64)
    nbin = port.shape[-1]
    dFT = jnp.fft.rfft(port, axis=-1)
    mFT = jnp.fft.rfft(model, axis=-1)
    errs_F = fourier_noise(noise, nbin)
    w = errs_F[:, None] ** -2.0 * jnp.where(
        jnp.arange(nbin // 2 + 1) == 0, F0_fact, 1.0)
    X = dFT * jnp.conj(mFT) * w
    M2 = (mFT.real ** 2 + mFT.imag ** 2) * w
    return X, M2


def _autodiff_covI(d, theta, flags, log10_tau):
    """Covariance in the infinite-frequency parameterization from an
    autodiff Hessian of the plain objective (independent oracle)."""
    X, M2 = _spectra(d)
    freqs = jnp.asarray(d.freqs, jnp.float64)

    def obj(t):
        return _chi2_prime_X(t, X, M2, freqs, P, NU_FIT, None, log10_tau)

    H = np.asarray(jax.hessian(obj)(theta))
    fa = np.asarray(FitFlags(*flags).as_array(jnp.float64))
    Hm = H * np.outer(fa, fa) + np.diag(1.0 - fa)
    cov = 2.0 * np.linalg.inv(Hm) * np.outer(fa, fa)
    cD_fit = (Dconst / P) * NU_FIT ** -2.0
    cG_fit = (Dconst ** 2.0 / P) * NU_FIT ** -4.0
    J = np.eye(5)
    J[0, 1] = -cD_fit
    J[0, 2] = -cG_fit
    return J @ cov @ J.T


def _fit(d, flags, log10_tau=True, **kw):
    return fit_portrait(d.port, d.model_port, d.noise_stds, d.freqs, P,
                        nu_fit=NU_FIT, fit_flags=FitFlags(*flags),
                        log10_tau=log10_tau, dtype=jnp.float64,
                        max_iter=60, **kw)


CASES = [
    # (flags, log10_tau, kwargs)
    ((True, True, False, False, False), False, {}),            # phi,DM
    ((True, False, True, False, False), False, {}),            # phi,GM
    ((False, False, False, True, True), True,
     dict(phi0=0.0213, DM0=0.004, tau0=TAU_IN, alpha0=ALPHA_IN)),
    ((True, True, False, True, False), True,
     dict(tau0=TAU_IN, alpha0=ALPHA_IN)),                      # phi,DM,tau
    ((True, True, True, False, False), False, {}),             # phi,DM,GM
    ((True, True, False, True, True), True,
     dict(tau0=TAU_IN, alpha0=ALPHA_IN)),                      # +alpha
    ((True, True, True, True, False), True,
     dict(tau0=TAU_IN, alpha0=ALPHA_IN)),                      # phi,DM,GM,tau
    ((True, True, True, True, True), True,
     dict(tau0=TAU_IN, alpha0=ALPHA_IN)),                      # all five
]


@pytest.mark.parametrize("flags,log10_tau,kw", CASES,
                         ids=["phi-DM", "phi-GM", "tau-alpha",
                              "phi-DM-tau", "phi-DM-GM",
                              "phi-DM-tau-alpha", "phi-DM-GM-tau",
                              "all-five"])
def test_nu_zero_property(data, flags, log10_tau, kw):
    """The reported reference frequencies zero the corresponding
    covariances of an independently (autodiff) rebuilt covariance."""
    d = data
    r = _fit(d, flags, log10_tau=log10_tau, **kw)
    assert int(r.return_code) in (0, 1, 2, 4)
    theta = _theta_hat(r, log10_tau)
    covI = _autodiff_covI(d, theta, flags, log10_tau)

    cD = (Dconst / P) * float(r.nu_DM) ** -2.0
    cG = (Dconst ** 2.0 / P) * float(r.nu_GM) ** -4.0
    u_phi = np.array([1.0, cD, cG, 0.0, 0.0])

    def corr(a, Ci, b):
        den = np.sqrt((a @ Ci @ a) * (b @ Ci @ b))
        return (a @ Ci @ b) / den

    if flags[0] and flags[1]:
        e = np.eye(5)[1]
        assert abs(corr(u_phi, covI, e)) < 1e-6, "Cov(phi, DM) != 0"
    if flags[0] and flags[2]:
        e = np.eye(5)[2]
        assert abs(corr(u_phi, covI, e)) < 1e-6, "Cov(phi, GM) != 0"
    if flags[3] and flags[4]:
        # log10 tau at nu: theta3' = theta3 + alpha log10(nu/nu_fit)
        u_tau = np.array([0.0, 0.0, 0.0, 1.0,
                          np.log10(float(r.nu_tau) / NU_FIT)])
        e = np.eye(5)[4]
        assert abs(corr(u_tau, covI, e)) < 1e-6, "Cov(tau', alpha) != 0"


def _per_channel_hessian(d, theta, log10_tau):
    """(nchan, 5, 5) per-channel Hessian of -C_n^2/S_n via autodiff."""
    X, M2 = _spectra(d)
    freqs = jnp.asarray(d.freqs, jnp.float64)

    def per_chan(t):
        from pulseportraiture_tpu.fit.portrait import _CS_general

        C, S = _CS_general(t, X, M2, freqs, P, NU_FIT, None, log10_tau)
        good = S > 0.0
        S_safe = jnp.where(good, S, 1.0)
        return -jnp.where(good, C ** 2.0 / S_safe, 0.0)

    return np.asarray(jax.jacfwd(jax.jacrev(per_chan))(theta))


@pytest.mark.slow  # ~29 s; the nu_DM zeroing property stays tier-1 via
# test_nu_zero_property[phi-DM], and the closed-form reference family
# keeps test_closed_form_phi_gm / test_closed_form_tau_alpha there
def test_closed_form_phi_dm(data):
    """Reference {phi, DM} weighted-mean form (pptoaslib.py:789-795):
    nu0 = (sum(nu^-2 W) / sum(W))^-1/2, W = H_phiDM_n/(nu^-2-nu_fit^-2)."""
    d = data
    r = _fit(d, (True, True, False, False, False), log10_tau=False)
    theta = _theta_hat(r, False)
    Hn = _per_channel_hessian(d, theta, False)
    freqs = np.asarray(d.freqs)
    W = Hn[:, 0, 1] / (freqs ** -2.0 - NU_FIT ** -2.0)
    nu0 = ((freqs ** -2.0 * W).sum() / W.sum()) ** -0.5
    assert float(r.nu_DM) == pytest.approx(nu0, rel=1e-6)


@pytest.mark.slow  # ~12 s; the closed-form family keeps
# test_closed_form_tau_alpha tier-1 and the property tests cover GM
def test_closed_form_phi_gm(data):
    """Reference {phi, GM} form (pptoaslib.py:796-803): nu^-4 weighted
    mean, power -1/4."""
    d = data
    r = _fit(d, (True, False, True, False, False), log10_tau=False)
    theta = _theta_hat(r, False)
    Hn = _per_channel_hessian(d, theta, False)
    freqs = np.asarray(d.freqs)
    W = Hn[:, 0, 2] / (freqs ** -4.0 - NU_FIT ** -4.0)
    nu0 = ((freqs ** -4.0 * W).sum() / W.sum()) ** -0.25
    assert float(r.nu_GM) == pytest.approx(nu0, rel=1e-6)


def test_closed_form_tau_alpha(data):
    """Reference {tau, alpha} form (pptoaslib.py:804-810):
    nu0 = exp(sum(ln(nu) W) / sum(W)), W = H_tau,alpha_n / ln(nu/nu_fit)."""
    d = data
    r = _fit(d, (False, False, False, True, True), log10_tau=True,
             phi0=0.0213, DM0=0.004, tau0=TAU_IN, alpha0=ALPHA_IN)
    theta = _theta_hat(r, True)
    Hn = _per_channel_hessian(d, theta, True)
    freqs = np.asarray(d.freqs)
    W = Hn[:, 3, 4] / np.log(freqs / NU_FIT)
    nu0 = np.exp((np.log(freqs) * W).sum() / W.sum())
    assert float(r.nu_tau) == pytest.approx(nu0, rel=1e-6)
