"""GetTOAs end-to-end: fake archives with known injected dDMs ->
wideband TOAs recover them (the reference's examples/example.py
verification flow, SURVEY §4)."""

import numpy as np
import pytest

from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.io.tim import write_TOAs
from pulseportraiture_tpu.pipeline import GetTOAs
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}
DDMS = [2e-4, -3e-4, 4e-4]


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("toas")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i, dDM in enumerate(DDMS):
        path = str(root / f"fake-{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=3, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.0, dDM=dDM,
                         start_MJD=MJD(55100 + 10 * i, 0.1),
                         noise_stds=0.08, dedispersed=False, quiet=True,
                         rng=100 + i)
        files.append(path)
    meta = root / "meta.txt"
    meta.write_text("\n".join(files) + "\n")
    return str(meta), gmodel, files


def test_metafile_and_gmodel_dispatch(dataset):
    meta, gmodel, files = dataset
    gt = GetTOAs(meta, gmodel, quiet=True)
    assert gt.datafiles == files
    assert gt.model.kind == "gmodel"


def test_get_toas_recovers_injected_ddms(dataset):
    meta, gmodel, files = dataset
    gt = GetTOAs(meta, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    assert len(gt.order) == 3
    assert len(gt.TOA_list) == 9  # 3 archives x 3 subints
    for i, dDM in enumerate(DDMS):
        # injected dDM recovered within 4 sigma and 2e-4 absolute
        assert gt.DeltaDM_means[i] == pytest.approx(
            dDM, abs=max(4 * gt.DeltaDM_errs[i], 2e-4))
        ok = gt.ok_isubs[i]
        assert np.all(np.isfinite(gt.phis[i][ok]))
        assert np.all(gt.snrs[i][ok] > 20)
        assert np.all(np.asarray(gt.rcs[i])[ok] >= 0)
    # the recovered phase at nu_DM is the dispersive delay of the
    # injected total DM at nu_DM minus the folding alignment at nu0
    # (the data is dispersed; injected achromatic phase was 0)
    from pulseportraiture_tpu.config import Dconst

    P = PAR["P0"]
    for i, dDM in enumerate(DDMS):
        ok = gt.ok_isubs[i]
        for isub in ok:
            nu_DM = gt.nu_refs[i][isub][0]
            expect = (Dconst * (PAR["DM"] + dDM) * nu_DM ** -2.0 / P
                      - Dconst * PAR["DM"] * 1500.0 ** -2.0 / P)
            expect = ((expect + 0.5) % 1.0) - 0.5
            got = gt.phis[i][isub]
            diff = ((got - expect + 0.5) % 1.0) - 0.5
            assert abs(diff) < 2e-3, (i, isub, got, expect)


def test_toa_flags_and_tim_output(dataset, tmp_path):
    meta, gmodel, files = dataset
    gt = GetTOAs(files[0], gmodel, quiet=True)
    gt.get_TOAs(quiet=True, print_phase=True,
                addtnl_toa_flags={"pta": "TEST"})
    toa = gt.TOA_list[0]
    for key in ("be", "fe", "f", "nbin", "nch", "nchx", "bw", "chbw",
                "subint", "tobs", "fratio", "tmplt", "snr", "gof",
                "phs", "phs_err", "pta", "phi_DM_cov"):
        assert key in toa.flags, key
    assert toa.DM is not None and toa.DM_error is not None
    assert toa.flags["nchx"] == 32
    out = tmp_path / "out.tim"
    write_TOAs(gt.TOA_list, outfile=str(out), append=False)
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 3
    assert "-pp_dm " in lines[0] and "-pta TEST" in lines[0]


def test_one_dm(dataset):
    meta, gmodel, files = dataset
    gt = GetTOAs(files[1], gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    gt.apply_one_DM()
    dms = {t.DM for t in gt.TOA_list}
    assert len(dms) == 1
    assert list(dms)[0] == pytest.approx(gt.DM0s[0] + gt.DeltaDM_means[0])
    assert gt.TOA_list[0].flags["one_DM"] == "True"


def test_zapped_channels_masked(dataset, tmp_path):
    """Archives with zapped channels still fit; masked channels get
    zero scales."""
    model = default_test_model(1500.0)
    w = np.ones((2, 32))
    w[:, :5] = 0.0
    path = str(tmp_path / "zap.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32, nbin=256,
                     tsub=60.0, noise_stds=0.08, weights=w,
                     dedispersed=False, quiet=True, rng=5)
    meta, gmodel, files = dataset
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    assert len(gt.TOA_list) == 2
    assert gt.TOA_list[0].flags["nchx"] == 27
    assert np.all(gt.scales[0][:, :5] == 0.0)
    assert np.all(np.isfinite(gt.DMs[0][gt.ok_isubs[0]]))


def test_narrowband_toas(dataset):
    meta, gmodel, files = dataset
    gt = GetTOAs(files[0], gmodel, quiet=True)
    gt.get_narrowband_TOAs(quiet=True)
    assert len(gt.TOA_list) == 3 * 32
    t = gt.TOA_list[0]
    assert "chan" in t.flags and t.DM is None
    assert t.TOA_error < 100.0  # us


def test_channels_to_zap_flags_corrupted(dataset, tmp_path):
    model = default_test_model(1500.0)
    path = str(tmp_path / "rfi.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=32, nbin=256,
                     tsub=60.0, noise_stds=0.05, dedispersed=False,
                     quiet=True, rng=11)
    # corrupt one channel with junk after generation
    from pulseportraiture_tpu.io.psrfits import read_archive

    arch = read_archive(path)
    rng = np.random.default_rng(0)
    arch.amps[0, 0, 10] += 10.0 * rng.standard_normal(256)
    arch.unload(path)
    meta, gmodel, files = dataset
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    zaps = gt.get_channels_to_zap(SNR_threshold=5.0, rchi2_threshold=2.0)
    assert 10 in zaps[0][0]
    assert len(zaps[0][0]) <= 4  # does not flag the whole band


def test_crosscheck_toas_agree_with_wideband(dataset):
    """The independent time-domain CCF estimator must agree with the
    harmonic-domain Newton fit at the few-bin-error level (the role of
    the reference's get_psrchive_TOAs cross-check, pptoas.py:1191)."""
    meta, gmodel, files = dataset
    gt = GetTOAs(files[0], gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    gt2 = GetTOAs(files[0], gmodel, quiet=True)
    toas = gt2.get_crosscheck_TOAs(quiet=True)
    assert len(toas) == 3
    assert toas[0].flags["alg"] == "ccf-parabolic"
    from pulseportraiture_tpu.config import Dconst

    P = PAR["P0"]
    for j, isub in enumerate(gt.ok_isubs[0]):
        # re-reference the wideband TOA (at its nu_DM) to the
        # crosscheck's nu0 via the fitted DM: t(nu) = t_inf +
        # Dconst*DM/nu^2 seconds
        nu_DM = float(gt.nu_refs[0][isub][0])
        nu0 = toas[j].frequency
        shift = Dconst * float(gt.DMs[0][isub]) * (nu0 ** -2.0
                                                   - nu_DM ** -2.0)
        t_wb = gt.TOAs[0][isub]
        t_cc = toas[j].MJD
        dt_sec = ((t_wb.day - t_cc.day) * 86400.0
                  + (t_wb.frac - t_cc.frac) * 86400.0 + shift)
        dphi = (dt_sec / P) % 1.0
        dphi = min(dphi, 1.0 - dphi)
        # independent estimators: allow a few phase bins (nbin=256)
        assert dphi < 10.0 / 256.0, (isub, dphi)


def test_instrumental_response_plumbed(dataset):
    """Enabling the instrumental-response config changes the model the
    fit sees but leaves the TOAs nearly unchanged for thin channels."""
    meta, gmodel, files = dataset
    gt = GetTOAs(files[0], gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    gt_ir = GetTOAs(files[0], gmodel, quiet=True)
    gt_ir.instrumental_response_dict["DM-smear"] = True
    gt_ir.get_TOAs(quiet=True)
    ok = gt.ok_isubs[0]
    assert np.all(np.isfinite(gt_ir.phis[0][ok]))
    # each run references phi to its own nu_DM — compare at a common
    # frequency; DM-smearing kernels are symmetric so the phase budge
    # should be small
    from pulseportraiture_tpu.ops import phase_transform

    P = PAR["P0"]
    for isub in ok:
        a = float(phase_transform(gt.phis[0][isub], gt.DMs[0][isub],
                                  gt.nu_refs[0][isub][0], 1500.0, P))
        b = float(phase_transform(gt_ir.phis[0][isub], gt_ir.DMs[0][isub],
                                  gt_ir.nu_refs[0][isub][0], 1500.0, P))
        d = abs(a - b) % 1.0
        assert min(d, 1.0 - d) < 2e-3, (isub, a, b)
    # wide boxcar smearing must actually change the fit
    gt_w = GetTOAs(files[0], gmodel, quiet=True)
    gt_w.instrumental_response_dict["wids"].append(0.05)
    gt_w.instrumental_response_dict["irf_types"].append("rect")
    gt_w.get_TOAs(quiet=True)
    assert np.all(np.isfinite(gt_w.phis[0][ok]))
    assert not np.allclose(gt_w.snrs[0][ok], gt.snrs[0][ok])


def test_fast_fit_routing_matches_reference(dataset):
    """config.use_fast_fit=True routes no-scattering pipeline fits
    through the complex-free f32 fast path; TOAs must agree with the
    complex f64 reference path to well under a phase bin."""
    from pulseportraiture_tpu import config

    meta, gmodel, files = dataset
    old = config.use_fast_fit
    try:
        # pin the baseline to the complex path even on TPU hosts, so
        # this never compares the fast path against itself
        config.use_fast_fit = False
        gt = GetTOAs(files[0], gmodel, quiet=True)
        gt.get_TOAs(quiet=True)
        config.use_fast_fit = True
        gt_f = GetTOAs(files[0], gmodel, quiet=True)
        gt_f.get_TOAs(quiet=True)
    finally:
        config.use_fast_fit = old
    ok = gt.ok_isubs[0]
    from pulseportraiture_tpu.ops import phase_transform

    P = PAR["P0"]
    for isub in ok:
        a = float(phase_transform(gt.phis[0][isub], gt.DMs[0][isub],
                                  gt.nu_refs[0][isub][0], 1500.0, P))
        b = float(phase_transform(gt_f.phis[0][isub], gt_f.DMs[0][isub],
                                  gt_f.nu_refs[0][isub][0], 1500.0, P))
        d = abs(a - b) % 1.0
        assert min(d, 1.0 - d) < 1e-4, (isub, a, b)
    assert np.allclose(gt_f.DMs[0][ok], gt.DMs[0][ok], atol=1e-5)
    assert np.all(np.isfinite(gt_f.snrs[0][ok]))


def test_fast_routing_scat_degenerate_subint(dataset, tmp_path):
    """A fit_scat run with a 1-good-channel subint must not crash when
    fast routing is enabled: the degenerate phase-only group carries a
    nonzero log10-tau seed, which the fast path cannot represent, so it
    must fall back to the scattering-capable engine."""
    from pulseportraiture_tpu import config

    model = default_test_model(1500.0)
    w = np.ones((2, 32))
    w[0, 1:] = 0.0  # subint 0: single good channel
    path = str(tmp_path / "degen.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32, nbin=256,
                     tsub=60.0, noise_stds=0.08, weights=w,
                     dedispersed=False, quiet=True, rng=7)
    meta, gmodel, files = dataset
    old = config.use_fast_fit
    try:
        config.use_fast_fit = True
        gt = GetTOAs(path, gmodel, quiet=True)
        gt.get_TOAs(fit_scat=True, quiet=True)
    finally:
        config.use_fast_fit = old
    ok = gt.ok_isubs[0]
    assert len(gt.TOA_list) == len(ok)
    assert np.all(np.isfinite(gt.phis[0][ok]))


def test_narrowband_scattering_fit(dataset, tmp_path):
    """Per-channel (phi, tau) narrowband fits — the capability the
    reference stubbed out (pptoas.py:1046-1049) — recover an injected
    scattering timescale."""
    model = default_test_model(1500.0)
    t_scat = 2e-4  # seconds at nu0=1500; P=4.074 ms -> ~0.05 rot
    path = str(tmp_path / "scat.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16, nbin=256,
                     nu0=1500.0, bw=200.0, tsub=60.0, noise_stds=0.02,
                     t_scat=t_scat, alpha=-4.0, dedispersed=False,
                     quiet=True, rng=3)
    meta, gmodel, files = dataset
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.get_narrowband_TOAs(fit_scat=True, quiet=True)
    assert len(gt.TOA_list) == 2 * 16
    P = PAR["P0"]
    # per-channel expected tau: t_scat * (nu/1500)^-4
    by_chan = {}
    for t in gt.TOA_list:
        assert "scat_time" in t.flags
        by_chan.setdefault(round(t.frequency, 3), []).append(
            t.flags["scat_time"] * 1e-6)  # us -> s
    ratios = []
    for nu, vals in by_chan.items():
        expect = t_scat * (nu / 1500.0) ** -4.0
        got = np.median(vals)
        ratios.append(got / expect)
    # recover tau within 25% in the median across the band
    assert 0.75 < np.median(ratios) < 1.25, ratios


def test_prefetch_identical_results(dataset):
    """prefetch=True (IO/compute overlap) must not change any result."""
    meta, gmodel, files = dataset
    gt = GetTOAs(meta, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    gt_p = GetTOAs(meta, gmodel, quiet=True)
    gt_p.get_TOAs(prefetch=True, quiet=True)
    assert gt_p.order == gt.order
    for i in range(len(gt.order)):
        np.testing.assert_array_equal(gt_p.phis[i], gt.phis[i])
        np.testing.assert_array_equal(gt_p.DMs[i], gt.DMs[i])
    assert len(gt_p.TOA_list) == len(gt.TOA_list)
