"""Content-addressed result cache (ISSUE 17 tentpole; ROADMAP 5(a)).

Real wideband-timing traffic is heavy-tailed: the same (archive,
template, options) triple arrives many times, and the TOA measurement
is a pure function of exactly those inputs.  The codec already
serializes per-request ``.tim`` payloads byte-exactly
(:func:`~.codec.write_tim_result` / :func:`~.codec.read_tim_result`),
so a cache hit can be byte-identical to a fresh fit *by construction*
— this module turns that into an O(1) fast path for repeat requests:

- **Key** — SHA-256 over the request's CONTENT: every archive file's
  bytes, the template/model file's bytes, the frozen fit-option
  snapshot (the same canonical form the server's lane cache keys on),
  and the numeric config tri-states that can alter output bytes
  (:data:`NUMERIC_CONFIG_KEYS`).  Any one-byte input perturbation or
  option flip produces a different key — content addressing stays
  honest.  The datafile paths are hashed too because the ``.tim``
  payload embeds them (completion sentinels carry absolute paths), so
  identical bytes under a different path must not alias.
- **Value** — the request's ``.tim`` payload, written with the codec's
  atomic temp-then-``os.replace`` discipline; a hit is served by an
  atomic byte copy of the stored entry, so hit output == fresh-fit
  output at the byte level.  Template-factory artifacts (``.gmodel`` /
  ``.spl``) store through the same store as opaque blobs.
- **Store** — a bounded on-disk LRU under ``config.cache_dir`` sized
  by ``config.cache_max_mb``; least-recently-USED entries evict first
  (hits refresh recency).  Torn entries — a truncated ``.tim`` missing
  its completion sentinels, or a blob whose length header disagrees —
  are treated as a MISS and deleted, never a crash.
- **Wiring** — the router checks the cache before placement (a hit
  never touches a host); the server checks at ``submit`` (catching
  single-host deployments) and populates when a clean fit completes.
  Per-tenant accounting charges hits and fits separately: a hit is
  visible to the admission ledger (``AdmissionQueue.record_hit``) but
  never billed against the tenant quota or the weighted-fair vtime.

Resolution follows the tri-state idiom: ``config.result_cache`` is
``off`` / ``'auto'`` / ``on`` (env ``PPT_RESULT_CACHE``, CLI
``--result-cache``); ``'auto'`` — the default — engages only when
``config.cache_dir`` is set, so the cache is off out of the box.
"""

import hashlib
import os
import threading

import numpy as np

from ..telemetry import NULL_TRACER
from . import codec

__all__ = ["ResultCache", "content_key", "resolve_result_cache",
           "NUMERIC_CONFIG_KEYS"]

# Config knobs that can (or are gated never to, but conservatively
# might) alter the bytes of a fitted .tim: device/fusion tri-states,
# precision selections, and the quality-loop thresholds.  They join
# the content key so flipping any of them invalidates instead of
# serving bytes fitted under a different numeric regime.  Serving /
# transport / telemetry knobs are deliberately absent — they cannot
# change result bytes, and keying on them would only shed hits.
NUMERIC_CONFIG_KEYS = (
    "dft_precision", "cross_spectrum_dtype", "dft_fold",
    "use_fast_fit", "use_matmul_dft", "fit_harmonic_window",
    "harmonic_window_tail", "scatter_compensated", "fit_fused",
    "fit_pallas", "fused_block", "lm_jacobian", "raw_subbyte",
    "bucket_pad", "zap_nstd", "quality_refit", "quality_max_gof",
    "quality_min_snr",
)

# Blob entries (template-factory artifacts) carry their own torn-entry
# detection: a fixed magic plus an explicit payload length, verified on
# read — a truncated file is a miss, never a half-artifact.
_BLOB_MAGIC = b"PPTBLOB1\n"


def _freeze(v):
    """Hashable canonical form of an option value (lists/dicts arrive
    from JSON request specs) — the same form the server's lane cache
    keys on, shared here so the content key and the lane key can never
    disagree about what an 'option change' is."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, np.ndarray):
        return (v.shape, v.tobytes())
    return v


def content_key(files, options):
    """SHA-256 hex digest over the CONTENT of a request: each file's
    absolute path and full bytes (archives + template/model), the
    frozen option snapshot, and the byte-relevant config knobs.
    Raises OSError if any input file is unreadable — callers fall back
    to the fit path, which reports the real error."""
    from .. import config

    h = hashlib.sha256()
    for path in files:
        p = os.path.abspath(str(path))
        h.update(b"\x00file\x00" + p.encode("utf-8", "surrogateescape"))
        with open(p, "rb") as fh:
            while True:
                chunk = fh.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
    frozen = tuple(sorted(
        (str(k), _freeze(v)) for k, v in dict(options or {}).items()))
    h.update(b"\x00options\x00" + repr(frozen).encode())
    knobs = tuple((k, getattr(config, k, None))
                  for k in NUMERIC_CONFIG_KEYS)
    h.update(b"\x00config\x00" + repr(knobs).encode())
    return h.hexdigest()


class ResultCache:
    """Bounded on-disk LRU of content-addressed ``.tim`` results and
    opaque artifact blobs.

    One directory, flat layout: ``<key>.tim`` for TOA results,
    ``<key>.blob`` for factory artifacts.  Writes are atomic
    (temp-then-``os.replace``); recency is tracked in-process and
    mirrored to file mtimes so a re-opened cache resumes an
    approximate LRU order.  All methods are thread-safe.
    """

    def __init__(self, cache_dir, max_mb=None, tracer=None):
        from .. import config

        self.dir = os.path.abspath(str(cache_dir))
        os.makedirs(self.dir, exist_ok=True)
        self.max_bytes = int(
            float(config.cache_max_mb if max_mb is None else max_mb)
            * 1e6)
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._lock = threading.Lock()
        # fname -> size, in LRU order (oldest first); seeded from the
        # directory so a restarted process inherits the prior store
        self._entries = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bytes_served = 0
        self.bytes_stored = 0
        try:
            found = []
            for fn in os.listdir(self.dir):
                if not fn.endswith((".tim", ".blob")):
                    continue
                fp = os.path.join(self.dir, fn)
                try:
                    st = os.stat(fp)
                except OSError:
                    continue
                found.append((st.st_mtime, fn, st.st_size))
            for _, fn, size in sorted(found):
                self._entries[fn] = size
        except OSError:
            pass

    # ------------------------------------------------------------------
    # internals (call with self._lock held unless noted)
    # ------------------------------------------------------------------

    def _path(self, fname):
        return os.path.join(self.dir, fname)

    def _touch(self, fname):
        """Refresh LRU recency: reinsert at the back, mirror to mtime
        (best-effort) so a future process sees the same order."""
        size = self._entries.pop(fname, None)
        if size is None:
            try:
                size = os.path.getsize(self._path(fname))
            except OSError:
                return
        self._entries[fname] = size
        try:
            os.utime(self._path(fname))
        except OSError:
            pass

    def _drop(self, fname, evict=False):
        size = self._entries.pop(fname, 0)
        try:
            os.unlink(self._path(fname))
        except OSError:
            pass
        if evict:
            self.evictions += 1
            if self.tracer.enabled:
                self.tracer.emit("cache_evict", key=fname, bytes=size)
                self.tracer.counter("cache_evict")

    def _account(self, fname, size):
        """Register a freshly stored entry and evict least-recently-used
        entries until the store fits ``max_bytes`` again."""
        self._entries.pop(fname, None)
        self._entries[fname] = size
        self.bytes_stored += size
        if size > self.max_bytes:
            # the entry ALONE can never fit: refuse it up front —
            # evicting the whole store to then drop it anyway would
            # trade every cached result for nothing
            self._drop(fname, evict=True)
            return
        total = sum(self._entries.values())
        while total > self.max_bytes and len(self._entries) > 1:
            oldest = next(iter(self._entries))
            if oldest == fname:  # never evict the entry just stored
                break
            total -= self._entries.get(oldest, 0)
            self._drop(oldest, evict=True)

    # ------------------------------------------------------------------
    # .tim results
    # ------------------------------------------------------------------

    def get_result(self, key, datafiles):
        """Look up a ``.tim`` result.  Returns ``(result, entry_path,
        n_bytes)`` on a hit — ``result`` is the recovered
        :class:`~..utils.bunch.DataBunch` (``recovered_from_tim`` shape:
        summary stats are not re-derived) and ``entry_path`` the stored
        file whose bytes ARE the fresh-fit bytes — or None on a miss.
        A torn entry (missing completion sentinels for any of
        ``datafiles``, or an unparseable tail) counts as a miss and is
        deleted."""
        fname = f"{key}.tim"
        path = self._path(fname)
        with self._lock:
            known = fname in self._entries or os.path.exists(path)
            if not known:
                self.misses += 1
                return None
            try:
                if not codec.tim_complete(path, datafiles):
                    raise ValueError("incomplete sentinel set")
                result = codec.read_tim_result(path)
                n_bytes = os.path.getsize(path)
            except (OSError, ValueError):
                # torn / truncated / foreign entry: a miss, never a
                # crash — and drop it so it cannot mislead again
                self._drop(fname)
                self.misses += 1
                return None
            self._touch(fname)
            self.hits += 1
            self.bytes_served += n_bytes
            return result, path, n_bytes

    def put_result(self, key, result):
        """Store a completed request's ``.tim`` payload.  Returns the
        stored byte count, or None when the result cannot be cached
        (skipped archives, ambiguous demux, write failure) — callers
        treat None as 'not cached', never an error."""
        if getattr(result, "n_skipped", 0):
            return None  # partial results write fewer sentinels
        if getattr(result, "recovered_from_tim", False):
            return None  # only cache fresh in-memory fits
        fname = f"{key}.tim"
        path = self._path(fname)
        try:
            codec.write_tim_result(result, path)  # atomic tmp+replace
            size = os.path.getsize(path)
        except (OSError, ValueError):
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        with self._lock:
            self._account(fname, size)
            if fname not in self._entries:  # evicted as oversize
                return None
        return size

    # ------------------------------------------------------------------
    # opaque blobs (template-factory artifacts)
    # ------------------------------------------------------------------

    def get_blob(self, key):
        """Look up an artifact blob; bytes on a hit, None on a miss.
        A length-header mismatch (torn entry) is a miss and deletes."""
        fname = f"{key}.blob"
        path = self._path(fname)
        with self._lock:
            if fname not in self._entries and not os.path.exists(path):
                self.misses += 1
                return None
            try:
                with open(path, "rb") as fh:
                    magic = fh.read(len(_BLOB_MAGIC))
                    header = fh.read(16)
                    payload = fh.read()
                if magic != _BLOB_MAGIC or len(header) != 16:
                    raise ValueError("bad blob header")
                if int(header.decode(), 16) != len(payload):
                    raise ValueError("torn blob")
            except (OSError, ValueError):
                self._drop(fname)
                self.misses += 1
                return None
            self._touch(fname)
            self.hits += 1
            self.bytes_served += len(payload)
            return payload

    def put_blob(self, key, data):
        """Store an artifact blob atomically; returns the stored byte
        count (None on failure)."""
        data = bytes(data)
        fname = f"{key}.blob"
        path = self._path(fname)
        tmp = path + ".tmp~"
        try:
            with open(tmp, "wb") as fh:
                fh.write(_BLOB_MAGIC)
                fh.write(f"{len(data):016x}".encode())
                fh.write(data)
            os.replace(tmp, path)
            size = os.path.getsize(path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        with self._lock:
            self._account(fname, size)
            if fname not in self._entries:
                return None
        return size


def resolve_result_cache(tracer=None, cache_dir=None, mode=None,
                         max_mb=None):
    """Resolve the tri-state ``config.result_cache`` knob into a
    :class:`ResultCache` or None (cache off).

    - ``False`` / ``'off'`` — None;
    - ``'auto'`` (the default) — a cache iff ``config.cache_dir`` is
      set, so the cache is OFF out of the box;
    - ``True`` / ``'on'`` — a cache; raises ValueError LOUDLY when no
      cache directory is configured (an explicitly-on cache silently
      doing nothing would be a lie).

    ``cache_dir`` / ``mode`` / ``max_mb`` override the config globals
    (used by per-instance server/router arguments and tests).
    """
    from .. import config

    mode = config.result_cache if mode is None else mode
    cache_dir = config.cache_dir if cache_dir is None else cache_dir
    if isinstance(mode, str):
        mode = mode.lower()
    table = {False: False, "off": False, "false": False, "0": False,
             True: True, "on": True, "true": True, "1": True,
             "auto": "auto", None: False}
    if mode not in table:
        raise ValueError(
            f"config.result_cache={mode!r}: expected off|auto|on "
            "(False | 'auto' | True)")
    mode = table[mode]
    if mode is False:
        return None
    if mode == "auto" and not cache_dir:
        return None
    if not cache_dir:
        raise ValueError(
            "config.result_cache='on' requires config.cache_dir "
            "(PPT_CACHE_DIR / --cache-dir): an explicitly-on cache "
            "with nowhere to store entries would silently serve "
            "nothing")
    return ResultCache(cache_dir, max_mb=max_mb, tracer=tracer)
