"""Harmonic-window (band-limited fast fit) validation: the window
derivation, the knob resolution rules, and parity of the truncated fit
against the full-spectrum fit (chi2/dof stay full-spectrum via the
Parseval Sd).  Round-4 feature; reference evaluates all harmonics
unconditionally (pptoaslib.py:564-614)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import FitFlags
from pulseportraiture_tpu.fit.portrait import (
    fit_portrait_batch_fast,
    model_harmonic_window,
    resolve_harmonic_window,
)
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NCHAN, NBIN = 64, 2048
FREQS = jnp.asarray(np.linspace(1200.0, 1999.0, NCHAN) + 0.5, jnp.float32)


def _data(key, **kw):
    model = default_test_model(1500.0)
    kw.setdefault("noise_std", 0.05)
    kw.setdefault("dtype", jnp.float32)
    return fake_portrait(key, model, FREQS, NBIN, P, **kw)


def test_window_derivation(key):
    d = _data(key)
    K = model_harmonic_window(np.asarray(d.model_port), NBIN)
    assert K is not None and K % 128 == 0
    assert 128 <= K <= 512  # smooth Gaussian template: narrow support
    # the window must actually cover the model power to the config tail
    spec = np.abs(np.fft.rfft(np.asarray(d.model_port), axis=-1)) ** 2
    tail = spec[:, K:].sum(axis=-1) / spec.sum(axis=-1)
    assert tail.max() < 1e-12


def test_window_white_template_stays_full(rng):
    white = rng.standard_normal((8, NBIN))
    assert model_harmonic_window(white, NBIN) is None


def test_window_ignores_dc_offset(key):
    """The tail criterion is DC-free (the fit zeroes harmonic 0, so a
    baseline offset carries no fit weight): a huge constant offset must
    not change the derived window.  Pre-fix, (n*mu)^2 inflated the
    denominator and loosened the criterion by ~1e6 here, silently
    truncating real model support."""
    d = _data(key)
    mp = np.asarray(d.model_port, np.float64)
    K0 = model_harmonic_window(mp, NBIN)
    K_off = model_harmonic_window(mp + 300.0, NBIN)
    assert K0 is not None and K_off == K0


def test_resolve_rejects_nonpositive_and_bad_strings(key):
    d = _data(key)
    mp = np.asarray(d.model_port)
    with pytest.raises(ValueError):
        resolve_harmonic_window(0, mp, NBIN)
    with pytest.raises(ValueError):
        resolve_harmonic_window(-5, mp, NBIN)
    with pytest.raises(ValueError):
        resolve_harmonic_window("Auto", mp, NBIN)
    # True means 'auto' (enable), never int(True) = K=128
    assert resolve_harmonic_window(True, mp, NBIN) \
        == resolve_harmonic_window("auto", mp, NBIN)


def test_parseval_sd_survives_baseline_offset(key):
    """The Parseval Sd uses the mean-removed power form: the naive
    n*sum(x^2) - X_0^2 cancels catastrophically in f32 at offset >>
    sigma (3x-wrong power at mu/sigma ~ 5e3), while the mean-removed
    form tracks the f64 truth.  (At such offsets the FULL-spectrum
    lane's own f32 spectral Sd degrades too — every dr_k matmul
    cancels the offset — so the oracle here is f64, not the full
    lane.)"""
    from pulseportraiture_tpu.fit.portrait import (_parseval_Sd,
                                                   make_weights)

    d = _data(key)
    port = jnp.asarray(np.asarray(d.port) + 500.0, jnp.float32)
    w = make_weights(d.noise_stds, NBIN, dtype=jnp.float32)
    got = float(_parseval_Sd(port, w))
    # f64 truth: one-sided spectral power, DC excluded, same weights
    spec = np.abs(np.fft.rfft(np.asarray(port, np.float64), axis=-1))**2
    want = float((np.asarray(w, np.float64)[..., 1:]
                  * spec[..., 1:]).sum())
    assert abs(got - want) < 1e-5 * want, (got, want)


@pytest.mark.slow  # ~26 s windowed-vs-full parity sweep (tier-1
# budget, r19): the window also carries in-bench chi2 gates and the
# lighter truncated-fit tests above stay in tier-1
def test_truncated_fit_parity_with_moderate_offset(key):
    """Fit-level chi2 parity with a baseline offset within the full
    lane's own f32 accuracy envelope (~100x the noise)."""
    d = _data(key, phi=0.04, DM=0.003)
    port = d.port + 5.0
    args = (port[None], d.model_port[None], d.noise_stds[None],
            FREQS, P, 1500.0)
    rf = fit_portrait_batch_fast(*args, harmonic_window=False)
    rt = fit_portrait_batch_fast(*args, harmonic_window=256)
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 5e-7
    assert np.allclose(rf.chi2, rt.chi2, rtol=2e-3), \
        (float(rf.chi2[0]), float(rt.chi2[0]))


def test_window_derivation_batched_model_chunks(key):
    """3-D batched models derive the same window as their 2-D slices
    (the chunked host path)."""
    d = _data(key)
    mp = np.asarray(d.model_port, np.float32)
    batched = np.stack([mp] * 5)
    assert model_harmonic_window(batched, NBIN) \
        == model_harmonic_window(mp, NBIN)


def test_resolve_rules(key):
    d = _data(key)
    mp = np.asarray(d.model_port)
    # config default 'auto': host model derives, device model does not
    assert resolve_harmonic_window(None, mp, NBIN) is not None
    assert resolve_harmonic_window(None, d.model_port, NBIN) is None
    # explicit int is tile-rounded; full-width requests collapse to None
    assert resolve_harmonic_window(200, None, NBIN) == 256
    assert resolve_harmonic_window(NBIN // 2 + 1, None, NBIN) is None
    assert resolve_harmonic_window(False, mp, NBIN) is None


def test_truncated_fit_parity(key):
    """Band-limited fit == full fit to rounding: the estimator is
    model-weighted, so harmonics beyond the model's support contribute
    nothing; chi2/dof must still count the full spectrum."""
    d = _data(key, phi=0.123, DM=0.004)
    K = model_harmonic_window(np.asarray(d.model_port), NBIN)
    args = (d.port[None], d.model_port[None], d.noise_stds[None],
            FREQS, P, 1500.0)
    rf = fit_portrait_batch_fast(*args, harmonic_window=False)
    rt = fit_portrait_batch_fast(*args, harmonic_window=K)
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 2e-7
    assert abs(float(rf.DM[0]) - float(rt.DM[0])) < 1e-7
    assert np.allclose(rf.phi_err, rt.phi_err, rtol=1e-4)
    assert np.allclose(rf.DM_err, rt.DM_err, rtol=1e-4)
    assert np.allclose(rf.snr, rt.snr, rtol=1e-5)
    # chi2: spectral sum vs time-domain Parseval — same value, both
    # f32-rounded over ~1e5 terms
    assert np.allclose(rf.chi2, rt.chi2, rtol=1e-3)
    assert int(rf.dof[0]) == int(rt.dof[0])
    # the fit must still recover the injection exactly as well
    assert abs(float(rt.phi[0]) - 0.123) < 1e-3


@pytest.mark.slow  # ~24 s scattering-lane window parity (tier-1
# budget, r19): bench_scatter gates the windowed scattering fit
# in-bench; the cheap window-shape tests above stay in tier-1
def test_truncated_scatter_fit_parity(key):
    """The scattering lane honors the window too (the scattering
    kernel only multiplies the template spectrum — never widens it —
    so the unscattered template's window is valid for every tau)."""
    model = default_test_model(1500.0)
    true_tau = 2e-4
    d = fake_portrait(key, model, FREQS, NBIN, P, tau=true_tau,
                      alpha=-4.0, noise_std=2e-3, dtype=jnp.float32)
    th0 = np.zeros((1, 5), np.float32)
    th0[0, 3] = np.log10(0.5 / NBIN)
    th0[0, 4] = -4.0
    flags = FitFlags(True, True, False, True, False)
    kw = dict(fit_flags=flags, theta0=jnp.asarray(th0), log10_tau=True,
              max_iter=60)
    args = (d.port[None], d.model_port[None], d.noise_stds[None],
            FREQS, P, 1500.0)
    rf = fit_portrait_batch_fast(*args, harmonic_window=False, **kw)
    rt = fit_portrait_batch_fast(*args, harmonic_window=384, **kw)
    assert abs(float(rf.tau[0]) - float(rt.tau[0])) \
        < 2e-4 * float(rf.tau[0])
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 1e-6
    # chi2 = Sd + f cancels catastrophically in f32 at this extreme
    # S/N (both lanes report the same noise-dominated value; the tight
    # chi2 parity check lives in test_truncated_fit_parity at sane
    # S/N) — only require agreement at the f32 cancellation scale
    assert np.allclose(rf.chi2, rt.chi2, rtol=2e-2)
    assert int(rf.dof[0]) == int(rt.dof[0])
    # recovery against the injection through the windowed lane
    expect = (true_tau / P) * (float(rt.nu_tau[0]) / 1500.0) ** -4.0
    assert abs(float(rt.tau[0]) - expect) / expect < 3e-3


@pytest.mark.parametrize("masked", [False, True])
def test_truncated_fit_masked_channels(key, masked):
    d = _data(key, phi=-0.07, DM=0.002)
    mask = jnp.ones((1, NCHAN), jnp.float32)
    if masked:
        mask = mask.at[:, ::4].set(0.0)
    args = dict(chan_masks=mask)
    rf = fit_portrait_batch_fast(
        d.port[None], d.model_port[None], d.noise_stds[None], FREQS, P,
        1500.0, harmonic_window=False, **args)
    rt = fit_portrait_batch_fast(
        d.port[None], d.model_port[None], d.noise_stds[None], FREQS, P,
        1500.0, harmonic_window=256, **args)
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 5e-7
    assert np.allclose(rf.chi2, rt.chi2, rtol=1e-3)
    assert int(rf.dof[0]) == int(rt.dof[0])


def test_window_noise_floor_engages_on_noisy_template(key, rng):
    """A data-built template carries a white Fourier noise floor far
    above harmonic_window_tail: the floor-aware criterion must still
    derive a window K << nharm (the absolute criterion alone pins it
    at full spectrum, silently forfeiting the win on the workload the
    framework targets)."""
    d = _data(key)
    mp = np.asarray(d.model_port, np.float64)
    for s in (1e-3, 1e-2, 3e-2):
        noisy = mp + rng.standard_normal(mp.shape) * s
        assert model_harmonic_window(noisy, NBIN, floor_sigma=0) is None
        K = model_harmonic_window(noisy, NBIN)
        assert K is not None and K <= 512, (s, K)


def test_window_clean_narrow_template_not_mistaken_for_floor():
    """A clean ultra-narrow template's spectrum is still DECAYING
    through the top quarter — genuine signal, not a white floor.  The
    flatness test (top two eighths within 2x) must refuse the
    subtraction so the floor-aware criterion reduces exactly to the
    absolute one (which keeps the full spectrum here: real power at
    1e-4 relative lives near Nyquist, 8 orders above the tail)."""
    x = (np.arange(NBIN) + 0.5) / NBIN
    narrow = np.exp(-0.5 * ((x - 0.3) / 0.0005) ** 2)
    narrow = np.repeat(narrow[None, :], 8, axis=0)
    assert model_harmonic_window(narrow, NBIN) \
        == model_harmonic_window(narrow, NBIN, floor_sigma=0) is None


def test_window_flat_spectrum_template_stays_full(rng):
    """A genuinely flat-spectrum template (delta pulse) must NOT be
    mistaken for a noise floor: its 'plateau' holds ~all the power, so
    the >10%-of-total guard disables subtraction and the window stays
    full."""
    delta = np.zeros((8, NBIN))
    delta[:, 100] = 1.0
    assert model_harmonic_window(delta, NBIN) is None
    # white noise likewise survives the floor-aware criterion
    assert model_harmonic_window(
        rng.standard_normal((8, NBIN)), NBIN) is None


def test_window_noisy_template_fit_parity_and_recovery(key, rng):
    """Fit-level gates for the floor-aware window on a noisy template:
    windowed vs full parity inside the |dphi| < 1e-4 driver gate, error
    bars unchanged, and truth recovery NOT degraded (truncation drops
    pure-noise template harmonics, so the windowed fit may only do
    better)."""
    from pulseportraiture_tpu.ops.phasor import phase_transform

    s = 0.01
    dphi_f, dphi_t = [], []
    for trial in range(4):
        d = _data(jax.random.PRNGKey(500 + trial), phi=0.04, DM=0.003)
        noisy = (np.asarray(d.model_port, np.float64)
                 + rng.standard_normal((NCHAN, NBIN)) * s)
        noisy = jnp.asarray(noisy, jnp.float32)
        K = model_harmonic_window(np.asarray(noisy), NBIN)
        assert K is not None
        args = (d.port[None], noisy[None], d.noise_stds[None],
                FREQS, P, 1500.0)
        rf = fit_portrait_batch_fast(*args, harmonic_window=False)
        rt = fit_portrait_batch_fast(*args, harmonic_window=K)
        assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 1e-4
        assert abs(float(rf.DM[0]) - float(rt.DM[0])) < 1e-3
        assert np.allclose(rf.phi_err, rt.phi_err, rtol=1e-3)
        assert int(rf.dof[0]) == int(rt.dof[0])
        for r, acc in ((rf, dphi_f), (rt, dphi_t)):
            ph = phase_transform(float(r.phi[0]), float(r.DM[0]),
                                 float(r.nu_DM[0]), d.nu_ref, P)
            acc.append((ph - 0.04 + 0.5) % 1.0 - 0.5)
    # truth recovery: windowed rms no worse than full-spectrum rms
    # (measured: ~2x BETTER at this template noise level)
    assert np.sqrt(np.mean(np.square(dphi_t))) \
        <= 1.5 * np.sqrt(np.mean(np.square(dphi_f)))


def test_window_noisy_template_bf16_calibration(key, rng):
    """The floor-aware window composes with the bf16 cross-spectrum
    default: windowed bf16 fit still matches the full-spectrum f32 fit
    inside the driver gate on a noisy template."""
    from pulseportraiture_tpu import config

    d = _data(key, phi=0.04, DM=0.003)
    noisy = (np.asarray(d.model_port, np.float64)
             + rng.standard_normal((NCHAN, NBIN)) * 0.01)
    noisy = jnp.asarray(noisy, jnp.float32)
    K = model_harmonic_window(np.asarray(noisy), NBIN)
    args = (d.port[None], noisy[None], d.noise_stds[None],
            FREQS, P, 1500.0)
    rf = fit_portrait_batch_fast(*args, harmonic_window=False)
    old = config.cross_spectrum_dtype
    try:
        config.cross_spectrum_dtype = "bfloat16"
        rt = fit_portrait_batch_fast(*args, harmonic_window=K)
    finally:
        config.cross_spectrum_dtype = old
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 1e-4
    assert np.allclose(rf.phi_err, rt.phi_err, rtol=5e-3)


@pytest.mark.slow
def test_window_engages_on_pipeline_built_spline_model(tmp_path):
    """End-to-end: a spline model built by the ACTUAL pipeline from a
    noisy synthetic archive (ppspline path, smoothing off so the
    template keeps its measured noise floor) must derive a real window
    — this is the workload the framework exists for, and the absolute
    criterion alone resolves it to full spectrum (K=None), silently
    forfeiting the round-4 speedup.  Also gates windowed-vs-full fit
    parity on that template."""
    from pulseportraiture_tpu.pipeline.spline import (
        DataPortrait as SplinePortrait)
    from pulseportraiture_tpu.synth import make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    PAR = {"PSR": "J1909-3744", "RAJ": "19:09:47.4",
           "DECJ": "-37:44:14.5", "P0": 0.002947, "PEPOCH": 55000.0,
           "DM": 10.391}
    nbin = 1024
    model = default_test_model(1500.0)
    path = str(tmp_path / "avg.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=32,
                     nbin=nbin, nu0=1500.0, bw=800.0, tsub=1800.0,
                     noise_stds=0.02, dedispersed=True,
                     start_MJD=MJD(55200, 0.3), quiet=True, rng=21)
    dp = SplinePortrait(path, quiet=True)
    dp.normalize_portrait("prof")
    dp.make_spline_model(max_ncomp=4, smooth=False, snr_cutoff=50.0,
                         quiet=True)
    mp = np.asarray(dp.model)
    # unsmoothed data-built template: absolute criterion gives up...
    assert model_harmonic_window(mp, nbin, floor_sigma=0) is None
    # ...the floor-aware one derives a real window (half spectrum here)
    K = model_harmonic_window(mp, nbin)
    assert K is not None and K <= 256, K
    # windowed fit on THIS template stays inside the driver gate
    freqs = jnp.asarray(dp.freqs[0], jnp.float32)
    port = jnp.asarray(dp.port, jnp.float32)
    mdl = jnp.asarray(mp, jnp.float32)
    ns = jnp.asarray(dp.noise_stds[0], jnp.float32)
    Pd = float(dp.Ps[0])
    args = (port[None], mdl[None], ns[None], freqs, Pd,
            float(freqs.mean()))
    rf = fit_portrait_batch_fast(*args, harmonic_window=False)
    rt = fit_portrait_batch_fast(*args, harmonic_window=K)
    assert abs(float(rf.phi[0]) - float(rt.phi[0])) < 1e-4
    assert np.allclose(rf.phi_err, rt.phi_err, rtol=1e-2)


@pytest.mark.slow
def test_window_engages_on_pipeline_built_gauss_model(tmp_path):
    """The OTHER template factory: a ppgauss-built model is analytic
    (generated from fitted Gaussian parameters), so the absolute
    criterion already engages — this locks the window DERIVATION for
    both pipeline template types (windowed-vs-full FIT parity on
    analytic templates is covered by test_truncated_fit_parity; the
    noisy-template fit gates live in the spline sibling test)."""
    from pulseportraiture_tpu.pipeline.gauss import (
        DataPortrait as GaussPortrait)
    from pulseportraiture_tpu.synth import make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    PAR = {"PSR": "J1909-3744", "RAJ": "19:09:47.4",
           "DECJ": "-37:44:14.5", "P0": 0.002947, "PEPOCH": 55000.0,
           "DM": 10.391}
    nbin = 1024
    model = default_test_model(1500.0)
    path = str(tmp_path / "avg.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=24,
                     nbin=nbin, nu0=1500.0, bw=800.0, tsub=1800.0,
                     noise_stds=0.02, dedispersed=True,
                     start_MJD=MJD(55200, 0.3), quiet=True, rng=21)
    dp = GaussPortrait(path, quiet=True)
    dp.make_gaussian_model(ref_prof=(1500.0, 200.0), niter=2,
                           auto_gauss=0.05, quiet=True)
    K = model_harmonic_window(np.asarray(dp.model), nbin)
    K_abs = model_harmonic_window(np.asarray(dp.model), nbin,
                                  floor_sigma=0)
    assert K is not None and K <= 384, K
    assert K_abs is not None  # analytic model: no floor needed
