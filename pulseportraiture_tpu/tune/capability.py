"""Per-backend capability table + the ONE tri-state resolver
(ISSUE 19 tentpole, layer 1; ROADMAP item 5b).

Before this module every performance tri-state in config.py —
``fit_fused``, ``fit_pallas``, ``bucket_pad``, the ``*_device`` knobs,
``dft_fold``, ``use_matmul_dft`` — resolved its ``'auto'`` arm with a
private ``jax.default_backend() == "tpu"`` spelling, scattered across
nine modules.  One rule, nine drifting copies.  This module collapses
them:

- :func:`resolve_auto` is the single resolution point for every
  ``'auto'`` tri-state.  Each knob declares its *polarity* in
  :data:`KNOB_POLARITY` (``'tpu'``: 'auto' engages the fast arm on
  TPU backends; ``'not_tpu'``: inverted — e.g. ``dft_fold``, whose
  fold trick pays only where the matmul DFT does NOT).  A source-scan
  test (tests/test_tune.py) asserts no ``== "tpu"`` spelling survives
  outside this package, so the rule cannot drift again.

- :func:`capability_record` derives a per-backend
  :class:`CapabilityRecord` once per process from the live
  ``jax.devices()``: platform, device kind, Pallas availability,
  preferred cross-spectrum dtype, sub-byte unpack support, plus
  cheap *measured* probes (dispatch floor, tiny matmul/DFT
  throughput).  The record is keyed by :func:`backend_fingerprint`
  (platform + device kind + jax version) — the same key the tuning
  DB (tune/store.py) uses, so persisted winners are never applied to
  a different backend than the one that measured them.

Import discipline: this module imports ONLY jax + stdlib.  config.py,
ops/*, fit/* all call into here, so importing any of them back would
cycle.  ``jax.default_backend()`` is read LIVE on every
:func:`resolve_auto` call (never cached): tests monkeypatch it on the
shared jax module object to exercise both polarities from a CPU host.
"""

import threading
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["KNOB_POLARITY", "CapabilityRecord", "backend_fingerprint",
           "capability_record", "capability_summary", "resolve_auto"]

# knob -> polarity of its 'auto' arm.  'tpu': auto means ON for TPU
# backends; 'not_tpu': auto means ON everywhere EXCEPT TPU.  Every
# tri-state in config.py appears here; adding a knob without a row is
# a loud KeyError at its first 'auto' resolution, not silent drift.
KNOB_POLARITY = {
    # fit engine lanes
    "fit_fused": "tpu",         # fused DFT->cross-spectrum program
    "fit_pallas": "tpu",        # Pallas kernels (compiled lane)
    "fast_fit": "tpu",          # device-resident fast fit default
    "use_matmul_dft": "tpu",    # matmul DFT vs jnp.fft
    "dft_fold": "not_tpu",      # fold trick pays where matmul DFT off
    # device-vs-host stage placement
    "gauss_device": "tpu",
    "align_device": "tpu",
    "gls_device": "tpu",
    "zap_device": "tpu",
    # pipeline layout / kernel mode
    "bucket_pad": "tpu",        # pow2 bucket lattice coarsening
    "pallas_interpret": "not_tpu",  # interpret-mode Pallas off-TPU
    "device_f32": "tpu",        # preferred on-device real dtype lane
    "noise_matmul_cumsum": "tpu",   # triangular-matmul cumsum spelling
}


class CapabilityRecord(NamedTuple):
    """What one backend can do + what it measures (one per process).

    The static fields come from the device table; the ``*_s`` /
    ``*_gflops`` fields are tiny live probes (a handful of dispatches,
    ~ms total) and are None until :func:`capability_record` is called
    with ``probe=True`` (the default) — callers that only need the
    static table (e.g. the serve stat wire) pass ``probe=False``
    on the first call to skip them entirely."""

    fingerprint: str
    platform: str           # jax.default_backend(): 'cpu'/'gpu'/'tpu'
    device_kind: str        # jax.devices()[0].device_kind
    n_devices: int
    pallas_available: bool  # jax.experimental.pallas importable
    preferred_cross_dtype: str   # cross-spectrum accumulation dtype
    subbyte_unpack: bool    # native sub-byte (int4) unpack lanes
    dispatch_floor_s: Optional[float]   # measured per-dispatch floor
    matmul_gflops: Optional[float]      # tiny f32 matmul probe
    dft_gflops: Optional[float]         # tiny rfft probe

    def wire_summary(self):
        """The JSON-safe subset a serving host reports over the
        ``stat`` wire op (serve/server.stats)."""
        return {"fingerprint": self.fingerprint,
                "platform": self.platform,
                "device_kind": self.device_kind,
                "n_devices": self.n_devices,
                "pallas_available": self.pallas_available,
                "matmul_gflops": self.matmul_gflops}


def backend_fingerprint():
    """Stable identity of THIS process's backend: platform + device
    kind + jax version.  The tuning DB key — winners measured on one
    fingerprint are refused (loudly) on any other."""
    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    return f"{jax.default_backend()}:{kind}:jax-{jax.__version__}"


def _pallas_available():
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except Exception:
        return False


def _probe_dispatch_floor(nrun=3, K=4):
    """Min-of-N slope of a trivial dispatch — the per-dispatch floor
    in seconds (profiling.devtime's estimator, inlined to keep this
    module free of package imports)."""
    import time

    x = jnp.zeros((8,), jnp.float32)
    f = jax.jit(lambda v: v + 1.0)
    f(x).block_until_ready()  # compile outside the clock
    best = None
    for _ in range(nrun):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        y = x
        for _ in range(K):
            y = f(y)
        y.block_until_ready()
        tK = time.perf_counter() - t0
        slope = (tK - t1) / (K - 1)
        if slope <= 0.0:
            slope = tK / K
        best = slope if best is None else min(best, slope)
    return best


def _probe_matmul_gflops(n=256, nrun=3):
    import time

    a = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda m: m @ m)
    f(a).block_until_ready()
    best = None
    for _ in range(nrun):
        t0 = time.perf_counter()
        f(a).block_until_ready()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return (2.0 * n ** 3 / max(best, 1e-9)) / 1e9


def _probe_dft_gflops(nchan=64, nbin=512, nrun=3):
    import time

    x = jnp.ones((nchan, nbin), jnp.float32)
    f = jax.jit(lambda v: jnp.fft.rfft(v, axis=-1))
    f(x).block_until_ready()
    best = None
    for _ in range(nrun):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    import math

    flops = 5.0 * nchan * nbin * math.log2(max(nbin, 2))
    return (flops / max(best, 1e-9)) / 1e9


_cache_lock = threading.Lock()
_cached = {}   # fingerprint -> CapabilityRecord


def capability_record(probe=True):
    """The process-wide :class:`CapabilityRecord` for the live
    backend, derived once per fingerprint and cached.  ``probe=False``
    skips the timing probes on a cold cache (fields stay None); a
    later ``probe=True`` call upgrades the cached record in place."""
    fp = backend_fingerprint()
    with _cache_lock:
        rec = _cached.get(fp)
    if rec is not None and (rec.dispatch_floor_s is not None
                            or not probe):
        return rec
    platform = jax.default_backend()
    devs = jax.devices()
    kind = devs[0].device_kind if devs else "none"
    rec = CapabilityRecord(
        fingerprint=fp,
        platform=platform,
        device_kind=kind,
        n_devices=len(devs),
        pallas_available=_pallas_available(),
        # TPU MXUs accumulate the cross-spectrum fastest in f32
        # (complex64); wide hosts keep the f64 reference spelling
        preferred_cross_dtype=("complex64" if platform == "tpu"
                               else "complex128"),
        subbyte_unpack=platform == "tpu",
        dispatch_floor_s=_probe_dispatch_floor() if probe else None,
        matmul_gflops=_probe_matmul_gflops() if probe else None,
        dft_gflops=_probe_dft_gflops() if probe else None,
    )
    with _cache_lock:
        _cached[fp] = rec
    return rec


def capability_summary():
    """JSON-safe record summary for the stat wire (static fields only
    on first call — the serving loop must not pay probe latency in a
    stat handler)."""
    return capability_record(probe=False).wire_summary()


def resolve_auto(knob, setting, label=None):
    """THE tri-state resolver: ``True``/``False`` pass through,
    ``'auto'`` (string, case/space-insensitive) resolves through
    :data:`KNOB_POLARITY`, anything else raises the knob's strict
    ValueError (``label`` overrides the knob name in the message so
    call sites keep their historical spellings, e.g.
    ``config.dft_fold``)."""
    if setting is True or setting is False:
        return setting
    is_auto = setting == "auto" or (
        isinstance(setting, str) and setting.strip().lower() == "auto")
    if not is_auto:
        raise ValueError(
            f"{label or knob} must be True, False, or 'auto'; got "
            f"{setting!r}")
    polarity = KNOB_POLARITY[knob]
    on_tpu = jax.default_backend() == "tpu"
    return on_tpu if polarity == "tpu" else not on_tpu
