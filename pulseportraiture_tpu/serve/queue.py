"""Admission queue + request objects for the TOA serving loop.

The queue is the BACKPRESSURE story of the service (ISSUE 8): it is
bounded in ARCHIVES (the unit of admission work — one archive is one
load + prepare + bucket fill), and a submit that would exceed the
bound raises :class:`ServeRejected` LOUDLY instead of absorbing
unbounded host memory.  Clients retry, shed load, or raise
``config.serve_queue_depth``; the server never silently queues more
than it agreed to.  Device-side concurrency is bounded separately by
the executor's ``max_inflight``/``pipeline_depth`` — the admission
bound only governs what the host has promised to prepare.

A :class:`ServeRequest` is one client submission: a batch of archives
measured against one template with one option set.  Its lifecycle is
submit -> admit (the server loads + buckets its archives; subints from
different requests coalesce into shared fused dispatches) -> done (the
per-request ``.tim``/result is demultiplexed back out).  ``result()``
blocks the submitting client; the server thread resolves it.

Multi-tenant QoS (ISSUE 13): requests carry a ``tenant`` label and the
queue keeps one FIFO lane per tenant.  ``get`` serves lanes
WEIGHTED-FAIR over archives (each lane's virtual time is archives
admitted / its ``config.serve_tenant_weight``; the lane furthest
behind goes next, and a lane waking from idle starts at the current
virtual time so it cannot burst on banked credit) — a bulk campaign
tenant can saturate the queue without starving a small interactive
tenant.  ``config.serve_tenant_quota`` additionally caps any one
tenant's pending archives below the global bound, so one tenant can
never occupy the whole admission queue in the first place; a submit
over its tenant quota is rejected retryably exactly like global
backpressure, but the message names the tenant and the knob.
"""

import itertools
import threading
import time

from ..obs.trace import new_trace_id

__all__ = ["ServeRejected", "ServeRequest", "AdmissionQueue"]


class ServeRejected(RuntimeError):
    """A submission the server did NOT accept: the admission queue is
    at capacity (backpressure — ``retryable`` is True, retry later or
    shed load) or the server is stopping/closed (``retryable`` False —
    resubmitting can never succeed).  Nothing about the request was
    enqueued."""

    def __init__(self, msg, retryable=False):
        super().__init__(msg)
        self.retryable = bool(retryable)


class ServeRequest:
    """One client submission to the serving loop.

    datafiles: archive paths (or a metafile path); modelfile: the
    template; options: make_wideband_lane kwargs (fit_scat=, DM0=,
    print_flux=, ...) — requests sharing (modelfile, options) share a
    lane and therefore coalesce into the same fused buckets; tim_out:
    optional path the server writes this request's .tim to (archive
    order, completion sentinels — byte-identical to the one-shot
    driver's checkpoint).  The server fills the bookkeeping fields;
    clients call :meth:`result`.
    """

    _ids = itertools.count()

    def __init__(self, datafiles, modelfile, options=None, tim_out=None,
                 name=None, tenant=None, trace_id=None):
        from ..pipeline.toas import _is_metafile, _read_metafile

        if isinstance(datafiles, str):
            self.datafiles = (_read_metafile(datafiles)
                              if _is_metafile(datafiles)
                              else [datafiles])
        else:
            self.datafiles = list(datafiles)
        if not self.datafiles:
            raise ValueError("ServeRequest: empty datafile list")
        self.modelfile = str(modelfile)
        self.options = dict(options or {})
        self.tim_out = tim_out
        self.name = str(name) if name is not None else \
            f"req{next(ServeRequest._ids)}"
        # QoS lane label: requests of one tenant share a weighted-fair
        # admission lane and a pending-archive quota
        self.tenant = str(tenant) if tenant is not None else "default"
        # distributed-tracing context (ISSUE 20): minted by the router
        # (or here for direct clients), stamped into every telemetry
        # event this request touches on any host
        self.trace_id = str(trace_id) if trace_id else new_trace_id()
        # lifecycle timestamps (monotonic): submit by the queue, admit/
        # done by the server — what the request_done latency split and
        # the pptrace serve section report
        self.t_submit = None
        self.t_admit = None
        self.t_done = None
        # server-side demux state: archive position -> (meta, assembly)
        self.meta = {}
        self.assembled = {}
        self.n_skipped = 0
        self.all_admitted = False
        # archive positions already sent through the quality-gated
        # zap-and-refit loop (server-side; the EXACTLY-ONCE bound —
        # a position in here never refits again)
        self.refit_pos = set()
        self._event = threading.Event()
        self._result = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        """Block up to ``timeout`` seconds for the server to resolve
        this request; True when resolved (result() will not block),
        False on timeout.  Unlike :meth:`result` this never raises —
        it is the polling primitive remote transports build on."""
        return self._event.wait(timeout)

    def result(self, timeout=None):
        """Block until the server resolves this request; returns the
        per-request DataBunch (TOA_list, order, DM0s, DeltaDM_means/
        errs, tim_out) or raises the server-side failure."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"{self.name}: no result within {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result


class AdmissionQueue:
    """Bounded, thread-safe request queue feeding one serving loop,
    with per-tenant weighted-fair lanes and quotas.

    ``submit`` (any client thread) appends or REJECTS — it never
    blocks, so a client can tell load-shedding from slowness.  ``get``
    (the server thread) pops with a timeout so the serving loop keeps
    ticking its deadline flushes while idle.  The archive-count
    accounting is released as the server admits each archive
    (:meth:`release`), i.e. the bound covers submitted-but-not-yet-
    prepared work.

    tenant_quota: None (global bound only), an int (every tenant's
    pending-archive cap), or a dict {tenant: cap} with an optional
    ``'*'`` default — ``config.serve_tenant_quota`` /
    PPT_SERVE_TENANT_QUOTA.  tenant_weight: {tenant: weight} (``'*'``
    default; unlisted tenants weigh 1.0) —
    ``config.serve_tenant_weight`` / PPT_SERVE_TENANT_WEIGHT.
    """

    def __init__(self, max_pending, tenant_quota=None,
                 tenant_weight=None):
        from .. import config

        self.max_pending = max(1, int(max_pending))
        if tenant_quota is None:
            tenant_quota = config.serve_tenant_quota
        if tenant_weight is None:
            tenant_weight = config.serve_tenant_weight
        self.tenant_quota = tenant_quota
        self.tenant_weight = dict(tenant_weight or {})
        self._cv = threading.Condition()
        self._lanes = {}           # tenant -> [requests] (FIFO)
        self._pending = 0          # archives, global
        self._pending_tenant = {}  # tenant -> archives pending
        self._served = {}          # tenant -> archives ever popped
        self._hits = {}            # tenant -> cache-hit archives
        self._closed = False

    # -- QoS resolution ------------------------------------------------

    def _quota_for(self, tenant):
        q = self.tenant_quota
        if q is None:
            return None
        if isinstance(q, dict):
            q = q.get(tenant, q.get("*"))
            return None if q is None else int(q)
        return int(q)

    def _weight_for(self, tenant):
        w = self.tenant_weight.get(tenant,
                                   self.tenant_weight.get("*", 1.0))
        return max(float(w), 1e-9)

    def _vtime(self, tenant):
        """A lane's virtual time: archives admitted over its weight —
        the weighted-fair scheduler serves the lane furthest behind."""
        return self._served.get(tenant, 0) / self._weight_for(tenant)

    def __len__(self):
        with self._cv:
            return sum(len(q) for q in self._lanes.values())

    @property
    def pending_archives(self):
        with self._cv:
            return self._pending

    def load_snapshot(self):
        """One lock-held snapshot of (queue_len, pending_archives).

        ``len(q)`` and ``q.pending_archives`` are two separate lock
        acquisitions — a stat/metrics reply built from both can report
        TORN load (a submit landing between the reads shows its
        archives but not its queue entry, or vice versa).  Every
        stat-shaped reply must read load through here (ISSUE 20
        satellite)."""
        with self._cv:
            return (sum(len(q) for q in self._lanes.values()),
                    self._pending)

    def tenant_snapshot(self):
        """{tenant: {queued, pending_archives, cache_hits}} — the QoS
        view tests and the fleet report read.  ``cache_hits`` counts
        result-cache hits recorded on the tenant's ledger: visible
        traffic that was never billed against the quota or the
        weighted-fair vtime."""
        with self._cv:
            return {t: {"queued": len(self._lanes.get(t, ())),
                        "pending_archives": self._pending_tenant
                        .get(t, 0),
                        "cache_hits": self._hits.get(t, 0)}
                    for t in set(self._lanes)
                    | set(self._pending_tenant) | set(self._hits)}

    def record_hit(self, tenant, n=1):
        """Ledger a result-cache hit for ``tenant`` (ISSUE 17): the
        hit is O(1) work served outside the queue, so it must be SEEN
        (per-tenant accounting, the fleet/cache report) but charged to
        neither the global admission bound, the tenant quota, nor the
        weighted-fair virtual time — billing hits as fits would starve
        a repeat-heavy tenant for traffic that costs nothing."""
        t = str(tenant) if tenant else "default"
        with self._cv:
            self._hits[t] = self._hits.get(t, 0) + int(n)

    def submit(self, request):
        """Enqueue or raise ServeRejected (queue full / tenant over
        quota / closed)."""
        n = len(request.datafiles)
        tenant = getattr(request, "tenant", None) or "default"
        with self._cv:
            if self._closed:
                raise ServeRejected(
                    "serving queue is closed (server stopping); "
                    f"request {request.name!r} rejected")
            quota = self._quota_for(tenant)
            if n > self.max_pending or (quota is not None
                                        and n > quota):
                # could NEVER fit, even into an idle queue: terminal,
                # not retryable — a retrying client would spin forever
                bound = self.max_pending if n > self.max_pending \
                    else quota
                knob = ("config.serve_queue_depth"
                        if n > self.max_pending
                        else f"tenant {tenant!r} quota "
                             "(config.serve_tenant_quota)")
                raise ServeRejected(
                    f"request {request.name!r} holds {n} archives, "
                    f"more than the whole bound {bound} of {knob}; "
                    "split it or raise the knob")
            if self._pending + n > self.max_pending:
                raise ServeRejected(
                    f"admission queue full: {self._pending} archive(s) "
                    f"pending + {n} submitted > queue depth "
                    f"{self.max_pending} (config.serve_queue_depth / "
                    "PPT_SERVE_QUEUE_DEPTH); retry later",
                    retryable=True)
            t_pending = self._pending_tenant.get(tenant, 0)
            if quota is not None and t_pending + n > quota:
                raise ServeRejected(
                    f"tenant {tenant!r} over quota: {t_pending} "
                    f"archive(s) pending + {n} submitted > tenant "
                    f"quota {quota} (config.serve_tenant_quota / "
                    "PPT_SERVE_TENANT_QUOTA); retry later",
                    retryable=True)
            lane = self._lanes.setdefault(tenant, [])
            if not lane:
                # a lane waking from idle starts at the CURRENT
                # virtual time: banked idle credit must not let it
                # monopolize the scheduler to "catch up"
                active = [self._vtime(t) for t, q in
                          self._lanes.items() if q and t != tenant]
                if active:
                    floor = min(active) * self._weight_for(tenant)
                    self._served[tenant] = max(
                        self._served.get(tenant, 0), int(floor))
            self._pending += n
            self._pending_tenant[tenant] = t_pending + n
            request.t_submit = time.monotonic()
            lane.append(request)
            self._cv.notify()

    def get(self, timeout=None):
        """Pop the next request weighted-fair across tenant lanes
        (FIFO within a lane), waiting up to ``timeout`` seconds; None
        on timeout (or closed-and-empty)."""
        with self._cv:
            if not any(self._lanes.values()) and not self._closed:
                self._cv.wait(timeout)
            active = sorted((t for t, q in self._lanes.items() if q),
                            key=lambda t: (self._vtime(t), t))
            if not active:
                return None
            tenant = active[0]
            req = self._lanes[tenant].pop(0)
            self._served[tenant] = self._served.get(tenant, 0) \
                + len(req.datafiles)
            return req

    def release(self, n=1, tenant=None):
        """Return ``n`` archives' worth of admission credit (the
        server admitted or abandoned them); ``tenant`` releases that
        lane's quota too."""
        with self._cv:
            self._pending = max(0, self._pending - int(n))
            if tenant is not None:
                t = str(tenant)
                self._pending_tenant[t] = max(
                    0, self._pending_tenant.get(t, 0) - int(n))

    def close(self):
        """Refuse all further submissions (graceful-drain entry);
        already-queued requests still drain."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self):
        """Pop everything still queued, every lane (abort path) — the
        caller fails these requests loudly."""
        with self._cv:
            out = []
            for t in sorted(self._lanes):
                out.extend(self._lanes[t])
            self._lanes = {}
            return out
