"""Cooley-Tukey matmul rFFT experiment (round 4, VERDICT #1 follow-on).

prof result: the bench fit's whale is the DFT front end (31 ms of 54 at
640x512x2048), not the moment passes (2 x 11.5 ms, already the minimal
count).  A two-stage CT factorization n = n1*n2 cuts the MXU FLOPs ~7x
(0.40 vs 2.75 TFLOP at 2048->1025) at the price of a harmonic-order
permutation, which the fit can absorb by permuting the k-weight vectors
instead of the data (moments/CCF/S-sums are all either k-weighted
reductions or order-free).

Variants measured (fused cross-spectrum program: DFT + X assembly to
bf16 + Sd reduction, matching prepare_portrait_fit_real's shape):
  direct      rfft_mm at 'default' (single-pass bf16) — production
  ct_A_B      stage1 contracts n1=A, stage2 contracts n2=B, permuted
              output, f32 intermediates
Accuracy: assembled X vs an f64 numpy oracle on a small slice.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    config.dft_precision = "default"

    from benchmarks.common import devtime
    from pulseportraiture_tpu.ops.fourier import rfft_mm

    NB, NCHAN, NBIN = 640, 512, 2048
    NHARM = NBIN // 2 + 1
    DT = jnp.float32

    key = jax.random.PRNGKey(0)
    ports = jax.block_until_ready(jax.jit(
        lambda k: jax.random.normal(k, (NB, NCHAN, NBIN), DT))(key))
    model = jax.block_until_ready(jax.jit(
        lambda k: jax.random.normal(k, (NCHAN, NBIN), DT))(
            jax.random.PRNGKey(1)))
    w = jnp.ones((1, 1, 1), DT)

    # model spectrum at high precision (tiny, shared)
    mr, mi = rfft_mm(model, precision="highest")
    mr = jax.block_until_ready(mr)

    def direct(p, s):
        dr, di = rfft_mm(p * (1.0 + s))
        Xr = ((dr * mr + di * mi) * w).astype(jnp.bfloat16)
        Xi = ((di * mr - dr * mi) * w).astype(jnp.bfloat16)
        Sd = jnp.sum((dr**2 + di**2) * w, axis=(-1, -2))
        return Xr, Xi, Sd

    def ct_plan(n1, n2, n, nharm, dtype):
        """Host-side constants for X[q*n1+r] = sum_b (Y[r,b] T[r,b])
        W_n2^{qb}, Y[r,b] = sum_a x[a*n2+b] W_n1^{ar}; q in [0, nq).
        Returns numpy weights + the permutation pos->k."""
        nq = (nharm - 1) // n1 + 1  # smallest q count covering nharm
        a = np.arange(n1)
        r = np.arange(n1)
        W1 = np.exp(-2j * np.pi * np.outer(a, r) / n1)  # (a, r)
        b = np.arange(n2)
        T = np.exp(-2j * np.pi * np.outer(r, b) / n)    # (r, b)
        q = np.arange(nq)
        W2 = np.exp(-2j * np.pi * np.outer(b, q) / n2)  # (b, q)
        # permuted positions: pos = r*nq + q  ->  k = q*n1 + r
        kk = (q[None, :] * n1 + r[:, None]).reshape(-1)  # (n1*nq,)
        return (W1.real.astype(dtype), W1.imag.astype(dtype),
                T.real.astype(dtype), T.imag.astype(dtype),
                W2.real.astype(dtype), W2.imag.astype(dtype), kk)

    def make_ct(n1, n2):
        n = n1 * n2
        W1r, W1i, Tr, Ti, W2r, W2i, kk = ct_plan(n1, n2, n, NHARM, "float32")
        # mask out mirror harmonics (k > nharm-1) and permute the model
        # conj-spectrum and weights into position order on the host
        valid = kk <= NHARM - 1
        kk_c = np.where(valid, kk, 0)
        m_h = (np.asarray(mr) + 1j * np.asarray(mi))  # (nchan, nharm)
        mprr = np.where(valid, m_h.real[:, kk_c], 0.0).astype(np.float32)
        mpri = np.where(valid, m_h.imag[:, kk_c], 0.0).astype(np.float32)
        mpr = jnp.asarray(mprr)
        mpi = jnp.asarray(mpri)

        def ct(p, s):
            x = (p * (1.0 + s)).reshape(p.shape[0], p.shape[1], n1, n2)
            # stage 1: contract a (axis -2)
            Yr = jnp.einsum("...ab,ar->...rb", x, jnp.asarray(W1r))
            Yi = jnp.einsum("...ab,ar->...rb", x, jnp.asarray(W1i))
            # twiddle (elementwise, fused)
            Zr = Yr * Tr - Yi * Ti
            Zi = Yr * Ti + Yi * Tr
            # stage 2: contract b (axis -1)
            Fr = (jnp.einsum("...rb,bq->...rq", Zr, jnp.asarray(W2r))
                  - jnp.einsum("...rb,bq->...rq", Zi, jnp.asarray(W2i)))
            Fi = (jnp.einsum("...rb,bq->...rq", Zr, jnp.asarray(W2i))
                  + jnp.einsum("...rb,bq->...rq", Zi, jnp.asarray(W2r)))
            Fr = Fr.reshape(p.shape[0], p.shape[1], -1)  # position order
            Fi = Fi.reshape(p.shape[0], p.shape[1], -1)
            Xr = ((Fr * mpr + Fi * mpi) * w).astype(jnp.bfloat16)
            Xi = ((Fi * mpr - Fr * mpi) * w).astype(jnp.bfloat16)
            Sd = jnp.sum((Fr**2 + Fi**2) * (w * valid), axis=(-1, -2))
            return Xr, Xi, Sd

        return ct, kk, valid

    # --- accuracy: one batch row vs f64 numpy oracle ----------------
    ph = np.asarray(ports[:1]).astype(np.float64)
    F64 = np.fft.rfft(ph, axis=-1)
    m64 = (np.asarray(mr) + 1j * np.asarray(mi)).astype(np.complex128)
    X64 = (F64 * np.conj(m64))[0]
    scale = np.abs(X64).max()

    def acc(fn, kk=None, valid=None):
        Xr, Xi, _ = jax.jit(fn)(ports[:1], jnp.float32(0.0))
        Xc = (np.asarray(Xr, np.float64)
              + 1j * np.asarray(Xi, np.float64))[0]
        if kk is None:
            got = Xc
        else:
            got = np.zeros((NCHAN, NHARM), complex)
            got[:, kk[valid]] = Xc[:, valid]
        return float(np.abs(got - X64).max() / scale)

    jobs = [("direct", direct, None, None)]
    for n1, n2 in ((128, 16), (16, 128), (64, 32), (32, 64)):
        fn, kk, valid = make_ct(n1, n2)
        jobs.append((f"ct_{n1}_{n2}", fn, kk, valid))

    counter = [0]
    for name, fn, kk, valid in jobs:
        err = acc(fn, kk, valid)
        jfn = jax.jit(fn)

        def call(jfn=jfn):
            counter[0] += 1
            return jfn(ports, jnp.float32(counter[0] * 1e-7))

        slope, single = devtime(
            call,
            lambda r: (r[0].astype(jnp.float32).sum()
                       + r[1].astype(jnp.float32).sum() + r[2].sum()),
            K=6, warm=2)
        print(json.dumps({"variant": name,
                          "slope_ms": round(slope * 1e3, 2),
                          "single_ms": round(single * 1e3, 1),
                          "max_rel_err": f"{err:.2e}"}), flush=True)


if __name__ == "__main__":
    main()
