"""Slope-timed composition of the windowed fast fit (VERDICT r4 #2).

Round 4 attributed only ~65% of the windowed fit's 640-batch slope
(DFT ~10 ms + 2 moment passes ~6.3 ms of ~25 ms); this decomposes the
rest by timing nested prefixes of the real program plus isolated
pieces, all at the bench shape (640 x 512 x 2048, K=256, bf16 X,
shared template), each via benchmarks/common.devtime slope timing.

Pieces (cumulative prefixes of fast_fit_one):
  dft        data+model matmul DFTs alone (windowed)
  xasm       + weights, X assembly, S0, Parseval Sd  (prepare, no seed)
  seed       + CCF phase seed                        (prepare, seed on)
  full       + Newton loop + finalize                (the whole fit)
Isolated:
  parseval   the full-spectrum time-domain Sd reduction alone
  moment     ONE harmonic moment pass over the windowed bf16 X
  loopfin    core_real on precomputed X (loop + finalize, no DFT/seed)

Prints one JSON line with all slopes (ms per 640-batch) and the
attribution ledger.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.fit.portrait import (
        FitFlags, _fit_portrait_core_real, _moments_real_xla,
        _parseval_Sd, _t_coeffs, make_weights, prepare_portrait_fit_real)
    from pulseportraiture_tpu.ops.fourier import irfft_mm, rfft_mm
    from pulseportraiture_tpu.ops.phasor import phase_shifts

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    NB, NCHAN, NBIN = (640 if on_tpu else 64), 512, 2048
    K = int(os.environ.get("PPT_K", 256))
    DTYPE = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    MAX_ITER = 25

    model, freqs = bench_model(NCHAN, NBIN)
    NB_SYNTH = 64

    @jax.jit
    def synth(key):
        k1, k2, k3 = jax.random.split(key, 3)
        phis = 0.1 * jax.random.uniform(k1, (NB_SYNTH,), DTYPE)
        dms = 0.003 * jax.random.uniform(k2, (NB_SYNTH,), DTYPE)
        delays = jax.vmap(
            lambda ph, dm: phase_shifts(ph, dm, 0.0, freqs, P, NU_FIT,
                                        NU_FIT))(phis, dms)
        Xr, Xi = rfft_mm(model)
        k = jnp.arange(Xr.shape[-1], dtype=DTYPE)
        ang = -2.0 * jnp.pi * delays[..., None] * k
        c, s = jnp.cos(ang), jnp.sin(ang)
        rot = irfft_mm(Xr * c - Xi * s, Xr * s + Xi * c, NBIN)
        return rot + 0.05 * jax.random.normal(k3, rot.shape, DTYPE)

    ports = jnp.tile(synth(jax.random.PRNGKey(0)), (NB // NB_SYNTH, 1, 1))
    noise = jnp.full((NB, NCHAN), 0.05, DTYPE)
    Ps = jnp.full((NB,), P, DTYPE)
    nus = jnp.full((NB,), NU_FIT, DTYPE)
    jax.block_until_ready(ports)

    # --- full fit --------------------------------------------------------
    def full():
        return fit_portrait_batch_fast(ports, model, noise, freqs, Ps,
                                       nus, max_iter=MAX_ITER,
                                       harmonic_window=K)

    t_full, _ = devtime(full, lambda r: r.phi)
    res = full()
    nfev = int(np.max(np.asarray(res.nfeval)))
    nfev_med = float(np.median(np.asarray(res.nfeval)))

    # --- prefix programs -------------------------------------------------
    @jax.jit
    def dft_only(ports):
        dr, di = jax.vmap(lambda p: rfft_mm(p, nharm=K))(ports)
        mr, mi = rfft_mm(model, nharm=K)
        return (jnp.sum(dr) + jnp.sum(di) + jnp.sum(mr) + jnp.sum(mi))

    def _prepare(port, ns, seed):
        w = make_weights(ns, NBIN, dtype=DTYPE)
        th0 = jnp.zeros(5, DTYPE)
        Xr, Xi, S0, Sd, th = prepare_portrait_fit_real(
            port, model, w, freqs, P, NU_FIT, th0, seed_phi=seed,
            seed_derotate=False, x_dtype=jnp.bfloat16, nharm_eff=K)
        return (jnp.sum(Xr.astype(jnp.float32)) + jnp.sum(S0) + Sd
                + jnp.sum(th))

    xasm = jax.jit(jax.vmap(lambda p, n: _prepare(p, n, False)))
    seed = jax.jit(jax.vmap(lambda p, n: _prepare(p, n, True)))

    @jax.jit
    def parseval(ports, noise):
        def one(p, ns):
            w = make_weights(ns, NBIN, dtype=DTYPE)
            return _parseval_Sd(p, w)
        return jnp.sum(jax.vmap(one)(ports, noise))

    # --- precomputed-X pieces -------------------------------------------
    @jax.jit
    def prep_out(ports, noise):
        def one(p, ns):
            w = make_weights(ns, NBIN, dtype=DTYPE)
            return prepare_portrait_fit_real(
                p, model, w, freqs, P, NU_FIT, jnp.zeros(5, DTYPE),
                seed_phi=True, seed_derotate=False,
                x_dtype=jnp.bfloat16, nharm_eff=K)
        return jax.vmap(one)(ports, noise)

    Xr, Xi, S0, Sd, th0 = jax.block_until_ready(prep_out(ports, noise))

    # X ships as arguments, not closed-over constants — a closure
    # would embed ~170 MB into the program and blow the tunneled
    # compile server's request-size limit
    core = jax.jit(jax.vmap(
        lambda xr, xi, s0, sd, t0: _fit_portrait_core_real.__wrapped__(
            xr, xi, s0, sd, freqs, P, NU_FIT, -1.0, t0,
            fit_flags=FitFlags(), max_iter=MAX_ITER,
            nharm_total=NBIN // 2 + 1)))
    loopfin = lambda: core(Xr, Xi, S0, Sd, th0)

    cvec, _ = _t_coeffs(freqs, P, NU_FIT)
    cvec = cvec.astype(DTYPE)
    thetas = jnp.asarray(np.asarray(res.phi), DTYPE)

    @jax.jit
    def moment(thetas, Xr, Xi):
        def one(th, xr, xi):
            t_n = th + cvec * 0.0
            C, C1, C2 = _moments_real_xla(t_n, xr, xi)
            return jnp.sum(C) + jnp.sum(C1) + jnp.sum(C2)
        return jnp.sum(jax.vmap(one)(thetas, Xr, Xi))

    t_dft, _ = devtime(lambda: dft_only(ports), lambda r: r)
    t_xasm, _ = devtime(lambda: xasm(ports, noise), lambda r: r)
    t_seed, _ = devtime(lambda: seed(ports, noise), lambda r: r)
    t_pars, _ = devtime(lambda: parseval(ports, noise), lambda r: r)
    t_loopfin, _ = devtime(loopfin, lambda r: r[0])
    t_mom, _ = devtime(lambda: moment(thetas, Xr, Xi), lambda r: r)

    ms = lambda t: round(t * 1e3, 2)
    out = {
        "metric": "windowed fast-fit slope breakdown, 640x512x2048 K=%d" % K,
        "batch": NB,
        "device": str(dev),
        "nfev_max": nfev,
        "nfev_median": nfev_med,
        "full_ms": ms(t_full),
        "dft_ms": ms(t_dft),
        "xasm_ms": ms(t_xasm),
        "seed_ms": ms(t_seed),
        "parseval_ms": ms(t_pars),
        "loopfin_precomputedX_ms": ms(t_loopfin),
        "one_moment_pass_ms": ms(t_mom),
        "attrib": {
            "dft": ms(t_dft),
            "xasm_minus_dft": ms(t_xasm - t_dft),
            "seed_minus_xasm": ms(t_seed - t_xasm),
            "full_minus_seed(loop+finalize)": ms(t_full - t_seed),
            "loop_est(nfev_med*moment)": ms(nfev_med * t_mom),
        },
        # built ONLY from independently measured pieces (prepare prefix
        # + loop/finalize on precomputed X) — never from differences
        # that include t_full, which would telescope to 1.0
        "attributed_frac": round((t_seed + t_loopfin) / t_full, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
