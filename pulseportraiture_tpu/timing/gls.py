"""Wideband generalized-least-squares timing fit (NumPy, float64).

The DMDATA-1 likelihood the reference validates with an external tempo
run (examples/example_make_model_and_TOAs.ipynb cells 43-56): arrival
times AND the per-TOA wideband DM measurements enter one weighted
least-squares system,

    chi^2 = sum_i ((t_res_i - A_t_i @ x) / sigma_t_i)^2
          + sum_i ((DM_i - DM_model(t_i) - A_d_i @ x) / sigma_DM_i)^2

linearized about a simple barycentric spin ephemeris (F0 [, F1] at
PEPOCH) plus a piecewise-constant DM model (DMX per observing epoch —
exactly the structure make_fake_pulsar injects).  White noise only; no
binary/astrometric terms — the synthetic archives this validates are
generated barycentric from the same parfile.

This is an offline validation step over a handful of TOAs — host
NumPy f64 is the right tool (timing needs ~1e-13 day precision; the
accelerator adds nothing at this size).
"""

from dataclasses import dataclass

import numpy as np

from ..config import Dconst

__all__ = ["wideband_gls_fit", "WidebandGLSResult"]

SECPERDAY = 86400.0

# Parfile keys whose presence means the pulsar needs a timing model
# this fit does not implement (VERDICT r5 #7): orbital elements of the
# BT/DD/ELL1/T2 binary families.  Silently ignoring them would produce
# arrival-time residuals with unmodeled orbital structure that the
# DMX/F0 columns partially absorb — a misfit with no visible symptom —
# so the fit refuses loudly instead.
_BINARY_KEYS = frozenset({
    "BINARY",
    # Keplerian elements (BT/DD/T2)
    "PB", "A1", "ECC", "E", "T0", "OM", "FB0", "FB1",
    # ELL1 parameterization
    "TASC", "EPS1", "EPS2", "EPS1DOT", "EPS2DOT",
    # relativistic / derivative terms
    "PBDOT", "XDOT", "A1DOT", "OMDOT", "ECCDOT", "EDOT",
    "GAMMA", "SINI", "M2", "MTOT", "KOM", "KIN", "SHAPMAX",
})


@dataclass
class WidebandGLSResult:
    params: dict              # name -> fitted offset value
    param_errs: dict
    time_resids_us: np.ndarray   # post-fit [us]
    prefit_resids_us: np.ndarray
    dm_resids: np.ndarray        # post-fit DM residuals [pc cm^-3]
    toa_errs_us: np.ndarray
    dm_errs: np.ndarray
    epochs: np.ndarray           # epoch index per TOA
    dmx: np.ndarray              # fitted DMX per epoch [pc cm^-3]
    dmx_errs: np.ndarray
    chi2: float
    dof: int
    wrms_us: float
    n_dropped_no_dm: int = 0     # input TOAs without -pp_dm/-pp_dme

    @property
    def red_chi2(self):
        return self.chi2 / max(self.dof, 1)


def _group_epochs(mjds, gap_days=0.5):
    """Epoch index per TOA: a new epoch wherever the (sorted) MJDs jump
    by more than gap_days."""
    order = np.argsort(mjds)
    out = np.zeros(len(mjds), int)
    cur = 0
    prev = None
    for j in order:
        if prev is not None and mjds[j] - prev > gap_days:
            cur += 1
        out[j] = cur
        prev = mjds[j]
    return out


def wideband_gls_fit(toas, par, fit_f0=True, fit_f1=False,
                     epoch_gap_days=0.5, allow_wraps=False):
    """Fit (phase offset[, dF0[, dF1]], DMX per epoch) to wideband TOAs.

    toas: list of timing.tim.TimTOA (needs frequency, mjd, error_us,
    dm, dm_err).  par: dict-like with F0 or P0, PEPOCH, DM (the
    parse_parfile output is fine — string values are converted).

    Returns WidebandGLSResult; DM measurements and arrival times are
    fit jointly (DMDATA-1 style), with the model DM at each TOA =
    par DM + DMX[epoch].

    TOAs lacking wideband DM measurements cannot enter the DMDATA
    system; they are dropped with a warning and counted in the
    result's n_dropped_no_dm (they used to vanish silently).

    Phase connection is validated: each prefit residual is wrapped to
    the nearest turn independently, which is only meaningful when the
    ephemeris predicts phase to well under half a turn across the
    campaign.  If the wrapped residuals of time-adjacent TOAs jump by
    more than half a turn, the pulse numbering is ambiguous and the
    fit would silently time a wrapped alias — that raises unless
    allow_wraps=True (for callers who accept per-TOA wrapping, e.g.
    offset-only fits on scrambled data)."""
    def fget(key, default=None):
        v = par.get(key, default)
        return float(str(v).replace("D", "E")) if v is not None else None

    # refuse binary-pulsar ephemerides LOUDLY: this model has no
    # orbital delay terms, and fitting anyway would silently time the
    # pulsar against a wrong (orbit-smeared) phase prediction
    binary = sorted(k for k in _BINARY_KEYS
                    if par.get(k) is not None) if hasattr(par, "get") \
        else []
    if binary:
        raise ValueError(
            "wideband_gls_fit: the parfile carries binary-orbit "
            f"parameters ({', '.join(binary)}) that this fit does not "
            "model — it implements only (offset, dF0[, dF1], DMX) for "
            "isolated barycentric pulsars.  Remove the binary "
            "parameters (isolated pulsar), or time these TOAs with "
            "tempo2/PINT, which model BT/DD/ELL1 orbits.")

    PEPOCH = fget("PEPOCH")
    if PEPOCH is None:
        raise ValueError(
            "wideband_gls_fit: parfile is missing PEPOCH (the spin "
            "reference epoch); add a 'PEPOCH <mjd>' line")
    if fget("F0") is None and fget("P0") is None:
        raise ValueError(
            "wideband_gls_fit: parfile has neither F0 nor P0; one spin "
            "parameter is required")
    DM0 = fget("DM", 0.0)

    n_in = len(toas)
    toas = [t for t in toas if t.dm is not None and t.dm_err]
    n = len(toas)
    n_dropped = n_in - n
    if n_dropped:
        import warnings

        warnings.warn(
            f"wideband_gls_fit: dropped {n_dropped} of {n_in} TOAs "
            "without -pp_dm/-pp_dme wideband DM flags (they cannot "
            "enter the DMDATA system)", stacklevel=2)
    if n < 2:
        raise ValueError("wideband GLS needs >= 2 TOAs with -pp_dm")
    freqs = np.array([t.frequency for t in toas])
    errs_us = np.array([t.error_us for t in toas])
    dms = np.array([t.dm for t in toas])
    dm_errs = np.array([t.dm_err for t in toas])
    mjd_i = np.array([t.mjd_int for t in toas], np.int64)
    mjd_f = np.array([t.mjd_frac for t in toas])
    mjds = mjd_i + mjd_f

    epochs = _group_epochs(mjds, epoch_gap_days)
    nep = epochs.max() + 1

    # infinite-frequency arrival time: subtract the MODEL dispersion
    # delay (par DM; the DMX corrections are fitted linearly below) at
    # the TOA's reference frequency.  Using the measured DMs here would
    # leak their noise into the arrival times and double-count the DMX
    # columns.
    disp_s = np.where(np.isfinite(freqs),
                      Dconst * DM0 * freqs ** -2.0, 0.0)
    # seconds since PEPOCH (f64: used only for design columns, where
    # ns precision is irrelevant)
    dt_s = ((mjd_i - int(PEPOCH)) * SECPERDAY
            + (mjd_f - (PEPOCH - int(PEPOCH))) * SECPERDAY
            - disp_s)

    # prefit phase residuals (nearest-turn wrap).  F0 * dt is ~1e9
    # turns for an MSP campaign — one f64 product would cost ns-level
    # rounding — so the integer-day part is reduced modulo 1 in exact
    # rational arithmetic via the SAME helper/representation the
    # spin-coherent synth uses (utils/spin.py; a float-rounded F0 here
    # would fake a ~1 ns/100 days residual slope against it), and only
    # the < half-day remainder (~1e7 turns, ~0.01 ns f64 error) is a
    # float product.
    from ..utils.spin import day_phase_frac, spin_F0

    F0r = spin_F0(par)
    F0 = float(F0r)  # design/conversion value, consistent with F0r
    pep_i = int(PEPOCH)
    phase_day = np.array(
        [day_phase_frac(F0r, pep_i, di) for di in mjd_i])
    phase_rem = F0 * ((mjd_f - (PEPOCH - pep_i)) * SECPERDAY - disp_s)
    phase = phase_day + phase_rem
    dphase = phase - np.round(phase)
    # phase-connection validation.  Nearest-turn wrapping is only valid
    # when every TRUE residual phase sits inside a +-0.5-turn window
    # around a common offset (the OFFSET parameter absorbs the mean).
    # The observable, rotation-invariant symptom of lost connection is
    # the OCCUPIED CIRCULAR ARC of the prefit residuals: residuals of
    # a connected campaign cluster (any cluster position is fine —
    # a constant offset at the +-0.5 boundary must NOT false-fire),
    # while a drifting-F0 campaign smears them over the circle.  When
    # more than half the circle is occupied no single wrap window can
    # contain the data and the fit would silently time wrapped
    # aliases.
    if not allow_wraps and n > 1:
        s = np.sort(dphase)
        largest_gap = max(float(np.diff(s).max(initial=0.0)),
                          1.0 - float(s[-1] - s[0]))
        occupied = 1.0 - largest_gap
        if occupied > 0.5:
            raise ValueError(
                "wideband_gls_fit: prefit phase residuals occupy "
                f"{occupied:.2f} turns of the phase circle — phase "
                "connection is lost and the nearest-turn wrap would "
                "silently time wrapped aliases.  Improve F0/F1 (or "
                "pass allow_wraps=True to accept per-TOA wrapping).")
    r_t = dphase / F0  # seconds

    # design matrix, time rows: d(model delay)/d(param) in seconds
    cols = {}
    cols["OFFSET"] = np.ones(n)
    # spin columns carry tempo's sign convention: the fitted value is
    # the CORRECTION TO ADD to the par parameter (residuals shrink when
    # the par moves toward truth)
    if fit_f0:
        cols["F0"] = -dt_s / F0
    if fit_f1:
        cols["F1"] = -0.5 * dt_s ** 2.0 / F0
    # DMX columns affect BOTH the time rows (through the dispersion
    # delay at the TOA frequency) and the DM rows
    names = list(cols)
    A_t = np.stack([cols[k] for k in names], axis=1)
    dmx_t = np.zeros((n, nep))
    finite = np.isfinite(freqs)
    for j in range(nep):
        sel = (epochs == j) & finite
        dmx_t[sel, j] = Dconst * freqs[sel] ** -2.0
    A_t = np.concatenate([A_t, dmx_t], axis=1)

    # DM rows: residual = DM_i - (DM0 + DMX[epoch])
    r_d = dms - DM0
    A_d = np.zeros((n, A_t.shape[1]))
    for j in range(nep):
        A_d[epochs == j, len(names) + j] = 1.0

    # stack and whiten
    sig_t = errs_us * 1e-6
    A = np.concatenate([A_t / sig_t[:, None], A_d / dm_errs[:, None]])
    r = np.concatenate([r_t / sig_t, r_d / dm_errs])

    # column-normalize: the raw design spans ~12 decades (seconds-per-Hz
    # vs seconds-per-DM columns), which wrecks both lstsq conditioning
    # and pinv's singular-value threshold for the covariance
    col = np.sqrt((A ** 2.0).sum(axis=0))
    col = np.where(col > 0, col, 1.0)
    An = A / col
    xn, *_ = np.linalg.lstsq(An, r, rcond=None)
    x = xn / col
    cov = (np.linalg.pinv(An.T @ An) / col[:, None]) / col[None, :]
    perr = np.sqrt(np.maximum(np.diag(cov), 0.0))

    post_t = r_t - A_t @ x
    post_d = r_d - A_d @ x
    chi2 = float(((post_t / sig_t) ** 2.0).sum()
                 + ((post_d / dm_errs) ** 2.0).sum())
    dof = 2 * n - A.shape[1]
    w = sig_t ** -2.0
    wrms = np.sqrt((post_t ** 2.0 * w).sum() / w.sum()) * 1e6

    params = dict(zip(names, x[:len(names)]))
    param_errs = dict(zip(names, perr[:len(names)]))
    return WidebandGLSResult(
        params=params, param_errs=param_errs,
        time_resids_us=post_t * 1e6, prefit_resids_us=r_t * 1e6,
        dm_resids=post_d, toa_errs_us=errs_us, dm_errs=dm_errs,
        epochs=epochs, dmx=x[len(names):], dmx_errs=perr[len(names):],
        chi2=chi2, dof=dof, wrms_us=float(wrms),
        n_dropped_no_dm=n_dropped)
