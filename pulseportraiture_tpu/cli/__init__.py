"""Command-line tools (SURVEY §2.2 L5): the five user entry points,
flag-compatible with the reference's OptionParser CLIs (pptoas.py:1479,
ppalign.py:283, ppgauss.py:666, ppspline.py:291, ppzap.py:107).

Run as `python -m pulseportraiture_tpu.cli.<tool>` or via the console
scripts installed by setup.py.
"""
