"""Bounded Levenberg-Marquardt least squares in JAX.

Replaces the reference's lmfit/MINPACK dependency (used by
fit_gaussian_profile pplib.py:1922-2002, fit_gaussian_portrait
pplib.py:2005-2133, fit_powlaw pplib.py:1841-1880).  Bounds are handled
with the same MINUIT-style parameter transforms lmfit uses, so bounded
parameters stay strictly inside their intervals and the Jacobian is
taken in the unbounded internal space by autodiff.  The loop is a
fixed-shape `lax.while_loop`; frozen parameters (vary=False) have their
Jacobian columns masked rather than changing the parameter vector's
shape, keeping everything jittable.

Error bars follow lmfit's default convention: covariance scaled by
reduced chi^2 (scale_covar=True), reported in external space via the
transform's chain rule.
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LMResult", "levenberg_marquardt"]


# --- bound transforms (lmfit/MINUIT convention) ---------------------------
# free:        x = u
# lower only:  x = lo - 1 + sqrt(u^2 + 1)
# upper only:  x = hi + 1 - sqrt(u^2 + 1)
# two-sided:   x = lo + (hi - lo)/2 * (sin(u) + 1)


def _to_external(u, lo, hi, kind):
    s = jnp.sqrt(u**2.0 + 1.0)
    return jnp.where(
        kind == 0, u,
        jnp.where(
            kind == 1, lo - 1.0 + s,
            jnp.where(kind == 2, hi + 1.0 - s,
                      lo + 0.5 * (hi - lo) * (jnp.sin(u) + 1.0)),
        ),
    )


def _to_internal(x, lo, hi, kind):
    xl = jnp.sqrt(jnp.maximum((x - lo + 1.0) ** 2.0 - 1.0, 0.0))
    xu = jnp.sqrt(jnp.maximum((hi - x + 1.0) ** 2.0 - 1.0, 0.0))
    frac = jnp.clip(2.0 * (x - lo) / jnp.where(hi > lo, hi - lo, 1.0) - 1.0,
                    -1.0, 1.0)
    return jnp.where(
        kind == 0, x,
        jnp.where(kind == 1, xl, jnp.where(kind == 2, -xu, jnp.arcsin(frac))),
    )


def _bounds_spec(lower, upper, n, dtype):
    lo = np.full(n, -np.inf) if lower is None else np.asarray(lower, float)
    hi = np.full(n, np.inf) if upper is None else np.asarray(upper, float)
    kind = np.zeros(n, np.int32)
    kind[np.isfinite(lo) & ~np.isfinite(hi)] = 1
    kind[~np.isfinite(lo) & np.isfinite(hi)] = 2
    kind[np.isfinite(lo) & np.isfinite(hi)] = 3
    # replace infs by dummies so the transforms never see inf arithmetic
    lo = np.where(np.isfinite(lo), lo, 0.0)
    hi = np.where(np.isfinite(hi), hi, 0.0)
    return (jnp.asarray(lo, dtype), jnp.asarray(hi, dtype),
            jnp.asarray(kind))


class LMResult(NamedTuple):
    x: jnp.ndarray          # fitted external parameters
    x_err: jnp.ndarray      # 1-sigma errors (scale_covar convention)
    chi2: jnp.ndarray
    dof: jnp.ndarray
    nfev: jnp.ndarray
    cov: jnp.ndarray        # external-space covariance (scaled)
    success: jnp.ndarray


class _LMState(NamedTuple):
    u: jnp.ndarray
    f: jnp.ndarray
    r: jnp.ndarray   # residual at u (kept so rejected steps don't recompute)
    J: jnp.ndarray   # Jacobian at u (ditto — the dominant per-step cost)
    lam: jnp.ndarray
    it: jnp.ndarray
    nfev: jnp.ndarray
    done: jnp.ndarray


@partial(jax.jit, static_argnames=("resid_fn", "max_iter"))
def _lm_core(resid_fn, aux, x0, lo, hi, kind, vary, max_iter=100, ftol=1e-10,
             lam0=1e-3):
    dt = x0.dtype
    u0 = _to_internal(x0, lo, hi, kind)
    vary = vary.astype(dt)
    nvary = jnp.sum(vary)

    def rfun(u):
        return resid_fn(_to_external(u, lo, hi, kind), *aux)

    def jac(u):
        J = jax.jacfwd(rfun)(u)  # (nres, nparam)
        return J * vary[None, :]

    def cond(s):
        return jnp.logical_and(s.it < max_iter, jnp.logical_not(s.done))

    def body(s):
        g = s.J.T @ s.r
        JTJ = s.J.T @ s.J
        dJ = jnp.diag(JTJ)
        dJ = jnp.maximum(dJ, 1e-14 * jnp.max(dJ))
        A = JTJ + s.lam * jnp.diag(dJ) + jnp.diag(1.0 - vary)
        step = -jnp.linalg.solve(A, g) * vary
        # near-degenerate Jacobian columns (e.g. a parameter just
        # inside a bound) can produce explosive internal steps; clamp
        # each element to a generous multiple of its current scale
        smax = 100.0 * (1.0 + jnp.abs(s.u))
        step = jnp.clip(step, -smax, smax)
        u_try = s.u + step
        r_try = rfun(u_try)
        f_new = jnp.sum(r_try**2.0)
        accept = f_new < s.f
        # converged: accepted near-Newton step (small damping) with
        # negligible relative improvement.  With large lam a small
        # improvement only means the step was short, not convergence.
        rel = (s.f - f_new) / (jnp.abs(s.f) + 1e-300)
        done = jnp.logical_and(jnp.logical_and(accept, rel < ftol),
                               s.lam <= lam0)
        # also converged if the gradient is essentially zero
        gnorm = jnp.max(jnp.abs(g * vary))
        done = jnp.logical_or(done, gnorm < 1e-14 * (s.f + 1.0))
        u_new = jnp.where(accept, u_try, s.u)
        # the Jacobian only changes when the step is accepted; a
        # rejected step reuses the stored one (skipping the dominant
        # per-iteration cost during lambda adjustment)
        J_new = jax.lax.cond(accept, jac, lambda _: s.J, u_new)
        return _LMState(
            u=u_new,
            f=jnp.where(accept, f_new, s.f),
            r=jnp.where(accept, r_try, s.r),
            J=J_new,
            lam=jnp.where(accept, s.lam * 0.3, s.lam * 5.0).clip(1e-12, 1e12),
            it=s.it + 1,
            nfev=s.nfev + 1,
            done=done,
        )

    r0 = rfun(u0)
    s0 = _LMState(
        u=u0,
        f=jnp.sum(r0**2.0),
        r=r0,
        J=jac(u0),
        lam=jnp.asarray(lam0, dt),
        it=jnp.asarray(0, jnp.int32),
        nfev=jnp.asarray(1, jnp.int32),
        done=jnp.asarray(False),
    )
    s = jax.lax.while_loop(cond, body, s0)

    # --- covariance in external space, lmfit scale_covar convention ---
    r, J = s.r, s.J
    JTJ = J.T @ J + jnp.diag(1.0 - vary)
    cov_u = jnp.linalg.inv(JTJ)
    nres = r.shape[0]
    dof = nres - nvary
    red = s.f / jnp.maximum(dof, 1.0)
    cov_u = cov_u * red
    # the transform is elementwise, so dx/du is diagonal
    D = jax.vmap(jax.grad(_to_external), in_axes=(0, 0, 0, 0))(
        s.u, lo, hi, kind)
    cov_x = cov_u * jnp.outer(D, D) * jnp.outer(vary, vary)
    x = _to_external(s.u, lo, hi, kind)
    x_err = jnp.sqrt(jnp.maximum(jnp.diagonal(cov_x), 0.0))
    return LMResult(
        x=x, x_err=x_err, chi2=s.f, dof=dof, nfev=s.nfev, cov=cov_x,
        success=s.done | (s.it < max_iter),
    )


def levenberg_marquardt(resid_fn, x0, aux=(), lower=None, upper=None,
                        vary=None, max_iter=100, ftol=1e-10):
    """Minimize sum(resid_fn(x, *aux)**2) over x with optional bounds.

    resid_fn: callable (x, *aux) -> residual vector; must be
    jax-traceable and HASHABLE (a module-level function).  Pass data
    arrays through `aux` — they are traced operands, so repeated fits
    with different data reuse one compilation.
    x0: (n,) initial external parameters (clipped into bounds).
    lower/upper: (n,) bounds with +-inf for unbounded; vary: (n,) bool.
    """
    x0 = jnp.asarray(x0, float)
    n = x0.shape[0]
    lo, hi, kind = _bounds_spec(lower, upper, n, x0.dtype)
    if vary is None:
        vary = jnp.ones(n, bool)
    vary = jnp.asarray(vary)
    # Nudge VARYING parameters strictly inside their bounds: at the
    # exact bound every transform has dx/du = 0 (u = 0 for one-sided,
    # the arcsin endpoints for two-sided), which zeroes the Jacobian
    # column and freezes the parameter forever.  Frozen (vary=False)
    # parameters keep their exact value.  The nudge must be large
    # enough that dx/du ~ sqrt(2*eps) does not make the column
    # numerically singular (which produces explosive internal steps).
    eps = 1e-4
    inside3 = jnp.clip(x0, lo + eps * (hi - lo), hi - eps * (hi - lo))
    inside1 = jnp.maximum(x0, lo + eps * (1.0 + jnp.abs(lo)))
    inside2 = jnp.minimum(x0, hi - eps * (1.0 + jnp.abs(hi)))
    x0 = jnp.where(vary & (kind == 3), inside3, x0)
    x0 = jnp.where(vary & (kind == 1), inside1, x0)
    x0 = jnp.where(vary & (kind == 2), inside2, x0)
    # frozen params still need finite internal coordinates
    x0 = jnp.where(~vary & (kind == 3),
                   jnp.clip(x0, lo, hi), x0)
    x0 = jnp.where(~vary & (kind == 1), jnp.maximum(x0, lo), x0)
    x0 = jnp.where(~vary & (kind == 2), jnp.minimum(x0, hi), x0)
    return _lm_core(resid_fn, tuple(aux), x0, lo, hi, kind, vary,
                    max_iter=max_iter, ftol=ftol)
