"""Device-mesh helpers.

The workload's parallel axes (SURVEY.md §2.9) are *batch* axes:
(archive, subint) fits are independent, and the only cross-channel
coupling inside one fit is a sum-reduction in the objective.  The
canonical mesh is therefore 2-D:

- ``data``: archive/subint batch — embarrassingly parallel, the
  dominant axis (DCN-safe, no communication except result gathers).
- ``chan``: frequency channels *within* one fit — sharding this axis
  makes XLA insert psum collectives for the chi^2 channel reduction
  over ICI; useful when single fits are huge or batches are small.

The reference has no distributed execution at all (a sequential
Python loop over archives, pptoas.py:258); this module is its
TPU-native replacement.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_data=None, n_chan=1, devices=None):
    """A ('data', 'chan') mesh over the given (default: all) devices."""
    devices = list(jax.devices() if devices is None else devices)
    n = len(devices)
    if n_data is None:
        n_data = n // n_chan
    assert n_data * n_chan <= n, f"mesh {n_data}x{n_chan} > {n} devices"
    dev_array = np.asarray(devices[: n_data * n_chan]).reshape(n_data, n_chan)
    return Mesh(dev_array, axis_names=("data", "chan"))


def batch_sharding(mesh, ndim, chan_axis=None):
    """NamedSharding: leading axis over 'data', optionally one axis over
    'chan', rest replicated."""
    spec = [None] * ndim
    spec[0] = "data"
    if chan_axis is not None and chan_axis < ndim:
        spec[chan_axis] = "chan"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())
