"""Stationary (translation-invariant) wavelet denoising.

TPU-native replacement for the reference's PyWavelets-based smoothing
(reference pplib.py:1692-1838: wavelet_smooth / smart_smooth /
fit_wavelet_smooth_function).  Instead of pywt.swt/iswt host loops, the
undecimated transform is implemented as FFT-domain circular
correlation/convolution with a-trous (upsampled) filters — fully
jittable, batched over channels with vmap, and the smart_smooth
(nlevel, fact) search is a vectorized grid evaluation instead of
per-profile scipy.optimize.brute.

The Daubechies scaling filters are computed once on host by spectral
factorization (no table, no pywt).  Perfect reconstruction of the
forward/inverse pair is covered by tests/test_spline.py.
"""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.noise import get_noise_PS

__all__ = [
    "daubechies",
    "swt",
    "iswt",
    "wavelet_smooth",
    "smart_smooth",
    "get_red_chi2",
]


@lru_cache(maxsize=None)
def daubechies(N=8):
    """Orthonormal Daubechies scaling filter with N vanishing moments
    (length 2N), by spectral factorization of the half-band polynomial.

    Returns (dec_lo, dec_hi) as float64 numpy arrays with sum(dec_lo)
    = sqrt(2) and the usual quadrature-mirror relation.
    """
    if N < 1:
        raise ValueError("N >= 1 required")
    if N == 1:  # Haar
        lo = np.array([1.0, 1.0]) / np.sqrt(2.0)
    else:
        # P(y) = sum_{k<N} C(N-1+k, k) y^k ; y = (2 - z - 1/z)/4.
        # Build the Laurent polynomial z^{N-1} P(y(z)) and keep the
        # roots inside the unit circle (minimum-phase factor).
        from math import comb

        py = np.array([comb(N - 1 + k, k) for k in range(N - 1, -1, -1)],
                      dtype=float)
        # y(z) expressed as polynomial in z (times z^-1): y = (-z^2 + 2z - 1)/(4z)
        yz = np.array([-1.0, 2.0, -1.0]) / 4.0
        total = np.zeros(2 * N - 1)
        for k in range(N):  # coefficient of y^{N-1-k} is py[k]
            term = np.array([1.0])
            for _ in range(N - 1 - k):
                term = np.convolve(term, yz)
            # multiply by z^{k} to bring everything to degree 2N-2
            padded = np.zeros(2 * N - 1)
            off = k
            padded[off:off + len(term)] += py[k] * term
            total += padded
        roots = np.roots(total)
        keep = roots[np.abs(roots) < 1.0]
        # h(z) ~ (1+z)^N * prod (z - r_i), normalized
        h = np.array([1.0])
        for _ in range(N):
            h = np.convolve(h, [1.0, 1.0])
        for r in keep:
            h = np.convolve(h, [1.0, -r])
        h = np.real(h)
        lo = h * (np.sqrt(2.0) / h.sum())
    hi = lo[::-1].copy()
    hi[1::2] *= -1.0
    return lo, hi


def _filter_ffts(nbin, nlevel, N=8, dtype=np.float64):
    """rfft of the a-trous upsampled (lo, hi) filters at each level,
    zero-padded to nbin.  Host-side, cached by the jit tracer."""
    lo, hi = daubechies(N)
    los, his = [], []
    for j in range(nlevel):
        step = 2 ** j
        for f, out in ((lo, los), (hi, his)):
            up = np.zeros(nbin, dtype=dtype)
            idx = (np.arange(len(f)) * step) % nbin
            np.add.at(up, idx, f)
            out.append(np.fft.rfft(up))
    return np.stack(los), np.stack(his)


@partial(jax.jit, static_argnames=("nlevel", "N"))
def swt(x, nlevel=5, N=8):
    """Stationary wavelet transform with periodic boundary.

    x: (..., nbin).  Returns (cA, cD), each (..., nlevel, nbin), finest
    level first (index 0 = level-1 detail), matching the convention the
    thresholding code expects.
    """
    nbin = x.shape[-1]
    loF, hiF = _filter_ffts(nbin, nlevel, N)
    loF = jnp.asarray(loF)
    hiF = jnp.asarray(hiF)
    cAs, cDs = [], []
    a = x
    for j in range(nlevel):
        aF = jnp.fft.rfft(a, axis=-1)
        # circular correlation = multiply by conj(filter fft)
        a_next = jnp.fft.irfft(aF * jnp.conj(loF[j]), n=nbin, axis=-1)
        d = jnp.fft.irfft(aF * jnp.conj(hiF[j]), n=nbin, axis=-1)
        cAs.append(a_next)
        cDs.append(d)
        a = a_next
    return jnp.stack(cAs, axis=-2), jnp.stack(cDs, axis=-2)


@partial(jax.jit, static_argnames=("N",))
def iswt(cA, cD, N=8):
    """Inverse of swt: reconstruct from the coarsest approximation and
    all detail levels.  cA, cD: (..., nlevel, nbin)."""
    nlevel, nbin = cA.shape[-2], cA.shape[-1]
    loF, hiF = _filter_ffts(nbin, nlevel, N)
    loF = jnp.asarray(loF)
    hiF = jnp.asarray(hiF)
    a = cA[..., -1, :]
    for j in range(nlevel - 1, -1, -1):
        aF = jnp.fft.rfft(a, axis=-1)
        dF = jnp.fft.rfft(cD[..., j, :], axis=-1)
        # synthesis: circular convolution with the same filters, halved
        a = 0.5 * jnp.fft.irfft(aF * loF[j] + dF * hiF[j], n=nbin, axis=-1)
    return a


def _universal_threshold(cD1, nbin, fact):
    """fact * (MAD/0.6745) * sqrt(2 ln nbin), from the finest-level
    coefficients (reference pplib.py:1725-1727 uses coeffs[0] = the
    first swt level)."""
    mad = jnp.median(jnp.abs(cD1), axis=-1)
    return fact * (mad / 0.6745) * jnp.sqrt(2.0 * jnp.log(nbin))


def _threshold(c, t, threshtype):
    if threshtype == "hard":
        return jnp.where(jnp.abs(c) > t, c, 0.0)
    elif threshtype == "soft":
        return jnp.sign(c) * jnp.maximum(jnp.abs(c) - t, 0.0)
    raise ValueError(f"unknown threshtype {threshtype!r}")


@partial(jax.jit, static_argnames=("nlevel", "threshtype", "N"))
def _wavelet_smooth_1d(prof, fact, nlevel, threshtype="hard", N=8):
    nbin = prof.shape[-1]
    cA, cD = swt(prof, nlevel=nlevel, N=N)
    # reference thresholds ALL coefficients (approx + detail) of the
    # stacked pywt.swt output (pplib.py:1728-1729); threshold value from
    # the first (coarsest-listed) element.  pywt.swt returns
    # [(cA_n, cD_n), ..., (cA_1, cD_1)] so coeffs[0] is the COARSEST
    # level pair; its median-abs is dominated by cA_n.  We use the
    # coarsest approximation+detail, matching that behavior.
    ref = jnp.concatenate([cA[..., -1, :], cD[..., -1, :]], axis=-1)
    t = _universal_threshold(ref, nbin, fact)
    t = t[..., None, None]
    cA = _threshold(cA, t, threshtype)
    cD = _threshold(cD, t, threshtype)
    return iswt(cA, cD, N=N)


def wavelet_smooth(port, nlevel=5, threshtype="hard", fact=1.0, N=8):
    """Wavelet-denoise a profile (nbin,) or portrait (nchan, nbin).

    Reference behavior: pplib.py:1692-1737 (pywt swt -> universal hard
    threshold -> iswt), but batched on device instead of a per-channel
    host loop.
    """
    port = jnp.asarray(port)
    fact = jnp.asarray(fact, port.dtype)
    return _wavelet_smooth_1d(port, fact, nlevel, threshtype, N)


def get_red_chi2(data, model, errs=None, dof=None):
    """Reduced chi^2 between data and model (reference pplib.py:754-779).

    1-D or 2-D; errs estimated per-profile from the power spectrum if
    not given; dof defaults to sum(shape) as in the reference.
    """
    data = jnp.asarray(data)
    model = jnp.asarray(model)
    if errs is None:
        errs = get_noise_PS(data)
    if dof is None:
        dof = sum(data.shape)
    resids = (data - model) / jnp.expand_dims(jnp.asarray(errs), -1) \
        if data.ndim == 2 else (data - model) / errs
    return jnp.sum(resids**2.0) / dof


@partial(jax.jit, static_argnames=("nlevel", "threshtype", "N", "nfact"))
def _smooth_score_grid(prof, nlevel, threshtype="hard", N=8, nfact=30,
                       fact_max=3.0, rchi2_tol=0.1):
    """For one profile and one nlevel, evaluate the smart_smooth score on
    a fact grid.  Returns (scores, facts, smoothed) with leading axis nfact.

    Score = pseudo-S/N (Fourier signal power / Fourier noise), zeroed
    when |red_chi2 - 1| > rchi2_tol (reference pplib.py:1814-1838).
    """
    nbin = prof.shape[-1]
    facts = jnp.linspace(0.0, fact_max, nfact, dtype=prof.dtype)
    cA, cD = swt(prof, nlevel=nlevel, N=N)
    ref = jnp.concatenate([cA[-1], cD[-1]], axis=-1)
    t0 = _universal_threshold(ref, nbin, 1.0)

    def one(fact):
        t = fact * t0
        sm = iswt(_threshold(cA, t, threshtype), _threshold(cD, t, threshtype),
                  N=N)
        sig = jnp.sum(jnp.abs(jnp.fft.rfft(sm)[1:]) ** 2.0)
        noise = get_noise_PS(sm) * jnp.sqrt(nbin / 2.0)
        snr = jnp.where(noise > 0.0, sig / jnp.where(noise > 0, noise, 1.0),
                        jnp.inf)
        snr = jnp.where(sig > 0.0, snr, 0.0)
        # red chi2 of data vs smooth, noise from the data profile; a
        # zero noise estimate means the gate cannot be evaluated ->
        # treat as failed (inf), never NaN (NaN comparisons would
        # silently PASS the gate)
        dnoise = get_noise_PS(prof)
        good_noise = dnoise > 0.0
        rchi2 = jnp.where(
            good_noise,
            jnp.sum(((prof - sm) / jnp.where(good_noise, dnoise, 1.0))
                    ** 2.0) / nbin,
            jnp.inf,
        )
        snr = jnp.where(jnp.abs(rchi2 - 1.0) > rchi2_tol, 0.0, snr)
        return snr, sm, rchi2

    scores, smoothed, rchi2s = jax.vmap(one)(facts)
    return scores, facts, smoothed, rchi2s


def smart_smooth(port, try_nlevels=None, rchi2_tol=0.1, threshtype="hard",
                 N=8, nfact=30, fact_max=3.0):
    """Auto-tuned wavelet smoothing (reference pplib.py:1740-1811).

    For each profile, maximize pseudo-S/N over (nlevel, fact) subject to
    reduced-chi2 within rchi2_tol of 1; profiles with no acceptable
    smoothing are zeroed.  The reference brute-forces fact with
    opt.brute per (profile, nlevel) on host; here the whole
    (nlevel x fact) grid is evaluated as batched device ops.
    """
    port = jnp.asarray(port)
    one_prof = port.ndim == 1
    if one_prof:
        port = port[None]
    nchan, nbin = port.shape
    if nbin % 2 != 0 or try_nlevels == 0:
        out = port[0] if one_prof else port
        return out
    if np.modf(np.log2(nbin))[0] != 0.0:
        try_nlevels = 1
    elif try_nlevels is None:
        try_nlevels = int(np.log2(nbin))
    try_nlevels = min(try_nlevels, int(np.log2(nbin)))

    best_score = jnp.full((nchan,), -jnp.inf, port.dtype)
    best_sm = jnp.zeros_like(port)
    for ilevel in range(try_nlevels):
        scores, facts, smoothed, _ = jax.vmap(
            lambda p: _smooth_score_grid(
                p, ilevel + 1, threshtype, N, nfact, fact_max, rchi2_tol
            )
        )(port)
        i = jnp.argmax(scores, axis=-1)
        sc = jnp.take_along_axis(scores, i[:, None], axis=-1)[:, 0]
        sm = jnp.take_along_axis(
            smoothed, i[:, None, None], axis=1
        )[:, 0, :]
        better = sc > best_score
        best_score = jnp.where(better, sc, best_score)
        best_sm = jnp.where(better[:, None], sm, best_sm)

    # zero out profiles whose best smoothing never met the chi2 gate,
    # and all-zero inputs (reference skips them / zeroes them)
    ok = (best_score > 0.0) & jnp.any(port != 0.0, axis=-1)
    best_sm = jnp.where(ok[:, None], best_sm, 0.0)
    return best_sm[0] if one_prof else best_sm
