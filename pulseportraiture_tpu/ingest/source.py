"""Ingest sources: where the observatory pipeline's archives come from.

Both sources share one tiny contract the driver polls:

  ``poll()``   -> list of (path, wait_s) newly admissible since the
                  last call (wait_s = discovery -> admission latency,
                  what bench_ingest's p50/p99 gate measures)
  ``defer(p)`` -> put a path back for a later retry (the driver calls
                  this on a truncation probe failure or serve
                  backpressure; the path re-admits once stable again)
  ``pending()``-> paths seen but not yet admissible (for drain logic)
  ``name``     -> telemetry label ('folder:<dir>' / 'socket:<ep>')

The WATCH-FOLDER source is the workhorse: telescope backends write
archives into a directory, usually in many chunks over seconds.  A
file is admitted only when (a) a ``<name>.done`` completion sentinel
sits next to it — the writer declares completeness explicitly — or
(b) its (size, mtime) signature has been UNCHANGED for
config.ingest_stable_ms.  Size-stability is a heuristic (a stalled
writer looks stable), which is why the driver ALSO runs the
io.scan_fits truncation probe before loading; the two layers together
make half-written PSRFITS unreachable by the loaders.

The SOCKET source is push-style: peers announce host-visible archive
paths over the serve/transport.py length-prefixed JSON framing (no
bulk data on the wire — the same shared-filesystem assumption the
remote serve transport makes).  An announcement declares completeness,
but announced files still pass the driver's truncation probe.
"""

import fnmatch
import os
import socket
import threading
import time

from .. import config
from ..serve.transport import TransportError, _recv_frame, _send_frame

__all__ = ["WatchFolderSource", "SocketSource", "announce"]


class WatchFolderSource:
    """Poll a directory for complete archives.

    folder:    directory to watch (must exist).
    patterns:  fnmatch patterns for candidate files (default
               ('*.fits',)); sentinel files are never candidates.
    poll_ms:   advisory poll cadence for the driver's idle sleep
               (default config.ingest_poll_ms) — poll() itself is
               cheap and stateless about time.
    stable_ms: size-stability window (default config.ingest_stable_ms).
    sentinel_suffix: completion-sentinel suffix ('.done'): the writer
               creates '<archive>.done' to bypass the stability wait.
    """

    def __init__(self, folder, patterns=("*.fits",), poll_ms=None,
                 stable_ms=None, sentinel_suffix=".done"):
        if not os.path.isdir(folder):
            raise ValueError(
                f"WatchFolderSource: {folder!r} is not a directory")
        self.folder = os.path.abspath(folder)
        self.patterns = tuple(patterns)
        self.poll_ms = (config.ingest_poll_ms if poll_ms is None
                        else float(poll_ms))
        self.stable_ms = (config.ingest_stable_ms if stable_ms is None
                          else float(stable_ms))
        self.sentinel_suffix = str(sentinel_suffix)
        self.name = f"folder:{self.folder}"
        # path -> {'sig': (size, mtime), 'first': t, 'changed': t}
        self._watch = {}
        self._admitted = set()

    def _candidates(self):
        for entry in sorted(os.listdir(self.folder)):
            if entry.endswith(self.sentinel_suffix):
                continue
            if any(fnmatch.fnmatch(entry, p) for p in self.patterns):
                yield os.path.join(self.folder, entry)

    def poll(self):
        """One admission pass -> list of (path, wait_s), in stable
        name order (deterministic for a fixed corpus)."""
        now = time.monotonic()
        out = []
        for path in self._candidates():
            if path in self._admitted:
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue  # vanished between listdir and stat
            sig = (st.st_size, st.st_mtime)
            ent = self._watch.get(path)
            if ent is None or ent["sig"] != sig:
                first = ent["first"] if ent else now
                self._watch[path] = {"sig": sig, "first": first,
                                     "changed": now}
                ent = self._watch[path]
                # a changed file is by definition not stable yet; only
                # the explicit sentinel overrides
                if not os.path.exists(path + self.sentinel_suffix):
                    continue
            stable = (now - ent["changed"]) * 1e3 >= self.stable_ms
            if stable or os.path.exists(path + self.sentinel_suffix):
                self._admitted.add(path)
                self._watch.pop(path, None)
                out.append((path, now - ent["first"]))
        return out

    def defer(self, path):
        """Put an admitted path back for a later retry: its stability
        clock restarts, so it re-admits only after staying unchanged
        for another stable_ms (or via its sentinel)."""
        now = time.monotonic()
        self._admitted.discard(path)
        try:
            st = os.stat(path)
            sig = (st.st_size, st.st_mtime)
        except OSError:
            sig = None
        # keep the original discovery time so wait_s stays honest
        first = self._watch.get(path, {}).get("first", now)
        self._watch[path] = {"sig": sig, "first": first, "changed": now}

    def pending(self):
        return sorted(self._watch)


class SocketSource:
    """Accept archive-path announcements over the serve wire framing.

    Frames (4-byte BE length + JSON, zlib marker bit honored):
      {"op": "ingest", "datafiles": [path, ...]} -> {"ok": true, "n": n}
      {"op": "stat"} -> {"ok": true, "pending": n}
      anything else -> {"ok": false, "error": msg}
    Use as a context manager or call start()/stop(); ``endpoint`` is
    the bound (host, port) — port 0 binds ephemeral.
    """

    def __init__(self, listen="127.0.0.1:0"):
        host, port = config.parse_hostport(listen)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.endpoint = self._sock.getsockname()
        self.name = f"socket:{self.endpoint[0]}:{self.endpoint[1]}"
        self._lock = threading.Lock()
        self._queue = []       # (path, t_announced)
        self._deferred = []
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="ppt-ingest-socket")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            # connect to unblock accept()
            with socket.create_connection(self.endpoint, timeout=1.0):
                pass
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._sock.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                break
            if self._stop.is_set():
                conn.close()
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        with conn:
            conn.settimeout(30.0)
            while True:
                try:
                    msg = _recv_frame(conn)
                except (TransportError, OSError):
                    return
                op = msg.get("op")
                if op == "ingest":
                    files = [str(f) for f in msg.get("datafiles", [])]
                    now = time.monotonic()
                    with self._lock:
                        self._queue.extend((f, now) for f in files)
                    _send_frame(conn, {"ok": True, "n": len(files)})
                elif op == "stat":
                    with self._lock:
                        n = len(self._queue) + len(self._deferred)
                    _send_frame(conn, {"ok": True, "pending": n})
                else:
                    _send_frame(conn, {"ok": False,
                                       "error": f"unknown op {op!r}"})
                    return

    def poll(self):
        now = time.monotonic()
        with self._lock:
            out = [(p, now - t) for p, t in self._queue]
            out += [(p, now - t) for p, t in self._deferred]
            self._queue = []
            self._deferred = []
        return out

    def defer(self, path):
        # no stability clock to restart: the announcer declared the
        # file complete, so a deferral (truncation / backpressure)
        # just re-queues it for the next poll
        with self._lock:
            self._deferred.append((path, time.monotonic()))

    def pending(self):
        with self._lock:
            return sorted(p for p, _ in self._queue + self._deferred)


def announce(endpoint, datafiles):
    """Client helper: announce host-visible archive paths to a
    SocketSource at 'host:port' (or a (host, port) tuple).  Returns
    the acknowledged count; raises TransportError on a refused or
    misbehaving peer."""
    if isinstance(endpoint, str):
        endpoint = config.parse_hostport(endpoint)
    files = ([datafiles] if isinstance(datafiles, str)
             else [str(f) for f in datafiles])
    try:
        with socket.create_connection(tuple(endpoint),
                                      timeout=10.0) as sock:
            _send_frame(sock, {"op": "ingest", "datafiles": files})
            reply = _recv_frame(sock)
    except OSError as e:
        raise TransportError(f"announce to {endpoint}: {e}")
    if not reply.get("ok"):
        raise TransportError(
            f"announce to {endpoint} refused: {reply.get('error')}")
    return int(reply.get("n", len(files)))
