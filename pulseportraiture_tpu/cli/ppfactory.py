"""ppfactory — build templates for a whole fleet of pulsars, batching
the Gaussian/spline LM fits across archives (pipeline/factory.py,
ISSUE 9).  One archive per line in the metafile, one template out per
archive (this is NOT ppgauss's JOIN metafile mode — multi-receiver
fits keep ppgauss).
"""

import argparse
import os
import sys

GAUSS_DEVICE_CHOICES = ("off", "auto", "on")
_GAUSS_DEVICE_TABLE = {"off": False, "auto": "auto", "on": True}


def parse_gauss_device(value, error=None):
    """Strict --gauss-device parse shared by ppfactory/ppgauss/
    ppspline: 'off' | 'auto' | 'on' -> the config tri-state value;
    anything else dies loudly BEFORE any file IO (SystemExit carries
    the message, the ppserve convention)."""
    v = str(value).lower()
    if v not in _GAUSS_DEVICE_TABLE:
        raise SystemExit(f"--gauss-device expected one of "
                         f"{'/'.join(GAUSS_DEVICE_CHOICES)}, got "
                         f"{value!r}")
    return _GAUSS_DEVICE_TABLE[v]


LM_JACOBIAN_CHOICES = ("auto", "analytic", "ad")


def parse_lm_jacobian(value, error=None):
    """Strict --lm-jacobian parse shared by ppfactory/ppgauss:
    'auto' | 'analytic' | 'ad' -> config.lm_jacobian; anything else
    dies loudly BEFORE any file IO."""
    v = str(value).lower()
    if v not in LM_JACOBIAN_CHOICES:
        raise SystemExit(f"--lm-jacobian expected one of "
                         f"{'/'.join(LM_JACOBIAN_CHOICES)}, got "
                         f"{value!r}")
    return v


def apply_lm_jacobian(value):
    """Apply a parsed --lm-jacobian to config (the knob is resolved
    inside fit/lm per call, so setting the module value routes every
    LM fit of this process — exactly the A/B the flag exists for)."""
    if value is None:
        return None
    from .. import config

    config.lm_jacobian = parse_lm_jacobian(value)
    return config.lm_jacobian


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppfactory", description=__doc__.splitlines()[0])
    p.add_argument("-M", "--metafile", required=True,
                   help="Metafile: one archive per line, one template "
                        "per archive.")
    p.add_argument("-O", "--outdir", default=None,
                   help="Directory for the output model files "
                        "[default: next to each archive].")
    p.add_argument("--kind", default="gauss",
                   choices=("gauss", "spline"),
                   help="Template type for every job.")
    p.add_argument("--max-ngauss", type=int, default=8,
                   help="Trial component counts 1..N fit per pulsar "
                        "in one breadth-first dispatch.")
    p.add_argument("--niter", type=int, default=0,
                   help="Portrait iterations after the initial fit.")
    p.add_argument("--mcode", dest="model_code", default="000",
                   help="Three-digit evolution-function code.")
    p.add_argument("--fitloc", dest="fixloc", action="store_false",
                   default=True, help="Let component positions evolve.")
    p.add_argument("--fixwid", action="store_true", default=False)
    p.add_argument("--fixamp", action="store_true", default=False)
    p.add_argument("--fitscat", dest="fixscat", action="store_false",
                   default=True, help="Fit a scattering timescale.")
    p.add_argument("--fitalpha", dest="fixalpha", action="store_false",
                   default=True, help="Fit the scattering index.")
    p.add_argument("--norm", dest="normalize", default=None,
                   choices=(None, "mean", "max", "prof", "rms", "abs"))
    p.add_argument("--gauss-device", default=None,
                   help="LM lane: 'off' (host-serial oracle), 'auto' "
                        "(batched on TPU), 'on' (force batched) "
                        "[default: config.gauss_device].")
    p.add_argument("--lm-jacobian", dest="lm_jacobian", default=None,
                   help="LM Jacobian source: 'auto' (analytic when the "
                        "model provides one), 'analytic' (require it), "
                        "'ad' (force jax.jacfwd — the digit oracle) "
                        "[default: config.lm_jacobian].")
    p.add_argument("--telemetry", default=None,
                   help="Write a JSONL event trace (template_fit "
                        "events; analyze with tools/pptrace.py).")
    from .ppserve import add_cache_flags

    add_cache_flags(p)
    p.add_argument("--verbose", dest="quiet", action="store_false",
                   default=True)
    return p


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    gauss_device = None
    if args.gauss_device is not None:
        gauss_device = parse_gauss_device(args.gauss_device)
    apply_lm_jacobian(args.lm_jacobian)
    if args.max_ngauss < 1:
        raise SystemExit(f"--max-ngauss must be >= 1, got "
                         f"{args.max_ngauss}")
    if args.niter < 0:
        raise SystemExit(f"--niter must be >= 0, got {args.niter}")
    if not os.path.exists(args.metafile):
        raise SystemExit(f"ppfactory: metafile not found: "
                         f"{args.metafile}")
    from ..pipeline.toas import _read_metafile

    files = _read_metafile(args.metafile)
    if not files:
        raise SystemExit(f"ppfactory: no archives listed in "
                         f"{args.metafile}")
    from ..pipeline.factory import build_templates
    from ..serve.cache import content_key, resolve_result_cache
    from .ppserve import apply_cache_flags

    apply_cache_flags(args, "ppfactory")
    # template-factory artifacts cache through the same content-
    # addressed store as TOA results (ISSUE 17): key = the archive's
    # bytes + the full factory option vector (any flag change
    # invalidates), value = the finished .gmodel/.spl bytes.  A hit
    # writes the stored artifact and skips the whole LM build.
    cache = resolve_result_cache()
    factory_opts = dict(
        kind=args.kind, max_ngauss=args.max_ngauss, niter=args.niter,
        model_code=args.model_code, fixloc=args.fixloc,
        fixwid=args.fixwid, fixamp=args.fixamp, fixscat=args.fixscat,
        fixalpha=args.fixalpha, normalize=args.normalize,
        gauss_device=gauss_device)
    ext = ".gmodel" if args.kind == "gauss" else ".spl"

    def outfile_for(f):
        # mirrors build_templates' derivation exactly
        if args.outdir:
            return os.path.join(args.outdir, os.path.basename(f) + ext)
        return f + ext

    build, keys, n_hits = list(files), {}, 0
    if cache is not None:
        if args.outdir:
            os.makedirs(args.outdir, exist_ok=True)
        build = []
        for f in files:
            try:
                keys[f] = content_key([f], factory_opts)
            except OSError:
                keys[f] = None  # unreadable: the build reports it
            blob = cache.get_blob(keys[f]) if keys[f] else None
            if blob is None:
                build.append(f)
                continue
            out = outfile_for(f)
            tmp = out + ".tmp~"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, out)
            n_hits += 1
    if build:
        build_templates(
            build, kind=args.kind, outdir=args.outdir,
            max_ngauss=args.max_ngauss, niter=args.niter,
            model_code=args.model_code, fixloc=args.fixloc,
            fixwid=args.fixwid, fixamp=args.fixamp,
            fixscat=args.fixscat, fixalpha=args.fixalpha,
            normalize=args.normalize, gauss_device=gauss_device,
            telemetry=args.telemetry, quiet=args.quiet)
    if cache is not None:
        for f in build:
            out = outfile_for(f)
            if keys.get(f) and os.path.exists(out):
                with open(out, "rb") as fh:
                    cache.put_blob(keys[f], fh.read())
        if not args.quiet:
            print(f"ppfactory: {n_hits}/{len(files)} template(s) "
                  "served from the result cache")
    return 0


if __name__ == "__main__":
    sys.exit(main())
