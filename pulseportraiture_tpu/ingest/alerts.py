"""Anomaly alerting on the timing-residual stream (ISSUE 18, layer 3).

Three detectors ride the incremental GLS lane's output:

* GLITCH — a pulsar glitch is an ACHROMATIC step in rotational phase
  (and usually frequency): every post-glitch arrival lands early by
  the same amount at every observing frequency.  The detector runs a
  two-sided CUSUM on the newest TOA's whitened post-fit time residual
  — before the fit's global columns can re-absorb a recent step, the
  newest residuals carry it almost in full.
* DM STEP — an interstellar-medium event moves the dispersion measure:
  a CHROMATIC nu^-2 delay signature across the band, which the
  wideband pipeline has already collapsed into per-TOA DM
  measurements.  The detector CUSUMs each COMPLETED epoch's
  error-weighted MEASURED DM against the median of the epochs before
  it (one sample per epoch: the running estimate of an open epoch
  would double-count).  It deliberately rides the measured stream,
  NOT the fitted per-epoch DMX: at a single band-center frequency per
  TOA a DMX column doubles as a free per-epoch time offset, so the
  GLS absorbs any unmodeled ACHROMATIC step (a glitch!) into DMX —
  far cheaper in chi^2 than leaving microseconds in the time rows —
  and the fitted stream chromatically confuses the two event kinds.
  The measured DMs come straight from each archive's portrait fit and
  cannot be moved by the timing solution.
* PROFILE CHANGE — mode changes / instrumental trouble reshape the
  pulse profile without moving its arrival time: the portrait fit's
  per-TOA reduced chi^2 (the same statistic the quality gates ride)
  rises persistently.  The detector CUSUMs the gof excess over 1.

CUSUM (Page 1954): with standardized innovations z_i, accumulate
S+ = max(0, S+ + z - k) and S- = max(0, S- - z - k); an alert fires
when either crosses h.  k (config.alert_cusum_k) sets the smallest
drift that accumulates — half the step size you care about is the
classic choice — and h (config.alert_cusum_h) trades detection delay
against false alarms.  After an alarm the sums reset (one event, one
alert).

Every alert emits the ``alert`` telemetry event (kind/pulsar/mjd/
score/threshold) that pptrace's alerts section and the n_alert /
alert_fp_rate summary keys aggregate.  For synthetic corpora,
``known_events`` lets the monitor tag each alert ``fp``
(false-positive) against ground truth so the bench can gate detection
quality.
"""

import numpy as np

from .. import config
from ..telemetry import NULL_TRACER, finite

__all__ = ["CusumDetector", "AlertMonitor"]


class CusumDetector:
    """Two-sided standardized CUSUM with reset-on-alarm.

    ``update(z)`` -> None, or the crossing score (signed: negative
    means the low-side sum crossed) when |S| first exceeds h.  After a
    crossing ``last_lag`` holds the number of samples since the
    estimated CHANGE ONSET, so the alert localizes the event rather
    than the (possibly delayed) detection.  The onset estimate starts
    from the classic one — where the crossing side's sum last left
    zero — then skips leading samples whose contribution (|z| - k) is
    a negligible fraction of the window's average: a single weak noise
    sample that happened to lift the sum off zero just before a hard
    step must not pull the onset early, while a slow drift (all
    contributions comparable) still localizes at its true start.
    """

    def __init__(self, k=None, h=None):
        self.k = config.alert_cusum_k if k is None else float(k)
        self.h = config.alert_cusum_h if h is None else float(h)
        if self.h <= 0:
            raise ValueError(f"CusumDetector: h must be > 0, got "
                             f"{self.h}")
        self.s_pos = 0.0
        self.s_neg = 0.0
        self.n = 0
        self.last_lag = None
        self._pos_start = None  # sample index where s_pos left zero
        self._neg_start = None
        self._zs = []           # z history; sample n -> _zs[n-1-_z0]
        self._z0 = 0

    def _onset(self, start, sign, score):
        window = [sign * self._zs[i - 1 - self._z0] - self.k
                  for i in range(start, self.n + 1)]
        floor = 0.5 * abs(score) / len(window)
        for off, c in enumerate(window):
            if c >= floor:
                return start + off
        return start

    def update(self, z):
        z = float(z)
        self.n += 1
        self._zs.append(z)
        if len(self._zs) > 8192:
            drop = len(self._zs) - 4096
            self._zs = self._zs[drop:]
            self._z0 += drop
        prev_pos, prev_neg = self.s_pos, self.s_neg
        self.s_pos = max(0.0, self.s_pos + z - self.k)
        self.s_neg = max(0.0, self.s_neg - z - self.k)
        if self.s_pos > 0 and prev_pos == 0:
            self._pos_start = self.n
        if self.s_neg > 0 and prev_neg == 0:
            self._neg_start = self.n
        if self.s_pos > self.h or self.s_neg > self.h:
            pos = self.s_pos > self.h
            score = self.s_pos if pos else -self.s_neg
            start = self._pos_start if pos else self._neg_start
            start = max(start or self.n, self._z0 + 1)
            onset = self._onset(start, 1.0 if pos else -1.0, score)
            self.last_lag = self.n - onset + 1
            self.reset()
            return score
        return None

    def reset(self):
        self.s_pos = 0.0
        self.s_neg = 0.0
        self._pos_start = None
        self._neg_start = None


class AlertMonitor:
    """Chain the detectors onto an incremental timing stream.

    pulsar:  label for the alert events.
    warmup:  ignore the first ``warmup`` observations on the per-TOA
             arms (glitch / profile) — the earliest fits swing while
             the solution is still rank-poor, and those transients are
             not anomalies.
    dm_warmup: minimum PRIOR epochs before the DM arm feeds its CUSUM
             (default 4 — enough to estimate the epochs' intrinsic
             scatter robustly; a shorter baseline's noisy median both
             false-alarms and mislocalizes).  The arm samples once per
             completed epoch against the median of all prior epochs,
             so it self-stabilizes much faster than the per-TOA arms —
             a per-TOA-sized warmup would swallow genuine early-epoch
             steps.
    epoch_gap_days: observations separated by more than this close the
             running DM epoch (default 0.5, the incremental lane's
             epoch rule; the arm groups arrival-ordered TOAs itself so
             the measured stream needs no fit at all).
    min_amp_sigma: a dm_step alert must also carry an amplitude of at
             least this many measurement sigmas (default 3.0): the
             CUSUM's accumulate-small-drifts strength is a weakness
             for ALERTING, where a 2-sigma wiggle that technically
             crossed h is noise, not an ISM event.  Crossings below
             the floor are dropped silently (no refractory advance).
    max_gof: profile-change arm's reference gof (default
             config.quality_max_gof): the CUSUM accumulates gof - 1
             and uses (max_gof - 1) as its k, so only persistent
             excess beyond fit noise accumulates.
    known_events: optional list of {'kind', 'mjd'[, 'window_days']}
             ground-truth events; each alert is then tagged
             ``fp=True/False`` by proximity (default window 5 days) —
             the bench's detection/false-alarm gates read this.
    refractory_days: suppress repeat alerts of one kind within this
             many days of the previous crossing (default 30).  A
             persistent step keeps re-crossing a reset CUSUM until the
             fit absorbs it; chain-suppression collapses that tail
             into the single alert the event deserves, while a
             genuinely new event after a quiet gap fires fresh.

    Feed it per TOA:  ``observe(result, toa[, gof=...])`` with the
    WidebandGLSResult the incremental lane returned AFTER folding
    ``toa`` in; call ``finish()`` once the stream ends to score the
    final (still-open) measured-DM epoch.  Fired alerts accumulate in
    ``.alerts`` and emit telemetry as they happen.
    """

    def __init__(self, pulsar, tracer=None, k=None, h=None, warmup=4,
                 dm_warmup=4, epoch_gap_days=0.5, min_amp_sigma=3.0,
                 max_gof=None, known_events=None,
                 refractory_days=30.0):
        self.pulsar = str(pulsar)
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.warmup = int(warmup)
        self.dm_warmup = int(dm_warmup)
        self.epoch_gap_days = float(epoch_gap_days)
        self.min_amp_sigma = float(min_amp_sigma)
        self.max_gof = (config.quality_max_gof if max_gof is None
                        else float(max_gof))
        self.known_events = ([dict(e) for e in known_events]
                             if known_events is not None else None)
        self.glitch = CusumDetector(k=k, h=h)
        self.dm = CusumDetector(k=k, h=h)
        self.profile = CusumDetector(k=max(self.max_gof - 1.0, 0.0)
                                     if k is None else k, h=h)
        self.refractory_days = float(refractory_days)
        self._last_cross = {}  # kind -> mjd of last crossing
        self.alerts = []
        self._n_obs = 0
        self._dm_fed = []      # epoch index per fed DM-arm sample
        self._ep_means = []    # closed epochs: weighted-mean measured DM
        self._ep_errs = []     # ... and its standard error
        self._ep_mjds = []     # ... and first observed-TOA MJD
        self._cur_w = 0.0      # open epoch: sum of 1/err^2
        self._cur_wd = 0.0     # ... sum of dm/err^2
        self._cur_first = None
        self._cur_last = None
        self._mjds = []        # observed-TOA MJDs, arrival order

    # -- emission -------------------------------------------------------

    def _emit(self, kind, mjd, score, threshold, **extra):
        # chain-suppression: every crossing advances the refractory
        # clock, so a persistent step's re-fires collapse into the one
        # alert already emitted
        last = self._last_cross.get(kind)
        self._last_cross[kind] = float(mjd)
        if last is not None and \
                float(mjd) - last <= self.refractory_days:
            return None
        alert = {"kind": kind, "pulsar": self.pulsar,
                 "mjd": float(mjd), "score": float(score),
                 "threshold": float(threshold)}
        if self.known_events is not None:
            alert["fp"] = not any(
                e["kind"] == kind
                and abs(float(e["mjd"]) - float(mjd))
                <= float(e.get("window_days", 5.0))
                for e in self.known_events)
        alert.update(extra)
        self.alerts.append(alert)
        if self.tracer.enabled:
            self.tracer.emit(
                "alert", kind=kind, pulsar=self.pulsar,
                mjd=finite(mjd, 6), score=finite(score, 3),
                threshold=finite(threshold, 3),
                **{k: (finite(v) if isinstance(v, float) else v)
                   for k, v in alert.items()
                   if k not in ("kind", "pulsar", "mjd", "score",
                                "threshold")})
        return alert

    # -- the observation hooks -----------------------------------------

    def _close_epoch(self):
        """Finalize the open measured-DM epoch and CUSUM it against
        the median of the epochs before it."""
        if not self._cur_w > 0:
            return
        j = len(self._ep_means)
        mean = self._cur_wd / self._cur_w
        err = float(np.sqrt(1.0 / self._cur_w))
        self._ep_means.append(float(mean))
        self._ep_errs.append(err)
        self._ep_mjds.append(float(self._cur_first))
        self._cur_w = self._cur_wd = 0.0
        self._cur_first = self._cur_last = None
        if j < self.dm_warmup:
            # too few prior epochs for a scatter estimate — don't
            # feed the detector at all (a fed-but-unemittable
            # crossing would silently consume the event)
            return
        prior = np.asarray(self._ep_means[:j], float)
        base = float(np.median(prior))
        if not err > 0:
            return
        # standardize by the measurement error and the prior epochs'
        # robust scatter in quadrature: a pulsar with intrinsic
        # epoch-to-epoch DM wander (ISM turbulence) has innovation
        # scatter beyond the formal error, and a CUSUM fed z's of
        # std > 1 turns that wander into false alarms.  The MAD is
        # immune to the few post-step outliers; the quadrature sum
        # double-counts err slightly (the scatter estimate already
        # contains it), which errs on the quiet side — the right bias
        # for an alerting system whose scatter estimate rides a
        # handful of epochs.
        scatter = 1.4826 * float(np.median(np.abs(prior - base)))
        z = (mean - base) / float(np.hypot(err, scatter))
        self._dm_fed.append(j)
        score = self.dm.update(z)
        if score is None:
            return
        # localize at the CUSUM change onset, not the (maybe delayed)
        # crossing epoch, at that epoch's first observed TOA
        lag = self.dm.last_lag or 1
        j0 = (self._dm_fed[-lag] if lag <= len(self._dm_fed) else j)
        base0 = float(np.median(np.asarray(self._ep_means[:j0],
                                           float)))
        amp = float(np.median(np.asarray(self._ep_means[j0:j + 1],
                                         float)) - base0)
        if abs(amp) < self.min_amp_sigma * self._ep_errs[j0]:
            return  # a sub-floor crossing is noise
        self._emit("dm_step", self._ep_mjds[j0], score, self.dm.h,
                   epoch=int(j0), amp=amp)

    def observe(self, result, toa, gof=None):
        """One TOA folded into the incremental solution.  Returns the
        alerts fired by this observation."""
        n_before = len(self.alerts)
        mjd = float(toa.mjd_int) + float(toa.mjd_frac)
        if toa.dm is not None and toa.dm_err:
            # mirrors the lane's usability test so _mjds stays aligned
            # with the fit's residual stream (arrival order, usable
            # TOAs only)
            self._mjds.append(mjd)
            # DM arm: accumulate the measured DM into the running
            # epoch; a gap beyond epoch_gap_days closes it and scores
            # the completed epoch
            if self._cur_last is not None and \
                    mjd - self._cur_last > self.epoch_gap_days:
                self._close_epoch()
            if self._cur_first is None:
                self._cur_first = mjd
            w = 1.0 / float(toa.dm_err) ** 2
            self._cur_w += w
            self._cur_wd += w * float(toa.dm)
            self._cur_last = mjd
        if result is not None:
            # glitch arm: the newest whitened post-fit time residual
            self._n_obs += 1
            z = (float(result.time_resids_us[-1])
                 / float(result.toa_errs_us[-1]))
            score = self.glitch.update(z)
            if score is not None and self._n_obs > self.warmup:
                # localize at the change start (glitch sample i rode
                # the i+1-th usable TOA: the first usable TOA yields
                # no fit yet)
                lag = self.glitch.last_lag or 1
                idx = len(self._mjds) - lag
                mjd_ev = (self._mjds[idx]
                          if 0 <= idx < len(self._mjds) else mjd)
                self._emit("glitch", mjd_ev, score, self.glitch.h)
        if gof is None and getattr(toa, "flags", None):
            gof = toa.flags.get("gof")
        if gof is not None:
            # one-sided: only EXCESS gof is an anomaly — a stream
            # whose gof sits persistently below 1 (conservative error
            # bars) must not accumulate on the low side
            score = self.profile.update(max(float(gof) - 1.0, 0.0))
            if score is not None and self.profile.n > self.warmup:
                self._emit("profile_change", mjd, score,
                           self.profile.h, gof=float(gof))
        return self.alerts[n_before:]

    def finish(self):
        """Score the final (still-open) measured-DM epoch; call when
        the stream ends.  Returns alerts fired."""
        n_before = len(self.alerts)
        self._close_epoch()
        return self.alerts[n_before:]
