"""Template-model dispatch: one entry point that accepts a .gmodel
text file, a spline model (.spl pickle / .npz), or a PSRFITS archive
as the template, mirroring the reference's try/except dispatch
(pptoas.py:392-419 and is_FITS_model pptoas.py:111,358-377) but keyed
on file magic instead of parse failures.
"""

import numpy as np

from ..io.gmodel import gen_gmodel_portrait, read_gmodel
from ..io.splmodel import read_spline_model
from ..utils.device import host_compute


def sniff_model_type(path):
    """'fits' | 'gmodel' | 'spline' by magic bytes / parseability
    (replaces the reference's `file -L` subprocess, pplib.py:3126)."""
    with open(path, "rb") as f:
        head = f.read(512)
    if head.startswith(b"SIMPLE"):
        return "fits"
    if head.startswith(b"PK\x03\x04") or str(path).endswith(".npz"):
        return "spline"
    try:
        text = head.decode("ascii")
    except UnicodeDecodeError:
        return "spline"  # pickle
    for line in text.splitlines():
        if line.split() and line.split()[0] in ("MODEL", "CODE", "FREQ"):
            return "gmodel"
    return "spline"


class TemplateModel:
    """A loaded template of any kind, evaluated lazily per (freqs,
    nbin, P) with caching — the reference re-parses and regenerates the
    model for every subint (SURVEY §3.1 'known inefficiency'); here the
    portrait is built once per unique frequency layout."""

    def __init__(self, modelfile, quiet=True):
        self.modelfile = str(modelfile)
        self.kind = sniff_model_type(modelfile)
        self._cache = {}
        self.gauss = None
        self.spline = None
        self.fits_port = None
        self.fits_freqs = None
        if self.kind == "gmodel":
            self.gauss = read_gmodel(modelfile, quiet=quiet)
            self.name = self.gauss.name
            self.nu_ref_model = self.gauss.nu_ref
        elif self.kind == "spline":
            self.spline = read_spline_model(modelfile, quiet=quiet)
            self.name = self.spline.modelname
            lo, hi = self.spline.freq_range()
            self.nu_ref_model = 0.5 * (lo + hi)
        else:
            from ..io.psrfits import load_data

            td = load_data(modelfile, dedisperse=True, pscrunch=True,
                           tscrunch=True, quiet=quiet)
            self.fits_port = np.asarray(td.subints[0, 0])
            self.fits_freqs = np.asarray(td.freqs[0])
            self.name = td.source
            self.nu_ref_model = float(td.nu0)

    @property
    def is_gaussian(self):
        return self.kind == "gmodel"

    def has_scattering(self):
        return self.kind == "gmodel" and self.gauss.tau != 0.0

    def portrait(self, freqs, nbin, P=None):
        """(nchan, nbin) model portrait at the given channel
        frequencies.  FITS templates require matching nbin and are
        matched channel-by-nearest-frequency."""
        freqs = np.atleast_1d(np.asarray(freqs, float))
        key = (freqs.tobytes(), int(nbin),
               None if P is None else round(float(P), 12))
        if key in self._cache:
            return self._cache[key]
        if self.kind in ("gmodel", "spline"):
            # one-time template generation uses complex phasors, which
            # some TPU runtimes cannot compile — build on host
            with host_compute():
                if self.kind == "gmodel":
                    port = gen_gmodel_portrait(self.gauss, np.arange(nbin),
                                               freqs, P=P, quiet=True)
                else:
                    port = self.spline.portrait(freqs, nbin=nbin)
        else:
            if self.fits_port.shape[-1] != nbin:
                raise ValueError(
                    f"FITS template nbin={self.fits_port.shape[-1]} != "
                    f"data nbin={nbin}")
            idx = np.abs(self.fits_freqs[None, :]
                         - freqs[:, None]).argmin(axis=1)
            port = self.fits_port[idx]
        port = np.asarray(port)
        self._cache[key] = port
        return port
