"""Synthetic data generation — the framework's test fixture.

Generates data portraits with *known injected* (phi, DM, GM, tau,
alpha, per-channel scales, noise, RFI mask, scintillation), so every
fit engine and pipeline can be validated by parameter recovery — the
reference's own end-to-end verification pattern (make_fake_pulsar,
reference pplib.py:3302-3499, driven by examples/example.py).

This module is portrait-level (pure arrays); the PSRFITS-archive
writer wrapping it lives in io/psrfits.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gaussian import GaussianModel, gen_gaussian_portrait
from ..ops.phasor import phase_shifts
from ..ops.phasor import phasor as make_phasor
from ..ops.scattering import add_scattering, scattering_times
from ..utils.bunch import DataBunch


def default_test_model(nu_ref=1500.0):
    """A 3-component evolving-Gaussian model like the reference's
    examples/example.gmodel (values chosen fresh, same structure)."""
    return GaussianModel(
        name="FAKE_0000+0000",
        code="000",
        nu_ref=nu_ref,
        dc=0.0,
        tau=0.0,
        alpha=-4.0,
        locs=np.array([0.48, 0.505, 0.52]),
        wids=np.array([0.045, 0.015, 0.022]),
        amps=np.array([4.0, 9.5, 2.5]),
        mlocs=np.array([-0.005, -0.003, 0.003]),
        mwids=np.array([-0.2, 0.16, -0.3]),
        mamps=np.array([-1.6, -2.0, -0.9]),
    )


def fake_portrait(
    key,
    model,
    freqs,
    nbin,
    P,
    phi=0.0,
    DM=0.0,
    GM=0.0,
    tau=0.0,
    alpha=None,
    nu_ref=None,
    scales=None,
    noise_std=1.0,
    zap_frac=0.0,
    scint_nsin=0,
    dtype=jnp.float64,
):
    """One (nchan, nbin) data portrait with known injected parameters.

    phi/DM/GM are referenced to ``nu_ref`` (default: model.nu_ref); a
    fit of this portrait against the clean model should recover them
    there.  ``tau`` [s at nu_ref] scatters with index ``alpha``;
    ``scales`` (nchan,) multiplies channels; ``noise_std`` adds white
    noise; ``zap_frac`` randomly zero-weights channels.

    Returns a DataBunch with port, model_port, weights, noise_stds,
    freqs, P and the injected truth values.
    """
    freqs = jnp.asarray(freqs, dtype)
    nchan = freqs.shape[0]
    nu_ref = model.nu_ref if nu_ref is None else nu_ref
    alpha = model.alpha if alpha is None else alpha
    params = {k: v.astype(dtype) if hasattr(v, "astype") else v
              for k, v in model.params_pytree().items()}

    clean = gen_gaussian_portrait(
        params, freqs, model.nu_ref, nbin, P=P, code=model.code, scattered=False
    )

    port = clean
    if tau != 0.0:
        taus = scattering_times(tau / P, alpha, freqs, nu_ref)
        port = add_scattering(port, taus)

    # delay by the injected (phi, DM, GM): rotate to *later* phase so
    # that fitting returns positive (phi, DM, GM)
    delays = phase_shifts(phi, DM, GM, freqs, P, nu_ref, nu_ref)
    pFT = jnp.fft.rfft(port, axis=-1)
    pFT = pFT * jnp.conj(make_phasor(delays, pFT.shape[-1]))
    port = jnp.fft.irfft(pFT, n=nbin, axis=-1)

    if scint_nsin:
        k_s, key = jax.random.split(jax.random.PRNGKey(0) if key is None else key)
        x = jnp.linspace(0.0, scint_nsin * jnp.pi, nchan)
        pattern = jnp.sin(x + jax.random.uniform(k_s) * 2 * jnp.pi) ** 2.0 + 0.1
        port = port * pattern[:, None]

    if scales is not None:
        port = port * jnp.asarray(scales, dtype)[:, None]

    k_n, k_z = jax.random.split(key if key is not None else jax.random.PRNGKey(0))
    if noise_std:
        port = port + noise_std * jax.random.normal(k_n, port.shape, dtype)

    weights = jnp.ones(nchan, dtype)
    if zap_frac > 0.0:
        weights = jnp.where(
            jax.random.uniform(k_z, (nchan,)) < zap_frac, 0.0, 1.0
        ).astype(dtype)
        port = port * weights[:, None]

    return DataBunch(
        port=port,
        model_port=clean,
        freqs=freqs,
        weights=weights,
        noise_stds=jnp.full((nchan,), noise_std, dtype),
        P=P,
        nbin=nbin,
        nu_ref=nu_ref,
        phi=phi,
        DM=DM,
        GM=GM,
        tau=tau,
        alpha=alpha,
        scales=scales,
    )


def fake_observation(
    key,
    model,
    nsub=1,
    nchan=64,
    nbin=1024,
    P=0.002,
    lofreq=1200.0,
    bw=800.0,
    dDM_std=0.0,
    **kwargs,
):
    """A stack of subint portraits (nsub, nchan, nbin) with per-subint
    random dDMs drawn from N(0, dDM_std) — the shape pptoas consumes.

    Returns (DataBunch with subints stacked, injected dDMs array).
    """
    chan_bw = bw / nchan
    freqs = lofreq + chan_bw * (jnp.arange(nchan) + 0.5)
    keys = jax.random.split(key, nsub + 1)
    dDMs = dDM_std * np.asarray(
        jax.random.normal(keys[0], (nsub,), jnp.float64)
    )
    subs, truths = [], []
    base_DM = kwargs.pop("DM", 0.0)
    for isub in range(nsub):
        b = fake_portrait(
            keys[isub + 1], model, freqs, nbin, P,
            DM=base_DM + float(dDMs[isub]), **kwargs,
        )
        subs.append(b.port)
        truths.append(b)
    first = truths[0]
    return (
        DataBunch(
            subints=jnp.stack(subs),
            model_port=first.model_port,
            freqs=freqs,
            weights=jnp.stack([t.weights for t in truths]),
            noise_stds=jnp.stack([t.noise_stds for t in truths]),
            P=P,
            nbin=nbin,
            nu_ref=first.nu_ref,
            DMs=base_DM + dDMs,
        ),
        dDMs,
    )
