"""Pallas interpret-mode gates (ISSUE 16): the fused-fit kernel and the
raw-lane decode+DFT kernel, run under ``pallas_call(interpret=True)`` on
CPU, must be BITWISE identical to the hand-blocked scan programs they
replace — same twiddles, same tiling, same op order.  The lattice here
is the merge gate for any kernel edit; the compiled-TPU arm of the same
comparisons runs in the chip-session sweep (benchmarks/BENCHMARKS.md).

Everything compares jit-vs-jit: eager and jit execution differ by FMA /
reduction-order contraction (~1e-12), and the streaming bucket programs
are always jitted, so jit-vs-jit is both the strict and the deployed
comparison."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pulseportraiture_tpu import config
from pulseportraiture_tpu.ops import fused as F
from pulseportraiture_tpu.ops.decode import PACKED_BITS, decode_stokes_I

from fits_forge import forge_archive, gaussian_portrait


pytestmark = pytest.mark.skipif(
    not F.HAVE_PALLAS_FUSED, reason="jax.experimental.pallas unavailable")


def _problem(nchan, nbin, dt, seed=0):
    rng = np.random.default_rng(seed)
    port = jnp.asarray(rng.normal(size=(nchan, nbin)), dt)
    model = jnp.asarray(rng.normal(size=(nchan, nbin)), dt)
    nharm = nbin // 4
    w = jnp.asarray(rng.uniform(0.5, 2.0, size=(nchan, nharm)), dt)
    return port, model, w, nharm


def _assert_kernel_matches_scan(nchan, nbin, dt, fold, want_m2, block):
    port, model, w, nharm = _problem(nchan, nbin, dt)

    @jax.jit
    def scan(p, m, wk):
        return F.fused_cross_spectrum(p, m, wk, nharm, fold=fold,
                                      want_m2=want_m2, block=block,
                                      pallas=False)

    @jax.jit
    def kernel(p, m, wk):
        return F.fused_cross_spectrum_pallas(p, m, wk, nharm, fold=fold,
                                             want_m2=want_m2,
                                             block=block)

    ref = scan(port, model, w)
    got = kernel(port, model, w)
    for r, g, name in zip(ref, got, ("Xr", "Xi", "o2")):
        assert np.array_equal(np.asarray(r), np.asarray(g)), (
            f"{name} not bitwise at {nchan}x{nbin} {dt} fold={fold} "
            f"m2={want_m2} block={block}: maxdiff="
            f"{np.max(np.abs(np.asarray(r) - np.asarray(g)))}")


class TestFitKernelParity:
    """fused_cross_spectrum_pallas vs the scan, bitwise."""

    # One directed row per independent axis flip off a ragged-channel
    # base case (13 channels never divides the block): dtype, fold,
    # want_m2, block override, block-not-dividing-nchan, tiny shape.
    DIRECTED = [
        (13, 128, "float64", True, False, None),
        (13, 128, "float32", True, False, None),
        (13, 128, "float64", False, True, None),
        (13, 128, "float64", True, True, 5),
        (24, 256, "float32", False, False, 8),
        (8, 64, "float64", True, False, None),
    ]

    @pytest.mark.parametrize("nchan,nbin,dt,fold,want_m2,block", DIRECTED)
    def test_parity_directed(self, nchan, nbin, dt, fold, want_m2,
                             block):
        _assert_kernel_matches_scan(nchan, nbin, dt, fold, want_m2,
                                    block)

    @pytest.mark.slow
    @pytest.mark.parametrize("nchan,nbin", [(24, 256), (13, 128),
                                            (8, 64)])
    @pytest.mark.parametrize("dt", ["float64", "float32"])
    def test_parity_full_lattice(self, nchan, nbin, dt):
        for fold in (False, True):
            for want_m2 in (False, True):
                for block in (None, 8, 5):
                    _assert_kernel_matches_scan(nchan, nbin, dt, fold,
                                                want_m2, block)

    def test_vmap_shared_model_parity(self):
        """The deployed shape: vmapped over subints with the template
        model unbatched (in_axes=None hoists its per-block DFT)."""
        rng = np.random.default_rng(7)
        nb, nchan, nbin, nharm = 3, 16, 128, 32
        port = jnp.asarray(rng.normal(size=(nb, nchan, nbin)))
        model = jnp.asarray(rng.normal(size=(nchan, nbin)))
        w = jnp.asarray(rng.uniform(0.5, 2.0, size=(nb, nchan, nharm)))

        scan = jax.jit(jax.vmap(
            lambda p, wk: F.fused_cross_spectrum(p, model, wk, nharm,
                                                 pallas=False),
            (0, 0)))
        kern = jax.jit(jax.vmap(
            lambda p, wk: F.fused_cross_spectrum_pallas(p, model, wk,
                                                        nharm),
            (0, 0)))
        for r, g, name in zip(scan(port, w), kern(port, w),
                              ("Xr", "Xi", "S0")):
            assert np.array_equal(np.asarray(r), np.asarray(g)), name

    def test_dispatch_routes_and_threads_block(self, monkeypatch):
        """fused_cross_spectrum(pallas=True) reaches the kernel AND
        forwards the block override (the stub used to drop it)."""
        seen = {}
        orig = F.fused_cross_spectrum_pallas

        def spy(*a, **k):
            seen.update(k)
            return orig(*a, **k)

        monkeypatch.setattr(F, "fused_cross_spectrum_pallas", spy)
        port, model, w, nharm = _problem(8, 64, "float64")
        F.fused_cross_spectrum(port, model, w, nharm, block=5,
                               pallas=True)
        assert seen.get("block") == 5


class TestDecodeKernelParity:
    """fused_decode_cross_spectrum_pallas vs decode_stokes_I + scan +
    host Parseval rows (the materialized raw lane), bitwise."""

    @pytest.mark.parametrize("code", ["p1", "p2", "p4"])
    def test_decode_parity(self, code):
        rng = np.random.default_rng(3)
        nbit = PACKED_BITS[code]
        nchan, nbin = 13, 128
        bpc = (nbin * nbit) // 8
        packed = jnp.asarray(rng.integers(0, 256, size=(nchan * bpc,)),
                             jnp.uint8)
        scl = jnp.asarray(rng.uniform(0.5, 2.0, size=(nchan,)))
        offs = jnp.asarray(rng.normal(size=(nchan,)))
        model = jnp.asarray(rng.normal(size=(nchan, nbin)))
        nharm = nbin // 4
        w = jnp.asarray(rng.uniform(0.5, 2.0, size=(nchan, nharm)))

        for fold in (False, True):
            for block in (None, 7):

                @jax.jit
                def ref(p, s, o, m, wk):
                    # decode_stokes_I already removes the min-window
                    # baseline — the kernel mirrors its full chain
                    x = decode_stokes_I(p[None], s[None], o[None],
                                        jnp.float64, code=code,
                                        nbin=nbin)[0]
                    Xr, Xi, S0 = F.fused_cross_spectrum(
                        x, m, wk, nharm, fold=fold, block=block,
                        pallas=False)
                    x0 = jnp.sum(x, axis=-1)
                    mu = x0 / nbin
                    pwr = nbin * jnp.sum((x - mu[..., None]) ** 2,
                                         axis=-1)
                    if nbin % 2 == 0:
                        sg = jnp.asarray((-1.0) ** jnp.arange(nbin),
                                         x.dtype)
                        pwr = pwr + jnp.sum(x * sg, axis=-1) ** 2
                    return Xr, Xi, S0, pwr, x0

                @jax.jit
                def kern(p, s, o, m, wk):
                    return F.fused_decode_cross_spectrum_pallas(
                        p.reshape(nchan, bpc), s, o, m, wk, nharm,
                        code=code, nbin=nbin, fold=fold, block=block)

                refs = ref(packed, scl, offs, model, w)
                got = kern(packed, scl, offs, model, w)
                for r, g, name in zip(refs, got,
                                      ("Xr", "Xi", "S0", "pwr", "x0")):
                    assert np.array_equal(np.asarray(r),
                                          np.asarray(g)), (
                        f"{name} not bitwise for {code} fold={fold} "
                        f"block={block}")

    def test_decode_kernel_rejects_bad_inputs(self):
        model = jnp.zeros((4, 100))
        w = jnp.ones((4, 25))
        raw = jnp.zeros((4, 25), jnp.uint8)
        one = jnp.ones((4,))
        with pytest.raises(ValueError, match="packed sub-byte"):
            F.fused_decode_cross_spectrum_pallas(
                raw, one, one, model, w, 25, code="i16", nbin=100)
        with pytest.raises(ValueError, match="byte-aligned"):
            # 100 bins x 1 bit = 100 bits: not a whole byte count
            F.fused_decode_cross_spectrum_pallas(
                raw, one, one, model, w, 25, code="p1", nbin=100)


class TestKnobs:
    """Tri-state / block-size knob semantics and the PPT_* env hooks."""

    def test_use_fit_pallas_strict(self, monkeypatch):
        assert F.use_fit_pallas(False) is False
        # forcing on either runs the kernel or refuses loudly — never a
        # silent fallback to the scan
        assert F.use_fit_pallas(True) is True
        # 'auto' never pays interpret overhead off-TPU
        if jax.default_backend() != "tpu":
            assert F.use_fit_pallas("auto") is False
        with pytest.raises(ValueError, match="fit_pallas"):
            F.use_fit_pallas("sometimes")
        monkeypatch.setattr(F, "HAVE_PALLAS_FUSED", False)
        with pytest.raises(RuntimeError, match="pallas"):
            F.use_fit_pallas(True)
        assert F.use_fit_pallas("auto") is False

    def test_fused_block_knob(self, monkeypatch):
        monkeypatch.setattr(config, "fused_block", None)
        assert F.fused_block_default() == 32
        monkeypatch.setattr(config, "fused_block", 8)
        assert F.fused_block_default() == 8
        assert F._block_size(4) == 4  # clamped to nchan
        monkeypatch.setattr(config, "fused_block", 0)
        with pytest.raises(ValueError, match="fused_block"):
            F.fused_block_default()

    def test_resolve_fit_fused_tokens(self, monkeypatch):
        from pulseportraiture_tpu.fit.portrait import (
            _parse_fit_fused, resolve_fit_fused)

        monkeypatch.setattr(config, "fit_fused", True)
        monkeypatch.setattr(config, "fit_pallas", False)
        monkeypatch.setattr(config, "fused_block", None)
        assert resolve_fit_fused(128) is True
        assert resolve_fit_fused(None) is False  # dead knob normalizes
        monkeypatch.setattr(config, "fit_pallas", True)
        assert resolve_fit_fused(128) == "pallas"
        monkeypatch.setattr(config, "fused_block", 8)
        assert resolve_fit_fused(128) == "pallas:8"
        monkeypatch.setattr(config, "fit_pallas", False)
        assert resolve_fit_fused(128) == "fused:8"
        assert _parse_fit_fused("pallas") == (True, None)
        assert _parse_fit_fused("pallas:8") == (True, 8)
        assert _parse_fit_fused("fused:8") == (False, 8)
        assert _parse_fit_fused(True) == (False, None)

    def test_env_hooks(self, monkeypatch):
        monkeypatch.setattr(config, "fit_pallas", "auto")
        monkeypatch.setattr(config, "fused_block", None)
        monkeypatch.setenv("PPT_FIT_PALLAS", "on")
        monkeypatch.setenv("PPT_FUSED_BLOCK", "16")
        changed = config.env_overrides()
        assert config.fit_pallas is True
        assert config.fused_block == 16
        assert "fit_pallas" in changed and "fused_block" in changed
        monkeypatch.setenv("PPT_FIT_PALLAS", "off")
        config.env_overrides()
        assert config.fit_pallas is False
        monkeypatch.setenv("PPT_FIT_PALLAS", "maybe")
        with pytest.raises(ValueError, match="PPT_FIT_PALLAS"):
            config.env_overrides()
        monkeypatch.setenv("PPT_FIT_PALLAS", "auto")
        monkeypatch.setenv("PPT_FUSED_BLOCK", "0")
        with pytest.raises(ValueError, match="PPT_FUSED_BLOCK"):
            config.env_overrides()
        monkeypatch.setenv("PPT_FUSED_BLOCK", "wide")
        with pytest.raises(ValueError, match="PPT_FUSED_BLOCK"):
            config.env_overrides()


# ---------------------------------------------------------------------
# Streaming .tim byte gates: flipping fit_pallas must not move a single
# byte of the timing product, raw lane and decoded lane alike, and the
# decode-fused kernel must actually ENGAGE for the sub-byte codes (a
# gate that silently measures the fallback is no gate).
# ---------------------------------------------------------------------

def _noisy_maker(nchan, nbin, nsub, npol, seed=3, sigma=0.08):
    base = gaussian_portrait(nchan, nbin)
    rng = np.random.default_rng(seed)
    noise = {(s, p): rng.normal(0.0, sigma, (nchan, nbin))
             for s in range(nsub) for p in range(npol)}
    return lambda s, p: base * (1.0 + 0.1 * p) + 0.1 * s + noise[(s, p)]


# nbin=256 is the smallest shape where a harmonic window can engage at
# all (resolve_harmonic_window tile-rounds to 128 and needs
# K < nbin//2 + 1), which both the fused lane and the decode-fused gate
# require.
_NSUB, _NCHAN, _NBIN = 2, 8, 256
_HWIN = 128


@pytest.fixture(scope="module")
def pallas_archives(tmp_path_factory):
    """One forged archive + tscrunched template per data dtype: i16
    (the decoded/materialized raw path) and the three packed sub-byte
    codes the decode-fused kernel covers."""
    from pulseportraiture_tpu.io.psrfits import (read_archive,
                                                 unload_new_archive)

    tmp = tmp_path_factory.mktemp("pallas_tim")
    out = {}
    for dtype in ("int16", "nbit1", "nbit2", "nbit4"):
        f = str(tmp / f"{dtype}.fits")
        forge_archive(f, nsub=_NSUB, nchan=_NCHAN, nbin=_NBIN, dedisp=0,
                      data_maker=_noisy_maker(_NCHAN, _NBIN, _NSUB, 1),
                      data_dtype=dtype)
        arch = read_archive(f)
        arch.tscrunch()
        tmpl = str(tmp / f"{dtype}_tmpl.fits")
        unload_new_archive(np.asarray(arch.amps), arch, tmpl, DM=0.0,
                           dmc=1, quiet=True)
        out[dtype] = (f, tmpl)
    return tmp, out


def _pallas_config(monkeypatch):
    """The CPU gating configuration: fast fit forced on (the 'auto'
    default is TPU-only), fused lane on, window engaged, so the
    fit_pallas flip is the ONLY moving part."""
    monkeypatch.setattr(config, "use_fast_fit", True)
    monkeypatch.setattr(config, "fit_fused", True)
    monkeypatch.setattr(config, "fit_harmonic_window", _HWIN)


def _stream_tim(files, tmpl, out, **kw):
    from pulseportraiture_tpu.pipeline import stream as S

    S.stream_wideband_TOAs(files, tmpl, nsub_batch=4, quiet=True,
                           tim_out=out, **kw)
    with open(out, "rb") as fh:
        return fh.read()


def test_stream_raw_tim_byte_identical_on_pallas_flip(
        pallas_archives, monkeypatch):
    """i16 raw lane: the fused-fit kernel (kernel A) rides the bucket
    program; flipping fit_pallas retraces and the .tim bytes must not
    move.  The spy proves the kernel arm actually traced."""
    tmp, out = pallas_archives
    f, tmpl = out["int16"]
    _pallas_config(monkeypatch)
    calls = []
    orig = F.fused_cross_spectrum_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(F, "fused_cross_spectrum_pallas", spy)
    monkeypatch.setattr(config, "fit_pallas", False)
    a = _stream_tim([f], tmpl, str(tmp / "i16_off.tim"))
    assert not calls
    monkeypatch.setattr(config, "fit_pallas", True)
    b = _stream_tim([f], tmpl, str(tmp / "i16_on.tim"))
    assert calls, "Pallas fused kernel never engaged"
    assert a and a == b


def test_stream_dec_tim_byte_identical_on_pallas_flip(
        pallas_archives, monkeypatch):
    """Decoded-lane twin: refuse _load_raw so the stream runs the
    host-decoded buckets, where kernel A is the only Pallas surface."""
    from pulseportraiture_tpu.pipeline import stream as S

    tmp, out = pallas_archives
    f, tmpl = out["int16"]
    _pallas_config(monkeypatch)

    def refuse(path, **kw):
        raise ValueError("forced decoded lane")

    monkeypatch.setattr(S, "_load_raw", refuse)
    monkeypatch.setattr(config, "fit_pallas", False)
    a = _stream_tim([f], tmpl, str(tmp / "dec_off.tim"))
    monkeypatch.setattr(config, "fit_pallas", True)
    b = _stream_tim([f], tmpl, str(tmp / "dec_on.tim"))
    assert a and a == b


@pytest.mark.parametrize("dtype,code", [("nbit1", "p1"),
                                        ("nbit2", "p2"),
                                        ("nbit4", "p4")])
def test_stream_decode_fused_tim_byte_identical(pallas_archives,
                                                monkeypatch, dtype,
                                                code):
    """Sub-byte raw lane: with fit_pallas on the decode-fused kernel
    (kernel B) replaces decode_stokes_I + prepare, and the .tim bytes
    must match the fit_pallas=False run of the SAME device-decoded
    lane.  The spy proves kernel B engaged (trace-time call)."""
    from pulseportraiture_tpu.pipeline import stream as S

    tmp, out = pallas_archives
    f, tmpl = out[dtype]
    assert S._load_raw(f).raw_code == code
    _pallas_config(monkeypatch)
    calls = []
    orig = F.fused_decode_cross_spectrum_pallas

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(F, "fused_decode_cross_spectrum_pallas", spy)
    monkeypatch.setattr(config, "fit_pallas", False)
    a = _stream_tim([f], tmpl, str(tmp / f"{code}_off.tim"))
    assert not calls
    monkeypatch.setattr(config, "fit_pallas", True)
    b = _stream_tim([f], tmpl, str(tmp / f"{code}_on.tim"))
    assert calls, f"decode-fused kernel never engaged for {code}"
    assert a and a == b
