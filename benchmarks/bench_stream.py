"""BASELINE.md config 5 (single-chip slice): streamed wideband TOAs for
a batch of PSRFITS archives through the full pipeline — file IO, native
SUBINT decode, shape-bucketed fused fit dispatches, .tim assembly.

Archives are generated on the fly into a temp dir (16 archives x 16
subints x 256 chan x 1024 bin by default — sized so generation stays a
small fraction of the benchmark); the measured figure is end-to-end
wall time of stream_wideband_TOAs including IO, which is the number an
IPTA-scale campaign sees per chip.

Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    import jax

    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 16))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}

    with tempfile.TemporaryDirectory() as td:
        mpath = os.path.join(td, "model.gmodel")
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
        files = []
        rng = 0
        for i in range(NARCH):
            path = os.path.join(td, f"a{i:03d}.fits")
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * i, dDM=1e-4 * i, noise_stds=0.05,
                             quiet=True, rng=i)
            files.append(path)

        # nsub_batch 64: buckets fill (and their h2d copies start, on
        # the dispatch thread) while later archives are still loading
        # warm (compile) on one archive, then measure the full campaign
        stream_wideband_TOAs(files[:1], mpath, nsub_batch=64, quiet=True)
        t0 = time.perf_counter()
        res = stream_wideband_TOAs(files, mpath, nsub_batch=64, quiet=True)
        wall = time.perf_counter() - t0

    ntoa = len(res.TOA_list)
    print(json.dumps({
        "metric": f"streamed TOAs incl. PSRFITS IO, {NARCH} archives x "
                  f"{NSUB}sub x {NCHAN}ch x {NBIN}bin",
        "value": round(ntoa / wall, 2),
        "unit": "TOAs/sec",
        "wall_s": round(wall, 2),
        "toas": ntoa,
        "fit_fraction": round(float(res.fit_duration) / wall, 3),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
