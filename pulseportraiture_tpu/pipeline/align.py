"""Iterative align-and-average of archives (ppalign equivalent).

Parity target: reference ppalign.py:65-280.  TPU-first restructure:
each iteration stacks every (archive, subint) into batches and runs ONE
vmapped (phi[, DM]) portrait fit plus one batched rotation per archive,
instead of the reference's nested Python loops with per-subint scipy
calls; iterations remain the only synchronization points (SURVEY §7.2
step 5).  The psradd/psrsmooth/vap subprocess dependencies are replaced
by internal averaging, wavelet smoothing, and header reads.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..fit.phase_shift import fit_phase_shift_batch
from ..fit.portrait import (FitFlags, fit_portrait_batch,
                            fit_portrait_batch_fast,
                            resolve_harmonic_window,
                            use_fast_fit_default)
from ..parallel.batch import (align_accumulate_archive,
                              align_accumulator_init, align_finalize,
                              use_align_device)
from ..utils.device import host_compute
from ..io.psrfits import load_data, read_archive, unload_new_archive
from ..models.gaussian import gen_gaussian_profile
from ..ops.fourier import irfft_c, rfft_c
from ..ops.phasor import phase_shifts, phasor
from ..ops.rotation import rotate_full, rotate_portrait
from .portrait import normalize_portrait
from .toas import _read_metafile


def psradd_archives(datafiles, outfile=None, quiet=False):
    """Average archives without alignment (internal psradd -T
    equivalent; reference ppalign.py:30-47).  Returns the average
    portrait and writes it as an archive if outfile is given."""
    total = None
    wsum = None
    first_arch = None
    for path in datafiles:
        d = load_data(path, dedisperse=True, tscrunch=True, pscrunch=True,
                      quiet=True)
        if first_arch is None:
            first_arch = read_archive(path)
            first_arch.tscrunch()
            first_arch.pscrunch()
        port = np.asarray(d.subints[0, 0])
        w = np.asarray(d.weights[0])[:, None]
        total = port * w if total is None else total + port * w
        wsum = w if wsum is None else wsum + w
    avg = total / np.maximum(wsum, 1e-30)
    if outfile is not None:
        unload_new_archive(avg[None, None], first_arch, outfile, DM=0.0,
                           dmc=1, quiet=quiet)
    return avg


def psrsmooth_archive(datafile, outfile=None, **kwargs):
    """Wavelet-smooth an archive's portrait (internal psrsmooth -W
    equivalent; reference ppalign.py:50-62)."""
    from ..models.wavelet import wavelet_smooth

    d = load_data(datafile, dedisperse=True, tscrunch=True, pscrunch=True,
                  quiet=True)
    sm = np.asarray(wavelet_smooth(np.asarray(d.subints[0, 0]), **kwargs))
    if outfile is None:
        outfile = datafile + ".sm"
    arch = read_archive(datafile)
    arch.tscrunch()
    arch.pscrunch()
    unload_new_archive(sm[None, None], arch, outfile, DM=0.0, dmc=1,
                       quiet=True)
    return sm


def make_constant_portrait(profile_or_archive, nchan):
    """Tile one profile across nchan channels (reference
    make_constant_portrait, pplib.py:993-1029)."""
    if isinstance(profile_or_archive, str):
        d = load_data(profile_or_archive, dedisperse=True, tscrunch=True,
                      pscrunch=True, fscrunch=True, quiet=True)
        prof = np.asarray(d.subints[0, 0, 0])
    else:
        prof = np.asarray(profile_or_archive, float)
    return np.tile(prof, (nchan, 1))


def gaussian_seed_portrait(nchan, nbin, fwhm, loc=0.5):
    """Single-Gaussian constant template (reference ppalign.py
    '-g fwhm' path, :386-396)."""
    prof = np.asarray(gen_gaussian_profile(
        {"dc": 0.0, "locs": np.array([loc]), "wids": np.array([fwhm]),
         "amps": np.array([1.0]), "mlocs": np.zeros(1),
         "mwids": np.zeros(1), "mamps": np.zeros(1),
         "tau": 0.0, "alpha": 0.0}, nbin, scattered=False))
    return np.tile(prof, (nchan, 1))


def _host_accumulate_archive(aligned_FT, total_weights, sub_cube, phis,
                             DMs, nu_ref_fit, Ps_ok, freqs0, noise,
                             masks, scales):
    """Host lane of one archive's weighted back-rotated accumulate
    (reference ppalign.py:236-242): weights = scales / noise^2, the
    rotation is a phasor multiply in the harmonic domain, and the whole
    archive accumulates as sum_j cFT_j * ph_j * w_j in chunks of 16
    (bounded memory) under host_compute() — no per-subint inverse
    transforms; the single irfft happens after the archive loop.

    This is the digit-exactness oracle for the device lane
    (parallel/batch.align_accumulate_archive) and the host arm of
    bench_align's A/B — one implementation for both, so the comparison
    is against the production math.  aligned_FT (npol, nchan, nharm)
    c128 and total_weights (nchan, nbin) are updated and returned;
    scales arrives already mask-multiplied (the loop's convention)."""
    noise_safe = np.where(noise > 0.0, noise, np.inf)
    w = masks * np.maximum(scales, 0.0) / noise_safe ** 2
    with host_compute():
        delays = phase_shifts(
            jnp.asarray(phis)[:, None],
            jnp.asarray(DMs)[:, None], 0.0,
            jnp.asarray(np.broadcast_to(freqs0, w.shape)),
            jnp.asarray(Ps_ok)[:, None],
            jnp.asarray(nu_ref_fit)[:, None], 1.0)
        for lo in range(0, len(sub_cube), 16):
            sl = slice(lo, lo + 16)
            cFT = rfft_c(jnp.asarray(sub_cube[sl]))
            ph = phasor(delays[sl], cFT.shape[-1])
            aligned_FT += np.asarray(jnp.sum(
                cFT * ph[:, None]
                * jnp.asarray(w[sl])[:, None, :, None],
                axis=0))
    total_weights += w.sum(axis=0)[:, None]
    return aligned_FT, total_weights


def align_archives(metafile, initial_guess, fit_dm=True, tscrunch=False,
                   pscrunch=True, SNR_cutoff=0.0, outfile=None, norm=None,
                   rot_phase=0.0, place=None, niter=1, quiet=False,
                   align_device=None):
    """Iteratively align and average archives against a template
    (reference ppalign.py:65-280; same options/semantics).

    initial_guess: archive path OR an (nchan, nbin) portrait array.
    The output archive has DM=0 and unit weights.  Returns the final
    average portrait (npol, nchan, nbin).

    align_device: None -> config.align_device; 'auto' = device
    accumulate on TPU backends; True/False force.  The device lane
    runs the rotate-and-stack template update as jitted split-real
    harmonic programs with donated accumulators (parallel/batch.py) —
    fit results and the subint stack never round-trip to the host
    inside an iteration; the host lane is the digit-exactness oracle
    (tests/test_pipeline_align.py).
    """
    if isinstance(metafile, str):
        datafiles = _read_metafile(metafile)
        if outfile is None:
            outfile = metafile + ".algnd.fits"
    else:
        datafiles = list(metafile)
        if outfile is None:
            outfile = "aligned.algnd.fits"
    state = "Intensity" if pscrunch else "Stokes"
    npol = 1 if pscrunch else 4

    if isinstance(initial_guess, str):
        md = load_data(initial_guess, state=state, dedisperse=True,
                       tscrunch=True, pscrunch=pscrunch, quiet=quiet)
        model_port = np.asarray(md.masks[0, 0] * md.subints[0, 0])
        template_arch_path = initial_guess
    else:
        model_port = np.asarray(initial_guess, float)
        template_arch_path = None
    nchan, nbin = model_port.shape[-2:]

    use_dev = use_align_device(align_device)
    # the device accumulate runs f32 on TPU (no f64 there; alignment
    # phasors stay accurate via the mod-1 wrap) and f64 elsewhere —
    # a CPU-forced device lane is the host path's digit-exactness peer
    from ..tune.capability import resolve_auto

    dev_dt = jnp.float32 if resolve_auto("device_f32", "auto") \
        else jnp.float64

    skip_these = set()
    final = None
    for it in range(niter):
        if not quiet:
            print(f"Doing iteration {it + 1}...")
        # the weighted stack accumulates in the HARMONIC domain: each
        # epoch contributes cFT * phasor * w (linear), and ONE irfft
        # per iteration recovers the average — instead of one inverse
        # transform per subint (reference ppalign.py:236-242 rotates
        # every subint back through the time domain).  Device lane:
        # the same math as jitted split-real programs with donated
        # on-chip accumulators (parallel/batch.py); host lane: chunked
        # c128 under host_compute().
        if use_dev:
            acc = align_accumulator_init(npol, nchan, nbin, dev_dt)
        else:
            aligned_FT = np.zeros((npol, nchan, nbin // 2 + 1), complex)
            total_weights = np.zeros((nchan, nbin))
        model_j = jnp.asarray(model_port)
        use_fast = use_fast_fit_default()
        if use_fast:
            # hoisted: one H2D transfer of the shared template per
            # iteration, not one per archive.  The harmonic window
            # derives per iteration from the HOST template: a noisy
            # early-iteration average has a flat spectral floor and
            # resolves to None (full spectrum) automatically; smooth
            # templates band-limit the fits (fit.portrait).
            model_f32 = jnp.asarray(model_port, jnp.float32)
            hwin = resolve_harmonic_window(None, model_port, nbin)
        mean_model = model_port.mean(axis=0)
        for path in datafiles:
            if path in skip_these:
                continue
            try:
                d = load_data(path, state=state, dedisperse=False,
                              dededisperse=True, tscrunch=tscrunch,
                              pscrunch=pscrunch, quiet=True)
            except Exception as e:  # noqa: BLE001 — skip-and-continue
                print(f"Skipping {path}: {e}")
                skip_these.add(path)
                continue
            if d.nchan != nchan or d.nbin != nbin:
                print(f"Skipping {path}: shape mismatch")
                skip_these.add(path)
                continue
            ok = np.asarray(d.ok_isubs, int)
            if len(ok) == 0:
                skip_these.add(path)
                continue
            if SNR_cutoff and float(d.prof_SNR) < SNR_cutoff:
                skip_these.add(path)
                continue
            freqs0 = np.asarray(d.freqs[0], float)
            Ps_ok = np.asarray(d.Ps[ok], float)
            masks = np.asarray(d.weights[ok] > 0.0, float)
            ports = np.asarray(d.subints[ok, 0], float)
            noise = np.asarray(d.noise_stds[ok, 0], float)
            DM_guess = 0.0 if d.dmc else float(d.DM)

            # phase guesses from the f-scrunched profiles vs the mean
            # template profile (ppalign.py:214-219): ONE batched
            # rotate + ONE batched 1-D FFTFIT for the whole archive
            # (round 4 dispatched an eager rotate + scalar fit per
            # subint); complex phasors -> host CPU when the
            # accelerator cannot compile them
            theta0 = np.zeros((len(ok), 5))
            theta0[:, 1] = DM_guess
            with host_compute():
                # chunked like the accumulate below: an un-chunked
                # rotate of a 64x512x2048 f64 archive materializes
                # ~1 GB of transient c128 spectra on host
                profs = np.empty((len(ok), nbin))
                for lo in range(0, len(ok), 16):
                    sl = slice(lo, lo + 16)
                    rot = np.asarray(rotate_full(
                        jnp.asarray(ports[sl])[:, None], 0.0, DM_guess,
                        jnp.asarray(Ps_ok[sl]),
                        jnp.asarray(np.broadcast_to(
                            freqs0, (len(ports[sl]), nchan))), np.inf))
                    profs[sl] = rot[:, 0].mean(axis=1)
                r = fit_phase_shift_batch(
                    profs, np.broadcast_to(mean_model, profs.shape),
                    np.median(noise, axis=1))
                theta0[:, 0] = np.asarray(r.phase, float)

            nchx = masks.sum(axis=1)
            if nchan > 1 and np.all(nchx > 1):
                # complex-free f32 fast path on TPU backends (ppalign's
                # fit is always (phi[, DM]) — never scattering)
                if use_fast:
                    fitter, ft = fit_portrait_batch_fast, jnp.float32
                    model_arg = model_f32  # shared 2-D
                    kw = {"harmonic_window":
                          hwin if hwin is not None else False}
                else:
                    fitter, ft = fit_portrait_batch, None
                    model_arg = jnp.broadcast_to(model_j, ports.shape)
                    kw = {}
                res = fitter(
                    jnp.asarray(ports, ft),
                    model_arg,
                    jnp.asarray(noise, ft), jnp.asarray(freqs0, ft),
                    jnp.asarray(Ps_ok, ft),
                    jnp.asarray(np.full(len(ok), freqs0.mean()), ft),
                    nu_out=freqs0.mean(),
                    theta0=jnp.asarray(theta0, ft),
                    fit_flags=FitFlags(True, bool(fit_dm), False, False,
                                       False),
                    chan_masks=jnp.asarray(masks, ft), **kw)
                # device lane: leave the fit leaves as device arrays —
                # the accumulate consumes them on-chip, no host pull
                phis, DMs = res.phi, res.DM
                scales, nu_ref_fit = res.scales, res.nu_DM
            else:  # 1-channel fallback (ppalign.py:230-235)
                phis = theta0[:, 0]
                DMs = np.full(len(ok), DM_guess)
                scales = masks.copy()
                nu_ref_fit = np.full(len(ok), freqs0.mean())

            # weighted accumulate of back-rotated subints
            # (ppalign.py:236-242): weights = scales / noise^2
            sub_cube = np.asarray(d.subints[ok], float)  # (nok, npol, ...)
            if use_dev:
                acc = align_accumulate_archive(
                    acc, sub_cube, phis, DMs, nu_ref_fit, Ps_ok,
                    freqs0, noise, masks, scales)
            else:
                aligned_FT, total_weights = _host_accumulate_archive(
                    aligned_FT, total_weights, sub_cube,
                    np.asarray(phis), np.asarray(DMs),
                    np.asarray(nu_ref_fit), Ps_ok, freqs0, noise,
                    masks, np.asarray(scales) * masks)
        if use_dev:
            # ONE device->host pull per iteration (the portrait seeds
            # the next iteration's host-side window derivation) — the
            # iteration boundary stays the only synchronization point
            if not np.asarray(acc[2]).any():
                raise RuntimeError("no archives could be aligned")
            aligned = np.asarray(align_finalize(acc, nbin), float)
        else:
            if not total_weights.any():
                raise RuntimeError("no archives could be aligned")
            with host_compute():
                aligned = np.array(irfft_c(jnp.asarray(aligned_FT),
                                           n=nbin))
            aligned /= np.maximum(total_weights, 1e-30)[None]
        model_port = aligned[0]
        final = aligned

    if norm is not None:
        for ipol in range(npol):
            final[ipol] = normalize_portrait(final[ipol], method=norm)
        model_port = final[0]
    if place is not None:
        # put the peak at the requested phase via a delta-profile
        # cross-correlation (ppalign.py:255-261)
        prof = model_port.mean(axis=0)
        peak = np.argmax(prof) / nbin
        rot_phase = peak - place
    if rot_phase:
        with host_compute():
            final = np.asarray(rotate_portrait(jnp.asarray(final),
                                               rot_phase))
        model_port = final[0]

    # write into a cloned archive with DM=0 and unit weights
    # (ppalign.py:262-279)
    src = template_arch_path or datafiles[0]
    arch = read_archive(src)
    arch.tscrunch()
    if pscrunch:
        arch.pscrunch()
    if arch.nchan != nchan or arch.nbin != nbin:
        raise ValueError("template archive shape changed on reload")
    unload_new_archive(final[None] if final.ndim == 3 else final, arch,
                       outfile, DM=0.0, dmc=1,
                       weights=np.ones((1, nchan)), quiet=quiet)
    if not quiet:
        print(f"Wrote {outfile}.")
    return final
