"""Behavior-defining constants.

Mirrors the semantics of the reference's module-level settings
(reference pplib.py:56-99) but exposed as an importable, overridable
config module instead of edit-the-source constants.
"""

# --- Dispersion constant [MHz^2 s cm^3 / pc] ------------------------------
# Two conventions exist (reference pplib.py:61-67); fitted DM values depend
# on the choice.  The "traditional" value is the default, matching TEMPO.
Dconst_exact = 4.148808e3
Dconst_trad = 0.000241**-1
Dconst = Dconst_trad

# --- Scattering -----------------------------------------------------------
# Default scattering power-law index: tau(nu) = tau * (nu/nu_tau)**alpha
# (reference pplib.py:70).
scattering_alpha = -4.0

# Vestigial fudge factor the reference kept in rotation signatures and
# never varied (pplib.py:99); retained solely so scripts reading it
# keep working.  Nothing in this package consumes it.
binshift = 1.0

# --- Noise estimation -----------------------------------------------------
# 'PS' = mean power of the top quarter of the power spectrum
# (reference pplib.py:74-78, 2312-2338).
default_noise_method = "PS"

# --- Fourier DC term ------------------------------------------------------
# Weight applied to the k=0 (DC) harmonic in all Fourier-domain fits.
# 0 removes sensitivity to the baseline (reference pplib.py:82).
F0_fact = 0.0

# --- Gaussian component bounds --------------------------------------------
# Upper bound on Gaussian FWHM [rotations] in template fits
# (reference pplib.py:86).
wid_max = 0.25

# Route no-scattering pipeline fits through the complex-free f32 fast
# path (fit_portrait_batch_fast).  'auto' = on TPU backends (where
# complex FFTs are unsupported or unusably slow); True/False force.
use_fast_fit = "auto"

# Run align_archives' rotate-and-accumulate template update on the
# default device via the jitted split-real harmonic accumulate
# (parallel/batch.align_accumulate_archive) instead of the chunked
# c128 host loop.  'auto' = on when the default backend is TPU (the
# accumulate dominates the align iteration there and the chip
# otherwise idles through it — VERDICT r5 #6); True/False force.  The
# host path is retained as the digit-exactness oracle and stays the
# CPU default; the device program is complex-free throughout (matmul
# DFTs, split-real phasor rotation, ONE irfft per iteration) with the
# accumulator buffers donated across archives so the stack stays
# device-resident.
align_device = "auto"

# Route template building's Gaussian LM fits (breadth-first
# auto_fit_profile trials and the template factory's fleet buckets,
# pipeline/factory.build_templates) through the BATCHED engine
# (fit/lm.levenberg_marquardt_batched): one vmapped dispatch fits a
# whole padded bucket of (pulsar, ngauss-trial) problems instead of one
# serial LM dispatch per fit.  'auto' = on TPU backends (where the
# serial per-problem loop idles the chip between tiny dispatches);
# True/False force.  The host-serial lane — the SAME padded problems
# through the single-problem engine one at a time — is retained as the
# digit-exactness oracle (bench_gauss gates .gmodel identity <= 1e-10).
gauss_device = "auto"

# Route the fleet timing stage's GLS solves (timing/fleet.py:
# fleet_gls_fit, the pptime CLI, stream_ipta_campaign(timing_pars=))
# through the BATCHED device lane: per-pulsar whitened systems are
# bucketed by power-of-two (rows, params) class, zero-padded, and each
# bucket solved in ONE jitted dispatch instead of one host solve per
# pulsar.  'auto' = on TPU backends (a millisecond linear solve cannot
# amortize a per-pulsar dispatch floor; one fleet dispatch can);
# True/False force.  The host-NumPy per-pulsar path (False) is the
# digit oracle — bench_gls.py gates batched-vs-serial solutions
# <= 1e-10 — and stays the CPU default.
gls_device = "auto"

# Route the median-algorithm zap statistics (pipeline/zap.py +
# quality/excision.py) through the batched device cut: the WHOLE
# iterative median + nstd cut for every subint runs inside one jitted
# while_loop — one dispatch per archive, zero per-iteration host round
# trips.  'auto' = on TPU backends; True/False force.  The host lane
# (the reference loop vectorized) is the digit oracle: the masked
# median is bit-exact on device (order-statistic bisection), the std
# agrees to ~1 ulp of accumulation, and the flagged-channel LISTS are
# gated identical by tests + bench_zap every run.
zap_device = "auto"

# Threshold [standard deviations] of the median-algorithm channel cut
# (the reference's hard-coded nstd=3, ppzap.py:30): a channel whose
# noise level exceeds median + zap_nstd*std of the surviving channels
# is flagged, iteratively.  Shared by ppzap, the streaming drivers'
# inline zap (zap_inline=), and the serving loop's refit proposals.
zap_nstd = 3.0

# --- Quality-gated refit (serve/server.ToaServer) --------------------------
# Master switch for the serving loop's closed quality loop: a request
# archive whose fitted TOAs trip the thresholds below triggers exactly
# ONE automatic zap-and-refit of that archive through the same warm
# lanes before its .tim is demuxed (loud when the refit cannot help or
# still trips).  Off by default: .tim output is byte-identical with
# the loop on or off for data that never trips a gate.
quality_refit = False

# A TOA whose goodness-of-fit (reduced chi^2, the -gof flag) exceeds
# this trips the refit gate.  The default matches the reference
# model-based zap threshold (ppzap -R, pptoas.py:1279).
quality_max_gof = 1.3

# A TOA whose S/N falls below this trips the refit gate; 0 disables
# the S/N gate (low S/N is usually irreducible, not zappable — opt in
# when RFI is known to suppress the matched filter).
quality_min_snr = 0.0

# Jacobian source for the Levenberg-Marquardt template engine
# (fit/lm.py).  The Gaussian profile/portrait models have CLOSED-FORM
# derivatives (the reference's analytic-gradient heritage, SURVEY
# §L3); when the model supplies its analytic residual-companion
# (fit/gauss._profile_resid_jac and the portrait twin), the engine can
# call it instead of jax.jacfwd — pure matmuls/elementwise work
# instead of nparam forward-mode passes re-tracing the model, and the
# win compounds under vmap where the lax.cond Jacobian-reuse degrades
# to jac-every-iteration (attrib.py gauss measured the AD jacobian at
# 443 of 503 ms/iteration, 0.97 attributed).
#   'auto' (default): analytic whenever the caller provides a
#          companion; jacfwd otherwise (powlaw and any external
#          resid_fn keep autodiff).
#   'analytic': require the companion — a fit without one refuses
#          loudly (an A/B run must not silently fall back to AD).
#   'ad': force jacfwd even when a companion exists — the digit
#          oracle lane (bench_gauss gates analytic-vs-AD <= 1e-10).
lm_jacobian = "auto"

# Fuse the wideband fit's windowed hot path (split-real DFT ->
# cross-spectrum -> per-channel power reductions,
# fit/portrait.prepare_portrait_fit_real and its scattering twin)
# into a hand-blocked single-program pass (ops/fused.py): the DFT
# spectra dr/di/mr/mi are never materialized at full (nchan, nharm) —
# each channel block flows DFT -> cross-spectrum -> S0/M2w inside one
# lax.scan step, so the prepare stage's HBM traffic drops from six
# full-size intermediates to the two the Newton loop actually reads
# (Xr, Xi).  Only active when the harmonic window is on (nharm_eff
# set): the windowed lane's full-spectrum data power already comes
# from the exact time-domain Parseval form, which is what keeps the
# fused program BYTE-identical to the unfused one (.tim gates in
# tests/test_stream.py and bench.py every run).
#   False: unfused (the round-5 program, bit-stable across releases).
#   'auto' (default): fused on TPU backends; unfused elsewhere (CPU CI
#          exercises the fused lane explicitly via tests/bench).
#   True:  force the fused program everywhere.
fit_fused = "auto"

# Which IMPLEMENTATION the fused lane runs (only meaningful when
# fit_fused is active): the hand-blocked lax.scan, or the Pallas
# kernel (ops/fused.fused_cross_spectrum_pallas) that runs each
# channel tile's DFT matmuls + cross-spectrum + power reduction
# VMEM-resident in ONE kernel — the below-XLA fusion the scan cannot
# express (XLA will not fuse a dot into its consumers; R17 measured
# the scan CPU-honest 0.84x).  On the raw streaming lane the kernel
# additionally absorbs the sub-byte decode chain
# (ops/fused.fused_decode_cross_spectrum_pallas) so the decoded f64
# portrait never materializes in HBM.  Outputs are BITWISE identical
# to the scan at any block size (tests/test_pallas_interpret.py; .tim
# byte gates unchanged when this flips).
#   False: always the scan (bit-stable across releases).
#   'auto' (default): the compiled kernel on TPU backends when Pallas
#          is importable; the scan elsewhere (CPU never silently pays
#          interpret-mode overhead).
#   True:  force the kernel everywhere — non-TPU backends run it
#          under pallas_call(interpret=True), the CPU development and
#          gating mode; loud RuntimeError if Pallas is unavailable.
fit_pallas = "auto"

# Channel-block override for BOTH fused implementations (scan tile and
# Pallas grid tile).  None (default): ops/fused._BLOCK_TARGET (32).
# Set a positive int to sweep the block size without code edits — the
# chip-session tuning lattice (benchmarks/BENCHMARKS.md config 6/2)
# drives this via PPT_FUSED_BLOCK.  Resolved at trace time and carried
# in the fit program cache keys, so a mid-process change retraces.
fused_block = None

# Matmul-DFT precision (ops/fourier.py) on accelerators:
# 'highest' = 6-pass bf16 (f32-exact to ~1e-7), 'high' = 3-pass
# (~1e-6 relative, ~20% faster end-to-end at bench shapes), 'default' =
# single-pass bf16 (~1e-3 relative per harmonic, ~40% faster end-to-end;
# the quantization error averages down across harmonics x channels in
# the fit's moments and measures BELOW 'high' on the |dphi| gate at
# bench noise levels — but do not use it for very-high-S/N data where
# ~1e-3 relative errors could rival the noise floor).  All three pass
# the |dphi| < 1e-4 accuracy gate at bench configs; f64 inputs are
# unaffected.  Scope: 'default' applies to the gate-validated portrait
# fit (rfft_mm call sites); the complex-interface helpers rfft_c /
# irfft_c used by rotation/scattering/CCF kernels clamp 'default' up to
# 'high', so alignment math never silently degrades to 1e-3.
dft_precision = "highest"

# Route complex-interface DFTs (ops/fourier.rfft_c / irfft_c) through
# the matmul weights instead of XLA's native FFT: 'auto' = on TPU
# backends (native FFT lowering measures ~2000x slower there);
# True/False force.  Precision follows dft_precision.
use_matmul_dft = "auto"

# Storage dtype for the fit's precomputed cross-spectrum X = d*conj(m)*w
# (fit/portrait.py fast lanes).  'bfloat16' (default since round 3)
# halves the Newton loop's HBM read traffic (~15% end-to-end on the
# no-scatter bench, +18% on the scattering bench); moments still
# accumulate in f32, pulls stay calibrated
# (tests/test_fit.py::test_fast_path_error_calibration_bf16), and the
# |dphi|-vs-NumPy gate measures BETTER than f32 storage at bench noise
# (quantization averages down across ~5e5 harmonic-channel terms).
# Applies ONLY when the working dtype is f32 — f64 runs (CPU parity /
# oracle paths) never narrow.  Set to None for f32 storage on
# extreme-S/N data where ~1e-3 per-term quantization could rival the
# noise floor.
cross_spectrum_dtype = "bfloat16"

# Compensated (Dot2: FMA residue capture + df64 pairwise summation)
# accumulation for the scattering fit's nine harmonic reductions
# (fit/portrait._cgh_scatter).  Cuts the f32 accumulation error from
# ~n*eps to ~sqrt(n)*eps so extreme-S/N tau fits resolve the chi^2
# valley to the sigma_tau limit instead of an f32 floor.  Hybrid: the
# plain loop converges first, then 2-3 compensated polish trips run
# (fit/portrait._hybrid_scatter_loop), so the whole fit costs ~2x the
# plain lane rather than paying Dot2 on every eval.  False (default):
# plain f32 sums — right for ordinary S/N, where the noise floor is
# orders of magnitude above the f32 valley.  When True, the fast lane
# forces full-precision X storage regardless of cross_spectrum_dtype
# (bf16 per-term quantization would dominate what Dot2 removes).
scatter_compensated = False

# Fold-symmetry matmul DFT (ops/fourier.rfft_mm): cos/sin symmetry of
# real input halves the contraction length exactly (two (n/2-1)-row
# matmuls replace two n-row ones; accuracy stays f32-grade, ~5e-7
# relative).  Whether the halved FLOPs win depends on the backend:
# measured ~25% faster on CPU at 64x512x2048->K=384 (sgemm is
# FLOP-bound there), but a net LOSS on TPU v5e (the lane-reversal
# relayout costs more than the saved MACs — benchmarks/exp_folddft.py,
# round 4).
#   False (default): always the direct matmul.  Keeps every lane's
#         outputs bit-stable across releases (the device-campaign
#         bench guards its packed output bit-for-bit).
#   'auto': fold on non-TPU backends only.
#   True:  force fold everywhere.
# bench_scatter.py enables 'auto' and re-validates through its tau
# accuracy gates every run.
dft_fold = False

# Local devices the streaming campaign drivers (pipeline/stream.py:
# stream_wideband_TOAs / stream_narrowband_TOAs) dispatch fused
# buckets across, round-robin with per-device bounded in-flight queues
# and one h2d worker thread per device.
#   'auto' (default): every local device of the default backend — a
#          multi-chip host feeds all its chips from one archive stream.
#   int:   use the first N local devices (loud error when N exceeds
#          the local device count — a silent clamp would quietly
#          invalidate a scaling A/B).
# Campaign output is digit-identical for any value: results stay keyed
# by (archive, subint) owners and .tim checkpoints are written in
# archive order regardless of completion order.
stream_devices = "auto"

# How many fused dispatches may be pending PER DEVICE before the
# streaming drivers block on that device's oldest (the bound is exact:
# a queue never holds more than this many).  Per-driver override via
# their max_inflight= argument.
stream_max_inflight = 4

# Per-device transfer-pipeline depth in the streaming drivers: how
# many buckets may occupy a device's two-stage copy->fit pipeline at
# once.  The host->device link is the campaign bottleneck on tunneled
# runtimes, so each device runs a dedicated COPY worker (stack +
# dtype-convert + device_put) ahead of its FIT worker (program
# enqueue); depth 2 (default) double-buffers — bucket N+1's h2d runs
# while bucket N's fused fit executes — and depth 1 serializes the
# stages (the pre-pipeline behavior, kept as the A/B arm; output is
# byte-identical for any depth).  Per-driver override via their
# pipeline_depth= argument.
stream_pipeline_depth = 2

# --- Serving (serve/: the continuous-batching TOA service) ----------------
# Deadline for partially-filled buckets in the serving loop
# (serve/server.ToaServer): a fused bucket launches when FULL
# (nsub_batch subints) or when its oldest pending subint has waited
# this many milliseconds — the continuous-batching policy that keeps
# per-request latency bounded under light traffic while heavy traffic
# fills buckets completely.  Per-server override via
# ToaServer(max_wait_ms=...) / ppserve --max-wait-ms.
serve_max_wait_ms = 50.0

# Admission-queue capacity of the serving loop, counted in ARCHIVES
# (the unit of admission work) across all pending requests.  The bound
# is the backpressure story: a submit that would exceed it is REJECTED
# loudly (serve.ServeRejected) rather than queued into unbounded host
# memory — clients retry or shed load.  Per-server override via
# ToaServer(queue_depth=...) / ppserve --queue-depth.
serve_queue_depth = 64

# --- Online ingest (ingest/: the observatory pipeline) --------------------
# Poll cadence [ms] of the watch-folder ingest source
# (ingest/source.WatchFolderSource): how often the directory is
# re-scanned for new archives.  Shorter polls shave admit latency at
# the cost of directory stat traffic; the bench gates admit->TOA p99
# against this.  Per-source override via WatchFolderSource(poll_ms=).
ingest_poll_ms = 200.0

# Size-stability window [ms] for watch-folder admission: a file whose
# size (or mtime) changed within the last this-many milliseconds is
# presumed still being written and is NOT admitted yet — the guard
# that keeps half-written PSRFITS out of the loaders.  A '<name>.done'
# completion sentinel next to the file bypasses the wait (the writer
# declares completeness explicitly).  Per-source override via
# WatchFolderSource(stable_ms=).
ingest_stable_ms = 500.0

# CUSUM reference value k for the residual-stream anomaly detectors
# (ingest/alerts.py), in units of the standardized residual's sigma:
# drifts smaller than k per sample accumulate nothing, so k sets the
# smallest step the detector is sensitive to (classic choice: half the
# step size you care about).  Per-detector override via
# CusumDetector(k=).
alert_cusum_k = 0.5

# CUSUM decision threshold h (same sigma units): an alert fires when
# the accumulated one-sided sum crosses h.  Larger h trades detection
# delay for false-alarm rate; the bench gates zero false alarms on a
# clean control corpus at the default.  Per-detector override via
# CusumDetector(h=).
alert_cusum_h = 5.0

# Full-resolve cadence of the incremental GLS lane
# (timing/incremental.IncrementalGLS): every this-many sequential TOA
# updates the lane rebuilds the whole system through the batch solver
# (the digit oracle) and REFUSES loudly if the incremental solution
# drifted beyond its tolerance — the guard that keeps O(params^2)
# rank updates honest against float accumulation.  0 disables the
# periodic resolve (structural resolves on new DMX epochs still
# happen).  Per-lane override via IncrementalGLS(resolve_every=).
gls_resolve_every = 64

# --- Cross-host routing (serve/router.py + serve/transport.py) ------------
# Default fleet for ToaRouter / the pproute CLI: a tuple of
# 'host:port' endpoints, each a ``ppserve --listen`` serving loop.
# () (default) = no fleet configured; pproute then requires --hosts.
# Set via PPT_ROUTER_HOSTS="hostA:9090,hostB:9090" (strict host:port
# parse per entry — a silently dropped endpoint would quietly shrink
# the fleet an A/B measures).
router_hosts = ()

# Total placement attempts the router spends per request before the
# last retryable rejection is raised: every ServeRejected(retryable)
# backpressure signal or unreachable host consumes one attempt, and
# each full pass over the fleet backs off exponentially (capped).
# Per-router override via ToaRouter(retry_max=...).
router_retry_max = 16

# Default listen endpoint for ``ppserve --listen`` (the remote-
# transport server): 'host:port' (port 0 = ephemeral, printed at
# start).  None (default) = ppserve serves its request file locally.
# Set via PPT_SERVE_LISTEN=host:port.
serve_listen = None

# --- Elastic fleet (serve/fleet.py + the ISSUE 13 router rework) -----------
# Deadline [ms] on the router's per-host ``stat`` liveness probes: a
# placement pass waits at most this long for a load refresh; a probe
# still outstanding past the deadline feeds the host's SUSPECT
# transition and the CACHED last-known load is used, so one hung host
# can never delay every submit behind its socket timeout.  Set via
# PPT_ROUTER_PROBE_MS (must be > 0).
router_probe_ms = 1000.0

# Hedged-request deadline [ms]: a routed request still unresolved
# after this long launches ONE duplicate attempt on the least-loaded
# other eligible host — first completion wins, the loser is cancelled
# at collection.  Tail-latency insurance for fleets with straggling
# hosts; byte-identity holds because both hosts serve identical .tim
# content (bench_router gates hedging off-vs-on byte-identical on a
# clean fleet).  None (default) = hedging off.  Set via
# PPT_ROUTER_HEDGE_MS=<ms>|off.
router_hedge_ms = None

# Watched fleet-membership file for ToaRouter / ``pproute
# --fleet-file``: one host:port per line (# comments); the router
# add/remove-hosts to match whenever the file changes, so operators
# grow or shrink a fleet by editing a file — no router restart.  None
# (default) = static membership only.  Set via PPT_ROUTER_FLEET_FILE.
router_fleet_file = None

# --- Multi-tenant QoS (serve/queue.AdmissionQueue) -------------------------
# Per-tenant pending-archive quota inside the admission queue: an int
# caps EVERY tenant, a dict {tenant: cap} (with optional '*' default)
# caps named tenants, None (default) applies only the global
# serve_queue_depth bound.  A tenant at its quota gets the same
# retryable ServeRejected backpressure as a full queue, but no single
# tenant can occupy the whole queue.  Set via
# PPT_SERVE_TENANT_QUOTA="<N>" or "tenantA:4,tenantB:32[,*:8]" or
# 'off'.
serve_tenant_quota = None

# Per-tenant weights for the admission queue's weighted-fair
# scheduler: {tenant: weight} ('*' sets the default; unlisted tenants
# weigh 1.0).  Lanes are served in proportion to weight, measured in
# ARCHIVES — a bulk campaign tenant with weight 1 cannot starve an
# interactive tenant with weight 4.  None (default) = equal weights.
# Set via PPT_SERVE_TENANT_WEIGHT="interactive:4,bulk:1" or 'off'.
serve_tenant_weight = None

# --- The link war (ISSUE 15): sub-byte raw transport + compression --------
# Raw-transport sub-byte NBIT lane: 1/2/4-bit packed DATA columns ship
# their PACKED bytes to the accelerator (raw codes 'p1'/'p2'/'p4') and
# the fused bucket program unpacks the bit planes with integer
# shifts/masks on device — a 2-bit archive ships 32x fewer bytes than
# the decoded-float64 fallback on the link that bottlenecks campaigns.
# False is the escape hatch: read_archive(decode=False) refuses
# sub-byte layouts again and the streaming loaders fall back to the
# host-decoded lane per archive (the digit oracle arm).
raw_subbyte = True

# Compressed transport for the streaming copy stage and the serve
# socket frames.  The h2d lane uses the lossless width-reduction block
# codec (io/blockcodec.py): integer raw payloads whose per-dispatch
# dynamic range fits a narrower bit width ship bit-plane packed (the
# device decode is the same unpack op the sub-byte lane uses, inside
# the fused program); the socket lane compresses large frames with
# zlib.  Tri-state:
#   False (default): never compress — bit-stable byte accounting.
#   'auto': a COST MODEL decides per dispatch, fed from the live
#          h2d_start/h2d_done MB/s telemetry — compress only when the
#          predicted codec wall is below the predicted link savings,
#          so a fast local link (bare CPU memcpy) never pays the codec
#          and a tunneled link engages it automatically.
#   True:  always compress when the payload is compressible (the
#          deterministic A/B arm; the cost model is bypassed).
# .tim output is digit-identical compressed or not — the codec is
# lossless and the decode runs before any arithmetic the fit sees.
transport_compress = False

# --- Result cache (ISSUE 17; ROADMAP item 5a) -----------------------------
# Content-addressed cache of completed .tim results (serve/cache.py):
# key = SHA-256 over (archive bytes, template bytes, frozen fit
# options, byte-relevant numeric knobs), value = the codec's byte-
# exact .tim payload, so a hit is byte-identical to a fresh fit by
# construction.  The router checks it before placement (a hit never
# touches a host); the server checks at submit and populates on
# request_done; ppfactory stores .gmodel/.spl artifacts through the
# same store.  Tri-state:
#   False (default spelling 'auto' below): off;
#   'auto': on iff cache_dir is set — the cache is OFF out of the box;
#   True:  on; raises loudly when cache_dir is unset.
result_cache = "auto"
# Directory holding the on-disk store (created on demand).  None
# (default) = no store, which with result_cache='auto' means OFF.
cache_dir = None
# Store size bound in MB: least-recently-used entries evict (with
# cache_evict telemetry) once the directory exceeds this.
cache_max_mb = 512.0

# Bucket-lattice coarsening (ROADMAP item 5): pad bucket channel
# layouts up to the next power of two with zero-weight channels so a
# campaign's (or serving fleet's) shape diversity costs log2 as many
# distinct XLA compiles.  Masked pad channels contribute exactly zero
# to every fit statistic, so .tim output is digit-identical padded vs
# exact (guarded by tests/test_serve.py).
#   False (default): exact shapes — keeps every lane's outputs
#          bit-stable across releases and pays one compile per nchan.
#   'auto': pad on TPU backends (where the compile cost dominates).
#   True:  always pad.
bucket_pad = False

# jax persistent compilation cache directory (ROADMAP item 5): the
# streaming drivers pay a trace + XLA compile per (bucket shape x
# device) on every process start, and a serving fleet re-pays that
# cold start across its whole shape lattice on every restart.  Set a
# path to have utils/device.enable_compile_cache() route jax's
# persistent cache there (created on demand; the stream executor and
# pptoas enable it automatically when set).  None (default) = off.
# Telemetry's cold-start events gate the before/after.
compile_cache_dir = None

# Campaign telemetry (telemetry.py): path of the JSONL event trace the
# campaign drivers (GetTOAs.get_TOAs, stream_wideband_TOAs /
# stream_narrowband_TOAs, stream_ipta_campaign) append structured
# events to — per-bucket dispatch/drain records with device ids and
# queue depths, per-archive prepare/flush/skip records, per-TOA fit
# quality, and a self-describing manifest header.  None (default) =
# off, with near-zero cost on the hot path (one attribute read per
# instrumentation site).  Per-call override via the drivers'
# telemetry= argument (a path, or a shared telemetry.Tracer); analyze
# with tools/pptrace.py.
telemetry_path = None

# --- Fleet observability (obs/, ISSUE 20) ---------------------------------
# Streaming metrics registry on ToaServer and ToaRouter: thread-safe
# counters/gauges plus fixed log-bucket latency histograms (p50/p99
# without sample retention), exported over the transports' 'metrics'
# op and aggregated fleet-wide by the router — what ppmon polls.  On
# by default: the off-cost is a handful of dict increments per request
# (never a device sync), and .tim output is byte-identical either way
# (bench_obs.py gates both).  False = the registries are never built
# and every instrumentation site is one attribute test.  Set via
# PPT_METRICS=off|on or ppserve/pproute --metrics.
metrics = True

# Per-tenant request-latency SLO targets in SECONDS for the burn-rate
# engine (obs/slo.py): {tenant: target_s} with '*' as the default
# objective, or a bare number applying to every tenant.  None
# (default) = no SLO tracking.  A tenant burning error budget >= 10x
# too fast over BOTH the 5-minute and 1-hour windows raises one
# slo_breach telemetry event per breach edge; attainment and burn
# rates ride the metrics export either way.  Set via
# PPT_SLO_TARGETS="interactive:0.5,bulk:30[,*:5]" (or a bare
# "<seconds>") or ppserve/pproute --slo-targets.
slo_targets = None

# ppmon dashboard refresh interval in milliseconds (how often the
# router's 'metrics' op is polled).  Set via PPT_MON_INTERVAL_MS or
# ppmon --interval.
mon_interval_ms = 1000.0

# Harmonic window for the fast fit lane.  A smooth template's power
# spectrum decays to numerical zero well below the Nyquist harmonic
# (the bench Gaussian template holds all but ~7e-13 of its power in
# k < 128 of 1025), and the fit's estimator is a matched filter — every
# statistic it computes weights the data by the model spectrum, so
# harmonics where the model has no power contribute exactly nothing.
# Truncating the data DFT and the Newton moment passes at the model's
# bandwidth is then numerically invisible (chi2/dof stay full-spectrum
# via a time-domain Parseval data-power term) and cuts the fit's two
# dominant costs — the MXU DFT and the VPU moment trig — by the same
# factor (measured round 4: 29.8 -> 10.0 ms and 11.0 -> 3.2 ms at
# 640x512x2048 with K=256).
#   "auto": derive K from the model's measured spectrum when the model
#           is host-resident (numpy); device-resident models keep the
#           full spectrum (no silent device pulls).
#   int:    explicit harmonic count (rounded up to a multiple of 128).
#   None:   always full spectrum.
fit_harmonic_window = "auto"
# Maximum relative model power allowed beyond the window (per channel,
# worst case).  1e-12 sits ~6 orders below f32's own rounding floor;
# one extra 128-harmonic block of margin is always added on top.
harmonic_window_tail = 1e-12
# Data-built templates (ppspline/ppgauss from real archives) carry a
# white Fourier noise floor ~1e-6..1e-4 of total power — far above
# harmonic_window_tail — which would pin the absolute criterion at
# full spectrum.  Harmonics at the template's own floor carry no
# matched-filter information, so the window derivation estimates each
# channel's floor from its top-quarter spectral plateau, subtracts the
# expected pure-noise tail, and requires the excess to clear this many
# sigma of the tail-sum fluctuation (std = sqrt(m)*mu for m tail
# harmonics) before a harmonic counts as needed.  Clean templates
# (floor ~ 0) reduce exactly to the absolute criterion; a floor
# holding >10% of total power is treated as signal (no subtraction).
# None or 0 disables floor awareness (round-4 behavior).
harmonic_window_floor_sigma = 20.0

# --- Autotune (tune/: the per-backend autotune subsystem) ------------------
# Straggler-compaction chunk length of the BATCHED template-factory LM
# fits (fit/gauss.py -> fit/lm.levenberg_marquardt_batched): re-batch
# the still-iterating problems every this many iterations.  Output is
# digit-identical for any value (compaction splits the loop at exact
# iteration boundaries), so this sits in the autotune identity tier.
# None = one uninterrupted dispatch.  16 was the round-12 hand-tuned
# winner on TPU v5e; the tuning DB overrides it per backend.
lm_compact_every = 16
# Path of the persisted JSON tuning DB (tune/store.TuningStore): the
# autotune sweep's accepted winners, keyed by (backend fingerprint,
# shape class).  None (default) = no persistence — every
# tune.ensure_tuned call re-sweeps.  A DB written on a different
# backend fingerprint is refused with a loud warning (never applied,
# never fatal).  Env: PPT_TUNE_DB=<path>|off.  CLI: pptoas/ppserve/
# pproute --tune-db.
tune_db = None
# Whether campaign entry points (pptoas --autotune) run the autotune
# sweep when the tuning DB has no entry for this backend + shape
# class.  False (default): tuning is explicit — a campaign never pays
# sweep time unasked.  Env: PPT_AUTOTUNE=off|on.
autotune = False
# Opt-in for the NUMERICS knob tier (tune/autotune.NUMERICS_TIER:
# cross_spectrum_dtype, dft_precision).  These change output digits —
# that is their point — so they are NEVER swept silently: False
# (default) sweeps only the output-identity-preserving tier (and every
# candidate there is still byte-gated against the default).  Env:
# PPT_TUNE_NUMERICS=off|on.
tune_numerics = False

# --- Model evolution codes ------------------------------------------------
# Per-parameter evolution function code string for .gmodel files:
# one digit each for (loc, wid, amp); '0' = power law, '1' = linear
# (reference pplib.py:95).
default_model_code = "000"

# --- TOA conventions ------------------------------------------------------
SECPERDAY = 86400.0
# TEMPO2 convention: 0.0 MHz in a .tim line means infinite frequency
# (reference pplib.py:3613).
INF_FREQ = 0.0

# --- Optimizer return-code strings (scipy fmin_tnc heritage; we keep the
# same small vocabulary so downstream flag plumbing is stable) --------------
RCSTRINGS = {
    -1: "INFEASIBLE: Infeasible (lower bound > upper bound)",
    0: "LOCALMINIMUM: Local minimum reached (|pg| ~= 0)",
    1: "CONVERGED: Converged (|f_n-f_(n-1)| ~= 0)",
    2: "CONVERGED: Converged (|x_n-x_(n-1)| ~= 0)",
    3: "MAXFUN: Max. number of function evaluations reached",
    4: "LSFAIL: Linear search failed",
    5: "CONSTANT: All lower bounds are equal to the upper bounds",
    6: "NOPROGRESS: Unable to progress",
    7: "USERABORT: User requested end of minimization",
}

# --- Environment hooks ----------------------------------------------------
# One documented A/B switch shared by every benchmark and CLI (the
# per-script parsing that used to live in bench.py).  Applied once at
# import; scripts that set their own config defaults re-apply with
# env_overrides() afterwards so the environment always wins:
#
#   PPT_LM_JACOBIAN=auto|analytic|ad -> lm_jacobian
#   PPT_FIT_FUSED=off|auto|on       -> fit_fused
#   PPT_FIT_PALLAS=off|auto|on      -> fit_pallas
#   PPT_FUSED_BLOCK=<N>             -> fused_block
#   PPT_XSPEC=float32|bfloat16      -> cross_spectrum_dtype
#   PPT_DFT_PRECISION=highest|high|default -> dft_precision
#   PPT_DFT_FOLD=off|auto|on        -> dft_fold
#   PPT_ALIGN_DEVICE=off|auto|on    -> align_device
#   PPT_GAUSS_DEVICE=off|auto|on    -> gauss_device
#   PPT_GLS_DEVICE=off|auto|on      -> gls_device
#   PPT_ZAP_DEVICE=off|auto|on      -> zap_device
#   PPT_ZAP_NSTD=<float>            -> zap_nstd
#   PPT_QUALITY_REFIT=off|on        -> quality_refit
#   PPT_QUALITY_MAX_GOF=<float>     -> quality_max_gof
#   PPT_QUALITY_MIN_SNR=<float>     -> quality_min_snr
#   PPT_STREAM_DEVICES=auto|<N>     -> stream_devices
#   PPT_MAX_INFLIGHT=<N>            -> stream_max_inflight
#   PPT_PIPELINE_DEPTH=<N>          -> stream_pipeline_depth
#   PPT_COMPILE_CACHE=<dir>|off     -> compile_cache_dir
#   PPT_TELEMETRY=<path>|off        -> telemetry_path
#   PPT_SERVE_MAX_WAIT_MS=<float>   -> serve_max_wait_ms
#   PPT_SERVE_QUEUE_DEPTH=<N>       -> serve_queue_depth
#   PPT_INGEST_POLL_MS=<float>      -> ingest_poll_ms
#   PPT_INGEST_STABLE_MS=<float>    -> ingest_stable_ms
#   PPT_ALERT_CUSUM_K=<float>       -> alert_cusum_k
#   PPT_ALERT_CUSUM_H=<float>       -> alert_cusum_h
#   PPT_GLS_RESOLVE_EVERY=<N>       -> gls_resolve_every
#   PPT_BUCKET_PAD=off|auto|on      -> bucket_pad
#   PPT_ROUTER_HOSTS=h:p[,h:p...]|off -> router_hosts
#   PPT_ROUTER_RETRY_MAX=<N>        -> router_retry_max
#   PPT_ROUTER_PROBE_MS=<float>     -> router_probe_ms
#   PPT_ROUTER_HEDGE_MS=<float>|off -> router_hedge_ms
#   PPT_ROUTER_FLEET_FILE=<path>|off -> router_fleet_file
#   PPT_SERVE_LISTEN=<host:port>|off -> serve_listen
#   PPT_SERVE_TENANT_QUOTA=<N>|t:N,...|off -> serve_tenant_quota
#   PPT_SERVE_TENANT_WEIGHT=t:W,...|off    -> serve_tenant_weight
#   PPT_RAW_SUBBYTE=on|off          -> raw_subbyte
#   PPT_TRANSPORT_COMPRESS=off|auto|on -> transport_compress
#   PPT_RESULT_CACHE=off|auto|on    -> result_cache
#   PPT_CACHE_DIR=<dir>|off         -> cache_dir
#   PPT_CACHE_MAX_MB=<float>        -> cache_max_mb
#   PPT_TUNE_DB=<path>|off          -> tune_db
#   PPT_AUTOTUNE=off|on             -> autotune
#   PPT_TUNE_NUMERICS=off|on        -> tune_numerics
#   PPT_METRICS=off|on              -> metrics
#   PPT_SLO_TARGETS=t:S,...|<S>|off -> slo_targets
#   PPT_MON_INTERVAL_MS=<float>     -> mon_interval_ms
#
# Unset variables leave the module values untouched; a typo in a
# KNOWN variable's value raises (strict like the config parsers — a
# silent fallback would quietly invalidate an A/B run), and an
# unrecognized PPT_*-prefixed NAME warns once to stderr: PPT_STREAM
# _DEVICE would otherwise be silently ignored while PPT_STREAM_DEVICES
# changes behavior.


# Every PPT_* variable something in this repo reads: the config hooks
# above plus the benchmark/test shape knobs (benchmarks/*.py, bench.py,
# tests/test_bench_smoke.py).  A new knob must be registered here or
# env_overrides() warns about it.
KNOWN_PPT_ENV = frozenset({
    # config hooks (this module)
    "PPT_LM_JACOBIAN", "PPT_FIT_FUSED",
    "PPT_FIT_PALLAS", "PPT_FUSED_BLOCK",
    "PPT_XSPEC", "PPT_DFT_PRECISION", "PPT_DFT_FOLD",
    "PPT_ALIGN_DEVICE", "PPT_GAUSS_DEVICE",
    "PPT_GLS_DEVICE", "PPT_ZAP_DEVICE", "PPT_ZAP_NSTD",
    "PPT_QUALITY_REFIT", "PPT_QUALITY_MAX_GOF", "PPT_QUALITY_MIN_SNR",
    "PPT_STREAM_DEVICES", "PPT_MAX_INFLIGHT",
    "PPT_PIPELINE_DEPTH", "PPT_COMPILE_CACHE", "PPT_TELEMETRY",
    "PPT_SERVE_MAX_WAIT_MS", "PPT_SERVE_QUEUE_DEPTH", "PPT_BUCKET_PAD",
    "PPT_INGEST_POLL_MS", "PPT_INGEST_STABLE_MS",
    "PPT_ALERT_CUSUM_K", "PPT_ALERT_CUSUM_H", "PPT_GLS_RESOLVE_EVERY",
    "PPT_ROUTER_HOSTS", "PPT_ROUTER_RETRY_MAX", "PPT_SERVE_LISTEN",
    "PPT_ROUTER_PROBE_MS", "PPT_ROUTER_HEDGE_MS",
    "PPT_ROUTER_FLEET_FILE", "PPT_SERVE_TENANT_QUOTA",
    "PPT_SERVE_TENANT_WEIGHT",
    "PPT_RAW_SUBBYTE", "PPT_TRANSPORT_COMPRESS",
    "PPT_RESULT_CACHE", "PPT_CACHE_DIR", "PPT_CACHE_MAX_MB",
    "PPT_TUNE_DB", "PPT_AUTOTUNE", "PPT_TUNE_NUMERICS",
    "PPT_METRICS", "PPT_SLO_TARGETS", "PPT_MON_INTERVAL_MS",
    # benchmark / smoke-test shape and mode knobs
    "PPT_NB", "PPT_NE", "PPT_NPSR", "PPT_NARCH", "PPT_NSUB",
    "PPT_NSUBB", "PPT_NCHAN", "PPT_NBIN", "PPT_NITER", "PPT_K",
    "PPT_NREQ", "PPT_NHOSTS", "PPT_DEVICES", "PPT_CAMPAIGN_CACHE",
    "PPT_ALIGN_CACHE",
    "PPT_GAUSS_CACHE", "PPT_NGAUSS",
    "PPT_TEMPLATE_NOISE", "PPT_STREAM_SPEEDUP_GATE",
    "PPT_HARMONIC_WINDOW", "PPT_TUNNEL_EMU", "PPT_RETUNE",
    "PPT_ZIPF_S", "PPT_CACHE_SPEEDUP_GATE",
    "PPT_NSEEDS", "PPT_INGEST_P99_GATE",
    "PPT_TUNE_NRUN", "PPT_SLOW_MS", "PPT_OBS_OVERHEAD_GATE",
})

def parse_hostport(spec):
    """'host:port' -> (host, port), loud on anything else — shared by
    the env hooks below, the serve transports, and the CLIs (a
    silently mis-parsed endpoint would strand a fleet member)."""
    s = str(spec).strip()
    host, sep, port = s.rpartition(":")
    if not sep or not host:
        raise ValueError(f"expected 'host:port', got {spec!r}")
    try:
        port = int(port)
    except ValueError:
        raise ValueError(
            f"expected an integer port in {spec!r}, got {port!r}")
    if not 0 <= port <= 65535:
        raise ValueError(f"port {port} out of range in {spec!r}")
    return host, port


def parse_tenant_spec(raw, name, cast=int, allow_bare=True):
    """Parse a tenant QoS spec: '<N>' (every tenant, needs
    allow_bare) or 'tenantA:N,tenantB:M[,*:K]' -> int-or-dict, loud on
    anything else — shared by the PPT_SERVE_TENANT_* env hooks and the
    ppserve/pproute CLIs (a silently mis-parsed quota would quietly
    remove a fairness guarantee)."""
    s = str(raw).strip()
    if not s:
        raise ValueError(f"{name}: empty tenant spec")
    if ":" not in s:
        if not allow_bare:
            raise ValueError(
                f"{name} must be 'tenant:value[,tenant:value...]' "
                f"pairs, got {s!r} (a bare value is meaningless for "
                "weights — equal weights are the default)")
        try:
            v = cast(s)
        except ValueError:
            raise ValueError(
                f"{name} must be a number or tenant:value pairs, got "
                f"{s!r}")
        if not v > 0:
            raise ValueError(f"{name} must be > 0, got {v}")
        return v
    out = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        tenant, sep, val = part.rpartition(":")
        if not sep or not tenant:
            raise ValueError(
                f"{name}: expected 'tenant:value', got {part!r}")
        try:
            v = cast(val)
        except ValueError:
            raise ValueError(
                f"{name}: expected a numeric value in {part!r}, got "
                f"{val!r}")
        if not v > 0:
            raise ValueError(
                f"{name}: value for tenant {tenant!r} must be > 0, "
                f"got {v}")
        if tenant in out:
            raise ValueError(
                f"{name}: tenant {tenant!r} listed twice")
        out[tenant] = v
    if not out:
        raise ValueError(f"{name}: no tenant:value pairs in {raw!r}")
    return out


_warned_unknown_ppt = set()  # warn ONCE per process per variable


def _warn_unknown_ppt_vars(environ):
    """Warn (once, stderr) about PPT_*-prefixed variables nothing
    reads — a typo like PPT_STREAM_DEVICE is silently inert while its
    correct spelling changes behavior, the worst kind of A/B hazard."""
    import difflib
    import sys as _sys

    for name in sorted(environ):
        if (not name.startswith("PPT_") or name in KNOWN_PPT_ENV
                or name in _warned_unknown_ppt):
            continue
        _warned_unknown_ppt.add(name)
        close = difflib.get_close_matches(name, KNOWN_PPT_ENV, n=1)
        hint = f" (did you mean {close[0]}?)" if close else ""
        print(f"pulseportraiture_tpu.config: ignoring unrecognized "
              f"environment variable {name}{hint} — known PPT_* hooks "
              "are listed in config.KNOWN_PPT_ENV", file=_sys.stderr)


def env_overrides():
    """Apply the PPT_* environment hooks to this module; call after
    setting script-level config defaults so the env A/B switch wins.
    Returns the names it changed."""
    import os as _os
    import sys as _sys

    cfg = _sys.modules[__name__]
    changed = []
    _warn_unknown_ppt_vars(_os.environ)
    lmjac = _os.environ.get("PPT_LM_JACOBIAN", "").lower()
    if lmjac:
        if lmjac not in ("auto", "analytic", "ad"):
            raise ValueError(
                f"PPT_LM_JACOBIAN must be 'auto', 'analytic' or 'ad', "
                f"got {lmjac!r}")
        cfg.lm_jacobian = lmjac
        changed.append("lm_jacobian")
    ffused = _os.environ.get("PPT_FIT_FUSED", "").lower()
    if ffused:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if ffused not in table:
            raise ValueError(
                f"PPT_FIT_FUSED must be 'off', 'auto' or 'on', got "
                f"{ffused!r}")
        cfg.fit_fused = table[ffused]
        changed.append("fit_fused")
    fpallas = _os.environ.get("PPT_FIT_PALLAS", "").lower()
    if fpallas:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if fpallas not in table:
            raise ValueError(
                f"PPT_FIT_PALLAS must be 'off', 'auto' or 'on', got "
                f"{fpallas!r}")
        cfg.fit_pallas = table[fpallas]
        changed.append("fit_pallas")
    fblock = _os.environ.get("PPT_FUSED_BLOCK", "")
    if fblock:
        try:
            v = int(fblock)
        except ValueError:
            raise ValueError(
                "PPT_FUSED_BLOCK must be a positive integer channel "
                f"block size, got {fblock!r}")
        if not v > 0:
            raise ValueError(f"PPT_FUSED_BLOCK must be > 0, got {v}")
        cfg.fused_block = v
        changed.append("fused_block")
    xspec = _os.environ.get("PPT_XSPEC", "").lower()
    if xspec:
        table = {"float32": None, "none": None, "bfloat16": "bfloat16"}
        if xspec not in table:
            raise ValueError(
                f"PPT_XSPEC must be 'float32' or 'bfloat16', got "
                f"{xspec!r}")
        cfg.cross_spectrum_dtype = table[xspec]
        changed.append("cross_spectrum_dtype")
    prec = _os.environ.get("PPT_DFT_PRECISION", "").lower()
    if prec:
        if prec not in ("highest", "high", "default"):
            raise ValueError(
                "PPT_DFT_PRECISION must be 'highest', 'high' or "
                f"'default', got {prec!r}")
        cfg.dft_precision = prec
        changed.append("dft_precision")
    fold = _os.environ.get("PPT_DFT_FOLD", "").lower()
    if fold:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if fold not in table:
            raise ValueError(
                f"PPT_DFT_FOLD must be 'off', 'auto' or 'on', got "
                f"{fold!r}")
        cfg.dft_fold = table[fold]
        changed.append("dft_fold")
    # the tri-state device-lane knobs share one strict parse
    for env_name, attr in (("PPT_ALIGN_DEVICE", "align_device"),
                           ("PPT_GAUSS_DEVICE", "gauss_device"),
                           ("PPT_GLS_DEVICE", "gls_device"),
                           ("PPT_ZAP_DEVICE", "zap_device")):
        val = _os.environ.get(env_name, "").lower()
        if val:
            table = {"off": False, "false": False, "auto": "auto",
                     "on": True, "true": True}
            if val not in table:
                raise ValueError(
                    f"{env_name} must be 'off', 'auto' or 'on', got "
                    f"{val!r}")
            setattr(cfg, attr, table[val])
            changed.append(attr)
    znstd = _os.environ.get("PPT_ZAP_NSTD", "")
    if znstd:
        try:
            v = float(znstd)
        except ValueError:
            raise ValueError(
                "PPT_ZAP_NSTD must be a positive number of standard "
                f"deviations, got {znstd!r}")
        if not v > 0:
            raise ValueError(f"PPT_ZAP_NSTD must be > 0, got {v}")
        cfg.zap_nstd = v
        changed.append("zap_nstd")
    qref = _os.environ.get("PPT_QUALITY_REFIT", "").lower()
    if qref:
        table = {"off": False, "false": False, "on": True, "true": True}
        if qref not in table:
            raise ValueError(
                f"PPT_QUALITY_REFIT must be 'off' or 'on', got {qref!r}")
        cfg.quality_refit = table[qref]
        changed.append("quality_refit")
    qgof = _os.environ.get("PPT_QUALITY_MAX_GOF", "")
    if qgof:
        try:
            v = float(qgof)
        except ValueError:
            raise ValueError(
                "PPT_QUALITY_MAX_GOF must be a positive reduced-chi^2 "
                f"threshold, got {qgof!r}")
        if not v > 0:
            raise ValueError(
                f"PPT_QUALITY_MAX_GOF must be > 0, got {v}")
        cfg.quality_max_gof = v
        changed.append("quality_max_gof")
    qsnr = _os.environ.get("PPT_QUALITY_MIN_SNR", "")
    if qsnr:
        try:
            v = float(qsnr)
        except ValueError:
            raise ValueError(
                "PPT_QUALITY_MIN_SNR must be a non-negative S/N "
                f"threshold (0 disables), got {qsnr!r}")
        if v < 0:
            raise ValueError(
                f"PPT_QUALITY_MIN_SNR must be >= 0, got {v}")
        cfg.quality_min_snr = v
        changed.append("quality_min_snr")
    sdev = _os.environ.get("PPT_STREAM_DEVICES", "").lower()
    if sdev:
        if sdev == "auto":
            cfg.stream_devices = "auto"
        else:
            try:
                n = int(sdev)
            except ValueError:
                raise ValueError(
                    "PPT_STREAM_DEVICES must be 'auto' or a positive "
                    f"device count, got {sdev!r}")
            if n < 1:
                raise ValueError(
                    "PPT_STREAM_DEVICES must be >= 1 when numeric, "
                    f"got {n}")
            cfg.stream_devices = n
        changed.append("stream_devices")
    minf = _os.environ.get("PPT_MAX_INFLIGHT", "")
    if minf:
        try:
            n = int(minf)
        except ValueError:
            raise ValueError(
                "PPT_MAX_INFLIGHT must be a positive integer, got "
                f"{minf!r}")
        if n < 1:
            raise ValueError(
                f"PPT_MAX_INFLIGHT must be >= 1, got {n}")
        cfg.stream_max_inflight = n
        changed.append("stream_max_inflight")
    pdep = _os.environ.get("PPT_PIPELINE_DEPTH", "")
    if pdep:
        try:
            n = int(pdep)
        except ValueError:
            raise ValueError(
                "PPT_PIPELINE_DEPTH must be a positive integer, got "
                f"{pdep!r}")
        if n < 1:
            raise ValueError(
                f"PPT_PIPELINE_DEPTH must be >= 1, got {n}")
        cfg.stream_pipeline_depth = n
        changed.append("stream_pipeline_depth")
    cache = _os.environ.get("PPT_COMPILE_CACHE", "")
    if cache:
        # 'off'/'none'/'0' disable explicitly (a wrapper script can
        # force the cache off over a config default)
        cfg.compile_cache_dir = (
            None if cache.lower() in ("off", "none", "0") else cache)
        changed.append("compile_cache_dir")
    wait = _os.environ.get("PPT_SERVE_MAX_WAIT_MS", "")
    if wait:
        try:
            w = float(wait)
        except ValueError:
            raise ValueError(
                "PPT_SERVE_MAX_WAIT_MS must be a non-negative number "
                f"of milliseconds, got {wait!r}")
        if w < 0:
            raise ValueError(
                f"PPT_SERVE_MAX_WAIT_MS must be >= 0, got {w}")
        cfg.serve_max_wait_ms = w
        changed.append("serve_max_wait_ms")
    qd = _os.environ.get("PPT_SERVE_QUEUE_DEPTH", "")
    if qd:
        try:
            n = int(qd)
        except ValueError:
            raise ValueError(
                "PPT_SERVE_QUEUE_DEPTH must be a positive integer, "
                f"got {qd!r}")
        if n < 1:
            raise ValueError(
                f"PPT_SERVE_QUEUE_DEPTH must be >= 1, got {n}")
        cfg.serve_queue_depth = n
        changed.append("serve_queue_depth")
    ipoll = _os.environ.get("PPT_INGEST_POLL_MS", "")
    if ipoll:
        try:
            v = float(ipoll)
        except ValueError:
            raise ValueError(
                "PPT_INGEST_POLL_MS must be a positive number of "
                f"milliseconds, got {ipoll!r}")
        if not v > 0:
            raise ValueError(
                f"PPT_INGEST_POLL_MS must be > 0, got {v}")
        cfg.ingest_poll_ms = v
        changed.append("ingest_poll_ms")
    istab = _os.environ.get("PPT_INGEST_STABLE_MS", "")
    if istab:
        try:
            v = float(istab)
        except ValueError:
            raise ValueError(
                "PPT_INGEST_STABLE_MS must be a non-negative number "
                f"of milliseconds, got {istab!r}")
        if v < 0:
            raise ValueError(
                f"PPT_INGEST_STABLE_MS must be >= 0, got {v}")
        cfg.ingest_stable_ms = v
        changed.append("ingest_stable_ms")
    ck = _os.environ.get("PPT_ALERT_CUSUM_K", "")
    if ck:
        try:
            v = float(ck)
        except ValueError:
            raise ValueError(
                "PPT_ALERT_CUSUM_K must be a non-negative number (in "
                f"sigma units), got {ck!r}")
        if v < 0:
            raise ValueError(
                f"PPT_ALERT_CUSUM_K must be >= 0, got {v}")
        cfg.alert_cusum_k = v
        changed.append("alert_cusum_k")
    ch = _os.environ.get("PPT_ALERT_CUSUM_H", "")
    if ch:
        try:
            v = float(ch)
        except ValueError:
            raise ValueError(
                "PPT_ALERT_CUSUM_H must be a positive number (in "
                f"sigma units), got {ch!r}")
        if not v > 0:
            raise ValueError(
                f"PPT_ALERT_CUSUM_H must be > 0, got {v}")
        cfg.alert_cusum_h = v
        changed.append("alert_cusum_h")
    rev = _os.environ.get("PPT_GLS_RESOLVE_EVERY", "")
    if rev:
        try:
            n = int(rev)
        except ValueError:
            raise ValueError(
                "PPT_GLS_RESOLVE_EVERY must be a non-negative "
                f"integer (0 disables), got {rev!r}")
        if n < 0:
            raise ValueError(
                f"PPT_GLS_RESOLVE_EVERY must be >= 0, got {n}")
        cfg.gls_resolve_every = n
        changed.append("gls_resolve_every")
    bpad = _os.environ.get("PPT_BUCKET_PAD", "").lower()
    if bpad:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if bpad not in table:
            raise ValueError(
                f"PPT_BUCKET_PAD must be 'off', 'auto' or 'on', got "
                f"{bpad!r}")
        cfg.bucket_pad = table[bpad]
        changed.append("bucket_pad")
    rh = _os.environ.get("PPT_ROUTER_HOSTS", "")
    if rh:
        if rh.lower() in ("off", "none"):
            cfg.router_hosts = ()
        else:
            hosts = []
            for part in rh.split(","):
                part = part.strip()
                if not part:
                    continue
                try:
                    parse_hostport(part)
                except ValueError as e:
                    raise ValueError(
                        "PPT_ROUTER_HOSTS must be a comma-separated "
                        f"list of host:port endpoints: {e}")
                hosts.append(part)
            if not hosts:
                raise ValueError(
                    "PPT_ROUTER_HOSTS must name at least one "
                    f"host:port endpoint (or 'off'), got {rh!r}")
            if len(set(hosts)) != len(hosts):
                raise ValueError(
                    f"PPT_ROUTER_HOSTS lists a duplicate endpoint: "
                    f"{rh!r}")
            cfg.router_hosts = tuple(hosts)
        changed.append("router_hosts")
    rmax = _os.environ.get("PPT_ROUTER_RETRY_MAX", "")
    if rmax:
        try:
            n = int(rmax)
        except ValueError:
            raise ValueError(
                "PPT_ROUTER_RETRY_MAX must be a positive integer, "
                f"got {rmax!r}")
        if n < 1:
            raise ValueError(
                f"PPT_ROUTER_RETRY_MAX must be >= 1, got {n}")
        cfg.router_retry_max = n
        changed.append("router_retry_max")
    pms = _os.environ.get("PPT_ROUTER_PROBE_MS", "")
    if pms:
        try:
            v = float(pms)
        except ValueError:
            raise ValueError(
                "PPT_ROUTER_PROBE_MS must be a positive number of "
                f"milliseconds, got {pms!r}")
        if not v > 0:
            raise ValueError(
                f"PPT_ROUTER_PROBE_MS must be > 0, got {v}")
        cfg.router_probe_ms = v
        changed.append("router_probe_ms")
    hms = _os.environ.get("PPT_ROUTER_HEDGE_MS", "")
    if hms:
        if hms.lower() in ("off", "none"):
            cfg.router_hedge_ms = None
        else:
            try:
                v = float(hms)
            except ValueError:
                raise ValueError(
                    "PPT_ROUTER_HEDGE_MS must be a non-negative "
                    f"number of milliseconds or 'off', got {hms!r}")
            if v < 0:
                raise ValueError(
                    f"PPT_ROUTER_HEDGE_MS must be >= 0, got {v}")
            cfg.router_hedge_ms = v
        changed.append("router_hedge_ms")
    ffile = _os.environ.get("PPT_ROUTER_FLEET_FILE", "")
    if ffile:
        cfg.router_fleet_file = (
            None if ffile.lower() in ("off", "none") else ffile)
        changed.append("router_fleet_file")
    tq = _os.environ.get("PPT_SERVE_TENANT_QUOTA", "")
    if tq:
        if tq.lower() in ("off", "none"):
            cfg.serve_tenant_quota = None
        else:
            cfg.serve_tenant_quota = parse_tenant_spec(
                tq, "PPT_SERVE_TENANT_QUOTA", cast=int,
                allow_bare=True)
        changed.append("serve_tenant_quota")
    tw = _os.environ.get("PPT_SERVE_TENANT_WEIGHT", "")
    if tw:
        if tw.lower() in ("off", "none"):
            cfg.serve_tenant_weight = None
        else:
            cfg.serve_tenant_weight = parse_tenant_spec(
                tw, "PPT_SERVE_TENANT_WEIGHT", cast=float,
                allow_bare=False)
        changed.append("serve_tenant_weight")
    listen = _os.environ.get("PPT_SERVE_LISTEN", "")
    if listen:
        if listen.lower() in ("off", "none"):
            cfg.serve_listen = None
        else:
            try:
                parse_hostport(listen)
            except ValueError as e:
                raise ValueError(f"PPT_SERVE_LISTEN: {e}")
            cfg.serve_listen = listen
        changed.append("serve_listen")
    rsb = _os.environ.get("PPT_RAW_SUBBYTE", "").lower()
    if rsb:
        table = {"off": False, "false": False, "on": True, "true": True}
        if rsb not in table:
            raise ValueError(
                f"PPT_RAW_SUBBYTE must be 'on' or 'off', got {rsb!r}")
        cfg.raw_subbyte = table[rsb]
        changed.append("raw_subbyte")
    tcomp = _os.environ.get("PPT_TRANSPORT_COMPRESS", "").lower()
    if tcomp:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if tcomp not in table:
            raise ValueError(
                "PPT_TRANSPORT_COMPRESS must be 'off', 'auto' or "
                f"'on', got {tcomp!r}")
        cfg.transport_compress = table[tcomp]
        changed.append("transport_compress")
    rcache = _os.environ.get("PPT_RESULT_CACHE", "").lower()
    if rcache:
        table = {"off": False, "false": False, "auto": "auto",
                 "on": True, "true": True}
        if rcache not in table:
            raise ValueError(
                "PPT_RESULT_CACHE must be 'off', 'auto' or 'on', got "
                f"{rcache!r}")
        cfg.result_cache = table[rcache]
        changed.append("result_cache")
    cdir = _os.environ.get("PPT_CACHE_DIR", "")
    if cdir:
        cfg.cache_dir = (None if cdir.lower() in ("off", "none", "0")
                         else cdir)
        changed.append("cache_dir")
    cmb = _os.environ.get("PPT_CACHE_MAX_MB", "")
    if cmb:
        try:
            mb = float(cmb)
        except ValueError:
            raise ValueError(
                "PPT_CACHE_MAX_MB must be a positive number of "
                f"megabytes, got {cmb!r}")
        if mb <= 0:
            raise ValueError(
                f"PPT_CACHE_MAX_MB must be > 0, got {mb}")
        cfg.cache_max_mb = mb
        changed.append("cache_max_mb")
    tdb = _os.environ.get("PPT_TUNE_DB", "")
    if tdb:
        cfg.tune_db = (None if tdb.lower() in ("off", "none", "0")
                       else tdb)
        changed.append("tune_db")
    atune = _os.environ.get("PPT_AUTOTUNE", "").lower()
    if atune:
        table = {"off": False, "false": False, "0": False,
                 "on": True, "true": True, "1": True}
        if atune not in table:
            raise ValueError(
                f"PPT_AUTOTUNE must be 'off' or 'on', got {atune!r}")
        cfg.autotune = table[atune]
        changed.append("autotune")
    tnum = _os.environ.get("PPT_TUNE_NUMERICS", "").lower()
    if tnum:
        table = {"off": False, "false": False, "0": False,
                 "on": True, "true": True, "1": True}
        if tnum not in table:
            raise ValueError(
                "PPT_TUNE_NUMERICS must be 'off' or 'on', got "
                f"{tnum!r}")
        cfg.tune_numerics = table[tnum]
        changed.append("tune_numerics")
    met = _os.environ.get("PPT_METRICS", "").lower()
    if met:
        table = {"off": False, "false": False, "0": False,
                 "on": True, "true": True, "1": True}
        if met not in table:
            raise ValueError(
                f"PPT_METRICS must be 'off' or 'on', got {met!r}")
        cfg.metrics = table[met]
        changed.append("metrics")
    slo = _os.environ.get("PPT_SLO_TARGETS", "")
    if slo:
        if slo.lower() in ("off", "none", "0"):
            cfg.slo_targets = None
        else:
            # bare seconds (every tenant) or tenant:seconds pairs;
            # float cast — sub-second interactive objectives are the
            # common case
            cfg.slo_targets = parse_tenant_spec(
                slo, "PPT_SLO_TARGETS", cast=float, allow_bare=True)
        changed.append("slo_targets")
    mon = _os.environ.get("PPT_MON_INTERVAL_MS", "")
    if mon:
        try:
            v = float(mon)
        except ValueError:
            raise ValueError(
                "PPT_MON_INTERVAL_MS must be a positive number of "
                f"milliseconds, got {mon!r}")
        if not v > 0:
            raise ValueError(
                f"PPT_MON_INTERVAL_MS must be > 0, got {v}")
        cfg.mon_interval_ms = v
        changed.append("mon_interval_ms")
    tel = _os.environ.get("PPT_TELEMETRY", "")
    if tel:
        # 'off'/'none'/'0' disable explicitly (so a wrapper script can
        # force telemetry off over a config default); anything else is
        # the trace path
        cfg.telemetry_path = (None if tel.lower() in ("off", "none", "0")
                              else tel)
        changed.append("telemetry_path")
    return changed


env_overrides()
