"""Synthetic data generation — the framework's test fixture.

Generates data portraits with *known injected* (phi, DM, GM, tau,
alpha, per-channel scales, noise, RFI mask, scintillation), so every
fit engine and pipeline can be validated by parameter recovery — the
reference's own end-to-end verification pattern (make_fake_pulsar,
reference pplib.py:3302-3499, driven by examples/example.py).

This module is portrait-level (pure arrays); the PSRFITS-archive
writer wrapping it lives in io/psrfits.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gaussian import GaussianModel, gen_gaussian_portrait
from ..ops.phasor import phase_shifts
from ..ops.phasor import phasor as make_phasor
from ..ops.scattering import add_scattering, scattering_times
from ..utils.bunch import DataBunch


def default_test_model(nu_ref=1500.0):
    """A 3-component evolving-Gaussian model like the reference's
    examples/example.gmodel (values chosen fresh, same structure)."""
    return GaussianModel(
        name="FAKE_0000+0000",
        code="000",
        nu_ref=nu_ref,
        dc=0.0,
        tau=0.0,
        alpha=-4.0,
        locs=np.array([0.48, 0.505, 0.52]),
        wids=np.array([0.045, 0.015, 0.022]),
        amps=np.array([4.0, 9.5, 2.5]),
        mlocs=np.array([-0.005, -0.003, 0.003]),
        mwids=np.array([-0.2, 0.16, -0.3]),
        mamps=np.array([-1.6, -2.0, -0.9]),
    )


def fake_portrait(
    key,
    model,
    freqs,
    nbin,
    P,
    phi=0.0,
    DM=0.0,
    GM=0.0,
    tau=0.0,
    alpha=None,
    nu_ref=None,
    scales=None,
    noise_std=1.0,
    zap_frac=0.0,
    scint_nsin=0,
    dtype=jnp.float64,
):
    """One (nchan, nbin) data portrait with known injected parameters.

    phi/DM/GM are referenced to ``nu_ref`` (default: model.nu_ref); a
    fit of this portrait against the clean model should recover them
    there.  ``tau`` [s at nu_ref] scatters with index ``alpha``;
    ``scales`` (nchan,) multiplies channels; ``noise_std`` adds white
    noise; ``zap_frac`` randomly zero-weights channels.

    Returns a DataBunch with port, model_port, weights, noise_stds,
    freqs, P and the injected truth values.
    """
    freqs = jnp.asarray(freqs, dtype)
    nchan = freqs.shape[0]
    nu_ref = model.nu_ref if nu_ref is None else nu_ref
    alpha = model.alpha if alpha is None else alpha
    params = {k: v.astype(dtype) if hasattr(v, "astype") else v
              for k, v in model.params_pytree().items()}

    clean = gen_gaussian_portrait(
        params, freqs, model.nu_ref, nbin, P=P, code=model.code, scattered=False
    )

    port = clean
    if tau != 0.0:
        taus = scattering_times(tau / P, alpha, freqs, nu_ref)
        port = add_scattering(port, taus)

    # delay by the injected (phi, DM, GM): rotate to *later* phase so
    # that fitting returns positive (phi, DM, GM)
    delays = phase_shifts(phi, DM, GM, freqs, P, nu_ref, nu_ref)
    pFT = jnp.fft.rfft(port, axis=-1)
    pFT = pFT * jnp.conj(make_phasor(delays, pFT.shape[-1]))
    port = jnp.fft.irfft(pFT, n=nbin, axis=-1)

    if scint_nsin:
        k_s, key = jax.random.split(jax.random.PRNGKey(0) if key is None else key)
        x = jnp.linspace(0.0, scint_nsin * jnp.pi, nchan)
        pattern = jnp.sin(x + jax.random.uniform(k_s) * 2 * jnp.pi) ** 2.0 + 0.1
        port = port * pattern[:, None]

    if scales is not None:
        port = port * jnp.asarray(scales, dtype)[:, None]

    k_n, k_z = jax.random.split(key if key is not None else jax.random.PRNGKey(0))
    if noise_std:
        port = port + noise_std * jax.random.normal(k_n, port.shape, dtype)

    weights = jnp.ones(nchan, dtype)
    if zap_frac > 0.0:
        weights = jnp.where(
            jax.random.uniform(k_z, (nchan,)) < zap_frac, 0.0, 1.0
        ).astype(dtype)
        port = port * weights[:, None]

    return DataBunch(
        port=port,
        model_port=clean,
        freqs=freqs,
        weights=weights,
        noise_stds=jnp.full((nchan,), noise_std, dtype),
        P=P,
        nbin=nbin,
        nu_ref=nu_ref,
        phi=phi,
        DM=DM,
        GM=GM,
        tau=tau,
        alpha=alpha,
        scales=scales,
    )


def fake_timing_campaign(par, truth=None, n_epochs=10, toas_per_epoch=2,
                         span_days=90.0, toa_err_us=0.1, dm_err=2e-4,
                         dmx=0.0, start_mjd=None, rng=None, site="@",
                         glitch=None, dm_step=None):
    """Synthesize a phase-connected wideband TOA campaign directly
    from a parfile — no archives, no portrait fits (ISSUE 11).

    The timing subsystem (timing/gls.py, timing/fleet.py) consumes
    ``.tim``-level TimTOA lists; generating them through the full
    archive -> GetTOAs pipeline costs seconds per pulsar, which makes
    fleet-scale fixtures (benchmarks/bench_gls.py: dozens of pulsars)
    impractical.  This helper realizes the TRUTH ephemeris exactly at
    the TOA level: for each epoch it picks an integer pulse number in
    exact rational arithmetic (utils/spin.py — frac(F0*dt) is ~1e9
    turns, beyond f64), places the barycentric arrival at that pulse,
    adds the orbital Roemer delay of the truth binary model by
    fixed-point iteration (two steps; the map contracts by 2*pi*A1/PB
    per step, so the self-consistency error is far below any TOA
    noise), and jitters by white noise of ``toa_err_us``.

    par:   the NOMINAL parfile mapping (what the caller will fit
           with).  truth: overrides merged over par to form the truth
           ephemeris (e.g. {'PB': pb + 1e-6}) — the fitted
           corrections should recover truth - par.
    dmx:   per-epoch DM offsets [pc cm^-3]: an array (len n_epochs),
           or a scalar std for random draws (0 = none).
    toa frequencies are infinite (the .tim 0.0-MHz convention): the
    dispersion delay is zero and the DMX columns are constrained
    through the DM rows alone, which keeps the fixture orthogonal to
    the dispersion machinery other tests cover.

    Returns (toas, truth_bunch) with truth_bunch carrying the truth
    par, the per-epoch DMX draws, and the injected correction dict
    {name: truth - nominal} for every spin/binary fit parameter.

    Anomaly injection (ISSUE 18 — ground truth for ingest/alerts.py):

    glitch:  {'epoch': k[, 'dphi': turns][, 'df0': Hz]} — from epoch
             k onward every arrival picks up the ACHROMATIC time step
             of a pulsar glitch: -dphi/F0 seconds (the phase jump)
             plus -df0*(t - t_glitch)/F0 (the frequency step's growing
             ramp).  Sign convention: a spun-UP pulsar (positive
             dphi/df0) arrives EARLY.
    dm_step: {'epoch': k, 'ddm': pc cm^-3} — the per-epoch DM offsets
             gain a persistent step of ddm from epoch k onward (the
             nu^-2 chromatic signature; at these infinite-frequency
             TOAs it rides the wideband DM measurements directly).

    Both events are recorded in the truth bunch as ``glitch`` /
    ``dm_step`` dicts with their epoch index and epoch MJD, so
    detection tests can score localization against ground truth.
    """
    from fractions import Fraction

    from ..timing.binary import binary_delay_np, parse_binary
    from ..timing.tim import TimTOA
    from ..utils.spin import rational, spin_F0

    rng = np.random.default_rng(rng)
    par = dict(par)
    tpar = {**par, **(truth or {})}
    F0r = spin_F0(tpar)
    pep = rational(tpar["PEPOCH"])
    DM0 = float(str(tpar.get("DM", 0.0)).replace("D", "E"))
    bp = parse_binary(tpar)
    if start_mjd is None:
        start_mjd = float(pep)
    dmx_arr = (np.asarray(dmx, float) if np.ndim(dmx) else
               (float(dmx) * rng.standard_normal(n_epochs)
                if dmx else np.zeros(n_epochs)))
    if dmx_arr.shape != (n_epochs,):
        raise ValueError(
            f"fake_timing_campaign: dmx must be scalar or length "
            f"{n_epochs}, got shape {dmx_arr.shape}")

    step = span_days / max(n_epochs - 1, 1)

    def _event(spec, name, keys):
        if spec is None:
            return None
        spec = dict(spec)
        bad = set(spec) - ({"epoch"} | set(keys))
        if bad or "epoch" not in spec:
            raise ValueError(
                f"fake_timing_campaign: {name} must be a dict with "
                f"'epoch' and any of {sorted(keys)}, got {spec!r}")
        ep = int(spec["epoch"])
        if not 0 <= ep < n_epochs:
            raise ValueError(
                f"fake_timing_campaign: {name} epoch {ep} outside "
                f"[0, {n_epochs})")
        spec["epoch"] = ep
        spec["mjd"] = start_mjd + ep * step
        return spec

    glitch = _event(glitch, "glitch", ("dphi", "df0"))
    dm_step = _event(dm_step, "dm_step", ("ddm",))
    if dm_step is not None:
        dmx_arr = dmx_arr.copy()
        dmx_arr[dm_step["epoch"]:] += float(dm_step["ddm"])

    F0 = float(F0r)
    toas = []
    for k in range(n_epochs):
        for j in range(toas_per_epoch):
            # target epoch; intra-epoch TOAs sit minutes apart so the
            # GLS 0.5-day gap grouping keeps them in one DMX epoch
            e = start_mjd + k * step + j * (180.0 / 86400.0)
            dt_s = (rational(e) - pep) * 86400
            N = round(F0r * dt_s)  # exact integer pulse number
            t_bary = pep + Fraction(N) / (F0r * 86400)  # days, exact
            day = int(t_bary // 1)
            frac = float(t_bary - day)
            delay = 0.0
            if bp is not None:
                # t_obs = t_bary + Delta(t_obs): two fixed-point steps
                delay = float(binary_delay_np(bp, day, frac))
                d1 = t_bary + Fraction(delay) / 86400
                delay = float(binary_delay_np(
                    bp, int(d1 // 1), float(d1 - int(d1 // 1))))
            noise_s = float(toa_err_us) * 1e-6 * rng.standard_normal()
            event_s = 0.0
            if glitch is not None and k >= glitch["epoch"]:
                dt_g = (e - glitch["mjd"]) * 86400.0
                event_s = -(float(glitch.get("dphi", 0.0))
                            + float(glitch.get("df0", 0.0)) * dt_g) / F0
            t_obs = t_bary + Fraction(delay + noise_s + event_s) / 86400
            day = int(t_obs // 1)
            frac = float(t_obs - day)
            toas.append(TimTOA(
                archive=f"synth_{k:03d}_{j}", frequency=np.inf,
                mjd_int=day, mjd_frac=frac,
                error_us=float(toa_err_us), site=site,
                dm=DM0 + dmx_arr[k] + dm_err * rng.standard_normal(),
                dm_err=float(dm_err)))

    # the correction dict a fit against the NOMINAL par should recover
    def _f(m, k, d=0.0):
        v = m.get(k)
        return float(str(v).replace("D", "E")) if v is not None else d

    injected = {}
    if _f(tpar, "F0") and _f(par, "F0"):
        injected["F0"] = _f(tpar, "F0") - _f(par, "F0")
    for key in ("PB", "A1", "TASC", "T0", "EPS1", "EPS2", "ECC", "OM"):
        if par.get(key) is not None or tpar.get(key) is not None:
            injected[key] = _f(tpar, key) - _f(par, key)
    return toas, DataBunch(par=tpar, nominal=par, dmx=dmx_arr,
                           injected=injected, binary=bp,
                           glitch=glitch, dm_step=dm_step)


def fake_observation(
    key,
    model,
    nsub=1,
    nchan=64,
    nbin=1024,
    P=0.002,
    lofreq=1200.0,
    bw=800.0,
    dDM_std=0.0,
    **kwargs,
):
    """A stack of subint portraits (nsub, nchan, nbin) with per-subint
    random dDMs drawn from N(0, dDM_std) — the shape pptoas consumes.

    Returns (DataBunch with subints stacked, injected dDMs array).
    """
    chan_bw = bw / nchan
    freqs = lofreq + chan_bw * (jnp.arange(nchan) + 0.5)
    keys = jax.random.split(key, nsub + 1)
    dDMs = dDM_std * np.asarray(
        jax.random.normal(keys[0], (nsub,), jnp.float64)
    )
    subs, truths = [], []
    base_DM = kwargs.pop("DM", 0.0)
    for isub in range(nsub):
        b = fake_portrait(
            keys[isub + 1], model, freqs, nbin, P,
            DM=base_DM + float(dDMs[isub]), **kwargs,
        )
        subs.append(b.port)
        truths.append(b)
    first = truths[0]
    return (
        DataBunch(
            subints=jnp.stack(subs),
            model_port=first.model_port,
            freqs=freqs,
            weights=jnp.stack([t.weights for t in truths]),
            noise_stds=jnp.stack([t.noise_stds for t in truths]),
            P=P,
            nbin=nbin,
            nu_ref=first.nu_ref,
            DMs=base_DM + dDMs,
        ),
        dDMs,
    )
