"""Reusable stage-attribution profiler for device programs.

Promotes the one-off methodology of ``benchmarks/exp_breakdown.py``
(round 5, which first attributed 100% of the windowed fast fit's slope)
into a library API, so any lane can answer "where does the batch
latency go?" with numbers that are honest under a tunneled, shared
accelerator:

- **Slope timing** (`devtime`): ``block_until_ready`` can return early
  on tunneled runtimes and host transfers are slow, so every
  measurement enqueues K dispatches back-to-back, reduces each result
  to a scalar ON DEVICE, and syncs once — the slope between the K-rep
  and 1-rep walls is steady-state device time.  Min over ``nrun``
  separate measurements: a shared chip's effective throughput swings up
  to ~8x with external load, and min-of-N is the standard unloaded-cost
  estimator.
- **Prefix stages**: cumulative slices of the real program, each
  measured independently; a stage's cost is the difference between its
  prefix slope and the previous one.  Timing prefixes of the *actual*
  program (not isolated re-creations) keeps fusion behavior honest —
  XLA schedules an isolated piece differently than the same piece
  embedded in the full program.
- **Piece stages**: everything after the last prefix, measured on
  precomputed inputs (e.g. the Newton loop on a prepared
  cross-spectrum).
- **The attribution check**: ``attributed = slope(last prefix) +
  sum(pieces)`` compared against the full program's slope.  The sum is
  built ONLY from independently measured programs — never from
  differences that include the full slope, which would telescope to
  1.0 by construction (the exp_breakdown lesson).  A lane is "fully
  attributed" when ``attributed_frac`` clears a stated tolerance
  (benchmarks gate on >= 0.9).

Typical use (see ``benchmarks/attrib.py`` for the two production
lanes)::

    stages = [
        Stage("dft",  dft_prefix_fn,  kind="prefix"),
        Stage("prep", prep_prefix_fn, kind="prefix"),
        Stage("newton", loop_on_precomputed_fn, kind="piece"),
    ]
    att = profile_stages(full_fn, stages, pick=lambda r: r.phi)
    print(att.breakdown_ms())      # {"stage_dft_ms": ..., ...}
    assert att.attributed_frac >= 0.9
"""

import time
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["devtime", "Stage", "StageTiming", "Attribution",
           "profile_stages"]


@jax.jit
def _scl(x):
    return jnp.sum(x)


def _identity(x):
    return x


def devtime(fn, pick=_identity, K=4, warm=1, nrun=3):
    """Slope-time ``fn`` (returns a pytree; ``pick`` selects an array
    to reduce device-side).  Returns ``(slope_s, single_s)``.

    The K dispatches are enqueued back-to-back with a device-side
    scalar reduction and ONE host sync; both the single synchronized
    rep and the K-rep run take the MIN over ``nrun`` measurements (see
    module docstring for why).  When the populations disagree under
    load and the slope goes non-positive, the conservative fallback
    ``tK / K`` counts one round-trip against the K batches."""
    for _ in range(warm):
        _ = np.asarray(_scl(pick(fn())))

    def single():
        t0 = time.perf_counter()
        _ = np.asarray(_scl(pick(fn())))
        return time.perf_counter() - t0

    def krun():
        t0 = time.perf_counter()
        for _ in range(K):
            s = _scl(pick(fn()))
        _ = np.asarray(s)
        return time.perf_counter() - t0

    t1 = min(single() for _ in range(nrun))
    tK = min(krun() for _ in range(nrun))
    slope = (tK - t1) / (K - 1)
    if slope <= 0:
        slope = tK / K
    return slope, t1


class Stage(NamedTuple):
    """One named, independently dispatchable slice of a program.

    kind 'prefix': a cumulative slice of the real program (each prefix
    contains all previous ones); its attributed cost is the difference
    from the previous prefix's slope.  kind 'piece': an isolated
    remainder on precomputed inputs (costs add directly).  ``fn`` takes
    no arguments (close over the inputs); ``pick`` selects the array to
    scalar-reduce on device (default: the result itself)."""

    name: str
    fn: Callable
    kind: str = "prefix"
    pick: Callable = _identity


class StageTiming(NamedTuple):
    name: str
    kind: str
    slope_s: float   # the stage program's own slope
    cost_s: float    # attributed cost (differenced for prefixes)


class Attribution(NamedTuple):
    """profile_stages result: the full program's slope, per-stage
    costs, and the independent-sum attribution check."""

    total_s: float
    single_s: float
    stages: tuple          # of StageTiming
    attributed_s: float    # last prefix slope + sum of piece slopes
    attributed_frac: float

    def check(self, min_frac=0.9):
        """True when the independently-measured stages cover at least
        ``min_frac`` of the full slope."""
        return self.attributed_frac >= min_frac

    def cost(self, name):
        for s in self.stages:
            if s.name == name:
                return s.cost_s
        raise KeyError(name)

    def breakdown_ms(self, ndigits=2):
        """JSON-ready flat dict: per-stage attributed cost in ms plus
        the totals and the attribution fraction — the per-stage fields
        the benchmark JSON lines carry."""
        out = {}
        for s in self.stages:
            out[f"stage_{s.name}_ms"] = round(s.cost_s * 1e3, ndigits)
        out["full_ms"] = round(self.total_s * 1e3, ndigits)
        out["attributed_frac"] = round(self.attributed_frac, 3)
        return out


def profile_stages(full_fn, stages, pick=_identity, K=4, warm=1,
                   nrun=3, devtime_fn: Optional[Callable] = None):
    """Measure ``full_fn`` and each ``Stage``; return an Attribution.

    ``stages``: prefixes in cumulative order, then pieces (order of
    pieces is free).  ``pick`` applies to full_fn's result.
    ``devtime_fn`` overrides the timer (tests stub it to avoid real
    dispatch timing)."""
    dt = devtime_fn or devtime
    total_s, single_s = dt(full_fn, pick, K=K, warm=warm, nrun=nrun)

    timings = []
    prev_prefix = 0.0
    last_prefix = 0.0
    piece_sum = 0.0
    seen_piece = False
    for st in stages:
        if st.kind not in ("prefix", "piece"):
            raise ValueError(f"unknown stage kind {st.kind!r}")
        slope_s, _ = dt(st.fn, st.pick, K=K, warm=warm, nrun=nrun)
        if st.kind == "prefix":
            if seen_piece:
                raise ValueError(
                    "prefix stages must precede piece stages "
                    f"(got prefix {st.name!r} after a piece)")
            cost = max(slope_s - prev_prefix, 0.0)
            prev_prefix = slope_s
            last_prefix = slope_s
        else:
            seen_piece = True
            cost = slope_s
            piece_sum += slope_s
        timings.append(StageTiming(st.name, st.kind, slope_s, cost))

    attributed = last_prefix + piece_sum
    frac = attributed / total_s if total_s > 0 else float("nan")
    return Attribution(total_s, single_s, tuple(timings), attributed,
                       frac)
