"""Close the timing loop: synthetic archives with injected per-epoch
dDMs -> wideband TOAs -> .tim -> in-repo NumPy wideband GLS -> white
residuals and recovered DMX.

This is the reference notebook's final tempo GLS validation
(examples/example_make_model_and_TOAs.ipynb cells 43-56, DMDATA 1)
without the tempo binary."""

import numpy as np
import pytest

from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.io.psrfits import parse_parfile
from pulseportraiture_tpu.io.tim import write_TOAs
from pulseportraiture_tpu.pipeline import GetTOAs
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.timing import read_tim, wideband_gls_fit
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55150.0, "DM": 3.139}
DDMS = [3e-4, -2e-4, 5e-4, -4e-4]
PHASES = [0.017, 0.017, 0.017, 0.017]  # common achromatic offset


@pytest.fixture(scope="module")
def tim_path(tmp_path_factory):
    root = tmp_path_factory.mktemp("timing")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i, dDM in enumerate(DDMS):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=3, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=120.0,
                         phase=PHASES[i], dDM=dDM,
                         start_MJD=MJD(55100 + 30 * i, 0.2),
                         noise_stds=0.05, dedispersed=False, quiet=True,
                         rng=500 + i, spin_coherent=True)
        files.append(path)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    out = str(root / "epochs.tim")
    write_TOAs(gt.TOA_list, outfile=out)
    return out


def test_read_tim_roundtrip(tim_path):
    toas = read_tim(tim_path)
    assert len(toas) == 4 * 3
    t = toas[0]
    assert t.dm is not None and t.dm_err > 0
    assert 55099 < t.mjd < 55200
    assert t.error_us < 10
    assert np.isfinite(t.frequency)
    # digit-exact MJD split
    assert 0.0 <= t.mjd_frac < 1.0


def test_wideband_gls_whitens_and_recovers_dmx(tim_path):
    toas = read_tim(tim_path)
    par = parse_parfile([f"{k} {v}" for k, v in PAR.items()])
    res = wideband_gls_fit(toas, par, fit_f0=True)
    # four observing epochs found
    assert len(res.dmx) == 4
    # post-fit arrival-time residuals are white at the TOA errors:
    # reduced chi^2 near 1 and per-TOA residuals within ~4 sigma
    assert 0.3 < res.red_chi2 < 3.0, res.red_chi2
    assert np.all(np.abs(res.time_resids_us)
                  < 5.0 * res.toa_errs_us), res.time_resids_us
    # the fit actually improved things (prefit carries the dDM signal)
    assert res.wrms_us < np.sqrt(np.mean(res.prefit_resids_us ** 2.0))
    # recovered per-epoch DMX match the injected dDMs
    for j, dDM in enumerate(DDMS):
        assert res.dmx[j] == pytest.approx(
            dDM, abs=max(4.0 * res.dmx_errs[j], 3e-5)), (j, dDM)
    # DM residuals consistent with their errors
    assert np.all(np.abs(res.dm_resids) < 5.0 * res.dm_errs)


def test_gls_detects_injected_spin_offset(tim_path):
    """A deliberate F0 perturbation in the par must be absorbed by the
    fitted dF0 and still produce white residuals."""
    toas = read_tim(tim_path)
    par = dict(PAR)
    f0 = 1.0 / PAR["P0"]
    par.pop("P0")
    par["F0"] = f0 * (1.0 + 3e-12)  # ~ 0.7 ns/day drift
    res = wideband_gls_fit(toas, par, fit_f0=True)
    # 1% recovery: the formal error (~0.06%) undershoots because the
    # F0/DMX/offset covariance leaves a few-ns systematic floor from
    # the TOA measurement itself; the injected drift is recovered to
    # 0.3% in practice
    assert res.params["F0"] == pytest.approx(-f0 * 3e-12, rel=0.01)


def test_gls_rejects_malformed_parfile(tim_path):
    from pulseportraiture_tpu.timing import read_tim, wideband_gls_fit

    toas = read_tim(tim_path)
    with pytest.raises(ValueError, match="PEPOCH"):
        wideband_gls_fit(toas, {"F0": 333.0, "DM": 10.0})
    with pytest.raises(ValueError, match="F0"):
        wideband_gls_fit(toas, {"PEPOCH": 55000.0, "DM": 10.0})


def test_gls_refuses_unmodeled_binary_parfile(tim_path):
    """Since ISSUE 11, complete ELL1/BT Keplerian parfiles are MODELED
    (tests/test_timing_binary.py covers the fit); the loud refusal now
    guards what is still unimplemented: Shapiro/relativistic keys and
    partial element sets — the likeliest hand-edited failure modes,
    which silently ignoring would time against an orbit-smeared phase
    prediction with no visible symptom.  Exercised through
    parse_parfile so real .par spellings are what is rejected."""
    toas = read_tim(tim_path)
    shapiro_par = parse_parfile([
        "PSR      J1012+5307",
        "RAJ      10:12:33.4",
        "DECJ     53:07:02.5",
        "F0       190.2678376220576",
        "PEPOCH   55150.0",
        "DM       9.0233",
        "BINARY   ELL1",
        "PB       0.60467271355",
        "A1       0.5818172",
        "TASC     50700.08162891",
        "EPS1     0.00000012",
        "EPS2     -0.00000007",
        "SINI     0.978",
        "M2       0.21",
    ])
    with pytest.raises(ValueError, match="binary-orbit"):
        wideband_gls_fit(toas, shapiro_par)
    # the message names the offending keys so the user knows what to
    # strip (or that they need tempo2/PINT)
    with pytest.raises(ValueError, match="M2.*SINI"):
        wideband_gls_fit(toas, shapiro_par)
    # a single orbital key is enough — partial binary parfiles are the
    # likeliest hand-edited failure mode
    par = dict(PAR)
    par["PB"] = 67.8
    with pytest.raises(ValueError, match="PB"):
        wideband_gls_fit(toas, par)
    # the isolated-pulsar parfile still fits
    res = wideband_gls_fit(toas, PAR)
    assert np.isfinite(res.chi2)


def test_gls_reports_dropped_no_dm_toas(tim_path):
    """TOAs lacking -pp_dm cannot enter the DMDATA system: they are
    dropped with a warning and counted, never silently (VERDICT r3
    weak #6)."""
    from dataclasses import replace

    toas = read_tim(tim_path)
    broken = [replace(t, dm=None, dm_err=None) if i % 3 == 0 else t
              for i, t in enumerate(toas)]
    n_broken = sum(1 for t in broken if t.dm is None)
    with pytest.warns(UserWarning, match="dropped"):
        res = wideband_gls_fit(broken, PAR)
    assert res.n_dropped_no_dm == n_broken
    # the untouched fit reports zero drops and no warning
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        res2 = wideband_gls_fit(toas, PAR)
    assert res2.n_dropped_no_dm == 0


def test_gls_rejects_lost_phase_connection(tim_path):
    """An F0 error big enough to drift > 0.5 turns between adjacent
    epochs must raise (the nearest-turn wrap would silently time a
    wrapped alias), and allow_wraps=True overrides."""
    toas = read_tim(tim_path)
    bad = dict(PAR)
    # dF0 ~ 2.5e-7 Hz drifts ~0.65 turns between the ~30-day epochs:
    # the wrapped residuals occupy ~0.65 turns of the circle (note
    # some LARGER dF0 values alias back to a clustered pattern — wraps
    # are then fundamentally undetectable from wrapped residuals, so
    # the guard makes no claim about them)
    bad["P0"] = PAR["P0"] * (1.0 + 2.5e-7 * PAR["P0"])
    with pytest.raises(ValueError, match="phase connection"):
        wideband_gls_fit(toas, bad)
    res = wideband_gls_fit(toas, bad, allow_wraps=True)
    assert np.isfinite(res.chi2)
    # the good ephemeris passes the check as before
    res2 = wideband_gls_fit(toas, PAR)
    assert res2.wrms_us < 1.0


def test_gls_boundary_offset_is_not_a_wrap(tim_path):
    """A perfectly-connected campaign whose constant phase offset sits
    at the +-0.5-turn wrap boundary (wrapped values alternate +0.4999
    / -0.4999) must NOT be rejected: the occupied-arc criterion is
    rotation-invariant on the circle."""
    from dataclasses import replace

    toas = read_tim(tim_path)
    P = PAR["P0"]
    shifted = [replace(t, mjd_frac=(t.mjd_frac + 0.5 * P / 86400.0)
                       % 1.0) for t in toas]
    res = wideband_gls_fit(shifted, PAR)
    # the half-turn offset is absorbed by OFFSET; residuals stay white
    assert res.wrms_us < 1.0
