"""Content-addressed result cache (ISSUE 17): hits must be
byte-identical to fresh fits on both data lanes, content (not path)
addressing must miss on any input perturbation, the on-disk LRU must
evict oldest-first and treat torn entries as misses, per-tenant
accounting must see hits without billing them as fits, and the new
config knobs must parse strictly."""

import io
import os
import shutil

import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.serve import (InProcTransport, ResultCache,
                                        ToaClient, ToaRouter, ToaServer,
                                        content_key,
                                        resolve_result_cache)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    root = tmp_path_factory.mktemp("cache")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16,
                         nbin=128, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55100 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=100 + i)
        files.append(path)
    return files, gmodel


def test_cache_hit_byte_identical_both_lanes_and_torn(campaign,
                                                      tmp_path):
    """The acceptance core, on the raw AND decoded lanes: the first
    (cache-off-equivalent) fit through a cache-on server writes the
    SAME bytes as the one-shot driver (off-vs-on identity on a clean
    corpus), the repeat is served from cache byte-identically, and a
    truncated store entry is a MISS that refits — never a crash."""
    files, gmodel = campaign
    cdir = str(tmp_path / "store")
    trace = str(tmp_path / "trace.jsonl")
    srv = ToaServer(nsub_batch=8, quiet=True, telemetry=trace,
                    result_cache=True, cache_dir=cdir).start()
    client = ToaClient(srv)
    for tscrunch, tag in ((False, "raw"), (True, "dec")):
        ref = str(tmp_path / f"{tag}_ref.tim")
        stream_wideband_TOAs(files, gmodel, nsub_batch=8, tim_out=ref,
                             tscrunch=tscrunch, quiet=True)
        t1 = str(tmp_path / f"{tag}_1.tim")
        hits0 = srv.stats()["cache_hits"]
        r1 = client.get_TOAs(files, gmodel, tim_out=t1, timeout=300,
                             name=f"{tag}1", tscrunch=tscrunch)
        assert open(t1, "rb").read() == open(ref, "rb").read()
        assert srv.stats()["cache_hits"] == hits0  # a fit, not a hit

        t2 = str(tmp_path / f"{tag}_2.tim")
        r2 = client.get_TOAs(files, gmodel, tim_out=t2, timeout=300,
                             name=f"{tag}2", tscrunch=tscrunch)
        assert srv.stats()["cache_hits"] == hits0 + 1
        assert open(t2, "rb").read() == open(ref, "rb").read()
        assert len(r2.TOA_list) == len(r1.TOA_list)
        # the recovered in-memory result re-parses the decimal .tim
        # text (the recovered_from_tim contract) — the BYTES above are
        # the exactness gate, the objects agree to text precision
        for ta, tb in zip(r1.TOA_list, r2.TOA_list):
            assert ta.MJD.day == tb.MJD.day
            assert ta.MJD.frac == pytest.approx(tb.MJD.frac,
                                                abs=1e-12)
            assert ta.DM == pytest.approx(tb.DM, rel=1e-6)

    # torn entry: truncate every stored .tim mid-payload — the next
    # lookup must MISS (and refit to the same bytes), not crash
    entries = [fn for fn in os.listdir(cdir) if fn.endswith(".tim")]
    assert entries, "cache-on server stored nothing"
    for fn in entries:
        p = os.path.join(cdir, fn)
        data = open(p, "rb").read()
        with open(p, "wb") as fh:
            fh.write(data[:max(1, len(data) // 2)])
    hits0 = srv.stats()["cache_hits"]
    misses0 = srv.cache.misses
    t3 = str(tmp_path / "torn.tim")
    client.get_TOAs(files, gmodel, tim_out=t3, timeout=300,
                    name="torn")
    assert srv.stats()["cache_hits"] == hits0
    assert srv.cache.misses == misses0 + 1
    assert (open(t3, "rb").read()
            == open(str(tmp_path / "raw_ref.tim"), "rb").read())
    srv.stop()

    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_cache_hit"] == 2
    assert summary["n_cache_store"] >= 2
    assert summary["cache_bytes_served"] > 0


def test_cache_content_not_path_addressed(campaign, tmp_path):
    """A one-byte archive perturbation MUST miss, and identical bytes
    under a DIFFERENT path must miss too (the .tim payload embeds
    absolute datafile paths — aliasing would serve wrong sentinels)."""
    files, gmodel = campaign
    srv = ToaServer(nsub_batch=8, quiet=True, result_cache=True,
                    cache_dir=str(tmp_path / "store")).start()
    client = ToaClient(srv)
    client.get_TOAs([files[0]], gmodel, timeout=300, name="seed")
    assert srv.stats()["cache_hits"] == 0

    pert = str(tmp_path / "perturbed.fits")
    shutil.copyfile(files[0], pert)
    with open(pert, "r+b") as fh:
        fh.seek(os.path.getsize(pert) - 64)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0x01]))
    client.get_TOAs([pert], gmodel, timeout=300, name="pert")
    assert srv.stats()["cache_hits"] == 0  # perturbation missed

    alias = str(tmp_path / "alias.fits")
    shutil.copyfile(files[0], alias)
    client.get_TOAs([alias], gmodel, timeout=300, name="alias")
    assert srv.stats()["cache_hits"] == 0  # same bytes, new path: miss

    client.get_TOAs([files[0]], gmodel, timeout=300, name="rehit")
    assert srv.stats()["cache_hits"] == 1  # the original still hits
    srv.stop()


def test_router_hit_short_circuits_placement(campaign, tmp_path):
    """A router-side hit never touches a host: per-host request counts
    stay frozen, the handle arrives pre-settled (no attempts — nothing
    for failover/hedge to re-place), and the trace shows the hit."""
    files, gmodel = campaign
    trace = str(tmp_path / "route.jsonl")
    srv = ToaServer(nsub_batch=8, quiet=True).start()
    router = ToaRouter([InProcTransport(srv, label="h0")],
                       telemetry=trace, result_cache=True,
                       cache_dir=str(tmp_path / "store"))
    t1 = str(tmp_path / "r1.tim")
    router.submit(files, gmodel, tim_out=t1, name="r1").result(300)
    placed0 = {lbl: st["n_requests"]
               for lbl, st in router.stats().items()}
    t2 = str(tmp_path / "r2.tim")
    rh = router.submit(files, gmodel, tim_out=t2, name="r2")
    res = rh.result(300)
    assert rh.attempts == []  # settled on arrival, never placed
    assert router.cache_hits == 1
    assert {lbl: st["n_requests"]
            for lbl, st in router.stats().items()} == placed0
    assert open(t2, "rb").read() == open(t1, "rb").read()
    assert len(res.TOA_list) == 4
    router.close()
    srv.stop()

    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_cache_hit"] == 1
    assert summary["n_cache_miss"] == 1
    assert summary["n_route_done"] == 2


def test_tenant_sees_hits_without_billing(campaign, tmp_path):
    """Per-tenant split: a hit lands on the tenant's hit ledger
    (visible in tenant_snapshot) but is never billed against the
    quota or the weighted-fair vtime — only real fits queue."""
    files, gmodel = campaign
    old = config.serve_tenant_quota
    try:
        # quota of ONE archive: a billed repeat would be rejected,
        # a cache hit sails through without touching the ledger
        config.serve_tenant_quota = {"bulk": 1, "*": 8}
        srv = ToaServer(nsub_batch=8, quiet=True, result_cache=True,
                        cache_dir=str(tmp_path / "store")).start()
        client = ToaClient(srv)
        client.get_TOAs([files[0]], gmodel, timeout=300, name="f1",
                        tenant="bulk")
        for i in range(3):  # repeats: all hits, quota never consulted
            client.get_TOAs([files[0]], gmodel, timeout=300,
                            name=f"h{i}", tenant="bulk")
        snap = srv.queue.tenant_snapshot()
        assert snap["bulk"]["cache_hits"] == 3
        assert snap["bulk"]["pending_archives"] == 0
        assert srv.stats()["cache_hits"] == 3
        srv.stop()
    finally:
        config.serve_tenant_quota = old


def test_lru_eviction_order_and_torn_blob(tmp_path):
    """Direct store semantics: least-recently-USED evicts first (a hit
    refreshes recency), an entry larger than the whole bound is
    refused, and a torn blob (bad length header) is a deleted miss."""
    rc = ResultCache(str(tmp_path / "s"), max_mb=0.003)  # 3000 bytes
    for k in ("k1", "k2", "k3"):
        assert rc.put_blob(k, bytes(900)) is not None
    assert rc.evictions == 0
    assert rc.get_blob("k1") is not None  # refresh k1's recency
    assert rc.put_blob("k4", bytes(900)) is not None  # -> evict k2
    assert rc.evictions == 1
    assert rc.get_blob("k2") is None  # the LRU victim
    assert rc.get_blob("k1") is not None  # survived via the refresh
    assert rc.get_blob("k3") is not None

    assert rc.put_blob("big", bytes(5000)) is None  # can never fit
    assert rc.get_blob("big") is None
    # the refused oversize entry must NOT have flushed the store
    assert rc.get_blob("k1") is not None
    assert rc.get_blob("k3") is not None

    # torn blob: corrupt the stored length header
    path = os.path.join(rc.dir, "k3.blob")
    data = open(path, "rb").read()
    open(path, "wb").write(data[:-10])
    misses0 = rc.misses
    assert rc.get_blob("k3") is None
    assert rc.misses == misses0 + 1
    assert not os.path.exists(path)  # dropped, cannot mislead again

    # a reopened store inherits the entries (mtime-seeded LRU)
    rc2 = ResultCache(str(tmp_path / "s"), max_mb=0.003)
    assert rc2.get_blob("k1") is not None


def test_content_key_sensitivity(campaign):
    """The key moves with file bytes, file path, options, and the
    byte-relevant config knobs — and with nothing else."""
    files, gmodel = campaign
    k0 = content_key([files[0], gmodel], {"fit_scat": False})
    assert k0 == content_key([files[0], gmodel], {"fit_scat": False})
    assert k0 != content_key([files[1], gmodel], {"fit_scat": False})
    assert k0 != content_key([files[0], gmodel], {"fit_scat": True})
    old = config.dft_precision
    try:
        # flip AWAY from whatever the harness set it to
        config.dft_precision = ("default" if old == "highest"
                                else "highest")
        assert k0 != content_key([files[0], gmodel],
                                 {"fit_scat": False})
    finally:
        config.dft_precision = old
    with pytest.raises(OSError):
        content_key(["/nonexistent/archive.fits"], {})


def test_resolve_tri_state(tmp_path):
    """off -> None; auto -> None WITHOUT a dir (the shipped default:
    off out of the box) and a live cache WITH one; on -> loud
    ValueError without a dir; junk mode -> loud ValueError."""
    assert resolve_result_cache(mode=False) is None
    assert resolve_result_cache(mode="off") is None
    old = (config.result_cache, config.cache_dir)
    try:
        config.result_cache, config.cache_dir = "auto", None
        assert resolve_result_cache() is None  # the shipped default
    finally:
        config.result_cache, config.cache_dir = old
    assert resolve_result_cache(mode="auto", cache_dir=None) is None
    rc = resolve_result_cache(mode="auto",
                              cache_dir=str(tmp_path / "a"))
    assert isinstance(rc, ResultCache)
    rc = resolve_result_cache(mode=True,
                              cache_dir=str(tmp_path / "b"))
    assert isinstance(rc, ResultCache)
    with pytest.raises(ValueError, match="cache_dir"):
        resolve_result_cache(mode=True, cache_dir=None)
    with pytest.raises(ValueError, match="result_cache"):
        resolve_result_cache(mode="sometimes")


def test_cache_env_hooks(monkeypatch):
    """PPT_RESULT_CACHE / PPT_CACHE_DIR / PPT_CACHE_MAX_MB: registered
    in KNOWN_PPT_ENV, strict parses, loud errors, did-you-mean."""
    old = (config.result_cache, config.cache_dir, config.cache_max_mb)
    try:
        for name in ("PPT_RESULT_CACHE", "PPT_CACHE_DIR",
                     "PPT_CACHE_MAX_MB"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_RESULT_CACHE", "on")
        monkeypatch.setenv("PPT_CACHE_DIR", "/tmp/ppt_rc")
        monkeypatch.setenv("PPT_CACHE_MAX_MB", "128")
        changed = config.env_overrides()
        for key in ("result_cache", "cache_dir", "cache_max_mb"):
            assert key in changed
        assert config.result_cache is True
        assert config.cache_dir == "/tmp/ppt_rc"
        assert config.cache_max_mb == 128.0
        monkeypatch.setenv("PPT_RESULT_CACHE", "auto")
        monkeypatch.setenv("PPT_CACHE_DIR", "off")
        config.env_overrides()
        assert config.result_cache == "auto"
        assert config.cache_dir is None
        monkeypatch.setenv("PPT_RESULT_CACHE", "off")
        config.env_overrides()
        assert config.result_cache is False
        for name, bad in (("PPT_RESULT_CACHE", "sometimes"),
                          ("PPT_CACHE_MAX_MB", "0"),
                          ("PPT_CACHE_MAX_MB", "-3"),
                          ("PPT_CACHE_MAX_MB", "big")):
            monkeypatch.setenv(name, bad)
            with pytest.raises(ValueError, match=name):
                config.env_overrides()
            monkeypatch.delenv(name)
        # did-you-mean on a typo'd knob
        import contextlib

        import pulseportraiture_tpu.config as cfgmod

        cfgmod._warned_unknown_ppt.discard("PPT_RESULT_CACH")
        monkeypatch.setenv("PPT_RESULT_CACH", "on")
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            config.env_overrides()
        assert "PPT_RESULT_CACHE" in err.getvalue()
    finally:
        (config.result_cache, config.cache_dir,
         config.cache_max_mb) = old


def test_cli_cache_flags_strict(tmp_path):
    """apply_cache_flags (shared by ppserve/pproute/ppfactory) dies
    loudly on junk and on 'on' without a dir — before any IO."""
    from pulseportraiture_tpu.cli.ppserve import (apply_cache_flags,
                                                  build_parser)
    old = (config.result_cache, config.cache_dir, config.cache_max_mb)
    try:
        p = build_parser()
        args = p.parse_args(["-r", "x.jsonl", "--result-cache",
                             "banana"])
        with pytest.raises(SystemExit, match="result-cache"):
            apply_cache_flags(args, "ppserve")
        args = p.parse_args(["-r", "x.jsonl", "--cache-max-mb", "-1"])
        with pytest.raises(SystemExit, match="cache-max-mb"):
            apply_cache_flags(args, "ppserve")
        config.cache_dir = None
        args = p.parse_args(["-r", "x.jsonl", "--result-cache", "on"])
        with pytest.raises(SystemExit, match="cache-dir"):
            apply_cache_flags(args, "ppserve")
        args = p.parse_args(["-r", "x.jsonl", "--result-cache", "on",
                             "--cache-dir", str(tmp_path / "c"),
                             "--cache-max-mb", "64"])
        apply_cache_flags(args, "ppserve")
        assert config.result_cache is True
        assert config.cache_max_mb == 64.0
    finally:
        (config.result_cache, config.cache_dir,
         config.cache_max_mb) = old


def test_ppfactory_artifact_cache(campaign, tmp_path, capsys):
    """Template-factory artifacts ride the same store: a second
    ppfactory run over the same metafile + options serves every
    .gmodel from cache, byte-identical to the built one."""
    files, _ = campaign
    from pulseportraiture_tpu.cli.ppfactory import main as ppfactory
    meta = str(tmp_path / "jobs.meta")
    with open(meta, "w") as fh:
        fh.write(files[0] + "\n")
    outdir = str(tmp_path / "out")
    old = (config.result_cache, config.cache_dir, config.cache_max_mb)
    try:
        argv = ["-M", meta, "-O", outdir, "--max-ngauss", "1",
                "--cache-dir", str(tmp_path / "store"), "--verbose"]
        assert ppfactory(argv) == 0
        out1 = capsys.readouterr().out
        assert "0/1 template(s) served from the result cache" in out1
        built = os.path.join(outdir,
                             os.path.basename(files[0]) + ".gmodel")
        ref = open(built, "rb").read()
        os.unlink(built)
        assert ppfactory(argv) == 0
        out2 = capsys.readouterr().out
        assert "1/1 template(s) served from the result cache" in out2
        assert open(built, "rb").read() == ref
    finally:
        (config.result_cache, config.cache_dir,
         config.cache_max_mb) = old
