"""Fleet observability plane (ISSUE 20): log-bucket histogram
quantiles vs exact, SLO burn-rate arithmetic under an injected clock,
distributed trace-ids stitched end-to-end over the socket lane (router
+ 2 hosts, failover + hedge, every request covered exactly once), the
fleet-wide ``metrics`` op, the ppmon --once --json schema, the
torn-load-snapshot fix, and the PPT_METRICS / PPT_SLO_TARGETS /
PPT_MON_INTERVAL_MS env hooks."""

import io
import json
import threading
import time

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.obs import (HIST_BOUNDS, MetricsRegistry,
                                      SloTracker, merge_exports,
                                      quantile_from_export)
from pulseportraiture_tpu.obs.merge import merge_traces
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.serve import (DEAD, AdmissionQueue,
                                        ServeRequest, SocketTransport,
                                        ToaRouter, ToaServer,
                                        TransportServer)
from pulseportraiture_tpu.serve.transport import KillableTransport
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}

# worst-case quantile relative error of the 8-per-decade log buckets:
# a reported quantile is the geometric midpoint of its bucket, so it
# is off by at most a half-bucket factor of 10**(1/16)
_HALF_BUCKET = 10.0 ** (1.0 / 16.0)


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    """3 tiny archives + the one-shot .tim reference bytes."""
    root = tmp_path_factory.mktemp("obs")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(3):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16,
                         nbin=128, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55100 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=300 + i)
        files.append(path)
    ref = str(root / "ref01.tim")
    stream_wideband_TOAs(files[:2], gmodel, nsub_batch=8, tim_out=ref,
                         quiet=True)
    return files, gmodel, open(ref, "rb").read()


# ---------------------------------------------------------------------------
# histogram quantiles
# ---------------------------------------------------------------------------

def test_histogram_quantiles_within_bucket_error():
    """Quantiles derived from bucket counts track the exact sample
    quantiles within the documented half-bucket factor, with no sample
    retention; bucket-wise merge of split registries is exact."""
    rng = np.random.default_rng(0)
    lat = np.exp(rng.normal(np.log(0.05), 1.0, size=5000))
    reg = MetricsRegistry()
    a, b = MetricsRegistry(), MetricsRegistry()
    for i, v in enumerate(lat):
        reg.observe("lat", float(v))
        (a if i % 2 else b).observe("lat", float(v))
    h = reg.export()["histograms"]["lat"]
    assert h["count"] == lat.size
    assert h["sum"] == pytest.approx(float(lat.sum()), rel=1e-6)
    for q in (0.50, 0.90, 0.99):
        est = quantile_from_export(h, q)
        exact = float(np.quantile(lat, q))
        assert exact / _HALF_BUCKET <= est <= exact * _HALF_BUCKET, \
            (q, est, exact)
        assert reg.quantile("lat", q) == est
    # fleet merge: summing per-host buckets == one histogram over all
    merged = merge_exports([a.export(), b.export()])
    assert merged["histograms"]["lat"]["counts"] == h["counts"]
    assert merge_exports([])["histograms"] == {}
    # a peer on a different bound table is refused, not under-merged
    bad = a.export()
    bad["histograms"]["lat"]["counts"] = [0, 1]
    with pytest.raises(ValueError, match="bucket-count mismatch"):
        merge_exports([b.export(), bad])
    # out-of-range samples land in the edge buckets, never lost
    edge = MetricsRegistry()
    edge.observe("lat", 1e-9)
    edge.observe("lat", 1e9)
    he = edge.export()["histograms"]["lat"]
    assert he["count"] == 2
    assert quantile_from_export(he, 0.25) == HIST_BOUNDS[0]
    assert quantile_from_export(he, 1.0) == HIST_BOUNDS[-1]
    assert quantile_from_export({"count": 0, "counts": []}, 0.5) is None


def test_registry_counters_concurrent():
    """Counter increments from many threads never lose updates (one
    lock over the name tables)."""
    reg = MetricsRegistry()

    def work():
        for _ in range(1000):
            reg.inc("n")

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("n") == 8000


# ---------------------------------------------------------------------------
# SLO burn-rate engine
# ---------------------------------------------------------------------------

def test_slo_burn_rate_edges_and_rearm():
    """Multi-window burn arithmetic under an injected clock: breach
    only when BOTH windows burn >= threshold, edge-triggered once per
    excursion, re-armed after the short window recovers; errors (inf
    latency) burn budget; untargeted tenants never breach."""
    clk = [0.0]
    trk = SloTracker({"interactive": 0.1}, objective=0.99,
                     windows=(10.0, 100.0), clock=lambda: clk[0])
    assert trk.target_for("interactive") == 0.1
    assert trk.target_for("bulk") is None  # no '*' default here
    # budget = 0.01; one bad request -> bad fraction 1.0 in both
    # windows -> burn 100x on each -> breach on the first observe
    br = trk.observe("interactive", float("inf"))
    assert br is not None
    assert br["tenant"] == "interactive"
    assert br["target_s"] == 0.1
    assert br["burn_short"] == br["burn_long"] == pytest.approx(100.0)
    # still hot: a second bad sample is NOT a second event
    clk[0] = 1.0
    assert trk.observe("interactive", 5.0) is None
    # recovery: good traffic drops the short burn below threshold
    clk[0] = 5.0
    for _ in range(99):
        assert trk.observe("interactive", 0.01) is None
    snap = trk.snapshot()
    assert snap["interactive"]["alerting"] is False
    assert snap["interactive"]["total"] == 101
    assert snap["interactive"]["good"] == 99
    assert snap["interactive"]["attainment"] == pytest.approx(
        99 / 101, abs=1e-3)
    assert set(snap["interactive"]["burn"]) == {"10", "100"}
    # past both windows the rings are empty again -> a fresh
    # excursion fires a SECOND edge
    clk[0] = 500.0
    br2 = trk.observe("interactive", 5.0)
    assert br2 is not None and br2["burn_short"] >= 10.0
    assert trk.burn_rate("interactive", 10.0) == pytest.approx(100.0)
    # untargeted tenant: attainment bookkeeping only, never a breach
    assert trk.observe("bulk", 1e9) is None
    assert trk.snapshot()["bulk"]["attainment"] is None
    # bare-number targets apply to every tenant via '*'
    assert SloTracker({"*": 2.0}).target_for("anyone") == 2.0


def test_slo_short_window_alone_does_not_page():
    """A transient blip hot in the short window but cold in the long
    one must NOT breach (the reason for multi-window alerting)."""
    clk = [0.0]
    trk = SloTracker({"*": 0.1}, objective=0.99,
                     windows=(10.0, 100.0), clock=lambda: clk[0])
    # long window full of good traffic first
    for i in range(200):
        clk[0] = 0.4 * i  # spread over 80 s
        assert trk.observe("t", 0.01) is None
    # now a burst of bads inside the short window only: short burn
    # goes hot, long burn stays ~2.4x < 10 -> no breach
    clk[0] = 81.0
    for _ in range(5):
        assert trk.observe("t", 9.9) is None
    assert trk.burn_rate("t", 10.0, now=81.0) >= 10.0
    assert trk.burn_rate("t", 100.0, now=81.0) < 10.0


# ---------------------------------------------------------------------------
# torn-load-snapshot fix (satellite)
# ---------------------------------------------------------------------------

def test_admission_queue_load_snapshot_is_atomic():
    """load_snapshot() returns (queue_len, pending_archives) from ONE
    lock acquisition: with every queued request holding exactly 7
    archives and a writer thread hammering submit/get/release, a
    snapshot can never observe pending outside [7*len, 7*len + 7]
    (the in-service request) — the torn two-lock read could."""
    q = AdmissionQueue(max_pending=10_000)
    stop = threading.Event()
    errors = []

    def writer():
        try:
            while not stop.is_set():
                q.submit(ServeRequest(["a"] * 7, "m"))
                got = q.get(timeout=0.5)
                q.release(7, tenant=got.tenant)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    t = threading.Thread(target=writer)
    t.start()
    try:
        deadline = time.monotonic() + 1.0
        n = 0
        while time.monotonic() < deadline:
            qlen, pending = q.load_snapshot()
            assert 7 * qlen <= pending <= 7 * qlen + 7, (qlen, pending)
            n += 1
        assert n > 100  # the sampler actually raced the writer
    finally:
        stop.set()
        t.join()
        q.close()
    assert not errors


# ---------------------------------------------------------------------------
# env hooks + manifest snapshot (satellite)
# ---------------------------------------------------------------------------

def test_obs_env_hooks_and_manifest_snapshot(tmp_path, monkeypatch):
    saved = (config.metrics, config.slo_targets, config.mon_interval_ms)
    try:
        for name in ("PPT_METRICS", "PPT_SLO_TARGETS",
                     "PPT_MON_INTERVAL_MS"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_METRICS", "off")
        monkeypatch.setenv("PPT_SLO_TARGETS", "interactive:0.5,*:5")
        monkeypatch.setenv("PPT_MON_INTERVAL_MS", "250")
        changed = config.env_overrides()
        assert {"metrics", "slo_targets", "mon_interval_ms"} <= \
            set(changed)
        assert config.metrics is False
        assert config.slo_targets == {"interactive": 0.5, "*": 5.0}
        assert config.mon_interval_ms == 250.0
        monkeypatch.setenv("PPT_SLO_TARGETS", "off")
        config.env_overrides()
        assert config.slo_targets is None
        # strict parses: a typo'd VALUE raises naming the knob
        for name, bad in (("PPT_METRICS", "maybe"),
                          ("PPT_SLO_TARGETS", "t:fast"),
                          ("PPT_MON_INTERVAL_MS", "0"),
                          ("PPT_MON_INTERVAL_MS", "soon")):
            monkeypatch.setenv(name, bad)
            with pytest.raises(ValueError, match=name):
                config.env_overrides()
            monkeypatch.delenv(name)
        # the knobs ride every trace manifest's config snapshot
        for key in ("metrics", "slo_targets", "mon_interval_ms"):
            assert key in telemetry.CONFIG_SNAPSHOT_KEYS
        trace = str(tmp_path / "t.jsonl")
        telemetry.Tracer(trace, run="snap").close()
        manifest, _ = telemetry.load_trace(trace)
        assert "slo_targets" in manifest["config"]
        assert "metrics" in manifest["config"]
    finally:
        (config.metrics, config.slo_targets,
         config.mon_interval_ms) = saved


# ---------------------------------------------------------------------------
# pptrace: no section vanishes on zero events (satellite)
# ---------------------------------------------------------------------------

def test_pptrace_sections_survive_empty_trace(tmp_path):
    """A manifest-only trace renders EVERY section with an explicit
    '(no ... events)' line — nothing crashes, nothing vanishes."""
    trace = str(tmp_path / "empty.jsonl")
    telemetry.Tracer(trace, run="empty").close()
    buf = io.StringIO()
    summary = telemetry.report(trace, file=buf)
    text = buf.getvalue()
    for header in ("-- serve (continuous batching) --",
                   "-- result cache (content-addressed) --",
                   "-- router (cross-host request sharding) --",
                   "-- fleet (membership / failover / QoS) --",
                   "-- template factory (batched LM buckets) --",
                   "-- timing (fleet-batched wideband GLS) --",
                   "-- data quality (zap + refit) --",
                   "-- online ingest + alerts --",
                   "-- tuning --",
                   "-- slo (latency objectives) --",
                   "-- skipped archives (0) --"):
        assert header in text, header
    assert text.count("(no ") >= 11
    assert summary["n_requests"] == 0
    assert summary["n_slo_breach"] == 0


def test_merge_refuses_pre_tracing_traces(tmp_path):
    trace = str(tmp_path / "old.jsonl")
    tr = telemetry.Tracer(trace, run="old")
    tr.emit("log", level="info", msg="no ids here")
    tr.close()
    with pytest.raises(ValueError, match="no trace_id"):
        merge_traces([trace])


# ---------------------------------------------------------------------------
# the e2e: router + 2 socket hosts, failover + hedge, merged
# timelines, fleet metrics op, ppmon --once --json
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_run(campaign, tmp_path_factory):
    """ONE routed fleet run shared by the merge/metrics/ppmon tests:
    2 socket hosts with per-host traces, hedging forced on every
    request, one mid-flight host kill, SLO targets set impossibly
    tight so breaches fire."""
    files, gmodel, refb = campaign
    root = tmp_path_factory.mktemp("fleetrun")
    rtrace = str(root / "router.jsonl")
    straces = [str(root / "hostA.jsonl"), str(root / "hostB.jsonl")]
    out = {"tims": {}, "refb": refb}
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True,
                   telemetry=straces[0]) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True,
                      telemetry=straces[1]) as h1:
        with TransportServer(h0, port=0) as lis_a, \
                TransportServer(h1, port=0) as lis_b:
            k0 = KillableTransport(
                SocketTransport(f"127.0.0.1:{lis_a.port}"))
            t1 = SocketTransport(f"127.0.0.1:{lis_b.port}")
            router = ToaRouter([k0, t1], telemetry=rtrace,
                               hedge_ms=0.0,
                               slo_targets={"*": 1e-6})
            names = {}
            tim0 = str(root / "A.tim")
            rh = router.submit(files[:2], gmodel, tim_out=tim0,
                               name="A", tenant="interactive")
            names["A"] = rh
            assert rh.host.label == k0.label
            k0.kill()  # dies with A in flight -> failover path
            names["B"] = router.submit(
                files[2:3], gmodel, tim_out=str(root / "B.tim"),
                name="B", tenant="bulk")
            for name, h in names.items():
                out["tims"][name] = h.result(300)
            # fleet-wide metrics + the ppmon surface, polled while
            # the router is live (a monitor endpoint like
            # `pproute --monitor`)
            out["fleet_metrics"] = router.metrics()
            with TransportServer(router, port=0) as mon:
                from pulseportraiture_tpu.cli import ppmon

                mt = SocketTransport(f"127.0.0.1:{mon.port}")
                out["mon_reply"] = mt.metrics()
                mt.close()
                buf = io.StringIO()
                ppmon.render(out["mon_reply"], file=buf)
                out["mon_text"] = buf.getvalue()
            out["host_metrics"] = SocketTransport(
                f"127.0.0.1:{lis_b.port}").metrics()
            out["stats"] = router.stats()
            router.close()
    out["traces"] = [rtrace] + straces
    out["bytes"] = {n: open(str(root / f"{n}.tim"), "rb").read()
                    for n in names}
    return out


def test_trace_ids_stitch_across_hosts(fleet_run):
    """Every request appears EXACTLY once in the merged cross-host
    timeline, the failover and the hedge ride their requests, and the
    per-request segments name a critical-path stage."""
    merged = merge_traces(fleet_run["traces"])
    assert merged["n_traces"] == 3
    roles = {t["role"] for t in merged["traces"]}
    assert roles == {"router", "host"}
    reqs = merged["requests"].values()
    by_name = {}
    for r in reqs:
        by_name.setdefault(r["req"], []).append(r)
    # exactly-once coverage: one trace_id per submitted request
    assert set(by_name) == {"A", "B"}
    assert all(len(v) == 1 for v in by_name.values()), by_name
    assert merged["n_requests"] == 2
    for r in reqs:
        assert r["router_wall_s"] is not None
        assert r["error"] is None
        assert r["n_host_spans"] >= 1  # host-side spans joined in
        assert r["critical"] in ("queue", "serve", "wire+collect")
        assert r["segments"]
    assert by_name["A"][0]["tenant"] == "interactive"
    # the kill produced a failover on A; hedge_ms=0 hedged >= 1 req
    assert by_name["A"][0]["failovers"], by_name["A"][0]
    assert any(r["hedged"] for r in reqs)
    # the merged text renderer names spans and flags
    buf = io.StringIO()
    from pulseportraiture_tpu.obs.merge import format_merge

    format_merge(merged, file=buf)
    text = buf.getvalue()
    assert "req A" in text and "req B" in text
    assert "failover" in text and "serve" in text


def test_fleet_run_tim_bytes_identical_and_slo_breaches(fleet_run):
    """Metrics + SLO tracking on changes NOTHING about the output:
    request A's .tim is byte-identical to the one-shot reference; the
    impossible SLO targets produced slo_breach telemetry the report
    surfaces."""
    assert fleet_run["bytes"]["A"] == fleet_run["refb"]
    assert all(st["outstanding"] == 0
               for st in fleet_run["stats"].values())
    rtrace = fleet_run["traces"][0]
    _, events = telemetry.validate_trace(rtrace)
    breaches = [e for e in events if e["type"] == "slo_breach"]
    assert breaches and breaches[0]["burn_short"] >= 10.0
    buf = io.StringIO()
    summary = telemetry.report(rtrace, file=buf)
    assert summary["n_slo_breach"] >= 1
    assert "interactive" in summary["slo_breach_tenants"] or \
        "bulk" in summary["slo_breach_tenants"]
    assert "-- slo (latency objectives) --" in buf.getvalue()
    assert "fast-burn breach" in buf.getvalue()


def test_router_metrics_aggregates_fleet(fleet_run):
    """ToaRouter.metrics(): per-host replies + the merged fleet view
    (queue depth, in-flight, latency quantiles from bucket-merged
    histograms, health states) + the router's own latency and SLO
    snapshot; a DEAD host degrades to an error entry instead of
    poisoning the reply."""
    m = fleet_run["fleet_metrics"]
    assert m["metrics_enabled"] is True
    assert m["fleet"]["n_hosts"] == 2
    states = set(m["fleet"]["states"].values())
    assert DEAD in states  # the killed host is reported, not hidden
    dead_lb = [lb for lb, st in m["fleet"]["states"].items()
               if st == DEAD][0]
    assert m["hosts"][dead_lb]["error"]
    live_lb = [lb for lb in m["hosts"] if lb != dead_lb][0]
    live = m["hosts"][live_lb]
    # n_live may be nonzero: a hedge/failover loser's handle is never
    # collected (its .tim is the durable artifact), so only assert the
    # field came through the wire
    assert live["queue_len"] == 0 and live["n_live"] is not None
    assert live["metrics"]["counters"]["requests_total"] >= 2
    assert live["p99_s"] is not None
    assert m["fleet"]["in_flight"] == 0
    assert m["fleet"]["queue_depth"] == 0
    assert m["fleet"]["p99_s"] is not None
    assert m["fleet"]["p50_s"] <= m["fleet"]["p99_s"]
    r = m["router"]
    assert r["p99_s"] is not None
    assert r["metrics"]["counters"]["route_submits"] == 2
    assert r["metrics"]["counters"]["route_done"] == 2
    # the impossible targets: every routed request burned budget
    assert r["slo"]["interactive"]["alerting"] is True
    assert r["slo"]["interactive"]["attainment"] == 0.0
    # single-host reply shape (the direct ppserve --listen view)
    hm = fleet_run["host_metrics"]
    assert hm["metrics_enabled"] is True
    assert "request_latency_s" in hm["metrics"]["histograms"]
    assert hm["slo"] is None  # no targets configured host-side


def test_ppmon_once_json_schema(fleet_run):
    """The monitor endpoint serves the fleet-shaped metrics reply over
    the wire, and ppmon's renderer + --once --json contract hold."""
    reply = fleet_run["mon_reply"]
    # the --once --json output IS this reply: it must be pure JSON
    flat = json.loads(json.dumps(reply))
    assert set(flat) == {"metrics_enabled", "hosts", "fleet", "router"}
    for key in ("n_hosts", "states", "queue_depth", "in_flight",
                "toas_per_s", "link_stall_frac", "p50_s", "p90_s",
                "p99_s", "metrics"):
        assert key in flat["fleet"], key
    for ent in flat["hosts"].values():
        for key in ("state", "outstanding", "queue_len", "p50_s",
                    "p99_s", "toas_per_s", "error"):
            assert key in ent, key
    assert flat["router"]["slo"], "per-tenant SLO attainment missing"
    for tenant, s in flat["router"]["slo"].items():
        assert {"target_s", "attainment", "alerting",
                "burn"} <= set(s)
    text = fleet_run["mon_text"]
    assert "ppmon: fleet (2 host(s))" in text
    assert "routed latency" in text and "-- slo --" in text


def test_ppmon_cli_once_json(fleet_run, capsys, tmp_path):
    """ppmon --once --json end-to-end against a live host endpoint
    (single-host shape), plus the unreachable-endpoint exit code."""
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as srv:
        with TransportServer(srv, port=0) as lis:
            from pulseportraiture_tpu.cli import ppmon

            rc = ppmon.main([f"127.0.0.1:{lis.port}", "--once",
                             "--json"])
            assert rc == 0
            reply = json.loads(capsys.readouterr().out)
            assert reply["metrics_enabled"] is True
            assert reply["queue_len"] == 0
            buf = io.StringIO()
            ppmon.render(reply, file=buf)
            assert "ppmon: host" in buf.getvalue()
            port = lis.port
    from pulseportraiture_tpu.cli import ppmon

    with pytest.raises(SystemExit, match="cannot reach"):
        ppmon.main([f"127.0.0.1:{port}", "--once", "--json"])
    with pytest.raises(SystemExit, match="endpoint"):
        ppmon.main(["not-an-endpoint", "--once"])
