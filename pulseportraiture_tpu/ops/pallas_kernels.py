"""Pallas TPU kernels for the fit hot loop.

The wideband fit's per-iteration cost is the harmonic-moment
computation (fit/portrait.py _cgh_fast): build the per-channel phasor
e^{i 2 pi t_n k}, multiply into the weighted cross-spectrum X, and
reduce three moments over harmonics.  The XLA path materializes the
(nchan, nharm) phasor and W = X * ph between fusions; this kernel
fuses phasor generation (VPU sin/cos), the complex multiply, and all
three reductions in a single VMEM pass — X is read from HBM exactly
once per iteration and nothing (nchan, nharm)-shaped is written back.

Opt-in via config.use_pallas (default False: XLA's fused reductions
measure ~10% faster at production shapes — see config.py); the XLA
path is the reference implementation and the two are tested against
each other (tests/test_pallas.py, interpret mode on CPU).
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on all platforms; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# channel-block rows per kernel instance (f32 sublane tile is 8;
# 128 keeps the VPU busy and the (BN, nharm) X block well under VMEM)
_BN = 128
_LANE = 128


def _moments_kernel(t_ref, xr_ref, xi_ref, out_ref):
    """One (BN, Hp) block: phasor + complex multiply + 3 reductions.

    t_ref: (BN, 1) phases t_n [rotations]; xr/xi: (BN, Hp) real/imag
    of X with zero padding; out: (BN, LANE) with lanes 0/1/2 holding
    (C, C1, C2) per channel row.
    """
    xr = xr_ref[:]
    xi = xi_ref[:]
    bn, hp = xr.shape
    k_int = jax.lax.broadcasted_iota(jnp.int32, (bn, hp), 1)
    k2pi = 2.0 * jnp.pi * k_int.astype(xr.dtype)
    ang = t_ref[:] * k2pi  # (BN, Hp)
    c = jnp.cos(ang)
    s = jnp.sin(ang)
    wr = xr * c - xi * s
    wi = xr * s + xi * c
    C = jnp.sum(wr, axis=1, keepdims=True)                 # Z0.real
    C1 = -jnp.sum(wi * k2pi, axis=1, keepdims=True)        # -Z1.imag
    C2 = -jnp.sum(wr * k2pi * k2pi, axis=1, keepdims=True)  # -Z2.real
    lane = jax.lax.broadcasted_iota(jnp.int32, (bn, _LANE), 1)
    out = jnp.where(lane == 0, C, 0.0)
    out = jnp.where(lane == 1, C1, out)
    out = jnp.where(lane == 2, C2, out)
    out_ref[:] = out


def _moments_impl(Xr, Xi, t, interpret=None):
    """(C, C1, C2) harmonic moments of X = Xr + i Xi under per-channel
    rotation t — everything real-valued in and out.

    Xr, Xi: (nchan, nharm) real/imag parts; t: (nchan,) phases in
    rotations.  Returns three (nchan,) real arrays:
      C  = Re sum_k X e^{i 2 pi t k}
      C1 = -Im sum_k X e^{i 2 pi t k} (2 pi k)
      C2 = -Re sum_k X e^{i 2 pi t k} (2 pi k)^2
    Matches the XLA forms in fit/portrait.py exactly (same f32 sin/cos
    semantics).

    The split-real signature is deliberate: the tunneled TPU runtime
    fails to compile programs that contain BOTH a complex-typed value
    and a Mosaic kernel, so the fit's real core (fit/portrait.py
    _fit_portrait_core_real) keeps the whole program complex-free.
    """
    if interpret is None:
        # Mosaic compiles on TPU only; everywhere else (CPU tests,
        # virtual-device meshes) fall back to interpret mode
        interpret = jax.default_backend() != "tpu"
    nchan, nharm = Xr.shape
    dt = Xr.dtype
    np_ = -nchan % _BN
    hp = -nharm % _LANE
    xr = jnp.pad(Xr, ((0, np_), (0, hp)))
    xi = jnp.pad(Xi, ((0, np_), (0, hp)))
    tcol = jnp.pad(t.astype(dt), (0, np_)).reshape(-1, 1)
    nb = (nchan + np_) // _BN
    # index maps use i*0 instead of literal 0: under jax_enable_x64 a
    # literal becomes an i64 constant next to the i32 grid index, which
    # Mosaic fails to legalize ("func.return (i32, i64)")
    out = pl.pallas_call(
        _moments_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((_BN, 1), lambda i: (i, i * 0),
                         memory_space=_VMEM),
            pl.BlockSpec((_BN, nharm + hp), lambda i: (i, i * 0),
                         memory_space=_VMEM),
            pl.BlockSpec((_BN, nharm + hp), lambda i: (i, i * 0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((_BN, _LANE), lambda i: (i, i * 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((nchan + np_, _LANE), dt),
        interpret=interpret,
    )(tcol, xr, xi)
    return out[:nchan, 0], out[:nchan, 1], out[:nchan, 2]


@jax.custom_batching.custom_vmap
def harmonic_moments_real(Xr, Xi, t):
    return _moments_impl(Xr, Xi, t)


@harmonic_moments_real.def_vmap
def _moments_vmap_rule(axis_size, in_batched, Xr, Xi, t):
    """vmap by flattening the batch into kernel rows: one big Pallas
    grid instead of a small per-fit grid replicated axis_size times
    (which loses to XLA on dispatch/pipelining)."""
    xb, ib, tb = in_batched
    if not xb:
        Xr = jnp.broadcast_to(Xr, (axis_size,) + Xr.shape)
    if not ib:
        Xi = jnp.broadcast_to(Xi, (axis_size,) + Xi.shape)
    if not tb:
        t = jnp.broadcast_to(t, (axis_size,) + t.shape)
    nb, nchan, nharm = Xr.shape
    C, C1, C2 = harmonic_moments_real(
        Xr.reshape(nb * nchan, nharm),
        Xi.reshape(nb * nchan, nharm),
        t.reshape(nb * nchan),
    )
    out = tuple(c.reshape(nb, nchan) for c in (C, C1, C2))
    return out, (True, True, True)


def harmonic_moments(X, t, interpret=False):
    """Complex-input convenience wrapper (tests / CPU interpret mode).

    Do not use inside TPU programs that reach the Pallas kernel — see
    harmonic_moments_real for why.
    """
    dt = jnp.float32 if X.dtype == jnp.complex64 else jnp.float64
    xr, xi = X.real.astype(dt), X.imag.astype(dt)
    if interpret:
        return _moments_impl(xr, xi, t, interpret=True)
    return harmonic_moments_real(xr, xi, t)
