"""ppalign — iteratively align and average archives.

Flag parity: reference ppalign.py:283-420, with the psradd/psrsmooth
subprocess steps replaced by the internal equivalents.
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppalign", description=__doc__.splitlines()[0])
    p.add_argument("-M", "--metafile", required=True,
                   help="Metafile of archives to average together.")
    p.add_argument("-I", "--init", dest="initial_guess", default=None,
                   help="Archive providing the initial alignment guess.")
    p.add_argument("-g", "--width", dest="fwhm", type=float, default=None,
                   help="Use a single-Gaussian template with this FWHM "
                        "[rot] as the initial guess.")
    p.add_argument("-D", "--no_DM", dest="fit_dm", action="store_false",
                   default=True, help="Align with phase only (no DM fit).")
    p.add_argument("-T", "--tscr", dest="tscrunch", action="store_true",
                   default=False, help="tscrunch archives first.")
    p.add_argument("-p", "--poln", dest="pscrunch", action="store_false",
                   default=True, help="Keep polarization (Stokes) data.")
    p.add_argument("-C", "--cutoff", dest="SNR_cutoff", type=float,
                   default=0.0, help="S/N cutoff for including archives.")
    p.add_argument("-o", "--outfile", default=None,
                   help="Output archive name. [default=<metafile>"
                        ".algnd.fits]")
    p.add_argument("-P", "--palign", action="store_true", default=False,
                   help="Initial template = unaligned sum of the archives "
                        "(internal psradd equivalent).")
    p.add_argument("-N", "--norm", default=None,
                   choices=(None, "mean", "max", "prof", "rms", "abs"),
                   help="Normalization applied to the final average.")
    p.add_argument("-s", "--smooth", action="store_true", default=False,
                   help="Wavelet-smooth the output average (internal "
                        "psrsmooth equivalent).")
    p.add_argument("-r", "--rot", dest="rot_phase", type=float,
                   default=0.0, help="Overall rotation of the output.")
    p.add_argument("--place", type=float, default=None,
                   help="Place the peak at this phase (overrides --rot).")
    p.add_argument("--niter", type=int, default=1,
                   help="Number of align/average iterations.")
    p.add_argument("--align-device", dest="align_device", default=None,
                   choices=("auto", "on", "off"),
                   help="Run the rotate-and-accumulate template update "
                        "on the default device (jitted split-real "
                        "harmonic programs) instead of the chunked "
                        "complex host loop.  auto = on for TPU "
                        "backends.  [default: config.align_device]")
    p.add_argument("--verbose", dest="quiet", action="store_false",
                   default=True)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    from ..pipeline.align import (
        align_archives,
        gaussian_seed_portrait,
        psradd_archives,
        psrsmooth_archive,
    )
    from ..pipeline.toas import _read_metafile

    datafiles = _read_metafile(args.metafile)
    if args.initial_guess:
        init = args.initial_guess
    elif args.palign:
        init = psradd_archives(datafiles, quiet=True)
    elif args.fwhm:
        from ..io.psrfits import read_archive

        a0 = read_archive(datafiles[0])
        init = gaussian_seed_portrait(a0.nchan, a0.nbin, args.fwhm)
    else:
        init = datafiles[0]
    outfile = args.outfile or (args.metafile + ".algnd.fits")
    adev = {None: None, "auto": "auto", "on": True,
            "off": False}[args.align_device]
    align_archives(datafiles, init, fit_dm=args.fit_dm,
                   tscrunch=args.tscrunch, pscrunch=args.pscrunch,
                   SNR_cutoff=args.SNR_cutoff, outfile=outfile,
                   norm=args.norm, rot_phase=args.rot_phase,
                   place=args.place, niter=args.niter, quiet=args.quiet,
                   align_device=adev)
    if args.smooth:
        import os.path

        base, _ = os.path.splitext(outfile)
        psrsmooth_archive(outfile, base + ".sm.fits")
    return 0


if __name__ == "__main__":
    sys.exit(main())
