"""Live observability plane (ISSUE 20): distributed request tracing,
streaming metrics, and SLO monitoring for the serving fleet.

Post-hoc JSONL traces (``telemetry.py``) answer "what happened"; this
package answers "what is happening".  Three pieces:

- ``obs.metrics`` — thread-safe counters/gauges plus fixed log-bucket
  latency histograms (p50/p90/p99 without sample retention), exported
  over the ``metrics`` transport op and mergeable fleet-wide because
  every host shares the same bucket bounds.
- ``obs.slo`` — per-tenant latency objectives with multi-window
  burn-rate tracking and edge-triggered breach events.
- ``obs.merge`` — stitch a router trace plus N host traces into
  per-request cross-host span timelines keyed by ``trace_id``.

The ``trace_id`` minted at submit time (``new_trace_id``) rides the
wire submit op and is stamped into every event a request touches on
any host, so ``pptrace merge`` can reconstruct each request's life
across processes.
"""

from .metrics import (HIST_BOUNDS, MetricsRegistry, global_registry,
                      merge_exports, quantile_from_export, record_h2d)
from .slo import SloTracker
from .trace import new_trace_id

__all__ = [
    "HIST_BOUNDS", "MetricsRegistry", "SloTracker", "global_registry",
    "merge_exports", "new_trace_id", "quantile_from_export",
    "record_h2d",
]
