from .archive import add_scintillation, make_fake_pulsar
from .fake import (default_test_model, fake_observation, fake_portrait,
                   fake_timing_campaign)
from .rfi import inject_rfi

__all__ = ["add_scintillation", "default_test_model", "fake_observation",
           "fake_portrait", "fake_timing_campaign", "inject_rfi",
           "make_fake_pulsar"]
