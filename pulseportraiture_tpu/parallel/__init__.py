from .mesh import make_mesh, batch_sharding, replicated
from .batch import (fit_portrait_sharded, fit_portrait_sharded_fast,
                    shard_batch)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated",
    "fit_portrait_sharded",
    "fit_portrait_sharded_fast",
    "shard_batch",
]
