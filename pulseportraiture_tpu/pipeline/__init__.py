"""Pipeline / orchestration layer (SURVEY §2.2 L4): TOA measurement,
align-and-average, template building, channel zapping."""

from .toas import GetTOAs  # noqa: F401
