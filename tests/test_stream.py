"""Cross-archive streaming driver: pooled-bucket fits must reproduce
GetTOAs' per-archive results, including with padding (bucket larger
than the subint count) and mixed archive shapes."""

import numpy as np
import pytest

from pulseportraiture_tpu.pipeline import GetTOAs, stream_wideband_TOAs
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}
DDMS = [2e-4, -3e-4, 4e-4, -1e-4]


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    root = tmp_path_factory.mktemp("stream")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i, dDM in enumerate(DDMS):
        path = str(root / f"ep{i}.fits")
        # one archive with a different channel count exercises the
        # multi-bucket path
        nchan = 24 if i == 2 else 32
        make_fake_pulsar(model, PAR, outfile=path, nsub=3, nchan=nchan,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.05 * i, dDM=dDM,
                         start_MJD=MJD(55100 + 10 * i, 0.1),
                         noise_stds=0.08, dedispersed=False, quiet=True,
                         rng=200 + i)
        files.append(path)
    return files, gmodel


def test_stream_matches_gettoas(campaign):
    files, gmodel = campaign
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True)
    assert res.order == files
    assert len(res.TOA_list) == 4 * 3
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25)
    by_key = {}
    for t in res.TOA_list:
        by_key[(t.archive, t.flags["subint"])] = t
    for i, f in enumerate(files):
        # per-archive DeltaDM statistics agree
        assert res.DeltaDM_means[i] == pytest.approx(
            gt.DeltaDM_means[i], abs=1e-7)
        for isub in gt.ok_isubs[i]:
            t = by_key[(f, int(isub))]
            # same TOA (phase + frequency reference) and DM
            assert t.frequency == pytest.approx(
                gt.nu_refs[i][isub][0], rel=1e-9)
            assert t.DM == pytest.approx(gt.DMs[i][isub], abs=1e-9)
            wb = gt.TOAs[i][isub]
            dt_us = abs((wb.day - t.MJD.day) * 86400.0
                        + (wb.frac - t.MJD.frac) * 86400.0) * 1e6
            assert dt_us < 1e-3, (i, isub, dt_us)  # sub-nanosecond
            assert t.TOA_error == pytest.approx(
                gt.TOA_errs[i][isub], rel=1e-6)


def test_stream_bucket_padding(campaign):
    """nsub_batch much larger than the total subint count: everything
    lands in one padded dispatch and results are unchanged."""
    files, gmodel = campaign
    a = stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True)
    b = stream_wideband_TOAs(files, gmodel, nsub_batch=256, quiet=True)
    assert len(a.TOA_list) == len(b.TOA_list)
    assert b.nfit == 2  # one per shape bucket
    for ta, tb in zip(a.TOA_list, b.TOA_list):
        assert ta.archive == tb.archive
        assert ta.DM == pytest.approx(tb.DM, abs=1e-12)
        assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)


def test_stream_skips_bad_archive(campaign, tmp_path):
    files, gmodel = campaign
    bad = str(tmp_path / "corrupt.fits")
    with open(bad, "w") as f:
        f.write("not a fits file")
    res = stream_wideband_TOAs([files[0], bad, files[1]], gmodel,
                               quiet=True)
    assert res.order == [files[0], files[1]]
    assert len(res.TOA_list) == 6


def test_stream_degenerate_subint(campaign, tmp_path):
    """A subint with one usable channel is demoted to a phase-only
    bucket (no garbage two-parameter fit), matching GetTOAs."""
    files, gmodel = campaign
    model = default_test_model(1500.0)
    w = np.ones((2, 32))
    w[0, 1:] = 0.0
    path = str(tmp_path / "degen.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32, nbin=256,
                     tsub=60.0, noise_stds=0.08, weights=w,
                     dedispersed=False, quiet=True, rng=9)
    res = stream_wideband_TOAs([path], gmodel, nsub_batch=8, quiet=True)
    assert len(res.TOA_list) == 2
    assert res.nfit == 2  # one full bucket + one phase-only bucket
    for t in res.TOA_list:
        assert np.isfinite(t.TOA_error)
    # the degenerate subint reports the fixed header DM (phase-only)
    t0 = [t for t in res.TOA_list if t.flags["subint"] == 0][0]
    assert t0.DM == pytest.approx(PAR["DM"], abs=1e-9)


def test_stream_incremental_tim(campaign, tmp_path):
    """tim_out appends each archive's lines as soon as it completes;
    the final file equals a one-shot write of the returned TOA_list."""
    from pulseportraiture_tpu.io.tim import write_TOAs

    files, gmodel = campaign
    tim_inc = tmp_path / "inc.tim"
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                               tim_out=str(tim_inc), quiet=True)
    tim_ref = tmp_path / "ref.tim"
    write_TOAs(res.TOA_list, outfile=str(tim_ref), append=False)
    raw = tim_inc.read_text().strip().splitlines()
    # the checkpoint interleaves per-archive completion sentinels
    # (comment lines readers skip) — one per archive
    sentinels = [l for l in raw if l.startswith("C ppt-done ")]
    assert len(sentinels) == len(files)
    li = [l for l in raw if not l.startswith("C ")]
    lr = tim_ref.read_text().strip().splitlines()
    # incremental emission may reorder across archives (bucket
    # completion order), but the line SET must match exactly
    assert sorted(li) == sorted(lr)
    assert len(li) == len(res.TOA_list)


@pytest.mark.slow  # ~13 s; the streamed-vs-get_TOAs parity core stays
# tier-1 via test_stream_matches_gettoas (phi-DM lane)
def test_stream_scattering_matches_gettoas(tmp_path):
    """Streamed scattering fits (fit_scat + auto seed) must reproduce
    GetTOAs' scattering results and emit the same TOA flag set."""
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        path = str(tmp_path / f"sc{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.02 * i, dDM=1e-4 * i, t_scat=3e-4,
                         alpha=-4.0, start_MJD=MJD(55200 + 10 * i, 0.1),
                         noise_stds=0.02, dedispersed=False, quiet=True,
                         rng=300 + i)
        files.append(path)
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=4, fit_scat=True,
                               scat_guess="auto", quiet=True)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(fit_scat=True, scat_guess="auto", quiet=True, max_iter=25)
    assert len(res.TOA_list) == 4
    by_key = {(t.archive, t.flags["subint"]): t for t in res.TOA_list}
    for i, f in enumerate(files):
        for j, t_ref in enumerate(gt.TOA_list[i * 2:(i + 1) * 2]):
            t = by_key[(f, t_ref.flags["subint"])]
            for key in ("scat_time", "log10_scat_time", "scat_ref_freq",
                        "scat_ind", "scat_ind_err"):
                assert key in t.flags, key
                assert t.flags[key] == pytest.approx(
                    t_ref.flags[key], rel=0.05, abs=1e-3), key
            # injected tau is 3e-4 s; scat_time flag is microseconds at
            # scat_ref_freq with index alpha
            expect_us = 3e-4 * 1e6 * (t.flags["scat_ref_freq"]
                                      / 1500.0) ** t.flags["scat_ind"]
            assert t.flags["scat_time"] == pytest.approx(expect_us,
                                                         rel=0.15)

    # nu_ref_tau re-references the reported tau like get_TOAs' -nu_tau
    res_r = stream_wideband_TOAs(files, gmodel, nsub_batch=4,
                                 fit_scat=True, scat_guess="auto",
                                 nu_ref_tau=1400.0, quiet=True)
    by_key_r = {(t.archive, t.flags["subint"]): t for t in res_r.TOA_list}
    for key, t in by_key.items():
        t_r = by_key_r[key]
        assert t_r.flags["scat_ref_freq"] == pytest.approx(1400.0)
        expect = (t.flags["scat_time"]
                  * (1400.0 / t.flags["scat_ref_freq"])
                  ** t.flags["scat_ind"])
        assert t_r.flags["scat_time"] == pytest.approx(expect, rel=1e-6)


def test_stream_raw_lane_dedispersed_and_iquv(tmp_path):
    """The raw lane covers dedispersed-on-disk archives (device-side
    re-dispersion by the stored DM) and IQUV multi-pol archives
    (Stokes I = pol 0, no host pscrunch) — results must match GetTOAs,
    which handles both on host."""
    from pulseportraiture_tpu.pipeline.stream import _load_raw

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i, (dedisp, npol) in enumerate([(True, 1), (False, 4),
                                        (True, 4)]):
        p = str(tmp_path / f"v{i}.fits")
        make_fake_pulsar(model, PAR, outfile=p, nsub=2, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.01 * i, dDM=2e-4, npol=npol,
                         state="Stokes",
                         start_MJD=MJD(55300 + i, 0.2), noise_stds=0.05,
                         dedispersed=dedisp, quiet=True, rng=700 + i)
        files.append(p)
    # all three land in the raw lane
    for f in files:
        d = _load_raw(f)
        assert d.raw_mode and d.raw.dtype == np.dtype(np.int16)
    assert _load_raw(files[0]).dmc is True
    assert _load_raw(files[1]).dmc is False

    res = stream_wideband_TOAs(files, gmodel, nsub_batch=4, quiet=True)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25)
    assert len(res.TOA_list) == len(gt.TOA_list) == 6
    by_key = {(t.archive, t.flags["subint"]): t for t in res.TOA_list}
    for t_ref in gt.TOA_list:
        t = by_key[(t_ref.archive, t_ref.flags["subint"])]
        # device re-dispersion (matmul DFT f64 on CPU) vs host pocketfft
        # agree to float precision; phases to sub-ns
        dt_us = abs((t.MJD - t_ref.MJD) * 86400.0 * 1e6)
        assert dt_us < 1e-3, (t_ref.archive, dt_us)
        assert t.DM == pytest.approx(t_ref.DM, abs=1e-7)


@pytest.mark.slow  # ~13 s; same rationale as the scattering variant
def test_stream_gm_matches_gettoas(tmp_path):
    """Streamed (phi, DM, GM) fits reproduce GetTOAs' GM results and
    flags, including the 2-usable-channel no-GM demotion."""
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    w = np.ones((2, 32))
    w[1, 2:] = 0.0  # subint 1: two usable channels -> GM dropped
    path = str(tmp_path / "gm.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                     nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                     dDM=1e-4, weights=w, start_MJD=MJD(55400, 0.2),
                     noise_stds=0.03, dedispersed=False, quiet=True,
                     rng=42)
    res = stream_wideband_TOAs([path], gmodel, nsub_batch=4,
                               fit_GM=True, quiet=True)
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.get_TOAs(fit_GM=True, quiet=True, max_iter=25)
    assert len(res.TOA_list) == 2
    by_key = {t.flags["subint"]: t for t in res.TOA_list}
    # the demoted 2-channel subint reports gm == 0.0 on both sides
    # (GetTOAs emits the flag for every subint of a fit_GM run)
    assert by_key[1].flags["gm"] == 0.0
    assert gt.TOA_list[1].flags["gm"] == 0.0
    for t_ref in gt.TOA_list:
        t = by_key[t_ref.flags["subint"]]
        if "gm" in t_ref.flags:
            assert "gm" in t.flags
            assert t.flags["gm"] == pytest.approx(t_ref.flags["gm"],
                                                  abs=1e-9)
            if t_ref.flags["gm_err"]:
                assert t.flags["gm_err"] == pytest.approx(
                    t_ref.flags["gm_err"], rel=1e-6)
        else:  # pragma: no cover - gm is emitted for every subint
            raise AssertionError("GetTOAs should emit gm for all subints")
        assert t.DM == pytest.approx(t_ref.DM, abs=1e-9)
        dt_us = abs((t.MJD - t_ref.MJD) * 86400.0 * 1e6)
        assert dt_us < 1e-3


def test_stream_flux_matches_gettoas(tmp_path):
    """Streamed flux estimates (print_flux) reproduce GetTOAs' flux
    flags, including with a fitted scattering tau in the model path."""
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    path = str(tmp_path / "fx.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                     nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                     dDM=1e-4, scales=2.5, t_scat=3e-4,
                     start_MJD=MJD(55500, 0.2), noise_stds=0.02,
                     dedispersed=False, quiet=True, rng=11)
    res = stream_wideband_TOAs([path], gmodel, nsub_batch=4,
                               fit_scat=True, scat_guess="auto",
                               print_flux=True, quiet=True)
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.get_TOAs(fit_scat=True, scat_guess="auto", print_flux=True,
                quiet=True, max_iter=25)
    by_key = {t.flags["subint"]: t for t in res.TOA_list}
    for t_ref in gt.TOA_list:
        t = by_key[t_ref.flags["subint"]]
        for key in ("flux", "flux_err", "flux_ref_freq"):
            assert key in t.flags, key
            assert t.flags[key] == pytest.approx(t_ref.flags[key],
                                                 rel=1e-3), key
        # injected per-channel scale 2.5 on a unit-ish template: the
        # estimate must be in the right ballpark
        assert t.flags["flux"] == pytest.approx(
            2.5 * float(np.mean(np.asarray(model.amps))), rel=1.0)


@pytest.mark.slow  # ~14 s (tier-1 budget, r19): the IRF plumbing
# keeps tier-1 coverage in test_pipeline_toas.py::
# test_instrumental_response_plumbed
def test_stream_instrumental_response_matches_gettoas(tmp_path):
    """Streamed fits with an instrumental-response kernel (achromatic
    Gaussian + DM smearing) reproduce GetTOAs' results."""
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    path = str(tmp_path / "ir.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                     nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                     dDM=1e-4, start_MJD=MJD(55600, 0.2),
                     noise_stds=0.03, dedispersed=False, quiet=True,
                     rng=13)
    ird = {"DM-smear": True, "wids": [0.002], "irf_types": ["gauss"]}
    res = stream_wideband_TOAs([path], gmodel, nsub_batch=4,
                               instrumental_response_dict=ird,
                               quiet=True)
    gt = GetTOAs(path, gmodel, quiet=True)
    gt.instrumental_response_dict.update(ird)
    gt.get_TOAs(quiet=True, max_iter=25)
    by_key = {t.flags["subint"]: t for t in res.TOA_list}
    for t_ref in gt.TOA_list:
        t = by_key[t_ref.flags["subint"]]
        assert t.DM == pytest.approx(t_ref.DM, abs=1e-9)
        dt_us = abs((t.MJD - t_ref.MJD) * 86400.0 * 1e6)
        assert dt_us < 1e-3, dt_us
        assert t.TOA_error == pytest.approx(t_ref.TOA_error, rel=1e-6)
    # mismatched config still raises
    with pytest.raises(ValueError, match="pair up"):
        stream_wideband_TOAs([path], gmodel,
                             instrumental_response_dict={
                                 "DM-smear": False, "wids": [0.1],
                                 "irf_types": []}, quiet=True)


@pytest.mark.slow  # ~22 s narrowband parity sweep (tier-1 budget,
# r19): test_stream_narrowband_multidevice_digit_identical keeps the
# NB streamed lane's digit gate in tier-1
def test_stream_narrowband_matches_gettoas(tmp_path):
    """Streamed narrowband (per-channel 1-D) TOAs reproduce
    get_narrowband_TOAs — both plain and with the per-channel
    scattering fit, across raw-lane archives."""
    from pulseportraiture_tpu.pipeline.stream import stream_narrowband_TOAs

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        p = str(tmp_path / f"nb{i}.fits")
        make_fake_pulsar(model, PAR, outfile=p, nsub=2, nchan=16,
                         nbin=256, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.02 * i, dDM=1e-4,
                         start_MJD=MJD(55700 + i, 0.2), noise_stds=0.03,
                         dedispersed=False, quiet=True, rng=800 + i)
        files.append(p)

    res = stream_narrowband_TOAs(files, gmodel, nsub_batch=4,
                                 print_phase=True, quiet=True)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_narrowband_TOAs(print_phase=True, quiet=True)
    assert len(res.TOA_list) == len(gt.TOA_list) == 2 * 2 * 16
    by_key = {(t.archive, t.flags["subint"], t.flags["chan"]): t
              for t in res.TOA_list}
    for t_ref in gt.TOA_list:
        t = by_key[(t_ref.archive, t_ref.flags["subint"],
                    t_ref.flags["chan"])]
        assert t.frequency == t_ref.frequency
        dt_us = abs((t.MJD - t_ref.MJD) * 86400.0 * 1e6)
        assert dt_us < 1e-3, dt_us
        assert t.TOA_error == pytest.approx(t_ref.TOA_error, rel=1e-6)
        assert t.flags["snr"] == pytest.approx(t_ref.flags["snr"],
                                               rel=1e-6)
        assert t.flags["phs"] == pytest.approx(t_ref.flags["phs"],
                                               abs=1e-9)

    # scattering variant (the reference's "NOT YET IMPLEMENTED" path)
    res_s = stream_narrowband_TOAs(files[:1], gmodel, nsub_batch=4,
                                   fit_scat=True, scat_guess="auto",
                                   quiet=True)
    gt_s = GetTOAs(files[:1], gmodel, quiet=True)
    gt_s.get_narrowband_TOAs(fit_scat=True, scat_guess="auto",
                             quiet=True, max_iter=25)
    by_key_s = {(t.flags["subint"], t.flags["chan"]): t
                for t in res_s.TOA_list}
    for t_ref in gt_s.TOA_list:
        t = by_key_s[(t_ref.flags["subint"], t_ref.flags["chan"])]
        dt_us = abs((t.MJD - t_ref.MJD) * 86400.0 * 1e6)
        assert dt_us < 1e-2, dt_us
        assert t.flags["log10_scat_time"] == pytest.approx(
            t_ref.flags["log10_scat_time"], abs=1e-3)


@pytest.mark.slow  # ~18 s fast-lane scattering parity (tier-1
# budget, r19): test_stream_scattering_matches_gettoas keeps the
# streamed scattering parity in tier-1 on the default lane
def test_stream_fast_lane_scattering_parity(tmp_path):
    """With config.use_fast_fit forced on (the TPU setting), scattering
    buckets route through the complex-free _cgh_scatter lane in f32 —
    results must match the f64 complex-engine run to f32 tolerances,
    with an instrumental-response kernel folded in."""
    from pulseportraiture_tpu import config

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        path = str(tmp_path / f"fs{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.02 * i, dDM=1e-4 * i, t_scat=3e-4,
                         alpha=-4.0, start_MJD=MJD(55300 + 10 * i, 0.1),
                         noise_stds=0.02, dedispersed=False, quiet=True,
                         rng=700 + i)
        files.append(path)
    ird = {"wids": [0.2e-3], "irf_types": ["rect"]}
    kw = dict(nsub_batch=4, fit_scat=True, scat_guess="auto",
              instrumental_response_dict=ird, quiet=True)
    ref = stream_wideband_TOAs(files, gmodel, **kw)
    assert config.use_fast_fit == "auto"
    config.use_fast_fit = True
    try:
        fast = stream_wideband_TOAs(files, gmodel, **kw)
    finally:
        config.use_fast_fit = "auto"
    assert len(fast.TOA_list) == len(ref.TOA_list) == 4
    by_key = {(t.archive, t.flags["subint"]): t for t in fast.TOA_list}
    for t_ref in ref.TOA_list:
        t = by_key[(t_ref.archive, t_ref.flags["subint"])]
        # arrival times agree to ~1e-7 s (f32 phase resolution x P)
        assert abs((t.MJD - t_ref.MJD) * 86400.0) < 5e-7
        assert t.DM == pytest.approx(t_ref.DM, abs=5e-4)
        assert t.flags["scat_time"] == pytest.approx(
            t_ref.flags["scat_time"], rel=0.02)
        assert t.flags["scat_ind"] == pytest.approx(
            t_ref.flags["scat_ind"], abs=0.05)
        assert t.flags["snr"] == pytest.approx(t_ref.flags["snr"],
                                               rel=0.01)


def test_stream_print_phase_flags(campaign):
    """print_phase emits the phs/phs_err flags exactly like GetTOAs."""
    files, gmodel = campaign
    res = stream_wideband_TOAs(files[:1], gmodel, nsub_batch=4,
                               print_phase=True, quiet=True)
    gt = GetTOAs(files[:1], gmodel, quiet=True)
    gt.get_TOAs(print_phase=True, quiet=True, max_iter=25)
    by_key = {t.flags["subint"]: t for t in res.TOA_list}
    for t_ref in gt.TOA_list:
        t = by_key[t_ref.flags["subint"]]
        assert t.flags["phs"] == pytest.approx(t_ref.flags["phs"],
                                               abs=1e-9)
        assert t.flags["phs_err"] == pytest.approx(
            t_ref.flags["phs_err"], rel=1e-6)


def test_stream_resume_skips_completed_and_drops_torn_tail(campaign,
                                                           tmp_path):
    """resume=True re-enters an interrupted checkpoint: the torn tail
    after the last completion sentinel is dropped, completed archives
    are skipped, and the final file is line-set-identical to an
    uninterrupted run."""
    files, gmodel = campaign
    tim_full = tmp_path / "full.tim"
    stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                         tim_out=str(tim_full), quiet=True)
    full_lines = sorted(l for l in tim_full.read_text().splitlines()
                        if l.strip())

    # forge an interrupted checkpoint: keep the first archive's block
    # (through its sentinel), then a torn partial line
    lines = tim_full.read_text().splitlines(keepends=True)
    first_done = next(i for i, l in enumerate(lines)
                      if l.startswith("C ppt-done "))
    tim_part = tmp_path / "part.tim"
    tim_part.write_text("".join(lines[:first_done + 1])
                        + "torn 1400.0 55100.12")
    done_arch = lines[first_done].split("C ppt-done ", 1)[1].strip()

    res = stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                               tim_out=str(tim_part), quiet=True,
                               resume=True)
    # the completed archive was skipped, not re-measured
    assert done_arch not in [t.archive for t in res.TOA_list]
    assert sorted(l for l in tim_part.read_text().splitlines()
                  if l.strip()) == full_lines


@pytest.mark.slow
def test_stream_narrowband_midrun_flush_no_duplicates(campaign,
                                                      tmp_path):
    """A narrowband bucket that fills MID-campaign (nsub_batch smaller
    than the total) must be cleared at launch: regression for the
    executor refactor dropping launch_nb's bucket clear, which would
    re-dispatch every prior subint on each flush and stamp premature
    completion sentinels."""
    from pulseportraiture_tpu.pipeline.stream import (
        stream_narrowband_TOAs)

    files, gmodel = campaign
    a = stream_narrowband_TOAs(files, gmodel, nsub_batch=2, quiet=True,
                               tim_out=str(tmp_path / "nb2.tim"))
    b = stream_narrowband_TOAs(files, gmodel, nsub_batch=64, quiet=True)
    keys_a = [(t.archive, t.flags["subint"], t.flags["chan"])
              for t in a.TOA_list]
    keys_b = [(t.archive, t.flags["subint"], t.flags["chan"])
              for t in b.TOA_list]
    assert len(keys_a) == len(set(keys_a))  # no duplicates
    assert sorted(keys_a) == sorted(keys_b)
    assert a.nfit > b.nfit  # the small batch really flushed mid-run


def test_checkpoint_sentinel_requires_newline(tmp_path):
    """A sentinel line without a trailing newline is a torn write (the
    writer died mid-sentinel): neither helper may count it, and
    sanitize must drop it with the tail so resume re-measures that
    archive exactly once instead of duplicating its TOA lines."""
    from pulseportraiture_tpu.pipeline.stream import (
        checkpoint_completed, sanitize_checkpoint)

    ck = tmp_path / "ck.tim"
    body = ("arch1 1400.0 55100.1 1.0 gbt\n"
            "C ppt-done /data/a1.fits\n"
            "arch2 1400.0 55100.2 1.0 gbt\n"
            "C ppt-done /data/a2.fi")  # torn mid-path, no newline
    ck.write_text(body)
    assert checkpoint_completed(str(ck)) == {"/data/a1.fits"}
    done = sanitize_checkpoint(str(ck))
    assert done == {"/data/a1.fits"}
    # everything after the last TERMINATED sentinel is gone
    assert ck.read_text() == ("arch1 1400.0 55100.1 1.0 gbt\n"
                              "C ppt-done /data/a1.fits\n")


def test_ipta_resume_scan_ignores_prefix_pulsar_shards(tmp_path):
    """The elastic-resume shard scan is anchored to the shard naming
    scheme: pulsar 'J1713' must not absorb 'J1713+0747''s checkpoint
    sentinels (its name is a prefix), or a shared archive path would be
    wrongly skipped for the wrong pulsar."""
    import os

    from pulseportraiture_tpu.pipeline.ipta import _shard_checkpoints

    names = ["J1713.tim", "J1713.p0.tim", "J1713.p12.tim",
             "J1713+0747.tim", "J1713+0747.p0.tim", "J1713x.tim",
             "J1713.p1.extra.tim"]
    for n in names:
        (tmp_path / n).touch()
    got = [os.path.basename(p)
           for p in _shard_checkpoints(str(tmp_path), "J1713")]
    assert got == ["J1713.p0.tim", "J1713.p12.tim", "J1713.tim"]
    got = [os.path.basename(p)
           for p in _shard_checkpoints(str(tmp_path), "J1713+0747")]
    assert got == ["J1713+0747.p0.tim", "J1713+0747.tim"]


def test_stream_multidevice_digit_identical(campaign, tmp_path):
    """ISSUE 4: the same mixed-shape campaign dealt round-robin across
    all 8 virtual devices must produce DIGIT-IDENTICAL output — .tim
    checkpoint content byte-for-byte (archive-order checkpoint writes
    make it completion-order-independent) and every assembled TOA
    field — while actually spreading buckets over more than one
    device."""
    files, gmodel = campaign
    tim1, tim8 = tmp_path / "d1.tim", tmp_path / "d8.tim"
    a = stream_wideband_TOAs(files, gmodel, nsub_batch=4,
                             stream_devices=1, tim_out=str(tim1),
                             quiet=True)
    b = stream_wideband_TOAs(files, gmodel, nsub_batch=4,
                             stream_devices=8, tim_out=str(tim8),
                             quiet=True)
    assert b.devices_used > 1, "buckets never left device 0"
    assert b.nfit == a.nfit
    assert tim1.read_bytes() == tim8.read_bytes()
    assert len(a.TOA_list) == len(b.TOA_list) == 12
    for ta, tb in zip(a.TOA_list, b.TOA_list):
        assert ta.archive == tb.archive
        assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)
        assert ta.DM == tb.DM
        assert ta.TOA_error == tb.TOA_error
        assert ta.flags == tb.flags
    assert a.DeltaDM_means == b.DeltaDM_means
    assert a.DeltaDM_errs == b.DeltaDM_errs


def test_stream_multidevice_resume_out_of_order(campaign, tmp_path):
    """Multi-device resume: forge an interrupted checkpoint (first
    archive's block + a torn tail), re-enter with 8 devices — where
    completions land out of archive order — and require the final file
    to equal the uninterrupted single-device run byte-for-byte."""
    files, gmodel = campaign
    tim_full = tmp_path / "full.tim"
    stream_wideband_TOAs(files, gmodel, nsub_batch=4, stream_devices=1,
                         tim_out=str(tim_full), quiet=True)
    lines = tim_full.read_text().splitlines(keepends=True)
    first_done = next(i for i, l in enumerate(lines)
                      if l.startswith("C ppt-done "))
    tim_part = tmp_path / "part.tim"
    tim_part.write_text("".join(lines[:first_done + 1])
                        + "torn 1400.0 55100.12")
    done_arch = lines[first_done].split("C ppt-done ", 1)[1].strip()
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=4,
                               stream_devices=8, tim_out=str(tim_part),
                               quiet=True, resume=True)
    assert res.devices_used > 1
    assert done_arch not in [t.archive for t in res.TOA_list]
    assert tim_part.read_bytes() == tim_full.read_bytes()


@pytest.mark.slow  # ~15 s; the inflight bound is also asserted by the
# serve executor's queue-depth gates in tests/test_serve.py
def test_stream_inflight_bound_exact(campaign):
    """The per-device in-flight bound is EXACT: with max_inflight=1 a
    device's queue never holds two pending dispatches (the old
    append-then-drain executor admitted max_inflight + 1)."""
    files, gmodel = campaign
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=2,
                               max_inflight=1, stream_devices=2,
                               quiet=True)
    assert res.nfit >= 4          # the bound was actually exercised
    assert res.peak_inflight == 1
    assert len(res.TOA_list) == 12


def test_resolve_stream_devices():
    """'auto' = every local device; an int = that prefix; bad values
    error loudly instead of clamping."""
    import jax

    from pulseportraiture_tpu.pipeline.stream import (
        resolve_stream_devices)

    devs = jax.local_devices()
    assert resolve_stream_devices("auto") == list(devs)
    assert resolve_stream_devices(3) == list(devs[:3])
    assert resolve_stream_devices("2") == list(devs[:2])
    assert resolve_stream_devices(devs[1:3]) == list(devs[1:3])
    with pytest.raises(ValueError, match=">= 1"):
        resolve_stream_devices(0)
    with pytest.raises(ValueError, match="exceeds"):
        resolve_stream_devices(len(devs) + 1)
    with pytest.raises(ValueError, match="stream_devices"):
        resolve_stream_devices("bananas")


def test_stream_env_hooks(monkeypatch):
    """PPT_STREAM_DEVICES / PPT_MAX_INFLIGHT ride config.env_overrides
    like the other PPT_* hooks (strict parse, loud errors — a silent
    fallback would quietly invalidate a scaling A/B)."""
    from pulseportraiture_tpu import config

    old = (config.stream_devices, config.stream_max_inflight)
    try:
        monkeypatch.setenv("PPT_STREAM_DEVICES", "auto")
        assert "stream_devices" in config.env_overrides()
        assert config.stream_devices == "auto"
        monkeypatch.setenv("PPT_STREAM_DEVICES", "4")
        config.env_overrides()
        assert config.stream_devices == 4
        for bad in ("0", "-2", "many"):
            monkeypatch.setenv("PPT_STREAM_DEVICES", bad)
            with pytest.raises(ValueError, match="PPT_STREAM_DEVICES"):
                config.env_overrides()
        monkeypatch.delenv("PPT_STREAM_DEVICES")
        monkeypatch.setenv("PPT_MAX_INFLIGHT", "7")
        assert "stream_max_inflight" in config.env_overrides()
        assert config.stream_max_inflight == 7
        for bad in ("0", "nope"):
            monkeypatch.setenv("PPT_MAX_INFLIGHT", bad)
            with pytest.raises(ValueError, match="PPT_MAX_INFLIGHT"):
                config.env_overrides()
    finally:
        config.stream_devices, config.stream_max_inflight = old


@pytest.mark.slow
def test_stream_ckpt_staleness_horizon(tmp_path, monkeypatch):
    """In-order checkpoint writes must not let an early archive stuck
    in a never-filling rare-shape bucket defer later archives' .tim
    durability forever: once it lags CKPT_STALENESS_HORIZON prepared
    archives, all pending buckets force-flush (visible as an extra
    dispatch), and the trigger depends only on the deterministic
    fill/launch sequence so output stays digit-identical across
    device counts."""
    from pulseportraiture_tpu.pipeline import stream as stream_mod
    from pulseportraiture_tpu.io import write_gmodel

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(6):
        nchan = 24 if i == 0 else 32  # archive 0: rare shape
        p = str(tmp_path / f"h{i}.fits")
        make_fake_pulsar(model, PAR, outfile=p, nsub=1, nchan=nchan,
                         nbin=128, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55800 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=500 + i)
        files.append(p)
    monkeypatch.setattr(stream_mod, "CKPT_STALENESS_HORIZON", 3)
    kw = dict(nsub_batch=64, quiet=True)  # nothing fills naturally
    a = stream_wideband_TOAs(files, gmodel, stream_devices=1, **kw)
    b = stream_wideband_TOAs(files, gmodel, stream_devices=8, **kw)
    # horizon fired at archive 3 (the rare bucket + the part-filled
    # common bucket flushed mid-run), tail flushed at end-of-stream:
    # 3 dispatches, not the 2 an end-only flush would fire
    assert a.nfit == b.nfit == 3
    assert len(a.TOA_list) == len(b.TOA_list) == 6
    for ta, tb in zip(a.TOA_list, b.TOA_list):
        assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)
        assert ta.DM == tb.DM


def test_stream_narrowband_multidevice_digit_identical(campaign,
                                                       tmp_path):
    """The narrowband streaming lane shares the multi-device executor:
    1 vs 8 devices must agree on every per-channel TOA field."""
    from pulseportraiture_tpu.pipeline.stream import (
        stream_narrowband_TOAs)

    files, gmodel = campaign
    a = stream_narrowband_TOAs(files[:2], gmodel, nsub_batch=2,
                               stream_devices=1, quiet=True)
    b = stream_narrowband_TOAs(files[:2], gmodel, nsub_batch=2,
                               stream_devices=8, quiet=True)
    assert b.devices_used > 1
    assert len(a.TOA_list) == len(b.TOA_list) > 0
    for ta, tb in zip(a.TOA_list, b.TOA_list):
        assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)
        assert ta.TOA_error == tb.TOA_error
        assert ta.flags == tb.flags


def test_stream_bf16_guard_estimate_tracks_exact_channel_snr(campaign):
    """The streaming lanes' bf16 guard input is snr/sqrt(nchan) — the
    packed result carries no per-channel S/N.  Bias bound, asserted on
    the golden corpus against GetTOAs' exact values (VERDICT r4 #7):

      estimate = rms(channel_snrs) <= max(channel_snrs) <= C * estimate

    The left inequality means the estimate can never OVER-fire (no
    false warnings).  The right is the under-fire bound: rms and max
    differ by at most sqrt(nchan_ok) in the adversarial single-bright-
    channel limit, but for band-limited flux evolution (this corpus:
    ~2x flux gradient plus spectral-index scaling) the measured factor
    is < 2; C = 4 leaves margin while still pinning the guard to fire
    within 4x of the exact trigger point in S/N."""
    files, gmodel = campaign
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25)
    checked = 0
    for t in res.TOA_list:
        iarch = files.index(t.archive)
        isub = t.flags["subint"]
        exact = np.asarray(gt.channel_snrs[iarch][isub])
        exact_max = float(np.nanmax(exact, initial=0.0))
        est = t.flags["snr"] / np.sqrt(t.flags["nch"])
        assert est <= exact_max * (1.0 + 1e-3), (est, exact_max)
        assert exact_max <= 4.0 * est, (est, exact_max)
        checked += 1
    assert checked == len(res.TOA_list) > 0


def test_stream_fused_tim_byte_identical(campaign, tmp_path,
                                         monkeypatch):
    """ISSUE 14: the fused hand-blocked DFT->cross-spectrum program
    (config.fit_fused) is BYTE-identical to the unfused one on both
    payload lanes — raw buckets and the decoded/tscrunch lane — with
    the harmonic window forced on (fusion is windowed-only; without a
    window the knob normalizes onto the unfused program)."""
    from pulseportraiture_tpu import config

    files, gmodel = campaign
    monkeypatch.setattr(config, "fit_harmonic_window", 128)
    for lane, kw in (("raw", {}), ("dec", {"tscrunch": True})):
        tims = {}
        for fused in (False, True):
            monkeypatch.setattr(config, "fit_fused", fused)
            tim = tmp_path / f"{lane}_fused{int(fused)}.tim"
            stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                                 tim_out=str(tim), quiet=True, **kw)
            tims[fused] = tim.read_bytes()
        assert tims[False] == tims[True], lane
        assert len(tims[False]) > 0
