"""Cross-host campaign router over an elastic fleet (ISSUE 10 + the
ISSUE 13 elastic-fleet rework; ROADMAP item 1).

:class:`ToaRouter` shards TOA requests across N warm serving loops
(serve/server.ToaServer behind serve/transport.py transports).  The
R13 router solved placement — least-loaded with sticky per-template
affinity and backpressure retries — over a STATIC host list; this
version adds the rest of the production serving story:

- **Dynamic membership + health state machine** (serve/fleet.py):
  hosts :meth:`add_host`/:meth:`remove_host` at runtime (or through a
  watched ``--fleet-file``), each walking
  ``JOINING -> HEALTHY -> SUSPECT -> DEAD -> REJOINED`` off bounded
  ``stat`` probes (``config.router_probe_ms`` — a hung host feeds
  SUSPECT instead of stalling placement) and submit/transport errors.
  Placement draws only from HEALTHY/SUSPECT members.
- **Exactly-once mid-fit failover**: a DEAD transition with requests
  in flight re-places them on the surviving fleet.  A request whose
  durable ``.tim`` already carries every completion sentinel is
  COLLECTED from the file (serve/codec.read_tim_result) and never
  re-fit; anything else re-dispatches with the dead host in the
  request's ``excluded`` set — the replacement returns its payload
  over the wire and the ROUTER writes its ``.tim`` atomically, so a
  kill-mid-sweep loses zero requests and duplicates zero ``.tim``
  lines even when the "dead" host turns out to be a zombie that
  finishes late (it rewrites the same path with identical bytes,
  fits being deterministic).
- **Hedged requests** (``config.router_hedge_ms`` / ``hedge_ms=``):
  an optional tail-latency policy — a request still unresolved after
  the hedge deadline launches ONE duplicate attempt on the
  least-loaded other eligible host; first completion wins, the loser
  is cancelled at collection (its result is reaped-and-discarded in
  the background so no host pins an abandoned payload).  A
  hedging-armed router routes every ``.tim`` through its own atomic
  writer — no host writes request paths — so two writers never share
  one file.  Byte-identity holds because fits are deterministic —
  bench_router gates hedging-off-vs-on byte-identical on a clean
  fleet.
- **Result-over-the-wire codec lane** (``write_tim='router'``):
  fleets WITHOUT a shared filesystem return the full TOA payload over
  the transport and the ROUTER writes the demuxed ``.tim``
  (serve/codec.write_tim_result) — byte-identical to the shared-fs
  lane, gated.
- **Refit-aware routing** (``quality_refit=True``; ROADMAP item 4
  tail): a collected result that trips the ``config.quality_max_gof``
  / ``quality_min_snr`` gates gets exactly ONE zap-and-refit routed
  to the CURRENT least-loaded HEALTHY host instead of pinned to the
  original lane — the ``refit`` telemetry event carries the host move
  (``host_from`` -> ``host``).  Enable this OR the server-side loop
  (``config.quality_refit``), not both.
- **Multi-tenant QoS plumb**: ``submit(tenant=...)`` rides the wire
  into the per-host AdmissionQueue's weighted-fair tenant lanes
  (serve/queue.py; ``config.serve_tenant_quota`` /
  ``serve_tenant_weight``), and the tenant label lands on
  route_submit/route_done for pptrace's per-tenant latency split.

Telemetry: ``router_start`` once; per request ``route_submit`` /
``route_retry`` / ``route_done`` (R13), plus ``fleet_transition`` per
health edge, ``route_failover`` per dead-host re-placement (action
``collected`` | ``redispatch``), and ``route_hedge`` per hedge launch
— the pptrace "router" and "fleet" sections aggregate exactly these.
"""

import os
import threading
import time

from ..telemetry import log, resolve_tracer
from . import codec
from .cache import content_key, resolve_result_cache
from .fleet import (DEAD, HEALTHY, PLACEABLE_STATES, Fleet,
                    FleetFileWatcher)
from .queue import ServeRejected
from .transport import TransportError

__all__ = ["ToaRouter", "RouteHandle", "ROUTER_BACKOFF_BASE_S",
           "ROUTER_BACKOFF_CAP_S"]

# Backoff after a full fleet pass found no host with admission room:
# base doubles per pass, capped (a campaign client is patient, but an
# unbounded doubling would look like a hang).
ROUTER_BACKOFF_BASE_S = 0.05
ROUTER_BACKOFF_CAP_S = 2.0
# Per-attempt result poll slices: the SHORT slice applies while the
# attempt set can still change (hedging armed, or several attempts
# racing) so hedge launches and failover swaps are noticed promptly;
# the LONG slice applies to a settled single attempt — a transport
# failure interrupts it on its own, so the only cost of a longer
# slice there is how late a cross-thread local resolution is noticed.
ROUTER_POLL_S = 0.1
ROUTER_POLL_SETTLED_S = 0.25
# Bound on one routed zap-and-refit round trip: a refit rides INSIDE
# the original request's collection, so an unbounded wait would wedge
# the client past any timeout it asked for; a refit that cannot
# finish in this long serves the ORIGINAL result loudly instead.
ROUTER_REFIT_TIMEOUT_S = 600.0
# Cadence of the orphan reaper (hedge losers): their results must be
# collected-and-discarded or the losing host's handle table would pin
# every abandoned payload for the connection's lifetime.
ROUTER_REAP_S = 0.25


class RouteHandle:
    """One routed request: its submit spec (kept so the router can
    re-place it), its live placement attempts (primary + at most one
    hedge), and the blocking :meth:`result`."""

    def __init__(self, router, host, handle, name, n_archives,
                 t_submit, spec):
        self._router = router
        self.host = host            # current primary member
        self._handle = handle
        self.name = name
        self.n_archives = n_archives
        self._t_submit = t_submit
        self.spec = spec            # dict: datafiles/modelfile/tim_out/
        #                                   options/tenant
        # live attempts: [(member, handle, router_tim)] — router_tim
        # marks attempts whose .tim the ROUTER writes from the decoded
        # payload at collection (codec lane, hedges, failover
        # replacements) instead of the serving host
        self.attempts = [(host, handle, spec.get("host_tim") is None
                          and spec.get("tim_out") is not None)]
        self.excluded = set()       # labels this request must avoid
        self._collected = False     # accounting/telemetry fired
        self._done = threading.Event()
        self._result = None
        self._error = None
        self._hedged = False
        self._redispatching = False
        self._refit_done = False

    @property
    def tim_out(self):
        return self.spec.get("tim_out")

    @property
    def datafiles(self):
        return self.spec["datafiles"]

    def done(self):
        return self._done.is_set()

    def wait(self, timeout=None):
        """Non-raising poll (the remote-transport primitive — lets a
        TransportServer front a router the same way it fronts a
        server); True when result() will not block."""
        return self._done.wait(timeout)

    def result(self, timeout=None):
        """Block for the per-request DataBunch (the one-shot driver's
        result shape) or raise the request's failure; either way the
        router's load accounting and route_done telemetry fire exactly
        once.  A TimeoutError leaves the request collectable."""
        return self._router._await(self, timeout)


class ToaRouter:
    """Shard TOA requests across an elastic fleet of warm serving
    loops.

    transports: transports (InProcTransport / SocketTransport) or
    'host:port' strings; may be empty when ``fleet_file`` supplies the
    membership.  retry_max: total placement attempts per request
    (None = ``config.router_retry_max``).  probe_ms: stat-probe
    deadline (None = ``config.router_probe_ms``).  hedge_ms: hedge
    launch deadline in ms (None = ``config.router_hedge_ms``; that
    default is None = off).  write_tim: 'host' (serving host writes
    each request's .tim — the shared-filesystem lane) or 'router'
    (the codec lane: the ROUTER writes the .tim from the decoded
    payload).  quality_refit: route ONE zap-and-refit of gate-tripping
    archives to the least-loaded HEALTHY host.  fleet_file: watched
    host list (serve/fleet.FleetFileWatcher).  telemetry: trace path
    or shared Tracer.  cost_model: placement cost = archives / each
    host's measured TOAs/s (True, the default — degrades exactly to
    least-loaded while throughput is unmeasured); False forces raw
    least-loaded (the A/B arm).

    Thread model: ``submit`` and ``RouteHandle.result`` are safe from
    any thread (one lock guards placement/handle state; probes and
    transport I/O run outside it); each host's own thread-safety is
    the transport's.
    """

    def __init__(self, transports=(), retry_max=None, telemetry=None,
                 quiet=True, probe_ms=None, hedge_ms=None,
                 write_tim="host", quality_refit=False,
                 fleet_file=None, fleet_poll_s=1.0,
                 result_cache=None, cache_dir=None, cost_model=None,
                 metrics=None, slo_targets=None):
        from .. import config

        transports = list(transports)
        if not transports and not fleet_file:
            raise ValueError("ToaRouter: no host endpoints")
        if write_tim not in ("host", "router"):
            raise ValueError(
                f"ToaRouter: write_tim must be 'host' (shared "
                f"filesystem) or 'router' (codec lane), got "
                f"{write_tim!r}")
        if retry_max is None:
            retry_max = config.router_retry_max
        self.retry_max = max(1, int(retry_max))
        if hedge_ms is None:
            hedge_ms = config.router_hedge_ms
        self.hedge_s = None if hedge_ms is None \
            else max(0.0, float(hedge_ms)) / 1e3
        self.write_tim = write_tim
        self.quality_refit = bool(quality_refit)
        # backend-aware placement cost (ISSUE 19): True (default)
        # divides each host's load by its measured TOAs/s from the
        # stat wire, so a heterogeneous fleet stops assigning equal
        # shares to unequal machines; False is the raw least-loaded
        # ordering (the A/B arm benchmarks/bench_autotune.py runs).
        # With no throughput measured anywhere the cost model degrades
        # EXACTLY to least-loaded, so the default is safe on any fleet.
        self.cost_model = True if cost_model is None else bool(cost_model)
        self.quiet = quiet
        self.tracer, self._own_tracer = resolve_tracer(telemetry,
                                                       run="pproute")
        # content-addressed result cache (ISSUE 17): a router-side hit
        # short-circuits placement entirely — the request never
        # touches a host.  Resolved from the config tri-state (off by
        # default; 'auto' engages only when a cache_dir is set).
        self.cache = resolve_result_cache(tracer=self.tracer,
                                          cache_dir=cache_dir,
                                          mode=result_cache)
        self.cache_hits = 0
        self.cache_bytes = 0
        # live observability plane (ISSUE 20): router-side streaming
        # counters + route-latency histograms, and per-tenant SLO
        # burn-rate tracking over the END-TO-END routed latency (the
        # number a client actually experiences, failovers and hedges
        # included).  None reads config.metrics / config.slo_targets.
        from ..obs.metrics import MetricsRegistry
        from ..obs.slo import SloTracker

        want_metrics = (config.metrics if metrics is None
                        else bool(metrics))
        self._metrics = MetricsRegistry() if want_metrics else None
        targets = (config.slo_targets if slo_targets is None
                   else slo_targets)
        self._slo = SloTracker(targets) if targets else None
        self._lock = threading.Lock()
        self._affinity = {}   # abspath(modelfile) -> FleetMember
        self._inflight = {}   # label -> set of RouteHandle
        self._orphans = []    # (member, handle): hedge losers to reap
        self._reaper = None
        self._closed = False
        self.fleet = Fleet(tracer=self.tracer, probe_ms=probe_ms,
                           on_dead=self._failover_host, quiet=quiet)
        for t in transports:
            self.fleet.add(t)
        self._watcher = None
        if fleet_file:
            self._watcher = FleetFileWatcher(self, fleet_file,
                                             poll_s=fleet_poll_s,
                                             quiet=quiet)
            self._watcher.resync()
            self._watcher.start()
        if self.tracer.enabled:
            self.tracer.emit("router_start",
                             n_hosts=len(self.fleet.members()),
                             hosts=self.host_labels(),
                             retry_max=self.retry_max)

    # ------------------------------------------------------------------
    # membership surface
    # ------------------------------------------------------------------

    @property
    def hosts(self):
        """Current members (any state) — kept for R13 callers."""
        return self.fleet.members()

    def host_labels(self):
        return [m.label for m in self.fleet.members()]

    def add_host(self, transport_or_address, label=None):
        """Join one endpoint at runtime (JOINING; promoted by its
        first successful probe).  Returns the member's label."""
        if self._closed:
            raise RuntimeError("ToaRouter is closed")
        return self.fleet.add(transport_or_address, label=label).label

    def remove_host(self, label):
        """Leave one endpoint gracefully: no new placements; requests
        already in flight there keep collecting.  True when the label
        was a member."""
        member = self.fleet.remove(label)
        if member is None:
            return False
        with self._lock:
            for key in [k for k, v in self._affinity.items()
                        if v is member]:
                del self._affinity[key]
        return True

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _costs(self, loads):
        """Placement costs from raw archive loads (ISSUE 19): cost =
        load / relative host speed, where speed is the host's measured
        TOAs/s normalized by the fastest measured member (so a host
        half as fast carries twice the cost per queued archive).
        Hosts with no measurement yet — cold, or pre-ISSUE-19 peers —
        count as fleet-fast, and with NO measurement anywhere (or
        cost_model off) the costs ARE the loads: exact least-loaded
        degradation.  Returns (costs, speeds); speeds convert an
        archive count into cost units (the affinity-yield
        threshold)."""
        speeds = {m: 1.0 for m in loads}
        if self.cost_model:
            rates = {m: m.toas_per_s for m in loads
                     if m.toas_per_s is not None and m.toas_per_s > 0}
            if rates:
                top = max(rates.values())
                for m, r in rates.items():
                    speeds[m] = max(r / top, 1e-6)
        costs = {m: loads[m] / speeds[m] for m in loads}
        return costs, speeds

    def _rank(self, modelfile, n_archives, excluded=frozenset(),
              use_affinity=True):
        """Placeable hosts to try, best first: the affinity host for
        this template leads while placing there would not leave it
        strictly more costly than the cheapest alternative; then
        cheapest-cost order (cost = load / measured relative speed —
        raw least-loaded when the cost model is off or unmeasured).
        use_affinity=False ranks purely by cost (failover replacements
        and routed refits must move OFF the original lane, not stick
        to it).  Loads come from the fleet's BOUNDED probe pass
        (cached while a probe is outstanding) so a hung host can never
        stall a placement; the lock guards only the affinity read."""
        loads = self.fleet.probe_all()
        loads = {m: v for m, v in loads.items()
                 if m.label not in excluded}
        if not loads:
            return [], False
        costs, speeds = self._costs(loads)
        by_cost = sorted(costs, key=lambda m: (costs[m], m.index))
        if not use_affinity:
            return by_cost, False
        with self._lock:
            aff = self._affinity.get(modelfile)
        if aff is not None and aff in costs and by_cost[0] is not aff \
                and costs[aff] - costs[by_cost[0]] \
                < n_archives / speeds[aff]:
            by_cost.remove(aff)
            by_cost.insert(0, aff)
            return by_cost, True
        return by_cost, aff is not None and by_cost[0] is aff

    def _place(self, datafiles, modelfile, tim_out, name, options,
               tenant, excluded=frozenset(), attempt0=0,
               affinity=True, trace_id=None):
        """The placement loop: try ranked hosts, retry retryable
        backpressure / unreachable hosts up to retry_max attempts with
        capped exponential backoff between full fleet passes; feed the
        health machine on transport errors.  Returns (member, handle,
        attempt, sticky) or raises the last failure."""
        n_archives = len(datafiles)
        mkey = os.path.abspath(str(modelfile))
        attempt = attempt0
        backoff = ROUTER_BACKOFF_BASE_S
        last_err = None
        while attempt < self.retry_max:
            ranked, sticky = self._rank(mkey, n_archives,
                                        excluded=excluded,
                                        use_affinity=affinity)
            if not ranked:
                # an empty pass still consumes an attempt, or an
                # all-DEAD fleet would spin here forever
                attempt += 1
                last_err = RuntimeError(
                    "ToaRouter: no placeable hosts (fleet: "
                    f"{self.fleet.snapshot()})")
            for host in ranked:
                if attempt >= self.retry_max:
                    break
                attempt += 1
                try:
                    handle = host.transport.submit(
                        datafiles, modelfile, tim_out=tim_out,
                        name=name, options=options, tenant=tenant,
                        trace_id=trace_id)
                except ServeRejected as e:
                    if not e.retryable:
                        raise  # could never fit anywhere: caller's bug
                    last_err = e
                except TransportError as e:
                    last_err = e
                    self.fleet.record_error(host, f"submit: {e}")
                else:
                    self.fleet.record_ok(host)
                    return (host, handle, attempt,
                            bool(sticky and host is ranked[0]))
                if self.tracer.enabled:
                    self.tracer.emit(
                        "route_retry", req=name, host=host.label,
                        attempt=attempt,
                        backoff_s=round(backoff, 4),
                        error=str(last_err))
                sticky = False  # a rejecting affinity host lost its turn
            # a full pass over the fleet found no room: back off so the
            # warm loops can drain, then re-rank (loads have moved)
            if attempt < self.retry_max:
                time.sleep(backoff)
                backoff = min(backoff * 2.0, ROUTER_BACKOFF_CAP_S)
        raise last_err if last_err is not None else RuntimeError(
            "ToaRouter: submit failed with no recorded error")

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               tenant=None, trace_id=None, **options):
        """Place one request on the fleet (thread-safe); returns a
        :class:`RouteHandle`.  Retries retryable backpressure and
        unreachable hosts up to ``retry_max`` placements with capped
        exponential backoff between full fleet passes; raises the last
        failure when the budget is exhausted, and terminal
        ``ServeRejected`` (retryable=False) immediately.  ``tenant``
        labels the request for the per-host QoS lanes.  ``trace_id``
        (None = mint one here) is the distributed-tracing context: it
        crosses the wire on every placement — hedges, failovers, and
        refits included — so ``pptrace merge`` can stitch the
        request's life across the router trace and N host traces."""
        from ..obs.trace import new_trace_id
        from ..pipeline.toas import _is_metafile, _read_metafile

        if self._closed:
            raise RuntimeError("ToaRouter is closed")
        if isinstance(datafiles, str):
            datafiles = (_read_metafile(datafiles)
                         if _is_metafile(datafiles) else [datafiles])
        datafiles = list(datafiles)
        n_archives = len(datafiles)
        mkey = os.path.abspath(str(modelfile))
        # codec lane: the serving host never writes — the router
        # demuxes the decoded payload at collection.  Hedging-armed
        # routers route EVERY .tim through the router too: the losing
        # primary of a hedged request would otherwise truncate-rewrite
        # the path after the winner's file was already read back
        host_tim = tim_out if (self.write_tim == "host"
                               and self.hedge_s is None) else None
        t0 = time.monotonic()
        trace_id = str(trace_id) if trace_id else new_trace_id()
        cache_key = None
        if self.cache is not None:
            hit_rh, cache_key = self._cache_lookup(
                datafiles, modelfile, tim_out, name, tenant, options,
                n_archives, t0, trace_id)
            if hit_rh is not None:
                return hit_rh
        host, handle, attempt, sticky = self._place(
            datafiles, modelfile, host_tim, name, options, tenant,
            trace_id=trace_id)
        spec = dict(datafiles=datafiles, modelfile=str(modelfile),
                    tim_out=tim_out, options=dict(options),
                    tenant=tenant, host_tim=host_tim,
                    trace_id=trace_id)
        rh = RouteHandle(self, host, handle,
                         name if name is not None
                         else getattr(handle, "name", None),
                         n_archives, t0, spec)
        rh._cache_key = cache_key
        with self._lock:
            host.outstanding += n_archives
            host.n_requests += 1
            host.n_archives += n_archives
            self._affinity[mkey] = host
            self._inflight.setdefault(host.label, set()).add(rh)
        if self._metrics is not None:
            self._metrics.inc("route_submits")
        if self.tracer.enabled:
            self.tracer.emit(
                "route_submit", req=rh.name, host=host.label,
                n_archives=n_archives, attempt=attempt,
                affinity=bool(sticky), tenant=tenant,
                trace_id=trace_id)
        return rh

    def _cache_lookup(self, datafiles, modelfile, tim_out, name,
                      tenant, options, n_archives, t0, trace_id=None):
        """Content-addressed lookup before placement (ISSUE 17).
        Returns ``(hit_handle, key)``: on a hit, a PRE-RESOLVED
        :class:`RouteHandle` — result set, ``_done`` set,
        ``_collected`` marked, NO attempts, never registered in
        ``_inflight`` — so ``_await`` returns on its first done-check
        and the failover/hedge machinery can never find (let alone
        re-place) an already-served request.  On a miss, ``(None,
        key)`` so the placed request populates the store at
        collection.  The request's ``.tim`` is served as an atomic
        byte copy of the stored entry: hit bytes == fresh-fit bytes by
        construction."""
        try:
            key = content_key(list(datafiles) + [modelfile], options)
        except OSError:
            # unreadable input: the placement path raises the real
            # error through the normal channel
            return None, None
        ent = self.cache.get_result(key, datafiles)
        if ent is None:
            if self.tracer.enabled:
                self.tracer.emit("cache_miss", req=name,
                                 source="router", tenant=tenant,
                                 trace_id=trace_id)
            return None, key
        result, entry_path, n_bytes = ent
        if tim_out:
            codec.copy_tim_atomic(entry_path, tim_out)
        result.tim_out = tim_out
        spec = dict(datafiles=list(datafiles),
                    modelfile=str(modelfile), tim_out=tim_out,
                    options=dict(options), tenant=tenant,
                    host_tim=None)
        rh = RouteHandle(self, None, None, name, n_archives, t0, spec)
        rh.attempts = []
        rh._collected = True
        rh._result = result
        self.cache_hits += 1
        self.cache_bytes += n_bytes
        wall = time.monotonic() - t0
        if self._metrics is not None:
            self._metrics.inc("route_submits")
            self._metrics.inc("route_done")
            self._metrics.inc("cache_hits")
            self._metrics.inc("cache_bytes", n_bytes)
            self._metrics.observe("route_latency_s", wall)
        if self._slo is not None:
            breach = self._slo.observe(tenant or "default", wall)
            if breach is not None and self.tracer.enabled:
                self.tracer.emit("slo_breach", source="router",
                                 **breach)
        if self.tracer.enabled:
            self.tracer.emit("route_submit", req=name, host=None,
                             n_archives=n_archives, attempt=0,
                             affinity=False, tenant=tenant,
                             trace_id=trace_id)
            self.tracer.emit("cache_hit", req=name, bytes=n_bytes,
                             source="router", tenant=tenant,
                             trace_id=trace_id)
            self.tracer.counter("cache_hit")
            self.tracer.emit("route_done", req=name, host=None,
                             wall_s=round(wall, 6),
                             n_toas=len(result.TOA_list), error=None,
                             tenant=tenant, hedged=False,
                             failover=None, trace_id=trace_id)
        rh._done.set()
        return rh, key

    # blocking conveniences mirroring serve.ToaClient -----------------

    def get_TOAs(self, datafiles, modelfile, timeout=None,
                 tim_out=None, name=None, tenant=None, **options):
        """Submit and wait (the one-shot driver's return shape)."""
        return self.submit(datafiles, modelfile, tim_out=tim_out,
                           name=name, tenant=tenant,
                           **options).result(timeout)

    def map(self, specs, timeout=None, return_errors=False):
        """Submit many, then wait for all, in spec order.  specs:
        (datafiles, modelfile[, kwargs]) tuples.  With
        return_errors=True a failed request's exception object takes
        its slot instead of poisoning the batch (siblings still
        return); default re-raises the first failure AFTER every
        sibling resolved, so one bad request never strands the rest
        (serve.client.collect_results — the same contract as
        ToaClient.map)."""
        from .client import collect_results

        handles = [self.submit(s[0], s[1],
                               **(dict(s[2]) if len(s) > 2 else {}))
                   for s in specs]
        return collect_results(handles, timeout, return_errors)

    # ------------------------------------------------------------------
    # collection: poll loop with hedging + failover awareness
    # ------------------------------------------------------------------

    def _await(self, rh, timeout):
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while True:
            if rh._done.is_set():
                if rh._error is not None:
                    raise rh._error
                return rh._result
            if (self.hedge_s is not None and not rh._hedged
                    and time.monotonic() - rh._t_submit
                    >= self.hedge_s):
                self._launch_hedge(rh)
            with self._lock:
                attempts = list(rh.attempts)
            if not attempts:
                # a failover is re-placing this request on another
                # thread; yield briefly and re-check
                time.sleep(0.01)
            # a collected request (incl. a cache hit, which resolves
            # pre-placed with no attempts) is SETTLED: the slow poll
            # suffices and nothing here may re-place it
            settled = rh._collected or (len(attempts) == 1
                                        and self.hedge_s is None)
            slice_s = ROUTER_POLL_SETTLED_S if settled \
                else ROUTER_POLL_S
            for host, handle, router_tim in attempts:
                left = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                wait = slice_s if left is None \
                    else min(slice_s, left)
                try:
                    res = host.transport.result(handle, wait)
                except TimeoutError:
                    continue  # not resolved: keep it accounted
                except TransportError as e:
                    self.fleet.record_error(host, f"result: {e}")
                    self._failover_attempt(rh, host, handle, e)
                    break  # attempts changed: re-snapshot
                except Exception as e:
                    # request-level failure ON the host: deterministic,
                    # terminal (the failing handle was already evicted
                    # by its transport)
                    self._finish(rh, host, error=e, win_handle=handle)
                    raise
                else:
                    return self._finish(rh, host, result=res,
                                        router_tim=router_tim,
                                        win_handle=handle)
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"{rh.name}: no result within {timeout} s")

    def _unaccount(self, rh, win_handle=None):
        """Release load accounting for every live attempt of ``rh``;
        losing attempts (anything but ``win_handle``) go to the
        orphan reaper so their completed server-side results are
        collected-and-discarded instead of pinned forever (caller
        holds the lock)."""
        for host, handle, _rt in rh.attempts:
            host.outstanding = max(0, host.outstanding
                                   - rh.n_archives)
            self._inflight.get(host.label, set()).discard(rh)
            if handle != win_handle:
                self._orphans.append((host, handle))
        rh.attempts = []
        if self._orphans and not self._closed and (
                self._reaper is None or not self._reaper.is_alive()):
            self._reaper = threading.Thread(target=self._reap_loop,
                                            name="ppt-route-reap",
                                            daemon=True)
            self._reaper.start()

    def _reap_loop(self):
        """Collect-and-discard abandoned attempts (hedge losers) in
        the background: eviction from the transports' handle tables
        happens at result-collection, so an uncollected loser would
        pin its whole payload for the connection's lifetime."""
        while not self._closed:
            with self._lock:
                orphans = list(self._orphans)
            if not orphans:
                return
            for host, handle in orphans:
                try:
                    host.transport.result(handle, 0.05)
                except TimeoutError:
                    continue  # still running: keep reaping
                except Exception:
                    pass      # dead host / failed request: forget it
                with self._lock:
                    try:
                        self._orphans.remove((host, handle))
                    except ValueError:
                        pass
            time.sleep(ROUTER_REAP_S)

    def _finish(self, rh, winner, result=None, error=None,
                router_tim=False, action=None, win_handle=None):
        """Resolve one request exactly once: release accounting for
        every attempt, reconcile the ``.tim`` (the router writes the
        winner's file — atomically — whenever the winning attempt did
        not carry the host-side path: the codec lane, hedge winners,
        failover replacements), run the optional routed refit, emit
        route_done."""
        with self._lock:
            already = rh._collected
            if not already:
                rh._collected = True
                self._unaccount(rh, win_handle=win_handle)
        if already:
            # lost the race (hedge twin resolved first): hand the
            # recorded outcome back once it lands
            rh._done.wait()
            if rh._error is not None:
                raise rh._error
            return rh._result
        hedged = rh._hedged
        if result is not None and router_tim and rh.tim_out:
            try:
                codec.write_tim_result(result, rh.tim_out)
                result.tim_out = rh.tim_out
            except (OSError, ValueError) as e:
                error, result = RuntimeError(
                    f"{rh.name}: result collected but its .tim could "
                    f"not be written at {rh.tim_out}: {e}"), None
        if result is not None and self.quality_refit and winner:
            try:
                result = self._maybe_refit(rh, winner, result)
            except Exception as e:
                # the refit is best-effort: a broken refit serves the
                # ORIGINAL result loudly, never wedges the request
                log(f"routed refit of {rh.name!r} failed: "
                    f"{type(e).__name__}: {e}; serving the original "
                    "fit", quiet=False, level="warn", tracer=None)
        if (result is not None and error is None
                and self.cache is not None
                and getattr(rh, "_cache_key", None)):
            # populate the content-addressed store with the final
            # (post-refit) result; put_result itself refuses partial
            # or tim-recovered payloads
            stored = self.cache.put_result(rh._cache_key, result)
            if stored and self.tracer.enabled:
                self.tracer.emit("cache_store", key=rh._cache_key,
                                 bytes=stored)
        rh._result = result
        rh._error = error
        wall_s = time.monotonic() - rh._t_submit
        tenant = rh.spec.get("tenant")
        if self._metrics is not None:
            self._metrics.inc("route_done")
            if error is not None:
                self._metrics.inc("route_failed")
            if hedged:
                self._metrics.inc("route_hedged")
            if action is not None:
                self._metrics.inc(f"route_failover_{action}")
            if result is not None:
                self._metrics.inc("toas_total",
                                  len(result.TOA_list or ()))
            self._metrics.observe("route_latency_s", wall_s)
        if self._slo is not None:
            breach = self._slo.observe(
                tenant or "default",
                wall_s if error is None else float("inf"))
            if breach is not None and self.tracer.enabled:
                self.tracer.emit("slo_breach", source="router",
                                 **breach)
        if self.tracer.enabled:
            self.tracer.emit(
                "route_done", req=rh.name,
                host=winner.label if winner is not None else None,
                wall_s=round(wall_s, 6),
                n_toas=len(result.TOA_list) if result else 0,
                error=str(error) if error else None,
                tenant=tenant, hedged=bool(hedged),
                failover=action,
                trace_id=rh.spec.get("trace_id"))
        rh._done.set()
        if error is not None:
            raise error
        return result

    # ------------------------------------------------------------------
    # hedging
    # ------------------------------------------------------------------

    def _launch_hedge(self, rh):
        """One duplicate attempt on the least-loaded other eligible
        host (best-effort: a fleet with nowhere else to place simply
        does not hedge).  A hedge attempt NEVER writes host-side: its
        payload returns over the wire and the router writes the
        winner's .tim atomically at collection, so two hosts cannot
        interleave writes on one path (the slow primary may still
        rewrite the same path later — with identical bytes, fits
        being deterministic)."""
        with self._lock:
            if rh._hedged or rh._collected or not rh.attempts:
                return
            rh._hedged = True   # one hedge per request, even on failure
            primary = rh.attempts[0][0]
        loads = self.fleet.probe_all()
        costs, _speeds = self._costs(loads)
        cands = [m for m in sorted(costs,
                                   key=lambda m: (costs[m], m.index))
                 if m is not primary and m.label not in rh.excluded]
        if not cands:
            return
        host = cands[0]
        try:
            handle = host.transport.submit(
                rh.datafiles, rh.spec["modelfile"], tim_out=None,
                name=rh.name, options=rh.spec["options"],
                tenant=rh.spec.get("tenant"),
                trace_id=rh.spec.get("trace_id"))
        except (ServeRejected, TransportError) as e:
            log(f"hedge of {rh.name!r} on {host.label} not placed: "
                f"{e}", quiet=self.quiet, level="warn", tracer=None)
            if isinstance(e, TransportError):
                self.fleet.record_error(host, f"hedge submit: {e}")
            return
        with self._lock:
            if rh._collected:
                return  # resolved while we were placing: abandon it
            rh.attempts.append((host, handle, True))
            host.outstanding += rh.n_archives
            self._inflight.setdefault(host.label, set()).add(rh)
        if self._metrics is not None:
            self._metrics.inc("hedges_launched")
        if self.tracer.enabled:
            self.tracer.emit("route_hedge", req=rh.name,
                             primary=primary.label, host=host.label,
                             trace_id=rh.spec.get("trace_id"))

    # ------------------------------------------------------------------
    # failover
    # ------------------------------------------------------------------

    def _failover_host(self, member):
        """Fleet callback: ``member`` went DEAD.  Re-place every
        request in flight on it (exactly once each)."""
        with self._lock:
            handles = list(self._inflight.get(member.label, ()))
        for rh in handles:
            handle = None
            with self._lock:
                for h, k, _s in rh.attempts:
                    if h is member:
                        handle = k
                        break
            if handle is not None:
                self._failover_attempt(rh, member, handle,
                                       TransportError(
                                           f"{member.label} is DEAD"))

    def _failover_attempt(self, rh, member, handle, err):
        """One attempt of ``rh`` died with its host.  Idempotent: the
        awaiting client thread and the fleet's on_dead callback may
        both arrive here.  Collect from the durable .tim when every
        sentinel landed; otherwise re-dispatch with the dead host
        excluded; resolve the request with the error only when neither
        is possible."""
        with self._lock:
            live = [(h, k, s) for h, k, s in rh.attempts
                    if h is member and k == handle]
            if not live or rh._collected:
                return
            rh.attempts.remove(live[0])
            rh.excluded.add(member.label)
            member.outstanding = max(0, member.outstanding
                                     - rh.n_archives)
            self._inflight.get(member.label, set()).discard(rh)
            if rh.attempts:
                return  # the hedge twin races on
            if rh._redispatching:
                return
            rh._redispatching = True
        try:
            # exactly-once: work whose .tim sentinels all landed is
            # durable — collect it, never re-fit
            if (self.write_tim == "host" and rh.tim_out
                    and codec.tim_complete(rh.tim_out, rh.datafiles)):
                res = codec.read_tim_result(rh.tim_out)
                if self.tracer.enabled:
                    self.tracer.emit("route_failover", req=rh.name,
                                     dead_host=member.label,
                                     action="collected", host=None,
                                     trace_id=rh.spec.get("trace_id"))
                log(f"failover: {rh.name!r} collected from its "
                    f"durable .tim after {member.label} died "
                    "(no re-fit)", quiet=self.quiet, level="warn",
                    tracer=None)
                self._finish(rh, None, result=res, action="collected")
                return
            # the replacement never writes host-side: if the "dead"
            # host is actually alive and still serving the original
            # attempt, two hosts must not interleave writes on one
            # path — the router writes the replacement's .tim from
            # the decoded payload at collection instead (and a zombie
            # completion later rewrites the same path with IDENTICAL
            # bytes, fits being deterministic)
            host, handle2, attempt, _sticky = self._place(
                rh.datafiles, rh.spec["modelfile"], None, rh.name,
                rh.spec["options"], rh.spec.get("tenant"),
                excluded=frozenset(rh.excluded), affinity=False,
                trace_id=rh.spec.get("trace_id"))
            with self._lock:
                rh.attempts.append((host, handle2,
                                    rh.tim_out is not None))
                rh.host = host
                rh._handle = handle2
                host.outstanding += rh.n_archives
                host.n_requests += 1
                host.n_archives += rh.n_archives
                self._inflight.setdefault(host.label, set()).add(rh)
                rh._redispatching = False
            if self.tracer.enabled:
                self.tracer.emit("route_failover", req=rh.name,
                                 dead_host=member.label,
                                 action="redispatch", host=host.label,
                                 attempt=attempt,
                                 trace_id=rh.spec.get("trace_id"))
            log(f"failover: {rh.name!r} re-dispatched to "
                f"{host.label} after {member.label} died "
                f"(excluded: {sorted(rh.excluded)})",
                quiet=self.quiet, level="warn", tracer=None)
        except Exception as e:
            if self.tracer.enabled:
                self.tracer.emit("route_failover", req=rh.name,
                                 dead_host=member.label,
                                 action="failed", host=None,
                                 trace_id=rh.spec.get("trace_id"))
            try:
                self._finish(rh, None, error=e, action="failed")
            except Exception:
                pass  # the awaiting client re-raises from rh._error

    # ------------------------------------------------------------------
    # refit-aware routing (ROADMAP item 4 tail)
    # ------------------------------------------------------------------

    def _gate_trips(self, toas):
        from .. import config

        import numpy as np

        for t in toas:
            gof = t.flags.get("gof")
            if gof is not None and np.isfinite(gof) \
                    and float(gof) > config.quality_max_gof:
                return True
            if config.quality_min_snr > 0.0:
                snr = t.flags.get("snr")
                if snr is not None and np.isfinite(snr) \
                        and float(snr) < config.quality_min_snr:
                    return True
        return False

    def _worst_gof(self, toas):
        import numpy as np

        gofs = [float(t.flags["gof"]) for t in toas
                if t.flags.get("gof") is not None
                and np.isfinite(t.flags["gof"])]
        return max(gofs) if gofs else None

    def _maybe_refit(self, rh, winner, res):
        """Routed quality loop: archives of a collected result that
        trip the gate get exactly ONE zap-and-refit request, placed on
        the current least-loaded HEALTHY host (affinity ignored — the
        point is to move OFF the original lane when it is loaded);
        the refit TOAs replace the originals in the demux and the
        request .tim is rewritten.  Every fallback serves the original
        result LOUDLY."""
        if rh._refit_done:
            return res
        rh._refit_done = True
        try:
            groups = list(codec.iter_archive_toas(res))
        except ValueError as e:
            log(f"routed refit of {rh.name!r} skipped: {e}",
                quiet=False, level="warn", tracer=None)
            return res
        trips = [f for f, toas in groups
                 if toas and self._gate_trips(toas)]
        if not trips:
            return res
        from ..io.psrfits import load_data
        from ..pipeline.zap import get_zap_channels, resolve_zap_nstd

        gof_before = {f: self._worst_gof(dict(groups)[f])
                      for f in trips}
        zap_map = {}
        for f in trips:
            try:
                d = load_data(f, dedisperse=False, dededisperse=True,
                              tscrunch=rh.spec["options"].get(
                                  "tscrunch", False),
                              pscrunch=True, quiet=True)
                lists = get_zap_channels(
                    d, nstd=resolve_zap_nstd(None),
                    tracer=self.tracer)
            except Exception as e:
                log(f"routed refit of {f} (request {rh.name!r}) not "
                    f"possible: {type(e).__name__}: {e}; serving the "
                    "original fit", quiet=False, level="warn",
                    tracer=None)
                continue
            if sum(len(z) for z in lists):
                zap_map[f] = lists
            else:
                if self.tracer.enabled:
                    self.tracer.emit(
                        "refit", req=rh.name, datafile=f,
                        n_channels=0, gof_before=gof_before[f],
                        gof_after=gof_before[f], improved=False,
                        host_from=winner.label, host=winner.label)
                log(f"routed refit of {f} (request {rh.name!r}) not "
                    "possible: the median algorithm flagged no "
                    "channels; serving the original fit",
                    quiet=False, level="warn", tracer=None)
        if not zap_map:
            return res
        # least-loaded HEALTHY placement, affinity OFF — the re-place-
        # off-the-original-lane rule this satellite exists for
        loads = self.fleet.probe_all()
        costs, _speeds = self._costs(loads)
        healthy = [m for m in sorted(costs,
                                     key=lambda m: (costs[m], m.index))
                   if m.state == HEALTHY]
        if not healthy:
            log(f"routed refit of {rh.name!r}: no HEALTHY host to "
                "re-place on; serving the original fit", quiet=False,
                level="warn", tracer=None)
            return res
        host2 = healthy[0]
        refit_files = sorted(zap_map)
        try:
            with self._lock:
                host2.outstanding += len(refit_files)
                host2.n_requests += 1
                host2.n_archives += len(refit_files)
            try:
                handle = host2.transport.submit(
                    refit_files, rh.spec["modelfile"], tim_out=None,
                    name=f"{rh.name}:refit",
                    options={**rh.spec["options"],
                             "zap_channels": zap_map},
                    tenant=rh.spec.get("tenant"),
                    trace_id=rh.spec.get("trace_id"))
                # BOUNDED: the refit rides inside the original
                # request's collection — a hung refit host must fall
                # back to serving the original, never wedge the client
                res2 = host2.transport.result(
                    handle, ROUTER_REFIT_TIMEOUT_S)
            finally:
                with self._lock:
                    host2.outstanding = max(
                        0, host2.outstanding - len(refit_files))
        except Exception as e:
            log(f"routed refit of {rh.name!r} on {host2.label} "
                f"failed: {type(e).__name__}: {e}; serving the "
                "original fit", quiet=False, level="warn", tracer=None)
            return res
        new_groups = dict(codec.iter_archive_toas(res2))
        pos2 = {f: i for i, f in enumerate(res2.order)}
        TOA_list = []
        for i, (f, toas) in enumerate(groups):
            if f in new_groups:
                toas = new_groups[f]
                j = pos2[f]
                res.DM0s[i] = res2.DM0s[j]
                res.DeltaDM_means[i] = res2.DeltaDM_means[j]
                res.DeltaDM_errs[i] = res2.DeltaDM_errs[j]
                gof_after = self._worst_gof(toas)
                n_ch = sum(len(z) for z in zap_map[f])
                improved = (gof_after is not None
                            and gof_before[f] is not None
                            and gof_after < gof_before[f])
                if self.tracer.enabled:
                    self.tracer.emit(
                        "refit", req=rh.name, datafile=f,
                        n_channels=int(n_ch),
                        gof_before=gof_before[f],
                        gof_after=gof_after,
                        improved=bool(improved),
                        host_from=winner.label, host=host2.label)
                if self._gate_trips(toas):
                    log(f"routed refit of {f} (request {rh.name!r}) "
                        "still trips the gate after zapping "
                        f"{n_ch} channel(s) (red-chi^2 "
                        f"{gof_before[f]} -> {gof_after}); serving "
                        "the zapped fit — no further refits",
                        quiet=False, level="warn", tracer=None)
            TOA_list.extend(toas)
        res.TOA_list = TOA_list
        if rh.tim_out:
            try:
                codec.write_tim_result(res, rh.tim_out)
            except OSError as e:
                log(f"routed refit of {rh.name!r}: merged result "
                    f"could not rewrite {rh.tim_out}: {e} (the "
                    "original host-written .tim remains)",
                    quiet=False, level="warn", tracer=None)
        return res

    # ------------------------------------------------------------------

    def stats(self):
        """Per-host placement snapshot: {label: {outstanding,
        n_requests, n_archives, state}} — what the dryrun witness and
        tests assert placement against without reading the trace."""
        with self._lock:
            return {m.label: {"outstanding": m.outstanding,
                              "n_requests": m.n_requests,
                              "n_archives": m.n_archives,
                              "state": m.state,
                              "toas_per_s": m.toas_per_s}
                    for m in self.fleet.members()}

    def metrics(self):
        """Fleet-wide live metrics (ISSUE 20): per-host ``metrics``
        replies plus the merged view — queue depth, in-flight, TOAs/s,
        p50/p90/p99 (bucket-wise histogram merge over the shared
        bounds), cache hit rate, link stall fraction, and the health
        states — and the router's own route-latency registry + SLO
        snapshot.  A host whose ``metrics`` op fails (dead, or a
        pre-obs build) degrades to its ``stat`` fields with the error
        recorded; the reply never raises on a sick fleet — ppmon must
        render outages, not crash on them."""
        from ..obs import metrics as obs_metrics

        with self._lock:
            members = [(m.label, m.state, m.outstanding, m.n_requests,
                        m.n_archives, m.toas_per_s, m.transport)
                       for m in self.fleet.members()]
        hosts = {}
        host_exports = []
        for (label, state, outstanding, n_req, n_arch, rate,
             transport) in members:
            ent = {"state": state, "outstanding": outstanding,
                   "n_requests": n_req, "n_archives": n_arch,
                   "toas_per_s": rate, "queue_len": None,
                   "pending_archives": None, "n_live": None,
                   "link_stall_frac": None, "slo": None,
                   "metrics": None, "p50_s": None, "p99_s": None,
                   "error": None}
            try:
                m = transport.metrics()
            except Exception as e:
                ent["error"] = str(e)
                try:
                    st = transport.stat()
                except Exception:
                    pass  # unreachable: the state field tells why
                else:
                    for k in ("queue_len", "pending_archives",
                              "n_live", "toas_per_s"):
                        ent[k] = st.get(k)
            else:
                for k in ("queue_len", "pending_archives", "n_live",
                          "toas_per_s", "link_stall_frac", "slo",
                          "cache_hits", "cache_bytes"):
                    ent[k] = m.get(k)
                ent["metrics"] = m.get("metrics")
                if ent["metrics"]:
                    host_exports.append(ent["metrics"])
                    h = (ent["metrics"].get("histograms") or {}) \
                        .get("request_latency_s")
                    if h:
                        ent["p50_s"] = obs_metrics \
                            .quantile_from_export(h, 0.50)
                        ent["p99_s"] = obs_metrics \
                            .quantile_from_export(h, 0.99)
            hosts[label] = ent
        merged = obs_metrics.merge_exports(host_exports)
        hl = merged["histograms"].get("request_latency_s")

        def _q(h, q):
            return obs_metrics.quantile_from_export(h, q) if h else None

        def _sum(key):
            vals = [hosts[lb][key] for lb in hosts
                    if hosts[lb][key] is not None]
            return sum(vals) if vals else None

        router_ex = (self._metrics.export()
                     if self._metrics is not None else None)
        n_sub = (router_ex or {}).get("counters", {}) \
            .get("route_submits", 0)
        rl = (router_ex or {}).get("histograms", {}) \
            .get("route_latency_s")
        return {
            "metrics_enabled": self._metrics is not None,
            "hosts": hosts,
            "fleet": {
                "n_hosts": len(hosts),
                "states": {lb: hosts[lb]["state"] for lb in hosts},
                "queue_depth": _sum("queue_len"),
                "pending_archives": _sum("pending_archives"),
                "in_flight": sum(hosts[lb]["outstanding"]
                                 for lb in hosts),
                "toas_per_s": _sum("toas_per_s"),
                "link_stall_frac": obs_metrics.link_stall_frac(merged),
                "p50_s": _q(hl, 0.50), "p90_s": _q(hl, 0.90),
                "p99_s": _q(hl, 0.99),
                "metrics": merged,
            },
            "router": {
                "cache_hits": self.cache_hits,
                "cache_bytes": self.cache_bytes,
                "cache_hit_rate": (round(self.cache_hits / n_sub, 4)
                                   if n_sub else None),
                "p50_s": _q(rl, 0.50), "p90_s": _q(rl, 0.90),
                "p99_s": _q(rl, 0.99),
                "metrics": router_ex,
                "slo": (self._slo.snapshot()
                        if self._slo is not None else None),
            },
        }

    def close(self):
        """Close every transport (idempotent).  The router never owns
        the remote servers — a fleet outlives its clients — so this
        releases connections only."""
        if self._closed:
            return
        self._closed = True
        if self._watcher is not None:
            self._watcher.stop()
        self.fleet.close()
        if self._own_tracer:
            self.tracer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
