"""Campaign telemetry: structured event tracing, run manifests, and
the ``pptrace`` report (ISSUE 5 tentpole).

The benchmark side of this repo has a strong profiling discipline
(profiling.py stage attribution, bench gates); this module is the
*production* counterpart: when a million-TOA campaign streams across K
chips, the operator needs to see which device got which bucket, where
the in-flight queues saturated, which archives stalled the in-order
checkpoint writer, what the K-compile cold start cost, and how fit
quality (reduced chi^2, nfev, S/N) drifted — without re-running
anything under a profiler.

Three layers:

- **Tracer** — a thread-safe, append-only JSONL event writer plus a
  counters/gauges registry.  One file per run; the FIRST record is a
  versioned *manifest* (schema version, jax backend + device list, a
  config snapshot of every ``config.env_overrides()``-controlled knob)
  so traces are self-describing; the LAST record dumps the counters.
  Disabled mode (the default — ``config.telemetry_path`` is None) is a
  module singleton whose methods are no-ops and whose ``enabled`` flag
  lets hot paths skip even building the event dict, so the off cost is
  one attribute read per instrumentation site.  Timestamps are taken
  only around calls that already block (dispatch drains, file IO) —
  tracing never adds a host sync to the device hot path.
- **Instrumentation** lives in the campaign drivers
  (pipeline/stream.py, pipeline/toas.py, pipeline/ipta.py), which emit
  the event vocabulary validated by :func:`validate_trace`.
- **Report** — :func:`report` (CLI: ``tools/pptrace.py`` or
  ``python -m pulseportraiture_tpu.telemetry report``) turns a trace
  into a device-utilization timeline, per-device busy/idle fractions,
  queue-depth statistics vs ``stream_max_inflight``, checkpoint
  straggler/stall analysis, cold-start (compile) accounting, and
  quality histograms.

The leveled :func:`log` helper also lives here (ISSUE 5 satellite):
one status-line function that honors ``quiet`` consistently across
every driver and mirrors its lines into the active trace.
"""

import json
import math
import os
import sys
import threading
import time

import numpy as np

__all__ = ["TRACE_SCHEMA_VERSION", "Tracer", "NULL_TRACER",
           "resolve_tracer", "log", "finite", "load_trace",
           "validate_trace", "report", "main"]

TRACE_SCHEMA_VERSION = 1

# Config knobs snapshotted into every manifest: the full set
# config.env_overrides() can touch, plus the dispatch-routing knobs a
# trace reader needs to interpret device/queue numbers.
CONFIG_SNAPSHOT_KEYS = (
    "cross_spectrum_dtype", "dft_precision", "dft_fold", "align_device",
    "gauss_device", "gls_device", "zap_device", "zap_nstd",
    "quality_refit", "quality_max_gof", "quality_min_snr",
    "stream_devices", "stream_max_inflight", "stream_pipeline_depth",
    "compile_cache_dir", "telemetry_path",
    "serve_max_wait_ms", "serve_queue_depth", "bucket_pad",
    "router_hosts", "router_retry_max", "serve_listen",
    "router_probe_ms", "router_hedge_ms", "router_fleet_file",
    "serve_tenant_quota", "serve_tenant_weight",
    "use_fast_fit", "use_matmul_dft", "fit_harmonic_window",
    "scatter_compensated", "lm_jacobian", "fit_fused",
    "raw_subbyte", "transport_compress",
    "result_cache", "cache_dir", "cache_max_mb",
    "ingest_poll_ms", "ingest_stable_ms",
    "alert_cusum_k", "alert_cusum_h", "gls_resolve_every",
    "tune_db", "autotune", "tune_numerics", "lm_compact_every",
    "slo_targets", "metrics", "mon_interval_ms",
)

# The event vocabulary: type -> fields REQUIRED beyond (type, t).
# Extra fields are allowed (forward-compatible); unknown types are NOT
# (validate_trace exists to catch event-shape drift when the executor
# changes — see tests/test_bench_smoke.py).
EVENT_FIELDS = {
    "manifest": {"schema", "run", "t0_unix", "backend", "devices",
                 "config"},
    "log": {"level", "msg"},
    "resume_skip": {"n_skipped"},
    "archive_skip": {"datafile", "reason"},
    "archive_prepare": {"iarch", "datafile", "n_ok", "n_subints",
                        "prep_s"},
    "archive_load": {"datafile", "load_s"},
    "archive_fit": {"datafile", "n_ok", "fit_s"},
    "dispatch": {"seq", "device", "shape", "n", "queue_depth", "cold"},
    "dispatched": {"seq", "device"},
    # the transfer pipeline's copy stage: h2d_start fires on the copy
    # worker as the bucket's host->device move begins (overlap = a fit
    # was in flight on that device, i.e. the link is hidden behind
    # compute); h2d_done carries the byte count and duration pptrace's
    # link-utilization section aggregates, plus the compression
    # accounting (ISSUE 15): bytes_logical = what the copy would have
    # shipped without the transport codec (== bytes when it never
    # engaged), codec_s = the probe/encode wall, and an optional
    # 'codec' decision tag ('engaged' | 'cost' | 'ratio') forming the
    # cost-model decision ledger
    "h2d_start": {"seq", "device", "overlap"},
    "h2d_done": {"seq", "device", "bytes", "h2d_s", "overlap",
                 "bytes_logical", "codec_s"},
    "drain": {"seq", "device", "wait_s", "scatter_s"},
    "quality": {"snr", "gof", "nfev"},
    "archive_done": {"iarch", "datafile"},
    "ckpt_flush": {"iarch", "datafile", "n_toas", "lag"},
    "force_flush": {"datafile", "lag"},
    "run_end": {"driver", "n_toas", "nfit"},
    "campaign_start": {"n_jobs", "pid", "nproc"},
    "pulsar_done": {"pulsar", "n_toas", "nfit"},
    "campaign_end": {"n_toas", "nfit", "wall_s"},
    # the serving loop (serve/server.py): request lifecycle, the
    # cross-request coalescing proof, and the AOT warmup ledger the
    # "serve" report section aggregates
    "serve_start": {"n_devices", "nsub_batch", "max_wait_ms",
                    "queue_depth"},
    "serve_stop": {"drained"},
    "request_submit": {"req", "n_archives"},
    "request_done": {"req", "n_toas", "n_archives", "wall_s",
                     "queue_s"},
    # one per fused dispatch the server launches: rows = real subints,
    # pad = padded rows, n_requests = distinct requests sharing the
    # bucket (> 1 is continuous batching doing its job)
    "batch_coalesce": {"seq", "n_requests", "rows", "pad"},
    # AOT warmup (utils/device.warmup_from_manifest): one per
    # (manifest shape x device) compiled before serving started
    "warmup_compile": {"shape", "device", "compile_s"},
    # the cross-host router (serve/router.ToaRouter): router_start
    # once per router; route_submit per PLACED request (host = the
    # endpoint that accepted it, attempt counts placements tried,
    # affinity marks a sticky-template win); route_retry per rejected
    # placement (backpressure or unreachable host) with the backoff
    # then applied; route_done when the client collected the result
    # (error non-null on a failed request).  The "router" report
    # section aggregates per-host shares, retry rate, and the
    # placement-imbalance metric from exactly these.
    "router_start": {"n_hosts", "hosts", "retry_max"},
    "route_submit": {"req", "host", "n_archives", "attempt",
                     "affinity"},
    "route_retry": {"req", "host", "attempt", "backoff_s", "error"},
    "route_done": {"req", "host", "wall_s", "n_toas", "error"},
    # the elastic fleet (serve/fleet.py + the router's failover/hedge
    # layer, ISSUE 13): fleet_transition per health-state edge
    # (JOINING/HEALTHY/SUSPECT/DEAD/REJOINED + LEFT on removal);
    # route_failover per dead-host re-placement (action 'collected' =
    # served from the durable .tim with no re-fit, 'redispatch' =
    # placed on a surviving host with the dead one excluded, 'failed'
    # = nowhere to go); route_hedge per hedge launch (primary = the
    # host the request was first placed on).  route_submit/route_done
    # and request_submit/request_done additionally carry a 'tenant'
    # label for the fleet section's per-tenant latency split.
    "fleet_transition": {"host", "from_state", "to_state", "reason"},
    "route_failover": {"req", "dead_host", "action"},
    "route_hedge": {"req", "primary", "host"},
    # the template factory (pipeline/factory.build_templates): one
    # template_fit per bucket dispatch — stage 'profile'|'portrait',
    # the bucket's shape key, rows (real problems), pad (padded rows:
    # B rounded to its power-of-two class + frozen pad components),
    # worst per-problem nfev, wall seconds, whether the batched
    # lane ran (False = host-serial oracle), and the Jacobian source
    # the dispatch resolved ('analytic' | 'ad' — the ISSUE 14 A/B axis)
    "template_fit": {"stage", "bucket", "rows", "pad", "nfev_max",
                     "wall_s", "batched", "jac"},
    # one per finished template job (pulsar)
    "template_job": {"datafile", "kind", "ngauss", "converged",
                     "iters"},
    "factory_end": {"n_jobs", "n_dispatches", "wall_s"},
    # the fleet timing stage (timing/fleet.fleet_gls_fit): one
    # timing_fit per GLS solve dispatch — bucket is the padded
    # (rows x params) shape class ('host:...' on the NumPy oracle
    # lane), rows = real systems in the dispatch, pad = zero-padded
    # batch rows, batched marks the one-dispatch-per-bucket lane
    # (False = per-pulsar serial, the bench A/B arm / host lane) —
    # and one fleet_end rollup per fleet_gls_fit call.  The "timing"
    # report section aggregates exactly these.
    "timing_fit": {"bucket", "rows", "pad", "wall_s", "batched"},
    "fleet_end": {"n_pulsars", "n_dispatches", "wall_s"},
    # the quality subsystem (quality/ + pipeline/zap.py + the serving
    # refit loop): zap_propose = one median-algorithm proposal pass
    # (n_iter = worst per-subint iteration count; device marks the
    # one-dispatch batched lane; wall_s is the zap wall the report
    # aggregates); zap_apply = a zap actually applied to weights/masks
    # (offline apply, the streaming inline lane per archive, or a
    # refit); refit = one serve-loop zap-and-refit resolution with the
    # before/after goodness-of-fit the quality section reports
    "zap_propose": {"datafile", "n_channels", "n_iter", "device",
                    "wall_s"},
    "zap_apply": {"datafile", "n_channels"},
    "refit": {"req", "datafile", "n_channels", "gof_before",
              "gof_after", "improved"},
    # the content-addressed result cache (serve/cache.py, ISSUE 17):
    # cache_hit per lookup served from the store (bytes = stored .tim
    # payload size, source = 'router' | 'server' — a router hit never
    # touched a host); cache_miss per lookup that fell through to the
    # fit path; cache_store per fresh fit persisted into the store;
    # cache_evict per LRU eviction under the cache_max_mb bound.
    # hit/miss additionally carry a 'tenant' label for the cache
    # section's per-tenant hit split.
    "cache_hit": {"req", "bytes", "source"},
    "cache_miss": {"req", "source"},
    "cache_store": {"key", "bytes"},
    "cache_evict": {"key", "bytes"},
    # the online observatory pipeline (ingest/, ISSUE 18):
    # ingest_admit = one archive admitted from a source into the warm
    # serve loop (wait_s = discovery->admission wall, the
    # size-stability + backpressure wait); ingest_skip = a discovered
    # file NOT admitted this pass with the reason ('unstable' = still
    # being written, 'truncated' = typed torn-file retry,
    # 'backpressure' = ServeRejected(retryable), 'error' = poisoned);
    # alert = one anomaly detection on the residual stream — kind is
    # 'glitch' | 'dm_step' | 'profile_change', pulsar/mjd locate it,
    # score is the CUSUM sum (or red-chi2) that crossed, threshold is
    # the h it crossed.  The "alerts" report section and the
    # n_alert/ingest_p99_s summary keys aggregate exactly these.
    "ingest_admit": {"datafile", "source", "wait_s"},
    "ingest_skip": {"datafile", "source", "reason"},
    "alert": {"kind", "pulsar", "mjd", "score", "threshold"},
    # the per-backend autotune subsystem (tune/, ISSUE 19): tune_probe
    # = one capability-record derivation (the backend fingerprint plus
    # the measured dispatch-floor/throughput probes); tune_sweep = one
    # knob swept (n_rejected counts candidates the byte-identity gate
    # refused before timing; winner == default means no candidate
    # beat it); tune_apply = one knob-set application with the DB-hit
    # witness — db_hit=true is the zero-re-sweep proof the "tuning"
    # report section and bench_autotune.py gate on.
    "tune_probe": {"backend", "device_kind", "fingerprint",
                   "dispatch_floor_s", "matmul_gflops", "dft_gflops"},
    "tune_sweep": {"shape_class", "knob", "default", "winner",
                   "n_candidates", "n_rejected", "default_s",
                   "best_s"},
    "tune_apply": {"shape_class", "db_hit", "db_path", "knobs",
                   "default_s", "tuned_s"},
    # the SLO engine (obs/slo.py, ISSUE 20): one slo_breach per EDGE
    # into fast-burn — both the short and long burn-rate windows
    # crossed the threshold for the tenant's latency objective.  The
    # event also carries 'source' ('router' = end-to-end routed
    # latency, 'server' = per-host serve latency) and 'window_s'
    # extras; re-armed only after the short window recovers, so a
    # sustained breach emits once, not once per request.
    "slo_breach": {"tenant", "target_s", "burn_short", "burn_long"},
    "counters": {"counters", "gauges"},
}


def finite(value, ndigits=None):
    """Round a float for an event payload, mapping NaN/Inf to None
    (JSON null) — json.dumps would otherwise write bare ``NaN`` tokens
    that strict JSON consumers (jq, log pipelines) reject.  Degenerate
    fits DO produce NaN chi2/snr, so quality emits route through
    here."""
    value = float(value)
    if not math.isfinite(value):
        return None
    return round(value, ndigits) if ndigits is not None else value


def _jsonable(obj):
    """json.dumps default= hook: numpy scalars/arrays -> plain Python.
    Device objects and anything else fall back to str."""
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


class Tracer:
    """Append-only JSONL event trace for one campaign run.

    Thread-safe: the streaming executor has one dispatch worker per
    device plus prefetch threads, and all of them emit (worker-side
    ``dispatched`` completions arrive via Future callbacks).  A single
    lock serializes writes; events carry their own monotonic ``t``
    (seconds since the manifest), so near-simultaneous events from
    different threads may appear a few microseconds out of ``t`` order
    in the file — readers sort on ``t`` when they care.

    The manifest (first record) makes the trace self-describing:
    schema version, the run label, wall-clock anchor, jax backend and
    local device list, and a snapshot of every env-overridable config
    knob.  ``close()`` appends the counters/gauges registry as the
    final record.
    """

    enabled = True

    def __init__(self, path, run="run"):
        from . import config

        self.path = str(path)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._counters = {}
        self._gauges = {}
        self._seq = 0
        self._closed = False
        # one-level rotation: a killed run's trace is crash forensics
        # (events flush per emit exactly so they survive), and resume
        # re-resolves the same telemetry path — truncating here would
        # destroy the record of what was in flight when the run died
        try:
            if os.path.getsize(self.path) > 0:
                os.replace(self.path, self.path + ".prev")
        except OSError:
            pass  # no previous trace
        self._fh = open(self.path, "w")
        try:
            import jax
            backend = jax.default_backend()
            devices = [str(d) for d in jax.local_devices()]
        except Exception:  # trace even when jax is broken/absent
            backend, devices = "unknown", []
        manifest = {
            "schema": TRACE_SCHEMA_VERSION,
            "run": str(run),
            "t0_unix": time.time(),
            "backend": backend,
            "devices": devices,
            "config": {k: getattr(config, k, None)
                       for k in CONFIG_SNAPSHOT_KEYS},
        }
        self.emit("manifest", **manifest)

    # -- event + registry API -----------------------------------------
    def emit(self, type, **fields):
        """Append one event record.  ``t`` is seconds since the
        manifest (monotonic clock)."""
        fields["type"] = type
        fields["t"] = round(time.perf_counter() - self._t0, 6)
        line = json.dumps(fields, separators=(",", ":"),
                          default=_jsonable) + "\n"
        with self._lock:
            if self._closed:
                return
            self._fh.write(line)
            self._fh.flush()  # crash-visible: a killed run keeps its
            # events on disk (the same stance as the .tim checkpoints)

    def next_seq(self):
        """Trace-global dispatch sequence number.  The TRACER owns the
        counter (not the executor): several executors can share one
        trace — stream_ipta_campaign runs one per pulsar — and the
        report pairs dispatch/dispatched/drain events by seq, so seqs
        must be unique across the whole file."""
        with self._lock:
            seq = self._seq
            self._seq += 1
            return seq

    def counter(self, name, inc=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc

    def gauge_max(self, name, value):
        """High-water-mark gauge (e.g. peak queue depth)."""
        with self._lock:
            if value > self._gauges.get(name, float("-inf")):
                self._gauges[name] = value

    def close(self):
        """Write the counters record and close the file (idempotent).
        The counters write and the closed flag flip under ONE lock
        acquisition: a straggling worker-thread emit (e.g. a
        ``dispatched`` Future callback on an aborted run) either lands
        before the counters record or is dropped — it can never
        interleave after it, so the counters record is always last."""
        with self._lock:
            if self._closed:
                return
            rec = {"type": "counters",
                   "t": round(time.perf_counter() - self._t0, 6),
                   "counters": dict(self._counters),
                   "gauges": dict(self._gauges)}
            self._fh.write(json.dumps(rec, separators=(",", ":"),
                                      default=_jsonable) + "\n")
            self._closed = True
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # events flush per emit, so a tracer dropped on an exception
        # path loses nothing on disk; this just releases the fd (and
        # appends the counters record when the interpreter still can)
        try:
            self.close()
        except Exception:
            pass


class _NullTracer:
    """The disabled tracer: every method is a no-op and ``enabled`` is
    False so instrumentation sites can skip building event payloads
    entirely — the telemetry-off cost of the streaming hot path is one
    attribute read per dispatch."""

    enabled = False
    path = None
    _seq = 0

    def emit(self, type, **fields):
        pass

    def next_seq(self):
        # never emitted, but still monotonic: the transfer pipeline's
        # overlap accounting (res.h2d_overlap_s) orders dispatches by
        # seq, and that stat is surfaced with telemetry off too.  A GIL
        # race between executors can at worst produce a tie, which the
        # strict < comparison reads as "not earlier" — an undercount,
        # never an overcount.
        _NullTracer._seq += 1
        return _NullTracer._seq

    def counter(self, name, inc=1):
        pass

    def gauge_max(self, name, value):
        pass

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NULL_TRACER = _NullTracer()


def resolve_tracer(arg=None, run="run"):
    """Resolve a driver's ``telemetry=`` argument to ``(tracer,
    owned)``.

    ``arg`` may be an existing Tracer (shared — e.g.
    stream_ipta_campaign threads ONE tracer through every per-pulsar
    stream call so the whole campaign lands in one trace; not owned,
    the caller closes it), a path (a new trace is opened; owned), or
    None (``config.telemetry_path`` decides; the NULL tracer when that
    is unset — the default).  Owned tracers must be closed by the
    caller that resolved them."""
    if isinstance(arg, (Tracer, _NullTracer)):
        return arg, False
    if arg is None:
        from . import config
        arg = getattr(config, "telemetry_path", None)
    if not arg:
        return NULL_TRACER, False
    return Tracer(arg, run=run), True


# ---------------------------------------------------------------------------
# Leveled status logging (ISSUE 5 satellite): the drivers' bare
# print() lines applied `quiet` inconsistently (load_for_toas defaults
# quiet=True, the driver classes quiet=False, and skip/fail messages
# ignored it entirely).  One helper, one rule.
# ---------------------------------------------------------------------------

def log(msg, quiet=False, level="info", tracer=None):
    """Driver status line.

    ``info`` honors ``quiet`` and goes to stdout (progress/summary
    lines).  ``warn`` goes to stderr and is NEVER suppressed —
    skip/fail reasons must not vanish just because a campaign runs
    quiet (and they are mirrored into the trace regardless, so a quiet
    campaign still records why an archive was dropped).  When a tracer
    is given the line is also recorded as a ``log`` event."""
    if level not in ("info", "warn"):
        raise ValueError(f"log level must be 'info' or 'warn', "
                         f"got {level!r}")
    if tracer is not None and tracer.enabled:
        tracer.emit("log", level=level, msg=str(msg))
    if level == "warn":
        print(msg, file=sys.stderr)
    elif not quiet:
        print(msg)


# ---------------------------------------------------------------------------
# Trace reading / validation
# ---------------------------------------------------------------------------

def load_trace(path):
    """Read a trace -> (manifest, events).  Raises ValueError on a
    malformed file (no manifest, bad JSON).  Events keep file order
    (writes are lock-serialized; ``t`` is per-event)."""
    records = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: bad JSON: {e}")
            records.append(rec)
    if not records or records[0].get("type") != "manifest":
        raise ValueError(f"{path}: first record is not a manifest")
    return records[0], records[1:]


def validate_trace(path):
    """Validate a trace against the schema: manifest first, known
    schema version, every event of a known type with its required
    fields.  Returns (manifest, events); raises ValueError naming the
    first offending record.  This is the drift guard the bench smoke
    test runs whenever the executor changes."""
    manifest, events = load_trace(path)
    if manifest.get("schema") != TRACE_SCHEMA_VERSION:
        raise ValueError(
            f"{path}: schema {manifest.get('schema')!r} != supported "
            f"{TRACE_SCHEMA_VERSION}")
    missing = EVENT_FIELDS["manifest"] - set(manifest)
    if missing:
        raise ValueError(f"{path}: manifest missing {sorted(missing)}")
    for i, ev in enumerate(events, 2):
        etype = ev.get("type")
        if etype not in EVENT_FIELDS:
            raise ValueError(f"{path}: record {i}: unknown event type "
                             f"{etype!r}")
        if "t" not in ev:
            raise ValueError(f"{path}: record {i}: no timestamp")
        missing = EVENT_FIELDS[etype] - set(ev)
        if missing:
            raise ValueError(f"{path}: record {i} ({etype}): missing "
                             f"{sorted(missing)}")
    return manifest, events


# ---------------------------------------------------------------------------
# pptrace report
# ---------------------------------------------------------------------------

def _merge_intervals(spans):
    """Union length-preserving merge of (start, end) intervals."""
    merged = []
    for s, e in sorted(spans):
        if merged and s <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((s, e))
    return merged


def _hist_lines(values, nbins=8, width=32, fmt="{:.3g}"):
    """Text histogram rows for the quality section."""
    values = np.asarray(values, float)
    values = values[np.isfinite(values)]
    if values.size == 0:
        return ["  (no samples)"]
    lo, hi = float(values.min()), float(values.max())
    if lo == hi:
        return [f"  {fmt.format(lo)} x{values.size}"]
    counts, edges = np.histogram(values, bins=nbins, range=(lo, hi))
    peak = max(int(counts.max()), 1)
    rows = []
    for c, e0, e1 in zip(counts, edges[:-1], edges[1:]):
        bar = "#" * max(1 if c else 0, round(width * c / peak))
        rows.append(f"  {fmt.format(e0):>10} .. {fmt.format(e1):<10} "
                    f"|{bar:<{width}}| {int(c)}")
    return rows


def _timeline(busy_by_dev, t_end, width=60):
    """ASCII device-utilization timeline: one row per device, '#'
    where the device had at least one dispatch in flight."""
    rows = []
    t_end = max(t_end, 1e-9)
    for dev in sorted(busy_by_dev):
        cells = [" "] * width
        for s, e in busy_by_dev[dev]:
            i0 = min(int(s / t_end * width), width - 1)
            i1 = min(int(e / t_end * width), width - 1)
            for i in range(i0, i1 + 1):
                cells[i] = "#"
        rows.append(f"  dev{dev} |{''.join(cells)}|")
    return rows


def report(path, file=None):
    """Analyze a trace and print the pptrace report.  Returns the
    summary dict (what the tests — and scripts — consume); the printed
    text is the same numbers, human-shaped."""
    out = file or sys.stdout
    manifest, events = validate_trace(path)
    by_type = {}
    for ev in events:
        by_type.setdefault(ev["type"], []).append(ev)

    p = lambda s="": print(s, file=out)  # noqa: E731
    p(f"== pptrace report: {path} ==")
    p(f"run {manifest['run']!r}  schema {manifest['schema']}  "
      f"backend {manifest['backend']}  "
      f"{len(manifest['devices'])} local device(s)")
    cfg = manifest.get("config", {})
    p("config: " + ", ".join(f"{k}={cfg[k]!r}" for k in sorted(cfg)))

    # ---- dispatch/drain bookkeeping ---------------------------------
    dispatches = by_type.get("dispatch", [])
    drains = {ev["seq"]: ev for ev in by_type.get("drain", [])}
    done = {ev["seq"]: ev for ev in by_type.get("dispatched", [])}
    per_dev = {}
    busy_by_dev = {}
    t_end = max((ev["t"] for ev in events), default=0.0)
    cold_s = warm_s = 0.0
    n_cold = n_warm = 0
    for ev in dispatches:
        dev = ev["device"]
        d = per_dev.setdefault(dev, {"dispatches": 0, "subints": 0,
                                     "shapes": set()})
        d["dispatches"] += 1
        d["subints"] += ev["n"]
        d["shapes"].add(ev["shape"])
        drain = drains.get(ev["seq"])
        end_t = drain["t"] if drain else ev["t"]
        busy_by_dev.setdefault(dev, []).append((ev["t"], end_t))
        w = done.get(ev["seq"])
        if w is None:
            # no worker completion recorded (non-Future handle, or the
            # run died before the callback) — counting it as 0 s warm
            # would dilute avg_warm and inflate the compile estimate
            continue
        worker_s = w["t"] - ev["t"]
        if ev.get("cold"):
            n_cold += 1
            cold_s += worker_s
        else:
            n_warm += 1
            warm_s += worker_s
    for dev in busy_by_dev:
        busy_by_dev[dev] = _merge_intervals(busy_by_dev[dev])

    nfit_run = None
    peak_run = None
    run_ends = by_type.get("run_end", [])
    if run_ends:
        nfit_run = sum(ev["nfit"] for ev in run_ends)
        peak_run = max(ev.get("peak_inflight", 0) for ev in run_ends)

    p("")
    p("-- devices --")
    p(f"  {'dev':>4} {'dispatches':>10} {'subints':>8} {'shapes':>7} "
      f"{'busy_s':>8} {'busy%':>6}")
    device_counts = {}
    for dev in sorted(per_dev):
        d = per_dev[dev]
        busy = sum(e - s for s, e in busy_by_dev.get(dev, []))
        frac = busy / t_end if t_end > 0 else 0.0
        device_counts[dev] = d["dispatches"]
        p(f"  {dev:>4} {d['dispatches']:>10} {d['subints']:>8} "
          f"{len(d['shapes']):>7} {busy:>8.3f} {100 * frac:>5.1f}%")
    total_disp = sum(device_counts.values())
    tail = f" (run_end nfit {nfit_run})" if nfit_run is not None else ""
    p(f"  total dispatches {total_disp}{tail}")
    if busy_by_dev:
        p(f"  timeline over {t_end:.3f} s ('#' = >=1 dispatch in "
          "flight):")
        for row in _timeline(busy_by_dev, t_end):
            p(row)

    # ---- queue depth ------------------------------------------------
    depths = [ev["queue_depth"] for ev in dispatches]
    # effective limit: the executor records its resolved per-call
    # max_inflight in run_end; the manifest's config snapshot is only
    # the process default and is wrong when a driver was called with
    # max_inflight= explicitly
    limits = [ev["max_inflight"] for ev in run_ends
              if ev.get("max_inflight")]
    limit = max(limits) if limits else cfg.get("stream_max_inflight")
    max_depth = max(depths) if depths else 0
    p("")
    p("-- queue depth (at dispatch) --")
    if depths:
        sat = sum(1 for d in depths if limit and d >= limit)
        src = "run" if limits else "config default"
        p(f"  max {max_depth}  mean {np.mean(depths):.2f}  "
          f"limit max_inflight={limit} ({src})  "
          f"saturated dispatches {sat}/{len(depths)}")
    else:
        p("  (no dispatches)")

    # ---- h2d link utilization ---------------------------------------
    h2d = by_type.get("h2d_done", [])
    h2d_bytes = sum(int(ev["bytes"]) for ev in h2d)
    # pre-compression traces (schema < this release) lack the logical
    # fields; shipped == logical there
    h2d_bytes_logical = sum(int(ev.get("bytes_logical", ev["bytes"]))
                            for ev in h2d)
    codec_s_total = sum(float(ev.get("codec_s", 0.0)) for ev in h2d)
    codec_decisions = {}
    for ev in h2d:
        dec = ev.get("codec")
        if dec is not None:
            codec_decisions[dec] = codec_decisions.get(dec, 0) + 1
    h2d_s = sum(float(ev["h2d_s"]) for ev in h2d)
    h2d_overlap_s = sum(float(ev["h2d_s"]) for ev in h2d
                        if ev.get("overlap"))
    h2d_stall_frac = (1.0 - h2d_overlap_s / h2d_s) if h2d_s > 0 else None
    h2d_compression = (h2d_bytes_logical / h2d_bytes
                       if h2d_bytes else None)
    p("")
    p("-- h2d link (copy stage) --")
    if h2d:
        mbps = h2d_bytes / max(h2d_s, 1e-9) / 1e6
        link_frac = h2d_s / max(t_end, 1e-9)
        p(f"  {len(h2d)} copies, {h2d_bytes / 1e6:.2f} MB in "
          f"{h2d_s:.3f} s ({mbps:.1f} MB/s effective); link busy "
          f"{100 * link_frac:.1f}% of wall")
        # h2d_s can sum to 0.0 (sub-microsecond copies round to 0 at
        # emit time), leaving h2d_stall_frac None
        ov_pct = 100 * h2d_overlap_s / h2d_s if h2d_s > 0 else 0.0
        stall = (f"{100 * h2d_stall_frac:.1f}%"
                 if h2d_stall_frac is not None else "n/a")
        p(f"  overlapped with in-flight fit: {h2d_overlap_s:.3f} s "
          f"({ov_pct:.1f}%)  ->  link stall "
          f"fraction {stall} (copy time the fit "
          "stage could not hide; lower pipeline stalls = raise "
          "stream_pipeline_depth only if this is high AND devices "
          "idle)")
        # transport-compression accounting (ISSUE 15): shipped vs
        # logical bytes, codec wall, and the cost-model decision
        # ledger — a trace with no decisions recorded compressed
        # nothing (transport_compress off, or no eligible payloads)
        if h2d_bytes_logical != h2d_bytes or codec_decisions:
            saved = h2d_bytes_logical - h2d_bytes
            ratio = h2d_compression or 1.0
            p(f"  compression: shipped {h2d_bytes / 1e6:.2f} MB of "
              f"{h2d_bytes_logical / 1e6:.2f} MB logical "
              f"({ratio:.2f}x, {saved / 1e6:.2f} MB saved), codec "
              f"wall {codec_s_total:.3f} s")
            if codec_decisions:
                parts = ", ".join(
                    f"{n} {k}" for k, n in sorted(
                        codec_decisions.items()))
                p(f"  cost-model decisions: {parts} ('engaged' = "
                  "packed; 'cost' = model predicted a loss; 'ratio' "
                  "= payload incompressible)")
        per_dev_h2d = {}
        for ev in h2d:
            d = per_dev_h2d.setdefault(ev["device"], [0, 0.0, 0.0, 0])
            d[0] += int(ev["bytes"])
            d[1] += float(ev["h2d_s"])
            d[2] += float(ev["h2d_s"]) if ev.get("overlap") else 0.0
            d[3] += int(ev.get("bytes_logical", ev["bytes"]))
        for dev in sorted(per_dev_h2d):
            b, s, o, lg = per_dev_h2d[dev]
            comp = (f", {lg / b:.2f}x compression" if lg != b else "")
            p(f"  dev{dev}: {b / 1e6:.2f} MB, {s:.3f} s, "
              f"{100 * (o / s if s else 0.0):.1f}% overlapped{comp}")
    else:
        p("  (no h2d events — pre-pipeline trace, or no dispatches)")

    # ---- checkpoint stalls / stragglers -----------------------------
    flushes = by_type.get("ckpt_flush", [])
    forces = by_type.get("force_flush", [])
    p("")
    p("-- checkpoint stalls --")
    if flushes:
        lags = sorted(flushes, key=lambda ev: -ev["lag"])
        p(f"  {len(flushes)} in-order flushes; "
          f"{len(forces)} staleness-horizon force-flushes")
        for ev in lags[:3]:
            if ev["lag"] > 0:
                p(f"  straggler: {ev['datafile']} flushed "
                  f"{ev['lag']} prepared archive(s) late "
                  f"({ev['n_toas']} TOAs)")
        if all(ev["lag"] == 0 for ev in flushes):
            p("  no archive deferred a checkpoint write")
    else:
        p("  (no checkpointing in this run)")

    # ---- cold start / compile accounting ----------------------------
    p("")
    p("-- cold start (first dispatch per shape x device: trace + XLA "
      "compile on the worker) --")
    if n_cold:
        avg_warm = warm_s / n_warm if n_warm else 0.0
        p(f"  {n_cold} cold dispatch(es), {cold_s:.3f} s on workers "
          f"(warm avg {avg_warm:.4f} s x {n_warm}); est. compile cost "
          f"~{max(cold_s - avg_warm * n_cold, 0.0):.3f} s")
    else:
        p("  (no dispatch events)")

    # ---- serve (request lifecycle + continuous batching) ------------
    req_done = by_type.get("request_done", [])
    coalesce = by_type.get("batch_coalesce", [])
    warmups = by_type.get("warmup_compile", [])
    occupancy = None
    req_p50 = req_p99 = None
    # every section below prints its header unconditionally with an
    # explicit "(no ... events)" line when the trace has none (ISSUE 20
    # satellite): a vanished section reads as a broken report, and an
    # operator diffing two traces needs the absence stated, not implied
    p("")
    p("-- serve (continuous batching) --")
    if not (req_done or coalesce or warmups):
        p("  (no serve events)")
    else:
        n_sub = len(by_type.get("request_submit", []))
        if req_done:
            walls = np.asarray([ev["wall_s"] for ev in req_done], float)
            queues = np.asarray([ev["queue_s"] for ev in req_done],
                                float)
            req_p50 = float(np.percentile(walls, 50))
            req_p99 = float(np.percentile(walls, 99))
            ntoa = sum(int(ev["n_toas"]) for ev in req_done)
            p(f"  {len(req_done)}/{n_sub or len(req_done)} requests "
              f"done, {ntoa} TOAs")
            p(f"  request latency (submit->done): p50 {req_p50:.3f} s  "
              f"p90 {np.percentile(walls, 90):.3f} s  "
              f"p99 {req_p99:.3f} s")
            serve_s = walls - queues
            p(f"  queue-wait vs serve split: mean wait "
              f"{queues.mean():.3f} s, mean serve {serve_s.mean():.3f} "
              f"s (of which fused-fit wall rides the device sections "
              "above)")
        if coalesce:
            rows = sum(int(ev["rows"]) for ev in coalesce)
            pad = sum(int(ev["pad"]) for ev in coalesce)
            occupancy = rows / max(rows + pad, 1)
            shared = sum(1 for ev in coalesce if ev["n_requests"] > 1)
            p(f"  batch occupancy: {rows} rows used / {pad} padded "
              f"({100 * occupancy:.1f}% full) across {len(coalesce)} "
              f"dispatches; {shared} dispatch(es) coalesced >1 "
              "request")
        if warmups:
            w_s = sum(float(ev["compile_s"]) for ev in warmups)
            p(f"  AOT warmup: {len(warmups)} (shape x device) "
              f"program(s) compiled in {w_s:.3f} s before serving")

    # ---- result cache (content-addressed .tim store) ----------------
    c_hit = by_type.get("cache_hit", [])
    c_miss = by_type.get("cache_miss", [])
    c_store = by_type.get("cache_store", [])
    c_evict = by_type.get("cache_evict", [])
    cache_hit_rate = None
    cache_bytes_served = None
    cache_bytes_stored = None
    cache_tenant_hits = {}
    p("")
    p("-- result cache (content-addressed) --")
    if not (c_hit or c_miss or c_store or c_evict):
        p("  (no cache events)")
    else:
        n_lookup = len(c_hit) + len(c_miss)
        cache_hit_rate = len(c_hit) / max(n_lookup, 1)
        cache_bytes_served = sum(int(ev["bytes"]) for ev in c_hit)
        cache_bytes_stored = sum(int(ev["bytes"]) for ev in c_store)
        p(f"  {len(c_hit)}/{n_lookup} lookup(s) hit "
          f"({100 * cache_hit_rate:.1f}%): {cache_bytes_served} bytes "
          f"served from the store vs {cache_bytes_stored} bytes "
          f"fitted-and-stored ({len(c_store)} fresh fit(s) cached)")
        by_source = {}
        for ev in c_hit:
            by_source[ev["source"]] = by_source.get(ev["source"], 0) + 1
        if by_source:
            p("  hit split by layer: " + ", ".join(
                f"{src}={n}" for src, n in sorted(by_source.items()))
              + " (router hits never touched a host)")
        for ev in c_hit:
            t = ev.get("tenant")
            if t is not None:
                cache_tenant_hits[t] = cache_tenant_hits.get(t, 0) + 1
        if cache_tenant_hits:
            miss_by_tenant = {}
            for ev in c_miss:
                t = ev.get("tenant")
                if t is not None:
                    miss_by_tenant[t] = miss_by_tenant.get(t, 0) + 1
            for t in sorted(cache_tenant_hits):
                n_h = cache_tenant_hits[t]
                n_m = miss_by_tenant.get(t, 0)
                p(f"  tenant {t!r}: {n_h} hit(s) / {n_m} fit(s) — hits "
                  "are not billed against the tenant quota")
        if c_evict:
            ev_bytes = sum(int(ev["bytes"]) for ev in c_evict)
            p(f"  eviction pressure: {len(c_evict)} entrie(s) evicted, "
              f"{ev_bytes} bytes released (store bounded by "
              "cache_max_mb; least-recently-used first)")

    # ---- router (cross-host request sharding) -----------------------
    r_starts = by_type.get("router_start", [])
    r_sub = by_type.get("route_submit", [])
    r_retry = by_type.get("route_retry", [])
    r_done = by_type.get("route_done", [])
    router_imbalance = None
    router_host_counts = {}
    p("")
    p("-- router (cross-host request sharding) --")
    if not (r_starts or r_sub or r_retry or r_done):
        p("  (no router events)")
    else:
        n_hosts = max((ev["n_hosts"] for ev in r_starts), default=0)
        per_host = {}
        for ev in r_sub:
            if ev["host"] is None:
                continue  # router-side cache hit: no host touched
            d = per_host.setdefault(ev["host"],
                                    {"requests": 0, "archives": 0,
                                     "affinity": 0})
            d["requests"] += 1
            d["archives"] += int(ev["n_archives"])
            d["affinity"] += bool(ev.get("affinity"))
        done_by_host = {}
        err_by_host = {}
        for ev in r_done:
            if ev["host"] is None:
                continue  # cache hit: counted in the cache section
            done_by_host[ev["host"]] = \
                done_by_host.get(ev["host"], 0) + 1
            if ev.get("error"):
                err_by_host[ev["host"]] = \
                    err_by_host.get(ev["host"], 0) + 1
        tot_req = sum(d["requests"] for d in per_host.values())
        tot_arch = sum(d["archives"] for d in per_host.values())
        if r_starts:
            p(f"  fleet: {n_hosts} host(s), retry_max "
              f"{max(ev['retry_max'] for ev in r_starts)}")
        if per_host:
            p(f"  {'host':>24} {'requests':>9} {'archives':>9} "
              f"{'arch%':>6} {'affinity':>9} {'done':>5} {'errors':>7}")
            for host in sorted(per_host):
                d = per_host[host]
                share = d["archives"] / max(tot_arch, 1)
                p(f"  {host:>24} {d['requests']:>9} "
                  f"{d['archives']:>9} {100 * share:>5.1f}% "
                  f"{d['affinity']:>9} {done_by_host.get(host, 0):>5} "
                  f"{err_by_host.get(host, 0):>7}")
                router_host_counts[host] = d["archives"]
            # placement imbalance: max per-host archive share over the
            # ideal even share (1.0 = perfectly balanced; H = all work
            # on one of H hosts).  Computed over hosts that RECEIVED
            # work against the router_start fleet size, so an idle
            # host drags the metric up — that is the point.
            denom = max(n_hosts, len(per_host))
            even = tot_arch / max(denom, 1)
            router_imbalance = (max(d["archives"]
                                    for d in per_host.values())
                                / max(even, 1e-9))
            p(f"  placement imbalance (max host share / even share): "
              f"{router_imbalance:.2f} (1.0 = balanced over "
              f"{denom} host(s))")
        if r_sub or r_retry:
            rate = len(r_retry) / max(len(r_sub) + len(r_retry), 1)
            p(f"  {len(r_sub)} placement(s), {len(r_retry)} "
              f"retried rejection(s) ({100 * rate:.1f}% of "
              "placement attempts); backpressure retries land on the "
              "next-least-loaded host")
        if r_done:
            walls = np.asarray([ev["wall_s"] for ev in r_done], float)
            n_err = sum(1 for ev in r_done if ev.get("error"))
            p(f"  {len(r_done)}/{tot_req or len(r_done)} request(s) "
              f"collected ({n_err} failed); routed latency p50 "
              f"{float(np.percentile(walls, 50)):.3f} s  p99 "
              f"{float(np.percentile(walls, 99)):.3f} s")

    # ---- fleet (membership / failover / hedging / tenant QoS) -------
    ftrans = by_type.get("fleet_transition", [])
    fover = by_type.get("route_failover", [])
    hedges = by_type.get("route_hedge", [])
    tenant_evs = [ev for ev in (r_done or req_done)
                  if ev.get("tenant") is not None]
    fleet_states = {}
    n_failover_collected = None
    tenant_latency = {}
    p("")
    p("-- fleet (membership / failover / QoS) --")
    if not (ftrans or fover or hedges or tenant_evs):
        p("  (no fleet events)")
    else:
        if ftrans:
            per_host_edges = {}
            for ev in ftrans:
                per_host_edges.setdefault(ev["host"], []).append(ev)
                fleet_states[ev["host"]] = ev["to_state"]
            p(f"  {len(ftrans)} health transition(s); state timeline:")
            for host in sorted(per_host_edges):
                edges = per_host_edges[host]
                path = " -> ".join(
                    f"{ev['to_state']}@{ev['t']:.2f}s"
                    for ev in edges[-6:])
                lead = "... -> " if len(edges) > 6 else ""
                p(f"    {host}: {lead}{path}")
            degraded = [h for h, s in fleet_states.items()
                        if s in ("SUSPECT", "DEAD")]
            if degraded:
                p(f"    degraded at end of trace: "
                  f"{', '.join(sorted(degraded))}")
        if fover:
            by_action = {}
            for ev in fover:
                by_action[ev["action"]] = \
                    by_action.get(ev["action"], 0) + 1
            n_failover_collected = by_action.get("collected", 0)
            parts = ", ".join(f"{n} {a}"
                              for a, n in sorted(by_action.items()))
            p(f"  {len(fover)} in-flight failover(s) ({parts}); "
              "'collected' requests were served from their durable "
              ".tim with no re-fit")
        if hedges:
            wins = sum(1 for ev in r_done if ev.get("hedged")
                       and not ev.get("error"))
            p(f"  {len(hedges)} hedged request(s) "
              f"({wins} resolved with a hedge outstanding); first "
              "completion wins, the loser is cancelled at collection")
        if tenant_evs:
            by_tenant = {}
            for ev in tenant_evs:
                by_tenant.setdefault(ev["tenant"], []).append(ev)
            p(f"  per-tenant latency split "
              f"({len(by_tenant)} tenant(s)):")
            p(f"  {'tenant':>16} {'requests':>9} {'errors':>7} "
              f"{'p50_s':>8} {'p99_s':>8}")
            for tenant in sorted(by_tenant):
                evs = by_tenant[tenant]
                walls = np.asarray([ev["wall_s"] for ev in evs], float)
                n_err = sum(1 for ev in evs if ev.get("error"))
                tenant_latency[tenant] = {
                    "n": len(evs),
                    "p50_s": float(np.percentile(walls, 50)),
                    "p99_s": float(np.percentile(walls, 99)),
                }
                p(f"  {tenant:>16} {len(evs):>9} {n_err:>7} "
                  f"{tenant_latency[tenant]['p50_s']:>8.3f} "
                  f"{tenant_latency[tenant]['p99_s']:>8.3f}")

    # ---- template factory (batched Gaussian/spline model building) --
    tfit = by_type.get("template_fit", [])
    tjobs = by_type.get("template_job", [])
    template_pad_frac = None
    template_wall_s = None
    p("")
    p("-- template factory (batched LM buckets) --")
    if not (tfit or tjobs):
        p("  (no template events)")
    else:
        by_stage = {}
        for ev in tfit:
            s = by_stage.setdefault(ev["stage"],
                                    [0, 0, 0, 0.0, 0, set()])
            s[0] += 1
            s[1] += int(ev["rows"])
            s[2] += int(ev["pad"])
            s[3] += float(ev["wall_s"])
            s[4] = max(s[4], int(ev["nfev_max"]))
            s[5].add(ev["bucket"])
        template_wall_s = sum(s[3] for s in by_stage.values())
        rows_all = sum(s[1] for s in by_stage.values())
        pad_all = sum(s[2] for s in by_stage.values())
        template_pad_frac = pad_all / max(rows_all + pad_all, 1)
        n_batched = sum(1 for ev in tfit if ev.get("batched"))
        for stage in sorted(by_stage):
            nd, rows, pad, wall, nfev, shapes = by_stage[stage]
            occ = rows / max(rows + pad, 1)
            p(f"  {stage}: {nd} dispatch(es) over {len(shapes)} "
              f"bucket shape(s), {rows} problems + {pad} padded "
              f"({100 * occ:.1f}% full), wall {wall:.3f} s, "
              f"worst nfev {nfev}")
        p(f"  {n_batched}/{len(tfit)} dispatches on the batched lane; "
          f"aggregate pad fraction "
          f"{100 * template_pad_frac:.1f}%")
        if tjobs:
            ngs = [int(ev["ngauss"]) for ev in tjobs
                   if ev.get("ngauss") is not None]
            conv = sum(1 for ev in tjobs if ev.get("converged"))
            p(f"  {len(tjobs)} template job(s) done "
              f"({conv} converged); ngauss "
              f"min/median/max {min(ngs)}/{int(np.median(ngs))}/"
              f"{max(ngs)}" if ngs else
              f"  {len(tjobs)} template job(s) done")

    # ---- timing (fleet-batched wideband GLS) ------------------------
    tim_fit = by_type.get("timing_fit", [])
    fleet_ends = by_type.get("fleet_end", [])
    timing_pad_frac = None
    timing_wall_s = None
    n_timing_pulsars = None
    timing_dispatches = None
    p("")
    p("-- timing (fleet-batched wideband GLS) --")
    if not (tim_fit or fleet_ends):
        p("  (no timing events)")
    else:
        if fleet_ends:
            n_timing_pulsars = sum(int(ev["n_pulsars"])
                                   for ev in fleet_ends)
            timing_dispatches = sum(int(ev["n_dispatches"])
                                    for ev in fleet_ends)
            fleet_wall = sum(float(ev["wall_s"]) for ev in fleet_ends)
            p(f"  {n_timing_pulsars} pulsar(s) solved in "
              f"{timing_dispatches} dispatch(es) across "
              f"{len(fleet_ends)} fleet call(s), wall {fleet_wall:.3f}"
              " s (serial would pay one dispatch per pulsar — the "
              "reduction is the batched lane's win)")
        if tim_fit:
            rows = sum(int(ev["rows"]) for ev in tim_fit)
            pad = sum(int(ev["pad"]) for ev in tim_fit)
            timing_pad_frac = pad / max(rows + pad, 1)
            timing_wall_s = sum(float(ev["wall_s"]) for ev in tim_fit)
            n_batched = sum(1 for ev in tim_fit if ev.get("batched"))
            shapes = {}
            for ev in tim_fit:
                s = shapes.setdefault(ev["bucket"], [0, 0, 0])
                s[0] += 1
                s[1] += int(ev["rows"])
                s[2] += int(ev["pad"])
            p(f"  {len(tim_fit)} solve dispatch(es) "
              f"({n_batched} batched), {rows} system(s) + {pad} "
              f"zero-padded ({100 * (1 - timing_pad_frac):.1f}% "
              f"full), solve wall {timing_wall_s:.3f} s")
            for key in sorted(shapes):
                nd, rw, pd = shapes[key]
                p(f"    bucket {key}: {nd} dispatch(es), {rw} "
                  f"system(s) + {pd} pad")
    # ---- data quality (zap + refit) ---------------------------------
    zprop = by_type.get("zap_propose", [])
    zapp = by_type.get("zap_apply", [])
    refits = by_type.get("refit", [])
    zap_channels_cut = None
    zap_wall_s = None
    refit_rate = None
    n_refit_improved = None
    p("")
    p("-- data quality (zap + refit) --")
    if not (zprop or zapp or refits):
        p("  (no quality events)")
    else:
        if zprop:
            zap_wall_s = sum(float(ev["wall_s"]) for ev in zprop)
            n_dev = sum(1 for ev in zprop if ev.get("device"))
            worst_iter = max(int(ev["n_iter"]) for ev in zprop)
            p(f"  {len(zprop)} zap proposal pass(es) "
              f"({n_dev} on the one-dispatch device lane), zap wall "
              f"{zap_wall_s:.3f} s, worst iteration count {worst_iter} "
              "(device lane: iterations run INSIDE the compiled loop — "
              "zero per-iteration host round-trips)")
        if zapp:
            zap_channels_cut = sum(int(ev["n_channels"]) for ev in zapp)
            p(f"  {len(zapp)} zap application(s), {zap_channels_cut} "
              "channel entr(ies) cut; per archive:")
            per_arch = {}
            for ev in zapp:
                per_arch[ev["datafile"]] = \
                    per_arch.get(ev["datafile"], 0) + int(ev["n_channels"])
            for df in sorted(per_arch, key=per_arch.get,
                             reverse=True)[:8]:
                p(f"    {df}: {per_arch[df]} channel entr(ies)")
        if refits:
            n_req = len(by_type.get("request_done", []))
            n_refit_improved = sum(1 for ev in refits
                                   if ev.get("improved"))
            refit_rate = len(refits) / max(n_req, 1) if n_req else None
            gb = [ev["gof_before"] for ev in refits
                  if ev.get("gof_before") is not None]
            ga = [ev["gof_after"] for ev in refits
                  if ev.get("gof_after") is not None]
            rate = (f"{100 * refit_rate:.1f}% of requests"
                    if refit_rate is not None else "n/a")
            p(f"  {len(refits)} refit(s) ({rate}), "
              f"{n_refit_improved} improved; red-chi^2 "
              f"before/after mean "
              f"{np.mean(gb) if gb else float('nan'):.3f} -> "
              f"{np.mean(ga) if ga else float('nan'):.3f}")
            for ev in refits:
                if not ev.get("improved"):
                    p(f"    NOT improved: {ev['datafile']} "
                      f"({ev['n_channels']} channel(s) cut, gof "
                      f"{ev.get('gof_before')} -> "
                      f"{ev.get('gof_after')})")

    # ---- quality ----------------------------------------------------
    qual = by_type.get("quality", [])
    snr = [v for ev in qual for v in ev["snr"]]
    gof = [v for ev in qual for v in ev["gof"]]
    nfev = [v for ev in qual for v in ev["nfev"]]
    p("")
    p(f"-- fit quality ({len(snr)} TOA records) --")
    for name, vals in (("snr", snr), ("gof (chi2/dof)", gof),
                       ("nfev", nfev)):
        p(f"  {name}:")
        for row in _hist_lines(vals):
            p(row)

    # ---- online ingest + alerts (ingest/, ISSUE 18) -----------------
    admits = by_type.get("ingest_admit", [])
    iskips = by_type.get("ingest_skip", [])
    alerts = by_type.get("alert", [])
    ingest_p50_s = ingest_p99_s = None
    alert_fp_rate = None
    incremental_resolves = None
    if by_type.get("counters"):
        incremental_resolves = (by_type["counters"][-1]["counters"]
                                .get("incremental_resolves"))
    p("")
    p("-- online ingest + alerts --")
    if not (admits or iskips or alerts):
        p("  (no ingest events)")
    else:
        if admits:
            waits = [float(ev["wait_s"]) for ev in admits
                     if ev.get("wait_s") is not None]
            if waits:
                ingest_p50_s = float(np.percentile(waits, 50))
                ingest_p99_s = float(np.percentile(waits, 99))
                p(f"  {len(admits)} archive(s) admitted; "
                  f"discovery->admission wait p50 {ingest_p50_s:.3f} s  "
                  f"p99 {ingest_p99_s:.3f} s")
            else:
                p(f"  {len(admits)} archive(s) admitted")
        if iskips:
            reasons = {}
            for ev in iskips:
                reasons[ev["reason"]] = reasons.get(ev["reason"], 0) + 1
            detail = ", ".join(f"{k}: {v}"
                               for k, v in sorted(reasons.items()))
            p(f"  {len(iskips)} admission deferral(s)/skip(s) "
              f"({detail})")
        if alerts:
            # a false positive is an alert the emitter flagged as not
            # matching any known injected/true event ('fp': true) —
            # synthetic corpora set it, live traces leave it absent
            n_fp = sum(1 for ev in alerts if ev.get("fp"))
            alert_fp_rate = n_fp / len(alerts)
            p(f"  {len(alerts)} alert(s) "
              f"({n_fp} flagged false-positive):")
            for ev in alerts[:10]:
                p(f"    {ev['kind']}: {ev['pulsar']} @ MJD "
                  f"{ev['mjd']:.4f}  score {ev['score']:.2f} "
                  f"(threshold {ev['threshold']:.2f})")
        elif admits:
            alert_fp_rate = 0.0
        if incremental_resolves is not None:
            p(f"  incremental GLS: {incremental_resolves} full "
              "resolve(s) against the batch oracle")

    # ---- tuning (tune/, ISSUE 19) -----------------------------------
    t_probe = by_type.get("tune_probe", [])
    t_sweep = by_type.get("tune_sweep", [])
    t_apply = by_type.get("tune_apply", [])
    tune_db_hits = sum(1 for ev in t_apply if ev.get("db_hit"))
    tune_db_misses = len(t_apply) - tune_db_hits
    p("")
    p("-- tuning --")
    if not (t_probe or t_sweep or t_apply):
        p("  (no tuning events)")
    else:
        if t_probe:
            ev = t_probe[-1]
            gf = ev.get("matmul_gflops")
            floor = ev.get("dispatch_floor_s")
            p(f"  backend {ev['fingerprint']}"
              + (f"  dispatch floor {floor * 1e6:.1f} us"
                 if floor else "")
              + (f"  matmul {gf:.1f} GFLOP/s" if gf else ""))
        for ev in t_sweep:
            margin = None
            if ev.get("default_s") and ev.get("best_s") is not None:
                margin = (float(ev["default_s"]) - float(ev["best_s"])) \
                    / float(ev["default_s"])
            p(f"  sweep [{ev['shape_class']}] {ev['knob']}: "
              f"{ev['n_candidates']} candidate(s), "
              f"{ev['n_rejected']} identity-rejected; winner "
              f"{ev['winner']} (default {ev['default']})"
              + (f"  margin {margin * 100:.1f}%"
                 if margin is not None else ""))
        for ev in t_apply:
            knobs = ev.get("knobs") or {}
            detail = ", ".join(f"{k}={v}"
                               for k, v in sorted(knobs.items())) \
                or "defaults"
            p(f"  apply [{ev['shape_class']}] "
              f"{'DB HIT' if ev.get('db_hit') else 'swept'}: {detail}")
        if t_apply:
            p(f"  tuning DB: {tune_db_hits} hit(s), "
              f"{tune_db_misses} miss(es) "
              f"({'zero re-sweeps' if t_apply and not t_sweep else f'{len(t_sweep)} knob sweep(s) paid'})")

    # ---- slo (latency objectives / burn-rate breaches) --------------
    breaches = by_type.get("slo_breach", [])
    slo_breach_tenants = {}
    p("")
    p("-- slo (latency objectives) --")
    if not breaches:
        p("  (no slo_breach events — objectives held, or no "
          "slo_targets configured)")
    else:
        for ev in breaches:
            slo_breach_tenants[ev["tenant"]] = \
                slo_breach_tenants.get(ev["tenant"], 0) + 1
        p(f"  {len(breaches)} fast-burn breach(es) across "
          f"{len(slo_breach_tenants)} tenant(s); each is an EDGE — a "
          "sustained breach emits once until the short window "
          "recovers:")
        for ev in breaches[:10]:
            p(f"    t={ev['t']:.2f}s tenant {ev['tenant']!r} "
              f"({ev.get('source', '?')}): target "
              f"{ev['target_s']:.3f}s, burn short "
              f"{ev['burn_short']:.1f}x / long {ev['burn_long']:.1f}x "
              "of error budget")

    skips = by_type.get("archive_skip", [])
    p("")
    p(f"-- skipped archives ({len(skips)}) --")
    if not skips:
        p("  (no archive_skip events)")
    else:
        for ev in skips[:10]:
            p(f"  {ev['datafile']}: {ev['reason']}")

    counters = {}
    gauges = {}
    if by_type.get("counters"):
        counters = by_type["counters"][-1]["counters"]
        gauges = by_type["counters"][-1]["gauges"]

    return {
        "manifest": manifest,
        "device_counts": device_counts,
        "total_dispatches": total_disp,
        "nfit": nfit_run,
        "max_queue_depth": max_depth,
        "peak_inflight": (gauges.get("peak_inflight")
                          if gauges else peak_run),
        "n_cold": n_cold,
        "cold_s": cold_s,
        "n_h2d": len(h2d),
        "h2d_bytes": h2d_bytes,
        "h2d_bytes_logical": h2d_bytes_logical,
        "h2d_compression": h2d_compression,
        "codec_s": codec_s_total,
        "codec_decisions": codec_decisions,
        "h2d_s": h2d_s,
        "h2d_stall_frac": h2d_stall_frac,
        "n_quality": len(snr),
        "n_force_flush": len(forces),
        "n_skipped": len(skips),
        "n_requests": len(req_done),
        "req_p50_s": req_p50,
        "req_p99_s": req_p99,
        "n_coalesce": len(coalesce),
        "batch_occupancy": occupancy,
        "n_warmup": len(warmups),
        "n_cache_hit": len(c_hit),
        "n_cache_miss": len(c_miss),
        "n_cache_store": len(c_store),
        "n_cache_evict": len(c_evict),
        "cache_hit_rate": cache_hit_rate,
        "cache_bytes_served": cache_bytes_served,
        "cache_bytes_stored": cache_bytes_stored,
        "cache_tenant_hits": cache_tenant_hits,
        "n_route_submit": len(r_sub),
        "n_route_retry": len(r_retry),
        "n_route_done": len(r_done),
        "router_imbalance": router_imbalance,
        "router_host_counts": router_host_counts,
        "n_fleet_transition": len(ftrans),
        "fleet_states": fleet_states,
        "n_failover": len(fover),
        "n_failover_collected": n_failover_collected,
        "n_hedge": len(hedges),
        "tenant_latency": tenant_latency,
        "n_template_fit": len(tfit),
        "n_template_jobs": len(tjobs),
        "template_pad_frac": template_pad_frac,
        "template_wall_s": template_wall_s,
        "n_zap_propose": len(zprop),
        "n_zap_apply": len(zapp),
        "n_refit": len(refits),
        "n_refit_improved": n_refit_improved,
        "refit_rate": refit_rate,
        "zap_channels_cut": zap_channels_cut,
        "zap_wall_s": zap_wall_s,
        "n_timing_fit": len(tim_fit),
        "n_timing_pulsars": n_timing_pulsars,
        "timing_dispatches": timing_dispatches,
        "timing_pad_frac": timing_pad_frac,
        "timing_wall_s": timing_wall_s,
        "n_ingest_admit": len(admits),
        "n_ingest_skip": len(iskips),
        "ingest_p50_s": ingest_p50_s,
        "ingest_p99_s": ingest_p99_s,
        "n_alert": len(alerts),
        "alert_fp_rate": alert_fp_rate,
        "incremental_resolves": incremental_resolves,
        "n_slo_breach": len(breaches),
        "slo_breach_tenants": slo_breach_tenants,
        "n_tune_probe": len(t_probe),
        "n_tune_sweep": len(t_sweep),
        "n_tune_apply": len(t_apply),
        "tune_db_hits": tune_db_hits,
        "tune_db_misses": tune_db_misses,
        "counters": counters,
        "gauges": gauges,
    }


def main(argv=None):
    """``python -m pulseportraiture_tpu.telemetry {report,validate}
    trace.jsonl`` — the same entry tools/pptrace.py wraps."""
    import argparse

    p = argparse.ArgumentParser(
        prog="pptrace",
        description="Analyze a pulseportraiture_tpu campaign trace "
                    "(JSONL, written via config.telemetry_path / "
                    "PPT_TELEMETRY / pptoas --telemetry).")
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="print the full trace report")
    rp.add_argument("trace", help="trace .jsonl path")
    vp = sub.add_parser("validate",
                        help="schema-check a trace and exit")
    vp.add_argument("trace", help="trace .jsonl path")
    mp = sub.add_parser(
        "merge",
        help="stitch a router trace + N host traces into per-request "
             "cross-host span timelines (joined on trace_id)")
    mp.add_argument("traces", nargs="+",
                    help="trace .jsonl paths (router + hosts, any "
                         "order — roles are auto-detected)")
    mp.add_argument("--json", action="store_true",
                    help="emit the merged structure as JSON instead "
                         "of the text timeline")
    args = p.parse_args(argv)
    if args.cmd == "validate":
        manifest, events = validate_trace(args.trace)
        print(f"{args.trace}: ok (schema {manifest['schema']}, "
              f"{len(events)} events)")
        return 0
    if args.cmd == "merge":
        from .obs.merge import main_merge
        main_merge(args.traces, as_json=args.json)
        return 0
    report(args.trace)
    return 0


if __name__ == "__main__":
    sys.exit(main())
