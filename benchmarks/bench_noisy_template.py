"""A/B benchmark: the harmonic window on a DATA-BUILT (noisy) template.

Round 4's headline speedup derived the window from a clean analytic
template; production templates come out of ppspline/ppgauss with a white
Fourier noise floor ~1e-6..1e-4 of total power, which pins the absolute
tail criterion at full spectrum.  This measures the round-5 noise-floor-
aware criterion (fit/portrait.model_harmonic_window) on such a template:
same batched fit, windowed vs full spectrum, plus the window each
criterion derives.  Template noise level via PPT_TEMPLATE_NOISE
(default 1e-2 of peak — the unsmoothed-spline regime measured in
tests/test_harmonic_window.py).

Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.fit.portrait import model_harmonic_window
    from pulseportraiture_tpu.ops.fourier import irfft_mm, rfft_mm
    from pulseportraiture_tpu.ops.phasor import phase_shifts

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    NB = int(os.environ.get("PPT_NB", 640 if on_tpu else 128))
    NCHAN = int(os.environ.get("PPT_NCHAN", 512))
    NBIN = int(os.environ.get("PPT_NBIN", 2048))
    DTYPE = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    s_tmpl = float(os.environ.get("PPT_TEMPLATE_NOISE", 1e-2))

    model_clean, freqs = bench_model(NCHAN, NBIN)
    # the data-built template: clean + white noise at the unsmoothed-
    # spline floor level (same structure the pipeline measurement in
    # test_window_engages_on_pipeline_built_spline_model exhibits)
    rng = np.random.default_rng(7)
    model_noisy = jnp.asarray(
        np.asarray(model_clean, np.float64)
        + rng.standard_normal((NCHAN, NBIN)) * s_tmpl, DTYPE)

    NB_SYNTH = min(128, NB)
    NTILE = -(-NB // NB_SYNTH)  # ceil: NB need not be a multiple

    @jax.jit
    def synth(key):
        k1, k2, k3 = jax.random.split(key, 3)
        phis = 0.1 * jax.random.uniform(k1, (NB_SYNTH,), DTYPE)
        dms = 0.003 * jax.random.uniform(k2, (NB_SYNTH,), DTYPE)
        delays = jax.vmap(
            lambda ph, dm: phase_shifts(ph, dm, 0.0, freqs, P, NU_FIT,
                                        NU_FIT))(phis, dms)
        Xr, Xi = rfft_mm(model_clean)
        k = jnp.arange(Xr.shape[-1], dtype=DTYPE)
        ang = -2.0 * jnp.pi * delays[..., None] * k
        c, s = jnp.cos(ang), jnp.sin(ang)
        rot = irfft_mm(Xr * c - Xi * s, Xr * s + Xi * c, NBIN)
        return rot + 0.05 * jax.random.normal(k3, rot.shape, DTYPE)

    ports = jnp.tile(synth(jax.random.PRNGKey(0)),
                     (NTILE, 1, 1))[:NB]
    noise = jnp.full((NB, NCHAN), 0.05, DTYPE)
    Ps = jnp.full((NB,), P, DTYPE)
    nus = jnp.full((NB,), NU_FIT, DTYPE)
    jax.block_until_ready(ports)

    mp_host = np.asarray(model_noisy)
    K_abs = model_harmonic_window(mp_host, NBIN, floor_sigma=0)
    K = model_harmonic_window(mp_host, NBIN)

    def run(hw):
        return fit_portrait_batch_fast(ports, model_noisy, noise, freqs,
                                       Ps, nus, max_iter=25,
                                       harmonic_window=hw)

    slope_full, lat_full = devtime(lambda: run(False), lambda r: r.phi)
    slope_win, lat_win = devtime(
        lambda: run(K if K is not None else False), lambda r: r.phi)

    # accuracy: windowed vs full on the same portraits
    rf, rt = run(False), run(K if K is not None else False)
    dphi = float(jnp.max(jnp.abs(rf.phi - rt.phi)))

    out = {
        "metric": "windowed-vs-full fit on noisy (data-built) template, "
                  "512ch x 2048bin",
        "value": round(NB / slope_win, 2),
        "unit": "TOAs/sec",
        "vs_baseline": round(slope_full / slope_win, 2),
        "full_toas_per_sec": round(NB / slope_full, 2),
        "template_noise": s_tmpl,
        "window_floor_aware": K,
        "window_absolute_criterion": K_abs,
        "batch": NB,
        "batch_ms_windowed": round(slope_win * 1e3, 2),
        "batch_ms_full": round(slope_full * 1e3, 2),
        "max_dphi_windowed_vs_full": float(f"{dphi:.2e}"),
        "accuracy_gate_1e-4": bool(dphi < 1e-4),
        "device": str(dev),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
