"""ppserve — run the continuous-batching TOA service over a request
set.

The serving loop (serve/server.ToaServer) keeps ONE warm stream
executor alive and coalesces compatible subints across requests into
shared fused dispatches; this CLI is its batch client: it reads a
JSONL request file, submits every request concurrently through the
bounded admission queue (retrying politely on backpressure), waits for
all results, and writes one ``<name>.tim`` per request — each
byte-identical to what the one-shot ``pptoas --stream`` would produce
for the same archives.

Request file: one JSON object per line —
    {"name": "J0030+0451", "datafiles": ["a.fits", ...] | "meta.txt",
     "modelfile": "J0030.spl", "options": {"fit_scat": true, ...},
     "tenant": "interactive"}
``options`` are stream_wideband_TOAs fit options (lane options);
requests sharing (modelfile, options) coalesce.  ``tenant``
(optional) labels the request's weighted-fair QoS lane
(config.serve_tenant_quota / serve_tenant_weight).

``--warmup-manifest trace.jsonl`` AOT-compiles every dispatch shape a
prior run's telemetry trace recorded before serving starts
(utils/device.warmup_from_manifest), so the first requests skip the
cold-start compiles; gate the before/after with ``--telemetry`` and
``tools/pptrace.py report`` (cold-start + serve sections).

``--listen HOST:PORT`` (or PPT_SERVE_LISTEN) runs the OTHER mode: no
request file — the warm server is exposed to remote clients over the
length-prefixed JSON transport (serve/transport.TransportServer), and
a ``pproute`` router on any machine shards campaign requests across a
fleet of such listeners (ISSUE 10).  Archive paths in remote requests
must be visible on THIS host (shared filesystem); each request's
``.tim`` is written here, byte-identical to the one-shot driver.
Port 0 binds an ephemeral port (printed at start).  The process
serves until SIGINT/SIGTERM, then drains gracefully.
"""

import argparse
import json
import os
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppserve", description=__doc__.splitlines()[0])
    p.add_argument("-r", "--requests", metavar="requests.jsonl",
                   default=None,
                   help="JSONL request file (one JSON object per "
                        "line: name, datafiles, modelfile, options). "
                        "Exactly one of -r / --listen.")
    p.add_argument("--listen", metavar="HOST:PORT", default=None,
                   help="Serve REMOTE clients instead of a request "
                        "file: expose the warm server on this "
                        "endpoint (port 0 = ephemeral, printed) for "
                        "pproute/SocketTransport clients; runs until "
                        "SIGINT, then drains. Also via "
                        "PPT_SERVE_LISTEN. [default: off]")
    p.add_argument("-O", "--outdir", metavar="DIR", default=".",
                   help="Directory for per-request <name>.tim outputs "
                        "(created). [default: .]")
    p.add_argument("--nsub-batch", dest="nsub_batch", type=int,
                   default=64, metavar="N",
                   help="Fused-bucket row count (the compiled batch "
                        "shape class). [default: 64]")
    p.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                   default=None, metavar="MS",
                   help="Deadline for partially-filled buckets: a "
                        "bucket launches when full OR when its oldest "
                        "subint has waited this long. [default: "
                        "config.serve_max_wait_ms / "
                        "PPT_SERVE_MAX_WAIT_MS]")
    p.add_argument("--queue-depth", dest="queue_depth", type=int,
                   default=None, metavar="N",
                   help="Admission-queue capacity in archives; a "
                        "submit beyond it is rejected loudly "
                        "(backpressure). [default: "
                        "config.serve_queue_depth / "
                        "PPT_SERVE_QUEUE_DEPTH]")
    p.add_argument("--stream-devices", dest="stream_devices",
                   default=None, metavar="auto|N",
                   help="Local devices to deal fused buckets across "
                        "('auto' = all, or a count). [default: "
                        "config.stream_devices]")
    p.add_argument("--max-inflight", dest="max_inflight", type=int,
                   default=None, metavar="N",
                   help="Pending fused dispatches per device before "
                        "the loop blocks on the oldest. [default: "
                        "config.stream_max_inflight]")
    p.add_argument("--pipeline-depth", dest="pipeline_depth",
                   default=None, type=int, metavar="N",
                   help="Per-device copy->fit transfer-pipeline "
                        "depth. [default: config.stream_pipeline_depth]")
    p.add_argument("--warmup-manifest", dest="warmup_manifest",
                   default=None, metavar="trace.jsonl",
                   help="AOT-compile every dispatch shape this prior "
                        "telemetry trace records before serving "
                        "starts (kills the cold-start compiles).")
    p.add_argument("--warmup-model", dest="warmup_model", default=None,
                   metavar="model",
                   help="Template whose portrait shapes the warmup "
                        "programs (with --warmup-manifest). "
                        "[default: synthetic profile]")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Write the serve trace (request lifecycle, "
                        "batch_coalesce occupancy, cold starts) here; "
                        "analyze with tools/pptrace.py. Also via "
                        "PPT_TELEMETRY. [default: off]")
    p.add_argument("--compile-cache", dest="compile_cache",
                   default=None, metavar="DIR",
                   help="Persistent jax compilation cache directory "
                        "(restarts skip the XLA compiles). Also via "
                        "PPT_COMPILE_CACHE. [default: off]")
    p.add_argument("--timeout", type=float, default=None, metavar="S",
                   help="Per-request result timeout in seconds. "
                        "[default: none]")
    add_cache_flags(p)
    add_tune_flags(p)
    add_obs_flags(p)
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def add_obs_flags(p):
    """The fleet-observability flags (ISSUE 20), shared by ppserve /
    pproute: the streaming metrics registry and the per-tenant SLO
    targets the burn-rate engine tracks."""
    p.add_argument("--metrics", dest="metrics", default=None,
                   metavar="off|on",
                   help="Streaming metrics registry (counters + "
                        "log-bucket latency histograms, exported over "
                        "the 'metrics' transport op for ppmon). .tim "
                        "output is byte-identical either way. Also "
                        "via PPT_METRICS. [default: on]")
    p.add_argument("--slo-targets", dest="slo_targets", default=None,
                   metavar="t:SEC,...|SEC|off",
                   help="Per-tenant request-latency SLO targets in "
                        "seconds ('*' = default tenant; a bare number "
                        "applies to every tenant). Burn-rate "
                        "breaches emit slo_breach telemetry and ride "
                        "the metrics export. Also via "
                        "PPT_SLO_TARGETS. [default: off]")


def apply_obs_flags(args, prog):
    """Validate the obs flags LOUDLY and apply them to config before
    server/router construction (the ctors snapshot config.metrics /
    config.slo_targets when not passed explicitly)."""
    from .. import config

    if args.metrics is not None:
        table = {"off": False, "on": True}
        v = str(args.metrics).lower()
        if v not in table:
            raise SystemExit(
                f"{prog}: --metrics: expected 'off' or 'on', got "
                f"{args.metrics!r}")
        config.metrics = table[v]
    if args.slo_targets is not None:
        s = str(args.slo_targets).strip()
        if s.lower() in ("off", "none"):
            config.slo_targets = None
        else:
            try:
                config.slo_targets = config.parse_tenant_spec(
                    s, "--slo-targets", cast=float, allow_bare=True)
            except ValueError as e:
                raise SystemExit(f"{prog}: {e}")


def add_tune_flags(p):
    """The tuning-DB flag (ISSUE 19), shared by ppserve / pproute /
    pptoas: point the process at a persisted per-backend tuning DB
    (tune/store.TuningStore)."""
    p.add_argument("--tune-db", dest="tune_db", default=None,
                   metavar="PATH",
                   help="Persisted per-backend tuning DB (JSON): "
                        "stored knob winners for this backend "
                        "fingerprint are applied at startup; a DB "
                        "from a different backend is refused with a "
                        "warning. Also via PPT_TUNE_DB. "
                        "[default: off]")


def apply_tune_flags(args, prog, tracer=None):
    """Apply --tune-db to config and load any stored winners for this
    backend (LOUD warnings on stale/corrupt DBs come from the
    store)."""
    from .. import config
    from ..telemetry import NULL_TRACER
    from ..tune import apply_from_db

    if getattr(args, "tune_db", None) is not None:
        config.tune_db = args.tune_db
    if config.tune_db:
        apply_from_db(tracer=tracer if tracer is not None
                      else NULL_TRACER)


def add_cache_flags(p):
    """The content-addressed result-cache flags (ISSUE 17), shared by
    ppserve / pproute / ppfactory."""
    p.add_argument("--result-cache", dest="result_cache", default=None,
                   metavar="off|auto|on",
                   help="Content-addressed result cache: 'off', "
                        "'auto' (on iff a cache dir is set — the "
                        "default), or 'on' (requires --cache-dir). "
                        "Hits are byte-identical to fresh fits. Also "
                        "via PPT_RESULT_CACHE. [default: auto]")
    p.add_argument("--cache-dir", dest="cache_dir", default=None,
                   metavar="DIR",
                   help="On-disk store directory (created on demand). "
                        "Also via PPT_CACHE_DIR. [default: off]")
    p.add_argument("--cache-max-mb", dest="cache_max_mb", type=float,
                   default=None, metavar="MB",
                   help="Store size bound; least-recently-used "
                        "entries evict beyond it. Also via "
                        "PPT_CACHE_MAX_MB. [default: "
                        "config.cache_max_mb]")


def apply_cache_flags(args, prog):
    """Validate the cache flags LOUDLY and apply them to config before
    any server/router/factory construction (the tri-state resolves at
    construction time)."""
    from .. import config

    if args.result_cache is not None:
        table = {"off": False, "auto": "auto", "on": True}
        v = str(args.result_cache).lower()
        if v not in table:
            raise SystemExit(
                f"{prog}: --result-cache: expected 'off', 'auto' or "
                f"'on', got {args.result_cache!r}")
        config.result_cache = table[v]
    if args.cache_max_mb is not None:
        if args.cache_max_mb <= 0:
            raise SystemExit(
                f"{prog}: --cache-max-mb: must be > 0, got "
                f"{args.cache_max_mb}")
        config.cache_max_mb = args.cache_max_mb
    if args.cache_dir is not None:
        config.cache_dir = args.cache_dir
    if config.result_cache is True and not config.cache_dir:
        raise SystemExit(
            f"{prog}: --result-cache on requires --cache-dir (or "
            "PPT_CACHE_DIR): an explicitly-on cache with nowhere to "
            "store entries would silently serve nothing")


def parse_requests(path):
    """Read + validate the JSONL request file -> list of dicts with
    name/datafiles/modelfile/options.  Loud SystemExit on anything
    malformed (a silently-dropped request line is a lost pulsar)."""
    if not os.path.exists(path):
        raise SystemExit(f"ppserve: request file not found: {path}")
    reqs, names = [], set()
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: bad JSON: {e}")
            if not isinstance(rec, dict):
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: expected an object")
            missing = {"datafiles", "modelfile"} - set(rec)
            if missing:
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: missing "
                    f"{sorted(missing)}")
            name = str(rec.get("name", f"req{lineno}"))
            if name in names:
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: duplicate request "
                    f"name {name!r} (each writes <name>.tim)")
            names.add(name)
            options = rec.get("options", {})
            if not isinstance(options, dict):
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: options must be an "
                    "object")
            tenant = rec.get("tenant")
            if tenant is not None and not isinstance(tenant, str):
                raise SystemExit(
                    f"ppserve: {path}:{lineno}: tenant must be a "
                    "string (the QoS lane label)")
            reqs.append(dict(name=name, datafiles=rec["datafiles"],
                             modelfile=str(rec["modelfile"]),
                             options=options, tenant=tenant))
    if not reqs:
        raise SystemExit(f"ppserve: no requests in {path}")
    return reqs


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.nsub_batch < 1:
        raise SystemExit("--nsub-batch: must be >= 1, got "
                         f"{args.nsub_batch}")
    if args.max_wait_ms is not None and args.max_wait_ms < 0:
        raise SystemExit("--max-wait-ms: must be >= 0, got "
                         f"{args.max_wait_ms}")
    if args.queue_depth is not None and args.queue_depth < 1:
        raise SystemExit("--queue-depth: must be >= 1, got "
                         f"{args.queue_depth}")
    if args.max_inflight is not None and args.max_inflight < 1:
        raise SystemExit("--max-inflight: must be >= 1, got "
                         f"{args.max_inflight}")
    if args.pipeline_depth is not None and args.pipeline_depth < 1:
        raise SystemExit("--pipeline-depth: depth must be >= 1, got "
                         f"{args.pipeline_depth}")
    stream_devices = args.stream_devices
    if stream_devices is not None:
        s = str(stream_devices).strip().lower()
        if s == "auto":
            stream_devices = "auto"
        else:
            try:
                stream_devices = int(s)
            except ValueError:
                raise SystemExit("--stream-devices: expected 'auto' "
                                 f"or a positive count, got "
                                 f"{args.stream_devices!r}")
            if stream_devices < 1:
                raise SystemExit("--stream-devices: count must be "
                                 f">= 1, got {stream_devices}")
    if args.warmup_model and not args.warmup_manifest:
        raise SystemExit("--warmup-model requires --warmup-manifest")
    from .. import config

    if args.listen is not None and args.requests is not None:
        raise SystemExit("ppserve: -r/--requests and --listen are "
                         "mutually exclusive (batch client vs fleet "
                         "member)")
    # PPT_SERVE_LISTEN is only a DEFAULT for the listen mode: an
    # explicit -r is a batch-mode request and must not conflict with
    # a fleet host's environment profile
    listen = args.listen
    if listen is None and args.requests is None:
        listen = config.serve_listen
    if listen is None and args.requests is None:
        raise SystemExit("ppserve: need -r/--requests (batch mode) or "
                         "--listen HOST:PORT (fleet member)")
    if listen is not None:
        try:
            config.parse_hostport(listen)
        except ValueError as e:
            raise SystemExit(f"ppserve: --listen: {e}")
        reqs = None
    else:
        reqs = parse_requests(args.requests)

    if args.compile_cache:
        from ..utils.device import enable_compile_cache

        config.compile_cache_dir = args.compile_cache
        enable_compile_cache(args.compile_cache)
    apply_cache_flags(args, "ppserve")
    apply_tune_flags(args, "ppserve")
    apply_obs_flags(args, "ppserve")
    os.makedirs(args.outdir, exist_ok=True)

    from ..serve import ServeRejected, ToaServer

    server = ToaServer(
        nsub_batch=args.nsub_batch, max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth, stream_devices=stream_devices,
        max_inflight=args.max_inflight,
        pipeline_depth=args.pipeline_depth, telemetry=args.telemetry,
        warmup_manifest=args.warmup_manifest,
        warmup_model=args.warmup_model, quiet=args.quiet)

    if listen is not None:
        # fleet-member mode: expose the warm loop to remote routers
        # and serve until a signal, then drain gracefully
        import signal
        import threading

        from ..serve import TransportServer

        host, port = config.parse_hostport(listen)
        stop = threading.Event()
        server.start()
        transport = TransportServer(server, host=host, port=port,
                                    quiet=args.quiet).start()
        print(f"ppserve: listening on {transport.label} "
              f"({len(server._ex.devices)} device(s)); Ctrl-C to "
              "drain and exit", flush=True)
        try:
            signal.signal(signal.SIGTERM,
                          lambda *a: stop.set())
            signal.signal(signal.SIGINT,
                          lambda *a: stop.set())
        except ValueError:
            pass  # not the main thread (tests drive main() directly)
        try:
            stop.wait()
        except KeyboardInterrupt:
            pass
        transport.close()
        server.stop(drain=True)
        return 0

    failures = 0
    t0 = time.time()
    with server:
        handles = []
        for rec in reqs:
            tim = os.path.join(args.outdir, f"{rec['name']}.tim")
            while True:
                try:
                    handles.append(server.submit(
                        rec["datafiles"], rec["modelfile"],
                        tim_out=tim, name=rec["name"],
                        tenant=rec.get("tenant"),
                        **rec["options"]))
                    break
                except ServeRejected as e:
                    if not e.retryable:
                        raise
                    # the CLI is a patient batch client: honor the
                    # backpressure instead of failing the run
                    if not args.quiet:
                        print(f"ppserve: {e}; retrying",
                              file=sys.stderr)
                    time.sleep(0.05)
        for rec, h in zip(reqs, handles):
            try:
                res = h.result(args.timeout)
            except Exception as e:
                failures += 1
                print(f"ppserve: request {rec['name']!r} FAILED: {e}",
                      file=sys.stderr)
                continue
            if not args.quiet:
                print(f"ppserve: {rec['name']}: "
                      f"{len(res.TOA_list)} TOAs from "
                      f"{len(res.order)} archive(s) -> {res.tim_out}")
    if not args.quiet:
        print(f"ppserve: {len(reqs) - failures}/{len(reqs)} requests "
              f"in {time.time() - t0:.2f} s")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
