"""ppzap — propose (and optionally apply) channel zaps.

Flag parity: reference ppzap.py:107-253.  Model-based path runs the
full GetTOAs fit and flags channels by red-chi2/S-N; model-less path
uses the iterative median algorithm on channel noise levels.  Beyond
the reference (which only prints `paz` commands), --apply edits the
archive weights directly, --telemetry emits the same ``zap_propose``/
``zap_apply`` events the inline streaming lane traces (so offline and
inline excision are analyzed with one pptrace report), and the device
lane runs each archive's whole iterative cut in ONE dispatch.
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppzap", description=__doc__.splitlines()[0])
    p.add_argument("-d", "--datafiles", required=True,
                   help="PSRFITS archive or metafile of archive names.")
    p.add_argument("-n", "--num_std", dest="nstd", type=float,
                   default=None,
                   help="Threshold [std] for the median algorithm "
                        "(default: config.zap_nstd / PPT_ZAP_NSTD).")
    p.add_argument("-N", "--norm", default=None,
                   choices=(None, "mean", "max", "prof", "rms", "abs"),
                   help="Normalize before the median algorithm.")
    p.add_argument("-m", "--modelfile", default=None,
                   help="Model file: use the fit-based zapping path.")
    p.add_argument("-T", "--tscrunch", action="store_true", default=False)
    p.add_argument("-S", "--SNR-threshold", dest="SNR_threshold",
                   type=float, default=8.0)
    p.add_argument("-R", "--rchi2-threshold", dest="rchi2_threshold",
                   type=float, default=1.3)
    p.add_argument("-o", "--outfile", default=None,
                   help="Write the paz commands to this file.")
    p.add_argument("--append", action="store_true", default=False,
                   help="Append to --outfile instead of overwriting "
                        "(the old always-append behavior silently "
                        "duplicated commands on reruns).")
    p.add_argument("--modify", action="store_true", default=False,
                   help="Print paz -m (modify in place) commands.")
    p.add_argument("--apply", action="store_true", default=False,
                   help="Apply the zaps directly to the archives "
                        "(weight edits; no PSRCHIVE needed).")
    p.add_argument("--hist", action="store_true", default=False,
                   help="Save a channel red-chi2 histogram (model path).")
    p.add_argument("--zap-device", default=None,
                   choices=("off", "auto", "on"),
                   help="Route the zap cut through the batched device "
                        "program (default: config.zap_device / "
                        "PPT_ZAP_DEVICE; flagged lists are digit-"
                        "identical either way).")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Append zap_propose/zap_apply events to this "
                        "JSONL trace (default: PPT_TELEMETRY / "
                        "config.telemetry_path; analyze with pptrace).")
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    from ..io.psrfits import load_data
    from ..pipeline.toas import GetTOAs, _is_metafile, _read_metafile
    from ..pipeline.zap import apply_zaps, get_zap_channels, print_paz_cmds
    from ..telemetry import resolve_tracer

    if _is_metafile(args.datafiles):
        datafiles = _read_metafile(args.datafiles)
    else:
        datafiles = [args.datafiles]

    device = (None if args.zap_device is None else
              {"off": False, "auto": "auto", "on": True}[args.zap_device])
    tracer, own_tracer = resolve_tracer(args.telemetry, run="ppzap")
    try:
        if args.modelfile:
            gt = GetTOAs(datafiles, args.modelfile, quiet=True)
            gt.get_TOAs(tscrunch=args.tscrunch, quiet=True)
            zap_list = gt.get_channels_to_zap(
                SNR_threshold=args.SNR_threshold,
                rchi2_threshold=args.rchi2_threshold,
                device=device, telemetry=tracer)
            # zap_list is aligned with gt.order (archives that actually
            # fitted), which may be shorter than datafiles if any were
            # skipped — keep the pairing consistent downstream
            datafiles = list(gt.order)
            if args.hist:
                import matplotlib

                matplotlib.use("Agg", force=True)
                import matplotlib.pyplot as plt
                import numpy as np

                vals = np.concatenate(
                    [r[np.isfinite(r)] for r in
                     (np.asarray(x).ravel() for x in gt.red_chi2s)])
                fig, ax = plt.subplots()
                ax.hist(vals, bins=30, color="0.3")
                ax.axvline(args.rchi2_threshold, color="r")
                ax.set_xlabel(r"red-$\chi^2$")
                fig.savefig(args.datafiles + ".rchi2.png",
                            bbox_inches="tight")
        else:
            zap_list = []
            for path in datafiles:
                d = load_data(path, dedisperse=False, dededisperse=True,
                              tscrunch=args.tscrunch, pscrunch=True,
                              quiet=True)
                if args.norm:
                    from ..pipeline.portrait import normalize_portrait

                    for isub in d.ok_isubs:
                        d.subints[isub, 0] = normalize_portrait(
                            d.subints[isub, 0], args.norm)
                        from ..io.psrfits import noise_std_ps

                        d.noise_stds[isub, 0] = noise_std_ps(
                            d.subints[isub, 0])
                zap_list.append(get_zap_channels(
                    d, nstd=args.nstd, device=device, tracer=tracer))

        total = sum(sum(len(z) for z in arch) for arch in zap_list)
        if not args.quiet:
            print(f"{total} channel entries flagged.")
        print_paz_cmds(datafiles, zap_list, modify=args.modify,
                       outfile=args.outfile, quiet=args.quiet,
                       append=args.append)
        if args.apply:
            for iarch, path in enumerate(datafiles):
                apply_zaps(path, zap_list[iarch], quiet=args.quiet,
                           tracer=tracer)
    finally:
        if own_tracer:
            tracer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
