"""Harmonic-domain filters and the power-spectrum noise-floor cutoff.

Capability parity with the reference's filter utilities
(pplib.py:1450-1561) and the 'fit' noise method (get_noise_fit,
pplib.py:2341-2373).  These are offline/host-side estimators used for
noise characterization and profile smoothing — numpy, not jax (they
run once per channel at load/model-build time, never inside the fit
loop, and find_kc's grid search is data-dependent control flow).

The reference marks wiener_filter "does not work" and fit_brickwall
"obviously wrong"; here both are implemented correctly: the Wiener
filter uses the noise-debiased signal power estimate, and the
brickwall fit picks the cutoff minimizing squared deviation from the
Wiener filter computed analytically via cumulative sums instead of an
O(N^2) python loop.
"""

import numpy as np

__all__ = [
    "wiener_filter",
    "brickwall_filter",
    "fit_brickwall",
    "half_triangle_function",
    "find_kc",
    "get_noise_fit",
]


def wiener_filter(prof, noise):
    """Wiener filter W_k = S_k / (S_k + N) for a noisy profile.

    prof: 1-D profile; noise: time-domain standard error of the profile.
    Returns the per-harmonic filter (len nbin//2+1, values in [0, 1]).

    Unlike the reference (pplib.py:1450-1464, marked "FIX does not
    work"), the signal power S_k is estimated by subtracting the
    expected white-noise power floor from the measured power, clipped
    at zero, which makes W_k -> 0 in noise-dominated harmonics.
    """
    prof = np.asarray(prof, np.float64)
    FFT = np.fft.rfft(prof)
    pows = (FFT * np.conj(FFT)).real / len(prof)
    # white noise of std sigma has E|X_k|^2 = nbin sigma^2, so in these
    # per-harmonic units the expected noise floor is exactly sigma^2
    noise_pow = float(noise) ** 2
    sig = np.clip(pows - noise_pow, 0.0, None)
    denom = np.where(pows > 0.0, pows, 1.0)
    return np.where(pows > 0.0, sig / denom, 0.0)


def brickwall_filter(N, kc):
    """Length-N filter: ones below harmonic kc, zeros above
    (reference pplib.py:1468-1476)."""
    fk = np.zeros(N)
    fk[: int(kc)] = 1.0
    return fk


def fit_brickwall(prof, noise):
    """Best-fit brickwall cutoff kc to the Wiener filter of prof.

    Minimizes sum_k (W_k - brickwall(kc)_k)^2 over kc.  Computed in
    closed form with cumulative sums: the objective at cutoff kc is
    sum_{k<kc}(W_k-1)^2 + sum_{k>=kc} W_k^2 (replaces the reference's
    O(N^2) loop at pplib.py:1479-1493, marked "obviously wrong").
    """
    wf = wiener_filter(prof, noise)
    # cost(kc) = prefix[(W-1)^2](kc) + (total[W^2] - prefix[W^2](kc))
    c1 = np.concatenate([[0.0], np.cumsum((wf - 1.0) ** 2)])
    c2 = np.concatenate([[0.0], np.cumsum(wf**2)])
    cost = c1 + (c2[-1] - c2)
    return int(np.argmin(cost))


def half_triangle_function(a, b, dc, N):
    """Half-triangle of base a, height b on a dc baseline, length N
    (reference pplib.py:1496-1506)."""
    fn = np.zeros(N) + dc
    a = int(np.floor(a))
    if a > 0:
        fn[:a] += -(np.float64(b) / a) * np.arange(a) + b
    return fn


def _kc_models(params_grid, N, fn):
    """Model curves for each (a, b, dc) row of params_grid, vectorized."""
    a = params_grid[:, 0:1]
    b = params_grid[:, 1:2]
    dc = params_grid[:, 2:3]
    x = np.arange(N)[None, :]
    if fn == "exp_dc":
        return b * np.exp(-a * x) + dc
    # half_tri: piecewise-linear descent over the first floor(a) points
    af = np.floor(a)
    ramp = np.where(x < af, -(b / np.maximum(af, 1.0)) * x + b, 0.0)
    return ramp + dc


def find_kc(pows, errs=1.0, fn="exp_dc", Ns=20):
    """Critical cutoff index where the noise floor of a power spectrum
    begins (reference pplib.py:1536-1561).

    Fits log10(pows) with a decaying exponential ('exp_dc') or
    half-triangle ('half_tri') over a brute-force parameter grid
    (vectorized over the whole grid instead of scipy.optimize.brute),
    then returns the first index where the fitted shape has decayed
    to <0.5% of its height ('exp_dc') or the fitted base ('half_tri').
    """
    pows = np.asarray(pows, np.float64)
    if not np.any(pows > 0.0):  # fully zapped channel: no spectrum
        return 0
    # an exactly-zero power (e.g. removed DC) would put -inf into the
    # log and NaN the whole chi2 grid; floor at 1e-12 of the peak
    pows = np.maximum(pows, pows.max() * 1e-12)
    data = np.log10(pows)
    N = len(data)
    lo, hi = data.min(), data.max()
    if fn == "exp_dc":
        a_r = np.linspace(N**-1.0, 1.0, Ns)
    elif fn == "half_tri":
        a_r = np.linspace(1, N, Ns)
    else:
        raise ValueError(f"unknown noise-floor fit function {fn!r}")
    b_r = np.linspace(0, hi - lo, Ns)
    dc_r = np.linspace(lo, hi, Ns)
    grid = np.stack(
        [g.ravel() for g in np.meshgrid(a_r, b_r, dc_r, indexing="ij")], axis=1
    )
    models = _kc_models(grid, N, fn)
    chi2 = np.sum(((data[None, :] - models) / errs) ** 2, axis=1)
    imin = int(np.argmin(chi2))
    a, b, dc = grid[imin]
    # significance check: a fitted decay height within the residual
    # scatter means the spectrum is flat (pure noise floor) — cutoff 0.
    # Without this, a tiny spurious b with slow decay returns N-1 and
    # the noise would be estimated from only the last few harmonics.
    resid = data - models[imin]
    if b <= 2.0 * resid.std():
        return 0
    if fn == "exp_dc":
        decayed = np.where(np.exp(-a * np.arange(N)) < 0.005)[0]
        return int(decayed.min()) if len(decayed) else N - 1
    return int(np.floor(a))


def get_noise_fit(data, fact=1.1, chans=False):
    """Off-pulse noise estimate from the mean power above a fitted
    noise-floor cutoff harmonic (reference pplib.py:2341-2373).

    data: 1- or 2-D array; fact scales the fitted cutoff; chans=True
    returns a per-channel estimate for 2-D input.
    """
    data = np.asarray(data, np.float64)
    if chans:
        # per-profile estimate over all leading axes, matching
        # get_noise_PS's batching: (..., nbin) -> (...)
        flat = data.reshape(-1, data.shape[-1])
        out = np.array([get_noise_fit(prof, fact=fact) for prof in flat])
        return out.reshape(data.shape[:-1])
    raveld = data.ravel()
    FFT = np.fft.rfft(raveld)
    pows = (FFT * np.conj(FFT)).real / len(raveld)
    k_crit = min(int(fact * find_kc(pows)), int(0.99 * len(pows)))
    return float(np.sqrt(np.mean(pows[k_crit:])))
