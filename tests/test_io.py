"""FITS codec + PSRFITS archive round-trip tests.

Oracle strategy (SURVEY §4): write archives from known arrays, read
them back, and assert bit-level/np.allclose recovery of data, weights,
frequencies, epochs, and folding periods; load_data key-set parity
with the reference's DataBunch (pplib.py:2904-2914).
"""

import numpy as np
import pytest

from pulseportraiture_tpu.io import fitsio
from pulseportraiture_tpu.io.psrfits import (
    load_data,
    new_archive,
    polyco_phase_freq,
    read_archive,
    unload_new_archive,
)
from pulseportraiture_tpu.utils.mjd import MJD


def _toy_archive(nsub=3, npol=1, nchan=8, nbin=64, DM=10.0, seed=0):
    rng = np.random.default_rng(seed)
    prof = np.exp(-0.5 * ((np.arange(nbin) / nbin - 0.3) / 0.02) ** 2)
    amps = (prof[None, None, None] * (1 + 0.3 * rng.random((nsub, npol,
                                                            nchan, 1)))
            + 0.01 * rng.normal(size=(nsub, npol, nchan, nbin)))
    freqs = np.linspace(1300.0, 1500.0, nchan)
    epochs = [MJD(55000, 0.1).add_seconds(60.0 * i) for i in range(nsub)]
    arch = new_archive(amps, freqs, 0.005, epochs, 60.0, DM=DM,
                       dedispersed=True, source="J0000+0000",
                       telescope="GBT",
                       psrparam=["PSR J0000+0000", "F0 200.0", "DM 10.0"])
    return arch, amps, freqs, epochs


def test_fits_roundtrip_bintable(tmp_path):
    from collections import OrderedDict

    path = tmp_path / "t.fits"
    rng = np.random.default_rng(1)
    cols = OrderedDict(
        A=rng.normal(size=(5, 16)).astype(">f8"),
        B=np.arange(5, dtype=">i4"),
        C=np.array([b"abc", b"de", b"fghi", b"j", b"kl"], dtype="S6"),
        D=rng.normal(size=(5, 2, 3)).astype(">f4"),
    )
    with open(path, "wb") as f:
        fitsio.write_primary(f, [("TESTKEY", 42, "a comment"),
                                 ("TESTSTR", "hello", ""),
                                 ("TESTFLT", 3.25, ""),
                                 ("TESTBOOL", True, "")])
        fitsio.write_bintable(f, "TTAB", cols, tdims={"D": (3, 2)})
    hdus = fitsio.read_fits(path)
    assert hdus[0].header["TESTKEY"] == 42
    assert hdus[0].header["TESTSTR"] == "hello"
    assert hdus[0].header["TESTFLT"] == 3.25
    assert hdus[0].header["TESTBOOL"] is True
    tab = fitsio.get_hdu(hdus, "TTAB")
    np.testing.assert_array_equal(tab.data["A"],
                                  cols["A"].astype(np.float64))
    np.testing.assert_array_equal(tab.data["B"], np.arange(5))
    assert [s.strip() for s in tab.data["C"].astype(str)] == \
        ["abc", "de", "fghi", "j", "kl"]
    assert tab.data["D"].shape == (5, 2, 3)
    np.testing.assert_allclose(tab.data["D"], cols["D"].astype(np.float64))


def test_archive_roundtrip(tmp_path):
    arch, amps, freqs, epochs = _toy_archive()
    path = tmp_path / "toy.fits"
    arch.unload(path)
    back = read_archive(path)
    # 16-bit quantization: relative error ~ range/65530
    scale = amps.max() - amps.min()
    np.testing.assert_allclose(back.amps, amps, atol=2e-4 * scale)
    np.testing.assert_allclose(back.freqs_table[0], freqs)
    np.testing.assert_allclose(back.folding_periods(), 0.005)
    assert back.get_dispersion_measure() == 10.0
    assert back.get_dedispersed()
    assert back.get_source() == "J0000+0000"
    eps = back.epochs()
    for e_in, e_out in zip(epochs, eps):
        assert abs(e_out - e_in) * 86400.0 < 1e-6  # < 1 us epoch error


def test_load_data_keys_and_values(tmp_path):
    arch, amps, freqs, epochs = _toy_archive()
    path = tmp_path / "toy.fits"
    arch.unload(path)
    d = load_data(path, quiet=True)
    expected_keys = {
        "arch", "backend", "backend_delay", "bw", "doppler_factors",
        "DM", "dmc", "epochs", "filename", "flux_prof", "freqs",
        "frontend", "integration_length", "masks", "nbin", "nchan",
        "noise_stds", "npol", "nsub", "nu0", "ok_ichans", "ok_isubs",
        "parallactic_angles", "phases", "prof", "prof_noise", "prof_SNR",
        "Ps", "SNRs", "source", "state", "subints", "subtimes",
        "telescope", "telescope_code", "weights"}
    assert expected_keys <= set(d.keys())
    assert d.nsub == 3 and d.nchan == 8 and d.nbin == 64 and d.npol == 1
    assert d.telescope_code == "1"  # GBT
    assert d.subints.shape == (3, 1, 8, 64)
    assert d.masks.shape == (3, 1, 8, 64)
    assert len(d.ok_ichans[0]) == 8
    assert d.prof_SNR > 10
    # baseline removed: off-pulse mean ~ 0
    off = d.subints[..., :4].mean()
    assert abs(off) < 0.02


def test_load_data_zapped_channels(tmp_path):
    arch, amps, freqs, epochs = _toy_archive()
    w = np.ones((3, 8))
    w[:, 2] = 0.0
    arch.set_weights(w)
    path = tmp_path / "toy.fits"
    arch.unload(path)
    d = load_data(path, quiet=True)
    assert list(d.ok_ichans[0]) == [0, 1, 3, 4, 5, 6, 7]
    assert d.masks[0, 0, 2].sum() == 0.0


def test_dedisperse_inverse(tmp_path):
    """dededisperse then dedisperse restores the data (rotate o
    unrotate = id oracle, SURVEY §4).  Fractional-bin FFT rotation is
    lossy only at the Nyquist harmonic (attenuated by cos(pi*t), same
    as the reference's rotate_data), so the oracle uses Nyquist-free
    data."""
    arch, amps, freqs, epochs = _toy_archive(DM=30.0)
    spec = np.fft.rfft(arch.amps, axis=-1)
    spec[..., -1] = 0.0  # zero the Nyquist bin
    arch.amps = np.fft.irfft(spec, n=arch.nbin, axis=-1)
    before = arch.get_data()
    arch.dededisperse()
    moved = arch.get_data()
    assert not np.allclose(moved, before, atol=1e-3)
    arch.dedisperse()
    np.testing.assert_allclose(arch.get_data(), before, atol=1e-8)


def test_unload_new_archive(tmp_path):
    arch, amps, freqs, epochs = _toy_archive()
    path = tmp_path / "mod.fits"
    new_amps = amps * 2.0
    unload_new_archive(new_amps, arch, path, DM=3.5, dmc=0, quiet=True)
    back = read_archive(path)
    scale = new_amps.max() - new_amps.min()
    np.testing.assert_allclose(back.amps, new_amps, atol=2e-4 * scale)
    assert back.get_dispersion_measure() == 3.5
    assert not back.get_dedispersed()


def test_polyco_eval():
    rows = {
        "REF_MJD": np.array([55000.5]),
        "REF_PHS": np.array([0.25]),
        "REF_F0": np.array([200.0]),
        "COEFF": np.array([[0.0, 1.2, 0.003, 0.0]]),
    }
    # at the reference epoch: freq = F0 + C1/60
    phase, freq = polyco_phase_freq(rows, 55000.5)
    assert phase == pytest.approx(0.25)
    assert freq == pytest.approx(200.0 + 1.2 / 60.0)
    # 10 minutes later
    phase, freq = polyco_phase_freq(rows, 55000.5 + 10.0 / 1440.0)
    assert freq == pytest.approx(200.0 + (1.2 + 2 * 0.003 * 10.0) / 60.0)
    assert phase == pytest.approx(0.25 + 10 * 60 * 200.0 + 1.2 * 10
                                  + 0.003 * 100.0)


def test_scrunches(tmp_path):
    arch, amps, freqs, epochs = _toy_archive(npol=1)
    arch.tscrunch()
    assert arch.nsub == 1
    np.testing.assert_allclose(arch.get_data()[0], amps.mean(axis=0),
                               atol=1e-10)
    arch2, amps2, _, _ = _toy_archive()
    arch2.fscrunch()
    assert arch2.nchan == 1
    np.testing.assert_allclose(arch2.get_data()[:, :, 0],
                               amps2.mean(axis=2), atol=1e-10)
