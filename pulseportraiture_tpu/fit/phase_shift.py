"""1-D FFTFIT: fit a phase shift (+ scale) between two profiles in the
Fourier domain (Taylor 1992).

The reference does a brute-force grid search over Ns=100 phases and
calls it "*linear* slow-down" (reference pplib.py:2136-2182, 2152).
Here: an exact dense cross-correlation via a zero-padded inverse FFT
(all nbin*oversamp lags at once — the mathematically right Ns -> inf),
then a fixed number of Newton steps on the harmonic-domain objective.
Jittable and vmappable.
"""

from functools import partial

import jax
import jax.numpy as jnp

from ..config import F0_fact
from ..ops.noise import fourier_noise, get_noise_PS
from ..ops.phasor import cexp
from ..utils.bunch import DataBunch
from ..utils.device import on_host
from ..ops.fourier import irfft_c, rfft_c


def _ccf_terms(dFT, mFT, errs_F):
    """Weighted cross-spectrum x_k = dFT_k * conj(mFT_k) / sig_F^2 with
    the DC term down-weighted by F0_fact."""
    x = dFT * jnp.conj(mFT) / errs_F**2.0
    return x.at[..., 0].multiply(F0_fact)


@partial(jax.jit, static_argnames=("oversamp", "newton_iters"))
def _fit_phase_shift_core(dFT, mFT, errs_F, oversamp=8, newton_iters=5):
    nharm = dFT.shape[-1]
    nbin = 2 * (nharm - 1)
    x = _ccf_terms(dFT, mFT, errs_F)
    k = jnp.arange(nharm, dtype=errs_F.dtype)

    # dense CCF over nbin*oversamp lags: C(phi_j) for phi_j = j/(nbin*ov)
    nlag = nbin * oversamp
    ccf = irfft_c(x, n=nlag) * nlag  # ~ C(phi_j), phi_j = j/nlag
    j0 = jnp.argmax(ccf)
    phi0 = j0.astype(errs_F.dtype) / nlag

    def C_fn(phi):
        return jnp.sum((x * cexp(2.0 * jnp.pi * k * phi)).real)

    dC = jax.grad(C_fn)
    d2C = jax.grad(dC)

    def newton(i, phi):
        g, h = dC(phi), d2C(phi)
        step = jnp.where(h < 0.0, -g / h, 0.0)
        # cap the step at one bin to stay in the bracketed peak
        step = jnp.clip(step, -1.0 / nbin, 1.0 / nbin)
        return phi + step

    phi = jax.lax.fori_loop(0, newton_iters, newton, phi0)

    S = jnp.sum(jnp.abs(mFT) ** 2.0 / errs_F**2.0 * jnp.where(k == 0, F0_fact, 1.0))
    Sd = jnp.sum(jnp.abs(dFT) ** 2.0 / errs_F**2.0 * jnp.where(k == 0, F0_fact, 1.0))
    C = C_fn(phi)
    scale = C / S
    curv = d2C(phi)
    # chi2(phi) = Sd - C^2/S profiled over scale; Var = (0.5 d2chi2/dphi2)^-1
    phi_err = jnp.where(
        (C > 0) & (curv < 0), (-scale * curv) ** -0.5, jnp.inf
    )
    scale_err = S**-0.5
    chi2 = Sd - C**2.0 / S
    dof = nbin - 2
    snr = jnp.sqrt(jnp.maximum(scale**2.0 * S, 0.0))
    phi = jnp.mod(phi + 0.5, 1.0) - 0.5
    return phi, phi_err, scale, scale_err, chi2, dof, snr


@on_host
def fit_phase_shift(data, model, noise_std=None, oversamp=8, newton_iters=5):
    """Fit the phase shift of ``data`` relative to ``model`` (both
    (nbin,) profiles).

    Returns a DataBunch(phase, phase_err, scale, scale_err, chi2, dof,
    red_chi2, snr) with the reference's field meanings
    (pplib.py:2136-2182): rotating ``data`` by ``phase`` aligns it
    with ``model``; ``scale * model`` matches the aligned data.

    Host-pinned: this scalar 1-D fit is seeding/diagnostic machinery
    (align guesses, template convergence checks) that callers routinely
    feed f64 profiles — whose c128 FFT no TPU runtime will compile —
    and at (nbin,) scale a host evaluation beats an accelerator
    dispatch anyway.  The batched variant below stays on-device.
    """
    data = jnp.asarray(data)
    model = jnp.asarray(model)
    nbin = data.shape[-1]
    if noise_std is None:
        noise_std = get_noise_PS(data)
    errs_F = fourier_noise(jnp.asarray(noise_std), nbin)
    dFT = rfft_c(data)
    mFT = rfft_c(model)
    phi, phi_err, scale, scale_err, chi2, dof, snr = _fit_phase_shift_core(
        dFT, mFT, errs_F * jnp.ones(()), oversamp=oversamp, newton_iters=newton_iters
    )
    return DataBunch(
        phase=phi,
        phase_err=phi_err,
        scale=scale,
        scale_err=scale_err,
        chi2=chi2,
        dof=dof,
        red_chi2=chi2 / dof,
        snr=snr,
    )


def fit_phase_shift_batch(data, model, noise_std, oversamp=8, newton_iters=5):
    """vmapped fit over leading batch dims of (…, nbin) data/model.

    f64 inputs are canonicalized to f32 on TPU backends (c128 spectra
    do not compile there); the scalar fit_phase_shift above is
    host-pinned instead."""
    from .portrait import _canonical_real_dtype

    data = _canonical_real_dtype(jnp.asarray(data))
    model = jnp.asarray(model).astype(data.dtype)
    nbin = data.shape[-1]
    errs_F = fourier_noise(jnp.asarray(noise_std, data.dtype), nbin)
    dFT = rfft_c(data)
    mFT = rfft_c(model)
    core = partial(
        _fit_phase_shift_core, oversamp=oversamp, newton_iters=newton_iters
    )
    for _ in range(data.ndim - 1):
        core = jax.vmap(core)
    phi, phi_err, scale, scale_err, chi2, dof, snr = core(dFT, mFT, errs_F)
    return DataBunch(
        phase=phi, phase_err=phi_err, scale=scale, scale_err=scale_err,
        chi2=chi2, dof=dof, red_chi2=chi2 / dof, snr=snr,
    )
