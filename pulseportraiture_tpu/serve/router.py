"""Cross-host campaign router (ISSUE 10 tentpole, second half;
ROADMAP item 2's scale-out tail).

One warm :class:`~.server.ToaServer` saturates one host's chips — and
at campaign scale the measured bottleneck is that host's host->device
link (BENCHMARKS 5b/5d: ~90-95% of wall blocked on transfer).  The
link is exactly the resource that MULTIPLIES across hosts, and pulsar
archives are embarrassingly parallel with no cross-host traffic until
the final GLS, so the scale-out shape is the continuous-batching
inference one: keep every replica warm, route at REQUEST granularity,
aggregate demuxed results deterministically.

:class:`ToaRouter` owns N host endpoints, each a transport
(serve/transport.py — ``InProcTransport`` or ``SocketTransport``)
reaching a warm serving loop:

- **Load-aware placement**: submits go to the host with the fewest
  pending archives — the router's own outstanding count (archives
  submitted through it and not yet collected) plus the host's live
  AdmissionQueue depth from ``stat()``, so externally-offered load on
  a shared host is visible too.
- **Sticky per-modelfile affinity**: requests using a template the
  router has already placed PREFER that host, so same-template
  requests keep coalescing into shared fused buckets (the server's
  per-(modelfile, options) lanes) instead of fragmenting their bucket
  fills across the fleet.  Affinity yields to balance exactly when it
  must: the affinity host wins unless its load exceeds the
  least-loaded host's by at least the incoming request's own archive
  count — i.e. unless placing the request on the affinity host would
  leave it strictly more loaded than placing it anywhere else.
- **Backpressure retries**: a ``ServeRejected(retryable=True)`` (a
  full admission queue) moves the request to the next-least-loaded
  host; a ``TransportError`` (host unreachable) does the same.  Each
  full pass over the fleet backs off exponentially
  (``ROUTER_BACKOFF_BASE_S`` doubling, capped) up to
  ``config.router_retry_max`` total attempts; terminal rejections
  (``retryable=False``) raise immediately.
- **Deterministic demux**: each request's ``.tim`` is written by the
  SERVING host through the server's existing per-request demux, so it
  is byte-identical to the single-host one-shot driver regardless of
  placement, retries, or completion order; the decoded result
  DataBunch rides the transport codec.

Telemetry: ``router_start`` once, then per request ``route_submit``
(chosen host, placement attempt count, affinity flag),
``route_retry`` (per rejected placement, with the backoff applied),
and ``route_done`` (serving host, wall, TOA count / error) — the
pptrace "router" section aggregates per-host shares, retry rate, and
a placement-imbalance metric from exactly these events.
"""

import os
import threading
import time

from ..telemetry import resolve_tracer
from .queue import ServeRejected
from .transport import TransportError

__all__ = ["ToaRouter", "RouteHandle", "ROUTER_BACKOFF_BASE_S",
           "ROUTER_BACKOFF_CAP_S"]

# Backoff after a full fleet pass found no host with admission room:
# base doubles per pass, capped (a campaign client is patient, but an
# unbounded doubling would look like a hang).
ROUTER_BACKOFF_BASE_S = 0.05
ROUTER_BACKOFF_CAP_S = 2.0


class _Host:
    """Router-side bookkeeping for one endpoint: the transport plus
    the outstanding-archives counter placement reads."""

    def __init__(self, transport, index):
        self.transport = transport
        self.index = index
        self.label = getattr(transport, "label", f"host{index}")
        self.outstanding = 0   # archives submitted, result not collected
        self.n_requests = 0    # requests ever placed here
        self.n_archives = 0    # archives ever placed here

    def load(self):
        """Pending archives from this router (outstanding) plus the
        host's own admission-queue depth (other clients' submits are
        visible there).  A host whose stat() is unreachable reports
        infinite load — placement simply avoids it this round."""
        try:
            pending = int(self.transport.stat()["pending_archives"])
        except TransportError:
            return float("inf")
        return self.outstanding + pending


class RouteHandle:
    """One routed request: which host took it, and the blocking
    :meth:`result` that demuxes through that host's transport."""

    def __init__(self, router, host, handle, name, n_archives,
                 t_submit):
        self._router = router
        self.host = host
        self._handle = handle
        self.name = name
        self.n_archives = n_archives
        self._t_submit = t_submit
        self._collected = False

    def result(self, timeout=None):
        """Block for the per-request DataBunch (the one-shot driver's
        result shape) or raise the request's failure; either way the
        router's load accounting and route_done telemetry fire exactly
        once."""
        try:
            res = self.host.transport.result(self._handle, timeout)
        except TimeoutError:
            raise  # not resolved: keep the load accounted, retryable
        except Exception as e:
            self._router._collected(self, error=e)
            raise
        self._router._collected(self, result=res)
        return res


class ToaRouter:
    """Shard TOA requests across a fleet of warm serving loops.

    transports: sequence of transport objects (InProcTransport /
    SocketTransport), or 'host:port' strings (each opens a
    SocketTransport).  retry_max: total placement attempts per request
    before the last retryable rejection is raised (None =
    ``config.router_retry_max``).  telemetry: trace path or shared
    Tracer (route_* events land there).

    Thread model: ``submit`` and ``RouteHandle.result`` are safe from
    any thread (one lock guards placement state); each host's own
    thread-safety is the transport's (SocketTransport serializes
    frames, ToaServer.submit is thread-safe).
    """

    def __init__(self, transports, retry_max=None, telemetry=None,
                 quiet=True):
        from .. import config
        from .transport import SocketTransport

        transports = list(transports)
        if not transports:
            raise ValueError("ToaRouter: no host endpoints")
        self.hosts = [
            _Host(SocketTransport(t) if isinstance(t, str) else t, i)
            for i, t in enumerate(transports)]
        labels = [h.label for h in self.hosts]
        if len(set(labels)) != len(labels):
            raise ValueError(
                f"ToaRouter: duplicate host endpoints: {labels}")
        if retry_max is None:
            retry_max = config.router_retry_max
        self.retry_max = max(1, int(retry_max))
        self.quiet = quiet
        self.tracer, self._own_tracer = resolve_tracer(telemetry,
                                                       run="pproute")
        self._lock = threading.Lock()
        self._affinity = {}  # abspath(modelfile) -> _Host
        self._closed = False
        if self.tracer.enabled:
            self.tracer.emit("router_start", n_hosts=len(self.hosts),
                             hosts=labels,
                             retry_max=self.retry_max)

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------

    def _rank(self, modelfile, n_archives):
        """Hosts to try, best first: the affinity host for this
        template leads while placing there would not leave it strictly
        more loaded than the least-loaded alternative; then
        least-loaded order.  The stat() RPCs run OUTSIDE the router
        lock — a hung host must stall only its own probe (until the
        transport's socket timeout), never every other thread's
        submit/result bookkeeping — so the loads are a snapshot; the
        lock guards only the affinity read."""
        loads = {h: h.load() for h in self.hosts}
        if not loads:
            return [], False
        by_load = sorted(loads, key=lambda h: (loads[h], h.index))
        with self._lock:
            aff = self._affinity.get(modelfile)
        if aff is not None and aff in loads and by_load[0] is not aff \
                and loads[aff] - loads[by_load[0]] < n_archives:
            by_load.remove(aff)
            by_load.insert(0, aff)
            return by_load, True
        return by_load, aff is not None and by_load[0] is aff

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               **options):
        """Place one request on the fleet (thread-safe); returns a
        :class:`RouteHandle`.  Retries retryable backpressure and
        unreachable hosts up to ``retry_max`` placements with capped
        exponential backoff between full fleet passes; raises the last
        failure when the budget is exhausted, and terminal
        ``ServeRejected`` (retryable=False) immediately."""
        from ..pipeline.toas import _is_metafile, _read_metafile

        if self._closed:
            raise RuntimeError("ToaRouter is closed")
        if isinstance(datafiles, str):
            datafiles = (_read_metafile(datafiles)
                         if _is_metafile(datafiles) else [datafiles])
        datafiles = list(datafiles)
        n_archives = len(datafiles)
        mkey = os.path.abspath(str(modelfile))
        attempt = 0
        backoff = ROUTER_BACKOFF_BASE_S
        last_err = None
        while attempt < self.retry_max:
            ranked, sticky = self._rank(mkey, n_archives)
            if not ranked:
                raise RuntimeError("ToaRouter: no reachable hosts")
            for host in ranked:
                if attempt >= self.retry_max:
                    break
                attempt += 1
                t0 = time.monotonic()
                try:
                    handle = host.transport.submit(
                        datafiles, modelfile, tim_out=tim_out,
                        name=name, options=options)
                except ServeRejected as e:
                    if not e.retryable:
                        raise  # could never fit anywhere: caller's bug
                    last_err = e
                except TransportError as e:
                    last_err = e
                else:
                    with self._lock:
                        host.outstanding += n_archives
                        host.n_requests += 1
                        host.n_archives += n_archives
                        self._affinity[mkey] = host
                    rh = RouteHandle(self, host, handle,
                                     name if name is not None
                                     else getattr(handle, "name", None),
                                     n_archives, t0)
                    if self.tracer.enabled:
                        self.tracer.emit(
                            "route_submit", req=rh.name,
                            host=host.label, n_archives=n_archives,
                            attempt=attempt,
                            affinity=bool(sticky
                                          and host is ranked[0]))
                    return rh
                if self.tracer.enabled:
                    self.tracer.emit(
                        "route_retry", req=name, host=host.label,
                        attempt=attempt,
                        backoff_s=round(backoff, 4),
                        error=str(last_err))
                sticky = False  # a rejecting affinity host lost its turn
            # a full pass over the fleet found no room: back off so the
            # warm loops can drain, then re-rank (loads have moved)
            if attempt < self.retry_max:
                time.sleep(backoff)
                backoff = min(backoff * 2.0, ROUTER_BACKOFF_CAP_S)
        raise last_err if last_err is not None else RuntimeError(
            "ToaRouter: submit failed with no recorded error")

    # blocking conveniences mirroring serve.ToaClient -----------------

    def get_TOAs(self, datafiles, modelfile, timeout=None,
                 tim_out=None, name=None, **options):
        """Submit and wait (the one-shot driver's return shape)."""
        return self.submit(datafiles, modelfile, tim_out=tim_out,
                           name=name, **options).result(timeout)

    def map(self, specs, timeout=None, return_errors=False):
        """Submit many, then wait for all, in spec order.  specs:
        (datafiles, modelfile[, kwargs]) tuples.  With
        return_errors=True a failed request's exception object takes
        its slot instead of poisoning the batch (siblings still
        return); default re-raises the first failure AFTER every
        sibling resolved, so one bad request never strands the rest
        (serve.client.collect_results — the same contract as
        ToaClient.map)."""
        from .client import collect_results

        handles = [self.submit(s[0], s[1],
                               **(dict(s[2]) if len(s) > 2 else {}))
                   for s in specs]
        return collect_results(handles, timeout, return_errors)

    # ------------------------------------------------------------------
    # completion accounting (RouteHandle calls back)
    # ------------------------------------------------------------------

    def _collected(self, rh, result=None, error=None):
        with self._lock:
            if rh._collected:
                return
            rh._collected = True
            rh.host.outstanding = max(
                0, rh.host.outstanding - rh.n_archives)
        if self.tracer.enabled:
            self.tracer.emit(
                "route_done", req=rh.name, host=rh.host.label,
                wall_s=round(time.monotonic() - rh._t_submit, 6),
                n_toas=len(result.TOA_list) if result else 0,
                error=str(error) if error else None)

    # ------------------------------------------------------------------

    def stats(self):
        """Per-host placement snapshot: {label: {outstanding,
        n_requests, n_archives}} — what the dryrun witness and tests
        assert placement against without reading the trace."""
        with self._lock:
            return {h.label: {"outstanding": h.outstanding,
                              "n_requests": h.n_requests,
                              "n_archives": h.n_archives}
                    for h in self.hosts}

    def close(self):
        """Close every transport (idempotent).  The router never owns
        the remote servers — a fleet outlives its clients — so this
        releases connections only."""
        if self._closed:
            return
        self._closed = True
        for h in self.hosts:
            try:
                h.transport.close()
            except Exception:
                pass
        if self._own_tracer:
            self.tracer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
