"""Unit oracles for the Fourier-domain kernels (SURVEY.md §4):
analytic FT identities, rotate∘unrotate = id, noise calibration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.config import Dconst
from pulseportraiture_tpu.ops import (
    DM_delay,
    add_scattering,
    fft_shift_bins,
    gaussian_profile,
    gaussian_profile_FT,
    get_noise_PS,
    get_scales,
    guess_fit_freq,
    instrumental_response_FT,
    phase_transform,
    phase_shifts,
    rotate_portrait,
    rotate_profile,
    scattering_kernel_time,
    scattering_profile_FT,
    scattering_times,
)


def test_rotate_unrotate_identity(rng):
    # exact for integer-bin shifts (any signal)
    prof = rng.normal(size=256)
    out = rotate_profile(rotate_profile(prof, 16.0 / 256), -16.0 / 256)
    np.testing.assert_allclose(out, prof, atol=1e-10)
    # for fractional shifts, exact on band-limited signals (the Nyquist
    # bin of white noise is not invertible under any real-output shift)
    smooth = np.asarray(gaussian_profile(256, 0.4, 0.05, 3.0))
    out = rotate_profile(rotate_profile(smooth, 0.123), -0.123)
    np.testing.assert_allclose(out, smooth, atol=1e-10)


def test_rotate_integer_bins_is_roll(rng):
    prof = rng.normal(size=128)
    # positive phase rotates to earlier phase: out[j] = in[j + s]
    out = rotate_profile(prof, 5.0 / 128)
    np.testing.assert_allclose(out, np.roll(prof, -5), atol=1e-10)


def test_rotate_portrait_dedisperses():
    nchan, nbin, P = 16, 512, 0.003
    freqs = jnp.linspace(1200.0, 1900.0, nchan)
    DM = 0.01
    # build a dispersed portrait: delta at phase 0.5 delayed per channel
    delays = (Dconst * DM / P) * (freqs**-2.0 - jnp.inf**-2.0)
    port = np.zeros((nchan, nbin))
    prof = np.exp(-0.5 * ((np.arange(nbin) / nbin - 0.5) / 0.02) ** 2)
    for n in range(nchan):
        port[n] = np.asarray(fft_shift_bins(jnp.asarray(prof), -delays[n] * nbin))
    # rotating by (0, DM) with nu_ref=inf should align all channels
    out = rotate_portrait(jnp.asarray(port), 0.0, DM, P, freqs, jnp.inf)
    for n in range(nchan):
        np.testing.assert_allclose(out[n], prof, atol=1e-8)


def test_phase_transform_consistency():
    P, DM = 0.005, 30.0
    phi1, nu1, nu2 = 0.1, 1400.0, 1700.0
    phi2 = phase_transform(phi1, DM, nu1, nu2, P, mod=False)
    # per-channel delays must be invariant
    freqs = jnp.array([1250.0, 1500.0, 1800.0])
    t1 = phase_shifts(phi1, DM, 0.0, freqs, P, nu1, 1.0)
    t2 = phase_shifts(phi2, DM, 0.0, freqs, P, nu2, 1.0)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(t2), atol=1e-12)


def test_DM_delay_sign():
    # lower frequency arrives later: positive delay vs higher ref freq
    assert float(DM_delay(10.0, 1200.0, 1600.0)) > 0


def test_gaussian_FT_matches_numerical():
    nbin = 1024
    loc, wid, amp = 0.3, 0.05, 2.5
    prof = gaussian_profile(nbin, loc, wid, amp)
    num_FT = jnp.fft.rfft(prof)
    ana_FT = gaussian_profile_FT(nbin // 2 + 1, loc, wid, amp)
    np.testing.assert_allclose(
        np.asarray(ana_FT), np.asarray(num_FT), atol=1e-6 * nbin * amp
    )


def test_scattering_FT_matches_time_domain():
    # the sampled kernel's DFT approaches the continuous analytic FT as
    # tau*nbin grows; discretization error is O(1/(tau*nbin))
    for nbin, tau, tol in [(2048, 0.01, 5e-2), (4096, 0.05, 5e-3)]:
        H_ana = scattering_profile_FT(tau, nbin // 2 + 1)
        kern = scattering_kernel_time(tau, nbin)
        H_num = jnp.fft.rfft(kern)
        np.testing.assert_allclose(np.asarray(H_num), np.asarray(H_ana), atol=tol)


def test_scattering_zero_tau_identity(rng):
    port = jnp.asarray(rng.normal(size=(4, 256)))
    out = add_scattering(port, jnp.zeros(4))
    np.testing.assert_allclose(np.asarray(out), np.asarray(port), atol=1e-12)


def test_scattering_conserves_flux(rng):
    prof = jnp.asarray(np.abs(rng.normal(size=(1, 512))))
    out = add_scattering(prof, jnp.array([0.05]))
    np.testing.assert_allclose(
        float(jnp.sum(out)), float(jnp.sum(prof)), rtol=1e-10
    )


def test_scattering_times_power_law():
    taus = scattering_times(1.0, -4.0, jnp.array([500.0, 1000.0]), 1000.0)
    np.testing.assert_allclose(np.asarray(taus), [16.0, 1.0], rtol=1e-12)


def test_instrumental_response_identity():
    H = instrumental_response_FT(0.0, 100, "rect")
    np.testing.assert_allclose(np.asarray(H), 1.0)


def test_noise_PS_calibrated(rng):
    sigma = 2.5
    data = rng.normal(scale=sigma, size=(64, 2048))
    est = np.asarray(get_noise_PS(jnp.asarray(data)))
    assert abs(est.mean() - sigma) / sigma < 0.03


def test_get_scales_recovers_amplitudes(rng):
    nchan, nbin = 8, 512
    prof = gaussian_profile(nbin, 0.5, 0.03, 1.0)
    true_scales = jnp.asarray(1.0 + np.arange(nchan, dtype=float))
    port = true_scales[:, None] * prof[None, :]
    dFT = jnp.fft.rfft(port, axis=-1)
    mFT = jnp.fft.rfft(jnp.broadcast_to(prof, (nchan, nbin)), axis=-1)
    errs_F = jnp.ones(nchan)
    scales = get_scales(dFT, mFT, errs_F)
    np.testing.assert_allclose(np.asarray(scales), np.asarray(true_scales), rtol=1e-8)


def test_guess_fit_freq_bounds():
    freqs = jnp.linspace(1200.0, 1900.0, 32)
    nu = float(guess_fit_freq(freqs))
    assert 1200.0 < nu < 1900.0


def test_fft_rotate_matches_rotate_profile(rng):
    from pulseportraiture_tpu.ops.rotation import fft_rotate

    x = jnp.asarray(rng.normal(size=64))
    # reference semantics (pplib.py:2655-2669): rotate LEFT by bins
    out = fft_rotate(x, 5.0)
    ref = np.roll(np.asarray(x), -5)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-10)
    # independent cross-check of the main rotation kernel on a
    # band-limited series (fractional rotation of a real even-length
    # series is lossy at Nyquist, so white noise would not round-trip)
    prof = gaussian_profile(64, 0.5, 0.1, 1.0)
    np.testing.assert_allclose(
        np.asarray(fft_rotate(prof, 2.3)),
        np.asarray(rotate_profile(prof, 2.3 / 64)), atol=1e-9)
    back = fft_rotate(fft_rotate(prof, 2.3), -2.3)
    np.testing.assert_allclose(np.asarray(back), np.asarray(prof),
                               atol=1e-9)


def test_gaussian_function_peak_and_fwhm():
    from pulseportraiture_tpu.ops.gaussian import gaussian_function

    xs = jnp.linspace(0.0, 1.0, 4097)
    y = np.asarray(gaussian_function(xs, 0.5, 0.1))
    assert y.max() == pytest.approx(1.0, abs=1e-6)
    above = np.asarray(xs)[y >= 0.5]
    assert above.max() - above.min() == pytest.approx(0.1, abs=1e-3)
    # norm=True integrates to one (reference pplib.py:782-798)
    yn = np.asarray(gaussian_function(xs, 0.5, 0.1, norm=True))
    assert np.trapezoid(yn, np.asarray(xs)) == pytest.approx(1.0,
                                                             abs=1e-4)


def test_fit_powlaw_function_residuals(rng):
    from pulseportraiture_tpu.fit.powlaw import fit_powlaw_function, powlaw

    freqs = np.linspace(1000.0, 2000.0, 16)
    data = np.asarray(powlaw(jnp.asarray(freqs), 1500.0, 2.0, -1.4))
    r = np.asarray(fit_powlaw_function((2.0, -1.4), freqs, 1500.0,
                                       jnp.asarray(data)))
    np.testing.assert_allclose(r, 0.0, atol=1e-12)


# --- exact sort-free median (ops/noise.exact_median_lastaxis) -----------


@pytest.mark.parametrize("shape", [(7, 64), (3, 4, 63), (1, 2)])
def test_exact_median_matches_jnp_median(rng, shape):
    from pulseportraiture_tpu.ops.noise import exact_median_lastaxis

    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    got = np.asarray(jax.jit(exact_median_lastaxis)(x))
    ref = np.asarray(jax.jit(lambda v: jnp.median(v, axis=-1))(x))
    # bit-identical, not just close: the streaming raw program promises
    # bit-stable packed output and get_SNR sits on that path
    assert np.array_equal(got.view(np.int32), ref.view(np.int32))


def test_exact_median_adversarial_values():
    from pulseportraiture_tpu.ops.noise import exact_median_lastaxis

    # duplicates, signed zeros, negatives, huge/tiny magnitudes
    rows = np.array([
        [-3.5, -0.0, 0.0, 1.25, 1.25, 7.0],
        [1e30, -1e30, 1e-30, -1e-30, 0.0, 2.0],
        [5.0, 5.0, 5.0, 5.0, 5.0, 5.0],
        [-1.0, -2.0, -3.0, -4.0, -5.0, -6.0],
    ], dtype=np.float32)
    got = np.asarray(exact_median_lastaxis(jnp.asarray(rows)))
    ref = np.median(rows, axis=-1).astype(np.float32)
    np.testing.assert_array_equal(got, ref)


def test_exact_median_f64_falls_back(rng):
    from pulseportraiture_tpu.ops.noise import exact_median_lastaxis

    x = jnp.asarray(rng.standard_normal((5, 33)))
    np.testing.assert_array_equal(
        np.asarray(exact_median_lastaxis(x)),
        np.median(np.asarray(x), axis=-1))


def test_get_snr_unchanged_by_median_swap(rng):
    # get_SNR through the sort-free median must equal the f64 numpy
    # recomputation of the same formula
    from pulseportraiture_tpu.ops.noise import get_SNR

    prof = rng.standard_normal((4, 128)).astype(np.float32)
    prof[:, 30:40] += 5.0
    snr = np.asarray(get_SNR(jnp.asarray(prof), jnp.asarray(
        np.full(4, 1.0, np.float32))))
    p = prof - np.median(prof, axis=-1, keepdims=True)
    peak = np.abs(p).max(axis=-1)
    weq = np.maximum(np.abs(p.sum(axis=-1)) / peak, 1.0)
    ref = np.abs(p.sum(axis=-1)) / (1.0 * np.sqrt(weq)) / 3.25
    np.testing.assert_allclose(snr, ref, rtol=2e-6)


# --- fold-symmetry matmul DFT (config.dft_fold) -------------------------


@pytest.mark.parametrize("nharm", [None, 16])
def test_rfft_mm_fold_matches_direct(rng, nharm):
    from pulseportraiture_tpu.ops.fourier import rfft_mm

    x = jnp.asarray(rng.standard_normal((3, 128)).astype(np.float32))
    dr, di = rfft_mm(x, fold=False, nharm=nharm)
    fr, fi = rfft_mm(x, fold=True, nharm=nharm)
    ref = np.fft.rfft(np.asarray(x, np.float64), axis=-1)
    if nharm is not None:
        ref = ref[..., :nharm]
    scale = np.abs(ref).max()
    assert np.abs(np.asarray(fr, np.float64) - ref.real).max() < 1e-5 * scale
    assert np.abs(np.asarray(fi, np.float64) - ref.imag).max() < 1e-5 * scale
    # fold and direct agree to f32 rounding on the same harmonics
    np.testing.assert_allclose(np.asarray(fr), np.asarray(dr),
                               atol=2e-5 * scale)
    np.testing.assert_allclose(np.asarray(fi), np.asarray(di),
                               atol=2e-5 * scale)


def test_rfft_mm_fold_odd_n_falls_back(rng):
    from pulseportraiture_tpu.ops.fourier import rfft_mm

    x = jnp.asarray(rng.standard_normal((2, 65)).astype(np.float32))
    dr, di = rfft_mm(x, fold=True)
    ref = np.fft.rfft(np.asarray(x, np.float64), axis=-1)
    scale = np.abs(ref).max()
    assert np.abs(np.asarray(dr, np.float64) - ref.real).max() < 1e-5 * scale


def test_dft_fold_config_strict():
    from pulseportraiture_tpu import config
    from pulseportraiture_tpu.ops.fourier import use_dft_fold

    old = config.dft_fold
    try:
        config.dft_fold = "typo"
        with pytest.raises(ValueError, match="dft_fold"):
            use_dft_fold()
        config.dft_fold = "auto"
        assert use_dft_fold() in (True, False)
        config.dft_fold = True
        assert use_dft_fold() is True
    finally:
        config.dft_fold = old
