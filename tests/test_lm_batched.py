"""Batched LM engine (ISSUE 9): digit parity with the single-problem
oracle on mixed-bounds/mixed-vary problem sets, padded-component
identity, straggler convergence inside the shared while_loop, and
per-problem nfev/success semantics — all at tiny shapes (the engine
semantics are shape-independent; tier-1 runs near its cap)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit.gauss import (fit_gaussian_profile,
                                            fit_gaussian_profiles_batched,
                                            gen_gaussian_profile_flat,
                                            pad_profile_params,
                                            profile_trial_seeds,
                                            profile_vary,
                                            select_best_trial,
                                            use_gauss_device)
from pulseportraiture_tpu.fit.lm import (levenberg_marquardt,
                                         levenberg_marquardt_batched)


def _quad_resid(x, t, y, s):
    return (y - (x[0] + x[1] * t + x[2] * t ** 2)) / s


def _flat_resid(x, t, y, s):
    # a problem with a parameter pinned far from optimum by bounds
    return (y - x[0] * jnp.exp(-x[1] * t)) / s


class TestBatchedParity:
    def test_mixed_bounds_mixed_vary_digit_parity(self, rng):
        """Every bound kind (free / lower / upper / two-sided) and a
        per-problem vary mask, batched vs single <= 1e-12."""
        B, npts = 6, 40
        t = np.linspace(0.0, 1.0, npts)
        ts, ys, ss, x0s, vs, los, his = [], [], [], [], [], [], []
        singles = []
        for b in range(B):
            y = (1.0 + b) + (2.0 + 0.5 * b) * t + 0.4 * t ** 2 \
                + 0.05 * rng.normal(size=npts)
            s = np.full(npts, 0.05)
            lower = np.array([-np.inf, 0.0 if b % 2 else -np.inf,
                              -1.0])
            upper = np.array([np.inf if b % 3 else 10.0, np.inf, 1.0])
            vary = np.array([True, True, b % 2 == 0])
            x0 = np.array([0.0, 1.0, 0.4])
            singles.append(levenberg_marquardt(
                _quad_resid, x0, aux=(t, y, s), lower=lower,
                upper=upper, vary=vary))
            ts.append(t), ys.append(y), ss.append(s)
            x0s.append(x0), vs.append(vary)
            los.append(lower), his.append(upper)
        res = levenberg_marquardt_batched(
            _quad_resid, np.stack(x0s),
            aux=(np.stack(ts), np.stack(ys), np.stack(ss)),
            lower=np.stack(los), upper=np.stack(his),
            vary=np.stack(vs))
        n_exact = 0
        for b in range(B):
            s1 = singles[b]
            # same minimum for every problem: chi2 to relative 1e-12,
            # parameters to 1e-8 (a near-threshold `done` test may flip
            # by an ulp between the batched and single programs, moving
            # the stopping point by one polishing step)
            assert abs(float(res.chi2[b]) - float(s1.chi2)) \
                <= 1e-12 * float(s1.chi2)
            assert np.max(np.abs(np.asarray(res.x)[b]
                                 - np.asarray(s1.x))) <= 1e-8
            assert int(res.dof[b]) == int(s1.dof)
            assert bool(res.success[b]) == bool(s1.success)
            # when the iteration trajectories match, results are
            # digit-identical
            if int(res.nfev[b]) == int(s1.nfev):
                n_exact += 1
                for f in ("x", "x_err"):
                    got = np.asarray(getattr(res, f))[b]
                    want = np.asarray(getattr(s1, f))
                    assert np.max(np.abs(got - want)) <= 1e-12, (b, f)
        assert n_exact >= B - 1  # at most one near-threshold flip here

    def test_x0_must_be_2d(self):
        with pytest.raises(ValueError, match=r"\(B, n\)"):
            levenberg_marquardt_batched(_quad_resid, np.zeros(3))

    def test_straggler_does_not_corrupt_finished_lanes(self, rng):
        """One hard problem (far seed, tight tolerance — many more
        iterations) shares the while_loop with easy ones; the easy
        problems' results must equal their standalone fits exactly
        (converged lanes hold their state while stragglers iterate)."""
        npts = 30
        t = np.linspace(0.0, 2.0, npts)
        s = np.full(npts, 0.02)
        y_easy = 2.0 - 1.0 * t + 0.1 * t ** 2 \
            + 0.02 * rng.normal(size=npts)
        y_hard = 5.0 + 3.0 * t - 0.8 * t ** 2 \
            + 0.02 * rng.normal(size=npts)
        x0_easy = np.array([2.0, -1.0, 0.1])   # near optimum
        x0_hard = np.array([-50.0, 40.0, -20.0])  # far seed
        r_easy = levenberg_marquardt(_quad_resid, x0_easy,
                                     aux=(t, y_easy, s))
        rb = levenberg_marquardt_batched(
            _quad_resid, np.stack([x0_easy, x0_hard]),
            aux=(np.stack([t, t]), np.stack([y_easy, y_hard]),
                 np.stack([s, s])))
        nfev = np.asarray(rb.nfev)
        assert nfev[1] > nfev[0]  # the straggler iterated longer
        for f in ("x", "x_err", "chi2", "nfev"):
            got = np.asarray(getattr(rb, f))[0]
            want = np.asarray(getattr(r_easy, f))
            assert np.max(np.abs(got - want)) <= 1e-12, f
        # the straggler still converged to the right answer
        assert np.allclose(np.asarray(rb.x)[1], [5.0, 3.0, -0.8],
                           atol=0.2)

    def test_nfev_success_semantics_per_problem(self, rng):
        """A problem capped by max_iter reports success=False without
        touching its batchmates' flags."""
        npts = 30
        t = np.linspace(0.0, 2.0, npts)
        s = np.full(npts, 0.02)
        # problem 0: noiseless data, seeded AT the optimum -> zero
        # gradient -> done within the tiny budget; problem 1: far seed
        # that cannot converge in 3 iterations
        y = 2.0 + 1.0 * t + 0.3 * t ** 2
        x0_good = np.array([2.0, 1.0, 0.3])
        x0_bad = np.array([-200.0, 150.0, -90.0])
        rb = levenberg_marquardt_batched(
            _quad_resid, np.stack([x0_good, x0_bad]),
            aux=(np.stack([t, t]), np.stack([y, y]),
                 np.stack([s, s])), max_iter=3)
        success = np.asarray(rb.success)
        nfev = np.asarray(rb.nfev)
        assert bool(success[0])
        assert not bool(success[1])
        assert nfev[1] >= 3  # burned its whole budget
        # all-frozen problems converge immediately (the factory's
        # batch-row padding relies on this)
        rb2 = levenberg_marquardt_batched(
            _quad_resid, np.stack([x0_good, x0_good]),
            aux=(np.stack([t, t]), np.stack([y, y]),
                 np.stack([s, s])),
            vary=np.stack([np.ones(3, bool), np.zeros(3, bool)]))
        assert np.asarray(rb2.nfev)[1] <= 2
        assert np.all(np.asarray(rb2.x)[1] == x0_good)


class TestCompaction:
    def test_compacted_chunks_match_single_dispatch(self, rng):
        """compact_every splits the shared while_loop at iteration
        boundaries and re-batches stragglers into power-of-two
        classes; per-problem trajectories — nfev included — must be
        identical to the uninterrupted dispatch."""
        B, npts = 6, 30
        t = np.linspace(0.0, 2.0, npts)
        s = np.full(npts, 0.02)
        ys, x0s = [], []
        for b in range(B):
            ys.append((1.0 + b) + 2.0 * t - 0.4 * t ** 2
                      + 0.02 * rng.normal(size=npts))
            # one far seed so iteration counts straggle
            x0s.append(np.array([-40.0, 30.0, -15.0]) if b == 3
                       else np.array([1.0 + b, 2.0, -0.4]))
        aux = (np.stack([t] * B), np.stack(ys), np.stack([s] * B))
        whole = levenberg_marquardt_batched(
            _quad_resid, np.stack(x0s), aux=aux, max_iter=80)
        compact = levenberg_marquardt_batched(
            _quad_resid, np.stack(x0s), aux=aux, max_iter=80,
            compact_every=8, compact_min_rows=2)
        nf = np.asarray(whole.nfev)
        assert nf[3] > nf.min()  # the straggler really straggled
        assert np.array_equal(np.asarray(compact.success),
                              np.asarray(whole.success))
        for f in ("x", "x_err", "chi2", "dof", "nfev"):
            got = np.asarray(getattr(compact, f), float)
            want = np.asarray(getattr(whole, f), float)
            assert np.max(np.abs(got - want)) <= 1e-12, f


class TestPaddedComponents:
    def test_padded_ngauss_identity(self, rng):
        """A profile trial padded with frozen zero-amplitude
        components fits digit-identically (<= 1e-12) to the unpadded
        fit — the property that lets heterogeneous ngauss share one
        compiled program."""
        nbin = 128
        truth = np.array([0.01, 0.0, 0.3, 0.04, 1.0, 0.6, 0.02, 0.5])
        prof = np.asarray(gen_gaussian_profile_flat(truth, nbin))
        data = prof + 0.01 * rng.normal(size=nbin)
        x0 = np.array([0.0, 0.0, 0.29, 0.05, 0.9, 0.61, 0.03, 0.4])
        r_unpadded = fit_gaussian_profile(data, x0, 0.01)
        padded, g = pad_profile_params(x0, 4)
        assert g == 2
        vary = profile_vary(g, 4)
        rb = fit_gaussian_profiles_batched(
            data[None], padded[None], np.array([0.01]), vary[None])
        x = np.asarray(rb.x)[0]
        xe = np.asarray(rb.x_err)[0]
        assert np.max(np.abs(x[:8] - r_unpadded.fitted_params)) <= 1e-12
        assert np.max(np.abs(xe[:8] - r_unpadded.fit_errs)) <= 1e-12
        # pad components unchanged, zero amplitude, zero error
        assert np.all(x[8::3][2:] == 0.0) or np.all(x[10::3] == 0.0)
        assert int(rb.dof[0]) == int(r_unpadded.dof)

    def test_pad_refuses_shrink(self):
        with pytest.raises(ValueError, match="cannot pad"):
            pad_profile_params(np.zeros(2 + 3 * 4), 2)


class TestTrialMachinery:
    def test_trial_seeds_shapes_and_determinism(self):
        prof = np.zeros(64)
        prof[20] = 1.0
        seeds = profile_trial_seeds(prof, 3, wid0=0.05, noise=0.1)
        assert [len(s) for s in seeds] == [5, 8, 11]
        assert seeds[0][2] == (20 + 0.5) / 64  # peak-seeded loc
        again = profile_trial_seeds(prof, 3, wid0=0.05, noise=0.1)
        for a, b in zip(seeds, again):
            assert np.array_equal(a, b)

    def test_select_best_trial_rules(self):
        # improving then stalling: stops at the stall
        assert select_best_trial([10.0, 5.0, 4.999]) == 1
        # within tolerance of 1 stops immediately
        assert select_best_trial([1.05, 0.9], rchi2_tol=0.1) == 0
        # non-finite trials skipped; all-bad -> None
        assert select_best_trial([np.nan, 2.0]) == 1
        assert select_best_trial([np.nan, np.inf]) is None
        # non-converged (or stalled) trials still compete — a
        # well-fitting capped trial must beat a converged underfit...
        assert select_best_trial([3139.0, 0.84],
                                 success=[True, False]) == 1
        # ...but need a >5% improvement (their chi2 carries
        # lane-dependent wander; a 1% margin could flip the selected
        # component count between the batched and serial engines)
        assert select_best_trial([10.0, 9.8],
                                 success=[True, False]) == 0
        assert select_best_trial([10.0, 9.8],
                                 success=[True, True],
                                 stalled=[False, True]) == 0
        assert select_best_trial([10.0, 9.8]) == 1  # converged: >1%

    def test_use_gauss_device_strict(self):
        assert use_gauss_device(True) is True
        assert use_gauss_device(False) is False
        assert use_gauss_device("auto") in (True, False)
        with pytest.raises(ValueError, match="gauss_device"):
            use_gauss_device("sometimes")


class TestAnalyticJacobian:
    """ISSUE 14: the closed-form residual-Jacobian companions vs
    jax.jacfwd — digit parity <= 1e-10 (relative to the Jacobian's own
    scale) across the mixed-bounds/vary/padded-ngauss option lattice,
    evaluated through fit/lm._make_jac, the EXACT evaluator both
    engine sites (init + loop) run."""

    GATE = 1e-10

    @pytest.fixture()
    def rng(self):
        return np.random.default_rng(77)

    def _gate(self, resid, jac, aux, x0, lower, upper, vary):
        from pulseportraiture_tpu.fit.lm import (_bounds_spec,
                                                 _make_jac,
                                                 _nudge_into_bounds,
                                                 _to_internal)

        x0 = jnp.asarray(x0, float)
        lo, hi, kind = _bounds_spec(lower, upper, x0.shape[0], x0.dtype)
        vary_b = jnp.asarray(vary)
        x0 = _nudge_into_bounds(x0, lo, hi, kind, vary_b)
        vary_f = vary_b.astype(x0.dtype)
        u0 = _to_internal(x0, lo, hi, kind)
        J_ad = np.asarray(_make_jac(resid, None, aux, lo, hi, kind,
                                    vary_f)(u0))
        J_an = np.asarray(_make_jac(resid, jac, aux, lo, hi, kind,
                                    vary_f)(u0))
        scale = max(float(np.max(np.abs(J_ad))), 1.0)
        delta = float(np.max(np.abs(J_ad - J_an))) / scale
        assert delta <= self.GATE, delta
        # frozen columns are exactly zero in BOTH lanes — the single
        # masking rule (_make_jac) all three consumers share
        frozen = ~np.asarray(vary)
        assert np.all(J_ad[:, frozen] == 0.0)
        assert np.all(J_an[:, frozen] == 0.0)

    @pytest.mark.slow
    def test_profile_lattice(self, rng):
        from pulseportraiture_tpu.fit.gauss import (_profile_resid,
                                                    _profile_resid_jac,
                                                    profile_bounds)

        nbin = 64
        data = jnp.asarray(rng.standard_normal(nbin))
        errs = jnp.full(nbin, 0.1)
        for ngauss, ngauss_pad in ((1, 1), (2, 2), (2, 4)):
            for fit_scat in (False, True):
                for freeze in (None, 0):
                    seed = [0.05, 0.8 if fit_scat else 0.0]
                    for ig in range(ngauss):
                        seed += [0.2 + 0.25 * ig, 0.03, 1.0 + ig]
                    padded, _ = pad_profile_params(seed, ngauss_pad)
                    vary = profile_vary(ngauss, ngauss_pad,
                                        fit_scattering=fit_scat)
                    if freeze is not None:
                        vary = vary.copy()
                        vary[2 + 3 * freeze] = False  # pin one loc
                    lower, upper = profile_bounds(ngauss_pad, nbin)
                    self._gate(_profile_resid, _profile_resid_jac,
                               (data, errs), padded, lower, upper,
                               vary)

    @pytest.mark.slow
    def test_portrait_lattice(self, rng):
        from pulseportraiture_tpu.fit.gauss import (_portrait_fns,
                                                    pad_portrait_params,
                                                    portrait_bounds,
                                                    portrait_vary)

        nchan, nbin = 6, 64
        data = jnp.asarray(rng.standard_normal((nchan, nbin)))
        errs = jnp.full(nchan, 0.1)
        freqs = jnp.linspace(1300.0, 1900.0, nchan)
        for code in ("000", "010", "111"):
            for ngauss, gpad in ((1, 1), (2, 4)):
                seed = [0.02, 0.5]
                for ig in range(ngauss):
                    seed += [0.3 + 0.2 * ig, 0.01, 0.04, 0.1,
                             1.0 + ig, -0.4]
                padded, _ = pad_portrait_params(seed, gpad)
                nmain = 2 + 6 * gpad
                x0 = np.concatenate([padded, [-4.0]])
                flags = np.ones(nmain, bool)
                vary = portrait_vary(flags[:2 + 6 * ngauss], gpad,
                                     fit_scattering_index=True)
                lower, upper = portrait_bounds(gpad, nbin)
                resid, rjac = _portrait_fns(code, nbin, 0, nmain)
                aux = (data, errs, freqs, jnp.asarray(1500.0),
                       jnp.asarray(0.003),
                       jnp.zeros((0, nchan), bool))
                self._gate(resid, rjac, aux, x0, lower, upper, vary)

    def test_portrait_join_columns(self, rng):
        """JOIN (phase, DM) columns and the rotation of every base
        column agree with autodiff — the multi-receiver layout the
        single-pulsar driver fits."""
        from pulseportraiture_tpu.fit.gauss import _portrait_fns

        nchan, nbin, njoin = 6, 64, 1
        nmain = 2 + 6 * 2
        data = jnp.asarray(rng.standard_normal((nchan, nbin)))
        errs = jnp.full(nchan, 0.1)
        freqs = jnp.linspace(1300.0, 1900.0, nchan)
        jm = np.zeros((njoin, nchan), bool)
        jm[0, 3:] = True
        x0 = np.concatenate([
            [0.02, 0.5],
            [0.3, 0.01, 0.04, 0.1, 2.0, -0.5],
            [0.6, -0.02, 0.02, 0.3, 1.0, 0.2],
            [0.01, 0.4],      # join (phase, DM)
            [-3.8]])
        lower = np.full(len(x0), -np.inf)
        upper = np.full(len(x0), np.inf)
        lower[1] = 0.0
        lower[4:nmain:6] = 0.5 / nbin
        upper[4:nmain:6] = 0.25
        lower[6:nmain:6] = 0.0
        resid, rjac = _portrait_fns("000", nbin, njoin, nmain)
        aux = (data, errs, freqs, jnp.asarray(1500.0),
               jnp.asarray(0.003), jnp.asarray(jm))
        vary = np.ones(len(x0), bool)
        self._gate(resid, rjac, aux, x0, lower, upper, vary)

    def test_init_and_loop_share_the_jac(self):
        """The vary mask is applied in ONE place: the initial state's
        J0 equals _make_jac's output bit-for-bit, for both sources
        (the satellite fix — the two sites used to mask on their
        own)."""
        from pulseportraiture_tpu.fit.gauss import (_profile_resid,
                                                    _profile_resid_jac)
        from pulseportraiture_tpu.fit.lm import (_bounds_spec,
                                                 _lm_init, _make_jac,
                                                 _to_internal)

        nbin = 32
        rng = np.random.default_rng(5)
        data = jnp.asarray(rng.standard_normal(nbin))
        errs = jnp.full(nbin, 0.1)
        x0 = jnp.asarray([0.0, 0.0, 0.3, 0.05, 1.0])
        lo, hi, kind = _bounds_spec(None, None, 5, x0.dtype)
        vary = jnp.asarray([True, False, True, True, True])
        vary_f = vary.astype(x0.dtype)
        u0 = _to_internal(x0, lo, hi, kind)
        for jac_src in (None, _profile_resid_jac):
            s0 = _lm_init(_profile_resid, (data, errs), x0, lo, hi,
                          kind, vary, jacobian=jac_src)
            J = _make_jac(_profile_resid, jac_src, (data, errs), lo,
                          hi, kind, vary_f)(u0)
            assert np.array_equal(np.asarray(s0.J), np.asarray(J))
            assert np.all(np.asarray(s0.J)[:, 1] == 0.0)

    def test_resolve_lm_jacobian_modes(self, monkeypatch):
        from pulseportraiture_tpu import config
        from pulseportraiture_tpu.fit.gauss import _profile_resid_jac
        from pulseportraiture_tpu.fit.lm import (resolve_lm_jacobian,
                                                 use_lm_jacobian)

        monkeypatch.setattr(config, "lm_jacobian", "auto")
        assert resolve_lm_jacobian(_profile_resid_jac) \
            is _profile_resid_jac
        assert resolve_lm_jacobian(None) is None
        monkeypatch.setattr(config, "lm_jacobian", "ad")
        assert resolve_lm_jacobian(_profile_resid_jac) is None
        monkeypatch.setattr(config, "lm_jacobian", "analytic")
        assert resolve_lm_jacobian(_profile_resid_jac) \
            is _profile_resid_jac
        with pytest.raises(ValueError, match="analytic"):
            resolve_lm_jacobian(None)
        monkeypatch.setattr(config, "lm_jacobian", "sometimes")
        with pytest.raises(ValueError, match="lm_jacobian"):
            use_lm_jacobian()

    @pytest.mark.slow  # ~20 s; the AD-vs-analytic digit gate also runs
    # in-bench (bench_gauss) and tier-1 keeps test_portrait_join_columns
    # + test_init_and_loop_share_the_jac on the analytic lane
    def test_batched_ad_vs_analytic_same_selection(self, rng):
        """The whole batched trial pipeline under both Jacobian
        sources: identical nfev trajectories at these well-conditioned
        shapes would be luck, but the SELECTED component count must
        never flip, and converged parameters agree far below the
        selection margins."""
        from pulseportraiture_tpu import config
        from pulseportraiture_tpu.fit.gauss import fit_profile_trials

        nbin = 128
        grid = np.arange(nbin) / nbin
        d = np.mod(grid - 0.3 + 0.5, 1.0) - 0.5
        prof = 2.0 * np.exp(-4 * np.log(2) * (d / 0.05) ** 2)
        prof = prof + 0.03 * rng.standard_normal(nbin)
        saved = config.lm_jacobian
        try:
            config.lm_jacobian = "ad"
            r_ad = fit_profile_trials(prof, 2, 0.03, serial=False)
            config.lm_jacobian = "analytic"
            r_an = fit_profile_trials(prof, 2, 0.03, serial=False)
        finally:
            config.lm_jacobian = saved
        assert r_ad.ngauss == r_an.ngauss
        assert np.max(np.abs(r_ad.params - r_an.params)) < 1e-6
