#!/usr/bin/env python
"""Cross-validate this framework's PSRFITS loader against PSRCHIVE.

The reference package is implicitly validated by PSRCHIVE itself (its
loader IS the C++ library, reference pplib.py:51).  This framework
carries its own codec, so where a PSRCHIVE installation exists, run

    python tools/psrchive_parity.py archive1.fits [archive2.fits ...]

and every comparable quantity is checked side by side:

  - geometry (nsub/npol/nchan/nbin), source/telescope metadata
  - DAT_FREQ table, weights
  - folding periods and mid-subint epochs
  - the decoded data cube (DAT_SCL/DAT_OFFS applied), compared after
    each side's own baseline removal and per-profile normalization
  - dedispersion: rotate_phase vs arch.dedisperse() (the reference's
    own oracle, pplib.py:2526-2527)

Exit code 0 = all archives match within tolerance; each failure prints
the quantity, archive, and max deviation.  Requires the `psrchive`
python bindings on PYTHONPATH (this script is a no-op in environments
without them — e.g. this repo's CI — and is excluded from the test
suite on purpose: its value is in the field, against real files this
codebase did not write).
"""

import sys

import numpy as np


def _fail(msg):
    print(f"  FAIL {msg}")
    return 1


def compare(path, pr):
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu.io.psrfits import load_data, read_archive

    print(f"== {path}")
    nbad = 0

    a_pr = pr.Archive_load(path)
    arch = read_archive(path)

    # --- geometry / metadata -----------------------------------------
    geom_pr = (a_pr.get_nsubint(), a_pr.get_npol(), a_pr.get_nchan(),
               a_pr.get_nbin())
    geom = (arch.nsub, arch.npol, arch.nchan, arch.nbin)
    if geom != geom_pr:
        nbad += _fail(f"geometry: {geom} vs psrchive {geom_pr}")
    if arch.get_source() != a_pr.get_source():
        nbad += _fail(f"source: {arch.get_source()!r} vs "
                      f"{a_pr.get_source()!r}")
    if abs(arch.get_dispersion_measure()
           - a_pr.get_dispersion_measure()) > 1e-6:
        nbad += _fail("DM mismatch")

    # --- frequencies & weights ---------------------------------------
    nsub, npol, nchan, nbin = geom
    fr_pr = np.array([[a_pr.get_Integration(s).get_centre_frequency(c)
                       for c in range(nchan)] for s in range(nsub)])
    if not np.allclose(arch.freqs_table, fr_pr, atol=1e-6):
        nbad += _fail(
            f"freqs: max d = {np.abs(arch.freqs_table - fr_pr).max()}")
    w_pr = a_pr.get_weights()
    if not np.allclose(arch.get_weights(), w_pr, rtol=1e-6):
        nbad += _fail("weights differ")

    # --- periods / epochs --------------------------------------------
    p_pr = np.array([a_pr.get_Integration(s).get_folding_period()
                     for s in range(nsub)])
    if not np.allclose(arch.folding_periods(), p_pr, rtol=1e-10):
        nbad += _fail(
            f"periods: max rel d = "
            f"{np.abs(arch.folding_periods() / p_pr - 1).max():.3g}")
    e_pr = np.array([a_pr.get_Integration(s).get_epoch().in_days()
                     for s in range(nsub)])
    e = np.array([x.to_float() for x in arch.epochs()])
    if not np.allclose(e, e_pr, atol=1e-9):  # ~0.1 ms
        nbad += _fail(f"epochs: max d = {np.abs(e - e_pr).max():.3g} d")

    # --- data cube (after both sides' baseline removal) ---------------
    d = load_data(path, rm_baseline=True, quiet=True)
    b = a_pr.clone()
    b.remove_baseline()
    cube_pr = b.get_data()
    cube = np.asarray(d.subints)
    if cube.shape != cube_pr.shape:
        nbad += _fail(f"cube shape {cube.shape} vs {cube_pr.shape}")
    else:
        # per-profile scale-free comparison (the two baseline
        # algorithms may differ by a constant in low-S/N channels)
        x = cube.reshape(-1, nbin)
        y = cube_pr.reshape(-1, nbin)
        keep = (np.ptp(y, axis=1) > 0) & (np.ptp(x, axis=1) > 0)
        cc = np.array([np.corrcoef(xi, yi)[0, 1]
                       for xi, yi in zip(x[keep], y[keep])])
        if len(cc) and cc.min() < 0.999:
            nbad += _fail(f"data: min profile corrcoef {cc.min():.6f}")
        resid = np.abs(x[keep] - y[keep]).max() if keep.any() else 0.0
        scale = np.abs(y[keep]).max() or 1.0
        if resid / scale > 1e-3:
            nbad += _fail(f"data: max rel resid {resid / scale:.3g}")

    # --- dedispersion oracle (reference pplib.py:2526-2527) ----------
    c = a_pr.clone()
    c.dedisperse()
    ded_pr = c.get_data()
    arch2 = read_archive(path)
    arch2.dedisperse()
    ded = np.asarray(arch2.amps)
    x = ded.reshape(-1, nbin)
    y = ded_pr.reshape(-1, nbin)
    keep = (np.ptp(y, axis=1) > 0) & (np.ptp(x, axis=1) > 0)
    cc = np.array([np.corrcoef(xi, yi)[0, 1]
                   for xi, yi in zip(x[keep], y[keep])])
    if len(cc) and cc.min() < 0.999:
        nbad += _fail(f"dedisperse: min corrcoef {cc.min():.6f}")

    print("  OK" if nbad == 0 else f"  {nbad} check(s) failed")
    return nbad


def main(argv):
    if not argv:
        print(__doc__)
        return 2
    try:
        import psrchive as pr
    except ImportError:
        print("psrchive python bindings not found; nothing to compare. "
              "Run this where PSRCHIVE is installed.")
        return 2
    bad = 0
    for path in argv:
        bad += compare(path, pr)
    print(f"{'ALL OK' if bad == 0 else f'{bad} total failures'} "
          f"across {len(argv)} archive(s)")
    return 0 if bad == 0 else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
