"""IPTA-scale multi-pulsar campaign driver (BASELINE.md config 5).

The reference measures one pulsar per invocation with a strictly
sequential archive loop (pptoas.py:258); config 5 is "45 pulsars x
~1000 archives, spline model + TOAs, streamed over pod".  This module
is the orchestration layer above pipeline/stream.py:

- a **job registry**: each pulsar brings its own archive list, template
  model, and optional per-pulsar fit options;
- **multi-host sharding across the (pulsar, archive) grid**: the
  flattened grid is dealt round-robin over processes
  (parallel.shard_files), so every host carries a balanced slice of
  every pulsar and no cross-host coordination is needed until the
  final summary gather;
- **per-pulsar buckets and outputs**: each pulsar's shard streams
  through stream_wideband_TOAs with its own model — bucket keys are
  per-pulsar by construction (different template portraits must never
  share a fused dispatch), and TOAs append incrementally to
  ``outdir/<pulsar>[.p<process>].tim`` so an interrupted campaign
  keeps every completed archive on disk;
- **cross-host summaries**: per-pulsar DeltaDM means/errors are
  allgathered (parallel.process_allgather) so every process returns
  the full campaign picture.

Why per-pulsar passes instead of one pooled cross-pulsar pass: subints
of different pulsars can never share a fused dispatch (each needs its
own template portrait), so pooling across pulsars buys nothing once a
pulsar's shard holds >= nsub_batch subints — at IPTA scale (~1000
archives x subints per pulsar) every bucket fills many times over
within one pulsar.  Cross-pulsar pooling would only reduce padding for
tiny per-pulsar shards, at the cost of per-element template DFTs in
every dispatch.
"""

import glob
import os
import re
import time

import numpy as np

from ..telemetry import log, resolve_tracer
from ..utils.bunch import DataBunch
from .stream import stream_wideband_TOAs
from .toas import _is_metafile, _read_metafile

__all__ = ["IPTAJob", "stream_ipta_campaign"]


def _shard_checkpoints(outdir, pulsar):
    """Existing checkpoint shards belonging to `pulsar`, anchored to
    the shard naming scheme ({pulsar}.tim and {pulsar}.pN.tim).  A bare
    prefix glob would absorb another pulsar whose name extends this
    one (e.g. 'J1713' reading 'J1713+0747.p0.tim') and wrongly mark
    its archives complete."""
    shard_re = re.compile(re.escape(pulsar) + r"(\.p\d+)?\.tim$")
    return sorted(
        p for p in glob.glob(os.path.join(outdir, f"{pulsar}*.tim"))
        if shard_re.fullmatch(os.path.basename(p)))


class IPTAJob:
    """One pulsar's campaign slice: archives + template + options.

    datafiles: list of paths or a metafile path; modelfile: .gmodel /
    spline / PSRFITS template; kwargs: per-pulsar overrides forwarded
    to stream_wideband_TOAs (e.g. fit_scat=True for the scattered
    pulsars only, DM0=...).
    """

    def __init__(self, pulsar, datafiles, modelfile, **kwargs):
        self.pulsar = str(pulsar)
        if isinstance(datafiles, str):
            self.datafiles = (_read_metafile(datafiles)
                              if _is_metafile(datafiles) else [datafiles])
        else:
            self.datafiles = list(datafiles)
        self.modelfile = str(modelfile)
        self.kwargs = dict(kwargs)


def stream_ipta_campaign(jobs, outdir=None, shard=True, nsub_batch=256,
                         quiet=False, resume=False, telemetry=None,
                         server=None, router=None, timing_pars=None,
                         timing_kwargs=None, **stream_kwargs):
    """Measure wideband TOAs for a multi-pulsar campaign.

    server: an already-started serve.ToaServer — the campaign becomes
    a THIN CLIENT of the long-lived serving loop (ISSUE 8): each
    pulsar's shard is submitted as one request against the shared warm
    executor, so jit caches and device pipelines carry across pulsars
    (and across campaigns — the server outlives this call), small
    per-pulsar shards coalesce into shared fused buckets, and the
    per-request .tim files land in outdir exactly as the executor-per-
    pulsar path writes them.  The server's nsub_batch/devices/
    telemetry govern dispatch (per-bucket events ride the SERVER's
    trace; this call's telemetry= still records the campaign rollup);
    job kwargs must be lane options (fit_scat=, DM0=, ...).
    resume=True is not supported with server= — restartability comes
    from re-submitting against the durable request .tim files.

    router: a serve.ToaRouter over a FLEET of warm serving loops
    (ISSUE 10) — same thin-client shape as server=, but each pulsar's
    request is placed on the least-loaded host (sticky per-template
    affinity, backpressure retries handled inside the router), so one
    campaign saturates many hosts' links at once.  Per-request .tim
    files are written by whichever host served the request and are
    byte-identical to the single-host path; archive paths and outdir
    must be visible to every host (the multihost drivers' shared-
    filesystem assumption).  Mutually exclusive with server=; the
    same lane-option and resume rules apply.

    jobs: sequence of IPTAJob (or (pulsar, datafiles, modelfile)
    tuples).  outdir: directory for per-pulsar .tim outputs (created;
    None = no .tim files).  shard=True splits the flattened
    (pulsar, archive) grid round-robin across jax processes when the
    distributed runtime is initialized (parallel/multihost.py) — on a
    single process it is a no-op.  stream_kwargs: campaign-wide
    defaults forwarded to every stream_wideband_TOAs call (per-job
    kwargs override them).

    resume=True makes the campaign ELASTIC: every existing checkpoint
    shard for a pulsar (``<pulsar>*.tim`` in outdir, from any previous
    process layout — a killed worker's shard included) is scanned for
    per-archive completion sentinels; partial tails are dropped
    (process 0 sanitizes shards no current process owns, each process
    its own) and only archives not yet recorded complete ANYWHERE are
    measured.  Re-entering after a worker death — with any process
    count — therefore finishes exactly the missing archives, and the
    union of the .tim shards equals an uninterrupted run's lines.
    Requires outdir.

    telemetry: a trace path or telemetry.Tracer — ONE tracer is
    threaded through every per-pulsar stream call, so the whole
    campaign (campaign start/end, resume rollup, per-pulsar rollups,
    and every per-bucket dispatch/drain record) lands in a single
    self-describing JSONL trace; None follows config.telemetry_path
    (default off).  Analyze with tools/pptrace.py.

    timing_pars: {pulsar: parfile path or mapping} — run the FLEET
    TIMING STAGE (ISSUE 11) after TOA collection: each listed
    pulsar's measured TOAs feed timing.fleet.fleet_gls_fit, so the
    campaign runs archives -> TOAs -> per-pulsar timing solutions in
    one traced pipeline (timing_fit/fleet_end events ride the same
    tracer; pptrace renders the "timing" section).  Pulsars without a
    parfile entry are skipped; timing_kwargs forwards fit options
    (fit_f1=, device=, batched=, ...).  The result's ``timing`` field
    carries the fleet_gls_fit DataBunch (None when timing_pars is
    not given).  Refused under multi-process sharding: a shard's
    partial TOA set would silently time a subsampled campaign — merge
    the .tim shards and run ``pptime`` instead.

    Returns a DataBunch with:
      pulsars     — job order (all jobs, even if this host's shard of
                    one is empty)
      per_pulsar  — {pulsar: stream result DataBunch} for THIS host's
                    shard
      TOA_list    — this host's TOAs across all pulsars
      DeltaDM_summary — {pulsar: (means, errs)} with per-archive
                    offset-DM statistics ALLGATHERED across hosts
                    (every process sees the whole campaign's values)
      nfit, fit_duration, wall_s — aggregate accounting
    """
    from .. import parallel

    jobs = [j if isinstance(j, IPTAJob) else IPTAJob(*j) for j in jobs]
    names = [j.pulsar for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate pulsar names in jobs: {names}")
    if resume and not outdir:
        raise ValueError("stream_ipta_campaign: resume=True needs "
                         "outdir (the checkpoints live there)")
    if server is not None and router is not None:
        raise ValueError(
            "stream_ipta_campaign: pass server= OR router=, not both "
            "(a router already owns its fleet of serving loops)")
    if (server is not None or router is not None) and resume:
        raise ValueError(
            "stream_ipta_campaign: resume=True is not supported with "
            "server=/router= — restart by re-submitting; the "
            "per-request .tim files are the durable artifact")
    if outdir:
        os.makedirs(outdir, exist_ok=True)

    # ---- shard the flattened (pulsar, archive) grid ------------------
    grid = [(j.pulsar, f) for j in jobs for f in j.datafiles]
    pid, nproc = parallel.process_index(), parallel.process_count()
    if timing_pars and shard and nproc > 1:
        raise ValueError(
            "stream_ipta_campaign: timing_pars= is not supported with "
            "multi-process sharding — each process holds only its "
            "shard of every pulsar's TOAs, and timing a subsampled "
            "campaign would silently misreport every solution.  Merge "
            "the checkpoint .tim shards and run pptime instead.")
    if timing_pars and resume:
        raise ValueError(
            "stream_ipta_campaign: timing_pars= is not supported with "
            "resume=True — a resumed run's TOA_list covers only the "
            "archives measured THIS run (already-checkpointed "
            "archives are skipped), so the timing stage would "
            "silently fit a subsampled campaign.  Run pptime on the "
            "completed .tim checkpoints instead.")
    if timing_pars:
        unknown = sorted(set(timing_pars) - set(names))
        if unknown:
            raise ValueError(
                f"stream_ipta_campaign: timing_pars names pulsars not "
                f"in jobs: {unknown}")
    mine = parallel.shard_files(grid) if shard else grid
    tracer, own_tracer = resolve_tracer(telemetry,
                                        run="stream_ipta_campaign")
    tracer.emit("campaign_start", n_jobs=len(jobs), pid=pid,
                nproc=nproc, resume=bool(resume),
                n_archives_shard=len(mine))
    try:
        by_psr = {}
        for psr, f in mine:
            by_psr.setdefault(psr, []).append(f)

        def _tim_name(pulsar, p=None):
            suffix = f".p{p if p is not None else pid}" \
                if (shard and nproc > 1) else ""
            return os.path.join(outdir, f"{pulsar}{suffix}.tim")

        completed = {}
        if resume:
            from .stream import checkpoint_completed, sanitize_checkpoint

            current_outputs = {os.path.abspath(_tim_name(j.pulsar, p))
                               for j in jobs for p in range(nproc)}
            for job in jobs:
                done = set()
                own = os.path.abspath(_tim_name(job.pulsar))
                will_stream = bool(by_psr.get(job.pulsar))
                for path in _shard_checkpoints(outdir, job.pulsar):
                    ap = os.path.abspath(path)
                    if ap == own and not will_stream:
                        # this process owns the filename but has no files
                        # for the pulsar this run (reshuffled grid), so no
                        # stream call will sanitize it — drop its torn
                        # tail here, or it pollutes the shard union
                        done |= sanitize_checkpoint(path)
                    elif ap in current_outputs:
                        # a live shard: its owner sanitizes it (stream
                        # resume=True, or the branch above); only read
                        done |= checkpoint_completed(path)
                    elif pid == 0:
                        # orphaned shard from a previous process layout
                        # (e.g. a killed worker): no current process
                        # writes it, so process 0 may drop its partial
                        # tail safely
                        done |= sanitize_checkpoint(path)
                    else:
                        done |= checkpoint_completed(path)
                completed[job.pulsar] = done
            ntot = sum(len(v) for v in completed.values())
            tracer.emit("resume_skip", n_skipped=ntot)
            log(f"IPTA resume: {ntot} archive(s) recorded complete "
                "across existing checkpoint shards will be skipped",
                quiet=quiet)

        t0 = time.time()
        per_pulsar = {}
        TOA_list = []
        nfit = 0
        fit_duration = 0.0
        if server is not None or router is not None:
            from ..serve import ServeRejected

            target = "ToaServer" if server is not None else "ToaRouter"
            # executor-level knobs belong to the SERVER (it was
            # constructed with them); forwarding them as lane options
            # would fail every request with an opaque TypeError deep
            # in the serving thread — refuse here, by name
            executor_kw = {"max_inflight", "pipeline_depth",
                           "stream_devices", "prefetch", "tim_out",
                           "resume", "skip_archives"}
            bad = executor_kw & (set(stream_kwargs)
                                 | {k for j in jobs for k in j.kwargs})
            if bad:
                raise ValueError(
                    f"stream_ipta_campaign: {sorted(bad)} are executor"
                    f"-level options — configure them on the {target} "
                    f"when using {'server=' if server is not None else 'router='}")
            # thin-client path: submit EVERY shard first (the serving
            # loop pipelines admissions against in-flight dispatches
            # and coalesces small shards across pulsars; the router
            # additionally spreads shards over its fleet), then collect
            handles = []
            for job in jobs:
                files = by_psr.get(job.pulsar, [])
                if not files:
                    continue
                tim_out = _tim_name(job.pulsar) if outdir else None
                kw = {**stream_kwargs, **job.kwargs}
                kw.pop("telemetry", None)
                if router is not None:
                    # the router owns backpressure retries (capped
                    # exponential backoff across the fleet)
                    h = router.submit(files, job.modelfile,
                                      tim_out=tim_out,
                                      name=job.pulsar, **kw)
                else:
                    while True:
                        try:
                            h = server.submit(files, job.modelfile,
                                              tim_out=tim_out,
                                              name=job.pulsar, **kw)
                            break
                        except ServeRejected as e:
                            if not getattr(e, "retryable", False):
                                raise
                            time.sleep(0.05)  # honor the backpressure
                handles.append((job, time.time(), h))
            for job, t_job, h in handles:
                res = per_pulsar[job.pulsar] = h.result()
                TOA_list.extend(res.TOA_list)
                if tracer.enabled:
                    tracer.emit("pulsar_done", pulsar=job.pulsar,
                                n_toas=len(res.TOA_list),
                                n_archives=len(res.order), nfit=0,
                                wall_s=round(time.time() - t_job, 6))
        for job in (jobs if server is None and router is None else ()):
            files = by_psr.get(job.pulsar, [])
            if not files:
                continue
            tim_out = _tim_name(job.pulsar) if outdir else None
            kw = {**stream_kwargs, **job.kwargs}
            t_job = time.time()
            res = stream_wideband_TOAs(
                files, job.modelfile, nsub_batch=nsub_batch,
                tim_out=tim_out, quiet=True, resume=resume,
                skip_archives=completed.get(job.pulsar),
                telemetry=kw.pop("telemetry", tracer), **kw)
            per_pulsar[job.pulsar] = res
            TOA_list.extend(res.TOA_list)
            nfit += res.nfit
            fit_duration += res.fit_duration
            if tracer.enabled:
                tracer.emit("pulsar_done", pulsar=job.pulsar,
                            n_toas=len(res.TOA_list),
                            n_archives=len(res.order), nfit=res.nfit,
                            fit_s=round(res.fit_duration, 6),
                            peak_inflight=res.peak_inflight,
                            wall_s=round(time.time() - t_job, 6))

        # ---- allgather per-pulsar DeltaDM summaries across hosts -----
        summary = {}
        for job in jobs:
            res = per_pulsar.get(job.pulsar)
            means = np.asarray(res.DeltaDM_means if res else [], float)
            errs = np.asarray(res.DeltaDM_errs if res else [], float)
            gm = parallel.process_allgather(means)
            ge = parallel.process_allgather(errs)
            summary[job.pulsar] = (np.concatenate([np.atleast_1d(g)
                                                   for g in gm]),
                                   np.concatenate([np.atleast_1d(g)
                                                   for g in ge]))

        # ---- fleet timing stage (archives -> TOAs -> solutions) ------
        timing = None
        if timing_pars:
            from ..timing.fleet import (TimingJob, fleet_gls_fit,
                                        toas_from_measurements)

            tjobs = []
            for job in jobs:
                par = timing_pars.get(job.pulsar)
                res = per_pulsar.get(job.pulsar)
                if par is None or res is None:
                    continue
                tjobs.append(TimingJob(
                    job.pulsar, toas_from_measurements(res.TOA_list),
                    par))
            if tjobs:
                timing = fleet_gls_fit(tjobs, telemetry=tracer,
                                       quiet=quiet,
                                       **(timing_kwargs or {}))

        wall = time.time() - t0
        n = len(TOA_list)
        log(f"IPTA campaign: {n} TOAs across {len(per_pulsar)}/"
            f"{len(jobs)} pulsars on process {pid}/{nproc} in "
            f"{wall:.2f} s ({nfit} fused dispatches, "
            f"{n / max(wall, 1e-9):.1f} TOAs/s end-to-end)",
            quiet=quiet, tracer=tracer)
        tracer.emit("campaign_end", n_toas=n, nfit=nfit,
                    n_pulsars=len(per_pulsar),
                    wall_s=round(wall, 6))
    finally:
        # a failed resume scan or pulsar must still leave a closed,
        # counter-bearing trace (same stance as the stream drivers)
        if own_tracer:
            tracer.close()
    return DataBunch(pulsars=names, per_pulsar=per_pulsar,
                     TOA_list=TOA_list, DeltaDM_summary=summary,
                     timing=timing,
                     nfit=nfit, fit_duration=fit_duration, wall_s=wall)
